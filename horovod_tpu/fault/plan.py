"""Seeded, deterministic fault injection for the control plane.

Elastic/fault-tolerant behavior is only trustworthy if every failure mode
is reproducible in CPU-only tests: "kill rank 1 at cycle 20", "drop the
next tick frame", "wedge backend init twice" must mean the same thing on
every run. A :class:`FaultPlan` is a list of rules loaded once per process
from ``HOROVOD_FAULT_PLAN`` (inline JSON, or ``@/path/to/plan.json``);
hooks in ``Wire.send/recv`` (sites ``wire_send``/``wire_recv``), the
controller cycle loop (``cycle``), and backend/distributed init (``init``)
consult it. All counting is per-site and deterministic; the only use of
randomness is optional delay jitter, drawn from a ``random.Random(seed)``
so two runs with the same plan sleep the same amounts.

Rule fields (JSON object per rule):

    site     "wire_send" | "wire_recv" | "cycle" | "init" (backend
             acquisition) | "init_distributed" (jax.distributed join) —
             the two init paths count separately so a plan's "at"/"times"
             don't shift with the launch mode — | "ckpt_save" (inside the
             async hvd-ckpt-writer thread, before the shard's atomic
             rename swing: kill/exit/delay tear the write exactly where
             a preempted rank would; "raise" exercises the writer's
             never-fail-the-job error path)
    action   "kill"  — SIGKILL this process (a real crash, no cleanup)
             "exit"  — os._exit(1) (a crash that still reports non-zero)
             "delay" — sleep ``seconds`` (± ``jitter`` fraction, seeded)
             "drop"  — wire_send only: silently skip sending the frame
             "raise" — raise FaultInjected(``message``)
             "wedge" — init only: raise InitWedged for the first ``times``
                       attempts, succeed afterwards
             "leave" — cycle only: gracefully retire this worker
                       (os._exit(0) — a clean departure, the membership-
                       churn half of elastic chaos; the coordinator sees
                       the closed wire and re-forms without it)
             "join"  — cycle only: spawn a CLONE of this process (same
                       argv/cwd) as an elastic joiner — the clone gets
                       HOROVOD_ELASTIC_JOIN=1 and a scrubbed fault plan
                       (it must not replay this rule, or a join storm
                       becomes a fork bomb) and is admitted at the next
                       membership epoch boundary
             "group_kill" — cycle only: SIGKILL every process whose
                       rank is in ``ranks`` at the SAME cycle count — a
                       correlated failure (a whole rack / power domain),
                       not N independent ones. The lockstep protocol
                       keeps cycle counts aligned across ranks, so the
                       deaths land together; the sim harness
                       (horovod_tpu/sim, docs/simcluster.md) applies the
                       rule to all its logical ranks in one stroke
    at       fire on the at-th event at this site (1-based); "wedge"
             ignores it (always the first ``times`` attempts)
    times    how many consecutive events fire (default 1)
    rank     only apply in the process with this HOROVOD_RANK (default all)
    ranks    "group_kill" only: the ranks that die together (required)
    seconds  delay duration (action "delay")
    jitter   ± fraction of ``seconds`` (seeded; default 0 = deterministic)
    message  error text for action "raise"

The hot path (``fault.hook(site)``) is a no-op returning ``None`` when no
plan is configured — one module-global read and a ``None`` check — so the
wire fast path pays nothing in production.
"""

from __future__ import annotations

import dataclasses
import json
import os
import random
import signal
import threading
import time
from typing import Dict, List, Optional

VALID_SITES = ("wire_send", "wire_recv", "cycle", "init",
               "init_distributed", "ckpt_save")
_INIT_SITES = ("init", "init_distributed")
VALID_ACTIONS = ("kill", "exit", "delay", "drop", "raise", "wedge",
                 "join", "leave", "group_kill")
# Membership-churn actions fire at controller-cycle granularity only: a
# join/leave mid-frame would tear a wire stream rather than exercise the
# elastic reshape path it exists to test.
_MEMBERSHIP_ACTIONS = ("join", "leave", "group_kill")


def _graceful_leave() -> None:
    """Action "leave": retire this worker cleanly (exit code 0 — the
    launcher must NOT respawn it, and chaos harnesses asserting on exit
    codes see an intentional departure). Module-level so tests can stub
    it."""
    os._exit(0)


def _spawn_joiner() -> None:
    """Action "join": fork-and-exec a clone of this process as an elastic
    joiner. Detached — the plan only guarantees a joiner ARRIVES; its
    admission is the coordinator's job. Module-level so tests can stub."""
    import subprocess
    import sys

    env = dict(os.environ)
    env["HOROVOD_ELASTIC_JOIN"] = "1"
    env.pop("HOROVOD_FAULT_PLAN", None)  # clones must not replay the plan
    subprocess.Popen([sys.executable] + sys.argv, env=env,
                     start_new_session=True)


class FaultInjected(RuntimeError):
    """Raised by an action "raise" rule (and the base of InitWedged)."""


class InitWedged(FaultInjected):
    """Injected init failure (action "wedge"): the shape of a TPU backend
    that hangs or errors K times before coming healthy (artifacts/
    tpu_outage_r6.md) — retried by ``common/retry.py``."""


@dataclasses.dataclass
class FaultRule:
    site: str
    action: str
    at: Optional[int] = None
    times: int = 1
    rank: Optional[int] = None
    ranks: Optional[List[int]] = None  # "group_kill": correlated victims
    seconds: float = 0.0
    jitter: float = 0.0
    message: str = ""

    def __post_init__(self):
        if self.site not in VALID_SITES:
            raise ValueError(f"unknown fault site {self.site!r} "
                             f"(valid: {VALID_SITES})")
        if self.action not in VALID_ACTIONS:
            raise ValueError(f"unknown fault action {self.action!r} "
                             f"(valid: {VALID_ACTIONS})")
        if self.action == "wedge" and self.site not in _INIT_SITES:
            raise ValueError('action "wedge" only applies to the init '
                             f'sites {_INIT_SITES}')
        if self.action == "drop" and self.site != "wire_send":
            raise ValueError('action "drop" only applies to site '
                             '"wire_send"')
        if self.action in _MEMBERSHIP_ACTIONS and self.site != "cycle":
            raise ValueError(
                f'action "{self.action}" only applies to site "cycle" '
                "(membership churn is an epoch-boundary event)")
        if self.action == "group_kill":
            if not self.ranks:
                # Without victims the rule is a silent no-op — a chaos
                # run that tests nothing. Fail at load, like the "at"
                # check below.
                raise ValueError(
                    'action "group_kill" needs "ranks" (the list of '
                    "ranks that die together)")
            self.ranks = sorted(int(r) for r in self.ranks)
        elif self.ranks is not None:
            raise ValueError(
                f'"ranks" only applies to action "group_kill" '
                f'(got action {self.action!r}); use "rank" to scope a '
                "single-process rule")
        if self.action != "wedge" and self.at is None:
            # Without "at" the rule would never fire — a chaos run that
            # silently tests nothing. Fail at load, not at runtime.
            raise ValueError(
                f'rule {self.site}/{self.action} needs "at" (the 1-based '
                'event number to fire on); only "wedge" may omit it')

    def fires_at(self, count: int) -> bool:
        """Whether this rule fires on the ``count``-th event (1-based)."""
        if self.action == "wedge":
            return count <= self.times
        if self.at is None:
            return False
        return self.at <= count < self.at + self.times


class FaultPlan:
    """The rules that apply to THIS process, with per-site event counters."""

    def __init__(self, rules: List[FaultRule], seed: int = 0,
                 rank: Optional[int] = None):
        self.seed = seed
        self.rank = rank
        # group_kill scopes by membership in its victim list — a rule
        # with ranks=[4,5,6,7] must load in exactly those processes (all
        # of which then die at the same lockstep cycle count); every
        # other action keeps the single-rank / all-ranks scoping. That
        # scoping NEEDS a rank identity: with HOROVOD_RANK unset or
        # unparseable the victim test would silently drop every
        # group_kill rule — a chaos run that tests nothing, the exact
        # failure mode this module fails loudly on.
        if rank is None and any(r.ranks is not None for r in rules):
            raise ValueError(
                "a group_kill rule needs this process's rank to scope "
                "its victim list, but HOROVOD_RANK is unset/unparseable")
        self.rules = [r for r in rules
                      if (rank in r.ranks if r.ranks is not None
                          else r.rank is None or r.rank == rank)]
        self._counts: Dict[str, int] = {}
        self._rng = random.Random(seed)
        self._lock = threading.Lock()

    @classmethod
    def from_json(cls, text: str,
                  rank: Optional[int] = None) -> "FaultPlan":
        spec = json.loads(text)
        if isinstance(spec, list):  # bare rule list shorthand
            spec = {"faults": spec}
        rules = [FaultRule(**entry) for entry in spec.get("faults", [])]
        return cls(rules, seed=int(spec.get("seed", 0)), rank=rank)

    @classmethod
    def from_env(cls) -> Optional["FaultPlan"]:
        from ..common.config import env_rank, fault_plan_raw

        raw = fault_plan_raw()
        if raw is None:
            return None
        if raw.startswith("@"):
            with open(raw[1:]) as f:
                raw = f.read()
        return cls.from_json(raw, rank=env_rank())

    def count(self, site: str) -> int:
        """Events seen so far at ``site`` (for tests/introspection)."""
        with self._lock:
            return self._counts.get(site, 0)

    def fire(self, site: str) -> Optional[str]:
        """Record one event at ``site`` and execute any matching rule.

        Returns ``"drop"`` when the caller must skip the operation;
        executes delay/kill/exit inline; raises for "raise"/"wedge".
        """
        with self._lock:
            count = self._counts.get(site, 0) + 1
            self._counts[site] = count
            fired = [r for r in self.rules
                     if r.site == site and r.fires_at(count)]
            delays = [r.seconds * (1.0 + r.jitter * self._rng.uniform(-1, 1)
                                   if r.jitter else 1.0)
                      for r in fired if r.action == "delay"]
        result: Optional[str] = None
        for delay in delays:  # sleep outside the lock
            if delay > 0:
                time.sleep(delay)
        for rule in fired:
            if rule.action in ("kill", "group_kill"):
                # group_kill reaches here only in processes whose rank is
                # in the victim list (the constructor filter): each dies
                # at the same cycle count — the correlated failure.
                os.kill(os.getpid(), signal.SIGKILL)
            elif rule.action == "exit":
                os._exit(1)
            elif rule.action == "leave":
                _graceful_leave()
            elif rule.action == "join":
                _spawn_joiner()
            elif rule.action == "drop":
                result = "drop"
            elif rule.action == "wedge":
                raise InitWedged(
                    rule.message
                    or f"fault injection: init wedged (attempt {count} of "
                       f"{rule.times} injected failures)")
            elif rule.action == "raise":
                raise FaultInjected(
                    rule.message
                    or f"fault injection: raise at {site} event {count}")
        return result
