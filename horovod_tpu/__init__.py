"""horovod_tpu — a TPU-native distributed training framework with the
capability set of Horovod 0.16.1 (reference: bigo-sg/horovod, mounted at
/root/reference), rebuilt for the JAX/XLA stack.

Architecture (vs the reference, see SURVEY.md):

* Control plane: TCP rendezvous + background controller (tensor fusion,
  response cache, timeline, stall detection) instead of MPI
  (``horovod/common/operations.cc``).
* Data plane: XLA collectives over ICI/DCN (``lax.psum`` & friends, sharded
  ``jit``) instead of NCCL; host tensors ride the native C++ ring backend.
* Two tiers: SPMD (jit over a device Mesh — the TPU hot path) and eager
  multi-process (Horovod parity for per-tensor host-driven collectives).

Top-level surface mirrors ``import horovod.torch as hvd`` /
``horovod.tensorflow``: init/rank/size, allreduce/allgather/broadcast
(+async), DistributedOptimizer, broadcast_parameters, Compression.
"""

__version__ = "0.1.0"

from . import compat  # noqa: F401  (installs jax.shard_map alias; first)
from .common.basics import (  # noqa: F401
    init,
    shutdown,
    is_initialized,
    rank,
    size,
    local_rank,
    local_size,
    cross_rank,
    cross_size,
    num_devices,
    local_num_devices,
    mpi_threads_supported,
)
from .ops.collective_ops import (  # noqa: F401
    Sum,
    Average,
    allreduce,
    allreduce_async,
    grouped_allreduce,
    grouped_allreduce_async,
    allgather,
    allgather_async,
    broadcast,
    broadcast_async,
    broadcast_object,
    allgather_object,
    barrier,
    reducescatter,
    alltoall,
    synchronize,
    poll,
    wait,
    set_default_spmd_axis,
)
from .compression import Compression  # noqa: F401
from .jax import (  # noqa: F401
    DistributedOptimizer,
    distributed_value_and_grad,
    broadcast_parameters,
    broadcast_optimizer_state,
)
from . import parallel  # noqa: F401
from . import metrics  # noqa: F401  (hvd.metrics.snapshot() et al.)
from . import trace  # noqa: F401  (hvd.trace.summary() / merge tooling)
from . import doctor  # noqa: F401  (hvd.doctor.report() / rule catalog)
from . import elastic  # noqa: F401  (hvd.elastic.run / State, docs/elastic.md)
from . import serving  # noqa: F401  (hvd.serving.serve / stats, docs/serving.md)
from .common import profiler  # noqa: F401
from .controller.bucket_scheduler import (  # noqa: F401
    BucketScheduler,
    partition_buckets,
    plan_from_compiled,
)
