"""Straggler attribution: who arrived last at negotiation, and by how much.

Input is a merged, clock-corrected event list (``trace/merge.py``). For
every collective sequence id, each rank's ``negotiate`` span begins when
that rank's request left for the coordinator (stamped after the send
completed, so an injected or real network stall shows up here); with all
ranks on one timebase:

    arrival(seq, rank) = start of rank's negotiate span for seq
    slack(seq)         = max_rank(arrival) - min_rank(arrival)
    straggler(seq)     = argmax_rank(arrival)
    lateness(seq, r)   = arrival(seq, r) - min_rank(arrival)

The report aggregates per rank (straggler cycles, lateness p50/p99/max)
and overall (slack distribution, worst offending collectives by name),
and — when telemetry is on — feeds two series into the Round-8 metrics
registry so dashboards see stragglers without parsing traces:

* ``hvd_negotiation_slack_seconds`` — histogram of per-collective slack;
* ``hvd_straggler_cycles_total{rank=…}`` — collectives a rank arrived
  last at (with positive slack).

Produced automatically as ``straggler_report.json`` when a traced job
shuts down cleanly, and on demand by
``python -m horovod_tpu.tools.straggler``.
"""

from __future__ import annotations

import json
import math
import os
from typing import Dict, List, Optional

from .. import metrics
from .tracer import REPORT_FILE

# Slack below this is clock-sync noise, not a straggler: typical offset
# uncertainty on a healthy local network is tens of microseconds.
DEFAULT_SLACK_EPSILON_SECONDS = 1e-4

_m = None


def _straggler_metrics():
    """Lazy registration (tests/test_metrics_lint.py: never at import
    time)."""
    global _m
    if _m is None:
        from types import SimpleNamespace

        _m = SimpleNamespace(
            slack=metrics.histogram(
                "hvd_negotiation_slack_seconds",
                "Per-collective negotiation slack: last rank's arrival "
                "minus first rank's, clock-corrected."),
            cycles=metrics.counter(
                "hvd_straggler_cycles_total",
                "Collectives this rank arrived last at negotiation for "
                "(slack above the epsilon).", ("rank",)))
    return _m


def _pctl(sorted_vals: List[float], q: float) -> Optional[float]:
    if not sorted_vals:
        return None
    idx = max(0, min(len(sorted_vals) - 1,
                     int(math.ceil(q * len(sorted_vals))) - 1))
    return sorted_vals[idx]


def attribute(events: List[dict],
              epsilon: float = DEFAULT_SLACK_EPSILON_SECONDS,
              feed: bool = True) -> dict:
    """Build the straggler report from merged (already clock-corrected)
    events. ``feed=True`` additionally populates the metrics registry
    (no-op with telemetry off)."""
    arrivals: Dict[int, Dict[int, float]] = {}  # seq -> {rank: seconds}
    ops: Dict[int, str] = {}
    clock: Dict[str, dict] = {}
    for ev in events:
        if ev.get("ph") == "M" and ev.get("name") == "clock_sync":
            args = ev.get("args", {})
            clock[str(args.get("rank", ev.get("pid")))] = {
                "applied_offset_seconds": args.get(
                    "applied_offset_seconds"),
                "uncertainty_seconds": args.get("uncertainty_seconds"),
                "synced": args.get("synced"),
            }
            continue
        if ev.get("name") != "negotiate" or ev.get("ph") != "X":
            continue
        args = ev.get("args", {})
        seq = args.get("seq")
        if seq is None:
            continue
        arrivals.setdefault(int(seq), {})[int(ev["pid"])] = \
            ev["ts"] / 1e6
        if "op" in args:
            ops[int(seq)] = args["op"]

    ranks = sorted({r for per in arrivals.values() for r in per})
    slacks: List[float] = []
    lateness: Dict[int, List[float]] = {r: [] for r in ranks}
    straggler_cycles: Dict[int, int] = {r: 0 for r in ranks}
    worst: List[dict] = []
    for seq in sorted(arrivals):
        per = arrivals[seq]
        if len(per) < 2:
            continue  # a collective not seen by >=2 ranks attributes nothing
        first = min(per.values())
        last_rank = max(per, key=lambda r: (per[r], r))
        slack = per[last_rank] - first
        slacks.append(slack)
        for r, t in per.items():
            lateness[r].append(t - first)
        if slack > epsilon:
            straggler_cycles[last_rank] += 1
            worst.append({"seq": seq, "op": ops.get(seq),
                          "slack_seconds": round(slack, 6),
                          "straggler": last_rank})

    worst.sort(key=lambda w: -w["slack_seconds"])
    slacks_sorted = sorted(slacks)
    per_rank = {}
    for r in ranks:
        vals = sorted(lateness[r])
        per_rank[str(r)] = {
            "straggler_cycles": straggler_cycles[r],
            "lateness_p50_seconds": _round(_pctl(vals, 0.5)),
            "lateness_p99_seconds": _round(_pctl(vals, 0.99)),
            "lateness_max_seconds": _round(vals[-1] if vals else None),
        }
    worst_rank = None
    if ranks and slacks:
        # Worst = most straggler cycles, ties broken by max lateness:
        # "who should you go look at" in one field.
        worst_rank = max(
            ranks, key=lambda r: (straggler_cycles[r],
                                  lateness[r] and max(lateness[r]) or 0.0))
    report = {
        "collectives": len(slacks),
        "ranks": ranks,
        "slack_epsilon_seconds": epsilon,
        "slack_p50_seconds": _round(_pctl(slacks_sorted, 0.5)),
        "slack_p99_seconds": _round(_pctl(slacks_sorted, 0.99)),
        "slack_max_seconds": _round(slacks_sorted[-1]
                                    if slacks_sorted else None),
        "per_rank": per_rank,
        "worst_rank": worst_rank,
        "worst_collectives": worst[:10],
        "clock": clock,
    }
    if feed and metrics.on() and slacks:
        m = _straggler_metrics()
        for s in slacks:
            m.slack.observe(s)
        for r, c in straggler_cycles.items():
            if c:
                m.cycles.labels(str(r)).inc(c)
    return report


def _round(v: Optional[float]) -> Optional[float]:
    return round(v, 6) if v is not None else None


def write_report(trace_dir: str, events: Optional[List[dict]] = None,
                 out_path: Optional[str] = None, feed: bool = True) -> str:
    """Attribute and write ``straggler_report.json`` next to the merged
    trace. With ``events`` omitted, reads ``merged_trace.json`` from
    ``trace_dir``."""
    if events is None:
        from .tracer import MERGED_TRACE_FILE

        with open(os.path.join(trace_dir, MERGED_TRACE_FILE)) as f:
            events = json.load(f)
    report = attribute(events, feed=feed)
    path = out_path or os.path.join(trace_dir, REPORT_FILE)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(report, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    return path


def summary(snap: Optional[Dict[str, dict]] = None) -> dict:
    """Compact straggler summary off the metrics registry (bench.py
    rows): negotiation-slack p99 and the rank with the most straggler
    cycles. Fields are None when no traced attribution ran."""
    snap = snap if snap is not None else metrics.snapshot()
    p99 = metrics.quantile(snap.get("hvd_negotiation_slack_seconds"), 0.99)
    worst_rank = None
    cycles = snap.get("hvd_straggler_cycles_total")
    if cycles and cycles.get("values"):
        (labels, count) = max(cycles["values"], key=lambda kv: kv[1])
        if count > 0:
            worst_rank = int(labels[0])
    return {
        "slack_p99_seconds": round(p99, 6) if p99 is not None else None,
        "worst_rank": worst_rank,
    }
