"""Cluster clock synchronization for merged tracing.

Each rank stamps trace events with its own wall clock; laying N rank
timelines on one axis needs each worker's offset from the coordinator's
clock. The coordinator estimates it NTP-style from RTT ping-pong
exchanges piggybacked on the wire's HEARTBEAT frames
(``common/wire.py``): it sends ``{"ping": t0}``, the worker echoes
``{"pong": t0, "wall": <its time.time()>}``, and on receipt at ``t1``

    rtt    = t1 - t0
    offset = peer_wall - (t0 + t1) / 2        # worker clock - ours
    uncertainty = rtt / 2

The midpoint estimate is exact for symmetric paths; for an asymmetric
path the error is bounded by ``rtt / 2`` (the pong may have left the
worker anywhere inside the RTT window), which is why the uncertainty is
recorded next to every offset instead of being rounded away. Samples
refresh continuously; the estimate per rank is the sample with the
smallest RTT inside a bounded window (queueing only ever inflates RTT,
so min-RTT is the least-contaminated observation — the classic NTP
filter).

The table is serialized to ``clock_offsets.json`` in the trace
directory so the offline merge (``trace/merge.py``,
``python -m horovod_tpu.tools.straggler``) can rebase per-rank
timestamps after the job is gone.
"""

from __future__ import annotations

import json
import os
import threading
import time

from ..analysis.lockorder import make_lock
from collections import deque
from typing import Dict, Optional

# Samples kept per rank: enough to ride out a noisy patch, small enough
# that a real clock step (NTP slew on the host) ages out quickly.
DEFAULT_WINDOW = 64


class ClockSync:
    """Per-rank wall-clock offset table, fed by pong observations."""

    def __init__(self, size: int, window: int = DEFAULT_WINDOW):
        self.size = size
        self._window = max(1, window)
        self._samples: Dict[int, deque] = {}
        self._lock = make_lock("trace.clock")

    def observe(self, rank: int, t0: float, peer_wall: float,
                t1: Optional[float] = None) -> None:
        """Record one completed ping-pong: sent at ``t0`` (our clock),
        answered with ``peer_wall`` (worker clock), received at ``t1``
        (our clock, default now)."""
        if t1 is None:
            t1 = time.time()  # hvdlint: disable=HVD004 (wall protocol)
        rtt = t1 - t0
        if rtt < 0:  # our own clock stepped mid-exchange: unusable
            return
        offset = peer_wall - (t0 + t1) / 2.0
        with self._lock:
            dq = self._samples.setdefault(int(rank), deque(
                maxlen=self._window))
            dq.append((rtt, offset, t1))

    def sample_count(self, rank: int) -> int:
        with self._lock:
            dq = self._samples.get(int(rank))
            return len(dq) if dq else 0

    def estimate(self, rank: int) -> "Optional[tuple]":
        """Best current ``(offset, uncertainty, rtt)`` for ``rank`` —
        the min-RTT sample in the window — or None with no samples.
        Rank 0 (the reference clock) is always ``(0, 0, 0)``."""
        if int(rank) == 0:
            return (0.0, 0.0, 0.0)
        with self._lock:
            dq = self._samples.get(int(rank))
            if not dq:
                return None
            rtt, offset, _ = min(dq, key=lambda s: s[0])
        return (offset, rtt / 2.0, rtt)

    def table(self) -> Dict[str, dict]:
        """JSON-clean offset table: the artifact the merge consumes.
        Ranks never observed appear with offset 0 and ``synced: false``
        so the merge stays total and the report can flag them."""
        out: Dict[str, dict] = {}
        for rank in range(self.size):
            est = self.estimate(rank)
            if est is None:
                out[str(rank)] = {"offset_seconds": 0.0,
                                  "uncertainty_seconds": None,
                                  "rtt_seconds": None,
                                  "samples": 0, "synced": False}
            else:
                offset, unc, rtt = est
                out[str(rank)] = {"offset_seconds": round(offset, 9),
                                  "uncertainty_seconds": round(unc, 9),
                                  "rtt_seconds": round(rtt, 9),
                                  "samples": self.sample_count(rank)
                                  if rank else 0,
                                  "synced": True}
        return out

    def write(self, path: str) -> str:
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.table(), f, indent=1, sort_keys=True)
            f.write("\n")
        os.replace(tmp, path)
        return path


def load_offsets(path: str) -> Dict[int, dict]:
    """Read a ``clock_offsets.json`` into {rank: entry}; a missing or
    malformed file yields {} (the merge then rebases with offset 0)."""
    try:
        with open(path) as f:
            raw = json.load(f)
        return {int(k): v for k, v in raw.items()}
    except (OSError, ValueError, TypeError):
        return {}
