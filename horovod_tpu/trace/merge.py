"""Merge per-rank trace files into one clock-corrected cluster trace.

Input: a trace directory holding ``trace.rank<N>.json`` files written by
:class:`~horovod_tpu.trace.tracer.TraceWriter` (each with a
``clock_sync`` wall anchor) and, optionally, ``clock_offsets.json``
written by the coordinator's :class:`~horovod_tpu.trace.clock.ClockSync`.

Output: ``merged_trace.json`` — one Chrome/Perfetto JSON array with one
process-row per rank, every timestamp rebased onto the coordinator's
clock:

    corrected_wall(rank, ts) = wall_anchor_rank - offset_rank + ts
    merged_ts                = corrected_wall - min_rank(corrected_wall(0))

so the earliest rank's trace start is t=0 and a span at the same merged
timestamp on two rows really happened at the same moment (within the
recorded offset uncertainty). Ranks missing from the offset table are
rebased with offset 0 and show up as ``synced: false`` in the metadata —
visible, not silently wrong.

The merge is a pure function of its input files (no clocks, no env), so
it is exercised by a byte-exact golden test and is safe to run offline
(``python -m horovod_tpu.tools.straggler``) long after the job died.
"""

from __future__ import annotations

import glob
import json
import os
import re
from typing import Dict, List, Optional

from .clock import load_offsets
from .tracer import MERGED_TRACE_FILE, OFFSETS_FILE

_RANK_FILE = re.compile(r"trace\.rank(\d+)\.json$")


def rank_trace_files(trace_dir: str) -> Dict[int, str]:
    """{rank: path} for every per-rank trace present in ``trace_dir``."""
    out: Dict[int, str] = {}
    for path in glob.glob(os.path.join(trace_dir, "trace.rank*.json")):
        m = _RANK_FILE.search(os.path.basename(path))
        if m:
            out[int(m.group(1))] = path
    return out


def _load_events(path: str) -> List[dict]:
    with open(path) as f:
        events = json.load(f)
    if not isinstance(events, list):
        raise ValueError(f"{path}: expected a JSON array of trace events")
    return events


def _wall_anchor(events: List[dict], path: str) -> float:
    for ev in events:
        if ev.get("name") == "clock_sync" and ev.get("ph") == "M":
            return float(ev["args"]["wall_anchor"])
    raise ValueError(
        f"{path}: no clock_sync metadata — not a mergeable rank trace")


def merge_events(per_rank: Dict[int, List[dict]],
                 offsets: Optional[Dict[int, dict]] = None) -> List[dict]:
    """Merge already-loaded per-rank event lists; returns the merged
    event list (metadata first, then spans sorted by corrected time)."""
    offsets = offsets or {}
    anchors: Dict[int, float] = {}
    corrected0: Dict[int, float] = {}
    for rank, events in per_rank.items():
        anchors[rank] = _wall_anchor(events, f"rank {rank}")
        entry = offsets.get(rank, {})
        off = float(entry.get("offset_seconds") or 0.0)
        corrected0[rank] = anchors[rank] - off
    base = min(corrected0.values())

    meta: List[dict] = []
    spans: List[dict] = []
    counts: Dict[str, int] = {}
    for rank in sorted(per_rank):
        shift_us = (corrected0[rank] - base) * 1e6
        entry = offsets.get(rank, {})
        for ev in per_rank[rank]:
            ev = dict(ev)
            ev["pid"] = rank  # one process-row per rank, whatever was stored
            if ev.get("ph") == "M":
                name = ev.get("name")
                if name == "trace_end":
                    continue  # replaced by one merged trailer
                if name == "clock_sync":
                    ev = {"name": "clock_sync", "ph": "M", "pid": rank,
                          "args": {
                              "rank": rank,
                              "wall_anchor": anchors[rank],
                              "applied_offset_seconds": float(
                                  entry.get("offset_seconds") or 0.0),
                              "uncertainty_seconds": entry.get(
                                  "uncertainty_seconds"),
                              "synced": bool(entry.get("synced", False))
                              or rank == 0,
                          }}
                meta.append(ev)
                continue
            if "ts" in ev:
                ev["ts"] = int(round(ev["ts"] + shift_us))
            spans.append(ev)
            counts[str(rank)] = counts.get(str(rank), 0) + 1
    spans.sort(key=lambda e: (e.get("ts", 0), e.get("pid", 0),
                              e.get("tid", 0), e.get("name", "")))
    trailer = {"name": "trace_end", "ph": "M", "pid": 0,
               "args": {"ranks": sorted(per_rank),
                        "events_per_rank": counts}}
    return meta + spans + [trailer]


def write_trace(events: List[dict], path: str) -> str:
    """One event per line, sorted keys: byte-stable for the golden test
    and diffable by humans. Written tmp+rename so a merge killed mid-write
    (e.g. the shutdown join timing out) can never leave a truncated file
    that downstream existence checks mistake for a complete merge."""
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        for i, ev in enumerate(events):
            f.write(("[\n" if i == 0 else ",\n")
                    + json.dumps(ev, sort_keys=True))
        f.write("\n]\n")
    os.replace(tmp, path)
    return path


def merge_trace_dir(trace_dir: str, out_path: Optional[str] = None,
                    offsets: Optional[Dict[int, dict]] = None) -> str:
    """Merge every ``trace.rank*.json`` under ``trace_dir`` and write
    ``merged_trace.json`` (or ``out_path``). Raises if no rank traces
    exist — an empty merge would look like a successful one."""
    files = rank_trace_files(trace_dir)
    if not files:
        raise FileNotFoundError(
            f"no trace.rank*.json files under {trace_dir!r}")
    if offsets is None:
        offsets = load_offsets(os.path.join(trace_dir, OFFSETS_FILE))
    per_rank = {rank: _load_events(path) for rank, path in files.items()}
    merged = merge_events(per_rank, offsets)
    return write_trace(merged,
                       out_path or os.path.join(trace_dir,
                                                MERGED_TRACE_FILE))
