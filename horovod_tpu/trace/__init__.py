"""Cluster-wide distributed tracing (see ``docs/tracing.md``).

Four pieces, Dapper-shaped (Sigelman et al., 2010) over the eager
control plane:

1. **Clock sync** (``trace/clock.py``) — the coordinator estimates each
   worker's wall-clock offset ± uncertainty from RTT ping-pong
   piggybacked on the wire's HEARTBEAT frames, refreshed for the life of
   the job, and serialized as ``clock_offsets.json``.
2. **Span propagation** (``trace/tracer.py`` + the controller) — the
   coordinator assigns a monotonically increasing **collective sequence
   id** per fused op, carried on the cycle reply; every rank emits
   ``enqueue → negotiate → fuse → execute → done`` phase spans tagged
   with it into its own ``trace.rank<N>.json``.
3. **Merge** (``trace/merge.py``) — per-rank files are rebased through
   the offset table into one ``merged_trace.json`` with one process-row
   per rank (Chrome/Perfetto).
4. **Attribution** (``trace/straggler.py``) — per collective, which rank
   arrived last at negotiation and the slack distribution per
   rank/phase; written as ``straggler_report.json`` and fed into the
   metrics registry (``hvd_negotiation_slack_seconds``,
   ``hvd_straggler_cycles_total{rank}``).

Enable with ``HOROVOD_TRACE_DIR=<dir>`` (or ``horovodrun --trace DIR``);
everything here is inert without it. Offline re-merge/attribution:
``python -m horovod_tpu.tools.straggler <trace_dir>``.
"""

from __future__ import annotations

from .clock import ClockSync, load_offsets  # noqa: F401
from .merge import (  # noqa: F401
    merge_events,
    merge_trace_dir,
    rank_trace_files,
    write_trace,
)
from .straggler import attribute, summary, write_report  # noqa: F401
from .tracer import (  # noqa: F401
    ALL_PHASES,
    MERGED_TRACE_FILE,
    OFFSETS_FILE,
    PHASES,
    REPORT_FILE,
    SERVING_PHASES,
    TraceWriter,
    rank_trace_path,
)

__all__ = [
    "ClockSync", "TraceWriter", "PHASES", "SERVING_PHASES", "ALL_PHASES",
    "rank_trace_path", "rank_trace_files", "merge_trace_dir",
    "merge_events", "write_trace", "attribute", "write_report", "summary",
    "load_offsets", "MERGED_TRACE_FILE", "OFFSETS_FILE", "REPORT_FILE",
]
# The HOROVOD_TRACE_DIR knob itself is parsed in exactly one place:
# common/config.py (Config.from_env().trace_dir).
