"""Per-rank collective span writer for the cluster trace.

Unlike ``common/timeline.py`` (rank 0 only, one chrome "process" per
tensor, a timebase private to the process), every rank writes its own
``trace.rank<N>.json`` here, and every span carries two things that make
the files mergeable:

* a ``clock_sync`` metadata event recording the wall-clock anchor of the
  file's (monotonic) timebase, so timestamps can be rebased onto any
  other rank's clock given an offset table (``trace/clock.py``);
* the collective **sequence id** the coordinator assigned to the fused
  op (``args.seq``), identical on every rank, so the merge can correlate
  "rank 2's execute span for seq 417" with everyone else's.

Phase vocabulary is FIXED — ``enqueue``/``negotiate``/``fuse``/
``execute``/``done`` — enforced here at emit time and by the source lint
in ``tests/test_metrics_lint.py``; ad-hoc phase strings would break the
merge's straggler attribution and every downstream dashboard.

Spans are buffered in memory (a few dicts per executed collective —
far below the event rate the Timeline's writer thread exists for) and
written as one JSON array at close; overflow beyond
``HOROVOD_TRACE_MAX_EVENTS`` drops-with-count like the timeline.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Optional

from ..analysis.lockorder import make_lock
from ..common.config import _env_int

# The fixed phase vocabulary: one chrome "thread" per phase per rank.
# PHASES is the collective pipeline (what the controller emits, what the
# merge's straggler attribution consumes); SERVING_PHASES is the serving
# engine's iteration loop (schedule / prefill / decode, written to its
# own ``trace.serving.rank<N>.json`` — deliberately NOT matched by the
# merge's rank-file pattern, so serving spans never pollute collective
# straggler attribution). ALL_PHASES is the writer's legal set; both
# sub-vocabularies stay fixed and lint-enforced
# (tests/test_metrics_lint.py). New entries append — tids are
# positional and pinned by the merge golden file.
PHASES = ("enqueue", "negotiate", "fuse", "execute", "done")
SERVING_PHASES = ("schedule", "prefill", "decode")
ALL_PHASES = PHASES + SERVING_PHASES

DEFAULT_MAX_EVENTS = 1 << 20

TRACE_FILE_FMT = "trace.rank{rank}.json"
MERGED_TRACE_FILE = "merged_trace.json"
OFFSETS_FILE = "clock_offsets.json"
REPORT_FILE = "straggler_report.json"


def rank_trace_path(trace_dir: str, rank: int) -> str:
    return os.path.join(trace_dir, TRACE_FILE_FMT.format(rank=rank))


class TraceWriter:
    """Buffered span writer for one rank. Thread-safe; close() is
    idempotent (the shutdown trace exchange and the controller's
    failure-path cleanup may both reach it)."""

    def __init__(self, path: str, rank: int,
                 max_events: Optional[int] = None):
        self._path = path
        self.rank = int(rank)
        self._mono0 = time.monotonic()
        self._wall0 = time.time()  # hvdlint: disable=HVD004 (anchor)
        self._max = max_events if max_events is not None else max(
            1024, _env_int("HOROVOD_TRACE_MAX_EVENTS", DEFAULT_MAX_EVENTS))
        self._lock = make_lock("trace.writer")
        self._events: list = []
        self._dropped = 0
        self._closed = False

    # -- emit ---------------------------------------------------------------

    def span(self, phase: str, t0: float, t1: float, seq: Optional[int] = None,
             op: Optional[str] = None, **args) -> None:
        """One complete ("X") event. ``t0``/``t1`` are ``time.monotonic()``
        stamps from this process; they are stored relative to the file's
        monotonic origin, which the ``clock_sync`` anchor ties to wall
        time."""
        if phase not in ALL_PHASES:
            raise ValueError(
                f"unknown trace phase {phase!r}; the vocabulary is fixed: "
                f"{ALL_PHASES}")
        a = dict(args)
        if seq is not None:
            a["seq"] = int(seq)
        if op is not None:
            a["op"] = op
        event = {
            "name": phase,
            "ph": "X",
            "pid": self.rank,
            # One chrome thread per phase: overlapping spans of DIFFERENT
            # phases (enqueue of op B during execute of op A) land on
            # separate tracks instead of mis-nesting.
            "tid": ALL_PHASES.index(phase) + 1,
            "ts": int(round((t0 - self._mono0) * 1e6)),
            "dur": max(0, int(round((t1 - t0) * 1e6))),
            "args": a,
        }
        with self._lock:
            if self._closed:
                return
            if len(self._events) >= self._max:
                self._dropped += 1
                return
            self._events.append(event)

    # -- lifecycle ----------------------------------------------------------

    def _metadata(self) -> list:
        meta = [{
            # The anchor that makes this file mergeable: absolute wall
            # clock at the monotonic origin (ts == 0), plus the rank.
            "name": "clock_sync", "ph": "M", "pid": self.rank,
            "args": {"wall_anchor": self._wall0,
                     "monotonic_origin": self._mono0,
                     "rank": self.rank},
        }, {
            "name": "process_name", "ph": "M", "pid": self.rank,
            "args": {"name": f"rank {self.rank}"},
        }, {
            "name": "process_sort_index", "ph": "M", "pid": self.rank,
            "args": {"sort_index": self.rank},
        }]
        for i, phase in enumerate(ALL_PHASES):
            meta.append({"name": "thread_name", "ph": "M", "pid": self.rank,
                         "tid": i + 1, "args": {"name": phase}})
        return meta

    def close(self) -> Optional[str]:
        """Write the file (metadata + spans + trailer); returns the path,
        or None if a prior close already wrote it."""
        with self._lock:
            if self._closed:
                return None
            self._closed = True
            events = self._events
            self._events = []
            dropped = self._dropped
        out = self._metadata() + events
        out.append({"name": "trace_end", "ph": "M", "pid": self.rank,
                    "args": {"dropped_events": dropped,
                             "events": len(events)}})
        with open(self._path, "w") as f:
            for i, ev in enumerate(out):
                f.write(("[\n" if i == 0 else ",\n") + json.dumps(ev))
            f.write("\n]\n")
        return self._path

    @property
    def path(self) -> str:
        return self._path

    def read_bytes(self) -> bytes:
        """The written file's bytes (for the shutdown push over the
        wire). Empty when close() hasn't produced a file."""
        try:
            with open(self._path, "rb") as f:
                return f.read()
        except OSError:
            return b""
