"""The serving engine: continuous batching over the paged decode path.

One :class:`ServingEngine` owns (a) the physical paged KV pools (one
``(num_blocks + 1, block_size, Hkv*head_dim)`` device array pair per
layer — block 0 is the null block), (b) the
:class:`~horovod_tpu.serving.scheduler.Scheduler` bookkeeping, and (c)
two compiled programs that do all device work:

* ``_paged_prefill`` — one request's (re-)prefill: the prompt runs the
  model's ordinary contiguous prefill (``hvd.decode.prefill`` — the
  exact computation ``generate()`` performs, so serving prefill is
  bit-identical to bare decode), the produced KV rows scatter into the
  request's blocks as whole pages, and the first new token is sampled
  from the final logits.
* ``_paged_step`` — ONE decode step for the whole slot batch: every
  running sequence advances one token through the paged decode kernel
  (``ops.decode_attention.paged_decode_attention``; per-sequence
  positions, block-table indirection in the kernel's index_map), riding
  the same sharding classifier as ``generate()`` — a heads-on-TP mesh
  keeps the Pallas fast path per shard
  (``sharded_paged_decode_step``), with in-place per-shard pool writes.

Between the two sits iteration-level scheduling: sequences join and
leave the decode batch at step boundaries, so a finished short request
never holds the batch hostage and a newly arrived one starts on the
next step (the continuous-batching answer to the b8 decode latency
floor, ``examples/decode_floor_probe.py``).

Both programs compile once per shape class (the step exactly once per
engine; prefill once per distinct prompt-block count) and both donate
the pools, so the cache update stays in place step over step.

Threading: the engine is driven either synchronously
(``run_until_idle()`` — deterministic, what the tests and the parity
acceptance use) or by its own daemon loop (``start()``; thread named
``hvd-serving-engine``). All state lives under one lock; device calls
run outside it so ``submit``/``stream`` never block on a decode step.
"""

from __future__ import annotations

import functools
import itertools
import os
import threading
import time
import weakref
from collections import deque
from typing import Dict, Iterator, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..analysis.lockorder import make_lock
from ..common import config as hvd_config
from ..common import hvd_logging as logging
from .kv_blocks import BlockPool, padded_table
from .prefix_cache import PrefixCache
from .scheduler import (
    CANCELLED,
    FAILED,
    FINISHED,
    REJECTED,
    RUNNING,
    TERMINAL_STATES,
    WAITING,
    CancelledError,
    RejectedError,
    Request,
    Scheduler,
    ServingConfig,
    zero_stats,
)

_m = None

# Every live engine in this process (a fleet runs several): the
# unlabeled hvd_serving_* gauges describe the PROCESS, so each sweep
# publishes the sum over live engines' latest per-engine snapshots —
# a lone engine's sweep would otherwise clobber the fleet view with
# just its own pool.
_LIVE_ENGINES: "weakref.WeakSet" = weakref.WeakSet()


def _serving_metrics():
    """Lazy registration (tests/test_metrics_lint.py: never at import
    time). One owner per ``hvd_serving_*`` series — docs/metrics.md."""
    global _m
    if _m is None:
        from types import SimpleNamespace

        from .. import metrics

        _m = SimpleNamespace(
            queue_depth=metrics.gauge(
                "hvd_serving_queue_depth",
                "Requests waiting for a decode slot."),
            queue_limit=metrics.gauge(
                "hvd_serving_queue_limit",
                "Admission bound on the waiting queue "
                "(HOROVOD_SERVING_QUEUE_DEPTH)."),
            active=metrics.gauge(
                "hvd_serving_active_sequences",
                "Sequences in the decode batch right now."),
            blocks_in_use=metrics.gauge(
                "hvd_serving_blocks_in_use",
                "Allocated KV-cache blocks."),
            blocks_total=metrics.gauge(
                "hvd_serving_blocks_total",
                "KV-cache pool capacity in blocks (null block excluded)."),
            block_util=metrics.gauge(
                "hvd_serving_block_utilization",
                "blocks_in_use / blocks_total, 0..1."),
            requests=metrics.counter(
                "hvd_serving_requests_total",
                "Serving requests by terminal outcome.", ("outcome",)),
            preemptions=metrics.counter(
                "hvd_serving_preemptions_total",
                "Sequences preempted (blocks dropped, recompute queued) "
                "because the block pool ran dry."),
            tokens=metrics.counter(
                "hvd_serving_tokens_generated_total",
                "Tokens produced across all requests."),
            steps=metrics.counter(
                "hvd_serving_steps_total",
                "Continuous-batching decode steps executed."),
            ttft=metrics.histogram(
                "hvd_serving_ttft_seconds",
                "Submit-to-first-token latency per request."),
            tpot=metrics.histogram(
                "hvd_serving_tpot_seconds",
                "Inter-token latency per generated token (decode steps "
                "plus any scheduling/preemption stall between them)."),
            prefix_hits=metrics.counter(
                "hvd_serving_prefix_hits_total",
                "Whole KV pages admitted warm (mapped copy-free onto "
                "blocks the prefix index already held)."),
            prefix_misses=metrics.counter(
                "hvd_serving_prefix_misses_total",
                "Whole KV pages that had to prefill cold."),
            prefix_cached=metrics.gauge(
                "hvd_serving_prefix_cached_blocks",
                "Blocks currently referenced by the prefix index."),
            prefix_evictions=metrics.counter(
                "hvd_serving_prefix_evictions_total",
                "Prefix-index entries dropped (pool pressure or "
                "capacity LRU)."),
            blocks_shared=metrics.gauge(
                "hvd_serving_blocks_shared",
                "Blocks with more than one live reference right now."),
            cow=metrics.counter(
                "hvd_serving_cow_copies_total",
                "Copy-on-write page copies (a sequence about to write "
                "into a shared page got a private copy first)."),
        )
    return _m


# ---------------------------------------------------------------------------
# Compiled programs. Module-level with the model STATIC (flax modules hash
# by structure) so repeated engine steps hit the jit cache — the _decode
# convention. ``path`` (+ mesh/axes) is part of the cache key for the same
# reason it is in generate(): a bare global flag would be ignored on a
# cache hit. Both donate the pools: the KV update must stay in place.


def _decode_path_ctx(path, mesh, head_axis, batch_axis):
    from ..models.llama import decode_path_context

    return decode_path_context(path, mesh, head_axis, batch_axis)


@functools.partial(
    jax.jit,
    static_argnames=("model", "greedy", "path", "mesh",
                     "head_axis", "batch_axis"),
    donate_argnums=(1,))
def _paged_prefill(model, pools, variables, prompt, plen, table_row, rng,
                   temperature, greedy=True, path="kernel",
                   mesh=None, head_axis=None, batch_axis=None):
    """(Re-)prefill ONE request into its blocks; returns
    ``(first_token, new_pools)``. ``prompt`` arrives PADDED to the
    page-aligned window (``plen`` real tokens rounded up to the block
    size), so the jit cache is keyed per block COUNT, not per prompt
    length — a production length mix compiles ~window/block_size
    programs, not one per length. The pad rows are causally inert: the
    picked logit (position ``plen - 1``) attends only positions below
    it, and the garbage KV rows they scatter into the last page sit
    above every later causal bound until the decode loop overwrites
    them position by position.

    The prompt runs the model's contiguous prefill on a scratch cache
    (the einsum-over-fresh-rows path — no matmul consumes the scratch
    buffers), then each layer's KV rows scatter into the pool as whole
    pages."""
    cfg = model.config
    head_dim = cfg.dim // cfg.num_heads
    f = cfg.num_kv_heads * head_dim
    layers = sorted(pools)
    dtype = pools[layers[0]]["k"].dtype
    block_size = pools[layers[0]]["k"].shape[1]
    window = prompt.shape[1]
    scratch = {
        layer: {"k": jnp.zeros((1, window, f), dtype),
                "v": jnp.zeros((1, window, f), dtype)}
        for layer in layers
    }
    with _decode_path_ctx(path, mesh, head_axis, batch_axis):
        logits, scratch = model.apply(variables, prompt, cache=scratch,
                                      cache_index=0)
    nb = window // block_size
    new_pools = {}
    for layer in layers:
        pages_k = scratch[layer]["k"][0].reshape(nb, block_size, f)
        pages_v = scratch[layer]["v"][0].reshape(nb, block_size, f)
        new_pools[layer] = {
            "k": pools[layer]["k"].at[table_row].set(pages_k),
            "v": pools[layer]["v"].at[table_row].set(pages_v),
        }
    last = logits[0, plen - 1].astype(jnp.float32)
    if greedy:
        token = jnp.argmax(last, axis=-1)
    else:
        token = jax.random.categorical(rng, last / temperature)
    return token, new_pools


@functools.partial(
    jax.jit,
    static_argnames=("model", "warm_pages", "total_pages", "greedy",
                     "path", "mesh", "head_axis", "batch_axis"),
    donate_argnums=(1,))
def _paged_warm_prefill(model, pools, variables, tail, plen, warm_table,
                        cold_table, rng, temperature, warm_pages=1,
                        total_pages=2, greedy=True, path="kernel",
                        mesh=None, head_axis=None, batch_axis=None):
    """Prefill of a request whose first ``warm_pages`` whole pages are
    already in the pool (prefix-cache hit): gather the warm KV pages
    into the scratch cache's leading rows, run the model over ONLY the
    cold tail tokens at ``cache_index = warm_len`` (the general
    chunked-append attention path — each tail query attends the warm
    history plus the fresh rows under the positional mask), scatter the
    cold pages into ``cold_table``, and sample the first token from the
    logits at global position ``plen - 1``.

    Parity with the cold :func:`_paged_prefill` is bitwise in f32: the
    scratch window is the SAME ``total_pages * block_size`` rows either
    way (softmax/matmul reduction extents match), warm rows hold the
    byte-identical KV an earlier prefill wrote, and rows past the valid
    window are zeros whose masked logits contribute exact zeros. The jit
    cache is keyed per (warm, total) page-count pair."""
    cfg = model.config
    head_dim = cfg.dim // cfg.num_heads
    f = cfg.num_kv_heads * head_dim
    layers = sorted(pools)
    dtype = pools[layers[0]]["k"].dtype
    block_size = pools[layers[0]]["k"].shape[1]
    window = total_pages * block_size
    warm_len = warm_pages * block_size
    scratch = {}
    for layer in layers:
        warm_k = pools[layer]["k"][warm_table].reshape(1, warm_len, f)
        warm_v = pools[layer]["v"][warm_table].reshape(1, warm_len, f)
        zeros = jnp.zeros((1, window, f), dtype)
        scratch[layer] = {
            "k": zeros.at[:, :warm_len].set(warm_k),
            "v": zeros.at[:, :warm_len].set(warm_v),
        }
    with _decode_path_ctx(path, mesh, head_axis, batch_axis):
        logits, scratch = model.apply(variables, tail, cache=scratch,
                                      cache_index=warm_len)
    nb_cold = total_pages - warm_pages
    new_pools = {}
    for layer in layers:
        pages_k = scratch[layer]["k"][0, warm_len:].reshape(
            nb_cold, block_size, f)
        pages_v = scratch[layer]["v"][0, warm_len:].reshape(
            nb_cold, block_size, f)
        new_pools[layer] = {
            "k": pools[layer]["k"].at[cold_table].set(pages_k),
            "v": pools[layer]["v"].at[cold_table].set(pages_v),
        }
    last = logits[0, plen - 1 - warm_len].astype(jnp.float32)
    if greedy:
        token = jnp.argmax(last, axis=-1)
    else:
        token = jax.random.categorical(rng, last / temperature)
    return token, new_pools


@functools.partial(jax.jit, donate_argnums=(0,))
def _copy_blocks(pools, src, dst):
    """Copy-on-write: duplicate whole pages ``src[i] -> dst[i]`` in every
    layer's pools (one fused gather+scatter per layer; keyed per copy
    count, and COW is rare by construction — see
    ``Scheduler.ensure_decode_capacity``)."""
    out = {}
    for layer in sorted(pools):
        k = pools[layer]["k"]
        v = pools[layer]["v"]
        out[layer] = {"k": k.at[dst].set(k[src]),
                      "v": v.at[dst].set(v[src])}
    return out


@functools.partial(
    jax.jit,
    static_argnames=("model", "all_greedy", "path", "mesh", "head_axis",
                     "batch_axis"),
    donate_argnums=(1,))
def _paged_step(model, pools, variables, tokens, lens, tables, temps, rng,
                all_greedy=True, path="kernel", mesh=None, head_axis=None,
                batch_axis=None):
    """One continuous-batching decode step over the whole slot batch:
    every slot's incoming token (position ``lens[i]``) writes its KV row
    into its block and attends its own window. Inactive slots point at
    the null block with lens 0 — their lane computes garbage that the
    host discards. ``all_greedy`` is static (known when the host builds
    the batch): the default temperature-0 workload then never traces the
    discarded gumbel sampling over (max_batch, vocab). Returns
    ``(next_tokens, new_pools)``."""
    cache = {
        layer: {"k": pools[layer]["k"], "v": pools[layer]["v"],
                "tables": tables}
        for layer in pools
    }
    with _decode_path_ctx(path, mesh, head_axis, batch_axis):
        logits, cache = model.apply(variables, tokens[:, None],
                                    cache=cache, cache_index=lens)
    last = logits[:, -1].astype(jnp.float32)
    next_tokens = jnp.argmax(last, axis=-1)
    if not all_greedy:
        sampled = jax.random.categorical(
            rng, last / jnp.maximum(temps, 1e-6)[:, None])
        next_tokens = jnp.where(temps > 0.0, sampled, next_tokens)
    new_pools = {layer: {"k": cache[layer]["k"], "v": cache[layer]["v"]}
                 for layer in cache}
    return next_tokens, new_pools


class RequestHandle:
    """Caller's view of one submitted request: block on the result,
    stream tokens as they are produced, or cancel."""

    def __init__(self, engine: "ServingEngine", req: Request):
        self._engine = engine
        self._req = req

    @property
    def rid(self) -> int:
        return self._req.rid

    @property
    def state(self) -> str:
        with self._engine._cond:
            return self._req.state

    @property
    def warm_pages(self) -> int:
        """Whole pages this request's last admission mapped warm from
        the prefix cache (0 = fully cold) — the loadgen's warm/cold
        TTFT split reads this."""
        with self._engine._cond:
            return self._req.warm_pages

    def ttft_seconds(self) -> Optional[float]:
        """Submit-to-first-token latency, or None before the first
        token."""
        with self._engine._cond:
            if self._req.first_token_t is None:
                return None
            return self._req.first_token_t - self._req.submit_t

    def result(self, timeout: Optional[float] = None) -> List[int]:
        """Generated token ids (prompt excluded). Raises
        :class:`CancelledError` on cancellation, ``RuntimeError`` on
        engine failure, ``TimeoutError`` on timeout."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._engine._cond:
            while self._req.state not in TERMINAL_STATES:
                remaining = None
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise TimeoutError(
                            f"request {self._req.rid} still "
                            f"{self._req.state} after {timeout}s")
                self._engine._cond.wait(remaining)
            return self._finish_locked()

    def _finish_locked(self) -> List[int]:
        if self._req.state == FINISHED:
            return list(self._req.tokens)
        if self._req.state == CANCELLED:
            raise CancelledError(f"request {self._req.rid} was cancelled")
        raise RuntimeError(
            f"request {self._req.rid} {self._req.state}: "
            f"{self._req.error}")

    def stream(self, timeout: Optional[float] = None) -> Iterator[int]:
        """Yield generated token ids as they are produced. The lock is
        dropped while the consumer runs, so slow consumers never stall
        the engine loop."""
        sent = 0
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            with self._engine._cond:
                while (len(self._req.tokens) <= sent
                       and self._req.state not in TERMINAL_STATES):
                    remaining = None
                    if deadline is not None:
                        remaining = deadline - time.monotonic()
                        if remaining <= 0:
                            raise TimeoutError(
                                f"request {self._req.rid} produced no "
                                f"token within {timeout}s")
                    self._engine._cond.wait(remaining)
                chunk = self._req.tokens[sent:]
                state = self._req.state
            for token in chunk:
                yield token
            sent += len(chunk)
            if state in TERMINAL_STATES and not chunk:
                if state != FINISHED:
                    with self._engine._cond:
                        self._finish_locked()
                return

    def cancel(self) -> None:
        """Cancel: a waiting request leaves the queue immediately, a
        running one is evicted (blocks freed) at the next step
        boundary."""
        self._engine._cancel(self._req)


class ServingEngine:
    """See module docstring. ``model`` is any causal LM with the cache
    call contract (``LlamaLM``, ``MoeLM``); ``variables`` may be
    TP-sharded with the Megatron specs — the engine classifies the
    sharding exactly like ``generate()`` and keeps the Pallas kernel
    through ``shard_map`` on heads-on-TP meshes."""

    def __init__(self, model, variables, config: Optional[ServingConfig]
                 = None, seed: int = 0):
        from ..models.llama import classify_decode_sharding

        self._model = model
        self._variables = variables
        cfg = config if config is not None else ServingConfig.from_env()
        mcfg = model.config
        model_max = int(getattr(mcfg, "max_seq_len", 0) or 0)
        max_seq = cfg.max_seq_len or model_max
        if not max_seq:
            raise ValueError(
                "the model declares no max_seq_len; set "
                "ServingConfig.max_seq_len (HOROVOD_SERVING_MAX_SEQ_LEN)")
        if model_max:
            max_seq = min(max_seq, model_max)
        self._config = cfg = ServingConfig(
            max_batch=cfg.max_batch, block_size=cfg.block_size,
            num_blocks=cfg.num_blocks, queue_depth=cfg.queue_depth,
            max_seq_len=max_seq, prefix_cache=cfg.prefix_cache,
            prefix_capacity=cfg.prefix_capacity)
        self._table_slots = (max_seq + cfg.block_size - 1) // cfg.block_size
        num_blocks = cfg.num_blocks or cfg.max_batch * self._table_slots
        pool = BlockPool(num_blocks, cfg.block_size)
        self._prefix = (PrefixCache(pool, cfg.prefix_capacity)
                        if cfg.prefix_cache else None)
        self._sched = Scheduler(pool, cfg.max_batch, cfg.queue_depth,
                                max_seq, prefix_cache=self._prefix)

        # Decode-path classification, exactly generate()'s: the dummy
        # prompt is host-resident (replicated), so the verdict follows
        # the VARIABLES' sharding.
        dummy = jnp.zeros((cfg.max_batch, 1), jnp.int32)
        self._path = classify_decode_sharding(variables, dummy,
                                              mcfg.num_kv_heads)
        if self._path.batch_axis is not None:
            # Serving batches are host-built and replicated, and the
            # shared block pool has no batch dim to shard over dp (see
            # sharded_paged_decode_step) — dp x tp means one engine per
            # dp replica. The replicated dummy above already yields
            # None; this is belt and braces against future classifier
            # inputs.
            import dataclasses

            self._path = dataclasses.replace(self._path, batch_axis=None)

        head_dim = mcfg.dim // mcfg.num_heads
        f = mcfg.num_kv_heads * head_dim
        shape = (num_blocks + 1, cfg.block_size, f)
        sharding = None
        if self._path.path == "kernel_tp":
            from jax.sharding import NamedSharding
            from jax.sharding import PartitionSpec as P

            sharding = NamedSharding(self._path.mesh,
                                     P(None, None, self._path.head_axis))

        def _pool_array():
            arr = jnp.zeros(shape, mcfg.dtype)
            return jax.device_put(arr, sharding) if sharding else arr

        self._pools = {
            f"layer_{i}": {"k": _pool_array(), "v": _pool_array()}
            for i in range(mcfg.num_layers)
        }

        self._lock = make_lock("serving.engine")
        self._cond = threading.Condition(self._lock)
        self._rng = jax.random.PRNGKey(seed)
        self._rid = itertools.count()
        self._thread: Optional[threading.Thread] = None
        self._stop = False
        self._closed = False
        self._submitted = 0
        self._finished = 0
        self._cancelled = 0
        self._tokens_generated = 0
        self._steps = 0
        # Sliding latency windows: one float per token would grow RSS
        # without bound on a long-lived engine, and stats() sorts these
        # under the lock — bound both costs. The metrics histograms keep
        # the full-lifetime distribution.
        self._ttfts: deque = deque(maxlen=4096)
        self._tpots: deque = deque(maxlen=4096)
        self._prefix_published: Dict[str, int] = {}
        self._live_peak = 0
        # Latest per-engine gauge numbers (whole dict swapped atomically
        # under the GIL; peers read it WITHOUT this engine's lock when
        # summing the process-wide gauges — see _update_gauges).
        self._gauge_snapshot: Dict[str, float] = {}
        _LIVE_ENGINES.add(self)
        self._tracer = None
        self._trace_checked = False

    # -- public API ---------------------------------------------------------

    @property
    def config(self) -> ServingConfig:
        return self._config

    @property
    def decode_path(self):
        """The :class:`~horovod_tpu.models.llama.DecodePath` verdict the
        engine's compiled programs ride (proof-of-path for harnesses)."""
        return self._path

    @property
    def closed(self) -> bool:
        """True once the engine can no longer serve (shutdown, or its
        loop died) — the router's liveness probe."""
        with self._cond:
            return self._closed

    def submit(self, prompt, max_new_tokens: int,
               temperature: float = 0.0) -> RequestHandle:
        """Admit one generation request. Raises
        :class:`~horovod_tpu.serving.RejectedError` when admission
        control refuses (queue at bound / request can never fit), and
        ``ValueError`` on malformed arguments."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        with self._cond:
            if self._closed:
                raise RuntimeError("engine is shut down")
            try:
                self._sched.check_admissible(prompt.shape[0],
                                             int(max_new_tokens))
            except RejectedError:
                if _metrics_on():
                    m = _serving_metrics()
                    m.requests.labels(REJECTED).inc()
                    # Publish the queue gauges here too: an engine whose
                    # every submission is rejected would otherwise never
                    # set them, and the doctor's saturation rule gates
                    # on the limit gauge being present. (We hold the
                    # engine lock, so refresh our own snapshot directly
                    # and publish the lock-free fleet sum —
                    # _update_gauges would re-take the lock.)
                    snap = dict(self._gauge_snapshot)
                    snap["queue_depth"] = self._sched.queue_depth_now()
                    snap["queue_limit"] = self._sched.queue_depth
                    self._gauge_snapshot = snap
                    _publish_gauge_totals(m)
                raise
            req = Request(rid=next(self._rid), prompt=prompt,
                          max_new_tokens=int(max_new_tokens),
                          temperature=float(temperature),
                          submit_t=time.monotonic())
            self._sched.enqueue(req)
            self._submitted += 1
            self._cond.notify_all()
        self._update_gauges()
        return RequestHandle(self, req)

    def step(self) -> bool:
        """One engine iteration: retire cancellations, admit + prefill
        joiners, top up block tables (preempting on exhaustion), run one
        batched decode step. Returns whether work remains. Thread-safe
        against submit/stream, but only ONE driver may call it (the
        loop thread, or the caller in synchronous mode)."""
        t_sched = time.monotonic()
        with self._cond:
            for req in list(self._sched.running.values()):
                if req.cancel_requested:
                    self._sched.retire(req, CANCELLED)
                    self._cancelled += 1
                    if _metrics_on():
                        _serving_metrics().requests.labels(CANCELLED).inc()
                    self._cond.notify_all()
            # A cancel that landed while the request sat RUNNING may have
            # been overtaken by a preemption (RUNNING -> WAITING with the
            # flag still set); purge those here or admit() would pay a
            # full recompute prefill for a request the very next scan
            # retires.
            for req in [r for r in self._sched.waiting
                        if r.cancel_requested]:
                self._sched.cancel_waiting(req)
                self._cancelled += 1
                if _metrics_on():
                    _serving_metrics().requests.labels(CANCELLED).inc()
                self._cond.notify_all()
            admitted = self._sched.admit()
            self._note_live_blocks()
        tracer = self._maybe_tracer()
        if tracer is not None:
            tracer.span("schedule", t_sched, time.monotonic(),
                        admitted=len(admitted),
                        running=len(self._sched.running))

        for req in admitted:
            self._prefill(req)

        with self._cond:
            preempted = self._sched.ensure_decode_capacity()
            copies = self._sched.pending_copies
            self._sched.pending_copies = []
            if preempted and _metrics_on():
                _serving_metrics().preemptions.inc(len(preempted))
            self._note_live_blocks()
            batch = self._sched.active()
            arrays = self._build_batch(batch) if batch else None
        if preempted:
            logging.warning(
                "serving: block pool exhausted — preempted %d sequence(s) "
                "for recompute (%s)", len(preempted),
                ", ".join(f"rid {r.rid}" for r in preempted))
        if copies:
            # Copy-on-write: duplicate the shared pages into the fresh
            # private blocks BEFORE the decode step writes into them.
            # Only this (single-driver) thread mutates block ownership,
            # so the source pages cannot be re-written before the copy.
            self._pools = _copy_blocks(
                self._pools,
                jnp.asarray([s for s, _ in copies], jnp.int32),
                jnp.asarray([d for _, d in copies], jnp.int32))
            if _metrics_on():
                _serving_metrics().cow.inc(len(copies))

        if arrays is not None:
            t_dec = time.monotonic()
            tokens, lens, tables, temps = arrays
            rng = self._next_rng()
            out_tokens, self._pools = _paged_step(
                self._model, self._pools, self._variables, tokens, lens,
                tables, temps, rng,
                all_greedy=bool((temps <= 0.0).all()),
                path=self._path.path,
                mesh=self._path.mesh, head_axis=self._path.head_axis,
                batch_axis=self._path.batch_axis)
            out_host = np.asarray(out_tokens)
            with self._cond:
                for req in batch:
                    if req.state == RUNNING and req.slot is not None:
                        self._append_token(req, int(out_host[req.slot]))
                self._steps += 1
            if _metrics_on():
                _serving_metrics().steps.inc()
            if tracer is not None:
                tracer.span("decode", t_dec, time.monotonic(),
                            batch=len(batch))
        self._update_gauges()
        with self._cond:
            return self._sched.has_work()

    def run_until_idle(self, max_steps: int = 100000) -> None:
        """Drive the engine synchronously until no request is waiting or
        running (tests, benches: fully deterministic scheduling)."""
        for _ in range(max_steps):
            if not self.step():
                return
        raise RuntimeError(f"engine still busy after {max_steps} steps")

    def start(self) -> "ServingEngine":
        """Spawn the background loop (daemon thread, named per the
        threading discipline). Idempotent."""
        with self._cond:
            if self._closed:
                raise RuntimeError("engine is shut down")
            if self._thread is not None:
                return self
            self._stop = False
            self._thread = threading.Thread(
                target=self._run_loop, name="hvd-serving-engine",
                daemon=True)
            self._thread.start()
        return self

    def shutdown(self, timeout: float = 10.0) -> None:
        """Stop the loop, fail whatever is still queued or running, and
        close the trace file. Idempotent."""
        with self._cond:
            self._stop = True
            self._closed = True
            thread = self._thread
            self._thread = None
            self._cond.notify_all()
        if thread is not None:
            thread.join(timeout)
        with self._cond:
            for req in list(self._sched.waiting) + list(
                    self._sched.running.values()):
                if req.state not in TERMINAL_STATES:
                    self._sched.cancel_waiting(req)
                    self._sched.retire(req, FAILED, "engine shut down")
                    if _metrics_on():
                        _serving_metrics().requests.labels(FAILED).inc()
            self._sched.waiting.clear()
            if self._prefix is not None:
                self._prefix.clear()   # release cache-held block refs
            self._cond.notify_all()
        if self._tracer is not None:
            self._tracer.close()

    def stats(self) -> Dict[str, float]:
        """Serving stats snapshot — ``zero_stats()`` shape, every key
        always present (docs/serving.md has the catalog)."""
        with self._cond:
            s = zero_stats()
            pool = self._sched.pool
            s.update({
                "queue_depth": self._sched.queue_depth_now(),
                "queue_limit": self._sched.queue_depth,
                "active_sequences": len(self._sched.running),
                "blocks_total": pool.num_blocks,
                "blocks_in_use": pool.blocks_in_use,
                "blocks_peak": pool.peak_in_use,
                "block_utilization": round(pool.utilization(), 4),
                "requests_submitted": self._submitted,
                "requests_finished": self._finished,
                "requests_rejected": self._sched.rejected,
                "requests_cancelled": self._cancelled,
                "preemptions": self._sched.preempted,
                "tokens_generated": self._tokens_generated,
                "steps": self._steps,
                "ttft_p50_seconds": _quantile(self._ttfts, 0.5),
                "ttft_p99_seconds": _quantile(self._ttfts, 0.99),
                "tpot_p50_seconds": _quantile(self._tpots, 0.5),
                "tpot_p99_seconds": _quantile(self._tpots, 0.99),
                "blocks_shared": pool.blocks_shared,
                "cow_copies": self._sched.cow_copies,
                "blocks_live": self._live_blocks(),
                "blocks_live_peak": self._live_peak,
            })
            if self._prefix is not None:
                s.update(self._prefix.stats())
            return s

    # -- internals ----------------------------------------------------------

    def _run_loop(self) -> None:
        while True:
            with self._cond:
                while not self._stop and not self._sched.has_work():
                    self._cond.wait(0.05)
                if self._stop:
                    return
            try:
                self.step()
            except Exception as exc:  # the loop must fail LOUDLY
                logging.error("serving engine loop died: %s", exc)
                with self._cond:
                    # The engine is dead, not idle: close it so later
                    # submit() raises instead of queueing requests no
                    # loop will ever process (and start() can't silently
                    # no-op on the stale thread handle).
                    self._closed = True
                    self._stop = True
                    self._thread = None
                    for req in list(self._sched.waiting) + list(
                            self._sched.running.values()):
                        if req.state not in TERMINAL_STATES:
                            self._sched.retire(req, FAILED, str(exc))
                            if _metrics_on():
                                _serving_metrics().requests.labels(
                                    FAILED).inc()
                    self._sched.waiting.clear()
                    if self._prefix is not None:
                        self._prefix.clear()
                    self._cond.notify_all()
                return

    def _cancel(self, req: Request) -> None:
        with self._cond:
            if req.state in TERMINAL_STATES:
                return
            if req.state == WAITING:
                self._sched.cancel_waiting(req)
                self._cancelled += 1
                if _metrics_on():
                    _serving_metrics().requests.labels(CANCELLED).inc()
                self._cond.notify_all()
            else:
                req.cancel_requested = True
                self._cond.notify_all()
        self._update_gauges()

    def _prefill(self, req: Request) -> None:
        t0 = time.monotonic()
        prompt = req.current_prompt()
        plen = int(prompt.shape[0])
        nb = self._sched.pool.blocks_for(plen)
        window = nb * self._config.block_size
        warm = min(req.warm_pages, max(0, nb - 1))
        # Pad to the page boundary so prefill compiles per block count,
        # not per length (see _paged_prefill).
        padded = np.zeros((1, window), np.int32)
        padded[0, :plen] = prompt
        rng = self._next_rng()
        greedy = req.temperature <= 0.0
        if warm:
            # Prefix-cache hit: the warm pages' KV already sits in the
            # pool — only the cold tail runs through the model.
            warm_len = warm * self._config.block_size
            token, self._pools = _paged_warm_prefill(
                self._model, self._pools, self._variables,
                jnp.asarray(padded[:, warm_len:]), jnp.int32(plen),
                jnp.asarray(req.blocks[:warm], jnp.int32),
                jnp.asarray(req.blocks[warm:nb], jnp.int32), rng,
                jnp.float32(max(req.temperature, 1e-6)),
                warm_pages=warm, total_pages=nb, greedy=greedy,
                path=self._path.path, mesh=self._path.mesh,
                head_axis=self._path.head_axis,
                batch_axis=self._path.batch_axis)
        else:
            table_row = jnp.asarray(req.blocks[:nb], jnp.int32)
            token, self._pools = _paged_prefill(
                self._model, self._pools, self._variables,
                jnp.asarray(padded), jnp.int32(plen), table_row, rng,
                jnp.float32(max(req.temperature, 1e-6)),
                greedy=greedy, path=self._path.path, mesh=self._path.mesh,
                head_axis=self._path.head_axis,
                batch_axis=self._path.batch_axis)
        token = int(np.asarray(token))
        with self._cond:
            if req.state == RUNNING:       # not cancelled mid-prefill
                self._register_prefix(req, plen)
                self._append_token(req, token)
        tracer = self._maybe_tracer()
        if tracer is not None:
            tracer.span("prefill", t0, time.monotonic(), rid=req.rid,
                        len=int(prompt.shape[0]), warm_pages=warm,
                        recompute=req.preemptions)

    def _register_prefix(self, req: Request, plen: int) -> None:
        """Caller holds the lock, right after a successful prefill:
        every whole page of the (re-)prefilled prompt enters the prefix
        index keyed by its chained digest (warm pages merely refresh
        their LRU position). The index takes one pool reference per new
        entry, so these pages outlive the request."""
        if self._prefix is None:
            return
        for i in range(min(plen // self._config.block_size,
                           len(req.page_hashes))):
            self._prefix.insert(req.page_hashes[i], req.blocks[i])

    def _append_token(self, req: Request, token: int) -> None:
        """Caller holds the lock."""
        now = time.monotonic()
        req.tokens.append(token)
        self._tokens_generated += 1
        if _metrics_on():
            _serving_metrics().tokens.inc()
        if req.first_token_t is None:
            req.first_token_t = now
            ttft = now - req.submit_t
            self._ttfts.append(ttft)
            if _metrics_on():
                _serving_metrics().ttft.observe(ttft)
        elif req.last_token_t is not None:
            tpot = now - req.last_token_t
            self._tpots.append(tpot)
            if _metrics_on():
                _serving_metrics().tpot.observe(tpot)
        req.last_token_t = now
        if req.is_done():
            self._sched.retire(req, FINISHED)
            self._finished += 1
            if _metrics_on():
                _serving_metrics().requests.labels(FINISHED).inc()
        self._cond.notify_all()

    def _build_batch(self, batch: List[Request]):
        """Caller holds the lock. Slot arrays for one decode step."""
        size = self._config.max_batch
        tokens = np.zeros((size,), np.int32)
        lens = np.zeros((size,), np.int32)
        tables = np.zeros((size, self._table_slots), np.int32)
        temps = np.zeros((size,), np.float32)
        for req in batch:
            slot = req.slot
            tokens[slot] = req.tokens[-1]
            lens[slot] = req.position_of_last_token()
            tables[slot] = padded_table(req.blocks, self._table_slots)
            temps[slot] = req.temperature
        return tokens, lens, tables, temps

    def _live_blocks(self) -> int:
        """Caller holds the lock. Blocks live sequences actually pin:
        in-use minus pages only the prefix index holds (those are
        reclaimable on demand — warm spare capacity, not footprint)."""
        cache_only = (self._prefix.cache_only_blocks()
                      if self._prefix is not None else 0)
        return self._sched.pool.blocks_in_use - cache_only

    def _note_live_blocks(self) -> None:
        """Caller holds the lock; called right after each allocation
        site (admission, per-step top-up) so ``blocks_live_peak`` is the
        true high-water mark of live footprint."""
        live = self._live_blocks()
        if live > self._live_peak:
            self._live_peak = live

    def _next_rng(self):
        self._rng, sub = jax.random.split(self._rng)
        return sub

    def _update_gauges(self) -> None:
        if not _metrics_on():
            return
        m = _serving_metrics()
        with self._cond:
            pool = self._sched.pool
            self._gauge_snapshot = {
                "queue_depth": self._sched.queue_depth_now(),
                "queue_limit": self._sched.queue_depth,
                "active": len(self._sched.running),
                "blocks_in_use": pool.blocks_in_use,
                "blocks_total": pool.num_blocks,
                "blocks_shared": pool.blocks_shared,
                "prefix_cached": (self._prefix.cached_blocks
                                  if self._prefix is not None else 0),
            }
            if self._prefix is not None:
                # The cache keeps cumulative ints; counters publish the
                # delta since the last sweep (counters only ever inc).
                for attr, counter in (("hits", m.prefix_hits),
                                      ("misses", m.prefix_misses),
                                      ("evictions", m.prefix_evictions)):
                    total = getattr(self._prefix, attr)
                    seen = self._prefix_published.get(attr, 0)
                    if total > seen:
                        counter.inc(total - seen)
                        self._prefix_published[attr] = total
        _publish_gauge_totals(m)

    # -- tracing ------------------------------------------------------------

    def _maybe_tracer(self):
        if not self._trace_checked:
            self._trace_checked = True
            tdir = (hvd_config.env_str("HOROVOD_TRACE_DIR") or "").strip()
            if tdir:
                from ..common.config import env_rank
                from ..trace import TraceWriter

                os.makedirs(tdir, exist_ok=True)
                rank = env_rank() or 0
                self._tracer = TraceWriter(
                    os.path.join(tdir, f"trace.serving.rank{rank}.json"),
                    rank)
        return self._tracer


def _metrics_on() -> bool:
    from .. import metrics

    return metrics.on()


def _publish_gauge_totals(m) -> None:
    """Process-wide gauges = sum over LIVE engines' latest per-engine
    snapshots (read lock-free: each snapshot dict is swapped whole
    under the GIL, and a slightly stale peer value is fine for a
    gauge). A fleet runs several engines in one process — any single
    engine publishing only its own numbers would clobber the fleet
    view. Closed engines drop out of the sum, so a replica kill is
    visible in the gauges."""
    totals: Dict[str, float] = {}
    for engine in list(_LIVE_ENGINES):
        if engine._closed:
            continue
        for key, value in engine._gauge_snapshot.items():
            totals[key] = totals.get(key, 0) + value
    m.queue_depth.set(totals.get("queue_depth", 0))
    m.queue_limit.set(totals.get("queue_limit", 0))
    m.active.set(totals.get("active", 0))
    m.blocks_in_use.set(totals.get("blocks_in_use", 0))
    m.blocks_total.set(totals.get("blocks_total", 0))
    m.block_util.set(
        totals.get("blocks_in_use", 0) / totals["blocks_total"]
        if totals.get("blocks_total") else 0.0)
    m.blocks_shared.set(totals.get("blocks_shared", 0))
    m.prefix_cached.set(totals.get("prefix_cached", 0))


def _quantile(values, q: float) -> float:
    """Exact-list percentile, same convention as the straggler report's
    (one definition of 'p99' across the repo)."""
    from ..trace.straggler import _pctl

    est = _pctl(sorted(values), q)
    return round(est, 6) if est is not None else 0.0
