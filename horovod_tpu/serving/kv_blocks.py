"""Paged KV-cache block accounting for the serving tier.

The physical cache is ONE pool of fixed-size blocks per layer
(``(num_blocks, block_size, Hkv*head_dim)`` device arrays owned by the
engine); this module owns the *bookkeeping*: which blocks are free,
which sequence holds which blocks, and the capacity numbers the
scheduler's admission/preemption decisions and the ``hvd_serving_*``
block gauges read. Keeping the accounting in plain Python (no jax)
makes every invariant unit-testable without a device.

Why paged at all: the contiguous decode cache allocates every sequence
its max-length window up front, so a batch of mixed-length requests
fragments HBM with slack nobody attends over. Fixed-size blocks share
one pool — a sequence holds exactly ``ceil(len / block_size)`` blocks,
frees them on exit, and the freed blocks are immediately reusable by
any other sequence (the Orca/vLLM design, adapted to this repo's
row-flat GQA cache and Pallas decode kernel — see
``ops.decode_attention.paged_decode_attention``).

Block id 0 is the reserved **null block**: never allocated. Block
tables pad with it (slots past a sequence's last block), and inactive
decode slots point every table entry at it, so their one-row decode
writes land there instead of corrupting live pages. Its CONTENT is
therefore garbage by design — every read of it sits above some
sequence's causal bound and is masked to an exact zero contribution.

Blocks are **ref-counted** (round 11): prefix caching maps a warm
prompt's pages onto blocks another sequence (or the prefix index
itself) already holds, so one physical page can back several logical
sequences. ``alloc`` hands a block out at refcount 1, ``share`` takes
one more reference, and ``free`` *releases one reference* — the block
returns to the free list only when the count hits zero. Over-release
(freeing a block nobody holds) and under-release (the loud invariants
below) stay bookkeeping bugs: a silently double-freed block would be
handed to two sequences and corrupt both.

The pool is NOT thread-safe by itself: the engine serializes access
under its scheduler lock (one mutator — the engine loop — plus
submit-time capacity checks).
"""

from __future__ import annotations

from typing import Dict, List, Sequence

#: Reserved all-zero block every table pads with; never handed out.
NULL_BLOCK = 0


class OutOfBlocks(RuntimeError):
    """The pool has no free block. The scheduler's cue to preempt
    (docs/serving.md: preemption-by-recompute), never a user-facing
    error."""


class BlockPool:
    """Free-list allocator over ``num_blocks`` physical block ids.

    ``num_blocks`` counts usable blocks; the null block is extra, so
    the physical arrays hold ``num_blocks + 1`` blocks and valid ids
    are ``1..num_blocks``. Allocation order is deterministic (lowest
    free id first, frees reused LIFO-then-sorted is NOT guaranteed —
    only determinism for a fixed call sequence is), which keeps every
    scheduling trace reproducible for the seeded bench."""

    def __init__(self, num_blocks: int, block_size: int):
        if num_blocks < 1:
            raise ValueError(f"need at least one block ({num_blocks})")
        if block_size < 1:
            raise ValueError(f"block_size must be positive ({block_size})")
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        # Stack of free ids; pop() hands out ascending ids from a fresh
        # pool, and freed blocks are reused most-recently-freed first
        # (their tiles are the likeliest still warm in HBM caches).
        self._free: List[int] = list(range(self.num_blocks, 0, -1))
        self._refs: Dict[int, int] = {}
        self._peak = 0
        self._allocs = 0
        self._frees = 0
        self._shares = 0

    # -- capacity arithmetic ------------------------------------------------

    def blocks_for(self, length: int) -> int:
        """Blocks covering ``length`` token positions."""
        return max(0, (int(length) + self.block_size - 1) // self.block_size)

    def can_fit(self, blocks: int) -> bool:
        return blocks <= len(self._free)

    # -- alloc/free ---------------------------------------------------------

    def alloc(self) -> int:
        if not self._free:
            raise OutOfBlocks(
                f"all {self.num_blocks} KV blocks are in use")
        block = self._free.pop()
        self._refs[block] = 1
        self._allocs += 1
        if len(self._refs) > self._peak:
            self._peak = len(self._refs)
        return block

    def alloc_many(self, n: int) -> List[int]:
        """All-or-nothing allocation of ``n`` blocks (admission must not
        half-admit a sequence and deadlock the pool)."""
        if not self.can_fit(n):
            raise OutOfBlocks(
                f"need {n} blocks, {len(self._free)} free "
                f"of {self.num_blocks}")
        return [self.alloc() for _ in range(n)]

    # -- sharing ------------------------------------------------------------

    def share(self, block: int) -> int:
        """Take one more reference on an already-held block (prefix-cache
        warm mapping: a new sequence's page lands on an existing block
        copy-free). Sharing the null block or a block nobody holds is a
        bookkeeping bug — warm mappings must come from live index
        entries, never stale ids."""
        block = int(block)
        if block == NULL_BLOCK:
            raise ValueError("the null block is never allocated")
        if block not in self._refs:
            raise ValueError(
                f"block {block} is not allocated — cannot share a block "
                "nobody holds (stale prefix-index entry?)")
        self._refs[block] += 1
        self._shares += 1
        return block

    def refcount(self, block: int) -> int:
        """Live reference count for ``block`` (0 = free)."""
        return self._refs.get(int(block), 0)

    def is_shared(self, block: int) -> bool:
        """More than one holder: a write into this block needs
        copy-on-write first (the sharing parity contract)."""
        return self._refs.get(int(block), 0) > 1

    def free(self, blocks: Sequence[int]) -> None:
        """Release one reference per listed block; a block returns to
        the pool only when its count hits zero (a donor freeing a shared
        page leaves the data live for the other holders). Freeing the
        null block, an unallocated id, or more times than it was
        alloc'd/shared is a bookkeeping bug — loud, because a silently
        over-released block would be handed to two sequences and corrupt
        both."""
        for block in blocks:
            block = int(block)
            if block == NULL_BLOCK:
                raise ValueError("the null block is never allocated")
            refs = self._refs.get(block, 0)
            if refs <= 0:
                raise ValueError(
                    f"block {block} is not allocated (double free?)")
            if refs == 1:
                del self._refs[block]
                self._free.append(block)
            else:
                self._refs[block] = refs - 1
            self._frees += 1

    # -- views --------------------------------------------------------------

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def blocks_in_use(self) -> int:
        return len(self._refs)

    @property
    def blocks_shared(self) -> int:
        """Blocks with more than one live reference right now."""
        return sum(1 for refs in self._refs.values() if refs > 1)

    @property
    def peak_in_use(self) -> int:
        return self._peak

    def utilization(self) -> float:
        return len(self._refs) / self.num_blocks if self.num_blocks else 0.0

    def stats(self) -> Dict[str, float]:
        """Accounting snapshot (JSON-clean) for ``engine.stats()`` and
        the block gauges."""
        return {
            "blocks_total": self.num_blocks,
            "blocks_in_use": self.blocks_in_use,
            "blocks_free": self.free_blocks,
            "blocks_peak": self.peak_in_use,
            "block_utilization": round(self.utilization(), 4),
            "blocks_shared": self.blocks_shared,
            "block_allocs": self._allocs,
            "block_frees": self._frees,
            "block_shares": self._shares,
        }


def padded_table(blocks: Sequence[int], slots: int) -> List[int]:
    """A sequence's block list padded to the static table width with the
    null block (the kernel's index_map needs a rectangular table)."""
    if len(blocks) > slots:
        raise ValueError(
            f"sequence holds {len(blocks)} blocks but the table has "
            f"{slots} slots — max_seq_len accounting is broken")
    return list(blocks) + [NULL_BLOCK] * (slots - len(blocks))
