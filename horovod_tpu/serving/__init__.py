"""hvd.serving — continuous batching + paged KV cache over the sharded
decode kernel (docs/serving.md).

Round 6 built the decode data path ("as fast as the hardware allows"):
a TP-shardable Pallas decode step with in-place cache writes. This
package is the layer that turns it into a serving product ("heavy
traffic from millions of users"): requests are admitted against an
explicit queue bound, join and leave the decode batch **between**
steps (iteration-level scheduling), and share one paged KV pool so
heterogeneous sequence lengths never fragment HBM — with preemption-
by-recompute when the pool runs dry, ``hvd_serving_*`` metrics, trace
spans, and a cluster-doctor rule watching saturation.

Quick start::

    import horovod_tpu as hvd
    engine = hvd.serving.serve(model, variables)      # starts the loop
    handle = engine.submit(prompt_ids, max_new_tokens=128)
    for token in handle.stream():
        ...
    hvd.serving.stats()     # well-formed zeros before the first request

Fleet (round 11 — docs/serving.md "Fleet architecture")::

    router = hvd.serving.fleet(model, variables, replicas=3)
    handle = router.submit(prompt_ids, max_new_tokens=128)
    handle.result()         # survives a replica dying mid-request
    router.health()         # per-replica liveness + load

Warm prompts (shared system prefixes) admit copy-free through the
per-replica prefix cache (``prefix_cache.PrefixCache``) and the router
sends them where their pages are already warm (prefix affinity).

The engine module (jax, flax) loads lazily — importing ``horovod_tpu``
stays light, and ``stats()`` answers without ever touching jax when no
engine exists.
"""

from __future__ import annotations

from typing import Optional

from .kv_blocks import NULL_BLOCK, BlockPool, OutOfBlocks  # noqa: F401
from .prefix_cache import PrefixCache, page_hashes  # noqa: F401
from .router import FleetHandle, Router, RouterConfig  # noqa: F401
from .scheduler import (  # noqa: F401
    CancelledError,
    RejectedError,
    Request,
    Scheduler,
    ServingConfig,
    zero_stats,
)

__all__ = [
    "BlockPool", "OutOfBlocks", "NULL_BLOCK", "PrefixCache",
    "page_hashes", "Request", "Scheduler", "ServingConfig",
    "RejectedError", "CancelledError", "ServingEngine", "RequestHandle",
    "Router", "RouterConfig", "FleetHandle", "serve", "fleet",
    "default_engine", "default_router", "stats", "zero_stats",
]

_default_engine = None
_default_router = None


def __getattr__(name):
    # PEP 562 lazy loading: ServingEngine/RequestHandle pull in jax and
    # the model stack; `import horovod_tpu` must not pay for that.
    if name in ("ServingEngine", "RequestHandle"):
        from . import engine as _engine

        return getattr(_engine, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def serve(model, variables, config: Optional[ServingConfig] = None,
          seed: int = 0, start: bool = True):
    """Create a :class:`ServingEngine`, register it as the module
    default (``stats()`` reports it), and start its background loop
    (pass ``start=False`` to drive it synchronously)."""
    global _default_engine
    from .engine import ServingEngine

    engine = ServingEngine(model, variables, config=config, seed=seed)
    _default_engine = engine
    if start:
        engine.start()
    return engine


def fleet(model, variables, replicas: Optional[int] = None,
          config: Optional[ServingConfig] = None,
          router_config: Optional[RouterConfig] = None,
          seed: int = 0, start: bool = True) -> Router:
    """Create ``replicas`` :class:`ServingEngine` data-parallel replicas
    (default ``HOROVOD_ROUTER_REPLICAS``) behind one :class:`Router`,
    register it as the module default (``stats()`` aggregates it), and
    start every replica loop. All replicas share ``seed``: greedy
    decoding is then bit-identical on every replica, which is what makes
    death-replay lossless (docs/serving.md, parity contract)."""
    global _default_router
    from .engine import ServingEngine

    rcfg = (router_config if router_config is not None
            else RouterConfig.from_env())
    n = replicas if replicas is not None else rcfg.replicas
    if n < 1:
        raise ValueError(f"a fleet needs at least one replica ({n})")
    engines = [ServingEngine(model, variables, config=config, seed=seed)
               for _ in range(n)]
    router = Router(engines, rcfg)
    _default_router = router
    if start:
        for engine in engines:
            engine.start()
    return router


def default_engine():
    """The engine ``serve()`` registered, or None."""
    return _default_engine


def default_router():
    """The router ``fleet()`` registered, or None."""
    return _default_router


def stats() -> dict:
    """The default fleet's aggregate stats (when ``fleet()`` ran), else
    the default engine's — or, before either exists, the same dict with
    every key present and zero (the ``controller_health()`` zero-state
    convention, pinned by test)."""
    if _default_router is not None:
        return _default_router.stats()
    if _default_engine is None:
        return zero_stats()
    return _default_engine.stats()
