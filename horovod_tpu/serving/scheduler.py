"""Iteration-level request scheduling for the serving engine.

The continuous-batching core (Orca's insight): scheduling decisions are
made **between decode steps**, never inside one. Each engine iteration
the scheduler (1) retires finished/cancelled sequences and frees their
blocks, (2) admits waiting requests into free decode slots while the
block pool can hold their prompts, and (3) tops up every running
sequence's block table for the next token — preempting the youngest
sequence (free its blocks, push it back to the FRONT of the queue)
when the pool runs dry. A preempted sequence resumes by **recompute**:
its prompt plus everything it already generated is re-prefilled on
readmission, which re-creates bit-equal KV rows — so preemption costs
work, never correctness.

Everything here is plain-Python bookkeeping over
:class:`~horovod_tpu.serving.kv_blocks.BlockPool` — no jax, no clocks
beyond ``time.monotonic`` stamps — so admission, eviction, and
preemption policy are unit-testable without a device. The engine owns
the lock; every method below assumes the caller holds it.
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Deque, Dict, List, Optional, Tuple

import numpy as np

from ..common import config as hvd_config
from .kv_blocks import BlockPool, OutOfBlocks
from .prefix_cache import PrefixCache

# Request lifecycle. WAITING -> RUNNING -> FINISHED is the happy path;
# RUNNING -> WAITING is preemption-by-recompute; CANCELLED/FAILED are
# terminal from either live state; REJECTED never enters the queue.
WAITING = "waiting"
RUNNING = "running"
FINISHED = "finished"
CANCELLED = "cancelled"
FAILED = "failed"
REJECTED = "rejected"

TERMINAL_STATES = (FINISHED, CANCELLED, FAILED, REJECTED)


class RejectedError(RuntimeError):
    """Admission control refused the request (queue at its bound, or the
    request could never fit the block pool). Callers shed load or retry
    elsewhere — the engine never queues without bound."""


class CancelledError(RuntimeError):
    """The request was cancelled before it finished."""


@dataclasses.dataclass
class ServingConfig:
    """Engine knobs. ``from_env`` reads the ``HOROVOD_SERVING_*``
    variables through the ``common/config.py`` accessors; explicit
    constructor arguments (tests, benches) override the environment."""

    max_batch: int = 8          # decode slots per step
    block_size: int = 16        # KV page size, token positions
    num_blocks: int = 0         # pool capacity; 0 = fully provisioned
    queue_depth: int = 128      # admission bound on WAITING requests
    max_seq_len: int = 0        # position budget; 0 = model's max
    prefix_cache: bool = True   # warm-prefix sharing (docs/serving.md)
    prefix_capacity: int = 0    # cache-held block bound; 0 = pressure-only

    @staticmethod
    def from_env() -> "ServingConfig":
        return ServingConfig(
            max_batch=hvd_config.serving_max_batch(),
            block_size=hvd_config.serving_block_size(),
            num_blocks=hvd_config.serving_num_blocks(),
            queue_depth=hvd_config.serving_queue_depth(),
            max_seq_len=hvd_config.serving_max_seq_len(),
            prefix_cache=hvd_config.serving_prefix_cache(),
            prefix_capacity=hvd_config.serving_prefix_capacity(),
        )


@dataclasses.dataclass
class Request:
    """One in-flight generation request and its full accounting."""

    rid: int
    prompt: np.ndarray                  # (S,) int32, the ORIGINAL prompt
    max_new_tokens: int
    temperature: float = 0.0
    state: str = WAITING
    tokens: List[int] = dataclasses.field(default_factory=list)
    blocks: List[int] = dataclasses.field(default_factory=list)
    slot: Optional[int] = None
    # Prefix sharing (set by admit()): how many leading whole pages of
    # current_prompt() were mapped onto existing blocks copy-free, and
    # the chained digests of ALL its whole pages (the engine's insert
    # keys once the prefill writes the cold ones).
    warm_pages: int = 0
    page_hashes: List[bytes] = dataclasses.field(default_factory=list)
    preemptions: int = 0
    cancel_requested: bool = False
    error: Optional[str] = None
    # time.monotonic() stamps (durations only — never wall anchors).
    submit_t: float = 0.0
    first_token_t: Optional[float] = None
    last_token_t: Optional[float] = None
    finish_t: Optional[float] = None

    @property
    def prompt_len(self) -> int:
        return int(self.prompt.shape[0])

    @property
    def generated(self) -> int:
        return len(self.tokens)

    def current_prompt(self) -> np.ndarray:
        """What a (re-)prefill must process: the original prompt plus
        every already-generated token (preemption-by-recompute replays
        the generated suffix to rebuild its KV rows)."""
        if not self.tokens:
            return self.prompt
        return np.concatenate(
            [self.prompt, np.asarray(self.tokens, self.prompt.dtype)])

    def position_of_last_token(self) -> int:
        """Global position of the newest generated token — the decode
        step's per-sequence ``cache_index`` (the token's KV row is
        written there; attention spans positions <= it). Generated token
        j (1-based) sits at position ``prompt_len + j - 1`` whether it
        was produced by decode or replayed by a recompute prefill."""
        return self.prompt_len + self.generated - 1

    def total_len(self) -> int:
        return self.prompt_len + self.generated

    def is_done(self) -> bool:
        return self.generated >= self.max_new_tokens


def zero_stats() -> Dict[str, float]:
    """The serving stats dict with every key present and zero — what
    ``hvd.serving.stats()`` returns before any engine exists (the
    ``controller_health()`` zero-state convention: downstream consumers
    index and chart without None-guards)."""
    return {
        "queue_depth": 0,
        "queue_limit": 0,
        "active_sequences": 0,
        "blocks_total": 0,
        "blocks_in_use": 0,
        "blocks_peak": 0,
        "block_utilization": 0.0,
        "requests_submitted": 0,
        "requests_finished": 0,
        "requests_rejected": 0,
        "requests_cancelled": 0,
        "preemptions": 0,
        "tokens_generated": 0,
        "steps": 0,
        "ttft_p50_seconds": 0.0,
        "ttft_p99_seconds": 0.0,
        "tpot_p50_seconds": 0.0,
        "tpot_p99_seconds": 0.0,
        # Prefix sharing (round 11; zeros when the cache is disabled).
        # blocks_live excludes pages only the prefix index holds —
        # reclaimable on demand, so they are warm spare capacity, not
        # footprint; blocks_live_peak is ITS high-water mark (sampled at
        # step boundaries, where all allocation happens).
        "blocks_live": 0,
        "blocks_live_peak": 0,
        "blocks_shared": 0,
        "cow_copies": 0,
        "prefix_hits": 0,
        "prefix_misses": 0,
        "prefix_hit_rate": 0.0,
        "prefix_cached_blocks": 0,
        "prefix_inserts": 0,
        "prefix_evictions": 0,
        # Fleet router (round 11; zeros for a routerless engine — the
        # default router's live numbers overlay these in
        # ``hvd.serving.stats()``).
        "router_replicas": 0,
        "router_requests": 0,
        "router_reroutes": 0,
        "router_replica_departures": 0,
    }


class Scheduler:
    """Admission queue + decode-slot/block-table bookkeeping.

    Owns the WAITING deque (bounded by ``queue_depth``), the slot map,
    and the :class:`BlockPool`. The engine calls, between decode steps::

        retire(...)            # free finished/cancelled sequences
        admitted = admit()     # new sequences to prefill, in FIFO order
        preempted = ensure_decode_capacity()

    and builds its decode batch from ``running`` afterwards.
    """

    def __init__(self, pool: BlockPool, max_batch: int, queue_depth: int,
                 max_seq_len: int,
                 prefix_cache: Optional[PrefixCache] = None):
        self.pool = pool
        self.max_batch = int(max_batch)
        self.queue_depth = int(queue_depth)
        self.max_seq_len = int(max_seq_len)
        self.prefix_cache = prefix_cache
        self.waiting: Deque[Request] = deque()
        self.running: Dict[int, Request] = {}      # slot -> Request
        self._free_slots: List[int] = list(range(self.max_batch - 1, -1, -1))
        self.rejected = 0
        self.preempted = 0
        self.cow_copies = 0
        # (src, dst) block copies the engine must perform on-device
        # BEFORE the next decode step (copy-on-write: a sequence about to
        # write into a shared page got a private block instead).
        self.pending_copies: List[Tuple[int, int]] = []

    # -- admission ----------------------------------------------------------

    def check_admissible(self, prompt_len: int, max_new_tokens: int) -> None:
        """Reject-before-queue checks: a request whose full window can
        never fit (position budget or whole pool) would deadlock the
        queue behind it — refuse it at the door, loudly."""
        total = prompt_len + max_new_tokens
        if prompt_len < 1 or max_new_tokens < 1:
            raise ValueError(
                f"need a non-empty prompt ({prompt_len}) and "
                f"max_new_tokens >= 1 ({max_new_tokens})")
        if total > self.max_seq_len:
            self.rejected += 1
            raise RejectedError(
                f"prompt ({prompt_len}) + max_new_tokens "
                f"({max_new_tokens}) exceeds the serving window "
                f"max_seq_len={self.max_seq_len}")
        if self.pool.blocks_for(total) > self.pool.num_blocks:
            self.rejected += 1
            raise RejectedError(
                f"request needs {self.pool.blocks_for(total)} KV blocks "
                f"at full length; the pool holds {self.pool.num_blocks} "
                "(raise HOROVOD_SERVING_NUM_BLOCKS)")
        if len(self.waiting) >= self.queue_depth:
            self.rejected += 1
            raise RejectedError(
                f"serving queue is full ({len(self.waiting)}/"
                f"{self.queue_depth} waiting); shed load or raise "
                "HOROVOD_SERVING_QUEUE_DEPTH")

    def enqueue(self, req: Request) -> None:
        """Append an admissible request (``check_admissible`` first)."""
        req.state = WAITING
        self.waiting.append(req)

    def requeue_front(self, req: Request) -> None:
        """A preempted sequence goes back to the FRONT: it has already
        consumed service, and FIFO fairness for the others is preserved
        by finishing it first once capacity returns."""
        req.state = WAITING
        self.waiting.appendleft(req)

    def admit(self) -> List[Request]:
        """Move waiting requests into free decode slots while the pool
        can hold their (re-)prefill blocks. FIFO — the head blocks the
        tail, which keeps TTFT honest (no starvation of long prompts).
        Admitted requests come back with blocks + slot assigned, ready
        for the engine's prefill.

        With a prefix cache, a request's leading whole pages that the
        index already holds are mapped onto the existing blocks
        **copy-free** (one shared reference each) — only the cold tail
        pages allocate, so a warm prompt admits at a fraction of its
        cold block cost and its prefill recomputes only the tail."""
        admitted: List[Request] = []
        while self.waiting and self._free_slots:
            req = self.waiting[0]
            need = self.pool.blocks_for(req.total_len())
            warm: List[int] = []
            hashes: List[bytes] = []
            if self.prefix_cache is not None:
                warm, hashes = self.prefix_cache.lookup(
                    req.current_prompt())
                for block in warm:
                    self.pool.share(block)
            if not self._ensure_free(need - len(warm)):
                if warm:
                    self.pool.free(warm)    # un-map; retry next step
                break
            self.waiting.popleft()
            req.blocks = list(warm) + self.pool.alloc_many(
                need - len(warm))
            req.warm_pages = len(warm)
            req.page_hashes = hashes
            if self.prefix_cache is not None:
                # Hit accounting on ADMISSION only — a request parked by
                # a full pool re-probes the index every step and would
                # otherwise inflate both counters.
                self.prefix_cache.hits += len(warm)
                self.prefix_cache.misses += len(hashes) - len(warm)
            req.slot = self._free_slots.pop()
            req.state = RUNNING
            self.running[req.slot] = req
            admitted.append(req)
        return admitted

    def _ensure_free(self, blocks: int) -> bool:
        """True once ``blocks`` are allocatable, releasing cold prefix-
        cache entries (cache-only references, LRU-first) to get there —
        warm pages nobody is using are the cheapest capacity on the
        machine, and evicting them beats preempting live work."""
        if self.pool.can_fit(blocks):
            return True
        if self.prefix_cache is not None:
            self.prefix_cache.release(blocks - self.pool.free_blocks)
        return self.pool.can_fit(blocks)

    # -- retirement ---------------------------------------------------------

    def _drop_pending_copies(self, req: Request) -> None:
        """A request leaving the batch must take its queued COW copies
        with it: its destination blocks return to the pool and could be
        re-handed out before the engine drains the copy list."""
        if self.pending_copies:
            mine = set(req.blocks)
            self.pending_copies = [
                (src, dst) for src, dst in self.pending_copies
                if dst not in mine]

    def retire(self, req: Request, state: str,
               error: Optional[str] = None) -> None:
        """Terminal transition: free blocks and slot, record state.
        ``free`` releases one reference per block — pages the prefix
        index (or another sequence) still holds stay live."""
        if req.blocks:
            self._drop_pending_copies(req)
            self.pool.free(req.blocks)
            req.blocks = []
        if req.slot is not None:
            self.running.pop(req.slot, None)
            self._free_slots.append(req.slot)
            req.slot = None
        req.state = state
        req.error = error
        if req.finish_t is None:
            req.finish_t = time.monotonic()

    def cancel_waiting(self, req: Request) -> None:
        """Remove a still-queued request (cancel before admission)."""
        try:
            self.waiting.remove(req)
        except ValueError:
            return
        req.state = CANCELLED

    # -- per-step capacity --------------------------------------------------

    def preempt(self, req: Request) -> None:
        """Preemption-by-recompute: drop the sequence's blocks and park
        it at the queue front; its generated tokens ride along and are
        replayed by the readmission prefill. Shared pages survive the
        free (the prefix index / other holders keep them), so a
        preempted warm request usually readmits warm again."""
        self._drop_pending_copies(req)
        self.pool.free(req.blocks)
        req.blocks = []
        if req.slot is not None:
            self.running.pop(req.slot, None)
            self._free_slots.append(req.slot)
            req.slot = None
        req.preemptions += 1
        self.preempted += 1
        self.requeue_front(req)

    def _grow_block(self, req: Request,
                    preempted: List[Request]) -> Optional[int]:
        """One block for ``req``, by whatever it takes: allocate,
        release cold prefix-cache entries, then preempt the YOUNGEST
        running sequence (most recently admitted — least sunk work to
        replay) and retry. Returns None when ``req`` itself became the
        victim."""
        while True:
            if not self.pool.free_blocks and self.prefix_cache is not None:
                self.prefix_cache.release(1)
            try:
                return self.pool.alloc()
            except OutOfBlocks:
                victim = max(self.running.values(), key=lambda r: r.rid)
                self.preempt(victim)
                preempted.append(victim)
                if victim is req:
                    return None

    def ensure_decode_capacity(self) -> List[Request]:
        """Before a decode step: every running sequence needs the block
        holding its next write position — and needs it PRIVATE. Allocate
        missing blocks oldest sequence first (cache relief before
        preemption, see :meth:`_grow_block`); then, if the write-target
        block is shared (another sequence or the prefix index holds it),
        schedule a **copy-on-write**: a fresh private block replaces it
        in this sequence's table, the page contents are queued on
        ``pending_copies`` for the engine to copy on-device before the
        step, and this sequence's reference on the shared original is
        released. Returns the preempted requests (already requeued). A
        lone running sequence can always grow: admission rejected
        anything whose full window exceeds the pool, and cache-only
        references always yield to a live sequence."""
        preempted: List[Request] = []
        survivors = sorted(self.running.values(), key=lambda r: r.rid)
        for req in survivors:
            if req.slot is None:
                continue                       # preempted this pass
            # The step writes the incoming token's KV row at position
            # total_len() - 1; the table must cover it.
            need = self.pool.blocks_for(req.total_len())
            while req.slot is not None and len(req.blocks) < need:
                block = self._grow_block(req, preempted)
                if block is not None:
                    req.blocks.append(block)
            if req.slot is None:
                continue
            widx = (req.total_len() - 1) // self.pool.block_size
            if self.pool.is_shared(req.blocks[widx]):
                fresh = self._grow_block(req, preempted)
                if fresh is None:
                    continue
                src = req.blocks[widx]
                req.blocks[widx] = fresh
                self.pending_copies.append((src, fresh))
                self.pool.free([src])          # our reference only
                self.cow_copies += 1
        return preempted

    # -- views --------------------------------------------------------------

    def active(self) -> List[Request]:
        """Running requests in slot order (the decode-batch layout)."""
        return [self.running[slot] for slot in sorted(self.running)]

    def queue_depth_now(self) -> int:
        return len(self.waiting)

    def has_work(self) -> bool:
        return bool(self.waiting or self.running)
