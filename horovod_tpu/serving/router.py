"""Fleet router: N data-parallel serving replicas behind one ``submit``.

One :class:`~horovod_tpu.serving.engine.ServingEngine` is one replica —
the engine deliberately rejects dp-sharded batches (the paged pool has
no batch dim to shard), so "more traffic" scales by *replication*, and
something has to spread requests, watch liveness, and absorb replica
churn. That something is this module, the serving twin of round 12's
elastic membership: a replica dying or joining is a **reshape** of the
fleet (epoch bump, placement set changes), never an outage.

Placement is **prefix-affinity-then-least-loaded**: a request whose
first whole page matches a prefix the router recently placed follows it
to the same replica — that replica's prefix cache holds the shared
prompt's pages warm, and splitting one system prompt's traffic across N
replicas would pay the cold prefill N times. Everything else (and every
affinity miss or overloaded/dead affinity target) goes to the replica
with the least queued + running work, read from the replicas' existing
stats endpoints (``engine.stats()`` — the same numbers the metrics
exporter publishes).

Failure handling rides the engines' own recompute discipline:

* a **dead replica** (engine shut down, or its loop died) is marked
  departed on discovery — at placement time, or when a request handle
  surfaces the failure; queued requests it held were failed by the
  engine and **re-route** on their next ``result()``/``stream()`` poll;
* **in-flight** requests replay on a surviving replica via the same
  path: resubmit the ORIGINAL prompt, and — greedy decoding being
  deterministic — skip the tokens already streamed (the router-level
  twin of preemption-by-recompute; replays cost work, never tokens).

The router is plain Python over the engines' public API — no jax — and
serializes its own bookkeeping under one lock (``serving.router``,
ordered strictly before any engine lock it reaches into).
"""

from __future__ import annotations

import dataclasses
import time
from collections import OrderedDict
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

from ..analysis.lockorder import make_lock
from ..common import config as hvd_config
from ..common import hvd_logging as logging
from .prefix_cache import page_hashes
from .scheduler import CancelledError, RejectedError, zero_stats

_m = None

#: Most first-page digests the affinity map remembers (LRU beyond it).
#: High-cardinality traffic would otherwise grow the map for the
#: process lifetime; the per-replica PrefixCache it mirrors is bounded
#: (capacity knob / pool pressure), so remembering more routes than the
#: caches can hold warm buys nothing.
AFFINITY_CAPACITY = 4096


def _router_metrics():
    """Lazy registration — one owner per ``hvd_router_*`` series
    (docs/metrics.md)."""
    global _m
    if _m is None:
        from types import SimpleNamespace

        from .. import metrics

        _m = SimpleNamespace(
            replicas=metrics.gauge(
                "hvd_router_replicas",
                "Live serving replicas in the fleet."),
            epoch=metrics.gauge(
                "hvd_router_epoch",
                "Fleet membership epoch (bumped by every replica "
                "departure or join — the serving twin of "
                "hvd_membership_epoch)."),
            requests=metrics.counter(
                "hvd_router_requests_total",
                "Requests placed, by replica id.", ("replica",)),
            reroutes=metrics.counter(
                "hvd_router_reroutes_total",
                "Requests replayed on another replica after their "
                "serving replica died (router-level recompute)."),
            departures=metrics.counter(
                "hvd_router_replica_departures_total",
                "Replica departures (death or scale-down), by replica "
                "id — the fleet-flapping signal.", ("replica",)),
            joins=metrics.counter(
                "hvd_router_replica_joins_total",
                "Replicas joined after fleet creation."),
            affinity_hits=metrics.counter(
                "hvd_router_affinity_hits_total",
                "Placements that followed a warm prefix to its "
                "replica."),
        )
    return _m


def _metrics_on() -> bool:
    from .. import metrics

    return metrics.on()


@dataclasses.dataclass
class RouterConfig:
    """Router knobs. ``from_env`` reads the ``HOROVOD_ROUTER_*``
    variables through the ``common/config.py`` accessors; explicit
    constructor arguments override the environment."""

    replicas: int = 2       # fleet size when the caller names no count
    affinity: bool = True   # prefix-affinity placement (else least-loaded)
    retries: int = 2        # replays per request after replica death

    @staticmethod
    def from_env() -> "RouterConfig":
        return RouterConfig(
            replicas=hvd_config.router_replicas(),
            affinity=hvd_config.router_affinity(),
            retries=hvd_config.router_retries(),
        )


@dataclasses.dataclass
class _Replica:
    rid: int
    engine: object
    alive: bool = True


class FleetHandle:
    """Caller's view of one routed request. Mirrors
    :class:`~horovod_tpu.serving.engine.RequestHandle` (``result`` /
    ``stream`` / ``cancel``), plus transparent replay: a replica dying
    under the request re-routes it instead of failing it."""

    def __init__(self, router: "Router", prompt: np.ndarray,
                 max_new_tokens: int, temperature: float,
                 replica: _Replica, handle):
        self._router = router
        self._prompt = prompt
        self._max_new_tokens = max_new_tokens
        self._temperature = temperature
        self._replica = replica
        self._handle = handle
        self._delivered = 0
        self.replays = 0
        self._cancelled = False

    @property
    def replica_id(self) -> int:
        """Which replica currently serves this request."""
        return self._replica.rid

    @property
    def state(self) -> str:
        return self._handle.state

    @property
    def warm_pages(self) -> int:
        return self._handle.warm_pages

    def ttft_seconds(self) -> Optional[float]:
        """Submit-to-first-token on the CURRENT serving replica (a
        replayed request reports its replay's latency — the price the
        caller actually paid is visible in ``replays``)."""
        return self._handle.ttft_seconds()

    def result(self, timeout: Optional[float] = None) -> List[int]:
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            remaining = (None if deadline is None
                         else max(0.0, deadline - time.monotonic()))
            try:
                return self._handle.result(timeout=remaining)
            except (CancelledError, TimeoutError):
                raise
            except RuntimeError as exc:
                self._reroute(exc)

    def stream(self, timeout: Optional[float] = None) -> Iterator[int]:
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            remaining = (None if deadline is None
                         else max(0.0, deadline - time.monotonic()))
            try:
                seen = 0
                for token in self._handle.stream(timeout=remaining):
                    seen += 1
                    if seen > self._delivered:
                        self._delivered += 1
                        yield token
                return
            except (CancelledError, TimeoutError):
                raise
            except RuntimeError as exc:
                self._reroute(exc)

    def cancel(self) -> None:
        self._cancelled = True
        self._handle.cancel()

    def _reroute(self, exc: RuntimeError) -> None:
        """The serving replica failed this request (engine shutdown or
        loop death): mark it departed and resubmit the original prompt
        elsewhere. Greedy decoding replays bit-identical tokens, so
        ``stream`` consumers see an uninterrupted sequence. A SAMPLED
        request (temperature > 0) that already delivered tokens cannot
        replay coherently — the replay draws a different sequence, and
        splicing its tail onto the delivered prefix would hand the
        consumer a frankensequence — so it fails loudly instead (a
        sampled request with nothing delivered yet replays fine: a
        fresh draw is a valid response)."""
        self._router._note_replica_failure(self._replica)
        if self._cancelled:
            raise CancelledError(
                "request was cancelled during replica failover") from exc
        if self._temperature > 0.0 and self._delivered > 0:
            raise RuntimeError(
                "replica died mid-stream of a sampled (temperature > 0) "
                "request; a replay would draw a different sequence and "
                "cannot splice onto the tokens already delivered — "
                "resubmit") from exc
        if self.replays >= self._router.config.retries:
            raise RuntimeError(
                f"request failed on {self.replays + 1} replica(s); "
                f"last error: {exc}") from exc
        self.replays += 1
        self._replica, self._handle = self._router._place(
            self._prompt, self._max_new_tokens, self._temperature,
            exclude={self._replica.rid})
        self._router._count_reroute()


class Router:
    """See module docstring. ``engines`` is a non-empty list of
    :class:`~horovod_tpu.serving.engine.ServingEngine` replicas (usually
    built by :func:`horovod_tpu.serving.fleet`)."""

    def __init__(self, engines, config: Optional[RouterConfig] = None):
        if not engines:
            raise ValueError("a fleet needs at least one replica")
        self.config = config if config is not None else (
            RouterConfig.from_env())
        self._lock = make_lock("serving.router")
        self._replicas: List[_Replica] = [
            _Replica(rid=i, engine=e) for i, e in enumerate(engines)]
        self._next_rid = len(engines)
        self._epoch = 0
        self._requests = 0
        self._reroutes = 0
        self._affinity_hits = 0
        self._departures: Dict[int, int] = {}
        self._joins = 0
        # First-whole-page digest -> rid of the replica whose prefix
        # cache is warm for it (block size is uniform across the
        # fleet). LRU-bounded at AFFINITY_CAPACITY.
        self._affinity: "OrderedDict[bytes, int]" = OrderedDict()
        self._block_size = engines[0].config.block_size
        self._update_gauges()

    # -- submission ---------------------------------------------------------

    def submit(self, prompt, max_new_tokens: int,
               temperature: float = 0.0) -> FleetHandle:
        """Place one request on the fleet. Raises
        :class:`~horovod_tpu.serving.RejectedError` when EVERY live
        replica's admission control refuses, ``RuntimeError`` when no
        replica is alive."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        replica, handle = self._place(prompt, int(max_new_tokens),
                                      float(temperature), exclude=set())
        return FleetHandle(self, prompt, int(max_new_tokens),
                           float(temperature), replica, handle)

    def _place(self, prompt: np.ndarray, max_new_tokens: int,
               temperature: float, exclude: set) -> Tuple[_Replica, object]:
        """Affinity-then-least-loaded placement with failure discovery:
        dead engines found along the way are marked departed, rejecting
        replicas are skipped, and the request lands on the first replica
        that admits it."""
        key = None
        if self.config.affinity:
            digests = page_hashes(prompt, self._block_size)
            key = digests[0] if digests else None
        last_reject: Optional[RejectedError] = None
        for replica, via_affinity in self._candidates(key, exclude):
            if replica.engine.closed:
                self._note_replica_failure(replica)
                continue
            try:
                handle = replica.engine.submit(
                    prompt, max_new_tokens, temperature=temperature)
            except RejectedError as exc:
                last_reject = exc
                continue
            except RuntimeError:
                self._note_replica_failure(replica)
                continue
            with self._lock:
                self._requests += 1
                if key is not None:
                    if via_affinity:
                        self._affinity_hits += 1
                    self._affinity[key] = replica.rid
                    self._affinity.move_to_end(key)
                    while len(self._affinity) > AFFINITY_CAPACITY:
                        self._affinity.popitem(last=False)
            if _metrics_on():
                m = _router_metrics()
                m.requests.labels(str(replica.rid)).inc()
                if via_affinity:
                    m.affinity_hits.inc()
            return replica, handle
        if last_reject is not None:
            raise RejectedError(
                f"every live replica rejected the request "
                f"({last_reject})")
        raise RuntimeError("no live serving replica in the fleet")

    def _candidates(self, key: Optional[bytes], exclude: set):
        """(replica, via_affinity) in placement order: the affinity
        target first — unless its queue already sits at half its bound
        (a warm cache does not pay for queueing behind a saturated
        replica) — then the rest by least queued + running work."""
        with self._lock:
            alive = [r for r in self._replicas
                     if r.alive and r.rid not in exclude]
            affinity_rid = self._affinity.get(key) if key is not None \
                else None
        first: List[Tuple[_Replica, bool]] = []
        rest: List[_Replica] = []
        for replica in alive:
            if replica.rid == affinity_rid:
                try:
                    s = replica.engine.stats()
                    saturated = (s["queue_depth"]
                                 >= max(1, s["queue_limit"] // 2))
                except Exception:
                    saturated = True
                if saturated:
                    rest.append(replica)
                else:
                    first.append((replica, True))
            else:
                rest.append(replica)

        def load(replica: _Replica) -> float:
            try:
                s = replica.engine.stats()
            except Exception:
                return float("inf")
            return s["queue_depth"] + s["active_sequences"]

        yield from first
        for replica in sorted(rest, key=load):
            yield (replica, False)

    # -- membership ---------------------------------------------------------

    def add_replica(self, engine) -> int:
        """A joiner: the fleet grows at the next epoch — new placements
        see it immediately (the least-loaded rule naturally drains the
        backlog onto it)."""
        with self._lock:
            rid = self._next_rid
            self._next_rid += 1
            self._replicas.append(_Replica(rid=rid, engine=engine))
            self._epoch += 1
            self._joins += 1
        logging.info("router: replica %d joined the fleet (epoch %d)",
                     rid, self._epoch)
        if _metrics_on():
            _router_metrics().joins.inc()
        self._update_gauges()
        return rid

    def remove_replica(self, rid: int) -> None:
        """Scale-down: shut the replica's engine down (its queued and
        running requests fail there and re-route through their handles)
        and record the departure."""
        with self._lock:
            replica = next((r for r in self._replicas if r.rid == rid),
                           None)
        if replica is None:
            raise ValueError(f"no replica {rid} in the fleet")
        replica.engine.shutdown()
        self._note_replica_failure(replica)

    def _note_replica_failure(self, replica: _Replica) -> None:
        """Reshape, not outage: record the departure once, bump the
        epoch, and keep serving on the survivors."""
        with self._lock:
            if not replica.alive:
                return
            replica.alive = False
            self._epoch += 1
            self._departures[replica.rid] = (
                self._departures.get(replica.rid, 0) + 1)
            # Warm prefixes on a dead replica are gone with its pools.
            self._affinity = OrderedDict(
                (k, rid) for k, rid in self._affinity.items()
                if rid != replica.rid)
        logging.warning(
            "router: replica %d left the fleet (epoch %d); re-routing "
            "its requests to the survivors", replica.rid, self._epoch)
        if _metrics_on():
            _router_metrics().departures.labels(str(replica.rid)).inc()
        self._update_gauges()

    def _count_reroute(self) -> None:
        with self._lock:
            self._reroutes += 1
        if _metrics_on():
            _router_metrics().reroutes.inc()

    # -- views --------------------------------------------------------------

    @property
    def epoch(self) -> int:
        with self._lock:
            return self._epoch

    def replicas(self) -> List[int]:
        """Live replica ids."""
        with self._lock:
            return [r.rid for r in self._replicas if r.alive]

    def engines(self) -> List[object]:
        """Live replica engines (chaos harnesses kill these directly;
        the router discovers the death like any other)."""
        with self._lock:
            return [r.engine for r in self._replicas if r.alive]

    def engine(self, rid: int):
        """The engine behind replica ``rid`` (dead or alive)."""
        with self._lock:
            for replica in self._replicas:
                if replica.rid == rid:
                    return replica.engine
        raise ValueError(f"no replica {rid} in the fleet")

    def health(self) -> Dict[int, dict]:
        """Per-replica liveness + load, from the replicas' own stats
        endpoints (dead replicas report ``alive: False`` only)."""
        with self._lock:
            replicas = list(self._replicas)
        out: Dict[int, dict] = {}
        for replica in replicas:
            entry = {"alive": replica.alive and not replica.engine.closed}
            if entry["alive"]:
                s = replica.engine.stats()
                entry.update(
                    queue_depth=s["queue_depth"],
                    active_sequences=s["active_sequences"],
                    blocks_in_use=s["blocks_in_use"],
                    requests_finished=s["requests_finished"])
            out[replica.rid] = entry
        return out

    def router_stats(self) -> Dict[str, float]:
        """The four ``router_*`` fields of the serving stats catalog."""
        with self._lock:
            return {
                "router_replicas": sum(
                    1 for r in self._replicas if r.alive),
                "router_requests": self._requests,
                "router_reroutes": self._reroutes,
                "router_replica_departures": sum(
                    self._departures.values()),
            }

    def stats(self) -> Dict[str, float]:
        """Fleet-aggregate serving stats in the ``zero_stats()`` shape:
        counters sum across live replicas, gauges sum (the fleet's pool
        is the union of the replicas' pools), latency percentiles take
        the worst replica (a fleet is as slow as where your request
        landed), and the ``router_*`` fields are live."""
        agg = zero_stats()
        with self._lock:
            engines = [r.engine for r in self._replicas if r.alive]
        worst = ("ttft_p50_seconds", "ttft_p99_seconds",
                 "tpot_p50_seconds", "tpot_p99_seconds")
        for engine in engines:
            s = engine.stats()
            for k, v in s.items():
                if k in worst:
                    agg[k] = max(agg[k], v)
                else:
                    agg[k] = agg.get(k, 0) + v
        # Ratios re-derive from the fleet sums — max() would report the
        # BEST replica's hit rate and mask a cold replica's collapse.
        prefix_total = agg["prefix_hits"] + agg["prefix_misses"]
        agg["prefix_hit_rate"] = (
            round(agg["prefix_hits"] / prefix_total, 4)
            if prefix_total else 0.0)
        agg["block_utilization"] = (
            round(agg["blocks_in_use"] / agg["blocks_total"], 4)
            if agg["blocks_total"] else 0.0)
        agg.update(self.router_stats())
        return agg

    def shutdown(self, timeout: float = 10.0) -> None:
        """Stop every replica engine (intentional teardown: no
        departure is recorded). Idempotent."""
        with self._lock:
            replicas = list(self._replicas)
        for replica in replicas:
            replica.engine.shutdown(timeout=timeout)
        with self._lock:
            for replica in replicas:
                replica.alive = False
        self._update_gauges()

    def _update_gauges(self) -> None:
        if not _metrics_on():
            return
        m = _router_metrics()
        with self._lock:
            m.replicas.set(sum(1 for r in self._replicas if r.alive))
            m.epoch.set(self._epoch)
