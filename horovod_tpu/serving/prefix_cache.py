"""Prefix index over the paged KV pool: warm prompts admit copy-free.

The dominant traffic shape at scale is K shared system prompts × unique
user tails. The block-table indirection already makes KV pages
position-independent *in storage* (``ops.decode_attention``), and a KV
row's *content* is fully determined by the token prefix up to it (causal
attention + absolute-position RoPE), so a whole page whose tokens —
and every token before them — match a page already in the pool holds
byte-identical KV. This module owns that mapping: a chained digest of
whole-page token prefixes → the physical block that already holds the
page's KV rows.

Keying is by **chained** hash (digest *i* commits to tokens
``0..(i+1)*block_size``), never by the page's own tokens alone: two
prompts sharing page *i*'s tokens but diverging earlier would collide
under a per-page key, and their KV rows genuinely differ (attention saw
different histories). The chain makes a hit a proof that the whole
prefix matches.

Reference discipline: the cache is a first-class holder — ``insert``
takes one :class:`~horovod_tpu.serving.kv_blocks.BlockPool` reference
per entry, so a donor sequence finishing (or being preempted, or
evicted) does NOT return its shared pages to the pool; they stay warm
for the next request. Under pool pressure the scheduler calls
:meth:`PrefixCache.release`, which drops least-recently-used entries
whose block the cache is the *only* holder of — entries still backing a
live sequence are skipped (releasing them frees nothing). Plain Python,
no jax: every invariant is unit-testable without a device.
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict
from typing import Dict, List, Sequence, Tuple

import numpy as np

from .kv_blocks import BlockPool


def page_hashes(tokens: Sequence[int], block_size: int) -> List[bytes]:
    """Chained digests for every WHOLE page of ``tokens``: digest ``i``
    commits to tokens ``0..(i+1)*block_size`` (16-byte blake2b over the
    previous digest plus the page's int32 bytes). A partial trailing
    page gets no digest — only full pages are ever shared."""
    arr = np.ascontiguousarray(np.asarray(tokens, np.int32).reshape(-1))
    out: List[bytes] = []
    prev = b""
    for i in range(arr.shape[0] // block_size):
        h = hashlib.blake2b(digest_size=16)
        h.update(prev)
        h.update(arr[i * block_size:(i + 1) * block_size].tobytes())
        prev = h.digest()
        out.append(prev)
    return out


class PrefixCache:
    """LRU index ``chained page digest -> physical block id``.

    ``capacity_blocks`` bounds how many blocks the cache may hold
    references to (0 = bounded only by pool pressure via
    :meth:`release`). The caller (scheduler/engine, under the engine
    lock) owns mutation ordering; the cache itself is not thread-safe.
    """

    def __init__(self, pool: BlockPool, capacity_blocks: int = 0):
        self.pool = pool
        self.block_size = pool.block_size
        self.capacity = max(0, int(capacity_blocks))
        # LRU: oldest entry first; move_to_end on every hit/refresh.
        self._index: "OrderedDict[bytes, int]" = OrderedDict()
        self.hits = 0
        self.misses = 0
        self.inserts = 0
        self.evictions = 0

    def __len__(self) -> int:
        return len(self._index)

    @property
    def cached_blocks(self) -> int:
        return len(self._index)

    def cache_only_blocks(self) -> int:
        """Blocks whose ONLY holder is the index — reclaimable on
        demand, so they are warm spare capacity rather than live
        footprint (the ``blocks_live`` accounting subtracts them)."""
        return sum(1 for block in self._index.values()
                   if self.pool.refcount(block) == 1)

    # -- lookup -------------------------------------------------------------

    def lookup(self, tokens: Sequence[int]
               ) -> Tuple[List[int], List[bytes]]:
        """Longest warm run of whole-page prefixes for ``tokens``.

        Returns ``(warm_blocks, hashes)``: ``warm_blocks`` are the
        physical blocks backing pages ``0..len(warm_blocks)-1``
        (matching stops at the first cold page — a later isolated hit is
        useless, its KV assumes a different history), and ``hashes`` are
        the chained digests of ALL full pages (the insert keys after the
        prefill writes the cold ones).

        The warm run is capped at ``floor((len-1)/block_size)`` pages:
        the prefill must run at least one real token to produce the
        next-token logits, so a fully-page-aligned, fully-warm prompt
        recomputes exactly its last page.

        Hit/miss accounting is the CALLER's (the scheduler counts once
        per admission — a request parked by a full pool re-probes every
        step and must not inflate the rate)."""
        hashes = page_hashes(tokens, self.block_size)
        n = int(np.asarray(tokens).reshape(-1).shape[0])
        cap = max(0, n - 1) // self.block_size
        warm: List[int] = []
        for digest in hashes[:cap]:
            block = self._index.get(digest)
            if block is None:
                break
            self._index.move_to_end(digest)
            warm.append(block)
        return warm, hashes

    # -- insert / evict -----------------------------------------------------

    def insert(self, digest: bytes, block: int) -> bool:
        """Register ``digest -> block``, taking one pool reference. An
        already-present digest only refreshes its LRU position (the
        existing block keeps serving — re-registering under a different
        block would strand the old entry's reference). Returns whether a
        new entry was created."""
        if digest in self._index:
            self._index.move_to_end(digest)
            return False
        if self.capacity and len(self._index) >= self.capacity:
            # Make room from the cold end; a full cache of entries all
            # pinned by live sequences declines the insert instead of
            # growing past its bound.
            self.release(1, for_capacity=True)
            if len(self._index) >= self.capacity:
                return False
        self.pool.share(block)
        self._index[digest] = block
        self.inserts += 1
        return True

    def release(self, need_blocks: int, for_capacity: bool = False) -> int:
        """Drop least-recently-used entries until ``need_blocks`` blocks
        returned to the pool (pool pressure: the scheduler calls this
        before resorting to preemption). Entries whose block a live
        sequence still shares are skipped — dropping them frees nothing
        — unless ``for_capacity`` is set (capacity eviction counts index
        slots, not freed blocks). Returns how many entries were
        dropped."""
        dropped = 0
        if need_blocks <= 0:
            return 0
        for digest in list(self._index):
            if dropped >= need_blocks:
                break
            block = self._index[digest]
            if not for_capacity and self.pool.refcount(block) != 1:
                continue
            del self._index[digest]
            self.pool.free([block])
            self.evictions += 1
            dropped += 1
        return dropped

    def clear(self) -> None:
        """Release every cache-held reference (engine shutdown)."""
        while self._index:
            _, block = self._index.popitem(last=False)
            self.pool.free([block])
            self.evictions += 1

    # -- views --------------------------------------------------------------

    def hit_rate(self) -> float:
        total = self.hits + self.misses
        return self.hits / total if total else 0.0

    def stats(self) -> Dict[str, float]:
        return {
            "prefix_hits": self.hits,
            "prefix_misses": self.misses,
            "prefix_hit_rate": round(self.hit_rate(), 4),
            "prefix_cached_blocks": self.cached_blocks,
            "prefix_inserts": self.inserts,
            "prefix_evictions": self.evictions,
        }
