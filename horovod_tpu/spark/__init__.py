"""Spark integration: ``horovod_tpu.spark.run(fn, args=...)``.

Reference: ``horovod/spark/__init__.py:92`` — runs ``fn`` on every Spark
executor as a Horovod rank. The reference builds this out of task services,
a custom ``mpirun`` rsh agent and pickled closures
(``spark/driver/mpirun_rsh.py``, ``spark/task/mpirun_exec_fn.py``); here
there is no MPI: a single registration round trip with the driver service
hands each task its topology + rendezvous addresses, and the task calls
``hvd.init()`` directly. Results are returned through Spark's own collect,
replacing the reference's result channel (``spark/__init__.py:223-227``).
"""

from __future__ import annotations

import os
from typing import Any, Callable, Optional, Sequence

from .driver import SparkDriverService, compute_assignments, register_task  # noqa: F401


def _task_fn(fn: Callable, args: tuple, kwargs: dict, driver_addr: str):
    def task(index, _iterator):
        assignment = register_task(driver_addr, index)
        os.environ.update({
            "HOROVOD_RANK": str(assignment["rank"]),
            "HOROVOD_SIZE": str(assignment["size"]),
            "HOROVOD_LOCAL_RANK": str(assignment["local_rank"]),
            "HOROVOD_LOCAL_SIZE": str(assignment["local_size"]),
            "HOROVOD_CROSS_RANK": str(assignment["cross_rank"]),
            "HOROVOD_CROSS_SIZE": str(assignment["cross_size"]),
            "HOROVOD_CONTROLLER_ADDR": assignment["controller_addr"],
            "HOROVOD_RING_ADDRS": assignment["ring_addrs"],
            "HOROVOD_SECRET_KEY": assignment["secret"],
        })
        # Orphaned-task self-termination (reference
        # spark/task/mpirun_exec_fn.py:25-35): if the executor's python
        # worker is orphaned mid-job, hvd.init()'s watchdog reaps it.
        os.environ.setdefault("HOROVOD_PARENT_WATCHDOG", "1")
        yield fn(*args, **kwargs)

    return task


def run(fn: Callable, args: tuple = (), kwargs: Optional[dict] = None,
        num_proc: Optional[int] = None) -> Sequence[Any]:
    """Run ``fn`` as a distributed job on Spark executors (reference
    ``horovod.spark.run``, ``spark/__init__.py:92-227``). Returns the list
    of every rank's return value, in rank order."""
    try:
        import pyspark  # noqa: F401
        from pyspark import SparkContext
    except ImportError as exc:
        raise ImportError(
            "horovod_tpu.spark requires pyspark, which is not installed in "
            "this environment") from exc

    kwargs = kwargs or {}
    sc = SparkContext._active_spark_context
    if sc is None:
        raise RuntimeError("no active SparkContext; create one before "
                           "horovod_tpu.spark.run(fn)")
    if num_proc is None:
        num_proc = sc.defaultParallelism

    driver = SparkDriverService(num_proc)
    addr = driver.addr()
    results = (
        sc.parallelize(range(num_proc), num_proc)
        .mapPartitionsWithIndex(_task_fn(fn, args, kwargs, addr))
        .collect())
    driver.join()
    return results
