"""Spark driver-side rendezvous service.

Reference: ``horovod/spark/driver/driver_service.py`` — tasks register with a
TCP service, the driver groups them by host hash (``spark/__init__.py:
172-182``) and then launches ``mpirun`` with an rsh agent routed through the
task services. TPU-native redesign: there is no mpirun to launch — the
registration reply itself carries everything a rank needs (topology ints,
controller address, ring addresses, job secret), and the task then simply
calls ``hvd.init()``.

Protocol (authenticated Wire frames):
  task  -> driver  {"index", "host", "ring_port", "controller_port"}
  driver -> task   {"rank", "size", "local_rank", "local_size",
                    "cross_rank", "cross_size", "controller_addr",
                    "ring_addrs", "secret"}
"""

from __future__ import annotations

import socket
import threading
from typing import Dict, List, Tuple

from ..common.wire import Wire, make_secret


def compute_assignments(
        registrations: List[dict]) -> List[dict]:
    """Pure assignment logic (unit-testable without Spark): given each
    task's {"index", "host", "ring_port", "controller_port"}, produce the
    per-task environment dict. Ranks are task indices; local ranks follow
    registration order within a host (reference groups by host hash,
    spark/__init__.py:172-182)."""
    size = len(registrations)
    by_index = sorted(registrations, key=lambda r: r["index"])
    hosts: List[str] = []
    local_rank: Dict[int, int] = {}
    local_size: Dict[str, int] = {}
    for reg in by_index:
        host = reg["host"]
        if host not in hosts:
            hosts.append(host)
        local_rank[reg["index"]] = local_size.get(host, 0)
        local_size[host] = local_size.get(host, 0) + 1

    rank0 = by_index[0]
    controller_addr = f"{rank0['host']}:{rank0['controller_port']}"
    ring_addrs = ",".join(f"{r['host']}:{r['ring_port']}" for r in by_index)
    secret = make_secret()

    out = []
    for reg in by_index:
        host = reg["host"]
        out.append({
            "rank": reg["index"],
            "size": size,
            "local_rank": local_rank[reg["index"]],
            "local_size": local_size[host],
            "cross_rank": hosts.index(host),
            "cross_size": len(hosts),
            "controller_addr": controller_addr,
            "ring_addrs": ring_addrs,
            "secret": secret,
        })
    return out


class SparkDriverService:
    """Accept ``num_proc`` registrations, reply with assignments, then stop.

    Runs on the Spark driver; the service address travels to the tasks in
    the closure (the reference passes its driver address the same way)."""

    def __init__(self, num_proc: int, timeout: float = 300.0):
        self.num_proc = num_proc
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind(("0.0.0.0", 0))
        self._listener.listen(num_proc)
        self._listener.settimeout(timeout)
        self.port = self._listener.getsockname()[1]
        self._thread = threading.Thread(target=self._serve,
                                        name="hvd-spark-driver", daemon=True)
        self._error = None
        self._thread.start()

    def addr(self) -> str:
        return f"{socket.gethostname()}:{self.port}"

    def _serve(self) -> None:
        wires: List[Tuple[dict, Wire]] = []
        try:
            while len(wires) < self.num_proc:
                conn, _ = self._listener.accept()
                wire = Wire(conn)
                reg = wire.recv_obj()
                wires.append((reg, wire))
            assignments = compute_assignments([r for r, _ in wires])
            by_index = {a["rank"]: a for a in assignments}
            for reg, wire in wires:
                wire.send_obj(by_index[reg["index"]])
                wire.close()
        except Exception as exc:  # surfaced via join()
            self._error = exc
        finally:
            self._listener.close()

    def join(self) -> None:
        self._thread.join()
        if self._error is not None:
            raise self._error


def register_task(driver_addr: str, index: int) -> dict:
    """Task-side registration: bind the ring/controller ports locally,
    register, receive the assignment."""
    def free_port() -> int:
        s = socket.socket()
        s.bind(("", 0))
        port = s.getsockname()[1]
        s.close()
        return port

    host, _, port = driver_addr.rpartition(":")
    sock = socket.create_connection((host, int(port)), timeout=300.0)
    wire = Wire(sock)
    wire.send_obj({
        "index": index,
        "host": socket.gethostname(),
        "ring_port": free_port(),
        "controller_port": free_port(),
    })
    assignment = wire.recv_obj()
    wire.close()
    return assignment
