"""Build + ctypes bindings for the native core.

The reference ships per-framework shared libraries built by a 1000-line
feature-probing ``setup.py`` and loads them through ctypes
(``horovod/common/basics.py:20-28``). Here the native core is dependency-free
C++ compiled on first use with g++ (cached by source mtime); ctypes loads the
same C ABI shape.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

from ..common import hvd_logging as logging

_SRC_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "src")
_BUILD_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "build")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_failed: Optional[str] = None

# Must match enum WireDType in ring.cc: the on-the-wire representation of
# f32 allreduce payloads (HOROVOD_RING_WIRE_DTYPE via common/config.py).
WIRE_DTYPE_CODES = {
    "none": 0,
    "bf16": 1,
    "fp16": 2,
    "int8": 3,
}
WIRE_DTYPE_NAMES = {v: k for k, v in WIRE_DTYPE_CODES.items()}

# Must match enum WireLink in ring.cc: which plane's connections a ring's
# wire traffic rides (indexes the per-link counter rows).
WIRE_LINK_CODES = {
    "flat": 0,
    "local": 1,
    "cross": 2,
}
WIRE_LINK_NAMES = {v: k for k, v in WIRE_LINK_CODES.items()}

# Native-engine telemetry plane (engine.cc): counter-slot layout of
# hvd_eng_get_counters. MUST mirror enum CounterSlot — hvdabi
# (analysis/cpp.py) pins the layout statically against the C enum, and
# the @slow rebuild smoke still cross-checks the compiled .so.
NATIVE_HIST_BUCKETS = 22   # kHistBuckets: registry DEFAULT_TIME_BUCKETS
NATIVE_HIST_SLOTS = NATIVE_HIST_BUCKETS + 1  # + the +Inf overflow slot
NATIVE_COUNTER_SCALARS = (
    "cycles", "tensors", "fused_tensors", "processed_bytes",
    "fusion_capacity", "fusion_fill", "spans", "spans_dropped",
    "bucket_bytes", "cache_hits", "cache_misses",
    # Round 16 pipelined data plane: high-water wire-queue depth,
    # cumulative µs the engine thread spent blocked on the wire thread,
    # and cycles whose launch order was changed by a priority tag.
    "pipeline_depth", "pipeline_stall_us", "priority_jumps")
_NATIVE_CYCLE_HIST_BASE = len(NATIVE_COUNTER_SCALARS)            # 14
_NATIVE_EXEC_HIST_BASE = _NATIVE_CYCLE_HIST_BASE + 2 + NATIVE_HIST_SLOTS
# Trailing slot: engine generation (bumped per init — lets the metrics
# mirror re-baseline when a new engine restarts the counters at zero).
_NATIVE_ENGINE_GEN = _NATIVE_EXEC_HIST_BASE + 2 + NATIVE_HIST_SLOTS  # 64
N_NATIVE_COUNTER_SLOTS = _NATIVE_ENGINE_GEN + 1                      # 65

# Must match enum SpanPhase in engine.cc: codes index the tracer's fixed
# PHASES vocabulary ("enqueue", "negotiate", "fuse", "execute", "done").
NATIVE_SPAN_OP_BYTES = 64

# Must match enum DType in ring.cc.
_DTYPE_CODES = {
    "float32": 0,
    "float64": 1,
    "int32": 2,
    "int64": 3,
    "uint8": 4,
    "float16": 5,
    "bfloat16": 6,
    "int8": 7,
    "int16": 8,
    "uint16": 9,
    "bool": 10,
}
_DTYPE_NAMES = {v: k for k, v in _DTYPE_CODES.items()}


def dtype_from_code(code: int) -> np.dtype:
    name = _DTYPE_NAMES[code]
    if name == "bfloat16":
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    return np.dtype(name)


# Vectorize the reduction loops for the build host (the reference uses
# AVX/F16C intrinsics with a scalar fallback, half.cc:28). The artifact name
# embeds a hash of (flags, host CPU signature): each ISA/flag combination
# gets its own immutable .so, so a different-ISA host on a shared filesystem
# rebuilds its own file instead of loading (or truncating under) a
# -march=native binary another host built and may have mmapped live.
_CXX_FLAGS = ["-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
              "-march=native"]


def _cpu_signature() -> str:
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith("flags"):
                    import hashlib

                    return hashlib.sha256(line.encode()).hexdigest()[:16]
    except OSError:
        pass
    import platform

    return platform.machine()


def _lib_path() -> str:
    import hashlib

    stamp = " ".join(_CXX_FLAGS) + " cpu:" + _cpu_signature()
    tag = hashlib.sha256(stamp.encode()).hexdigest()[:12]
    return os.path.join(_BUILD_DIR, f"libhvdcore-{tag}.so")


def _needs_build(lib_path: str) -> bool:
    if not os.path.exists(lib_path):
        return True
    lib_mtime = os.path.getmtime(lib_path)
    for fname in os.listdir(_SRC_DIR):
        if os.path.getmtime(os.path.join(_SRC_DIR, fname)) > lib_mtime:
            return True
    return False


def build() -> str:
    """Compile the native core (idempotent; cached by source mtimes, with
    the flags/CPU signature baked into the artifact name). Concurrent
    builders (N ranks starting at once) each compile to a private temp file
    and atomically rename it into place, so a loader can never dlopen a
    half-written binary."""
    os.makedirs(_BUILD_DIR, exist_ok=True)
    lib_path = _lib_path()
    if _needs_build(lib_path):
        sources = sorted(
            os.path.join(_SRC_DIR, f) for f in os.listdir(_SRC_DIR)
            if f.endswith(".cc"))
        tmp_path = f"{lib_path}.tmp.{os.getpid()}"
        # -lrt: shm_open lives in librt on pre-2.34 glibc (no-op on newer).
        cmd = ["g++", *_CXX_FLAGS, *sources, "-o", tmp_path, "-lrt"]
        logging.debug("building native core: %s", " ".join(cmd))
        try:
            result = subprocess.run(cmd, capture_output=True, text=True)
            if result.returncode != 0:
                raise RuntimeError(
                    f"native core build failed:\n{result.stderr}")
            os.replace(tmp_path, lib_path)
        finally:
            if os.path.exists(tmp_path):
                os.remove(tmp_path)
    return lib_path


def loaded() -> Optional[ctypes.CDLL]:
    """The already-loaded library, or None — WITHOUT triggering a build.
    For observability paths (metrics mirroring) that must never pay a
    compile just to report zeros."""
    return _lib


def wire_stats() -> dict:
    """Ring wire-traffic counters (hvd_ring_get_wire_stats): actual and
    f32-equivalent bytes per wire dtype plus cumulative compress seconds,
    with a per-link-class breakdown under ``by_link`` (flat/local/cross —
    how the two-level plane proves the cross hop carries int8 while the
    local hop stays f32). All-zeros when the native core was never
    loaded."""
    lib = loaded()
    out = {
        "tx_bytes": {name: 0 for name in WIRE_DTYPE_CODES},
        "logical_bytes": {name: 0 for name in WIRE_DTYPE_CODES},
        "by_link": {
            link: {"tx_bytes": {name: 0 for name in WIRE_DTYPE_CODES},
                   "logical_bytes": {name: 0 for name in WIRE_DTYPE_CODES}}
            for link in WIRE_LINK_CODES},
        "compress_seconds": 0.0,
        "chunk_bytes": 0,
    }
    if lib is None:
        return out
    tx = (ctypes.c_longlong * 4)()
    logical = (ctypes.c_longlong * 4)()
    comp = ctypes.c_double()
    lib.hvd_ring_get_wire_stats(tx, logical, ctypes.byref(comp))
    for name, code in WIRE_DTYPE_CODES.items():
        out["tx_bytes"][name] = int(tx[code])
        out["logical_bytes"][name] = int(logical[code])
    for link, lcode in WIRE_LINK_CODES.items():
        lib.hvd_ring_get_wire_stats_link(lcode, tx, logical)
        row = out["by_link"][link]
        for name, code in WIRE_DTYPE_CODES.items():
            row["tx_bytes"][name] = int(tx[code])
            row["logical_bytes"][name] = int(logical[code])
    out["compress_seconds"] = float(comp.value)
    out["chunk_bytes"] = int(lib.hvd_ring_get_chunk_bytes())
    return out


def set_chunk_bytes(nbytes: int) -> None:
    """Push the ring transfer-chunk size (per-rank pipelining granularity;
    clamped/rounded by the C side). No-op when the core isn't loaded."""
    lib = loaded()
    if lib is not None:
        lib.hvd_ring_set_chunk_bytes(int(nbytes))


def native_counters() -> Optional[dict]:
    """The native engine's cumulative telemetry counters
    (``hvd_eng_get_counters``) as a dict: the scalar slots by name plus
    ``cycle_seconds``/``execute_seconds`` histograms ({count, sum_seconds,
    counts[23]} over the registry's DEFAULT_TIME_BUCKETS edges). None when
    the core isn't loaded, no engine ever initialized in this process
    (e.g. the Python controller merely using the ring data plane), or the
    loaded .so reports a different slot layout (ABI drift — also caught
    statically by hvdabi and loudly by the @slow rebuild smoke)."""
    lib = loaded()
    if lib is None or not lib.hvd_eng_active():
        return None
    arr = (ctypes.c_longlong * N_NATIVE_COUNTER_SLOTS)()
    n = lib.hvd_eng_get_counters(arr, N_NATIVE_COUNTER_SLOTS)
    if n != N_NATIVE_COUNTER_SLOTS:
        logging.warning(
            "native engine counter layout drift (.so reports %d slots, "
            "bindings expect %d); rebuild the core", n,
            N_NATIVE_COUNTER_SLOTS)
        return None
    out = {name: int(arr[i]) for i, name in enumerate(NATIVE_COUNTER_SCALARS)}

    def _hist(base):
        return {"count": int(arr[base]),
                "sum_seconds": arr[base + 1] / 1e6,
                "counts": [int(arr[base + 2 + i])
                           for i in range(NATIVE_HIST_SLOTS)]}

    out["cycle_seconds"] = _hist(_NATIVE_CYCLE_HIST_BASE)
    out["execute_seconds"] = _hist(_NATIVE_EXEC_HIST_BASE)
    out["engine_gen"] = int(arr[_NATIVE_ENGINE_GEN])
    return out


def drain_engine_spans(batch: int = 512):
    """Yield ``(phase_code, seq, t0, t1, tensors, op)`` for every span in
    the engine's ring, oldest first, consuming them. ``t0``/``t1`` are
    CLOCK_MONOTONIC seconds (``time.monotonic()``'s clock), ``seq`` is -1
    when no collective id applies. Stops when the ring is empty."""
    lib = loaded()
    if lib is None:
        return
    stride = NATIVE_SPAN_OP_BYTES
    phases = (ctypes.c_int * batch)()
    seqs = (ctypes.c_longlong * batch)()
    t0s = (ctypes.c_double * batch)()
    t1s = (ctypes.c_double * batch)()
    tensors = (ctypes.c_int * batch)()
    ops = ctypes.create_string_buffer(batch * stride)
    while True:
        n = lib.hvd_eng_get_spans(batch, phases, seqs, t0s, t1s, tensors,
                                  ops, stride)
        if n <= 0:
            return
        raw = ops.raw
        for i in range(n):
            op = raw[i * stride:(i + 1) * stride].split(b"\0", 1)[0]
            yield (int(phases[i]), int(seqs[i]), float(t0s[i]),
                   float(t1s[i]), int(tensors[i]),
                   op.decode(errors="replace"))
        if n < batch:
            return


def load() -> Optional[ctypes.CDLL]:
    """Load (building if needed); returns None if the toolchain is absent,
    letting callers fall back to the pure-Python star data plane."""
    global _lib, _build_failed
    with _lock:
        if _lib is not None:
            return _lib
        if _build_failed is not None:
            return None
        try:
            path = build()
        except (RuntimeError, FileNotFoundError) as exc:
            _build_failed = str(exc)
            logging.warning(
                "native core unavailable (%s); using Python data plane",
                exc)
            return None
        lib = ctypes.CDLL(path)
        lib.hvd_ring_init.argtypes = [
            ctypes.c_int, ctypes.c_int, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_int]
        lib.hvd_ring_init.restype = ctypes.c_int
        lib.hvd_ring_allreduce.argtypes = [
            ctypes.c_void_p, ctypes.c_long, ctypes.c_int, ctypes.c_int]
        lib.hvd_ring_allreduce.restype = ctypes.c_int
        # Round 10: wire-compressed allreduce (trailing wire-dtype code +
        # int8 error-feedback residual out-buffer) and the chunk/stat
        # surface for the autotuner and metrics mirroring.
        lib.hvd_ring_allreduce_wire.argtypes = [
            ctypes.c_void_p, ctypes.c_long, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_void_p]
        lib.hvd_ring_allreduce_wire.restype = ctypes.c_int
        lib.hvd_ring_set_chunk_bytes.argtypes = [ctypes.c_long]
        lib.hvd_ring_set_chunk_bytes.restype = None
        lib.hvd_ring_get_chunk_bytes.argtypes = []
        lib.hvd_ring_get_chunk_bytes.restype = ctypes.c_long
        lib.hvd_ring_get_wire_stats.argtypes = [
            ctypes.POINTER(ctypes.c_longlong),
            ctypes.POINTER(ctypes.c_longlong),
            ctypes.POINTER(ctypes.c_double)]
        lib.hvd_ring_get_wire_stats.restype = None
        # Round 12: per-link-class counter slice + link tagging + the
        # send-rate cap (bandwidth-probe link emulation).
        lib.hvd_ring_get_wire_stats_link.argtypes = [
            ctypes.c_int, ctypes.POINTER(ctypes.c_longlong),
            ctypes.POINTER(ctypes.c_longlong)]
        lib.hvd_ring_get_wire_stats_link.restype = None
        lib.hvd_ringh_set_link.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.hvd_ringh_set_link.restype = None
        lib.hvd_ringh_set_rate.argtypes = [ctypes.c_void_p, ctypes.c_double]
        lib.hvd_ringh_set_rate.restype = None
        lib.hvd_ring_allgather.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_long), ctypes.c_void_p,
            ctypes.c_int]
        lib.hvd_ring_allgather.restype = ctypes.c_int
        lib.hvd_ring_broadcast.argtypes = [
            ctypes.c_void_p, ctypes.c_long, ctypes.c_int, ctypes.c_int]
        lib.hvd_ring_broadcast.restype = ctypes.c_int
        lib.hvd_ring_last_error.restype = ctypes.c_char_p
        lib.hvd_ring_shutdown.restype = None
        # Handle-based ring ABI: several rings per process (flat + the
        # hierarchical local/cross pair).
        lib.hvd_ringh_create.argtypes = [
            ctypes.c_int, ctypes.c_int, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_int]
        lib.hvd_ringh_create.restype = ctypes.c_void_p
        lib.hvd_ringh_allreduce.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_long, ctypes.c_int,
            ctypes.c_int]
        lib.hvd_ringh_allreduce.restype = ctypes.c_int
        lib.hvd_ringh_allreduce_wire.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_long, ctypes.c_int,
            ctypes.c_int, ctypes.c_int, ctypes.c_void_p]
        lib.hvd_ringh_allreduce_wire.restype = ctypes.c_int
        lib.hvd_ringh_allgather.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.POINTER(ctypes.c_long),
            ctypes.c_void_p, ctypes.c_int]
        lib.hvd_ringh_allgather.restype = ctypes.c_int
        lib.hvd_ringh_broadcast.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_long, ctypes.c_int,
            ctypes.c_int]
        lib.hvd_ringh_broadcast.restype = ctypes.c_int
        lib.hvd_ringh_destroy.argtypes = [ctypes.c_void_p]
        lib.hvd_ringh_destroy.restype = None
        # Native eager-tier engine (engine.cc; reference C ABI shape at
        # horovod/common/operations.cc:1595-1650).
        # Round 16: trailing pipeline-enable flag (double-buffered fusion
        # + wire thread) on init, trailing launch priority on enqueue.
        lib.hvd_eng_init.argtypes = [
            ctypes.c_int, ctypes.c_int, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_int, ctypes.c_double,
            ctypes.c_longlong, ctypes.c_int, ctypes.c_int, ctypes.c_double,
            ctypes.c_double, ctypes.c_char_p, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_int, ctypes.c_int]
        lib.hvd_eng_init.restype = ctypes.c_int
        lib.hvd_eng_enqueue.argtypes = [
            ctypes.c_int, ctypes.c_char_p, ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_longlong), ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_void_p, ctypes.c_int]
        lib.hvd_eng_enqueue.restype = ctypes.c_longlong
        lib.hvd_eng_poll.argtypes = [ctypes.c_longlong]
        lib.hvd_eng_poll.restype = ctypes.c_int
        lib.hvd_eng_wait.argtypes = [ctypes.c_longlong]
        lib.hvd_eng_wait.restype = ctypes.c_int
        lib.hvd_eng_wait_for.argtypes = [ctypes.c_longlong, ctypes.c_double]
        lib.hvd_eng_wait_for.restype = ctypes.c_int
        lib.hvd_eng_hier_active.restype = ctypes.c_int
        lib.hvd_eng_result_nbytes.argtypes = [ctypes.c_longlong]
        lib.hvd_eng_result_nbytes.restype = ctypes.c_longlong
        lib.hvd_eng_result_ndim.argtypes = [ctypes.c_longlong]
        lib.hvd_eng_result_ndim.restype = ctypes.c_int
        lib.hvd_eng_result_dtype.argtypes = [ctypes.c_longlong]
        lib.hvd_eng_result_dtype.restype = ctypes.c_int
        lib.hvd_eng_result_in_place.argtypes = [ctypes.c_longlong]
        lib.hvd_eng_result_in_place.restype = ctypes.c_int
        lib.hvd_eng_result_shape.argtypes = [
            ctypes.c_longlong, ctypes.POINTER(ctypes.c_longlong)]
        lib.hvd_eng_result_shape.restype = None
        lib.hvd_eng_result_copy.argtypes = [ctypes.c_longlong, ctypes.c_void_p]
        lib.hvd_eng_result_copy.restype = ctypes.c_int
        lib.hvd_eng_result_sizes_count.argtypes = [ctypes.c_longlong]
        lib.hvd_eng_result_sizes_count.restype = ctypes.c_int
        lib.hvd_eng_result_sizes.argtypes = [
            ctypes.c_longlong, ctypes.POINTER(ctypes.c_longlong)]
        lib.hvd_eng_result_sizes.restype = None
        lib.hvd_eng_handle_error.argtypes = [ctypes.c_longlong]
        lib.hvd_eng_handle_error.restype = ctypes.c_char_p
        lib.hvd_eng_release.argtypes = [ctypes.c_longlong]
        lib.hvd_eng_release.restype = None
        lib.hvd_eng_set_params.argtypes = [ctypes.c_longlong, ctypes.c_double]
        lib.hvd_eng_set_params.restype = None
        lib.hvd_eng_get_stats.argtypes = [
            ctypes.POINTER(ctypes.c_longlong),
            ctypes.POINTER(ctypes.c_longlong),
            ctypes.POINTER(ctypes.c_double)]
        lib.hvd_eng_get_stats.restype = None
        lib.hvd_eng_shutdown.restype = ctypes.c_int
        lib.hvd_eng_last_error.restype = ctypes.c_char_p
        # Round 14: native telemetry plane — span ring drain, cumulative
        # counters/histograms, the trace enable flag, the synced
        # tuned-bucket slot and the span-stamp overhead probe.
        lib.hvd_eng_active.argtypes = []
        lib.hvd_eng_active.restype = ctypes.c_int
        lib.hvd_eng_trace_set.argtypes = [ctypes.c_int, ctypes.c_longlong]
        lib.hvd_eng_trace_set.restype = None
        lib.hvd_eng_get_spans.argtypes = [
            ctypes.c_longlong, ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_longlong),
            ctypes.POINTER(ctypes.c_double),
            ctypes.POINTER(ctypes.c_double), ctypes.POINTER(ctypes.c_int),
            ctypes.c_char_p, ctypes.c_int]
        lib.hvd_eng_get_spans.restype = ctypes.c_int
        lib.hvd_eng_get_counters.argtypes = [
            ctypes.POINTER(ctypes.c_longlong), ctypes.c_int]
        lib.hvd_eng_get_counters.restype = ctypes.c_int
        lib.hvd_eng_set_tuned_bucket.argtypes = [ctypes.c_longlong]
        lib.hvd_eng_set_tuned_bucket.restype = None
        lib.hvd_eng_span_probe.argtypes = [ctypes.c_longlong]
        lib.hvd_eng_span_probe.restype = ctypes.c_double
        _lib = lib
        return _lib


class RingBackend:
    """Thin numpy-facing wrapper over the handle-based C ABI. A process can
    hold several rings at once (the flat ring plus the hierarchical
    local/cross pair); each is owned by the controller's background thread
    (single-threaded by contract, like the reference's
    background-thread-owns-MPI design)."""

    def __init__(self, rank: int, size: int, addrs: str, secret: bytes):
        lib = load()
        if lib is None:
            raise RuntimeError(f"native core unavailable: {_build_failed}")
        self._lib = lib
        key = (ctypes.c_uint8 * len(secret)).from_buffer_copy(secret)
        self._handle = lib.hvd_ringh_create(
            rank, size, addrs.encode(), key, len(secret))
        if not self._handle:
            raise RuntimeError(
                f"ring init failed: {self._last_error()}")

    def _last_error(self) -> str:
        return self._lib.hvd_ring_last_error().decode(errors="replace")

    @staticmethod
    def dtype_code(dtype) -> Optional[int]:
        return _DTYPE_CODES.get(str(dtype))

    def allreduce_(self, array: np.ndarray, average: bool,
                   wire_dtype: int = 0,
                   residual: Optional[np.ndarray] = None) -> np.ndarray:
        """In-place sum (or mean) across ranks. ``wire_dtype`` is a
        WIRE_DTYPE_CODES code compressing f32 payloads on the wire (0
        keeps the stream byte-identical to the pre-round-10 ring);
        ``residual`` (f32, same element count, C-contiguous) receives the
        int8 error-feedback residual."""
        code = self.dtype_code(array.dtype)
        assert code is not None, f"unsupported dtype {array.dtype}"
        assert array.flags.c_contiguous
        res_ptr = None
        if residual is not None:
            assert residual.dtype == np.float32 and \
                residual.size == array.size and residual.flags.c_contiguous
            res_ptr = residual.ctypes.data_as(ctypes.c_void_p)
        rc = self._lib.hvd_ringh_allreduce_wire(
            self._handle, array.ctypes.data_as(ctypes.c_void_p), array.size,
            code, 1 if average else 0, int(wire_dtype), res_ptr)
        if rc != 0:
            raise RuntimeError(f"ring allreduce failed: {self._last_error()}")
        return array

    def allgather(self, array: np.ndarray, counts) -> np.ndarray:
        """Concatenate per-rank blocks (element counts per rank in
        ``counts``) along a flat axis; caller reshapes."""
        code = self.dtype_code(array.dtype)
        assert code is not None, f"unsupported dtype {array.dtype}"
        assert array.flags.c_contiguous
        counts_arr = (ctypes.c_long * len(counts))(*counts)
        out = np.empty(int(sum(counts)), dtype=array.dtype)
        rc = self._lib.hvd_ringh_allgather(
            self._handle, array.ctypes.data_as(ctypes.c_void_p), counts_arr,
            out.ctypes.data_as(ctypes.c_void_p), code)
        if rc != 0:
            raise RuntimeError(f"ring allgather failed: {self._last_error()}")
        return out

    def broadcast_(self, array: np.ndarray, root: int) -> np.ndarray:
        code = self.dtype_code(array.dtype)
        assert code is not None, f"unsupported dtype {array.dtype}"
        assert array.flags.c_contiguous
        rc = self._lib.hvd_ringh_broadcast(
            self._handle, array.ctypes.data_as(ctypes.c_void_p), array.size,
            code, root)
        if rc != 0:
            raise RuntimeError(f"ring broadcast failed: {self._last_error()}")
        return array

    def set_link(self, link) -> None:
        """Tag this ring's link class (``WIRE_LINK_CODES`` name or code)
        so its traffic lands in the right per-link counter row."""
        code = WIRE_LINK_CODES.get(link, link)
        self._lib.hvd_ringh_set_link(self._handle, int(code))

    def set_rate(self, bytes_per_s: float) -> None:
        """Cap this ring's send rate (bytes/s; 0 = unlimited). Emulation
        knob for the bandwidth probe — models a slow cross-node link on a
        loopback box; production jobs leave it unset."""
        self._lib.hvd_ringh_set_rate(self._handle, float(bytes_per_s))

    def shutdown(self) -> None:
        if getattr(self, "_handle", None):
            self._lib.hvd_ringh_destroy(self._handle)
            self._handle = None
