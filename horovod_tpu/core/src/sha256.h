// Compact SHA-256 + HMAC-SHA256 (FIPS 180-4 / RFC 2104), used to
// authenticate ring-connection handshakes with the per-job secret — the
// native counterpart of the Python wire's HMAC framing
// (horovod_tpu/common/wire.py; reference horovod/run/common/util/network.py).
// Self-contained: no OpenSSL dependency in the runtime image.
#pragma once

#include <cstdint>
#include <cstring>
#include <string>

namespace hvd {

class SHA256 {
 public:
  SHA256() { reset(); }

  void reset() {
    h_[0] = 0x6a09e667; h_[1] = 0xbb67ae85; h_[2] = 0x3c6ef372;
    h_[3] = 0xa54ff53a; h_[4] = 0x510e527f; h_[5] = 0x9b05688c;
    h_[6] = 0x1f83d9ab; h_[7] = 0x5be0cd19;
    len_ = 0;
    buf_len_ = 0;
  }

  void update(const uint8_t* data, size_t n) {
    len_ += n;
    while (n > 0) {
      size_t take = 64 - buf_len_;
      if (take > n) take = n;
      std::memcpy(buf_ + buf_len_, data, take);
      buf_len_ += take;
      data += take;
      n -= take;
      if (buf_len_ == 64) {
        process_block(buf_);
        buf_len_ = 0;
      }
    }
  }

  void finish(uint8_t out[32]) {
    uint64_t bit_len = len_ * 8;
    uint8_t pad = 0x80;
    update(&pad, 1);
    uint8_t zero = 0;
    while (buf_len_ != 56) update(&zero, 1);
    uint8_t lenb[8];
    for (int i = 0; i < 8; i++) lenb[i] = (uint8_t)(bit_len >> (56 - 8 * i));
    update(lenb, 8);
    for (int i = 0; i < 8; i++) {
      out[4 * i] = (uint8_t)(h_[i] >> 24);
      out[4 * i + 1] = (uint8_t)(h_[i] >> 16);
      out[4 * i + 2] = (uint8_t)(h_[i] >> 8);
      out[4 * i + 3] = (uint8_t)(h_[i]);
    }
  }

 private:
  static uint32_t rotr(uint32_t x, int n) { return (x >> n) | (x << (32 - n)); }

  void process_block(const uint8_t* p) {
    static const uint32_t K[64] = {
        0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b,
        0x59f111f1, 0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01,
        0x243185be, 0x550c7dc3, 0x72be5d74, 0x80deb1fe, 0x9bdc06a7,
        0xc19bf174, 0xe49b69c1, 0xefbe4786, 0x0fc19dc6, 0x240ca1cc,
        0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da, 0x983e5152,
        0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
        0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc,
        0x53380d13, 0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85,
        0xa2bfe8a1, 0xa81a664b, 0xc24b8b70, 0xc76c51a3, 0xd192e819,
        0xd6990624, 0xf40e3585, 0x106aa070, 0x19a4c116, 0x1e376c08,
        0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a, 0x5b9cca4f,
        0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
        0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2};
    uint32_t w[64];
    for (int i = 0; i < 16; i++)
      w[i] = (uint32_t(p[4 * i]) << 24) | (uint32_t(p[4 * i + 1]) << 16) |
             (uint32_t(p[4 * i + 2]) << 8) | uint32_t(p[4 * i + 3]);
    for (int i = 16; i < 64; i++) {
      uint32_t s0 = rotr(w[i - 15], 7) ^ rotr(w[i - 15], 18) ^ (w[i - 15] >> 3);
      uint32_t s1 = rotr(w[i - 2], 17) ^ rotr(w[i - 2], 19) ^ (w[i - 2] >> 10);
      w[i] = w[i - 16] + s0 + w[i - 7] + s1;
    }
    uint32_t a = h_[0], b = h_[1], c = h_[2], d = h_[3];
    uint32_t e = h_[4], f = h_[5], g = h_[6], h = h_[7];
    for (int i = 0; i < 64; i++) {
      uint32_t S1 = rotr(e, 6) ^ rotr(e, 11) ^ rotr(e, 25);
      uint32_t ch = (e & f) ^ (~e & g);
      uint32_t t1 = h + S1 + ch + K[i] + w[i];
      uint32_t S0 = rotr(a, 2) ^ rotr(a, 13) ^ rotr(a, 22);
      uint32_t maj = (a & b) ^ (a & c) ^ (b & c);
      uint32_t t2 = S0 + maj;
      h = g; g = f; f = e; e = d + t1;
      d = c; c = b; b = a; a = t1 + t2;
    }
    h_[0] += a; h_[1] += b; h_[2] += c; h_[3] += d;
    h_[4] += e; h_[5] += f; h_[6] += g; h_[7] += h;
  }

  uint32_t h_[8];
  uint64_t len_;
  uint8_t buf_[64];
  size_t buf_len_;
};

inline void hmac_sha256(const uint8_t* key, size_t key_len,
                        const uint8_t* msg, size_t msg_len,
                        uint8_t out[32]) {
  uint8_t k[64];
  std::memset(k, 0, 64);
  if (key_len > 64) {
    SHA256 h;
    h.update(key, key_len);
    h.finish(k);  // first 32 bytes; rest zero
  } else {
    std::memcpy(k, key, key_len);
  }
  uint8_t ipad[64], opad[64];
  for (int i = 0; i < 64; i++) {
    ipad[i] = k[i] ^ 0x36;
    opad[i] = k[i] ^ 0x5c;
  }
  uint8_t inner[32];
  SHA256 hi;
  hi.update(ipad, 64);
  hi.update(msg, msg_len);
  hi.finish(inner);
  SHA256 ho;
  ho.update(opad, 64);
  ho.update(inner, 32);
  ho.finish(out);
}

}  // namespace hvd
