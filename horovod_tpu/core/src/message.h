// Control-plane message protocol for the native engine: Request / Response
// structs, a compact binary wire format, and the cross-rank validation matrix.
//
// Reference: horovod/common/message.{h,cc} + common/wire/message.fbs — each
// rank's background thread emits a Request per pending tensor (rank, type,
// dtype, name, shape, root); the coordinator replies with a fused
// ResponseList. The reference serializes with FlatBuffers; payloads here are
// tiny and ride the already-authenticated ring connections, so a hand-rolled
// little-endian framing is used instead (one fewer vendored dependency).
//
// construct_response reproduces the reference's full validation matrix
// (ConstructResponse, horovod/common/operations.cc:198-371): mismatched
// dtype / op / shape / root across ranks produces an ERROR response whose
// message is delivered to every participating rank's callback. Error strings
// match horovod_tpu/common/message.py (the Python controller) so both
// engines surface identical diagnostics.

#ifndef HVD_TPU_MESSAGE_H_
#define HVD_TPU_MESSAGE_H_

#include <cstdint>
#include <cstring>
#include <map>
#include <sstream>
#include <string>
#include <vector>

namespace hvd {

enum RequestType : uint8_t {  // reference message.h:47
  REQ_ALLREDUCE = 0,
  REQ_ALLGATHER = 1,
  REQ_BROADCAST = 2,
};

enum ResponseType : uint8_t {  // reference message.h:132
  RESP_ALLREDUCE = 0,
  RESP_ALLGATHER = 1,
  RESP_BROADCAST = 2,
  RESP_ERROR = 3,
};

struct Request {  // reference message.h:40-120
  int32_t request_rank = 0;
  uint8_t request_type = REQ_ALLREDUCE;
  uint8_t dtype = 0;  // ring.cc DType code
  int32_t root_rank = -1;
  // Launch priority (0 = none). The coordinator stable-sorts each cycle's
  // fused responses by the tagged priority so the optimizer-critical
  // bucket jumps the launch queue on EVERY rank identically. Like dtype,
  // the value must agree across ranks for a given tensor; it is NOT part
  // of same_params — a priority mismatch reorders, it doesn't error.
  int32_t priority = 0;
  std::vector<int64_t> shape;
  std::string tensor_name;

  bool same_params(const Request& o) const {
    return request_type == o.request_type && dtype == o.dtype &&
           root_rank == o.root_rank && shape == o.shape;
  }
};

struct RequestList {  // reference message.h:186-215
  std::vector<Request> requests;
  bool shutdown = false;
};

struct Response {  // reference message.h:125-184
  uint8_t response_type = RESP_ALLREDUCE;
  std::vector<std::string> tensor_names;
  std::string error_message;
  // Allgather only: every rank's dim-0 size, rank order.
  std::vector<int64_t> tensor_sizes;
};

struct ResponseList {
  std::vector<Response> responses;
  bool shutdown = false;
};

// ---------------------------------------------------------------- wire format

class Writer {
 public:
  std::vector<uint8_t> buf;
  void u8(uint8_t v) { buf.push_back(v); }
  void u32(uint32_t v) {
    size_t n = buf.size();
    buf.resize(n + 4);
    std::memcpy(buf.data() + n, &v, 4);
  }
  void i32(int32_t v) { u32((uint32_t)v); }
  void i64(int64_t v) {
    size_t n = buf.size();
    buf.resize(n + 8);
    std::memcpy(buf.data() + n, &v, 8);
  }
  void u64(uint64_t v) { i64((int64_t)v); }
  void str(const std::string& s) {
    u32((uint32_t)s.size());
    buf.insert(buf.end(), s.begin(), s.end());
  }
  void i64vec(const std::vector<int64_t>& v) {
    u32((uint32_t)v.size());
    for (int64_t x : v) i64(x);
  }
  void u64vec(const std::vector<uint64_t>& v) {
    u32((uint32_t)v.size());
    for (uint64_t x : v) u64(x);
  }
};

class Reader {
 public:
  const uint8_t* p;
  const uint8_t* end;
  bool ok = true;
  Reader(const uint8_t* data, size_t n) : p(data), end(data + n) {}

  bool need(size_t n) {
    if ((size_t)(end - p) < n) {
      ok = false;
      return false;
    }
    return true;
  }
  uint8_t u8() {
    if (!need(1)) return 0;
    return *p++;
  }
  uint32_t u32() {
    if (!need(4)) return 0;
    uint32_t v;
    std::memcpy(&v, p, 4);
    p += 4;
    return v;
  }
  int32_t i32() { return (int32_t)u32(); }
  int64_t i64() {
    if (!need(8)) return 0;
    int64_t v;
    std::memcpy(&v, p, 8);
    p += 8;
    return v;
  }
  uint64_t u64() { return (uint64_t)i64(); }
  std::string str() {
    uint32_t n = u32();
    if (!need(n)) return "";
    std::string s((const char*)p, n);
    p += n;
    return s;
  }
  std::vector<int64_t> i64vec() {
    uint32_t n = u32();
    std::vector<int64_t> v;
    v.reserve(n);
    for (uint32_t i = 0; i < n && ok; i++) v.push_back(i64());
    return v;
  }
  std::vector<uint64_t> u64vec() {
    uint32_t n = u32();
    std::vector<uint64_t> v;
    v.reserve(n);
    for (uint32_t i = 0; i < n && ok; i++) v.push_back(u64());
    return v;
  }
};

inline void write_request(Writer& w, const Request& r) {
  w.i32(r.request_rank);
  w.u8(r.request_type);
  w.u8(r.dtype);
  w.i32(r.root_rank);
  w.i32(r.priority);
  w.i64vec(r.shape);
  w.str(r.tensor_name);
}

inline Request read_request(Reader& r) {
  Request q;
  q.request_rank = r.i32();
  q.request_type = r.u8();
  q.dtype = r.u8();
  q.root_rank = r.i32();
  q.priority = r.i32();
  q.shape = r.i64vec();
  q.tensor_name = r.str();
  return q;
}

inline void write_response(Writer& w, const Response& r) {
  w.u8(r.response_type);
  w.str(r.error_message);
  w.u32((uint32_t)r.tensor_names.size());
  for (const auto& n : r.tensor_names) w.str(n);
  w.i64vec(r.tensor_sizes);
}

inline Response read_response(Reader& r) {
  Response q;
  q.response_type = r.u8();
  q.error_message = r.str();
  uint32_t n = r.u32();
  for (uint32_t i = 0; i < n && r.ok; i++) q.tensor_names.push_back(r.str());
  q.tensor_sizes = r.i64vec();
  return q;
}

// --------------------------------------------------------- validation matrix

// Python-tuple-style shape formatting, matching the Python controller's
// error strings: "()", "(2,)", "(2, 3)".
inline std::string shape_str(const std::vector<int64_t>& shape) {
  std::ostringstream os;
  os << "(";
  for (size_t i = 0; i < shape.size(); i++) {
    if (i) os << ", ";
    os << shape[i];
  }
  if (shape.size() == 1) os << ",";
  os << ")";
  return os.str();
}

inline const char* type_name(uint8_t t) {
  switch (t) {
    case REQ_ALLREDUCE: return "allreduce";
    case REQ_ALLGATHER: return "allgather";
    case REQ_BROADCAST: return "broadcast";
  }
  return "?";
}

// dtype_name is provided by the engine (maps ring DType codes to numpy-style
// names for error messages).
std::string dtype_name(uint8_t code);

// Build one tensor's Response once all `size` ranks have submitted requests
// (reference ConstructResponse, operations.cc:198-371: first mismatch wins,
// error names the offending ranks' values). `requests[i]` is rank i's.
inline Response construct_response(const std::vector<Request>& requests,
                                   int size) {
  const Request& first = requests[0];
  const std::string& name = first.tensor_name;
  Response err;
  err.response_type = RESP_ERROR;
  err.tensor_names.push_back(name);

  for (int i = 1; i < size; i++) {
    const Request& req = requests[i];
    if (req.request_type != first.request_type) {
      std::ostringstream os;
      os << "Mismatched collective operations: rank " << first.request_rank
         << " requested " << type_name(first.request_type) << " of tensor "
         << name << ", but rank " << req.request_rank << " requested "
         << type_name(req.request_type) << ".";
      err.error_message = os.str();
      return err;
    }
  }
  for (int i = 1; i < size; i++) {
    const Request& req = requests[i];
    if (req.dtype != first.dtype) {
      std::ostringstream os;
      os << "Mismatched data types: rank " << first.request_rank
         << " has tensor " << name << " with dtype " << dtype_name(first.dtype)
         << ", but rank " << req.request_rank << " has dtype "
         << dtype_name(req.dtype) << ".";
      err.error_message = os.str();
      return err;
    }
  }

  if (first.request_type == REQ_ALLREDUCE) {
    for (int i = 1; i < size; i++) {
      const Request& req = requests[i];
      if (req.shape != first.shape) {
        std::ostringstream os;
        os << "Mismatched allreduce tensor shapes: rank " << first.request_rank
           << " has shape " << shape_str(first.shape) << " for tensor " << name
           << ", but rank " << req.request_rank << " has shape "
           << shape_str(req.shape) << ".";
        err.error_message = os.str();
        return err;
      }
    }
    Response r;
    r.response_type = RESP_ALLREDUCE;
    r.tensor_names.push_back(name);
    return r;
  }

  if (first.request_type == REQ_BROADCAST) {
    for (int i = 1; i < size; i++) {
      const Request& req = requests[i];
      if (req.root_rank != first.root_rank) {
        std::ostringstream os;
        os << "Mismatched broadcast root ranks: rank " << first.request_rank
           << " specified root " << first.root_rank << " for tensor " << name
           << ", but rank " << req.request_rank << " specified "
           << req.root_rank << ".";
        err.error_message = os.str();
        return err;
      }
    }
    if (first.root_rank < 0 || first.root_rank >= size) {
      std::ostringstream os;
      os << "Invalid broadcast root rank " << first.root_rank << " for tensor "
         << name << ": world size is " << size << ".";
      err.error_message = os.str();
      return err;
    }
    const Request& root_req = requests[first.root_rank];
    for (int i = 0; i < size; i++) {
      const Request& req = requests[i];
      if (req.shape != root_req.shape) {
        std::ostringstream os;
        os << "Mismatched broadcast tensor shapes: root rank "
           << root_req.request_rank << " has shape "
           << shape_str(root_req.shape) << " for tensor " << name
           << ", but rank " << req.request_rank << " has shape "
           << shape_str(req.shape) << ".";
        err.error_message = os.str();
        return err;
      }
    }
    Response r;
    r.response_type = RESP_BROADCAST;
    r.tensor_names.push_back(name);
    return r;
  }

  // ALLGATHER
  for (int i = 1; i < size; i++) {
    const Request& req = requests[i];
    if (req.shape.size() != first.shape.size()) {
      std::ostringstream os;
      os << "Mismatched allgather tensor ranks: rank " << first.request_rank
         << " has rank-" << first.shape.size() << " tensor " << name
         << ", but rank " << req.request_rank << " has rank "
         << req.shape.size() << ".";
      err.error_message = os.str();
      return err;
    }
    if (!first.shape.empty() &&
        !std::equal(req.shape.begin() + 1, req.shape.end(),
                    first.shape.begin() + 1)) {
      std::ostringstream os;
      os << "Mismatched allgather tensor shapes: all dimensions except the "
            "first must match; rank "
         << first.request_rank << " has shape " << shape_str(first.shape)
         << " for tensor " << name << ", but rank " << req.request_rank
         << " has shape " << shape_str(req.shape) << ".";
      err.error_message = os.str();
      return err;
    }
  }
  if (first.shape.empty()) {
    std::ostringstream os;
    os << "Allgather of scalar tensor " << name
       << " is not possible: tensors must have at least one dimension.";
    err.error_message = os.str();
    return err;
  }
  Response r;
  r.response_type = RESP_ALLGATHER;
  r.tensor_names.push_back(name);
  r.tensor_sizes.resize(size);
  for (int i = 0; i < size; i++) r.tensor_sizes[i] = requests[i].shape[0];
  return r;
}

}  // namespace hvd

#endif  // HVD_TPU_MESSAGE_H_
