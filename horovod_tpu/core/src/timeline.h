// Horovod Timeline for the native engine: Chrome-tracing JSON profiler.
//
// Reference: horovod/common/timeline.{h,cc} — rank 0 writes one
// chrome://tracing file covering all ranks (the coordinator knows every
// tensor's lifecycle), with a dedicated writer thread draining a queue so the
// hot path never blocks (timeline.h:46-74, WriterLoop timeline.cc:120).
//
// Event vocabulary and JSON shape match the Python twin
// (horovod_tpu/common/timeline.py) so tooling and tests treat both engines'
// traces identically: per-tensor chrome "process" (pid) metadata, NEGOTIATE_*
// B/E spans, per-rank instant events during negotiation, top-level op spans,
// tid-1 activity spans, and opt-in CYCLE_START instants.

#ifndef HVD_TPU_TIMELINE_H_
#define HVD_TPU_TIMELINE_H_

#include <chrono>
#include <condition_variable>
#include <cstdio>
#include <deque>
#include <map>
#include <mutex>
#include <string>
#include <thread>

namespace hvd {

class Timeline {
 public:
  Timeline(const std::string& filename, bool mark_cycles)
      : mark_cycles_(mark_cycles),
        start_(std::chrono::steady_clock::now()),
        file_(std::fopen(filename.c_str(), "w")) {
    if (file_) {
      std::fputs("[\n", file_);
      writer_ = std::thread([this] { writer_loop(); });
    }
  }

  ~Timeline() { close(); }

  bool enabled() const { return file_ != nullptr; }

  void negotiate_start(const std::string& tensor, const char* op_name) {
    char ev[160];
    std::snprintf(ev, sizeof(ev),
                  "{\"name\": \"NEGOTIATE_%s\", \"ph\": \"B\", \"pid\": %d, "
                  "\"ts\": %lld}",
                  op_name, pid_of(tensor), now_us());
    emit(ev);
  }

  // Instant event when a rank's request arrives at the coordinator.
  void negotiate_rank_ready(const std::string& tensor, int rank) {
    char ev[160];
    std::snprintf(ev, sizeof(ev),
                  "{\"name\": \"%d\", \"ph\": \"i\", \"pid\": %d, "
                  "\"ts\": %lld, \"s\": \"p\"}",
                  rank, pid_of(tensor), now_us());
    emit(ev);
  }

  void negotiate_end(const std::string& tensor, const char* op_name) {
    char ev[160];
    std::snprintf(ev, sizeof(ev),
                  "{\"name\": \"NEGOTIATE_%s\", \"ph\": \"E\", \"pid\": %d, "
                  "\"ts\": %lld}",
                  op_name, pid_of(tensor), now_us());
    emit(ev);
  }

  // Top-level operation span (ALLREDUCE/ALLGATHER/BROADCAST).
  void start(const std::string& tensor, const char* op_name) {
    char ev[160];
    std::snprintf(ev, sizeof(ev),
                  "{\"name\": \"%s\", \"ph\": \"B\", \"pid\": %d, "
                  "\"ts\": %lld}",
                  op_name, pid_of(tensor), now_us());
    emit(ev);
  }

  void activity_start(const std::string& tensor, const char* activity) {
    char ev[192];
    std::snprintf(ev, sizeof(ev),
                  "{\"name\": \"%s\", \"ph\": \"B\", \"pid\": %d, "
                  "\"tid\": 1, \"ts\": %lld}",
                  activity, pid_of(tensor), now_us());
    emit(ev);
  }

  void activity_end(const std::string& tensor) {
    char ev[128];
    std::snprintf(ev, sizeof(ev),
                  "{\"ph\": \"E\", \"pid\": %d, \"tid\": 1, \"ts\": %lld}",
                  pid_of(tensor), now_us());
    emit(ev);
  }

  void end(const std::string& tensor) {
    char ev[128];
    std::snprintf(ev, sizeof(ev),
                  "{\"ph\": \"E\", \"pid\": %d, \"ts\": %lld}",
                  pid_of(tensor), now_us());
    emit(ev);
  }

  void mark_cycle_start() {
    if (!mark_cycles_) return;
    char ev[128];
    std::snprintf(ev, sizeof(ev),
                  "{\"name\": \"CYCLE_START\", \"ph\": \"i\", \"pid\": 0, "
                  "\"ts\": %lld, \"s\": \"g\"}",
                  now_us());
    emit(ev);
  }

  void close() {
    if (!file_ || closed_) return;
    {
      std::lock_guard<std::mutex> g(mu_);
      closed_ = true;
    }
    cv_.notify_all();
    if (writer_.joinable()) writer_.join();
    std::fputs("{\"name\": \"trace_end\", \"ph\": \"M\", \"pid\": 0}\n]\n",
               file_);
    std::fclose(file_);
    file_ = nullptr;
  }

 private:
  long long now_us() const {
    return std::chrono::duration_cast<std::chrono::microseconds>(
               std::chrono::steady_clock::now() - start_)
        .count();
  }

  // Tensor names are user input: escape per JSON (the Python twin gets this
  // from json.dumps) so names with quotes/backslashes/control chars keep the
  // trace parseable.
  static std::string json_escape(const std::string& s) {
    std::string out;
    out.reserve(s.size());
    for (unsigned char c : s) {
      switch (c) {
        case '"': out += "\\\""; break;
        case '\\': out += "\\\\"; break;
        case '\n': out += "\\n"; break;
        case '\r': out += "\\r"; break;
        case '\t': out += "\\t"; break;
        default:
          if (c < 0x20) {
            char buf[8];
            std::snprintf(buf, sizeof(buf), "\\u%04x", c);
            out += buf;
          } else {
            out += (char)c;
          }
      }
    }
    return out;
  }

  int pid_of(const std::string& tensor) {
    std::lock_guard<std::mutex> g(pid_mu_);
    auto it = pids_.find(tensor);
    if (it != pids_.end()) return it->second;
    int pid = (int)pids_.size() + 1;
    pids_[tensor] = pid;
    emit("{\"name\": \"process_name\", \"ph\": \"M\", \"pid\": " +
         std::to_string(pid) + ", \"args\": {\"name\": \"" +
         json_escape(tensor) + "\"}}");
    emit("{\"name\": \"process_sort_index\", \"ph\": \"M\", \"pid\": " +
         std::to_string(pid) + ", \"args\": {\"sort_index\": " +
         std::to_string(pid) + "}}");
    return pid;
  }

  void emit(const std::string& event) {
    if (!file_) return;
    {
      std::lock_guard<std::mutex> g(mu_);
      if (closed_ || queue_.size() >= (1u << 20)) return;  // drop, don't block
      queue_.push_back(event);
    }
    cv_.notify_one();
  }

  void writer_loop() {
    std::unique_lock<std::mutex> lk(mu_);
    while (true) {
      cv_.wait(lk, [this] { return closed_ || !queue_.empty(); });
      while (!queue_.empty()) {
        std::string ev = std::move(queue_.front());
        queue_.pop_front();
        lk.unlock();
        std::fputs(ev.c_str(), file_);
        std::fputs(",\n", file_);
        lk.lock();
      }
      if (closed_) return;
    }
  }

  bool mark_cycles_;
  std::chrono::steady_clock::time_point start_;
  std::FILE* file_;
  std::mutex mu_;        // guards queue_ + closed_
  std::mutex pid_mu_;    // guards pids_
  std::condition_variable cv_;
  std::deque<std::string> queue_;
  std::map<std::string, int> pids_;
  bool closed_ = false;
  std::thread writer_;
};

}  // namespace hvd

#endif  // HVD_TPU_TIMELINE_H_
