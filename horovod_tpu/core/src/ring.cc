// Native TCP ring collectives for host tensors — the data-plane replacement
// for the reference's MPI CPU ops (horovod/common/ops/mpi_operations.cc):
// bandwidth-optimal ring allreduce (reduce-scatter + allgather phases, the
// same algorithm the reference gets from MPI/NCCL underneath), ring
// allgather with per-rank counts (MPI_Allgatherv equivalent,
// mpi_operations.cc:95-173), and ring broadcast (mpi_operations.cc:334-358).
//
// Exposed as a C ABI consumed over ctypes (the reference exposes its C ABI
// the same way, horovod/common/operations.cc:1595-1650 + common/basics.py).
// Two surfaces: the legacy global-ring functions (hvd_ring_*) used by the
// native engine (engine.cc), and handle-based functions (hvd_ringh_*) so one
// process can hold several rings at once — the two-level hierarchical data
// plane needs a local ring, a cross ring and the flat ring side by side (the
// reference likewise holds one NCCL comm per device set,
// nccl_operations.cc:114).
// Single-threaded by contract: only the controller background thread calls
// in, mirroring the reference's one-background-thread-owns-MPI design
// (SURVEY.md §5 "Race detection").
//
// Connections are authenticated with HMAC-SHA256 over the per-job secret
// (sha256.h), so a stray connection to a ring port cannot inject data.

#include <arpa/inet.h>
#include <errno.h>
#include <fcntl.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/socket.h>
#include <unistd.h>

#if defined(__F16C__)
#include <immintrin.h>
#endif

#include <atomic>
#include <chrono>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

#include "sha256.h"

namespace {

std::string g_error;

void set_error(const std::string& msg) { g_error = msg; }

struct Ring {
  int rank = -1;
  int size = 0;
  int left_fd = -1;   // recv from left neighbor
  int right_fd = -1;  // send to right neighbor
  int listen_fd = -1;
  std::vector<uint8_t> secret;
  // Link class this ring's connections ride (LINK_* below): indexes the
  // per-link wire-traffic counters so the flat, local and cross planes
  // account separately (hvd_ring_wire_bytes_total{dtype,link}).
  int link = 0;
  // Optional send-rate cap in bytes/s (0 = unlimited): a token bucket the
  // send paths meter through, used by the bandwidth probe to emulate a
  // slow cross-node link on a loopback box. Per ring, so a hierarchical
  // layout can cap only its cross ring.
  double rate_Bps = 0.0;
  double rate_tokens = 0.0;
  double rate_t = 0.0;
  // Wire-compression scratch, persistent across calls so steady-state
  // allreduces allocate nothing (single-threaded per ring by contract).
  std::vector<char> wtx, wrx, wfwd;
  std::vector<float> wscratch;
};

// Link classes for the wire-traffic counters. Must match
// core.bindings.WIRE_LINK_CODES.
enum WireLink {
  LINK_FLAT = 0,   // the flat (global) ring
  LINK_LOCAL = 1,  // hierarchical intra-node ring
  LINK_CROSS = 2,  // hierarchical cross ring (local roots)
};
constexpr int kNumLinks = 3;

enum DType {
  DT_F32 = 0,
  DT_F64 = 1,
  DT_I32 = 2,
  DT_I64 = 3,
  DT_U8 = 4,
  DT_F16 = 5,
  DT_BF16 = 6,
  DT_I8 = 7,
  DT_I16 = 8,
  DT_U16 = 9,
  DT_BOOL = 10,  // reduced with logical OR (any), like MPI_LOR
};

size_t dtype_size(int dt) {
  switch (dt) {
    case DT_F32: case DT_I32: return 4;
    case DT_F64: case DT_I64: return 8;
    case DT_U8: case DT_I8: case DT_BOOL: return 1;
    case DT_F16: case DT_BF16: case DT_I16: case DT_U16: return 2;
  }
  return 0;
}

// --- in-flight wire compression (round-10: ROADMAP item 4) ------------------
// The reference fuses fp16 compression into its NCCL data path; here the
// analogue compresses each chunk AT SEND TIME on the TCP ring: allreduce
// payloads of f32 travel the wire as bf16/fp16 (half the bytes) or as int8
// with a per-block scale (quarter the bytes), while every accumulation
// stays in f32. Selected per call (the wire_dtype arg threaded from
// HOROVOD_RING_WIRE_DTYPE through common/config.py); WIRE_NONE keeps the
// pre-round-10 byte stream exactly. Non-f32 dtypes always travel
// uncompressed — the half types already are their own wire format, and
// integer sums must be exact.

enum WireDType {
  WIRE_NONE = 0,
  WIRE_BF16 = 1,
  WIRE_F16 = 2,
  WIRE_I8 = 3,
};

// int8 quantization block: ONE f32 scale per this many elements, fixed so
// the wire format never depends on the (autotuned, per-rank) transfer
// chunk size — sender and receiver need no chunk agreement. 4096 elems =
// 16 KiB of f32, 4 KiB on the wire + 4-byte scale (~0.1% overhead).
constexpr long kQuantBlock = 4096;

// Pipelining/transfer chunk for the reduce-while-receive sink AND the
// compress-ahead cursor. 256 KiB was the round-3 constant; round 10 makes
// it runtime-settable (hvd_ring_set_chunk_bytes) so the GP autotuner can
// fit it to the link class (ICI/DCN/TCP/loopback). Multiple of 8 by
// construction (setter rounds), so chunk boundaries stay element-aligned
// for every dtype size.
std::atomic<long> g_chunk_bytes{256 * 1024};

long chunk_bytes_now() { return g_chunk_bytes.load(std::memory_order_relaxed); }

// Wire traffic accounting, indexed by [WireLink][WireDType]: actual bytes
// handed to the kernel vs the f32-equivalent ("logical") bytes they
// carry, plus time spent in compress/decompress kernels. Python mirrors
// these into hvd_ring_wire_bytes_total{dtype,link} /
// hvd_ring_compress_seconds.
std::atomic<long long> g_wire_tx_bytes[kNumLinks][4];
std::atomic<long long> g_wire_logical_bytes[kNumLinks][4];
std::atomic<long long> g_compress_ns{0};

struct CompressTimer {
  std::chrono::steady_clock::time_point t0;
  CompressTimer() : t0(std::chrono::steady_clock::now()) {}
  ~CompressTimer() {
    g_compress_ns.fetch_add(
        std::chrono::duration_cast<std::chrono::nanoseconds>(
            std::chrono::steady_clock::now() - t0)
            .count(),
        std::memory_order_relaxed);
  }
};

// Wire bytes for n f32 elements under a wire dtype (int8 adds one f32
// scale per quant block).
size_t wire_nbytes(long n, int wire) {
  switch (wire) {
    case WIRE_BF16: case WIRE_F16: return (size_t)n * 2;
    case WIRE_I8:
      return (size_t)n + 4 * (size_t)((n + kQuantBlock - 1) / kQuantBlock);
    default: return (size_t)n * 4;
  }
}


// --- half-precision conversions (scalar; reference uses F16C intrinsics
// with a scalar fallback, common/half.cc:28-78) -----------------------------

float f16_to_f32(uint16_t h) {
  uint32_t sign = (uint32_t)(h & 0x8000) << 16;
  uint32_t exp = (h >> 10) & 0x1f;
  uint32_t mant = h & 0x3ff;
  uint32_t bits;
  if (exp == 0) {
    if (mant == 0) {
      bits = sign;
    } else {  // subnormal
      exp = 127 - 15 + 1;
      while (!(mant & 0x400)) {
        mant <<= 1;
        exp--;
      }
      mant &= 0x3ff;
      bits = sign | (exp << 23) | (mant << 13);
    }
  } else if (exp == 31) {
    if (mant) mant |= 0x200;  // quiet the NaN, like VCVTPH2PS
    bits = sign | 0x7f800000 | (mant << 13);
  } else {
    bits = sign | ((exp - 15 + 127) << 23) | (mant << 13);
  }
  float f;
  std::memcpy(&f, &bits, 4);
  return f;
}

uint16_t f32_to_f16(float f) {
  // Round-to-nearest-even, matching F16C's _mm256_cvtps_ph: the scalar
  // tail and the vector body must produce byte-identical results.
  uint32_t bits;
  std::memcpy(&bits, &f, 4);
  uint16_t sign = (uint16_t)((bits >> 16) & 0x8000);
  int32_t exp = (int32_t)((bits >> 23) & 0xff) - 127 + 15;
  uint32_t mant = bits & 0x7fffff;
  if (exp >= 31) {
    if (((bits >> 23) & 0xff) == 0xff && mant)
      // NaN: quiet bit + truncated payload, exactly VCVTPS2PH's result
      // (an exp>=31 finite or inf still becomes inf below).
      return sign | 0x7e00 | (uint16_t)(mant >> 13);
    return sign | 0x7c00;  // overflow -> inf
  }
  if (exp <= 0) {
    if (exp < -10) return sign;
    mant |= 0x800000;
    uint32_t shift = (uint32_t)(14 - exp);
    uint32_t half = mant >> shift;
    uint32_t dropped_mask = (1u << shift) - 1;
    uint32_t dropped = mant & dropped_mask;
    uint32_t halfway = 1u << (shift - 1);
    if (dropped > halfway || (dropped == halfway && (half & 1)))
      half++;  // RNE on the subnormal shift
    return sign | (uint16_t)half;
  }
  uint32_t half = (uint32_t)(exp << 10) | (mant >> 13);
  uint32_t dropped = mant & 0x1fff;
  if (dropped > 0x1000 || (dropped == 0x1000 && (half & 1)))
    half++;  // RNE; mantissa carry correctly bumps the exponent
  return sign | (uint16_t)half;
}

float bf16_to_f32(uint16_t h) {
  uint32_t bits = (uint32_t)h << 16;
  float f;
  std::memcpy(&f, &bits, 4);
  return f;
}

uint16_t f32_to_bf16(float f) {
  uint32_t bits;
  std::memcpy(&bits, &f, 4);
  // round-to-nearest-even on the dropped 16 bits
  uint32_t rounding = 0x7fff + ((bits >> 16) & 1);
  return (uint16_t)((bits + rounding) >> 16);
}

// --- vectorized half-precision block ops -----------------------------------
// The reference vectorizes its fp16 sum with F16C/AVX intrinsics behind a
// runtime CPUID check (common/half.cc:28-78). Here the dispatch is at
// COMPILE time: bindings.py builds this .so with -march=native and keys the
// artifact name on the host CPU's flag signature, so __F16C__ being defined
// means the host has it. Scalar tails use the RNE scalar converters above,
// which match the intrinsics bit-for-bit.

void f16_to_f32_block(const uint16_t* s, float* d, long n) {
  long i = 0;
#if defined(__F16C__)
  for (; i + 8 <= n; i += 8)
    _mm256_storeu_ps(
        d + i, _mm256_cvtph_ps(_mm_loadu_si128((const __m128i*)(s + i))));
#endif
  for (; i < n; i++) d[i] = f16_to_f32(s[i]);
}

void f32_to_f16_block(const float* s, uint16_t* d, long n) {
  long i = 0;
#if defined(__F16C__)
  for (; i + 8 <= n; i += 8)
    _mm_storeu_si128(
        (__m128i*)(d + i),
        _mm256_cvtps_ph(_mm256_loadu_ps(s + i), _MM_FROUND_TO_NEAREST_INT));
#endif
  for (; i < n; i++) d[i] = f32_to_f16(s[i]);
}

void bf16_to_f32_block(const uint16_t* s, float* d, long n) {
  // Branchless widen; autovectorizes under -O3 -march=native.
  for (long i = 0; i < n; i++) d[i] = bf16_to_f32(s[i]);
}

void f32_to_bf16_block(const float* s, uint16_t* d, long n) {
  for (long i = 0; i < n; i++) d[i] = f32_to_bf16(s[i]);
}

// --- wire codec: f32 <-> wire chunk, with int8 residual capture ------------

// The int8 codec's arithmetic contract is plain mul-THEN-add with f32
// rounding at every step: q*scale rounds before it is added/subtracted.
// GCC's default -ffp-contract=fast would fuse those into FMAs (no
// intermediate rounding), making the reduced values compiler-dependent
// and — worse — making the recorded residual differ by an ulp from
// x - (what the receiver actually adds), which breaks the exact
// error-feedback telescoping. Contraction stays off for the codec only.
#pragma GCC push_options
#pragma GCC optimize("fp-contract=off")

// Compress n f32 into the wire format. Quant blocks are anchored at the
// start of the region being compressed (callers only ever hand in whole
// segments, or block-aligned chunks of one, so scale positions are
// deterministic for the receiver). For WIRE_I8, `residual` (nullable)
// receives x - dequant(quant(x)) per element — the exact error this
// quantization introduced, which the error-feedback layer
// (controller/native.py) carries into the next allreduce.
size_t wire_compress(const float* src, long n, int wire, char* dst,
                     float* residual) {
  CompressTimer t;
  switch (wire) {
    case WIRE_BF16:
      f32_to_bf16_block(src, (uint16_t*)dst, n);
      return (size_t)n * 2;
    case WIRE_F16:
      f32_to_f16_block(src, (uint16_t*)dst, n);
      return (size_t)n * 2;
    case WIRE_I8: {
      char* p = dst;
      for (long b = 0; b < n; b += kQuantBlock) {
        long m = n - b < kQuantBlock ? n - b : kQuantBlock;
        float amax = 0.0f;
        for (long i = 0; i < m; i++) {
          float a = std::fabs(src[b + i]);
          if (a > amax) amax = a;
        }
        float scale = amax / 127.0f;
        std::memcpy(p, &scale, 4);
        p += 4;
        int8_t* q = (int8_t*)p;
        if (scale == 0.0f) {
          std::memset(q, 0, (size_t)m);
          if (residual)
            for (long i = 0; i < m; i++) residual[b + i] = src[b + i];
        } else {
          float inv = 1.0f / scale;
          for (long i = 0; i < m; i++) {
            float v = src[b + i] * inv;
            // RNE like the half converters; clamp keeps +-inf sane.
            v = v > 127.0f ? 127.0f : (v < -127.0f ? -127.0f : v);
            q[i] = (int8_t)std::nearbyint(v);
          }
          if (residual)
            for (long i = 0; i < m; i++)
              residual[b + i] = src[b + i] - (float)q[i] * scale;
        }
        p += m;
      }
      return (size_t)(p - dst);
    }
  }
  std::memcpy(dst, src, (size_t)n * 4);
  return (size_t)n * 4;
}

// Decompress n elements of a wire chunk into f32. `accumulate` adds into
// dst (reduce-scatter phase, f32 accumulation per the compression
// contract); otherwise overwrites (allgather phase).
void wire_decompress(const char* src, long n, int wire, float* dst,
                     bool accumulate, float* scratch) {
  CompressTimer t;
  switch (wire) {
    case WIRE_BF16: {
      const uint16_t* s = (const uint16_t*)src;
      if (accumulate)
        for (long i = 0; i < n; i++) dst[i] += bf16_to_f32(s[i]);
      else
        bf16_to_f32_block(s, dst, n);
      return;
    }
    case WIRE_F16: {
      const uint16_t* s = (const uint16_t*)src;
      if (accumulate) {
        // F16C-widen into scratch, then a vectorizable f32 add.
        for (long off = 0; off < n; off += kQuantBlock) {
          long m = n - off < kQuantBlock ? n - off : kQuantBlock;
          f16_to_f32_block(s + off, scratch, m);
          for (long i = 0; i < m; i++) dst[off + i] += scratch[i];
        }
      } else {
        f16_to_f32_block(s, dst, n);
      }
      return;
    }
    case WIRE_I8: {
      const char* p = src;
      for (long b = 0; b < n; b += kQuantBlock) {
        long m = n - b < kQuantBlock ? n - b : kQuantBlock;
        float scale;
        std::memcpy(&scale, p, 4);
        p += 4;
        const int8_t* q = (const int8_t*)p;
        if (accumulate)
          for (long i = 0; i < m; i++) dst[b + i] += (float)q[i] * scale;
        else
          for (long i = 0; i < m; i++) dst[b + i] = (float)q[i] * scale;
        p += m;
      }
      return;
    }
  }
  if (accumulate) {
    const float* s = (const float*)src;
    for (long i = 0; i < n; i++) dst[i] += s[i];
  } else {
    std::memcpy(dst, src, (size_t)n * 4);
  }
}

#pragma GCC pop_options

// One cache-friendly block of converted operands per iteration: big enough
// to amortize loop overhead, small enough that 3 x 512 floats stay in L1.
// (bf16 stays on its fused single-pass loop — see accumulate DT_BF16 —
// so only f16 takes the blocked form.)
constexpr long kHalfBlock = 512;

void accumulate_f16(uint16_t* d, const uint16_t* s, long count) {
  float a[kHalfBlock], b[kHalfBlock];
  for (long off = 0; off < count; off += kHalfBlock) {
    long n = count - off < kHalfBlock ? count - off : kHalfBlock;
    f16_to_f32_block(d + off, a, n);
    f16_to_f32_block(s + off, b, n);
    for (long i = 0; i < n; i++) a[i] += b[i];
    f32_to_f16_block(a, d + off, n);
  }
}

void scale_f16(uint16_t* d, long count, double factor) {
  // Multiply in double like every other dtype's scale path (and like the
  // pre-vectorization loop): one rounding convention across half types.
  float a[kHalfBlock];
  for (long off = 0; off < count; off += kHalfBlock) {
    long n = count - off < kHalfBlock ? count - off : kHalfBlock;
    f16_to_f32_block(d + off, a, n);
    for (long i = 0; i < n; i++) a[i] = (float)(a[i] * factor);
    f32_to_f16_block(a, d + off, n);
  }
}

void accumulate(void* dst, const void* src, long count, int dt) {
  switch (dt) {
    case DT_F32: {
      float* d = (float*)dst;
      const float* s = (const float*)src;
      for (long i = 0; i < count; i++) d[i] += s[i];
      break;
    }
    case DT_F64: {
      double* d = (double*)dst;
      const double* s = (const double*)src;
      for (long i = 0; i < count; i++) d[i] += s[i];
      break;
    }
    case DT_I32: {
      int32_t* d = (int32_t*)dst;
      const int32_t* s = (const int32_t*)src;
      for (long i = 0; i < count; i++) d[i] += s[i];
      break;
    }
    case DT_I64: {
      int64_t* d = (int64_t*)dst;
      const int64_t* s = (const int64_t*)src;
      for (long i = 0; i < count; i++) d[i] += s[i];
      break;
    }
    case DT_U8: {
      uint8_t* d = (uint8_t*)dst;
      const uint8_t* s = (const uint8_t*)src;
      for (long i = 0; i < count; i++) d[i] = (uint8_t)(d[i] + s[i]);
      break;
    }
    case DT_I8: {
      int8_t* d = (int8_t*)dst;
      const int8_t* s = (const int8_t*)src;
      for (long i = 0; i < count; i++) d[i] = (int8_t)(d[i] + s[i]);
      break;
    }
    case DT_I16: {
      int16_t* d = (int16_t*)dst;
      const int16_t* s = (const int16_t*)src;
      for (long i = 0; i < count; i++) d[i] = (int16_t)(d[i] + s[i]);
      break;
    }
    case DT_U16: {
      uint16_t* d = (uint16_t*)dst;
      const uint16_t* s = (const uint16_t*)src;
      for (long i = 0; i < count; i++) d[i] = (uint16_t)(d[i] + s[i]);
      break;
    }
    case DT_BOOL: {
      uint8_t* d = (uint8_t*)dst;
      const uint8_t* s = (const uint8_t*)src;
      for (long i = 0; i < count; i++) d[i] = (uint8_t)(d[i] || s[i]);
      break;
    }
    case DT_F16:
      accumulate_f16((uint16_t*)dst, (const uint16_t*)src, count);
      break;
    case DT_BF16: {
      // Single fused pass, not the blocked form: the branchless widen/
      // add/RNE-narrow loop autovectorizes as-is and measured ~3% FASTER
      // than block-converting through scratch (4.0 vs 3.9 Gelem/s).
      uint16_t* d = (uint16_t*)dst;
      const uint16_t* s = (const uint16_t*)src;
      for (long i = 0; i < count; i++)
        d[i] = f32_to_bf16(bf16_to_f32(d[i]) + bf16_to_f32(s[i]));
      break;
    }
  }
}

void scale(void* buf, long count, int dt, double factor) {
  switch (dt) {
    case DT_F32: {
      float* d = (float*)buf;
      for (long i = 0; i < count; i++) d[i] = (float)(d[i] * factor);
      break;
    }
    case DT_F64: {
      double* d = (double*)buf;
      for (long i = 0; i < count; i++) d[i] *= factor;
      break;
    }
    case DT_F16: {
      scale_f16((uint16_t*)buf, count, factor);
      break;
    }
    case DT_BF16: {
      uint16_t* d = (uint16_t*)buf;  // fused pass (see accumulate DT_BF16)
      for (long i = 0; i < count; i++)
        d[i] = f32_to_bf16((float)(bf16_to_f32(d[i]) * factor));
      break;
    }
    default:
      break;  // integer average is not defined; caller avoids it
  }
}

// --- socket helpers --------------------------------------------------------

bool wait_fd(int fd, short events) {
  struct pollfd pfd{fd, events, 0};
  int rc = poll(&pfd, 1, 60000);
  if (rc <= 0) {
    set_error(rc == 0 ? "socket wait timed out (60s)"
                      : std::string("poll: ") + strerror(errno));
    return false;
  }
  return true;
}

// Work on both blocking (handshake) and non-blocking (data phase) fds.
// Last time any ring in this process moved bytes (monotonic seconds).
// shm.cc's barrier reads this — and, crucially, a cross-PROCESS sink in
// the shared segment (set via hvd_ring_set_progress_sink) — to turn its
// timeout into an IDLE timeout: local ranks waiting at a barrier while
// their group leader's cross-node phase moves a large payload observe the
// leader's progress through the shared word and must not be killed.
std::atomic<double> g_last_progress{0.0};
std::atomic<std::atomic<double>*> g_progress_sink{nullptr};

double prog_mono_s() {
  return std::chrono::duration_cast<std::chrono::duration<double>>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

void mark_progress() {
  double now = prog_mono_s();
  g_last_progress.store(now, std::memory_order_relaxed);
  auto* sink = g_progress_sink.load(std::memory_order_acquire);
  if (sink) sink->store(now, std::memory_order_relaxed);
}

// Token-bucket gate for the optional per-ring send-rate cap: how many of
// `want` bytes may go out now (0 = bucket dry; the caller retries after
// the built-in short sleep). ~10 ms burst so pacing is smooth without
// per-byte wakeups. Unlimited (the default) is a single branch.
size_t rate_allow(Ring& ring, size_t want) {
  if (ring.rate_Bps <= 0.0 || want == 0) return want;
  double now = prog_mono_s();
  if (ring.rate_t == 0.0) ring.rate_t = now;
  ring.rate_tokens += (now - ring.rate_t) * ring.rate_Bps;
  ring.rate_t = now;
  double cap = ring.rate_Bps * 0.01 + 65536.0;
  if (ring.rate_tokens > cap) ring.rate_tokens = cap;
  if (ring.rate_tokens < 1.0) {
    std::this_thread::sleep_for(std::chrono::microseconds(200));
    return 0;
  }
  return want < (size_t)ring.rate_tokens ? want
                                         : (size_t)ring.rate_tokens;
}

void rate_consume(Ring& ring, size_t n) {
  if (ring.rate_Bps > 0.0) ring.rate_tokens -= (double)n;
}

bool send_all(int fd, const void* buf, size_t n) {
  const char* p = (const char*)buf;
  while (n > 0) {
    ssize_t k = send(fd, p, n, MSG_NOSIGNAL);
    if (k < 0) {
      if (errno == EINTR) continue;
      if (errno == EAGAIN || errno == EWOULDBLOCK) {
        if (!wait_fd(fd, POLLOUT)) return false;
        continue;
      }
      set_error(std::string("send: ") + strerror(errno));
      return false;
    }
    p += k;
    n -= (size_t)k;
    mark_progress();
  }
  return true;
}

bool recv_all(int fd, void* buf, size_t n) {
  char* p = (char*)buf;
  while (n > 0) {
    ssize_t k = recv(fd, p, n, 0);
    if (k < 0) {
      if (errno == EINTR) continue;
      if (errno == EAGAIN || errno == EWOULDBLOCK) {
        if (!wait_fd(fd, POLLIN)) return false;
        continue;
      }
      set_error(std::string("recv: ") + strerror(errno));
      return false;
    }
    if (k == 0) {
      set_error("recv: peer closed");
      return false;
    }
    p += k;
    n -= (size_t)k;
    mark_progress();
  }
  return true;
}

// Segmented pipelining (round-3 verdict item #3): during a reduce-scatter
// step, accumulate already-received chunks into the destination while the
// kernel keeps streaming later bytes into the socket buffers — single
// thread, but compute and wire genuinely overlap. The chunk size balances
// overlap granularity against per-chunk call overhead; 256 KiB default,
// runtime-settable per link class (g_chunk_bytes above).
struct ReduceSink {
  char* dst;        // segment being reduced into (same layout as rbuf)
  int dtype;
  size_t esz;
  size_t acc_done = 0;  // bytes of rbuf already accumulated
  // Snapshot once per step: a mid-step autotune push must not shear the
  // chunk grid this sink is draining on.
  size_t chunk = (size_t)chunk_bytes_now();

  void drain(const char* rbuf, size_t roff, bool final) {
    size_t ready = final ? roff : (roff / chunk) * chunk;
    // Chunk boundaries stay element-aligned: the setter keeps chunk a
    // multiple of every dtype size (1/2/4/8).
    if (ready <= acc_done) return;
    accumulate(dst + acc_done, rbuf + acc_done,
               (long)((ready - acc_done) / esz), dtype);
    acc_done = ready;
  }
};

// Full-duplex exchange: send `sn` bytes right while receiving `rn` bytes from
// left. Poll-driven so large segments can't deadlock on filled socket
// buffers (both neighbors send simultaneously each ring step). When `sink`
// is given, completed receive chunks are reduced in while the rest streams.
bool exchange(Ring& ring, const void* sbuf, size_t sn, void* rbuf, size_t rn,
              ReduceSink* sink = nullptr) {
  size_t soff = 0, roff = 0;
  while (soff < sn || roff < rn) {
    struct pollfd fds[2];
    int nf = 0;
    int si = -1, ri = -1;
    if (soff < sn) {
      fds[nf].fd = ring.right_fd;
      fds[nf].events = POLLOUT;
      si = nf++;
    }
    if (roff < rn) {
      fds[nf].fd = ring.left_fd;
      fds[nf].events = POLLIN;
      ri = nf++;
    }
    int rc = poll(fds, nf, 60000);
    if (rc < 0) {
      if (errno == EINTR) continue;
      set_error(std::string("poll: ") + strerror(errno));
      return false;
    }
    if (rc == 0) {
      set_error("ring exchange timed out (60s)");
      return false;
    }
    if (si >= 0 && (fds[si].revents & (POLLOUT | POLLERR | POLLHUP))) {
      size_t allowed = rate_allow(ring, sn - soff);
      ssize_t k = allowed == 0
                      ? 0
                      : send(ring.right_fd, (const char*)sbuf + soff, allowed,
                             MSG_NOSIGNAL);
      if (k < 0 && errno != EAGAIN && errno != EWOULDBLOCK && errno != EINTR) {
        set_error(std::string("send: ") + strerror(errno));
        return false;
      }
      if (k > 0) {
        soff += (size_t)k;
        rate_consume(ring, (size_t)k);
        mark_progress();
      }
    }
    if (ri >= 0 && (fds[ri].revents & (POLLIN | POLLERR | POLLHUP))) {
      ssize_t k = recv(ring.left_fd, (char*)rbuf + roff, rn - roff, 0);
      if (k < 0 && errno != EAGAIN && errno != EWOULDBLOCK && errno != EINTR) {
        set_error(std::string("recv: ") + strerror(errno));
        return false;
      }
      if (k == 0) {
        set_error("recv: peer closed");
        return false;
      }
      if (k > 0) {
        roff += (size_t)k;
        mark_progress();
        if (sink) sink->drain((const char*)rbuf, roff, false);
      }
    }
  }
  if (sink) sink->drain((const char*)rbuf, roff, true);
  return true;
}

// --- compress-ahead pipeline (round 10) -------------------------------------

// Pipelining granularity in f32 elements: the transfer chunk, rounded up
// to whole int8 quant blocks so scale headers never straddle a chunk.
long wire_chunk_elems(int wire) {
  long e = chunk_bytes_now() / 4;
  if (wire == WIRE_I8) e = ((e + kQuantBlock - 1) / kQuantBlock) * kQuantBlock;
  if (e < kQuantBlock) e = kQuantBlock;
  return e;
}

// Sender side: converts the outgoing f32 segment into wire format one
// chunk AHEAD of the send offset, so the cast of chunk k+1 runs while
// chunk k's bytes drain from the socket buffer — the send-side twin of
// the round-3 ReduceSink.
struct CompressCursor {
  const float* src;
  long n;
  int wire;
  char* wbuf;         // wire_nbytes(n, wire) capacity
  float* residual;    // nullable; int8 error-feedback capture
  long chunk_elems;
  size_t total;       // wire bytes when fully compressed
  long elems_done = 0;
  size_t ready = 0;   // wire bytes materialized so far

  CompressCursor(const float* src, long n, int wire, char* wbuf,
                 float* residual)
      : src(src), n(n), wire(wire), wbuf(wbuf), residual(residual),
        chunk_elems(wire_chunk_elems(wire)), total(wire_nbytes(n, wire)) {}

  bool done() const { return elems_done >= n; }

  void compress_next() {
    long m = n - elems_done < chunk_elems ? n - elems_done : chunk_elems;
    ready += wire_compress(src + elems_done, m, wire, wbuf + ready,
                           residual ? residual + elems_done : nullptr);
    elems_done += m;
  }

  // Invariant after this call: ready > soff unless fully compressed — the
  // exchange loop always has bytes to hand to send().
  void ensure_ahead(size_t soff) {
    size_t one = wire_nbytes(chunk_elems, wire);
    while (!done() && ready < soff + 2 * one) compress_next();
  }
};

// Receiver side: widens completed wire chunks into the f32 destination
// (accumulating during reduce-scatter, overwriting during allgather)
// while later bytes still stream.
struct WireSink {
  float* dst;
  long n;
  int wire;
  const char* wrecv;
  bool acc;          // accumulate (phase 1) vs overwrite (phase 2)
  float* scratch;    // kQuantBlock floats (f16 widen staging)
  long chunk_elems;
  long elems_done = 0;
  size_t consumed = 0;  // wire bytes drained

  void drain(size_t roff, bool final) {
    (void)final;  // the last recv completes the last chunk exactly
    while (elems_done < n) {
      long m = n - elems_done < chunk_elems ? n - elems_done : chunk_elems;
      size_t need = wire_nbytes(m, wire);  // chunk starts block-aligned
      if (roff < consumed + need) return;
      wire_decompress(wrecv + consumed, m, wire, dst + elems_done, acc,
                      scratch);
      consumed += need;
      elems_done += m;
    }
  }
};

// Full-duplex wire exchange: like exchange(), but the send side either
// streams from a CompressCursor (tx != nullptr; compresses ahead of the
// wire) or relays precompressed bytes verbatim (sbuf/sn), and the receive
// side drains completed wire chunks through a WireSink.
bool exchange_w(Ring& ring, CompressCursor* tx, const char* sbuf, size_t sn,
                char* rbuf, size_t rn, WireSink* sink) {
  size_t soff = 0, roff = 0;
  size_t slimit = tx ? tx->total : sn;
  while (soff < slimit || roff < rn) {
    if (tx) tx->ensure_ahead(soff);
    const char* sp = tx ? tx->wbuf : sbuf;
    struct pollfd fds[2];
    int nf = 0;
    int si = -1, ri = -1;
    if (soff < slimit) {
      fds[nf].fd = ring.right_fd;
      fds[nf].events = POLLOUT;
      si = nf++;
    }
    if (roff < rn) {
      fds[nf].fd = ring.left_fd;
      fds[nf].events = POLLIN;
      ri = nf++;
    }
    int rc = poll(fds, nf, 60000);
    if (rc < 0) {
      if (errno == EINTR) continue;
      set_error(std::string("poll: ") + strerror(errno));
      return false;
    }
    if (rc == 0) {
      set_error("ring exchange timed out (60s)");
      return false;
    }
    if (si >= 0 && (fds[si].revents & (POLLOUT | POLLERR | POLLHUP))) {
      size_t avail = tx ? tx->ready : sn;
      size_t allowed = rate_allow(ring, avail - soff);
      ssize_t k = allowed == 0
                      ? 0
                      : send(ring.right_fd, sp + soff, allowed, MSG_NOSIGNAL);
      if (k < 0 && errno != EAGAIN && errno != EWOULDBLOCK && errno != EINTR) {
        set_error(std::string("send: ") + strerror(errno));
        return false;
      }
      if (k > 0) {
        soff += (size_t)k;
        rate_consume(ring, (size_t)k);
        mark_progress();
      }
    }
    if (ri >= 0 && (fds[ri].revents & (POLLIN | POLLERR | POLLHUP))) {
      ssize_t k = recv(ring.left_fd, rbuf + roff, rn - roff, 0);
      if (k < 0 && errno != EAGAIN && errno != EWOULDBLOCK && errno != EINTR) {
        set_error(std::string("recv: ") + strerror(errno));
        return false;
      }
      if (k == 0) {
        set_error("recv: peer closed");
        return false;
      }
      if (k > 0) {
        roff += (size_t)k;
        mark_progress();
        if (sink) sink->drain(roff, false);
      }
    }
  }
  if (sink) sink->drain(roff, true);
  return true;
}

bool parse_addr(const std::string& addr, std::string* host, int* port) {
  size_t colon = addr.rfind(':');
  if (colon == std::string::npos) return false;
  *host = addr.substr(0, colon);
  *port = atoi(addr.c_str() + colon + 1);
  return true;
}

void auth_token(const Ring& ring, int sender_rank, uint8_t out[32]) {
  char msg[64];
  int n = snprintf(msg, sizeof(msg), "hvd-ring-hello:%d", sender_rank);
  hvd::hmac_sha256(ring.secret.data(), ring.secret.size(), (const uint8_t*)msg,
                   (size_t)n, out);
}

void ring_close(Ring& ring) {
  for (int* fd : {&ring.left_fd, &ring.right_fd, &ring.listen_fd}) {
    if (*fd >= 0) {
      close(*fd);
      *fd = -1;
    }
  }
  ring.rank = -1;
  ring.size = 0;
}

// addrs: comma-separated "host:port" per rank, in rank order.
int ring_init(Ring& ring, int rank, int size, const char* addrs_cstr,
              const uint8_t* secret, int secret_len) {
  ring.rank = rank;
  ring.size = size;
  ring.secret.assign(secret, secret + secret_len);
  if (size == 1) return 0;

  std::vector<std::string> addrs;
  std::string cur, all(addrs_cstr);
  for (char c : all) {
    if (c == ',') {
      addrs.push_back(cur);
      cur.clear();
    } else {
      cur += c;
    }
  }
  if (!cur.empty()) addrs.push_back(cur);
  if ((int)addrs.size() != size) {
    set_error("ring_init: addrs count != size");
    return -1;
  }

  std::string my_host;
  int my_port = 0;
  if (!parse_addr(addrs[rank], &my_host, &my_port)) {
    set_error("ring_init: bad own address " + addrs[rank]);
    return -1;
  }

  // Listen for the left neighbor.
  ring.listen_fd = socket(AF_INET, SOCK_STREAM, 0);
  int one = 1;
  setsockopt(ring.listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  // Request large buffers BEFORE listen/connect: the TCP window-scale
  // factor is fixed at the handshake, and accepted sockets inherit the
  // listener's options. The kernel clamps to net.core.{r,w}mem_max —
  // raise those sysctls for the full 8 MiB on high-BDP links.
  int bufsz = 8 << 20;
  setsockopt(ring.listen_fd, SOL_SOCKET, SO_SNDBUF, &bufsz, sizeof(bufsz));
  setsockopt(ring.listen_fd, SOL_SOCKET, SO_RCVBUF, &bufsz, sizeof(bufsz));
  struct sockaddr_in sa;
  std::memset(&sa, 0, sizeof(sa));
  sa.sin_family = AF_INET;
  sa.sin_addr.s_addr = INADDR_ANY;
  sa.sin_port = htons((uint16_t)my_port);
  if (bind(ring.listen_fd, (struct sockaddr*)&sa, sizeof(sa)) < 0) {
    set_error(std::string("bind ") + addrs[rank] + ": " + strerror(errno));
    return -1;
  }
  if (listen(ring.listen_fd, 4) < 0) {
    set_error(std::string("listen: ") + strerror(errno));
    return -1;
  }

  // Connect to the right neighbor, retrying while it comes up (the Python
  // WorkerClient does the same, controller/service.py).
  int right = (rank + 1) % size;
  std::string rhost;
  int rport;
  if (!parse_addr(addrs[right], &rhost, &rport)) {
    set_error("ring_init: bad right address " + addrs[right]);
    return -1;
  }
  struct addrinfo hints, *res = nullptr;
  std::memset(&hints, 0, sizeof(hints));
  hints.ai_family = AF_INET;
  hints.ai_socktype = SOCK_STREAM;
  char portstr[16];
  snprintf(portstr, sizeof(portstr), "%d", rport);
  if (getaddrinfo(rhost.c_str(), portstr, &hints, &res) != 0 || !res) {
    set_error("getaddrinfo failed for " + rhost);
    return -1;
  }
  // Rendezvous window (reference horovodrun --start-timeout), exported by
  // the launcher as HOROVOD_START_TIMEOUT.
  int start_timeout_s = 120;
  if (const char* st = getenv("HOROVOD_START_TIMEOUT")) {
    int v = atoi(st);
    if (v > 0) start_timeout_s = v;
  }
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::seconds(start_timeout_s);
  while (true) {
    ring.right_fd = socket(AF_INET, SOCK_STREAM, 0);
    setsockopt(ring.right_fd, SOL_SOCKET, SO_SNDBUF, &bufsz, sizeof(bufsz));
    setsockopt(ring.right_fd, SOL_SOCKET, SO_RCVBUF, &bufsz, sizeof(bufsz));
    if (connect(ring.right_fd, res->ai_addr, res->ai_addrlen) == 0) break;
    close(ring.right_fd);
    ring.right_fd = -1;
    if (std::chrono::steady_clock::now() > deadline) {
      freeaddrinfo(res);
      set_error("connect to right neighbor timed out: " + addrs[right]);
      return -1;
    }
    std::this_thread::sleep_for(std::chrono::milliseconds(50));
  }
  freeaddrinfo(res);
  setsockopt(ring.right_fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));

  // Authenticate to the right neighbor.
  uint8_t token[36];
  uint32_t rank_be = htonl((uint32_t)rank);
  std::memcpy(token, &rank_be, 4);
  auth_token(ring, rank, token + 4);
  if (!send_all(ring.right_fd, token, sizeof(token))) return -1;

  // Accept + verify the left neighbor.
  int left = (rank - 1 + size) % size;
  ring.left_fd = accept(ring.listen_fd, nullptr, nullptr);
  if (ring.left_fd < 0) {
    set_error(std::string("accept: ") + strerror(errno));
    return -1;
  }
  setsockopt(ring.left_fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  uint8_t peer[36];
  if (!recv_all(ring.left_fd, peer, sizeof(peer))) return -1;
  uint32_t peer_rank_be;
  std::memcpy(&peer_rank_be, peer, 4);
  int peer_rank = (int)ntohl(peer_rank_be);
  uint8_t expect[32];
  auth_token(ring, peer_rank, expect);
  if (peer_rank != left || std::memcmp(peer + 4, expect, 32) != 0) {
    set_error("left-neighbor authentication failed");
    return -1;
  }

  // Non-blocking from here on: exchange() interleaves duplex progress via
  // poll, and a blocking send of a large segment against a neighbor doing
  // the same would deadlock once both socket buffers fill.
  for (int fd : {ring.left_fd, ring.right_fd}) {
    int flags = fcntl(fd, F_GETFL, 0);
    fcntl(fd, F_SETFL, flags | O_NONBLOCK);
  }
  return 0;
}

// Compressed-wire ring allreduce for f32 buffers: the same
// reduce-scatter + allgather schedule as the uncompressed path, but every
// hop's bytes travel as bf16/fp16/int8 while all arithmetic stays f32.
int ring_allreduce_wire_f32(Ring& ring, float* buf, long count, int average,
                            int wire, float* residual) {
  long nseg = ring.size;
  long base_len = count / nseg, rem = count % nseg;
  auto seg_off = [&](long s) { return s * base_len + (s < rem ? s : rem); };
  auto seg_len = [&](long s) { return base_len + (s < rem ? 1 : 0); };
  long max_len = base_len + (rem ? 1 : 0);
  size_t max_wire = wire_nbytes(max_len, wire);
  ring.wtx.resize(max_wire);
  ring.wrx.resize(max_wire);
  ring.wfwd.resize(max_wire);
  long ce = wire_chunk_elems(wire);
  // Widen staging is only ever used in kQuantBlock strides (see
  // wire_decompress's f16 path) — never a full transfer chunk.
  ring.wscratch.resize((size_t)kQuantBlock);

  // Phase 1: reduce-scatter. Outgoing chunks are cast/quantized AT SEND
  // TIME, one chunk ahead of the wire (CompressCursor); received wire
  // chunks widen and accumulate in f32 as they complete (WireSink). For
  // int8, every quantization error this rank introduces lands in
  // `residual` at the sent segment's offsets — phase 1 sends every
  // segment except our own, the phase-2 owner quantization covers that
  // one, so each element's error is written exactly once per call.
  for (int step = 0; step < ring.size - 1; step++) {
    long s_send = (ring.rank - step + ring.size) % ring.size;
    long s_recv = (ring.rank - step - 1 + ring.size) % ring.size;
    CompressCursor tx(buf + seg_off(s_send), seg_len(s_send), wire,
                      ring.wtx.data(),
                      residual ? residual + seg_off(s_send) : nullptr);
    WireSink sink{buf + seg_off(s_recv), seg_len(s_recv), wire,
                  ring.wrx.data(), /*acc=*/true, ring.wscratch.data(), ce};
    if (!exchange_w(ring, &tx, nullptr, 0, ring.wrx.data(),
                    wire_nbytes(seg_len(s_recv), wire), &sink))
      return -1;
    g_wire_tx_bytes[ring.link][wire] += (long long)tx.total;
    g_wire_logical_bytes[ring.link][wire] += 4ll * seg_len(s_send);
  }

  // Our own (fully reduced) segment: quantize it ONCE and keep the
  // dequantized value locally, so the bytes we ship in the allgather are
  // exactly what we hold — every rank ends bit-identical.
  long own = (ring.rank + 1) % ring.size;
  wire_compress(buf + seg_off(own), seg_len(own), wire, ring.wfwd.data(),
                residual ? residual + seg_off(own) : nullptr);
  wire_decompress(ring.wfwd.data(), seg_len(own), wire, buf + seg_off(own),
                  /*accumulate=*/false, ring.wscratch.data());

  // Phase 2: allgather of reduced segments, forwarding the received WIRE
  // bytes verbatim on the next hop. (bf16/f16 recompression would be
  // lossless — half -> f32 -> half round-trips exactly — but an int8
  // block whose max |q| < 127 would re-derive a different scale, so
  // relaying the exact bytes is both cheaper and the only correct
  // choice.) Received chunks decompress into the destination while later
  // bytes still stream.
  for (int step = 0; step < ring.size - 1; step++) {
    long s_send = (ring.rank + 1 - step + ring.size) % ring.size;
    long s_recv = (ring.rank - step + ring.size) % ring.size;
    size_t sn = wire_nbytes(seg_len(s_send), wire);
    size_t rn = wire_nbytes(seg_len(s_recv), wire);
    WireSink sink{buf + seg_off(s_recv), seg_len(s_recv), wire,
                  ring.wrx.data(), /*acc=*/false, ring.wscratch.data(), ce};
    if (!exchange_w(ring, nullptr, ring.wfwd.data(), sn, ring.wrx.data(), rn,
                    &sink))
      return -1;
    g_wire_tx_bytes[ring.link][wire] += (long long)sn;
    g_wire_logical_bytes[ring.link][wire] += 4ll * seg_len(s_send);
    std::swap(ring.wfwd, ring.wrx);  // this step's recv = next step's send
  }
  if (average) scale(buf, count, DT_F32, 1.0 / ring.size);
  return 0;
}

// In-place ring allreduce (sum; average divides afterwards for float
// types). ``wire_dtype`` (WIRE_*) compresses f32 payloads on the wire;
// WIRE_NONE (and every non-f32 dtype) keeps the pre-round-10 byte stream
// exactly. ``residual`` is the int8 error-feedback out-buffer (f32,
// ``count`` elements; see ring_allreduce_wire_f32).
int ring_allreduce(Ring& ring, void* buf, long count, int dtype, int average,
                   int wire_dtype = WIRE_NONE, void* residual = nullptr) {
  // Residual contract: when a caller hands an error-feedback buffer but
  // this call performs NO quantization (size 1, non-int8 wire, non-f32
  // dtype), the buffer is zeroed — a stale residual must never be carried
  // into the next round as if it were this round's error.
  bool quantizing = dtype == DT_F32 && wire_dtype == WIRE_I8 && ring.size > 1;
  if (residual && dtype == DT_F32 && !quantizing)
    std::memset(residual, 0, (size_t)count * 4);
  if (ring.size <= 1) return 0;
  size_t esz = dtype_size(dtype);
  if (esz == 0) {
    set_error("unsupported dtype");
    return -1;
  }
  if (dtype == DT_F32 && wire_dtype != WIRE_NONE &&
      wire_dtype >= 0 && wire_dtype <= WIRE_I8) {
    return ring_allreduce_wire_f32(
        ring, (float*)buf, count, average, wire_dtype,
        quantizing ? (float*)residual : nullptr);
  }
  char* base = (char*)buf;
  long nseg = ring.size;
  long base_len = count / nseg, rem = count % nseg;
  auto seg_off = [&](long s) { return s * base_len + (s < rem ? s : rem); };
  auto seg_len = [&](long s) { return base_len + (s < rem ? 1 : 0); };

  std::vector<char> tmp((size_t)(base_len + 1) * esz);

  // Phase 1: reduce-scatter. After size-1 steps, rank r owns the fully
  // reduced segment (r+1)%size. The ReduceSink accumulates received
  // chunks while later bytes still stream (pipelined, see exchange());
  // HOROVOD_RING_PIPELINE=0 restores the unpipelined exchange-then-reduce
  // sequence (measurement escape hatch, allreduce_bandwidth_r4.json).
  static const bool pipelined = [] {
    const char* e = getenv("HOROVOD_RING_PIPELINE");
    return !(e && e[0] == '0');
  }();
  for (int step = 0; step < ring.size - 1; step++) {
    long s_send = (ring.rank - step + ring.size) % ring.size;
    long s_recv = (ring.rank - step - 1 + ring.size) % ring.size;
    ReduceSink sink{base + seg_off(s_recv) * esz, dtype, esz};
    if (!exchange(ring, base + seg_off(s_send) * esz,
                  (size_t)seg_len(s_send) * esz, tmp.data(),
                  (size_t)seg_len(s_recv) * esz,
                  pipelined ? &sink : nullptr))
      return -1;
    g_wire_tx_bytes[ring.link][WIRE_NONE] +=
        (long long)seg_len(s_send) * (long long)esz;
    g_wire_logical_bytes[ring.link][WIRE_NONE] +=
        (long long)seg_len(s_send) * (long long)esz;
    if (!pipelined)
      accumulate(base + seg_off(s_recv) * esz, tmp.data(), seg_len(s_recv),
                 dtype);
  }
  // Phase 2: allgather of reduced segments.
  for (int step = 0; step < ring.size - 1; step++) {
    long s_send = (ring.rank + 1 - step + ring.size) % ring.size;
    long s_recv = (ring.rank - step + ring.size) % ring.size;
    if (!exchange(ring, base + seg_off(s_send) * esz,
                  (size_t)seg_len(s_send) * esz, base + seg_off(s_recv) * esz,
                  (size_t)seg_len(s_recv) * esz))
      return -1;
    g_wire_tx_bytes[ring.link][WIRE_NONE] +=
        (long long)seg_len(s_send) * (long long)esz;
    g_wire_logical_bytes[ring.link][WIRE_NONE] +=
        (long long)seg_len(s_send) * (long long)esz;
  }
  if (average) scale(buf, count, dtype, 1.0 / ring.size);
  return 0;
}

// Ring allgather with per-rank element counts (MPI_Allgatherv equivalent).
// out must hold sum(counts); own block is copied internally.
int ring_allgather(Ring& ring, const void* in, const long* counts, void* out,
                   int dtype) {
  size_t esz = dtype_size(dtype);
  if (esz == 0) {
    set_error("unsupported dtype");
    return -1;
  }
  std::vector<long> offs(ring.size + 1, 0);
  for (int r = 0; r < ring.size; r++) offs[r + 1] = offs[r] + counts[r];
  char* base = (char*)out;
  std::memcpy(base + offs[ring.rank] * esz, in,
              (size_t)counts[ring.rank] * esz);
  for (int step = 0; step < (ring.size > 1 ? ring.size - 1 : 0); step++) {
    long b_send = (ring.rank - step + ring.size) % ring.size;
    long b_recv = (ring.rank - step - 1 + ring.size) % ring.size;
    if (!exchange(ring, base + offs[b_send] * esz,
                  (size_t)counts[b_send] * esz, base + offs[b_recv] * esz,
                  (size_t)counts[b_recv] * esz))
      return -1;
  }
  return 0;
}

// Ring (pipeline) broadcast from root, in place.
int ring_broadcast(Ring& ring, void* buf, long count, int dtype, int root) {
  if (ring.size <= 1) return 0;
  size_t esz = dtype_size(dtype);
  if (esz == 0) {
    set_error("unsupported dtype");
    return -1;
  }
  size_t nbytes = (size_t)count * esz;
  int right = (ring.rank + 1) % ring.size;
  if (ring.rank == root) {
    return send_all(ring.right_fd, buf, nbytes) ? 0 : -1;
  }
  if (!recv_all(ring.left_fd, buf, nbytes)) return -1;
  if (right != root) {
    if (!send_all(ring.right_fd, buf, nbytes)) return -1;
  }
  return 0;
}

// The default (global) ring used by the legacy hvd_ring_* ABI — the native
// engine's single flat ring (engine.cc).
Ring g_ring;

}  // namespace

extern "C" {

const char* hvd_ring_last_error() { return g_error.c_str(); }

// --- legacy global-ring ABI (native engine path) ---------------------------

int hvd_ring_init(int rank, int size, const char* addrs_cstr,
                  const uint8_t* secret, int secret_len) {
  return ring_init(g_ring, rank, size, addrs_cstr, secret, secret_len);
}

int hvd_ring_allreduce(void* buf, long count, int dtype, int average) {
  return ring_allreduce(g_ring, buf, count, dtype, average);
}

// Wire-compressed variant (round 10): ``wire_dtype`` is a WireDType code
// (0 none, 1 bf16, 2 fp16, 3 int8); ``residual`` is the int8
// error-feedback out-buffer (f32 x count, nullable). The default-code
// path is byte-identical to hvd_ring_allreduce.
int hvd_ring_allreduce_wire(void* buf, long count, int dtype, int average,
                            int wire_dtype, void* residual) {
  return ring_allreduce(g_ring, buf, count, dtype, average, wire_dtype,
                        residual);
}

int hvd_ring_allgather(const void* in, const long* counts, void* out,
                       int dtype) {
  return ring_allgather(g_ring, in, counts, out, dtype);
}

int hvd_ring_broadcast(void* buf, long count, int dtype, int root) {
  return ring_broadcast(g_ring, buf, count, dtype, root);
}

// Raw neighbor I/O for the native engine's control token (engine.cc): the
// token and the fused ResponseList ride the same authenticated connections
// as the data phases, in strict alternation from the single engine thread.
int hvd_ring_send_right(const void* buf, long n) {
  return send_all(g_ring.right_fd, buf, (size_t)n) ? 0 : -1;
}

int hvd_ring_recv_left(void* buf, long n) {
  return recv_all(g_ring.left_fd, buf, (size_t)n) ? 0 : -1;
}

void hvd_ring_shutdown() { ring_close(g_ring); }

// --- handle-based ABI (Python controller; several rings per process) -------

void* hvd_ringh_create(int rank, int size, const char* addrs_cstr,
                       const uint8_t* secret, int secret_len) {
  Ring* ring = new Ring();
  if (ring_init(*ring, rank, size, addrs_cstr, secret, secret_len) != 0) {
    ring_close(*ring);
    delete ring;
    return nullptr;
  }
  return ring;
}

int hvd_ringh_allreduce(void* h, void* buf, long count, int dtype,
                        int average) {
  return ring_allreduce(*(Ring*)h, buf, count, dtype, average);
}

int hvd_ringh_allreduce_wire(void* h, void* buf, long count, int dtype,
                             int average, int wire_dtype, void* residual) {
  return ring_allreduce(*(Ring*)h, buf, count, dtype, average, wire_dtype,
                        residual);
}

int hvd_ringh_allgather(void* h, const void* in, const long* counts, void* out,
                        int dtype) {
  return ring_allgather(*(Ring*)h, in, counts, out, dtype);
}

int hvd_ringh_broadcast(void* h, void* buf, long count, int dtype, int root) {
  return ring_broadcast(*(Ring*)h, buf, count, dtype, root);
}

void hvd_ringh_destroy(void* h) {
  if (!h) return;
  ring_close(*(Ring*)h);
  delete (Ring*)h;
}

// --- dtype kernels shared with the /dev/shm local data plane (shm.cc) ------

void hvd_dtype_accumulate(void* dst, const void* src, long count, int dtype) {
  accumulate(dst, src, count, dtype);
}

// Scalar reference for the half-precision sum: the exact element-at-a-time
// loop the blocked/F16C path replaced. Kept as a test seam — parity tests
// assert the vector path is byte-identical, and the bandwidth artifact
// measures the speedup against it. Other dtypes fall through to the one
// shared implementation.
void hvd_dtype_accumulate_scalar(void* dst, const void* src, long count,
                                 int dtype) {
  if (dtype == DT_F16) {
    uint16_t* d = (uint16_t*)dst;
    const uint16_t* s = (const uint16_t*)src;
    for (long i = 0; i < count; i++)
      d[i] = f32_to_f16(f16_to_f32(d[i]) + f16_to_f32(s[i]));
    return;
  }
  if (dtype == DT_BF16) {
    uint16_t* d = (uint16_t*)dst;
    const uint16_t* s = (const uint16_t*)src;
    for (long i = 0; i < count; i++)
      d[i] = f32_to_bf16(bf16_to_f32(d[i]) + bf16_to_f32(s[i]));
    return;
  }
  accumulate(dst, src, count, dtype);
}

long hvd_dtype_size(int dtype) { return (long)dtype_size(dtype); }

void hvd_dtype_scale(void* buf, long count, int dtype, double factor) {
  scale(buf, count, dtype, factor);
}

// --- wire-compression config + stats (round 10) -----------------------------

// Transfer-chunk size for the reduce-while-receive sink and the
// compress-ahead cursor — per-rank pipelining granularity only (the int8
// wire format is anchored on fixed 4096-element quant blocks, so no
// cross-rank agreement is needed and the autotuner may retune this live).
// Rounded to a multiple of 8 so chunk boundaries stay element-aligned for
// every dtype; clamped to [16 KiB, 64 MiB].
void hvd_ring_set_chunk_bytes(long nbytes) {
  if (nbytes < 16 * 1024) nbytes = 16 * 1024;
  if (nbytes > 64l * 1024 * 1024) nbytes = 64l * 1024 * 1024;
  g_chunk_bytes.store(nbytes & ~7l, std::memory_order_relaxed);
}

long hvd_ring_get_chunk_bytes() { return chunk_bytes_now(); }

// Cumulative allreduce data-phase traffic by wire dtype (index =
// WireDType code 0..3), summed over link classes: actual bytes this rank
// handed to the kernel and the uncompressed-equivalent ("logical") bytes
// they carried, plus the total time spent in compress/decompress
// kernels. Python mirrors these into hvd_ring_wire_bytes_total{dtype,
// link} / hvd_ring_compress_seconds (per-link detail via
// hvd_ring_get_wire_stats_link).
void hvd_ring_get_wire_stats(long long* tx_bytes, long long* logical_bytes,
                             double* compress_s) {
  for (int i = 0; i < 4; i++) {
    long long tx = 0, logical = 0;
    for (int l = 0; l < kNumLinks; l++) {
      tx += g_wire_tx_bytes[l][i].load(std::memory_order_relaxed);
      logical += g_wire_logical_bytes[l][i].load(std::memory_order_relaxed);
    }
    tx_bytes[i] = tx;
    logical_bytes[i] = logical;
  }
  *compress_s = g_compress_ns.load(std::memory_order_relaxed) / 1e9;
}

// Per-link-class slice of the same counters (link = WireLink code 0..2:
// flat/local/cross). The two-level data plane accounts its local and
// cross hops separately, so the wire counters can PROVE "the cross hop
// carries int8 bytes while the local hop stays f32".
void hvd_ring_get_wire_stats_link(int link, long long* tx_bytes,
                                  long long* logical_bytes) {
  if (link < 0 || link >= kNumLinks) link = 0;
  for (int i = 0; i < 4; i++) {
    tx_bytes[i] = g_wire_tx_bytes[link][i].load(std::memory_order_relaxed);
    logical_bytes[i] =
        g_wire_logical_bytes[link][i].load(std::memory_order_relaxed);
  }
}

// Tag a handle-based ring with its link class (WireLink code) so its
// traffic lands in the right counter row. The flat default is 0; the
// engine/controller tag their hierarchical local/cross rings at init.
void hvd_ringh_set_link(void* h, int link) {
  ((Ring*)h)->link = (link >= 0 && link < kNumLinks) ? link : 0;
}

// Cap a handle-based ring's send rate (bytes/s; 0 restores unlimited).
// Emulation/measurement knob: the bandwidth probe uses it to model a
// slow cross-node link on a loopback test box (docs/wire-compression.md);
// production jobs leave it unset.
void hvd_ringh_set_rate(void* h, double bytes_per_s) {
  Ring* ring = (Ring*)h;
  ring->rate_Bps = bytes_per_s > 0.0 ? bytes_per_s : 0.0;
  ring->rate_tokens = 0.0;
  ring->rate_t = 0.0;
}

// Monotonic timestamp of the last byte any ring in this process moved
// (0.0 before any traffic). shm.cc's barrier uses it as a liveness signal
// so its timeout is idle-based, not a cap on a progressing cross phase.
double hvd_ring_progress_mono_s() {
  return g_last_progress.load(std::memory_order_relaxed);
}

// Register a shared-memory word that also receives progress timestamps —
// making ring liveness visible ACROSS the local group's processes. Pass
// nullptr to unregister (must happen before the segment unmaps).
void hvd_ring_set_progress_sink(void* addr) {
  g_progress_sink.store((std::atomic<double>*)addr,
                        std::memory_order_release);
}

}  // extern "C"
