// Bit-indexed LRU response cache for the native engine.
//
// Reference: horovod/common/response_cache.{h,cc} — an LRU of Responses keyed
// by tensor name + parameters (op/dtype/shape/root), bit-indexed so per-cycle
// coordination is a bitvector AND across ranks (response_cache.cc:303)
// instead of the full negotiation. A hit whose parameters changed
// invalidates the entry (propagated with an OR pass).
//
// Coherence contract (same as the Python twin, horovod_tpu/common/
// response_cache.py): cache state must evolve identically on every rank so
// bit positions stay coherent. lookup() therefore does NOT touch LRU order
// (local queue order may differ per rank); touch() and put() are called only
// at points ordered identically across ranks (bypass execution walks agreed
// bits in ascending order; puts happen in ResponseList order).

#ifndef HVD_TPU_RESPONSE_CACHE_H_
#define HVD_TPU_RESPONSE_CACHE_H_

#include <cstdint>
#include <list>
#include <map>
#include <string>
#include <vector>

#include "message.h"

namespace hvd {

class ResponseCache {
 public:
  explicit ResponseCache(int capacity) : capacity_(capacity) {
    for (int i = 0; i < capacity; i++) free_bits_.push_back(i);
  }

  // Bit position on a parameter-exact hit; -1 on miss.
  int lookup(const Request& req) const {
    auto it = entries_.find(req.tensor_name);
    if (it == entries_.end()) return -1;
    if (!it->second.params.same_params(req)) return -1;
    return it->second.bit;
  }

  // Bit of a same-name entry whose params no longer match; -1 if none.
  int stale_bit(const Request& req) const {
    auto it = entries_.find(req.tensor_name);
    if (it == entries_.end()) return -1;
    return it->second.params.same_params(req) ? -1 : it->second.bit;
  }

  // LRU-touch (bypass execution; deterministic order across ranks).
  void touch(int bit) {
    auto it = by_bit_.find(bit);
    if (it == by_bit_.end()) return;
    auto& e = entries_[it->second];
    lru_.erase(e.lru_pos);
    lru_.push_back(it->second);
    e.lru_pos = std::prev(lru_.end());
  }

  bool get(int bit, std::string* name, Response* response) const {
    auto it = by_bit_.find(bit);
    if (it == by_bit_.end()) return false;
    *name = it->second;
    *response = entries_.at(it->second).response;
    return true;
  }

  void put(const Request& req, const Response& response) {
    if (capacity_ <= 0) return;
    auto it = entries_.find(req.tensor_name);
    if (it != entries_.end()) {
      it->second.params = req;
      it->second.response = response;
      lru_.erase(it->second.lru_pos);
      lru_.push_back(req.tensor_name);
      it->second.lru_pos = std::prev(lru_.end());
      return;
    }
    if (free_bits_.empty()) {
      // Evict LRU (reference response_cache.cc put path).
      const std::string& old_name = lru_.front();
      int old_bit = entries_[old_name].bit;
      by_bit_.erase(old_bit);
      entries_.erase(old_name);
      lru_.pop_front();
      free_bits_.push_back(old_bit);
    }
    int bit = free_bits_.front();
    free_bits_.erase(free_bits_.begin());
    Entry e;
    e.bit = bit;
    e.params = req;
    e.response = response;
    lru_.push_back(req.tensor_name);
    e.lru_pos = std::prev(lru_.end());
    entries_[req.tensor_name] = e;
    by_bit_[bit] = req.tensor_name;
  }

  void evict_bit(int bit) {
    auto it = by_bit_.find(bit);
    if (it == by_bit_.end()) return;
    auto& e = entries_[it->second];
    lru_.erase(e.lru_pos);
    entries_.erase(it->second);
    by_bit_.erase(it);
    free_bits_.push_back(bit);
  }

  size_t size() const { return entries_.size(); }
  int capacity() const { return capacity_; }

 private:
  struct Entry {
    int bit = -1;
    Request params;
    Response response;
    std::list<std::string>::iterator lru_pos;
  };

  int capacity_;
  std::map<std::string, Entry> entries_;
  std::map<int, std::string> by_bit_;
  std::vector<int> free_bits_;
  std::list<std::string> lru_;  // front = least recently used
};

// Fixed-width bitmask helpers (the wire carries capacity/64 words; the
// Python controller uses arbitrary-precision ints for the same masks).
class BitMask {
 public:
  explicit BitMask(int nbits)
      : words_((size_t)((nbits + 63) / 64), 0) {}
  explicit BitMask(std::vector<uint64_t> words) : words_(std::move(words)) {}

  void set(int bit) { words_[bit / 64] |= (uint64_t)1 << (bit % 64); }
  bool test(int bit) const {
    size_t w = (size_t)(bit / 64);
    if (w >= words_.size()) return false;
    return (words_[w] >> (bit % 64)) & 1;
  }
  void and_with(const BitMask& o) {
    for (size_t i = 0; i < words_.size(); i++)
      words_[i] &= i < o.words_.size() ? o.words_[i] : 0;
  }
  void or_with(const BitMask& o) {
    for (size_t i = 0; i < words_.size(); i++)
      if (i < o.words_.size()) words_[i] |= o.words_[i];
  }
  void and_not(const BitMask& o) {
    for (size_t i = 0; i < words_.size(); i++)
      if (i < o.words_.size()) words_[i] &= ~o.words_[i];
  }
  std::vector<int> bits() const {
    std::vector<int> out;
    for (size_t w = 0; w < words_.size(); w++) {
      uint64_t v = words_[w];
      while (v) {
        int b = __builtin_ctzll(v);
        out.push_back((int)(w * 64 + (size_t)b));
        v &= v - 1;
      }
    }
    return out;
  }
  const std::vector<uint64_t>& words() const { return words_; }

 private:
  std::vector<uint64_t> words_;
};

}  // namespace hvd

#endif  // HVD_TPU_RESPONSE_CACHE_H_
