// /dev/shm local data plane: same-host ranks exchange bytes through one
// POSIX shared-memory segment instead of the kernel socket stack.
//
// Reference analogue: MPIHierarchicalAllgather's node-shared window
// (MPI_Win_allocate_shared, horovod/common/ops/mpi_operations.cc:216-243) —
// the reference's intra-node phase is literally a memcpy into shared memory
// followed by cross-node MPI on the node leader. This file gives the native
// engine's hierarchical local phase (engine.cc hier_ring_allreduce /
// execute_allgather) the same structure: slots in a mapped segment, a
// process-shared pthread barrier for phase sync, parallel chunk reduction
// across local ranks, and the cross-node traffic still on the TCP ring of
// local roots. Loopback TCP moves every byte through the kernel twice;
// this moves it through cache-speed memcpy/SIMD reduce loops.
//
// Lifecycle: rank 0 creates and initializes the segment, peers attach and
// spin on the ready flag, everyone meets in one attach barrier, then rank 0
// shm_unlinks the name — the segment lives until the last munmap, and a
// crashed job leaks nothing. A stale same-name segment from a killed job is
// unlinked and recreated. Phase sync is a sense-reversal barrier with a
// 60 s timeout (matching the TCP ring's socket-wait timeout, ring.cc
// wait_fd): a local rank dying mid-operation surfaces as an engine error
// on its peers instead of an unbounded hang.

#include <fcntl.h>
#include <linux/futex.h>
#include <sched.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <sys/syscall.h>
#include <unistd.h>

#include <atomic>
#include <cerrno>
#include <chrono>
#include <climits>
#include <cstdint>
#include <cstring>
#include <ctime>
#include <string>

extern "C" {
// ring.cc (shared dtype kernels + error sink + ring liveness signal)
void hvd_dtype_accumulate(void* dst, const void* src, long count, int dtype);
long hvd_dtype_size(int dtype);
const char* hvd_ring_last_error();
double hvd_ring_progress_mono_s();
}

namespace {

// Written once via hvd_shm-internal set_error; read via hvd_shm_last_error.
std::string g_shm_error;

void set_error(const std::string& msg) { g_shm_error = msg; }

constexpr uint32_t kMagic = 0x48565353;  // "HVSS"
constexpr size_t kAlign = 64;

size_t align_up(size_t n) { return (n + kAlign - 1) & ~(kAlign - 1); }

struct Header {
  uint32_t magic;
  std::atomic<uint32_t> ready;
  // Sense-reversal barrier state: arrivals in the current phase, and the
  // phase generation waiters spin on.
  std::atomic<uint32_t> arrived;
  std::atomic<uint32_t> generation;
  // Cross-process liveness word: every local rank's ring layer stamps its
  // transfer progress here (hvd_ring_set_progress_sink), so barrier
  // waiters can tell "leader busy moving bytes" from "rank died". On its
  // own cache line: the leader stores per socket chunk while peers spin
  // on `generation` — sharing a line would ping-pong it every chunk.
  alignas(64) std::atomic<double> heartbeat;
  long slot_bytes;
  int nslots;
};

constexpr double kBarrierTimeoutS = 60.0;  // == ring.cc wait_fd timeout

struct Group {
  Header* hdr = nullptr;
  uint8_t* result = nullptr;  // one slot-sized reduction/broadcast area
  uint8_t* slots = nullptr;   // nslots contiguous slot areas
  size_t map_len = 0;
  int rank = 0;
  int size = 1;
  long slot_bytes = 0;

  uint8_t* slot(int r) const { return slots + (size_t)r * slot_bytes; }
};

double mono_s() {
  return std::chrono::duration_cast<std::chrono::duration<double>>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

// Process-shared futex on the generation word (plain FUTEX_WAIT/WAKE, not
// the PRIVATE variant — the segment is mapped by several processes).
long futex_wait(std::atomic<uint32_t>* addr, uint32_t expected,
                double timeout_s) {
  struct timespec ts;
  ts.tv_sec = (time_t)timeout_s;
  ts.tv_nsec = (long)((timeout_s - (double)ts.tv_sec) * 1e9);
  return syscall(SYS_futex, (uint32_t*)addr, FUTEX_WAIT, expected, &ts,
                 nullptr, 0);
}

void futex_wake_all(std::atomic<uint32_t>* addr) {
  syscall(SYS_futex, (uint32_t*)addr, FUTEX_WAKE, INT_MAX, nullptr, nullptr,
          0);
}

bool barrier(Group* g) {
  Header* h = g->hdr;
  uint32_t gen = h->generation.load(std::memory_order_acquire);
  uint32_t pos = h->arrived.fetch_add(1, std::memory_order_acq_rel) + 1;
  if (pos == (uint32_t)g->size) {
    // Last arriver releases the phase. arrived resets BEFORE the
    // generation bump: peers only proceed (and re-arrive for the next
    // phase) after acquiring the new generation, which orders the reset
    // before any next-phase increment.
    h->arrived.store(0, std::memory_order_relaxed);
    h->generation.fetch_add(1, std::memory_order_release);
    futex_wake_all(&h->generation);
    return true;
  }
  // Brief yield phase first (covers the near-simultaneous-arrival case
  // without a syscall round-trip), then block in the kernel with a bounded
  // wait — a dead local rank surfaces as an error after kBarrierTimeoutS
  // instead of hanging forever. Kept short: on a timeshared core long
  // yield-spinning steals quanta from the very peers being waited on.
  for (int i = 0; i < 8; i++) {
    if (h->generation.load(std::memory_order_acquire) != gen) return true;
    sched_yield();
  }
  // IDLE timeout, not a phase-duration cap: a peer may legitimately hold
  // everyone at this barrier for a long time while its cross-node TCP
  // phase moves a large payload (hier_ring_allreduce: non-root local
  // ranks wait in the broadcast barrier during the leader's cross-ring
  // exchange). Ring traffic in this process resets the deadline — only
  // "nothing moved for kBarrierTimeoutS" is treated as a dead rank, the
  // same semantics as the ring's per-poll 60 s (ring.cc wait_fd).
  double start = mono_s();
  for (;;) {
    if (h->generation.load(std::memory_order_acquire) != gen) return true;
    // Freshest liveness of the whole local group: this process's ring
    // traffic OR any peer's (stamped into the shared heartbeat word).
    double anchor = hvd_ring_progress_mono_s();
    double hb = h->heartbeat.load(std::memory_order_relaxed);
    if (hb > anchor) anchor = hb;
    if (anchor < start) anchor = start;
    double remain = anchor + kBarrierTimeoutS - mono_s();
    if (remain <= 0) {
      set_error("shm barrier timed out (60s idle) — a local rank died or "
                "stalled mid-operation");
      return false;
    }
    // Wake (or EAGAIN on a raced generation bump, or timeout slice) and
    // re-check; 1 s slices keep the idle deadline honest across spurious
    // wakes and refresh the ring-progress anchor.
    futex_wait(&h->generation, gen, remain < 1.0 ? remain : 1.0);
  }
}

}  // namespace

extern "C" {

const char* hvd_shm_last_error() { return g_shm_error.c_str(); }

// Create (rank 0) or attach (others) the local group segment. `name` must
// be identical across the group and unique per job+group (the engine
// derives it from the job secret). Returns nullptr on failure.
void* hvd_shm_create(int local_rank, int local_size, const char* name,
                     long slot_bytes) {
  // Slot must hold at least one element of the widest dtype (8 bytes) per
  // chunk or the chunk loops would never advance; anything below a page is
  // a misconfiguration anyway.
  if (local_size < 2 || slot_bytes < 4096) {
    set_error("shm group needs local_size >= 2 and slot_bytes >= 4096");
    return nullptr;
  }
  size_t header_len = align_up(sizeof(Header));
  size_t map_len =
      header_len + align_up((size_t)slot_bytes) * (size_t)(local_size + 1);

  int fd = -1;
  if (local_rank == 0) {
    fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
    if (fd < 0 && errno == EEXIST) {
      // Stale segment from a killed job: replace it.
      shm_unlink(name);
      fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
    }
    if (fd < 0) {
      set_error(std::string("shm_open(create): ") + strerror(errno));
      return nullptr;
    }
    if (ftruncate(fd, (off_t)map_len) != 0) {
      set_error(std::string("ftruncate: ") + strerror(errno));
      close(fd);
      shm_unlink(name);
      return nullptr;
    }
  } else {
    // Attach: the creator may not have run yet; poll briefly. The stale-
    // segment race (we open a dead job's same-name segment just before
    // rank 0 unlinks and recreates it) is closed below by re-checking that
    // the NAME still resolves to the inode we mapped before entering the
    // attach barrier; the job secret is random per launch by default, so
    // same-name staleness only arises with a user-pinned secret.
    for (int tries = 0; tries < 30000; tries++) {  // <= ~30 s
      fd = shm_open(name, O_RDWR, 0600);
      if (fd >= 0) {
        struct stat st;
        if (fstat(fd, &st) == 0 && (size_t)st.st_size >= map_len) break;
        close(fd);
        fd = -1;
      }
      usleep(1000);
    }
    if (fd < 0) {
      set_error("shm attach timed out waiting for the group creator");
      return nullptr;
    }
  }
  struct stat mapped_st;
  if (fstat(fd, &mapped_st) != 0) {
    set_error(std::string("fstat: ") + strerror(errno));
    close(fd);
    if (local_rank == 0) shm_unlink(name);
    return nullptr;
  }

  void* base =
      mmap(nullptr, map_len, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (base == MAP_FAILED) {
    set_error(std::string("mmap: ") + strerror(errno));
    if (local_rank == 0) shm_unlink(name);
    return nullptr;
  }

  Group* g = new Group();
  g->hdr = (Header*)base;
  g->result = (uint8_t*)base + header_len;
  g->slots = g->result + align_up((size_t)slot_bytes);
  g->map_len = map_len;
  g->rank = local_rank;
  g->size = local_size;
  g->slot_bytes = slot_bytes;

  if (local_rank == 0) {
    g->hdr->arrived.store(0, std::memory_order_relaxed);
    g->hdr->generation.store(0, std::memory_order_relaxed);
    g->hdr->heartbeat.store(0.0, std::memory_order_relaxed);
    g->hdr->magic = kMagic;
    g->hdr->slot_bytes = slot_bytes;
    g->hdr->nslots = local_size;
    g->hdr->ready.store(1, std::memory_order_release);
  } else {
    bool up = false;
    for (int tries = 0; tries < 30000; tries++) {
      if (g->hdr->ready.load(std::memory_order_acquire) == 1 &&
          g->hdr->magic == kMagic) {
        up = true;
        break;
      }
      usleep(1000);
    }
    if (!up || g->hdr->slot_bytes != slot_bytes ||
        g->hdr->nslots != local_size) {
      set_error(!up ? "shm group never became ready"
                    : "shm group geometry mismatch across ranks");
      munmap(base, map_len);
      delete g;
      return nullptr;
    }
    // Stale-segment guard: if the mapping came from a dead job's segment,
    // rank 0 has by now unlinked that name and created a fresh inode (its
    // very first step). Verify the name still resolves to OUR inode; if
    // not, drop everything and re-attach to the fresh one.
    int check_fd = shm_open(name, O_RDWR, 0600);
    bool stale = true;
    if (check_fd >= 0) {
      struct stat now_st;
      if (fstat(check_fd, &now_st) == 0 &&
          now_st.st_ino == mapped_st.st_ino &&
          now_st.st_dev == mapped_st.st_dev)
        stale = false;
      close(check_fd);
    }
    if (stale) {
      munmap(base, map_len);
      delete g;
      // One level of retry reattaches to the fresh segment; a second stale
      // hit means something else owns the name (two live jobs sharing a
      // pinned secret) — refuse rather than loop.
      static thread_local int reattach_depth = 0;
      if (reattach_depth >= 1) {
        set_error("shm segment name keeps changing under us (two jobs "
                  "sharing one HOROVOD_SECRET_KEY?)");
        return nullptr;
      }
      reattach_depth++;
      void* again = hvd_shm_create(local_rank, local_size, name, slot_bytes);
      reattach_depth--;
      return again;
    }
  }

  // Everyone is mapped; the name can go away now — the segment lives until
  // the last munmap, and nothing leaks if the job dies.
  if (!barrier(g)) {
    munmap(base, map_len);
    if (local_rank == 0) shm_unlink(name);
    delete g;
    return nullptr;
  }
  if (local_rank == 0) shm_unlink(name);
  return g;
}

// In-place local-group allreduce (sum / logical-OR for bool). Chunked by
// slot size; within each chunk every rank reduces its 1/N share of the
// elements across all slots in parallel (the local cores do the reduction
// together, the way the reference's node ranks share the window).
int hvd_shm_allreduce_g(void* h, void* buf, long count, int dtype) {
  Group* g = (Group*)h;
  if (!g) {
    set_error("null shm group");
    return -1;
  }
  long esz = hvd_dtype_size(dtype);
  if (esz <= 0) {
    set_error("unsupported dtype for shm allreduce");
    return -1;
  }
  long elems_per_chunk = g->slot_bytes / esz;
  uint8_t* p = (uint8_t*)buf;
  for (long off = 0; off < count; off += elems_per_chunk) {
    long n = count - off < elems_per_chunk ? count - off : elems_per_chunk;
    std::memcpy(g->slot(g->rank), p + off * esz, (size_t)n * esz);
    if (!barrier(g)) return -1;
    // This rank's share of the chunk: elements [lo, hi).
    long per = n / g->size;
    long lo = (long)g->rank * per;
    long hi = g->rank == g->size - 1 ? n : lo + per;
    if (hi > lo) {
      std::memcpy(g->result + lo * esz, g->slot(0) + lo * esz,
                  (size_t)(hi - lo) * esz);
      for (int s = 1; s < g->size; s++)
        hvd_dtype_accumulate(g->result + lo * esz, g->slot(s) + lo * esz,
                             hi - lo, dtype);
    }
    if (!barrier(g)) return -1;
    std::memcpy(p + off * esz, g->result, (size_t)n * esz);
    // The next chunk overwrites slots and result; nobody may still be
    // reading this chunk's bytes when that happens.
    if (!barrier(g)) return -1;
  }
  return 0;
}

int hvd_shm_broadcast_g(void* h, void* buf, long count, int dtype, int root) {
  Group* g = (Group*)h;
  if (!g) {
    set_error("null shm group");
    return -1;
  }
  long esz = hvd_dtype_size(dtype);
  if (esz <= 0) {
    set_error("unsupported dtype for shm broadcast");
    return -1;
  }
  if (root < 0 || root >= g->size) {
    set_error("shm broadcast root out of range");
    return -1;
  }
  long elems_per_chunk = g->slot_bytes / esz;
  uint8_t* p = (uint8_t*)buf;
  for (long off = 0; off < count; off += elems_per_chunk) {
    long n = count - off < elems_per_chunk ? count - off : elems_per_chunk;
    if (g->rank == root)
      std::memcpy(g->result, p + off * esz, (size_t)n * esz);
    if (!barrier(g)) return -1;
    if (g->rank != root)
      std::memcpy(p + off * esz, g->result, (size_t)n * esz);
    if (!barrier(g)) return -1;
  }
  return 0;
}

// Local-group allgather with per-rank element counts (variable first dims).
// Each pass moves up to slot_bytes of each rank's block; receivers copy
// every rank's pass-bytes straight from the slots into the right output
// offsets.
int hvd_shm_allgather_g(void* h, const void* in, const long* counts,
                        void* out, int dtype) {
  Group* g = (Group*)h;
  if (!g) {
    set_error("null shm group");
    return -1;
  }
  long esz = hvd_dtype_size(dtype);
  if (esz <= 0) {
    set_error("unsupported dtype for shm allgather");
    return -1;
  }
  long elems_per_chunk = g->slot_bytes / esz;
  long max_count = 0;
  for (int r = 0; r < g->size; r++)
    if (counts[r] > max_count) max_count = counts[r];
  // Output offset (elements) of each rank's block.
  long my_off = 0;
  for (int r = 0; r < g->rank; r++) my_off += counts[r];

  const uint8_t* src = (const uint8_t*)in;
  uint8_t* dst = (uint8_t*)out;
  for (long off = 0; off < max_count; off += elems_per_chunk) {
    long mine = counts[g->rank] - off;
    if (mine > elems_per_chunk) mine = elems_per_chunk;
    if (mine > 0)
      std::memcpy(g->slot(g->rank), src + off * esz, (size_t)mine * esz);
    if (!barrier(g)) return -1;
    long out_off = 0;
    for (int r = 0; r < g->size; r++) {
      long theirs = counts[r] - off;
      if (theirs > elems_per_chunk) theirs = elems_per_chunk;
      if (theirs > 0)
        std::memcpy(dst + (out_off + off) * esz, g->slot(r),
                    (size_t)theirs * esz);
      out_off += counts[r];
    }
    if (!barrier(g)) return -1;
  }
  return 0;
}

// Address of the shared heartbeat word, for hvd_ring_set_progress_sink.
void* hvd_shm_heartbeat_addr(void* h) {
  Group* g = (Group*)h;
  return g && g->hdr ? (void*)&g->hdr->heartbeat : nullptr;
}

void hvd_shm_destroy(void* h) {
  Group* g = (Group*)h;
  if (!g) return;
  if (g->hdr) munmap((void*)g->hdr, g->map_len);
  delete g;
}

}  // extern "C"
