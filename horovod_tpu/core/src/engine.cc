// Native eager-tier engine: background coordinator thread, negotiation,
// Tensor Fusion, response cache with bitvector coordination, stall detection
// and the Chrome-trace timeline — the C++ runtime around the ring data plane.
//
// Reference: horovod/common/operations.cc — a singleton HorovodGlobalState
// owns a background thread (BackgroundThreadLoop, operations.cc:857) that
// ticks every cycle_time_ms (RunLoopOnce, operations.cc:1246), drains the
// request queue, negotiates globally-ready tensors, packs fusion groups
// (FuseResponses, operations.cc:450-573), executes collectives and fires
// completion callbacks; a bit-indexed response cache short-circuits repeat
// negotiations (operations.cc:1166-1381) and the coordinator warns/aborts on
// stalled ranks (operations.cc:688-769).
//
// Same machine, different transport: where the reference runs negotiation as
// MPI_Gatherv/Bcast among host processes and the data plane on MPI/NCCL,
// this engine circulates a control token around the authenticated TCP ring
// (ring.cc) — rank 0 starts a token carrying its RequestList + cache
// bitvectors, every rank appends its own, rank 0 receives the full set,
// negotiates, and sends the fused ResponseList around the same ring. Data
// phases then run as ring collectives in ResponseList order, which is
// identical on every rank (the invariant the negotiation establishes).
// Python half: horovod_tpu/controller/native.py over the C ABI below (the
// reference exposes its C ABI the same way, operations.cc:1595-1650).

#include <algorithm>
#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <map>
#include <memory>
#include <mutex>
#include <stdexcept>
#include <string>
#include <thread>
#include <vector>

#include "message.h"
#include "response_cache.h"
#include "timeline.h"

#include "sha256.h"

// Ring data plane C ABI (ring.cc) + /dev/shm local data plane (shm.cc).
extern "C" {
void* hvd_shm_create(int local_rank, int local_size, const char* name,
                     long slot_bytes);
int hvd_shm_allreduce_g(void* h, void* buf, long count, int dtype);
int hvd_shm_broadcast_g(void* h, void* buf, long count, int dtype, int root);
int hvd_shm_allgather_g(void* h, const void* in, const long* counts,
                        void* out, int dtype);
void* hvd_shm_heartbeat_addr(void* h);
void hvd_shm_destroy(void* h);
const char* hvd_shm_last_error();
void hvd_ring_set_progress_sink(void* addr);
int hvd_ring_init(int rank, int size, const char* addrs, const uint8_t* secret,
                  int secret_len);
int hvd_ring_allreduce(void* buf, long count, int dtype, int average);
int hvd_ring_allreduce_wire(void* buf, long count, int dtype, int average,
                            int wire_dtype, void* residual);
int hvd_ring_allgather(const void* in, const long* counts, void* out,
                       int dtype);
int hvd_ring_broadcast(void* buf, long count, int dtype, int root);
int hvd_ring_send_right(const void* buf, long n);
int hvd_ring_recv_left(void* buf, long n);
void hvd_ring_shutdown();
const char* hvd_ring_last_error();
// Handle-based rings (several per process) for the two-level hierarchical
// data plane.
void* hvd_ringh_create(int rank, int size, const char* addrs,
                       const uint8_t* secret, int secret_len);
int hvd_ringh_allreduce(void* h, void* buf, long count, int dtype,
                        int average);
int hvd_ringh_allreduce_wire(void* h, void* buf, long count, int dtype,
                             int average, int wire_dtype, void* residual);
void hvd_ringh_set_link(void* h, int link);
int hvd_ringh_allgather(void* h, const void* in, const long* counts,
                        void* out, int dtype);
int hvd_ringh_broadcast(void* h, void* buf, long count, int dtype, int root);
void hvd_ringh_destroy(void* h);
}

namespace hvd {

// numpy-style names for ring.cc DType codes (error-message parity with the
// Python controller's construct_response).
std::string dtype_name(uint8_t code) {
  switch (code) {
    case 0: return "float32";
    case 1: return "float64";
    case 2: return "int32";
    case 3: return "int64";
    case 4: return "uint8";
    case 5: return "float16";
    case 6: return "bfloat16";
    case 7: return "int8";
    case 8: return "int16";
    case 9: return "uint16";
    case 10: return "bool";
  }
  return "dtype#" + std::to_string((int)code);
}

namespace {

size_t dtype_size(uint8_t dt) {
  // Must mirror ring.cc's DType enum (codes are the ctypes ABI).
  switch (dt) {
    case 0: case 2: return 4;               // f32, i32
    case 1: case 3: return 8;               // f64, i64
    case 4: case 7: case 10: return 1;      // u8, i8, bool
    case 5: case 6: case 8: case 9: return 2;  // f16, bf16, i16, u16
  }
  return 0;
}

double mono_s() {
  return std::chrono::duration_cast<std::chrono::duration<double>>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

constexpr const char* kShutdownMsg = "Horovod has been shut down";

const char* op_name(uint8_t t) {
  switch (t) {
    case RESP_ALLREDUCE: return "ALLREDUCE";
    case RESP_ALLGATHER: return "ALLGATHER";
    case RESP_BROADCAST: return "BROADCAST";
  }
  return "ERROR";
}

// Async-op handle slot (reference torch/handle_manager.h:31-42).
struct HandleSlot {
  int status = 0;  // 0 pending, 1 ok, 2 error
  std::string error;
  uint8_t dtype = 0;
  // Result landed in the caller's own buffer (allreduce/broadcast): data
  // stays empty and the Python side returns the array it enqueued.
  bool in_place = false;
  std::vector<int64_t> shape;
  std::vector<uint8_t> data;
  // Allgather only: every rank's first-dim size from the negotiated
  // Response, so the API layer can locate a rank's slice without a second
  // sizes collective (the reference surfaces the same via TensorShape,
  // torch/adapter_v2.cc:91-102).
  std::vector<int64_t> tensor_sizes;
};

// Tensor-table entry (reference TensorTableEntry, common/common.h:167-184).
// ZERO-COPY CONTRACT: `user` points at the caller-owned buffer passed to
// enqueue. The caller (native.py keeps the numpy array referenced on the
// handle) guarantees it stays alive and un-mutated until the handle
// resolves; the engine reads from it and — for allreduce/broadcast —
// writes the result back into it, the way the reference reduces in place
// on framework-owned memory (mpi_operations.cc:40-49, torch
// _handle_map keeping tensors alive, torch/mpi_ops.py:54).
struct Entry {
  Request request;
  uint8_t* user = nullptr;
  size_t nbytes = 0;
  long long handle = -1;
  // int8 wire error-feedback out-buffer (f32 x element count, caller-owned
  // and pinned like `user`; nullable). The ring writes the quantization
  // error of this tensor's bytes here; controller/native.py carries it
  // into the next allreduce.
  float* residual = nullptr;
  // Trace stamps (monotonic seconds): user call time, and the moment the
  // request departed in a tick — taken POST-send like the Python
  // controller's, so a rank whose sends stall is the rank that looks
  // late. sent_at < 0 = never departed (cache-bypass ops).
  double enqueued_at = 0;
  double sent_at = -1;
};

struct Tick {
  int32_t rank = 0;
  bool shutdown = false;
  std::vector<uint64_t> cache_words;
  std::vector<uint64_t> invalid_words;
  std::vector<Request> requests;
};

struct Reply {
  bool shutdown = false;
  std::vector<uint64_t> bypass_words;
  std::vector<uint64_t> invalid_words;
  ResponseList responses;
  // Base collective sequence id for this cycle (trace correlation): each
  // rank derives per-op ids by walking the identical bypass+responses
  // order, exactly like the Python controller's reply["trace_seq"].
  long long trace_seq = 0;
  // Autotuned gradient-bucket size, pushed by rank 0's tune loop and
  // synced to every rank on the cycle reply (0 = no value yet) — the
  // token slot the round-13 python-engine tune sync left open.
  long long bucket_bytes = 0;
};

void write_tick(Writer& w, const Tick& t) {
  w.i32(t.rank);
  w.u8(t.shutdown ? 1 : 0);
  w.u64vec(t.cache_words);
  w.u64vec(t.invalid_words);
  w.u32((uint32_t)t.requests.size());
  for (const auto& r : t.requests) write_request(w, r);
}

Tick read_tick(Reader& r) {
  Tick t;
  t.rank = r.i32();
  t.shutdown = r.u8() != 0;
  t.cache_words = r.u64vec();
  t.invalid_words = r.u64vec();
  uint32_t n = r.u32();
  for (uint32_t i = 0; i < n && r.ok; i++) t.requests.push_back(read_request(r));
  return t;
}

void write_reply(Writer& w, const Reply& rep) {
  w.u8(rep.shutdown ? 1 : 0);
  w.u64vec(rep.bypass_words);
  w.u64vec(rep.invalid_words);
  w.i64(rep.trace_seq);
  w.i64(rep.bucket_bytes);
  w.u32((uint32_t)rep.responses.responses.size());
  for (const auto& resp : rep.responses.responses) write_response(w, resp);
}

Reply read_reply(Reader& r) {
  Reply rep;
  rep.shutdown = r.u8() != 0;
  rep.bypass_words = r.u64vec();
  rep.invalid_words = r.u64vec();
  rep.trace_seq = r.i64();
  rep.bucket_bytes = r.i64();
  uint32_t n = r.u32();
  for (uint32_t i = 0; i < n && r.ok; i++)
    rep.responses.responses.push_back(read_response(r));
  rep.responses.shutdown = rep.shutdown;
  return rep;
}

class EngineError : public std::runtime_error {
 public:
  using std::runtime_error::runtime_error;
};

// ------------------------------------------------------------- telemetry
// Native half of the five-layer observability stack (docs/observability.md):
// per-op trace spans in a fixed-capacity ring behind ONE atomic enabled
// flag (zero-overhead-off, the r8 cached-boolean contract in C), plus
// always-on cumulative counters and log-spaced time histograms, all
// drained over the C ABI (hvd_eng_get_spans / hvd_eng_get_counters) by
// controller/native.py into the TraceWriter and metrics registry.

// Phase codes: MUST stay index-aligned with trace/tracer.py PHASES
// ("enqueue", "negotiate", "fuse", "execute", "done") — the Python drain
// maps code -> PHASES[code] and the vocabulary is lint-frozen.
enum SpanPhase : int {
  PH_ENQUEUE = 0,
  PH_NEGOTIATE = 1,
  PH_FUSE = 2,
  PH_EXECUTE = 3,
  PH_DONE = 4,
};

constexpr size_t kSpanOpBytes = 64;  // truncated tensor/fused-op name

struct Span {
  double t0 = 0, t1 = 0;  // CLOCK_MONOTONIC seconds (time.monotonic()'s
                          // clock — steady_clock on this platform), so the
                          // Python TraceWriter's monotonic anchor applies.
  long long seq = -1;     // coordinator-assigned collective seq (-1 none)
  int32_t phase = 0;      // SpanPhase
  int32_t tensors = 0;    // fuse spans: entries packed into the fused op
  char op[kSpanOpBytes] = {0};
};

// Histogram bucket upper bounds: EXACTLY the registry's
// DEFAULT_TIME_BUCKETS (metrics/registry.py: 1e-4 * 2^i, i in 0..21) so
// the Python mirror ingests bucket counts verbatim — no re-binning.
constexpr int kHistBuckets = 22;
constexpr int kHistSlots = kHistBuckets + 1;  // + the +Inf overflow slot

struct TimeHist {
  long long counts[kHistSlots] = {0};
  long long count = 0;
  long long sum_us = 0;

  void observe(double seconds) {
    int i = 0;
    double edge = 1e-4;
    while (i < kHistBuckets && seconds > edge) {
      edge *= 2.0;
      i++;
    }
    counts[i]++;
    count++;
    sum_us += (long long)(seconds * 1e6);
  }
};

// Counter-slot layout for hvd_eng_get_counters: APPEND-ONLY, mirrored by
// NATIVE_COUNTER_SCALARS / N_NATIVE_COUNTER_SLOTS in core/bindings.py (a
// drift is a hvdabi finding: python -m horovod_tpu.tools.abicheck).
enum CounterSlot : int {
  CTR_CYCLES = 0,
  CTR_TENSORS = 1,
  CTR_FUSED_TENSORS = 2,
  CTR_PROCESSED_BYTES = 3,
  CTR_FUSION_CAPACITY = 4,
  CTR_FUSION_FILL = 5,
  CTR_SPANS = 6,
  CTR_SPANS_DROPPED = 7,
  CTR_BUCKET_BYTES = 8,
  CTR_CACHE_HITS = 9,
  CTR_CACHE_MISSES = 10,
  // Pipelined data plane (round 16): high-water count of fused groups
  // outstanding on the wire thread, cumulative µs the engine thread spent
  // blocked on the wire (no free fusion slot / draining before a control
  // frame), and cycles whose response order was changed by a priority tag.
  CTR_PIPELINE_DEPTH = 11,
  CTR_PIPELINE_STALL_US = 12,
  CTR_PRIORITY_JUMPS = 13,
  CTR_CYCLE_HIST_COUNT = 14,
  CTR_CYCLE_HIST_SUM_US = 15,
  CTR_CYCLE_HIST_BUCKETS = 16,                           // .. +kHistSlots
  CTR_EXEC_HIST_COUNT = CTR_CYCLE_HIST_BUCKETS + kHistSlots,
  CTR_EXEC_HIST_SUM_US = CTR_EXEC_HIST_COUNT + 1,
  CTR_EXEC_HIST_BUCKETS = CTR_EXEC_HIST_SUM_US + 1,      // .. +kHistSlots
  // Engine generation (bumped per hvd_eng_init): counters restart at
  // zero with every new engine, so the Python mirror re-baselines when
  // it sees a new generation instead of clamping on "decreasing" totals.
  CTR_ENGINE_GEN = CTR_EXEC_HIST_BUCKETS + kHistSlots,
  N_COUNTER_SLOTS = CTR_ENGINE_GEN + 1,                  // 65
};

constexpr size_t kSpanRingDefault = 1 << 16;
constexpr size_t kSpanRingMin = 256;
constexpr size_t kSpanRingMax = 1 << 20;

// Two-level (hierarchical) data-plane state, populated by hvd_eng_init
// BEFORE the Engine is constructed (the engine thread starts in the ctor,
// so the rings must exist first). Analogue of the reference's
// NCCLHierarchicalAllreduce comm pair (nccl_operations.cc:167-363).
struct HierState {
  void* local_ring = nullptr;  // TCP ring inside this node (shm fallback)
  void* cross_ring = nullptr;  // ring of local roots (local_rank 0 only)
  void* shm = nullptr;         // /dev/shm local group (preferred local plane)
  int local_rank = 0, local_size = 1, cross_rank = 0, cross_size = 1;
  bool allreduce = false, allgather = false;
  // Per-link wire dtypes (WireDType codes) for the two-level allreduce
  // data plane: independent knobs for the local and cross hops
  // (HOROVOD_RING_WIRE_DTYPE_LOCAL/_CROSS via common/config.py, defaults
  // by link class). wire_local is ignored when the local plane is the
  // /dev/shm segment — memcpys through one mapping have no wire.
  int wire_local = 0, wire_cross = 0;
};
HierState g_hier;

// The engine singleton (reference HorovodGlobalState, global_state.h:44).
class Engine {
 public:
  Engine(int rank, int size, double cycle_ms, long long fusion_threshold,
         int cache_capacity, bool stall_disable, double stall_warn_s,
         double stall_shutdown_s, const std::string& timeline_path,
         bool timeline_mark_cycles, int wire_dtype, bool pipeline)
      : rank_(rank),
        size_(size),
        cycle_ms_(cycle_ms),
        fusion_threshold_(fusion_threshold),
        stall_disable_(stall_disable),
        stall_warn_s_(stall_warn_s),
        stall_shutdown_s_(stall_shutdown_s),
        wire_dtype_(wire_dtype),
        cache_(cache_capacity),
        hier_(g_hier) {
    // Pipelining covers the flat ring's allreduce path only: the two-level
    // plane's shared cross-hop scratch and multi-ring calls stay serial
    // (allgather/broadcast always drain first — see execute()).
    pipeline_ =
        pipeline && !(hier_.allreduce && (hier_.local_ring || hier_.shm));
    // Test-only determinism hook: per-job wire-thread sleep so a size-1
    // fake ring exhibits measurable fill-while-on-wire overlap.
    const char* delay = getenv("HOROVOD_PIPELINE_TEST_DELAY_US");
    if (delay && *delay) test_delay_us_ = atoll(delay);
    if (!timeline_path.empty() && rank == 0)
      timeline_ = std::make_unique<Timeline>(timeline_path,
                                             timeline_mark_cycles);
    if (pipeline_) wire_thread_ = std::thread([this] { wire_loop(); });
    thread_ = std::thread([this] { run_loop(); });
  }

  ~Engine() {
    request_shutdown();
    if (thread_.joinable()) thread_.join();
    if (timeline_) timeline_->close();
  }

  // ------------------------------------------------------- enqueue (any thread)

  // Returns handle >= 0; -2 duplicate name; -3 shut down.
  long long enqueue(uint8_t op, const std::string& name, void* data,
                    const int64_t* shape, int ndim, uint8_t dtype,
                    int32_t root_rank, void* residual, int32_t priority) {
    std::lock_guard<std::mutex> g(mu_);
    if (closed_ || shutdown_requested_) return -3;
    if (table_.count(name)) return -2;  // reference IncrementTensorCount dup
    Entry e;
    e.enqueued_at = mono_s();
    e.residual = (float*)residual;
    e.request.request_rank = rank_;
    e.request.request_type = op;
    e.request.dtype = dtype;
    e.request.root_rank = root_rank;
    e.request.priority = priority;
    e.request.shape.assign(shape, shape + ndim);
    e.request.tensor_name = name;
    size_t count = 1;
    for (int i = 0; i < ndim; i++) count *= (size_t)shape[i];
    e.nbytes = count * dtype_size(dtype);
    e.user = (uint8_t*)data;  // zero-copy: see Entry's contract note
    long long h = next_handle_++;
    e.handle = h;
    handles_.emplace(h, HandleSlot{});
    table_.emplace(name, std::move(e));
    queue_.push_back(name);
    return h;
  }

  // -------------------------------------------------------- handles (any thread)

  int poll(long long h) {
    std::lock_guard<std::mutex> g(mu_);
    auto it = handles_.find(h);
    if (it == handles_.end()) return -1;
    return it->second.status;
  }

  int wait(long long h) {
    std::unique_lock<std::mutex> lk(mu_);
    for (;;) {
      auto it = handles_.find(h);
      if (it == handles_.end()) return -1;
      if (it->second.status != 0) return it->second.status == 1 ? 0 : 1;
      handle_cv_.wait(lk);
    }
  }

  // 0 ok, 1 error, -1 unknown handle, -2 timed out.
  int wait_for(long long h, double timeout_s) {
    std::unique_lock<std::mutex> lk(mu_);
    auto deadline = std::chrono::steady_clock::now() +
                    std::chrono::duration<double>(timeout_s);
    for (;;) {
      auto it = handles_.find(h);
      if (it == handles_.end()) return -1;
      if (it->second.status != 0) return it->second.status == 1 ? 0 : 1;
      if (handle_cv_.wait_until(lk, deadline) == std::cv_status::timeout) {
        auto it2 = handles_.find(h);
        if (it2 != handles_.end() && it2->second.status != 0)
          return it2->second.status == 1 ? 0 : 1;
        return -2;
      }
    }
  }

  HandleSlot* slot(long long h) {
    std::lock_guard<std::mutex> g(mu_);
    auto it = handles_.find(h);
    return it == handles_.end() ? nullptr : &it->second;
  }

  void release(long long h) {
    std::lock_guard<std::mutex> g(mu_);
    handles_.erase(h);
  }

  void set_params(long long fusion_threshold, double cycle_ms) {
    if (fusion_threshold > 0) fusion_threshold_ = fusion_threshold;
    if (cycle_ms > 0) cycle_ms_ = cycle_ms;
  }

  void get_stats(long long* cycles, long long* bytes, double* busy_s) {
    *cycles = cycles_.load();
    *bytes = processed_bytes_.load();
    *busy_s = busy_us_.load() / 1e6;
  }

  // --------------------------------------------------- telemetry (any thread)

  void trace_set(bool enabled, long long capacity) {
    std::lock_guard<std::mutex> g(tele_mu_);
    if (capacity > 0) {
      size_t cap = (size_t)std::min<long long>(
          std::max<long long>(capacity, (long long)kSpanRingMin),
          (long long)kSpanRingMax);
      ring_.assign(cap, Span{});
      ring_head_ = ring_size_ = 0;
    } else if (ring_.empty()) {
      ring_.assign(kSpanRingDefault, Span{});
    }
    trace_on_.store(enabled, std::memory_order_relaxed);
  }

  // One complete span into the ring. THE zero-overhead-off contract: with
  // tracing disabled this is a single relaxed atomic load and a return —
  // nothing else (pinned by the source guard + measured probe in
  // tests/test_native_telemetry.py).
  void stamp_span(int phase, double t0, double t1, long long seq,
                  int tensors, const char* op) {
    if (!trace_on_.load(std::memory_order_relaxed)) return;
    std::lock_guard<std::mutex> g(tele_mu_);
    if (ring_.empty()) return;
    size_t cap = ring_.size();
    size_t pos;
    if (ring_size_ == cap) {
      // Full: the NEW span takes the oldest slot (head advances) and the
      // drop is counted — the engine thread never blocks on a slow
      // drainer and a record is never torn.
      pos = ring_head_;
      ring_head_ = (ring_head_ + 1) % cap;
      spans_dropped_.fetch_add(1, std::memory_order_relaxed);
    } else {
      pos = (ring_head_ + ring_size_) % cap;
      ring_size_++;
    }
    Span& s = ring_[pos];
    s.t0 = t0;
    s.t1 = t1;
    s.seq = seq;
    s.phase = phase;
    s.tensors = tensors;
    std::strncpy(s.op, op ? op : "", kSpanOpBytes - 1);
    s.op[kSpanOpBytes - 1] = 0;
    spans_total_.fetch_add(1, std::memory_order_relaxed);
  }

  // Drain up to `max` spans, oldest first; returns the count consumed.
  int drain_spans(long long max, int32_t* phases, long long* seqs,
                  double* t0s, double* t1s, int32_t* tensors, char* ops,
                  int op_stride) {
    std::lock_guard<std::mutex> g(tele_mu_);
    if (ring_.empty() || max <= 0) return 0;
    long long n = std::min<long long>(max, (long long)ring_size_);
    for (long long i = 0; i < n; i++) {
      const Span& s = ring_[(ring_head_ + (size_t)i) % ring_.size()];
      phases[i] = s.phase;
      seqs[i] = s.seq;
      t0s[i] = s.t0;
      t1s[i] = s.t1;
      tensors[i] = s.tensors;
      std::strncpy(ops + (size_t)i * (size_t)op_stride, s.op,
                   (size_t)op_stride - 1);
      ops[(size_t)i * (size_t)op_stride + (size_t)op_stride - 1] = 0;
    }
    ring_head_ = (ring_head_ + (size_t)n) % ring_.size();
    ring_size_ -= (size_t)n;
    return (int)n;
  }

  void get_counters(long long* out, int n) {
    long long tmp[N_COUNTER_SLOTS] = {0};
    tmp[CTR_CYCLES] = cycles_.load(std::memory_order_relaxed);
    tmp[CTR_TENSORS] = tensors_total_.load(std::memory_order_relaxed);
    tmp[CTR_FUSED_TENSORS] = fused_tensors_.load(std::memory_order_relaxed);
    tmp[CTR_PROCESSED_BYTES] =
        processed_bytes_.load(std::memory_order_relaxed);
    tmp[CTR_FUSION_CAPACITY] = fusion_cap_.load(std::memory_order_relaxed);
    tmp[CTR_FUSION_FILL] = fusion_fill_.load(std::memory_order_relaxed);
    tmp[CTR_SPANS] = spans_total_.load(std::memory_order_relaxed);
    tmp[CTR_SPANS_DROPPED] =
        spans_dropped_.load(std::memory_order_relaxed);
    tmp[CTR_BUCKET_BYTES] = bucket_synced_.load(std::memory_order_relaxed);
    tmp[CTR_CACHE_HITS] = cache_hits_.load(std::memory_order_relaxed);
    tmp[CTR_CACHE_MISSES] = cache_misses_.load(std::memory_order_relaxed);
    tmp[CTR_PIPELINE_DEPTH] =
        pipeline_depth_.load(std::memory_order_relaxed);
    tmp[CTR_PIPELINE_STALL_US] =
        pipeline_stall_us_.load(std::memory_order_relaxed);
    tmp[CTR_PRIORITY_JUMPS] =
        priority_jumps_.load(std::memory_order_relaxed);
    {
      std::lock_guard<std::mutex> g(tele_mu_);
      tmp[CTR_CYCLE_HIST_COUNT] = cycle_hist_.count;
      tmp[CTR_CYCLE_HIST_SUM_US] = cycle_hist_.sum_us;
      for (int i = 0; i < kHistSlots; i++)
        tmp[CTR_CYCLE_HIST_BUCKETS + i] = cycle_hist_.counts[i];
      tmp[CTR_EXEC_HIST_COUNT] = exec_hist_.count;
      tmp[CTR_EXEC_HIST_SUM_US] = exec_hist_.sum_us;
      for (int i = 0; i < kHistSlots; i++)
        tmp[CTR_EXEC_HIST_BUCKETS + i] = exec_hist_.counts[i];
    }
    for (int i = 0; i < n && i < N_COUNTER_SLOTS; i++) out[i] = tmp[i];
  }

  // Coordinator-side tuned-bucket slot: the value rides the NEXT cycle
  // reply to every rank (coordinate() reads it). Harmless on workers.
  void set_tuned_bucket(long long nbytes) {
    bucket_push_.store(nbytes, std::memory_order_relaxed);
  }

  // Micro-bench for the overhead guard: stamp n spans through the real
  // path (enabled or disabled — whatever trace_set left), return seconds.
  double span_probe(long long n) {
    double t0 = mono_s();
    for (long long i = 0; i < n; i++)
      stamp_span(PH_EXECUTE, t0, t0, -1, 0, "probe");
    return mono_s() - t0;
  }

  void request_shutdown() { shutdown_requested_ = true; }
  bool closed() {
    std::lock_guard<std::mutex> g(mu_);
    return closed_;
  }

  // Cooperative teardown: flag the shutdown, wait for the loop to exit
  // (the flag must circulate so every rank closes on the same cycle), then
  // release the bulk memory. The Engine object itself stays alive — see the
  // note at hvd_eng_shutdown.
  void finish() {
    request_shutdown();
    if (thread_.joinable()) thread_.join();
    if (timeline_) timeline_->close();
    std::lock_guard<std::mutex> g(mu_);
    fusion_buffer_.clear();
    fusion_buffer_.shrink_to_fit();
    for (FusionSlot& s : slots_) {
      s.buf.clear();
      s.buf.shrink_to_fit();
      s.residual.clear();
      s.residual.shrink_to_fit();
    }
    finished_ = true;
  }

  bool finished() {
    std::lock_guard<std::mutex> g(mu_);
    return finished_;
  }

  // True when the two-level data plane is active (test/introspection seam;
  // the Python controller exposes its rings the same way).
  bool hier_active() const {
    return (hier_.local_ring != nullptr || hier_.shm != nullptr) &&
           (hier_.allreduce || hier_.allgather);
  }

 private:
  // ------------------------------------------------------------- cycle loop

  void run_loop() {
    try {
      while (true) {
        {
          std::lock_guard<std::mutex> g(mu_);
          if (closed_) break;
        }
        if (rank_ == 0) {
          // The coordinator paces the token (reference sleeps cycle_time in
          // every rank's loop, operations.cc:1250-1255; workers here are
          // paced by token arrival instead). With pipelining the pacing
          // window runs CONCURRENTLY with the wire drain: last cycle's
          // fused groups keep moving on the wire thread while this thread
          // reaps/copies out, and only drain time past the pacing deadline
          // counts as a pipeline stall.
          double deadline = mono_s() + cycle_ms_.load() / 1000.0;
          if (pipeline_) reap_wire(/*wait_all=*/true, deadline);
          double remain = deadline - mono_s();
          if (remain > 0)
            std::this_thread::sleep_for(
                std::chrono::duration<double>(remain));
        }
        double t0 = mono_s();
        if (timeline_) timeline_->mark_cycle_start();
        cycle();
        double dt = mono_s() - t0;
        busy_us_ += (long long)(dt * 1e6);
        {
          std::lock_guard<std::mutex> g(tele_mu_);
          cycle_hist_.observe(dt);
        }
        cycles_++;
      }
    } catch (const std::exception& exc) {
      std::fprintf(stderr, "[hvd-native:%d] engine loop failed: %s\n", rank_,
                   exc.what());
      fail_all_and_close(exc.what());
    }
    if (size_ > 1) hvd_ring_shutdown();
    if (hier_.local_ring) hvd_ringh_destroy(hier_.local_ring);
    if (hier_.cross_ring) hvd_ringh_destroy(hier_.cross_ring);
    // Unregister the heartbeat sink BEFORE unmapping the segment it
    // points into (all ring traffic has stopped; no racing writer).
    if (hier_.shm) {
      hvd_ring_set_progress_sink(nullptr);
      hvd_shm_destroy(hier_.shm);
    }
    hier_.local_ring = hier_.cross_ring = hier_.shm = nullptr;
    if (timeline_) timeline_->close();
  }

  Tick build_tick(std::vector<std::string>* sent_names) {
    std::lock_guard<std::mutex> g(mu_);
    Tick t;
    t.rank = rank_;
    t.shutdown = shutdown_requested_;
    BitMask cache_mask(cache_.capacity());
    BitMask invalid_mask(cache_.capacity());
    for (const std::string& name : queue_) {
      auto& entry = table_.at(name);
      int bit = cache_.lookup(entry.request);
      if (bit >= 0) {
        bit_pending_[bit] = name;
        continue;
      }
      int stale = cache_.stale_bit(entry.request);
      if (stale >= 0) invalid_mask.set(stale);
      t.requests.push_back(entry.request);
      sent_names->push_back(name);
    }
    queue_.clear();
    for (const auto& kv : bit_pending_) cache_mask.set(kv.first);
    t.cache_words = cache_mask.words();
    t.invalid_words = invalid_mask.words();
    return t;
  }

  // Stamp the departure time of this cycle's requests AFTER their tick
  // left (send-path stalls charge the sender — the Python controller's
  // POST-send contract). Only runs with tracing on.
  void mark_sent(const std::vector<std::string>& names) {
    double now = mono_s();
    std::lock_guard<std::mutex> g(mu_);
    for (const auto& name : names) {
      auto it = table_.find(name);
      if (it != table_.end()) it->second.sent_at = now;
    }
  }

  void cycle() {
    // The wire thread shares the ring sockets with the control plane: a
    // rank must fully drain its wire queue before reading a control frame
    // (interleaved reads would corrupt both streams). All ranks drain in
    // the identical FIFO order, so every queued collective's peer traffic
    // is guaranteed to flow before anyone touches control I/O. Rank 0
    // drained inside the pacing window (run_loop) instead.
    if (pipeline_ && rank_ != 0) reap_wire(/*wait_all=*/true);
    std::vector<std::string> sent_names;
    Tick own = build_tick(&sent_names);
    bool tr = trace_on_.load(std::memory_order_relaxed);
    Reply reply;
    if (size_ == 1) {
      if (tr && !sent_names.empty()) mark_sent(sent_names);
      reply = coordinate({own});
    } else if (rank_ == 0) {
      // Start the token with our tick; receive it back with everyone's.
      Writer w;
      w.u32(1);
      write_tick(w, own);
      send_frame(w.buf);
      if (tr && !sent_names.empty()) mark_sent(sent_names);
      std::vector<uint8_t> token = recv_frame();
      Reader r(token.data(), token.size());
      uint32_t n = r.u32();
      std::vector<Tick> ticks;
      for (uint32_t i = 0; i < n && r.ok; i++) ticks.push_back(read_tick(r));
      if (!r.ok || ticks.size() != (size_t)size_)
        throw EngineError("malformed control token");
      std::sort(ticks.begin(), ticks.end(),
                [](const Tick& a, const Tick& b) { return a.rank < b.rank; });
      reply = coordinate(ticks);
      Writer rw;
      write_reply(rw, reply);
      send_frame(rw.buf);
    } else {
      // Append our tick to the token and pass it on.
      std::vector<uint8_t> token = recv_frame();
      Reader r(token.data(), token.size());
      uint32_t n = r.u32();
      Writer w;
      w.u32(n + 1);
      w.buf.insert(w.buf.end(), token.begin() + 4, token.end());
      write_tick(w, own);
      send_frame(w.buf);
      if (tr && !sent_names.empty()) mark_sent(sent_names);
      // Receive the reply; forward before processing so downstream ranks
      // enter the data phase too.
      std::vector<uint8_t> raw = recv_frame();
      if ((rank_ + 1) % size_ != 0) send_frame(raw);
      Reader rr(raw.data(), raw.size());
      reply = read_reply(rr);
      if (!rr.ok) throw EngineError("malformed control reply");
    }
    process_reply(reply);
  }

  // --------------------------------------------------------- control frames
  //
  // Frame-kind coverage vs the 7-kind SPEC in analysis/protocol.py,
  // checked statically by `protocheck --native` (analysis/cpp.py). The
  // native engine's control plane is raw length-prefixed replies on the
  // coordinator wires — it does not yet speak the kind-byte protocol, so
  // every kind beyond the data plane is declared unsupported here rather
  // than silently dropped (ROADMAP item 1 is the work that flips these
  // to handled).
  //
  // hvdabi:frame-kind kind=data status=handled via=recv_frame
  // hvdabi:frame-kind kind=heartbeat status=unsupported reason=python-engine-only
  // hvdabi:frame-kind kind=abort status=unsupported reason=python-engine-only
  // hvdabi:frame-kind kind=join status=unsupported reason=python-engine-only
  // hvdabi:frame-kind kind=reshape status=unsupported reason=python-engine-only
  // hvdabi:frame-kind kind=shard_fetch status=unsupported reason=python-engine-only
  // hvdabi:frame-kind kind=shard_data status=unsupported reason=python-engine-only

  void send_frame(const std::vector<uint8_t>& payload) {
    uint32_t len = (uint32_t)payload.size();
    if (hvd_ring_send_right(&len, 4) != 0 ||
        hvd_ring_send_right(payload.data(), (long)payload.size()) != 0)
      throw EngineError(std::string("control send failed: ") +
                        hvd_ring_last_error());
  }

  std::vector<uint8_t> recv_frame() {
    uint32_t len = 0;
    if (hvd_ring_recv_left(&len, 4) != 0)
      throw EngineError(std::string("control recv failed: ") +
                        hvd_ring_last_error());
    if (len > (1u << 28)) throw EngineError("oversized control frame");
    std::vector<uint8_t> payload(len);
    if (len && hvd_ring_recv_left(payload.data(), (long)len) != 0)
      throw EngineError(std::string("control recv failed: ") +
                        hvd_ring_last_error());
    return payload;
  }

  // ------------------------------------------------------- coordinator side

  Reply coordinate(const std::vector<Tick>& ticks) {
    Reply reply;
    BitMask and_mask(ticks[0].cache_words.empty()
                         ? BitMask(cache_.capacity())
                         : BitMask(ticks[0].cache_words));
    BitMask invalid(cache_.capacity());
    for (const auto& t : ticks) {
      reply.shutdown = reply.shutdown || t.shutdown;
      invalid.or_with(BitMask(t.invalid_words));
      and_mask.and_with(BitMask(t.cache_words));
    }
    and_mask.and_not(invalid);

    // Negotiation (reference operations.cc:1388-1475): accumulate per-tensor
    // requests; a tensor is ready when every rank reported it.
    double now = mono_s();
    std::vector<Response> ready;
    for (const auto& t : ticks) {
      for (const auto& req : t.requests) {
        auto& entry = message_table_[req.tensor_name];
        if (entry.empty()) {
          first_seen_[req.tensor_name] = now;
          if (timeline_)
            timeline_->negotiate_start(req.tensor_name,
                                       op_name(req.request_type));
        }
        if (timeline_)
          timeline_->negotiate_rank_ready(req.tensor_name, t.rank);
        entry[t.rank] = req;
      }
    }
    for (auto it = message_table_.begin(); it != message_table_.end();) {
      if ((int)it->second.size() == size_) {
        std::vector<Request> requests;
        for (int r = 0; r < size_; r++) requests.push_back(it->second[r]);
        ready.push_back(construct_response(requests, size_));
        if (timeline_)
          timeline_->negotiate_end(it->first,
                                   op_name(requests[0].request_type));
        first_seen_.erase(it->first);
        stall_warned_.erase(it->first);
        it = message_table_.erase(it);
      } else {
        ++it;
      }
    }

    check_stalls(now);
    reply.responses.responses = fuse_responses(std::move(ready));
    prioritize_responses(reply.responses.responses);
    reply.responses.shutdown = reply.shutdown;
    reply.bypass_words = and_mask.words();
    reply.invalid_words = invalid.words();
    // One base collective seq id per cycle (the r9 tracer's correlation
    // key): every rank walks the identical bypass-then-responses order,
    // so base + index is the same id on every rank's trace row.
    reply.trace_seq = next_seq_;
    next_seq_ += (long long)and_mask.bits().size() +
                 (long long)reply.responses.responses.size();
    // Synced tuned-bucket push (rank 0's tune loop -> every rank).
    reply.bucket_bytes = bucket_push_.load(std::memory_order_relaxed);
    return reply;
  }

  // Tensor Fusion packing (reference FuseResponses, operations.cc:450-573):
  // join ALLREDUCE responses of equal dtype while the fused byte count stays
  // under the threshold, with look-ahead past mismatched dtypes. dtype/bytes
  // are snapshotted under ONE mu_ acquisition for the whole cycle — the old
  // per-candidate response_dtype()/response_bytes() helpers took the lock
  // O(n^2) times exactly when fusion matters (hundreds of small tensors).
  std::vector<Response> fuse_responses(std::vector<Response> responses) {
    struct Pending {
      Response r;
      uint8_t dtype = 0;
      long long bytes = 0;
    };
    std::deque<Pending> pending;
    {
      std::lock_guard<std::mutex> g(mu_);
      for (auto& r : responses) {
        Pending p;
        if (r.response_type == RESP_ALLREDUCE) {
          p.dtype = table_.at(r.tensor_names[0]).request.dtype;
          for (const auto& name : r.tensor_names)
            p.bytes += (long long)table_.at(name).nbytes;
        }
        p.r = std::move(r);
        pending.push_back(std::move(p));
      }
    }
    std::vector<Response> out;
    while (!pending.empty()) {
      Pending first = std::move(pending.front());
      pending.pop_front();
      if (first.r.response_type != RESP_ALLREDUCE) {
        out.push_back(std::move(first.r));
        continue;
      }
      long long total = first.bytes;
      for (size_t i = 0; i < pending.size();) {
        Pending& cand = pending[i];
        if (cand.r.response_type == RESP_ALLREDUCE &&
            cand.dtype == first.dtype) {
          if (total + cand.bytes <= fusion_threshold_) {
            for (auto& n : cand.r.tensor_names)
              first.r.tensor_names.push_back(std::move(n));
            total += cand.bytes;
            pending.erase(pending.begin() + (long)i);
            continue;
          }
        }
        i++;  // look-ahead (reference operations.cc:483-499)
      }
      out.push_back(std::move(first.r));
    }
    return out;
  }

  // Priority scheduling: the optimizer-critical bucket (tagged by the
  // BucketScheduler, carried on Request.priority) jumps the launch queue
  // HERE, at coordination — the one place with a global view — so every
  // rank executes the identical reordered sequence and the wire FIFO
  // stays rank-consistent (a per-rank local jump would desynchronize the
  // ring call pairing). Stable sort: equal priorities keep negotiation
  // order, so untagged jobs are bit-for-bit unaffected.
  void prioritize_responses(std::vector<Response>& responses) {
    if (responses.size() < 2) return;
    std::vector<int32_t> prio(responses.size(), 0);
    bool any = false;
    {
      std::lock_guard<std::mutex> g(mu_);
      for (size_t i = 0; i < responses.size(); i++) {
        for (const auto& name : responses[i].tensor_names) {
          auto it = table_.find(name);
          if (it != table_.end() && it->second.request.priority > prio[i])
            prio[i] = it->second.request.priority;
        }
        if (prio[i] > 0) any = true;
      }
    }
    if (!any) return;
    std::vector<size_t> order(responses.size());
    for (size_t i = 0; i < order.size(); i++) order[i] = i;
    std::stable_sort(order.begin(), order.end(),
                     [&](size_t a, size_t b) { return prio[a] > prio[b]; });
    bool moved = false;
    for (size_t i = 0; i < order.size(); i++)
      moved = moved || order[i] != i;
    if (!moved) return;
    std::vector<Response> sorted;
    sorted.reserve(responses.size());
    for (size_t i : order) sorted.push_back(std::move(responses[i]));
    responses = std::move(sorted);
    priority_jumps_.fetch_add(1, std::memory_order_relaxed);
  }

  // Reference CheckForStalledTensors (operations.cc:688-769).
  void check_stalls(double now) {
    if (stall_disable_) return;
    for (const auto& kv : first_seen_) {
      const std::string& name = kv.first;
      double age = now - kv.second;
      if (age <= stall_warn_s_) continue;
      double last = stall_warned_.count(name) ? stall_warned_[name] : 0.0;
      if (now - last > stall_warn_s_) {
        std::string missing;
        const auto& seen = message_table_[name];
        for (int r = 0; r < size_; r++) {
          if (!seen.count(r)) {
            if (!missing.empty()) missing += ", ";
            missing += std::to_string(r);
          }
        }
        std::fprintf(stderr,
                     "[hvd-native:%d] WARNING: One or more tensors were "
                     "submitted to be reduced, gathered or broadcasted by "
                     "subset of ranks and are waiting for remainder of ranks "
                     "for more than %ds. Stalled op: %s [missing ranks: %s]\n",
                     rank_, (int)stall_warn_s_, name.c_str(), missing.c_str());
        stall_warned_[name] = now;
      }
      if (stall_shutdown_s_ > 0 && age > stall_shutdown_s_) {
        std::fprintf(stderr,
                     "[hvd-native:%d] ERROR: Stall duration exceeded "
                     "HOROVOD_STALL_SHUTDOWN_TIME_SECONDS: aborting job "
                     "(stalled op: %s)\n",
                     rank_, name.c_str());
        shutdown_requested_ = true;
      }
    }
  }

  // ----------------------------------------------------------- both sides

  void process_reply(const Reply& reply) {
    double reply_at = mono_s();
    if (reply.bucket_bytes > 0)
      bucket_synced_.store(reply.bucket_bytes, std::memory_order_relaxed);
    BitMask invalid(reply.invalid_words);
    for (int bit : invalid.bits()) {
      std::lock_guard<std::mutex> g(mu_);
      cache_.evict_bit(bit);
      auto it = bit_pending_.find(bit);
      if (it != bit_pending_.end()) {
        // Cache entry died under a pending hit: renegotiate.
        queue_.push_back(it->second);
        bit_pending_.erase(it);
      }
    }

    // Per-op seq ids: base from the reply, walked over the identical
    // bypass-then-responses order on every rank (python _process_reply
    // parity — merged traces correlate across engines on args.seq).
    long long seq = reply.trace_seq;
    BitMask bypass(reply.bypass_words);
    std::vector<int> bypass_bits = bypass.bits();
    // Cache-bypass ops never reach the coordinator's priority sort (they
    // skip negotiation), so the walk order applies the same key locally:
    // priority desc, bit index asc. Priorities are rank-consistent by
    // contract (like dtype agreement), so every rank walks — and stamps
    // seq ids over — the identical order.
    if (bypass_bits.size() > 1) {
      std::lock_guard<std::mutex> g(mu_);
      auto bit_prio = [&](int bit) -> int32_t {
        auto it = bit_pending_.find(bit);
        if (it == bit_pending_.end()) return 0;
        auto te = table_.find(it->second);
        return te == table_.end() ? 0 : te->second.request.priority;
      };
      std::stable_sort(bypass_bits.begin(), bypass_bits.end(),
                       [&](int a, int b) { return bit_prio(a) > bit_prio(b); });
    }
    for (int bit : bypass_bits) {
      // Cached fast path (reference RunBypass, operations.cc:1166-1215).
      std::string cached_name;
      Response cached;
      std::string name;
      {
        std::lock_guard<std::mutex> g(mu_);
        if (!cache_.get(bit, &cached_name, &cached))
          throw EngineError("bypass bit not in cache");
        cache_.touch(bit);
        auto it = bit_pending_.find(bit);
        if (it == bit_pending_.end())
          throw EngineError("bypass bit with no pending tensor");
        name = it->second;
        bit_pending_.erase(it);
      }
      Response r;
      r.response_type = cached.response_type;
      r.tensor_names.push_back(name);
      r.tensor_sizes = cached.tensor_sizes;
      cache_hits_.fetch_add(1, std::memory_order_relaxed);
      execute(r, /*cache_put=*/false, seq++, reply_at);
    }

    for (const auto& resp : reply.responses.responses) {
      if (resp.response_type != RESP_ERROR)
        cache_misses_.fetch_add(1, std::memory_order_relaxed);
      execute(resp, /*cache_put=*/true, seq++, reply_at);
    }

    // Act only on the *circulated* shutdown flag, never the local one: a
    // locally-set flag must first ride a tick so every rank closes on the
    // same cycle (otherwise this rank would drop out of the token chain
    // while peers still expect its hops).
    if (reply.shutdown) {
      // Final-cycle collectives still complete successfully (serial-engine
      // parity): drain the wire queue while the sockets are healthy —
      // every rank holds the same queue, so the drain is symmetric.
      if (pipeline_) reap_wire(/*wait_all=*/true);
      fail_all_and_close(kShutdownMsg);
    }
  }

  // Fail every pending op and close — in ONE critical section, so an
  // enqueue racing the teardown either lands before (and is failed here) or
  // observes closed_ and returns the shutdown error; no handle can slip
  // into the table after the sweep and hang its waiter.
  void fail_all_and_close(const std::string& msg) {
    // Stop the wire thread FIRST: queued WireJobs hold Entry pointers into
    // table_, which the sweep below clears. On the clean path the queue was
    // already drained (process_reply); on error paths the sockets are
    // closed so in-flight ring calls fail promptly instead of hanging on a
    // dead peer. The failed jobs' handles are swept below like any other.
    teardown_wire_thread();
    {
      std::lock_guard<std::mutex> g(mu_);
      for (auto& kv : table_) {
        auto it = handles_.find(kv.second.handle);
        if (it != handles_.end() && it->second.status == 0) {
          it->second.status = 2;
          it->second.error = msg;
        }
      }
      table_.clear();
      queue_.clear();
      bit_pending_.clear();
      closed_ = true;
    }
    handle_cv_.notify_all();
  }

  // ------------------------------------------------------------ data plane

  void execute(const Response& response, bool cache_put, long long seq,
               double reply_at) {
    // Only the allreduce path is pipelined. Everything else (allgather,
    // broadcast, errors) runs serially on this thread and — because it
    // touches the shared ring sockets — must wait for every in-flight
    // wire job first, preserving the serial engine's execution order.
    if (pipeline_ && response.response_type != RESP_ALLREDUCE)
      reap_wire(/*wait_all=*/true);
    if (response.response_type == RESP_ERROR) {
      std::vector<long long> hs;
      {
        std::lock_guard<std::mutex> g(mu_);
        for (const auto& name : response.tensor_names) {
          auto it = table_.find(name);
          if (it == table_.end()) continue;
          auto hit = handles_.find(it->second.handle);
          if (hit != handles_.end()) {
            hit->second.status = 2;
            hit->second.error = response.error_message;
          }
          table_.erase(it);
        }
      }
      handle_cv_.notify_all();
      return;
    }

    // Entries stay in the table until completion; only this thread mutates
    // them after enqueue, so reading outside the lock is safe.
    std::vector<Entry*> entries;
    {
      std::lock_guard<std::mutex> g(mu_);
      for (const auto& name : response.tensor_names)
        entries.push_back(&table_.at(name));
    }
    std::string tname =
        entries.size() == 1
            ? entries[0]->request.tensor_name
            : "fused[" + std::to_string(entries.size()) + "]";
    if (trace_on_.load(std::memory_order_relaxed)) {
      // Retroactive per-tensor spans, now that the fused op's seq is
      // known (python _execute parity): enqueue = user call -> request
      // departure; negotiate = departure -> this reply. Cache-bypass ops
      // never departed — no negotiate span, by design.
      for (Entry* e : entries) {
        double dep = e->sent_at >= 0 ? e->sent_at : reply_at;
        stamp_span(PH_ENQUEUE, e->enqueued_at, dep, seq, 0,
                   e->request.tensor_name.c_str());
        if (e->sent_at >= 0)
          stamp_span(PH_NEGOTIATE, e->sent_at, reply_at, seq, 0,
                     e->request.tensor_name.c_str());
      }
    }
    if (timeline_) timeline_->start(tname, op_name(response.response_type));

    if (pipeline_ && response.response_type == RESP_ALLREDUCE) {
      // Double-buffered path: pack into a free fusion slot and hand the
      // ring call to the wire thread; copy-out, EF residual slices, cache
      // insert and handle completion happen at reap — in FIFO submit
      // order, so results and completion order match the serial engine.
      submit_allreduce(entries, response, cache_put, seq, tname);
      return;
    }

    long long nbytes = 0;
    if (response.response_type == RESP_ALLREDUCE)
      nbytes = execute_allreduce(entries, tname, seq);
    else if (response.response_type == RESP_ALLGATHER)
      nbytes = execute_allgather(*entries[0], response, tname, seq);
    else
      nbytes = execute_broadcast(*entries[0], tname, seq);
    processed_bytes_ += nbytes;
    tensors_total_.fetch_add((long long)entries.size(),
                             std::memory_order_relaxed);
    if (entries.size() > 1)
      fused_tensors_.fetch_add((long long)entries.size(),
                               std::memory_order_relaxed);

    {
      std::lock_guard<std::mutex> g(mu_);
      for (Entry* e : entries) {
        if (cache_put) {
          Response single;
          single.response_type = response.response_type;
          single.tensor_names.push_back(e->request.tensor_name);
          single.tensor_sizes = response.tensor_sizes;
          cache_.put(e->request, single);
        }
        table_.erase(e->request.tensor_name);
      }
    }
    if (timeline_) timeline_->end(tname);
    handle_cv_.notify_all();
  }

  void complete(Entry* e, std::vector<int64_t> shape,
                std::vector<uint8_t> data,
                std::vector<int64_t> tensor_sizes = {}) {
    std::lock_guard<std::mutex> g(mu_);
    auto it = handles_.find(e->handle);
    if (it == handles_.end()) return;
    it->second.status = 1;
    it->second.dtype = e->request.dtype;
    it->second.shape = std::move(shape);
    it->second.data = std::move(data);
    it->second.tensor_sizes = std::move(tensor_sizes);
  }

  // Result already lives in the caller's buffer: no bytes cross the ABI.
  void complete_in_place(Entry* e) {
    std::lock_guard<std::mutex> g(mu_);
    auto it = handles_.find(e->handle);
    if (it == handles_.end()) return;
    it->second.status = 1;
    it->second.dtype = e->request.dtype;
    it->second.shape = e->request.shape;
    it->second.in_place = true;
  }

  // Timeline activity vocabulary mirrors the reference's op-specific names
  // (common.h:30-51): say which data plane actually moved the bytes.
  const char* data_plane_activity(bool hier_enabled) const {
    if (hier_enabled && hier_.shm) return "SHM_CROSS_RING_COLLECTIVE";
    if (hier_enabled && hier_.local_ring) return "HIER_RING_COLLECTIVE";
    return "TCP_COLLECTIVE";
  }

  const char* allreduce_activity() const {
    return data_plane_activity(hier_.allreduce);
  }

  long long execute_allreduce(std::vector<Entry*>& entries,
                              const std::string& tname, long long seq) {
    uint8_t dtype = entries[0]->request.dtype;
    size_t esz = dtype_size(dtype);
    size_t total_bytes = 0;
    for (Entry* e : entries) total_bytes += e->nbytes;
    double t_fuse = mono_s();

    if (entries.size() == 1) {
      // Unfused: reduce in place directly on the caller's buffer — zero
      // staging copies (the reference likewise reduces unfused entries in
      // place, mpi_operations.cc:40-49).
      Entry* e = entries[0];
      if (timeline_) timeline_->activity_start(tname, allreduce_activity());
      double t_exec = mono_s();
      if (size_ > 1) {
        if (hier_.allreduce && (hier_.local_ring || hier_.shm)) {
          // Per-link wire dtypes + residual threading: the hier plane
          // fully writes e->residual (errors or zeros) like the flat one.
          hier_ring_allreduce(e->user, (long)(total_bytes / esz), dtype,
                              e->residual);
        } else if (hvd_ring_allreduce_wire(e->user, (long)(total_bytes / esz),
                                           dtype, 0, wire_dtype_,
                                           e->residual) != 0) {
          throw EngineError(std::string("ring allreduce failed: ") +
                            hvd_ring_last_error());
        }
      } else if (e->residual) {
        std::memset(e->residual, 0, (total_bytes / esz) * sizeof(float));
      }
      double t_done = mono_s();
      if (timeline_) timeline_->activity_end(tname);
      complete_in_place(e);
      observe_exec(t_done - t_exec);
      if (trace_on_.load(std::memory_order_relaxed)) {
        double t_end = mono_s();
        stamp_span(PH_FUSE, t_fuse, t_exec, seq, 1, tname.c_str());
        stamp_span(PH_EXECUTE, t_exec, t_done, seq, 0, tname.c_str());
        stamp_span(PH_DONE, t_done, t_end, seq, 0, tname.c_str());
      }
      return (long long)total_bytes;
    }

    // Fusion buffer (reference FusionBufferManager: one persistent buffer,
    // lazily allocated, fusion_buffer_manager.cc:21-45).
    if (fusion_buffer_.capacity() < total_bytes) {
      if (timeline_) timeline_->activity_start(tname, "INIT_FUSION_BUFFER");
      fusion_buffer_.reserve(std::max(
          total_bytes, (size_t)std::min<long long>(fusion_threshold_,
                                                   64ll << 20)));
      if (timeline_) timeline_->activity_end(tname);
    }
    fusion_buffer_.resize(total_bytes);
    fusion_fill_.store((long long)total_bytes, std::memory_order_relaxed);
    fusion_cap_.store((long long)fusion_buffer_.capacity(),
                      std::memory_order_relaxed);

    if (timeline_) timeline_->activity_start(tname, "MEMCPY_IN_FUSION_BUFFER");
    size_t off = 0;
    for (Entry* e : entries) {
      std::memcpy(fusion_buffer_.data() + off, e->user, e->nbytes);
      off += e->nbytes;
    }
    if (timeline_) {
      timeline_->activity_end(tname);
      timeline_->activity_start(tname, allreduce_activity());
    }
    double t_exec = mono_s();
    // Fused error feedback: the ring records quantization errors for the
    // WHOLE fused buffer into a scratch; each entry's slice is copied out
    // to its own residual after the reduce (entries without one simply
    // drop their slice — uncompensated, like a residual-less caller).
    bool any_residual = false;
    for (Entry* e : entries) any_residual = any_residual || e->residual;
    float* fused_residual = nullptr;
    if (any_residual && dtype == 0 /* DT_F32 */) {
      residual_scratch_.resize(total_bytes / esz);
      fused_residual = residual_scratch_.data();
    }
    if (size_ > 1) {
      if (hier_.allreduce && (hier_.local_ring || hier_.shm)) {
        hier_ring_allreduce(fusion_buffer_.data(),
                            (long)(total_bytes / esz), dtype,
                            fused_residual);
      } else if (hvd_ring_allreduce_wire(fusion_buffer_.data(),
                                         (long)(total_bytes / esz), dtype,
                                         0, wire_dtype_,
                                         fused_residual) != 0) {
        throw EngineError(std::string("ring allreduce failed: ") +
                          hvd_ring_last_error());
      }
    }
    double t_done = mono_s();
    if (timeline_) {
      timeline_->activity_end(tname);
      timeline_->activity_start(tname, "MEMCPY_OUT_FUSION_BUFFER");
    }
    // Unpack straight back into the caller buffers — the old path staged
    // through per-entry vectors plus a ctypes copy on the Python side.
    off = 0;
    for (Entry* e : entries) {
      std::memcpy(e->user, fusion_buffer_.data() + off, e->nbytes);
      if (e->residual) {
        // Both data planes fully write the fused scratch (quantization
        // errors or zeros), so a slice copy is always correct.
        if (fused_residual && size_ > 1)
          std::memcpy(e->residual, fused_residual + off / esz,
                      (e->nbytes / esz) * sizeof(float));
        else
          std::memset(e->residual, 0, (e->nbytes / esz) * sizeof(float));
      }
      off += e->nbytes;
      complete_in_place(e);
    }
    if (timeline_) timeline_->activity_end(tname);
    observe_exec(t_done - t_exec);
    if (trace_on_.load(std::memory_order_relaxed)) {
      double t_end = mono_s();
      stamp_span(PH_FUSE, t_fuse, t_exec, seq, (int)entries.size(),
                 tname.c_str());
      stamp_span(PH_EXECUTE, t_exec, t_done, seq, 0, tname.c_str());
      stamp_span(PH_DONE, t_done, t_end, seq, 0, tname.c_str());
    }
    return (long long)total_bytes;
  }

  // Two-level allreduce: sum inside the node (through /dev/shm when
  // active, TCP local ring otherwise), exchange node sums across the local
  // roots' cross ring, fan back out locally. Each hop applies ITS link's
  // wire dtype (hier_.wire_local / wire_cross) to f32 payloads — the
  // cross hop is the slow inter-node link where int8+EF pays most
  // (docs/wire-compression.md).
  //
  // Residual contract (matches ring_allreduce's): when `residual` is
  // non-null it is FULLY written by this call — each element receives the
  // exact quantization error this rank introduced on whichever hops it
  // quantized (local errors + the root's cross errors), or zero. Summing
  // every rank's residual gives exactly true_sum - computed_sum (local
  // sums are exact or locally compensated; cross errors live on the
  // roots), so the error-feedback telescoping holds through the
  // two-level plane end-to-end.
  void hier_ring_allreduce(void* buf, long count, uint8_t dtype,
                           float* residual) {
    bool f32 = dtype == 0;
    int wl = f32 ? hier_.wire_local : 0;
    int wc = f32 ? hier_.wire_cross : 0;
    bool is_root = hier_.local_rank == 0;
    bool local_q = f32 && wl == 3 /* WIRE_I8 */ && hier_.local_size > 1 &&
                   hier_.local_ring != nullptr;
    bool cross_q = f32 && wc == 3 && hier_.cross_size > 1 && is_root;
    // Cross errors go to the caller's buffer directly when the local hop
    // recorded nothing; when BOTH hops quantize, the cross hop stages
    // through a scratch that is added in (each ring call overwrites its
    // residual buffer, so the two contributions must be summed here).
    float* cross_res = nullptr;
    if (residual) {
      if (cross_q && local_q) {
        hier_residual_scratch_.resize((size_t)count);
        cross_res = hier_residual_scratch_.data();
      } else if (cross_q) {
        cross_res = residual;
      }
      if (!local_q && !cross_q)
        std::memset(residual, 0, (size_t)count * sizeof(float));
    }
    if (hier_.shm) {
      // Local plane is the shared segment: memcpys, no wire, exact sums.
      if (hvd_shm_allreduce_g(hier_.shm, buf, count, dtype) != 0)
        throw EngineError(std::string("shm local allreduce failed: ") +
                          hvd_shm_last_error());
      if (is_root &&
          hvd_ringh_allreduce_wire(hier_.cross_ring, buf, count, dtype, 0,
                                   wc, cross_res) != 0)
        throw EngineError(std::string("cross ring allreduce failed: ") +
                          hvd_ring_last_error());
      if (hvd_shm_broadcast_g(hier_.shm, buf, count, dtype, 0) != 0)
        throw EngineError(std::string("shm local broadcast failed: ") +
                          hvd_shm_last_error());
      return;
    }
    if (hvd_ringh_allreduce_wire(hier_.local_ring, buf, count, dtype, 0, wl,
                                 local_q ? residual : nullptr) != 0)
      throw EngineError(std::string("local ring allreduce failed: ") +
                        hvd_ring_last_error());
    if (is_root &&
        hvd_ringh_allreduce_wire(hier_.cross_ring, buf, count, dtype, 0, wc,
                                 cross_res) != 0)
      throw EngineError(std::string("cross ring allreduce failed: ") +
                        hvd_ring_last_error());
    if (residual && local_q && cross_q)
      for (long i = 0; i < count; i++) residual[i] += cross_res[i];
    if (hvd_ringh_broadcast(hier_.local_ring, buf, count, dtype, 0) != 0)
      throw EngineError(std::string("local ring broadcast failed: ") +
                        hvd_ring_last_error());
  }

  long long execute_allgather(Entry& e, const Response& response,
                              const std::string& tname, long long seq) {
    double t_exec = mono_s();
    uint8_t dtype = e.request.dtype;
    size_t esz = dtype_size(dtype);
    long long trailing = 1;
    for (size_t i = 1; i < e.request.shape.size(); i++)
      trailing *= e.request.shape[i];
    std::vector<long> counts;
    long long total_elems = 0;
    for (int64_t s : response.tensor_sizes) {
      counts.push_back((long)(s * trailing));
      total_elems += s * trailing;
    }
    std::vector<uint8_t> out((size_t)total_elems * esz);
    const char* gather_act = data_plane_activity(hier_.allgather);
    if (timeline_) timeline_->activity_start(tname, gather_act);
    if (size_ > 1) {
      if (hier_.allgather && (hier_.local_ring || hier_.shm)) {
        // Two-level: gather inside the node (shm slots or TCP local ring),
        // local roots exchange node blobs, fan the full result back out
        // (MPIHierarchicalAllgather shape, mpi_operations.cc:179-329 — the
        // shm path IS its MPI_Win_allocate_shared window; contiguous rank
        // grouping makes node order == rank order).
        int ls = hier_.local_size, cr = hier_.cross_rank;
        std::vector<long> local_counts(counts.begin() + (size_t)cr * ls,
                                       counts.begin() + (size_t)(cr + 1) * ls);
        long long local_elems = 0;
        for (long c : local_counts) local_elems += c;
        std::vector<uint8_t> local_out((size_t)local_elems * esz);
        int lrc = hier_.shm
                      ? hvd_shm_allgather_g(hier_.shm, e.user,
                                            local_counts.data(),
                                            local_out.data(), dtype)
                      : hvd_ringh_allgather(hier_.local_ring, e.user,
                                            local_counts.data(),
                                            local_out.data(), dtype);
        if (lrc != 0)
          throw EngineError(std::string("local allgather failed: ") +
                            (hier_.shm ? hvd_shm_last_error()
                                       : hvd_ring_last_error()));
        if (hier_.local_rank == 0) {
          std::vector<long> group_counts(hier_.cross_size, 0);
          for (int g = 0; g < hier_.cross_size; g++)
            for (int i = 0; i < ls; i++)
              group_counts[g] += counts[(size_t)g * ls + i];
          if (hvd_ringh_allgather(hier_.cross_ring, local_out.data(),
                                  group_counts.data(), out.data(),
                                  dtype) != 0)
            throw EngineError(std::string("cross ring allgather failed: ") +
                              hvd_ring_last_error());
        }
        int brc = hier_.shm
                      ? hvd_shm_broadcast_g(hier_.shm, out.data(),
                                            (long)total_elems, dtype, 0)
                      : hvd_ringh_broadcast(hier_.local_ring, out.data(),
                                            (long)total_elems, dtype, 0);
        if (brc != 0)
          throw EngineError(std::string("local broadcast failed: ") +
                            (hier_.shm ? hvd_shm_last_error()
                                       : hvd_ring_last_error()));
      } else if (hvd_ring_allgather(e.user, counts.data(), out.data(),
                                    dtype) != 0) {
        throw EngineError(std::string("ring allgather failed: ") +
                          hvd_ring_last_error());
      }
    } else {
      std::memcpy(out.data(), e.user, e.nbytes);
    }
    if (timeline_) timeline_->activity_end(tname);
    double t_done = mono_s();
    std::vector<int64_t> shape = e.request.shape;
    int64_t dim0 = 0;
    for (int64_t s : response.tensor_sizes) dim0 += s;
    shape[0] = dim0;
    long long nbytes = (long long)out.size();
    complete(&e, std::move(shape), std::move(out), response.tensor_sizes);
    observe_exec(t_done - t_exec);
    trace_exec_done(seq, tname, t_exec, t_done);
    return nbytes;
  }

  long long execute_broadcast(Entry& e, const std::string& tname,
                              long long seq) {
    double t_exec = mono_s();
    size_t esz = dtype_size(e.request.dtype);
    if (timeline_) timeline_->activity_start(tname, "TCP_COLLECTIVE");
    if (size_ > 1) {
      // In place on the caller's buffer: the root sends from it, everyone
      // else receives into it.
      if (hvd_ring_broadcast(e.user, (long)(e.nbytes / esz),
                             e.request.dtype, e.request.root_rank) != 0)
        throw EngineError(std::string("ring broadcast failed: ") +
                          hvd_ring_last_error());
    }
    if (timeline_) timeline_->activity_end(tname);
    double t_done = mono_s();
    complete_in_place(&e);
    observe_exec(t_done - t_exec);
    trace_exec_done(seq, tname, t_exec, t_done);
    return (long long)e.nbytes;
  }

  // execute + done spans for the single-phase ops (allgather/broadcast) —
  // the Python controller's _trace_exec_done shape.
  void trace_exec_done(long long seq, const std::string& op, double t0,
                       double t1) {
    if (!trace_on_.load(std::memory_order_relaxed)) return;
    double t2 = mono_s();
    stamp_span(PH_EXECUTE, t0, t1, seq, 0, op.c_str());
    stamp_span(PH_DONE, t1, t2, seq, 0, op.c_str());
  }

  void observe_exec(double seconds) {
    std::lock_guard<std::mutex> g(tele_mu_);
    exec_hist_.observe(seconds);
  }

  // ------------------------------------------- pipelined data plane (r16)
  //
  // Double-buffered fusion: the engine thread packs fused group N+1 into
  // one FusionSlot and copies group N-1 out of the other while the wire
  // thread keeps group N's ring call moving — the r10 CompressCursor
  // send-ahead pattern lifted one level up, from chunks within a
  // collective to whole fused groups within a cycle. Jobs flow through a
  // strict FIFO (reply order, identical on every rank): the wire thread
  // runs them front-to-back and the engine thread reaps them
  // front-to-back, so ring-call pairing, results, completion order and
  // the EF residual stream are bit-for-bit the serial engine's. The wire
  // thread's residual writes are scoped to its ONE in-flight group; the
  // engine thread slices them out per entry only after the job is done.

  struct FusionSlot {
    std::vector<uint8_t> buf;
    std::vector<float> residual;  // fused EF staging for this slot
    bool busy = false;            // guarded by wire_mu_
  };

  struct WireJob {
    int slot = -1;  // fusion slot index; -1 = in-place single entry
    std::vector<Entry*> entries;
    Response response;  // for cache insertion at reap
    bool cache_put = false;
    long long seq = 0;
    std::string tname;
    uint8_t dtype = 0;
    size_t total_bytes = 0;
    void* wire_buf = nullptr;   // slot buffer or the entry's user buffer
    float* residual = nullptr;  // slot scratch or the entry's residual
    double t_exec = 0, t_done = 0;  // wire window (wire thread)
    bool started = false, done = false;  // guarded by wire_mu_
    std::string error;  // non-empty: the ring call failed
  };

  void wire_loop() {
    std::unique_lock<std::mutex> lk(wire_mu_);
    for (;;) {
      WireJob* job = nullptr;
      wire_cv_.wait(lk, [&] {
        for (auto& j : wire_queue_)
          if (!j->started) return true;
        return wire_stop_;
      });
      for (auto& j : wire_queue_)
        if (!j->started) {
          job = j.get();
          break;
        }
      if (!job) return;  // stop requested and nothing left to run
      job->started = true;
      lk.unlock();
      double t_exec = mono_s();
      try {
        run_wire_job(job);
      } catch (const std::exception& exc) {
        job->error = exc.what();
      }
      double t_done = mono_s();
      job->t_exec = t_exec;
      job->t_done = t_done;
      stamp_span(PH_EXECUTE, t_exec, t_done, job->seq, 0,
                 job->tname.c_str());
      lk.lock();
      job->done = true;
      wire_done_cv_.notify_all();
    }
  }

  // The ring call — the ONLY work the wire thread does. Residual writes
  // target this job's buffers exclusively (the in-flight group), so error
  // feedback telescopes exactly as on the serial path.
  void run_wire_job(WireJob* job) {
    if (test_delay_us_ > 0)
      std::this_thread::sleep_for(
          std::chrono::microseconds(test_delay_us_));
    long count = (long)(job->total_bytes / dtype_size(job->dtype));
    if (size_ > 1) {
      if (hvd_ring_allreduce_wire(job->wire_buf, count, job->dtype, 0,
                                  wire_dtype_, job->residual) != 0)
        throw EngineError(std::string("ring allreduce failed: ") +
                          hvd_ring_last_error());
    } else if (job->residual) {
      std::memset(job->residual, 0, (size_t)count * sizeof(float));
    }
  }

  // Engine thread: pack the group and hand it to the wire thread.
  void submit_allreduce(std::vector<Entry*>& entries,
                        const Response& response, bool cache_put,
                        long long seq, const std::string& tname) {
    uint8_t dtype = entries[0]->request.dtype;
    size_t esz = dtype_size(dtype);
    size_t total_bytes = 0;
    for (Entry* e : entries) total_bytes += e->nbytes;
    double t_fuse = mono_s();

    auto job = std::make_unique<WireJob>();
    job->entries = entries;
    job->response = response;
    job->cache_put = cache_put;
    job->seq = seq;
    job->tname = tname;
    job->dtype = dtype;
    job->total_bytes = total_bytes;

    if (entries.size() == 1) {
      // Unfused: in place on the caller's pinned buffer, no slot burned.
      // The wire thread owns the entry's residual until reap.
      job->wire_buf = entries[0]->user;
      job->residual = size_ > 1 ? entries[0]->residual : nullptr;
    } else {
      int si = acquire_slot();
      FusionSlot& slot = slots_[si];
      if (slot.buf.capacity() < total_bytes) {
        if (timeline_)
          timeline_->activity_start(tname, "INIT_FUSION_BUFFER");
        slot.buf.reserve(std::max(
            total_bytes, (size_t)std::min<long long>(fusion_threshold_,
                                                     64ll << 20)));
        if (timeline_) timeline_->activity_end(tname);
      }
      slot.buf.resize(total_bytes);
      fusion_fill_.store((long long)total_bytes, std::memory_order_relaxed);
      fusion_cap_.store((long long)slot.buf.capacity(),
                        std::memory_order_relaxed);
      if (timeline_)
        timeline_->activity_start(tname, "MEMCPY_IN_FUSION_BUFFER");
      size_t off = 0;
      for (Entry* e : entries) {
        std::memcpy(slot.buf.data() + off, e->user, e->nbytes);
        off += e->nbytes;
      }
      if (timeline_) timeline_->activity_end(tname);
      bool any_residual = false;
      for (Entry* e : entries) any_residual = any_residual || e->residual;
      job->wire_buf = slot.buf.data();
      if (any_residual && dtype == 0 /* DT_F32 */) {
        slot.residual.resize(total_bytes / esz);
        job->residual = slot.residual.data();
      }
      job->slot = si;
    }
    stamp_span(PH_FUSE, t_fuse, mono_s(), seq, (int)entries.size(),
               tname.c_str());
    {
      std::lock_guard<std::mutex> g(wire_mu_);
      wire_queue_.push_back(std::move(job));
      long long depth = (long long)wire_queue_.size();
      if (depth > pipeline_depth_.load(std::memory_order_relaxed))
        pipeline_depth_.store(depth, std::memory_order_relaxed);
    }
    wire_cv_.notify_one();
  }

  // Free fusion slot, reaping opportunistically: with two slots at most
  // two fused groups are outstanding — N on the wire while N+1 packs,
  // because N-1 gets copied out right here.
  int acquire_slot() {
    for (;;) {
      reap_wire(/*wait_all=*/false);
      {
        std::lock_guard<std::mutex> g(wire_mu_);
        for (int i = 0; i < 2; i++)
          if (!slots_[i].busy) {
            slots_[i].busy = true;
            return i;
          }
      }
      // Both slots in flight: block until the oldest job lands (counted
      // as a pipeline stall inside reap_wire's wait).
      reap_wire_front();
    }
  }

  // Reap completed jobs oldest-first. wait_all=true drains the whole
  // queue — required before ANY control-frame I/O, because the wire
  // thread shares the ring sockets. Engine-thread time spent blocked in
  // the wait (beyond `stall_after`, used by rank 0 to exclude its pacing
  // window) is charged to CTR_PIPELINE_STALL_US.
  void reap_wire(bool wait_all, double stall_after = 0.0) {
    for (;;) {
      std::unique_ptr<WireJob> job;
      {
        std::unique_lock<std::mutex> lk(wire_mu_);
        if (wire_queue_.empty()) return;
        if (!wire_queue_.front()->done) {
          if (!wait_all) return;
          double t0 = mono_s();
          wire_done_cv_.wait(
              lk, [&] { return wire_queue_.front()->done; });
          double stalled = mono_s() - std::max(t0, stall_after);
          if (stalled > 0)
            pipeline_stall_us_.fetch_add((long long)(stalled * 1e6),
                                         std::memory_order_relaxed);
        }
        job = std::move(wire_queue_.front());
        wire_queue_.pop_front();
      }
      finish_job(*job);
    }
  }

  // Block until the oldest in-flight job completes and reap it.
  void reap_wire_front() {
    std::unique_ptr<WireJob> job;
    {
      std::unique_lock<std::mutex> lk(wire_mu_);
      if (wire_queue_.empty()) return;
      if (!wire_queue_.front()->done) {
        double t0 = mono_s();
        wire_done_cv_.wait(lk,
                           [&] { return wire_queue_.front()->done; });
        pipeline_stall_us_.fetch_add(
            (long long)((mono_s() - t0) * 1e6),
            std::memory_order_relaxed);
      }
      job = std::move(wire_queue_.front());
      wire_queue_.pop_front();
    }
    finish_job(*job);
  }

  // Reap one job on the engine thread: copy-out + per-entry EF residual
  // slices, cache insert, handle completion, accounting and spans —
  // everything the serial execute_allreduce tail does, in the same order.
  void finish_job(WireJob& job) {
    if (!job.error.empty()) {
      release_slot(job.slot);
      throw EngineError(job.error);
    }
    size_t esz = dtype_size(job.dtype);
    if (job.slot >= 0) {
      FusionSlot& slot = slots_[job.slot];
      if (timeline_)
        timeline_->activity_start(job.tname, "MEMCPY_OUT_FUSION_BUFFER");
      size_t off = 0;
      for (Entry* e : job.entries) {
        std::memcpy(e->user, slot.buf.data() + off, e->nbytes);
        if (e->residual) {
          // Both outcomes fully write the entry's residual: the wire
          // thread's fused scratch slice, or zeros (size-1 / non-f32).
          if (job.residual && size_ > 1)
            std::memcpy(e->residual, job.residual + off / esz,
                        (e->nbytes / esz) * sizeof(float));
          else
            std::memset(e->residual, 0,
                        (e->nbytes / esz) * sizeof(float));
        }
        off += e->nbytes;
      }
      if (timeline_) timeline_->activity_end(job.tname);
      release_slot(job.slot);
    } else if (job.entries.size() == 1 && size_ == 1 &&
               job.entries[0]->residual) {
      std::memset(job.entries[0]->residual, 0,
                  (job.entries[0]->nbytes / esz) * sizeof(float));
    }
    processed_bytes_ += (long long)job.total_bytes;
    tensors_total_.fetch_add((long long)job.entries.size(),
                             std::memory_order_relaxed);
    if (job.entries.size() > 1)
      fused_tensors_.fetch_add((long long)job.entries.size(),
                               std::memory_order_relaxed);
    {
      std::lock_guard<std::mutex> g(mu_);
      for (Entry* e : job.entries) {
        if (job.cache_put) {
          Response single;
          single.response_type = job.response.response_type;
          single.tensor_names.push_back(e->request.tensor_name);
          single.tensor_sizes = job.response.tensor_sizes;
          cache_.put(e->request, single);
        }
        auto it = handles_.find(e->handle);
        if (it != handles_.end()) {
          it->second.status = 1;
          it->second.dtype = e->request.dtype;
          it->second.shape = e->request.shape;
          it->second.in_place = true;
        }
        table_.erase(e->request.tensor_name);
      }
    }
    observe_exec(job.t_done - job.t_exec);
    stamp_span(PH_DONE, job.t_done, mono_s(), job.seq, 0,
               job.tname.c_str());
    if (timeline_) timeline_->end(job.tname);
    handle_cv_.notify_all();
  }

  void release_slot(int si) {
    if (si < 0) return;
    std::lock_guard<std::mutex> g(wire_mu_);
    slots_[si].busy = false;
  }

  // Stop + join the wire thread (idempotent). Queued-but-unstarted jobs
  // still run — on the error path the sockets are closed first so they
  // fail fast instead of hanging on a dead peer; their entries are left
  // for the caller's table sweep.
  void teardown_wire_thread() {
    if (!wire_thread_.joinable()) return;
    bool inflight;
    {
      std::lock_guard<std::mutex> g(wire_mu_);
      wire_stop_ = true;
      inflight = !wire_queue_.empty();
    }
    wire_cv_.notify_all();
    if (inflight && size_ > 1) hvd_ring_shutdown();  // idempotent
    wire_thread_.join();
    std::lock_guard<std::mutex> g(wire_mu_);
    wire_queue_.clear();
    slots_[0].busy = slots_[1].busy = false;
  }

  // ------------------------------------------------------------ members

  int rank_, size_;
  std::atomic<double> cycle_ms_;
  std::atomic<long long> fusion_threshold_;
  bool stall_disable_;
  double stall_warn_s_, stall_shutdown_s_;
  // Wire compression for the flat ring's allreduce data phases (WireDType
  // code from HOROVOD_RING_WIRE_DTYPE via common/config.py; ring.cc only
  // applies it to f32 payloads). The hierarchical plane has its own
  // per-link pair in hier_.wire_local / hier_.wire_cross.
  int wire_dtype_ = 0;
  std::vector<float> residual_scratch_;  // fused-buffer EF staging
  // Cross-hop EF staging when BOTH hier hops quantize (local errors land
  // in the caller's residual, cross errors stage here and are added).
  std::vector<float> hier_residual_scratch_;

  std::mutex mu_;  // guards table_/queue_/handles_/bit_pending_/cache_/closed_
  std::condition_variable handle_cv_;
  std::deque<std::string> queue_;
  std::map<std::string, Entry> table_;
  std::map<long long, HandleSlot> handles_;
  std::map<int, std::string> bit_pending_;
  ResponseCache cache_;
  HierState hier_;  // copied from g_hier at construction
  long long next_handle_ = 0;
  bool closed_ = false;
  bool finished_ = false;
  std::atomic<bool> shutdown_requested_{false};

  // Coordinator-only (reference MessageTable, global_state.h:34).
  std::map<std::string, std::map<int, Request>> message_table_;
  std::map<std::string, double> first_seen_;
  std::map<std::string, double> stall_warned_;

  std::vector<uint8_t> fusion_buffer_;
  std::unique_ptr<Timeline> timeline_;

  std::atomic<long long> cycles_{0};
  std::atomic<long long> processed_bytes_{0};
  std::atomic<long long> busy_us_{0};

  // Telemetry plane (span ring + histograms under tele_mu_; counters are
  // relaxed atomics — always on, a handful of increments per op).
  std::atomic<bool> trace_on_{false};
  std::mutex tele_mu_;  // guards ring_/ring_head_/ring_size_/*_hist_
  std::vector<Span> ring_;
  size_t ring_head_ = 0, ring_size_ = 0;
  TimeHist cycle_hist_, exec_hist_;
  std::atomic<long long> spans_total_{0}, spans_dropped_{0};
  std::atomic<long long> tensors_total_{0}, fused_tensors_{0};
  std::atomic<long long> cache_hits_{0}, cache_misses_{0};
  std::atomic<long long> fusion_fill_{0}, fusion_cap_{0};
  // Synced tuned-bucket slot: push set on rank 0 via the ABI, synced
  // adopted from the cycle reply on every rank.
  std::atomic<long long> bucket_push_{0}, bucket_synced_{0};
  long long next_seq_ = 0;  // coordinator-only: next collective seq id

  // Pipelined data plane (r16). wire_mu_ guards wire_queue_ /
  // wire_stop_ / the slots' busy flags; it is never held across mu_ or
  // tele_mu_ (the static lock graph stays acyclic). Only the engine
  // thread pushes/pops the queue; the wire thread just flips
  // started/done on the front-most unstarted job.
  bool pipeline_ = false;
  long long test_delay_us_ = 0;  // HOROVOD_PIPELINE_TEST_DELAY_US hook
  std::mutex wire_mu_;
  std::condition_variable wire_cv_;       // wakes the wire thread
  std::condition_variable wire_done_cv_;  // wakes the engine thread
  std::deque<std::unique_ptr<WireJob>> wire_queue_;
  bool wire_stop_ = false;
  FusionSlot slots_[2];
  std::atomic<long long> pipeline_depth_{0};     // high-water outstanding
  std::atomic<long long> pipeline_stall_us_{0};  // engine blocked on wire
  std::atomic<long long> priority_jumps_{0};     // reordered cycles
  std::thread wire_thread_;

  std::thread thread_;
};

// Intentionally leaked on shutdown: C-ABI accessors (wait/slot/release) read
// this pointer without a lock from arbitrary Python threads, so destroying
// the Engine while a waiter is inside it would be a use-after-free. Shutdown
// instead joins the background thread and releases the bulk buffers
// (Engine::finish); the husk stays valid so late waiters resolve cleanly.
// The reference keeps its HorovodGlobalState singleton alive for the process
// lifetime the same way (horovod/common/operations.cc:90).
Engine* g_engine = nullptr;
std::mutex g_engine_mu;
std::string g_last_error;
long long g_engine_gen = 0;  // bumped per engine init -> CTR_ENGINE_GEN

}  // namespace
}  // namespace hvd

// ----------------------------------------------------------------- C ABI
// (reference operations.cc:1595-1650 exposes the same lifecycle surface.)

extern "C" {

const char* hvd_eng_last_error() { return hvd::g_last_error.c_str(); }

int hvd_eng_init(int rank, int size, const char* ring_addrs,
                 const uint8_t* secret, int secret_len, double cycle_ms,
                 long long fusion_threshold, int cache_capacity,
                 int stall_disable, double stall_warn_s,
                 double stall_shutdown_s, const char* timeline_path,
                 int timeline_mark_cycles, int wire_dtype,
                 int wire_dtype_local, int wire_dtype_cross, int pipeline) {
  std::lock_guard<std::mutex> g(hvd::g_engine_mu);
  if (hvd::g_engine && !hvd::g_engine->finished()) {
    hvd::g_last_error = "engine already initialized";
    return -1;
  }
  if (size > 1) {
    if (hvd_ring_init(rank, size, ring_addrs, secret, secret_len) != 0) {
      hvd::g_last_error = hvd_ring_last_error();
      return -1;
    }
  }
  // Two-level hierarchical rings (reference HOROVOD_HIERARCHICAL_* flags).
  // Gated exactly like the Python controller: flags on, launcher-exported
  // group addresses present, real two-level topology — the predicate is
  // env-derived so it is identical on every rank.
  hvd::g_hier = hvd::HierState{};
  auto env_true = [](const char* name) {
    // Mirrors the Python config._env_bool exactly: strip, lowercase, and
    // "", "0", "false", "no", "off" are false — both engines must read a
    // documented flag identically.
    const char* v = getenv(name);
    if (!v) return false;
    std::string s(v);
    size_t a = s.find_first_not_of(" \t\r\n");
    if (a == std::string::npos) return false;
    size_t b = s.find_last_not_of(" \t\r\n");
    s = s.substr(a, b - a + 1);
    for (char& c : s) c = (char)tolower((unsigned char)c);
    return s != "" && s != "0" && s != "false" && s != "no" && s != "off";
  };
  auto env_int = [](const char* name, int dflt) {
    const char* v = getenv(name);
    return v && *v ? atoi(v) : dflt;
  };
  const char* local_addrs = getenv("HOROVOD_LOCAL_RING_ADDRS");
  const char* cross_addrs = getenv("HOROVOD_CROSS_RING_ADDRS");
  const char* cpu_ops = getenv("HOROVOD_CPU_OPS");
  hvd::g_hier.allreduce = env_true("HOROVOD_HIERARCHICAL_ALLREDUCE");
  hvd::g_hier.allgather = env_true("HOROVOD_HIERARCHICAL_ALLGATHER");
  hvd::g_hier.local_rank = env_int("HOROVOD_LOCAL_RANK", 0);
  hvd::g_hier.local_size = env_int("HOROVOD_LOCAL_SIZE", 1);
  hvd::g_hier.cross_rank = env_int("HOROVOD_CROSS_RANK", 0);
  hvd::g_hier.cross_size = env_int("HOROVOD_CROSS_SIZE", 1);
  // Per-link wire dtypes ride the ABI (resolved by common/config.py from
  // HOROVOD_RING_WIRE_DTYPE_LOCAL/_CROSS + link-class defaults) so both
  // engines share one resolver; clamp garbage to the untouched stream.
  hvd::g_hier.wire_local =
      (wire_dtype_local >= 0 && wire_dtype_local <= 3) ? wire_dtype_local : 0;
  hvd::g_hier.wire_cross =
      (wire_dtype_cross >= 0 && wire_dtype_cross <= 3) ? wire_dtype_cross : 0;
  if ((hvd::g_hier.allreduce || hvd::g_hier.allgather) && local_addrs &&
      cross_addrs && hvd::g_hier.local_size > 1 &&
      hvd::g_hier.cross_size > 1 && !(cpu_ops && strcmp(cpu_ops, "star") == 0)) {
    // Local plane: /dev/shm by default — same-host bytes move as memcpys
    // through one shared mapping (the reference's MPI_Win_allocate_shared
    // analogue, mpi_operations.cc:216-243) instead of crossing the kernel
    // socket stack twice over loopback. HOROVOD_SHM_DISABLE=1 falls back
    // to the TCP local ring. The choice is env-derived, so it is identical
    // on every local rank — a mixed group would deadlock.
    if (!env_true("HOROVOD_SHM_DISABLE")) {
      // Segment name from the job secret + group id: unique per job, equal
      // across the group's ranks.
      hvd::SHA256 hasher;
      hasher.update(secret, (size_t)secret_len);
      int32_t group = hvd::g_hier.cross_rank;
      hasher.update((const uint8_t*)&group, sizeof(group));
      uint8_t digest[32];
      hasher.finish(digest);
      char name[32] = "/hvd";
      for (int i = 0; i < 8; i++)
        std::snprintf(name + 4 + 2 * i, 3, "%02x", digest[i]);
      long slot = 4 << 20;
      const char* slot_env = getenv("HOROVOD_SHM_SLOT_BYTES");
      if (slot_env && *slot_env && atol(slot_env) > 0) slot = atol(slot_env);
      hvd::g_hier.shm = hvd_shm_create(
          hvd::g_hier.local_rank, hvd::g_hier.local_size, name, slot);
      if (!hvd::g_hier.shm) {
        hvd::g_last_error = std::string("shm local data plane failed (") +
                            hvd_shm_last_error() +
                            "); set HOROVOD_SHM_DISABLE=1 to use the TCP "
                            "local ring";
        return -1;
      }
      // Ring transfers stamp liveness into the shared heartbeat so barrier
      // waiters in OTHER local processes can tell "leader busy on the
      // cross phase" from "rank died" (idle timeout, see shm.cc).
      hvd_ring_set_progress_sink(
          hvd_shm_heartbeat_addr(hvd::g_hier.shm));
    } else {
      hvd::g_hier.local_ring = hvd_ringh_create(
          hvd::g_hier.local_rank, hvd::g_hier.local_size, local_addrs, secret,
          secret_len);
      if (!hvd::g_hier.local_ring) {
        hvd::g_last_error = hvd_ring_last_error();
        return -1;
      }
      hvd_ringh_set_link(hvd::g_hier.local_ring, 1 /* LINK_LOCAL */);
    }
    if (hvd::g_hier.local_rank == 0) {
      hvd::g_hier.cross_ring = hvd_ringh_create(
          hvd::g_hier.cross_rank, hvd::g_hier.cross_size, cross_addrs, secret,
          secret_len);
      if (hvd::g_hier.cross_ring)
        hvd_ringh_set_link(hvd::g_hier.cross_ring, 2 /* LINK_CROSS */);
      if (!hvd::g_hier.cross_ring) {
        hvd::g_last_error = hvd_ring_last_error();
        // Don't leak the half-built pair (its bound listener would make a
        // retry fail with EADDRINUSE forever). Unregister the heartbeat
        // sink BEFORE unmapping the segment it points into — later ring
        // traffic (retry handshakes) must not store through a stale
        // pointer.
        if (hvd::g_hier.local_ring) hvd_ringh_destroy(hvd::g_hier.local_ring);
        if (hvd::g_hier.shm) {
          hvd_ring_set_progress_sink(nullptr);
          hvd_shm_destroy(hvd::g_hier.shm);
        }
        hvd::g_hier = hvd::HierState{};
        return -1;
      }
    }
  } else {
    hvd::g_hier.allreduce = hvd::g_hier.allgather = false;
  }
  // A previous finished engine is leaked deliberately (see g_engine note).
  hvd::g_engine_gen++;
  hvd::g_engine = new hvd::Engine(
      rank, size, cycle_ms, fusion_threshold, cache_capacity,
      stall_disable != 0, stall_warn_s, stall_shutdown_s,
      timeline_path ? timeline_path : "", timeline_mark_cycles != 0,
      wire_dtype, pipeline != 0);
  return 0;
}

long long hvd_eng_enqueue(int op, const char* name, void* data,
                          const long long* shape, int ndim, int dtype,
                          int root_rank, void* residual, int priority) {
  if (!hvd::g_engine) {
    hvd::g_last_error = "engine not initialized";
    return -1;
  }
  return hvd::g_engine->enqueue((uint8_t)op, name, data,
                                (const int64_t*)shape, ndim, (uint8_t)dtype,
                                root_rank, residual, (int32_t)priority);
}

int hvd_eng_poll(long long h) {
  return hvd::g_engine ? hvd::g_engine->poll(h) : -1;
}

int hvd_eng_wait(long long h) {
  return hvd::g_engine ? hvd::g_engine->wait(h) : -1;
}

int hvd_eng_wait_for(long long h, double timeout_s) {
  return hvd::g_engine ? hvd::g_engine->wait_for(h, timeout_s) : -1;
}

int hvd_eng_hier_active() {
  return hvd::g_engine && hvd::g_engine->hier_active() ? 1 : 0;
}

long long hvd_eng_result_nbytes(long long h) {
  auto* s = hvd::g_engine ? hvd::g_engine->slot(h) : nullptr;
  return s ? (long long)s->data.size() : -1;
}

int hvd_eng_result_ndim(long long h) {
  auto* s = hvd::g_engine ? hvd::g_engine->slot(h) : nullptr;
  return s ? (int)s->shape.size() : -1;
}

int hvd_eng_result_dtype(long long h) {
  auto* s = hvd::g_engine ? hvd::g_engine->slot(h) : nullptr;
  return s ? (int)s->dtype : -1;
}

// 1 when the result was written into the caller's enqueue buffer
// (allreduce/broadcast); 0 when it lives in the slot (allgather).
int hvd_eng_result_in_place(long long h) {
  auto* s = hvd::g_engine ? hvd::g_engine->slot(h) : nullptr;
  return s && s->in_place ? 1 : 0;
}

void hvd_eng_result_shape(long long h, long long* out) {
  auto* s = hvd::g_engine ? hvd::g_engine->slot(h) : nullptr;
  if (!s) return;
  for (size_t i = 0; i < s->shape.size(); i++) out[i] = s->shape[i];
}

// Allgather: number of ranks in the negotiated per-rank first-dim list
// (0 for other ops), and the list itself.
int hvd_eng_result_sizes_count(long long h) {
  auto* s = hvd::g_engine ? hvd::g_engine->slot(h) : nullptr;
  return s ? (int)s->tensor_sizes.size() : -1;
}

void hvd_eng_result_sizes(long long h, long long* out) {
  auto* s = hvd::g_engine ? hvd::g_engine->slot(h) : nullptr;
  if (!s) return;
  for (size_t i = 0; i < s->tensor_sizes.size(); i++)
    out[i] = s->tensor_sizes[i];
}

int hvd_eng_result_copy(long long h, void* dst) {
  auto* s = hvd::g_engine ? hvd::g_engine->slot(h) : nullptr;
  if (!s) return -1;
  std::memcpy(dst, s->data.data(), s->data.size());
  return 0;
}

const char* hvd_eng_handle_error(long long h) {
  auto* s = hvd::g_engine ? hvd::g_engine->slot(h) : nullptr;
  return s ? s->error.c_str() : "unknown handle";
}

void hvd_eng_release(long long h) {
  if (hvd::g_engine) hvd::g_engine->release(h);
}

void hvd_eng_set_params(long long fusion_threshold, double cycle_ms) {
  if (hvd::g_engine) hvd::g_engine->set_params(fusion_threshold, cycle_ms);
}

void hvd_eng_get_stats(long long* cycles, long long* bytes, double* busy_s) {
  if (hvd::g_engine)
    hvd::g_engine->get_stats(cycles, bytes, busy_s);
  else {
    *cycles = 0;
    *bytes = 0;
    *busy_s = 0;
  }
}

// 1 when an engine exists in this process (live or finished husk) — lets
// the Python metrics mirror skip processes whose only native use is the
// ring data plane (the Python controller also loads this library).
int hvd_eng_active() { return hvd::g_engine ? 1 : 0; }

// Arm/disarm span tracing. capacity > 0 (re)sizes the span ring (clamped
// to [256, 2^20]; resets it); capacity <= 0 keeps/creates the default.
void hvd_eng_trace_set(int enabled, long long capacity) {
  if (hvd::g_engine) hvd::g_engine->trace_set(enabled != 0, capacity);
}

// Drain up to `max` spans oldest-first into caller-provided parallel
// arrays (`ops` holds fixed `op_stride`-byte NUL-terminated name slots);
// returns the count consumed. Phase codes index trace/tracer.py PHASES.
int hvd_eng_get_spans(long long max, int* phases, long long* seqs,
                      double* t0s, double* t1s, int* tensors, char* ops,
                      int op_stride) {
  if (!hvd::g_engine) return 0;
  return hvd::g_engine->drain_spans(max, phases, seqs, t0s, t1s, tensors,
                                    ops, op_stride);
}

// Cumulative counters + histogram buckets (slot layout: CounterSlot /
// bindings.NATIVE_COUNTER_SCALARS..N_NATIVE_COUNTER_SLOTS). Fills
// min(n, slot count) entries of
// `out`; returns the slot count so callers can size-check. Zeros when no
// engine was ever initialized.
int hvd_eng_get_counters(long long* out, int n) {
  if (hvd::g_engine)
    hvd::g_engine->get_counters(out, n);
  else
    for (int i = 0; i < n && i < hvd::N_COUNTER_SLOTS; i++) out[i] = 0;
  if (n > hvd::CTR_ENGINE_GEN) out[hvd::CTR_ENGINE_GEN] = hvd::g_engine_gen;
  return hvd::N_COUNTER_SLOTS;
}

// Rank 0's tune loop pushes the GP-tuned gradient-bucket size here; the
// value rides the next cycle reply so EVERY rank adopts it together
// (docs/overlap.md — the token slot the r13 sync left open).
void hvd_eng_set_tuned_bucket(long long nbytes) {
  if (hvd::g_engine) hvd::g_engine->set_tuned_bucket(nbytes);
}

// Overhead micro-bench: stamp n spans through the real path under the
// current trace_set state; returns elapsed seconds.
double hvd_eng_span_probe(long long n) {
  return hvd::g_engine ? hvd::g_engine->span_probe(n) : 0.0;
}

int hvd_eng_shutdown() {
  std::lock_guard<std::mutex> g(hvd::g_engine_mu);
  if (!hvd::g_engine) return 0;
  hvd::g_engine->finish();  // join loop + free buffers; husk stays valid
  return 0;
}

}  // extern "C"
