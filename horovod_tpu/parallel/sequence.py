"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

No reference-repo equivalent (SURVEY.md §5: "Long-context — ABSENT"); this is
the rebuild's first-class long-context layer, built directly on the
collective substrate the reference's architecture maps to (the ICI ring that
``NCCLHierarchicalAllreduce`` approximates with NCCL rings is here the
transport for K/V rotation).

* ``ring_attention`` — blockwise attention with K/V shards rotating around
  the mesh axis via ``lax.ppermute`` (one neighbor hop per step, riding ICI),
  accumulating with the online-softmax recurrence. Sequence length scales
  linearly with the number of chips; per-chip memory stays O(S_local).
* ``ulysses_attention`` — all-to-all head/sequence reshard: each chip
  attends over the FULL sequence for 1/N of the heads, then reshards back.
  Cheaper than ring for moderate S (two all-to-alls), requires H % N == 0.

Both are shard_map-tier functions: call them inside
``jax.shard_map`` with the sequence axis sharded over ``axis_name``.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30


def _block_attend(q, k, v, sm_scale, q_pos, k_pos, causal, key_mask):
    """One (Sq_local x Sk_block) attention block in f32: returns
    (unnormalized acc, running max, running sum) contributions. ``q_pos`` /
    ``k_pos`` are the GLOBAL positions of the local rows/keys (vectors), so
    any sequence layout — contiguous or zigzag — uses the same math.
    Grouped K/V heads (Hkv < H) are repeated here — the dense path runs at
    short S where the extra copy is cheap; the flash path routes groups in
    its grid instead."""
    from ..ops.attention import repeat_kv

    k, v = repeat_kv(q, k, v)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * sm_scale
    if key_mask is not None:
        s = jnp.where(key_mask[:, None, None, :], s, NEG_INF)
    if causal:
        s = jnp.where((k_pos[None, :] <= q_pos[:, None])[None, None, :, :],
                      s, NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)  # (b,h,q,1)
    # Guard fully-masked blocks: exp(NEG_INF - NEG_INF) would be 1.
    m_safe = jnp.maximum(m, NEG_INF / 2)
    p = jnp.exp(s - m_safe) * (m > NEG_INF / 2)
    l = jnp.sum(p, axis=-1, keepdims=True)
    acc = jnp.einsum("bhqk,bkhd->bhqd", p, v.astype(jnp.float32))
    return acc, m_safe, l


def zigzag_positions(idx, s_local, axis_size):
    """Global positions held by shard ``idx`` in the zigzag layout: the
    sequence is cut into 2N blocks and shard i holds blocks (i, 2N-1-i), so
    every shard owns an equal share of early AND late positions and causal
    ring steps do balanced work on every device."""
    half = s_local // 2
    lo = idx * half + jnp.arange(half)
    hi = (2 * axis_size - 1 - idx) * half + jnp.arange(half)
    return jnp.concatenate([lo, hi])


def _zigzag_order(axis_size):
    """Block order of the zigzag layout: shard i holds blocks
    (i, 2N-1-i)."""
    order = []
    for i in range(axis_size):
        order += [i, 2 * axis_size - 1 - i]
    return order


def _zigzag_split(x, axis_size, axis):
    n2 = 2 * axis_size
    s = x.shape[axis]
    if s % n2:
        raise ValueError(
            f"zigzag layout needs the sequence ({s}) divisible by "
            f"2*axis_size ({n2})")
    return jnp.split(x, n2, axis=axis)


def zigzag_shard(x, axis_size, axis: int = 1):
    """Reorder a GLOBAL sequence axis into zigzag shard order: after this,
    splitting the axis into ``axis_size`` equal chunks gives each shard its
    (i, 2N-1-i) block pair. Inverse: ``zigzag_unshard``."""
    blocks = _zigzag_split(x, axis_size, axis)
    return jnp.concatenate([blocks[i] for i in _zigzag_order(axis_size)],
                           axis=axis)


def zigzag_unshard(x, axis_size, axis: int = 1):
    """Inverse of ``zigzag_shard``."""
    blocks = _zigzag_split(x, axis_size, axis)
    order = _zigzag_order(axis_size)
    inverse = [0] * len(order)
    for pos, blk in enumerate(order):
        inverse[blk] = pos
    return jnp.concatenate([blocks[inverse[i]] for i in range(len(order))],
                           axis=axis)


def _half_attend(qh, kh, vh, sm_scale, mask, tri):
    """Attention of q rows over one K/V half-block (``tri``: the two blocks
    share a global offset, so causality is the plain within-block triangle).
    Thin wrapper over ``_block_attend`` — one online-softmax kernel, one set
    of fully-masked-row guards."""
    return _block_attend(qh, kh, vh, sm_scale, jnp.arange(qh.shape[1]),
                         jnp.arange(kh.shape[1]), tri, mask)


def _merge_contrib(a, b):
    """Merge two online-softmax contributions for the same q rows."""
    acc_a, m_a, l_a = a
    acc_b, m_b, l_b = b
    m = jnp.maximum(m_a, m_b)
    alpha = jnp.exp(m_a - m)
    beta = jnp.exp(m_b - m)
    return acc_a * alpha + acc_b * beta, m, l_a * alpha + l_b * beta


def _zigzag_causal_cases(q, k, v, key_mask, my_idx, src, attend):
    """Causal zigzag step computing ONLY the allowed half-block products —
    each ring step costs half a dense block on every device (this is where
    the layout's load balancing becomes real FLOPs savings, not masking).
    ``attend(qh, kh, vh, mask_h, tri)`` returns the (acc, m, l)
    contribution of one half-block — the dense and flash paths share this
    case analysis so the load-balancing invariant is encoded once.

    With q halves (block i, block 2N-1-i) and the source's K/V halves
    (block j, block 2N-1-j), causality reduces to three cases:
      j == i: lo x lo triangular; hi x lo full; hi x hi triangular
      j <  i: both q halves attend lo fully (hi keys are all in the future)
      j >  i: only the hi queries attend, over both key halves fully
    """
    b, s_local, hn, d = q.shape
    h = s_local // 2
    qlo, qhi = q[:, :h], q[:, h:]
    klo, khi = k[:, :h], k[:, h:]
    vlo, vhi = v[:, :h], v[:, h:]
    mlo = key_mask[:, :h] if key_mask is not None else None
    mhi = key_mask[:, h:] if key_mask is not None else None

    def none_rows(n):
        return (jnp.zeros((b, hn, n, d), jnp.float32),
                jnp.full((b, hn, n, 1), NEG_INF / 2, jnp.float32),
                jnp.zeros((b, hn, n, 1), jnp.float32))

    def cat(lo, hi):
        return tuple(jnp.concatenate([x, y], axis=2)
                     for x, y in zip(lo, hi))

    def eq_case():
        lo = attend(qlo, klo, vlo, mlo, True)
        hi = _merge_contrib(attend(qhi, klo, vlo, mlo, False),
                            attend(qhi, khi, vhi, mhi, True))
        return cat(lo, hi)

    def lt_case():  # src holds strictly earlier lo block
        return attend(q, klo, vlo, mlo, False)

    def gt_case():  # only hi queries are late enough to see src's keys
        return cat(none_rows(h), attend(qhi, k, v, key_mask, False))

    return lax.cond(src == my_idx, eq_case,
                    lambda: lax.cond(src < my_idx, lt_case, gt_case))


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def _flash_block_pair(q, maskf, k_blk, v_blk, diag_causal, scale):
    """(out, lse) of one ring block via the Pallas kernel, forward AND
    backward: the lse output carries real gradient through the cross-block
    merge, and the Pallas FA-2 backward models it exactly — a cotangent on
    lse is a per-row shift of the delta term (see ``_flash_backward``'s
    ``dlse``), so the backward streams K/V tiles too instead of
    rematerializing the (S_local x S_local) dense block."""
    from ..ops.attention import (
        FLASH_DEFAULT_BLOCK_K,
        FLASH_DEFAULT_BLOCK_Q,
        _auto_interpret,
        _flash_forward,
    )

    return _flash_forward(q, k_blk, v_blk, maskf, diag_causal, scale,
                          FLASH_DEFAULT_BLOCK_Q, FLASH_DEFAULT_BLOCK_K,
                          _auto_interpret())


def _flash_block_pair_fwd(q, maskf, k_blk, v_blk, diag_causal, scale):
    out, lse = _flash_block_pair(q, maskf, k_blk, v_blk, diag_causal, scale)
    return (out, lse), (q, maskf, k_blk, v_blk, out, lse)


def _flash_block_pair_bwd(diag_causal, scale, res, cts):
    from ..ops.attention import (
        FLASH_DEFAULT_BLOCK_K,
        FLASH_DEFAULT_BLOCK_Q,
        _auto_interpret,
        _flash_backward,
    )

    q, maskf, k_blk, v_blk, out, lse = res
    from ..common.config import flash_xla_bwd

    if flash_xla_bwd():
        # Same escape hatch as flash_attention's backward: rematerialize
        # the (out, lse) pair densely and differentiate through XLA
        # (O(S_local^2) memory; trace-time switch).
        def dense_pair(q_, k_, v_):
            pos = jnp.arange(q_.shape[1])
            a, m, l = _block_attend(q_, k_, v_, scale, pos, pos,
                                    diag_causal, maskf)
            l_safe = jnp.maximum(l, 1e-30)
            o = (a / l_safe).transpose(0, 2, 1, 3).astype(q_.dtype)
            lse = (m + jnp.log(l_safe))[..., 0]
            bh, hh, sh = lse.shape
            return o, lse.reshape(bh * hh, 1, sh)

        _, vjp = jax.vjp(dense_pair, q, k_blk, v_blk)
        dq, dk, dv = vjp(cts)
        return dq, None, dk, dv
    do, dlse = cts
    dq, dk, dv = _flash_backward(
        q, k_blk, v_blk, maskf, out, lse, do, diag_causal, scale,
        FLASH_DEFAULT_BLOCK_Q, FLASH_DEFAULT_BLOCK_K, _auto_interpret(),
        dlse=dlse)
    return dq, None, dk, dv


_flash_block_pair.defvjp(_flash_block_pair_fwd, _flash_block_pair_bwd)


def _flash_contrib_triple(qh, kh, vh, mask_h, tri, scale):
    """One block (or zigzag half-block) through the Pallas kernel, as an
    online-softmax contribution triple (acc, m, l) for ``qh``'s rows: the
    normalised (out, lse) pair re-enters the merge as acc=out, m=lse, l=1
    (out_i carries weight exp(lse_i) in the cross-block merge). ``tri``:
    block and queries share a global offset, so causality is the plain
    within-block triangle — exactly the kernel's causal mode."""
    b, _, hn, _ = qh.shape
    if mask_h is None:
        mask_h = jnp.ones((b, kh.shape[1]), bool)
    o, lse = _flash_block_pair(qh, mask_h, kh, vh, tri, scale)
    a = o.transpose(0, 2, 1, 3).astype(jnp.float32)
    m = lse.reshape(b, hn, qh.shape[1])[..., None]
    return a, m, jnp.ones_like(m)


def ring_attention(q, k, v, axis_name: str = "seq", causal: bool = False,
                   sm_scale: Optional[float] = None, key_mask=None,
                   layout: str = "contiguous", use_flash="auto"):
    """Attention over a sequence sharded along ``axis_name``.

    Args (local shards, inside shard_map):
      q, k, v: (B, S_local, H, D); global sequence = concat over the axis in
        rank order. k/v may carry FEWER (grouped) heads — Hkv with
        H % Hkv == 0: since the ring rotates K/V (not Q), GQA cuts the
        per-step ICI bytes to Hkv/H, and the flash inner kernel routes
        query-head groups natively (the dense path repeats locally).
        key_mask: optional (B, S_local) bool for local keys.
      layout: "contiguous" (shard i holds positions [i*S_local, ...)) or
        "zigzag" (shard i holds blocks (i, 2N-1-i) — see ``zigzag_shard``;
        balances causal work across devices, since with contiguous layout
        device N-1 computes every ring step while device 0 is fully masked
        after the first).
      use_flash: run each ring block through the Pallas flash kernel
        instead of materialising the (S_local x S_local) score matrix —
        the per-block (out, lse) pair merges into the online softmax as
        (acc=out, m=lse, l=1); zigzag streams each causal half-block the
        same way. "auto" (default) enables it once the per-KERNEL-CALL
        token count reaches FLASH_AUTO_MIN_SEQ: S_local for contiguous
        (and non-causal zigzag), S_local/2 for causal zigzag, whose
        calls run on half-blocks.
    Returns: (B, S_local, H, D) — attention of local queries over the FULL
      global sequence, in the same layout as the inputs.
    """
    from ..ops.attention import _check_gqa_heads

    _check_gqa_heads(q, k, v, "ring_attention")
    axis_size = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    b, s_local, hn, d = q.shape
    scale = sm_scale if sm_scale is not None else 1.0 / (d ** 0.5)
    if layout not in ("contiguous", "zigzag"):
        raise ValueError(f"unknown ring_attention layout: {layout!r}")
    if layout == "zigzag" and s_local % 2:
        raise ValueError(
            f"zigzag layout needs an even local sequence (got {s_local})")
    if use_flash == "auto":
        from ..ops.attention import FLASH_AUTO_MIN_SEQ

        # Causal zigzag streams HALF-blocks through the kernel, so the
        # dense-vs-flash crossover applies at s_local/2.
        flash_tokens = (s_local // 2 if causal and layout == "zigzag"
                        else s_local)
        use_flash = flash_tokens >= FLASH_AUTO_MIN_SEQ

    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def positions(idx):
        if layout == "zigzag":
            return zigzag_positions(idx, s_local, axis_size)
        return idx * s_local + jnp.arange(s_local)

    q_pos = positions(my_idx)

    def _empty_contrib():
        return (jnp.zeros((b, hn, s_local, d), jnp.float32),
                jnp.full((b, hn, s_local, 1), NEG_INF / 2, jnp.float32),
                jnp.zeros((b, hn, s_local, 1), jnp.float32))

    def flash_half(qh, kh, vh, mh, tri):
        return _flash_contrib_triple(qh, kh, vh, mh, tri, scale)

    def dense_half(qh, kh, vh, mh, tri):
        return _half_attend(qh, kh, vh, scale, mh, tri)

    def contributions(k_blk, v_blk, mask_blk, src):
        if use_flash:
            if not causal:
                return flash_half(q, k_blk, v_blk, mask_blk, False)
            if layout == "zigzag":
                # Same balanced three-case analysis as the dense path,
                # each half-block streamed through the Pallas kernel.
                return _zigzag_causal_cases(q, k_blk, v_blk, mask_blk,
                                            my_idx, src, flash_half)
            # Contiguous causal: past blocks attend fully, the diagonal
            # block is standard intra-block causal, future blocks skip.
            return lax.cond(
                src < my_idx,
                lambda: flash_half(q, k_blk, v_blk, mask_blk, False),
                lambda: lax.cond(
                    src == my_idx,
                    lambda: flash_half(q, k_blk, v_blk, mask_blk, True),
                    _empty_contrib))
        if causal and layout == "zigzag":
            # Only the allowed half-blocks are computed — balanced ~half a
            # dense block per device per step.
            return _zigzag_causal_cases(q, k_blk, v_blk, mask_blk,
                                        my_idx, src, dense_half)
        if causal and layout == "contiguous":
            # Blocks entirely in the future are skipped, not masked: device
            # i computes i+1 of the N steps (zigzag balances this).
            def compute():
                a, bm, bl = _block_attend(q, k_blk, v_blk, scale, q_pos,
                                          positions(src), causal, mask_blk)
                return a, bm, bl

            return lax.cond(src <= my_idx, compute, _empty_contrib)
        a, bm, bl = _block_attend(q, k_blk, v_blk, scale, q_pos,
                                  positions(src), causal, mask_blk)
        return a, bm, bl

    def step(carry, _):
        k_blk, v_blk, mask_blk, src, m, l, acc = carry
        a, bm, bl = contributions(k_blk, v_blk, mask_blk, src)
        m_new = jnp.maximum(m, bm)
        alpha = jnp.exp(m - m_new)
        beta = jnp.exp(bm - m_new)
        l_new = l * alpha + bl * beta
        acc_new = acc * alpha + a * beta
        # Rotate K/V (and mask) to the next neighbor over ICI; the block we
        # receive originated at src-1.
        k_next = lax.ppermute(k_blk, axis_name, perm)
        v_next = lax.ppermute(v_blk, axis_name, perm)
        mask_next = (lax.ppermute(mask_blk, axis_name, perm)
                     if mask_blk is not None else None)
        src_next = (src - 1) % axis_size
        return (k_next, v_next, mask_next, src_next, m_new, l_new, acc_new), None

    m0 = jnp.full((b, hn, s_local, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, hn, s_local, 1), jnp.float32)
    acc0 = jnp.zeros((b, hn, s_local, d), jnp.float32)
    carry = (k, v, key_mask, my_idx, m0, l0, acc0)
    (_, _, _, _, m, l, acc), _ = lax.scan(step, carry, None, length=axis_size)

    out = acc / jnp.maximum(l, 1e-30)  # zeros for fully-masked rows
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


def ulysses_attention(q, k, v, axis_name: str = "seq", causal: bool = False,
                      sm_scale: Optional[float] = None, attention_fn=None):
    """All-to-all sequence parallelism (DeepSpeed-Ulysses pattern): reshard
    (B, S_local, H, D) -> (B, S_global, H_local, D), attend over the full
    sequence with 1/N of the heads, reshard back. Two ``lax.all_to_all``s on
    ICI replace N-1 ring hops."""
    from ..ops.attention import _check_gqa_heads

    axis_size = lax.psum(1, axis_name)
    hn = q.shape[2]
    # GQA invariants up front (v heads == k heads, H % Hkv == 0): a bad v
    # shape would otherwise surface later as a confusing inner-attention
    # or collective error.
    _check_gqa_heads(q, k, v, "ulysses_attention")
    if hn % axis_size or k.shape[2] % axis_size:
        raise ValueError(
            f"ulysses_attention: query heads ({hn}) and K/V heads "
            f"({k.shape[2]}) must both divide by axis size ({axis_size}); "
            "use ring_attention instead")

    def scatter_heads(x):
        # (B, S_local, H, D) -> (B, S_global, H/N, D)
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    def gather_heads(x):
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    qg, kg, vg = scatter_heads(q), scatter_heads(k), scatter_heads(v)
    if attention_fn is None:
        # make_attention_fn's auto selection: the resharded arrays hold
        # the FULL sequence, so long-context calls hit the Pallas kernel
        # and short ones the plain XLA path.
        from ..ops.attention import make_attention_fn

        attention_fn = make_attention_fn(causal=causal, sm_scale=sm_scale)
    return gather_heads(attention_fn(qg, kg, vg, None))
