"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

No reference-repo equivalent (SURVEY.md §5: "Long-context — ABSENT"); this is
the rebuild's first-class long-context layer, built directly on the
collective substrate the reference's architecture maps to (the ICI ring that
``NCCLHierarchicalAllreduce`` approximates with NCCL rings is here the
transport for K/V rotation).

* ``ring_attention`` — blockwise attention with K/V shards rotating around
  the mesh axis via ``lax.ppermute`` (one neighbor hop per step, riding ICI),
  accumulating with the online-softmax recurrence. Sequence length scales
  linearly with the number of chips; per-chip memory stays O(S_local).
* ``ulysses_attention`` — all-to-all head/sequence reshard: each chip
  attends over the FULL sequence for 1/N of the heads, then reshards back.
  Cheaper than ring for moderate S (two all-to-alls), requires H % N == 0.

Both are shard_map-tier functions: call them inside
``jax.shard_map`` with the sequence axis sharded over ``axis_name``.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30


def _block_attend(q, k, v, sm_scale, q_off, k_off, causal, key_mask):
    """One (Sq_local x Sk_block) attention block in f32: returns
    (unnormalized acc, running max, running sum) contributions."""
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * sm_scale
    if key_mask is not None:
        s = jnp.where(key_mask[:, None, None, :], s, NEG_INF)
    if causal:
        qi = q_off + jnp.arange(q.shape[1])[:, None]
        ki = k_off + jnp.arange(k.shape[1])[None, :]
        s = jnp.where((ki <= qi)[None, None, :, :], s, NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)  # (b,h,q,1)
    # Guard fully-masked blocks: exp(NEG_INF - NEG_INF) would be 1.
    m_safe = jnp.maximum(m, NEG_INF / 2)
    p = jnp.exp(s - m_safe) * (m > NEG_INF / 2)
    l = jnp.sum(p, axis=-1, keepdims=True)
    acc = jnp.einsum("bhqk,bkhd->bhqd", p, v.astype(jnp.float32))
    return acc, m_safe, l


def ring_attention(q, k, v, axis_name: str = "seq", causal: bool = False,
                   sm_scale: Optional[float] = None, key_mask=None):
    """Attention over a sequence sharded along ``axis_name``.

    Args (local shards, inside shard_map):
      q, k, v: (B, S_local, H, D); global sequence = concat over the axis in
        rank order. key_mask: optional (B, S_local) bool for local keys.
    Returns: (B, S_local, H, D) — attention of local queries over the FULL
      global sequence.
    """
    axis_size = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    b, s_local, hn, d = q.shape
    scale = sm_scale if sm_scale is not None else 1.0 / (d ** 0.5)

    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    q_off = my_idx * s_local

    def step(carry, _):
        k_blk, v_blk, mask_blk, src, m, l, acc = carry
        k_off = src * s_local
        a, bm, bl = _block_attend(q, k_blk, v_blk, scale, q_off, k_off,
                                  causal, mask_blk)
        m_new = jnp.maximum(m, bm)
        alpha = jnp.exp(m - m_new)
        beta = jnp.exp(bm - m_new)
        l_new = l * alpha + bl * beta
        acc_new = acc * alpha + a * beta
        # Rotate K/V (and mask) to the next neighbor over ICI; the block we
        # receive originated at src-1.
        k_next = lax.ppermute(k_blk, axis_name, perm)
        v_next = lax.ppermute(v_blk, axis_name, perm)
        mask_next = (lax.ppermute(mask_blk, axis_name, perm)
                     if mask_blk is not None else None)
        src_next = (src - 1) % axis_size
        return (k_next, v_next, mask_next, src_next, m_new, l_new, acc_new), None

    m0 = jnp.full((b, hn, s_local, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, hn, s_local, 1), jnp.float32)
    acc0 = jnp.zeros((b, hn, s_local, d), jnp.float32)
    carry = (k, v, key_mask, my_idx, m0, l0, acc0)
    (_, _, _, _, m, l, acc), _ = lax.scan(step, carry, None, length=axis_size)

    out = acc / jnp.maximum(l, 1e-30)  # zeros for fully-masked rows
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


def ulysses_attention(q, k, v, axis_name: str = "seq", causal: bool = False,
                      sm_scale: Optional[float] = None, attention_fn=None):
    """All-to-all sequence parallelism (DeepSpeed-Ulysses pattern): reshard
    (B, S_local, H, D) -> (B, S_global, H_local, D), attend over the full
    sequence with 1/N of the heads, reshard back. Two ``lax.all_to_all``s on
    ICI replace N-1 ring hops."""
    axis_size = lax.psum(1, axis_name)
    hn = q.shape[2]
    if hn % axis_size:
        raise ValueError(
            f"ulysses_attention: heads ({hn}) must divide by axis size "
            f"({axis_size}); use ring_attention instead")

    def scatter_heads(x):
        # (B, S_local, H, D) -> (B, S_global, H/N, D)
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    def gather_heads(x):
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    qg, kg, vg = scatter_heads(q), scatter_heads(k), scatter_heads(v)
    if attention_fn is None:
        from ..ops.attention import reference_attention

        out = reference_attention(qg, kg, vg, causal=causal,
                                  sm_scale=sm_scale)
    else:
        out = attention_fn(qg, kg, vg, None)
    return gather_heads(out)
