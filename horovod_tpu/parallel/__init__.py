"""Parallelism substrate: device meshes, shardings, and (TPU extensions)
sequence/context parallelism.

The reference implements data parallelism only (SURVEY.md §2.3); the mesh
utilities here are its substrate plus the axes future strategies hang off."""

from . import hierarchical, moe, pipeline, sequence  # noqa: F401
from .moe import moe_apply, moe_apply_dense, switch_aux_loss  # noqa: F401
from .hierarchical import (  # noqa: F401
    hierarchical_allgather,
    hierarchical_allreduce,
)
from .pipeline import (  # noqa: F401
    pipeline_1f1b,
    collect_from_last_stage,
    pipeline_apply,
    pipeline_loss,
    stack_stage_params,
)
from .mesh import (  # noqa: F401
    DATA_AXIS,
    common_mesh,
    make_mesh,
    make_multislice_mesh,
    mesh,
    set_mesh,
    reset_mesh,
    data_sharding,
    replicated_sharding,
    shard_batch,
    sharding_axes,
    replicate,
)
