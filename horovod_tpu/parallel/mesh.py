"""Device-mesh utilities — the TPU-native substrate for data parallelism.

The reference's unit of parallelism is the *process* (one per GPU), with NCCL
rings built at runtime (``horovod/common/ops/nccl_operations.cc:111-153``). On
TPU the unit is the *chip* on a ``jax.sharding.Mesh``: XLA lowers collectives
onto ICI rings/tori automatically from sharding annotations, so "building the
ring" is replaced by "choosing the mesh".

The reference only implements data parallelism (SURVEY.md §2.3), so the default
mesh is 1-D over every chip with axis name ``"data"``. The helpers accept
arbitrary extra axes (``model``, ``seq``, ...) because the same substrate
carries TP/SP — see ``horovod_tpu.parallel`` extensions.
"""

from __future__ import annotations

import threading
from typing import Mapping, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

DATA_AXIS = "data"

_lock = threading.Lock()
_global_mesh: Optional[Mesh] = None


def axis_size(axis_name: str) -> int:
    """Size of a mesh axis, callable inside ``shard_map``/``pmap``."""
    return jax.lax.psum(1, axis_name)


def make_mesh(
    axes: Optional[Mapping[str, int]] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a mesh. Default: 1-D ``("data",)`` over all visible devices.

    ``axes`` maps axis name -> size; one axis may be -1 (inferred). Axis order
    matters on hardware: earlier axes change slowest, and XLA maps the
    trailing axes onto the densest ICI dimension, so put the
    highest-bandwidth-demand axis (e.g. ``model``) last.
    """
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    n = len(devices)
    if not axes:
        axes = {DATA_AXIS: n}
    names = tuple(axes.keys())
    sizes = [int(s) for s in axes.values()]
    if sizes.count(-1) > 1:
        raise ValueError("at most one mesh axis may be -1")
    if -1 in sizes:
        known = int(np.prod([s for s in sizes if s != -1])) or 1
        if n % known:
            raise ValueError(f"cannot infer axis: {n} devices not divisible by {known}")
        sizes[sizes.index(-1)] = n // known
    if int(np.prod(sizes)) != n:
        raise ValueError(f"mesh axes {dict(zip(names, sizes))} != {n} devices")
    arr = np.array(devices).reshape(sizes)
    return Mesh(arr, names)


def make_multislice_mesh(
    n_slices: Optional[int] = None,
    dcn_axis: str = "dcn",
    ici_axis: str = "ici",
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """A 2-D ``(dcn, ici)`` mesh for multi-slice topologies: the outer axis
    crosses slice boundaries (slow DCN), the inner axis stays within a
    slice (fast ICI) — feed it to
    ``hierarchical_allreduce(inner_axis=ici_axis, outer_axis=dcn_axis)``
    (the ``NCCLHierarchicalAllreduce`` analogue; see docs/running.md).

    On a real multi-slice runtime the grouping comes from each device's
    ``slice_index``; elsewhere (virtual CPU devices, single slice split
    for testing) pass ``n_slices`` to group contiguously.
    """
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    n = len(devices)
    slice_ids = {getattr(d, "slice_index", None) for d in devices}
    if None not in slice_ids and len(slice_ids) > 1:
        by_slice = {}
        for d in devices:
            by_slice.setdefault(d.slice_index, []).append(d)
        per = {len(v) for v in by_slice.values()}
        if len(per) != 1:
            raise ValueError(
                f"unequal slice sizes {sorted(per)}: cannot build a "
                "rectangular (dcn, ici) mesh")
        if n_slices is not None and n_slices != len(by_slice):
            raise ValueError(
                f"n_slices={n_slices} but the runtime reports "
                f"{len(by_slice)} slices")
        arr = np.array([by_slice[s] for s in sorted(by_slice)])
        return Mesh(arr, (dcn_axis, ici_axis))
    if n_slices is None:
        raise ValueError(
            "n_slices is required to split these devices: they form a "
            "single slice or carry no slice_index (virtual platforms)")
    if n % n_slices:
        raise ValueError(f"{n} devices not divisible by {n_slices} slices")
    arr = np.array(devices).reshape(n_slices, n // n_slices)
    return Mesh(arr, (dcn_axis, ici_axis))


def mesh() -> Mesh:
    """The process-global mesh, lazily a 1-D data mesh over all devices."""
    global _global_mesh
    with _lock:
        if _global_mesh is None:
            _global_mesh = make_mesh()
        return _global_mesh


def set_mesh(m: Mesh) -> None:
    global _global_mesh
    with _lock:
        _global_mesh = m


def reset_mesh() -> None:
    global _global_mesh
    with _lock:
        _global_mesh = None


def sharding_axes(x) -> Optional[tuple]:
    """Per-dimension mesh-axis names of an array placed with a
    ``NamedSharding``: a tuple of axis-name tuples, one per dim (``()``
    = that dim is replicated). Returns ``None`` when the value carries
    no ``NamedSharding`` (host numpy, tracers, other sharding types) —
    callers treat that as "unknown", not "replicated".

    The decode-path classifier (``models.llama``) uses this to recognize
    the Megatron TP pattern (heads sharded on exactly one axis) without
    hard-coding axis names."""
    sh = getattr(x, "sharding", None)
    spec = getattr(sh, "spec", None)
    if spec is None or not isinstance(sh, NamedSharding):
        return None
    ndim = getattr(x, "ndim", None)
    if ndim is None:
        return None
    out = []
    for i in range(ndim):
        entry = spec[i] if i < len(spec) else None
        if entry is None:
            out.append(())
        elif isinstance(entry, str):
            out.append((entry,))
        else:
            out.append(tuple(entry))
    return tuple(out)


def common_mesh(tree) -> Optional[Mesh]:
    """The single ``Mesh`` shared by every ``NamedSharding`` leaf of
    ``tree``; ``None`` when no leaf carries one OR the leaves disagree
    (mixed meshes are "exotic" to every consumer of this helper)."""
    found = None
    for leaf in jax.tree_util.tree_leaves(tree):
        sh = getattr(leaf, "sharding", None)
        if not isinstance(sh, NamedSharding):
            continue
        if found is None:
            found = sh.mesh
        elif sh.mesh != found:
            return None
    return found


def data_sharding(m: Optional[Mesh] = None, *dims_after_batch: Optional[str]) -> NamedSharding:
    """Sharding for a batch: leading dim split over every mesh axis named
    ``data``-like; remaining dims follow ``dims_after_batch`` (default
    replicated)."""
    m = m or mesh()
    return NamedSharding(m, PartitionSpec(DATA_AXIS, *dims_after_batch))


def replicated_sharding(m: Optional[Mesh] = None) -> NamedSharding:
    m = m or mesh()
    return NamedSharding(m, PartitionSpec())


def shard_batch(tree, m: Optional[Mesh] = None):
    """Place a host pytree on the mesh, batch dim split along ``data``.

    TPU-native replacement for the reference pattern of each process loading
    its own shard (``examples/tensorflow_mnist.py`` dataset sharding by rank):
    one controller process places the global batch; XLA scatters it.
    """
    m = m or mesh()
    sh = NamedSharding(m, PartitionSpec(DATA_AXIS))
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, sh), tree)


def replicate(tree, m: Optional[Mesh] = None):
    """Replicate a pytree (params/optimizer state) across the mesh."""
    m = m or mesh()
    sh = NamedSharding(m, PartitionSpec())
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, sh), tree)
