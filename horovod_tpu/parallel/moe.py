"""Expert parallelism: mixture-of-experts dispatch over a mesh axis.

The reference has no MoE (2019 CNN-era, SURVEY.md §2.3); this is a TPU
extension on the same substrate: experts live along an ``"expert"`` mesh
axis, and token dispatch/return ride ``jax.lax.all_to_all`` over ICI — the
canonical TPU MoE layout (GShard/Switch): tokens are packed into
``[experts, capacity, d_model]`` buffers by index-based routing — int32
cumsum capacity slots (``_route``) and gather-only row permutations
whose custom_vjps route the transposes through the inverse
slot→assignment map (``_pack_rows``/``_combine_rows``; the one-hot mask
einsums this replaces cost more FLOPs than the experts at LM scale, and
autodiff's scatter-add transposes cost ~2.3x a gather on TPU) —
exchanged all-to-all so each device holds its expert's tokens from
every peer, transformed, and exchanged back.

Routing is top-k with capacity dropping (Switch for ``k=1``, GShard for
``k=2``): per expert at most ``capacity = ceil(k*T/E * capacity_factor)``
assignments survive (scaled by ``k`` because top-k routing emits ``k*T``
assignments in total); overflow tokens pass through with zero expert
output (the standard residual-passthrough convention). The Switch load-balancing
auxiliary loss is returned alongside the output.
"""

from __future__ import annotations

from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp

from .mesh import axis_size


def switch_aux_loss(probs: jax.Array, expert_mask: jax.Array) -> jax.Array:
    """Load-balancing loss (Switch Transformer eq. 4): E * sum_e
    fraction_of_tokens(e) * mean_router_prob(e). Minimised at uniform
    routing, where it equals 1. Accumulated in float32: a bf16 mean over
    many tokens would round the fractions."""
    num_experts = probs.shape[-1]
    fraction = expert_mask.astype(jnp.float32).mean(axis=0)
    mean_prob = probs.astype(jnp.float32).mean(axis=0)
    return num_experts * jnp.sum(fraction * mean_prob)


class _Routing:
    """Index bundle from :func:`_route` (per round, all int32/f-dtype
    lists of length ``num_selected``): each round's per-token expert id,
    capacity slot (``>= capacity`` == dropped) and combine weight."""

    def __init__(self, expert_idx, slot, combine_w):
        self.expert_idx = expert_idx    # k x [T]
        self.slot = slot                # k x [T]
        self.combine_w = combine_w      # k x [T]


def _route(probs: jax.Array, capacity: int, num_selected: int,
           normalize_gates: bool, dtype
           ) -> Tuple[_Routing, jax.Array]:
    """Top-k routing with capacity dropping — index-based (round 3).

    The round-2 implementation built one-hot ``[T, E, C]`` dispatch/combine
    masks and moved tokens with ``tec,td->ecd`` einsums; at LM scale that
    matmul costs ~2.6x the expert FLOPs themselves (T x (E*C) x D) and
    capped MoE MFU at ~23%. This version keeps the cheap part of that
    scheme — each round's capacity slot from an int32 cumsum over the
    [T, E] one-hot, filling in (round, token) order with a cross-round
    carry — and replaces the einsums with gather-only row permutations
    (``_pack_to_experts``/``_gather_from_experts`` via ``_pack_rows``/
    ``_combine_rows``): O(T*D + E*C*D) memory traffic, no O(T*E*C)
    anything, and no argsort (measured slower than the cumsum on the
    v5e vector unit).

    Routing decisions (argmax, gates) are computed from f32 probs;
    combine weights drop to ``dtype`` at the end so y doesn't silently
    promote bf16 streams. Returns ``(routing, aux)``.
    """
    tokens, num_experts = probs.shape
    choices, slots, gates = [], [], []
    avail = jnp.ones_like(probs)          # experts still choosable per token
    total_mask = jnp.zeros_like(probs)
    # Tokens already assigned per expert (slots fill in round-major,
    # token-ascending order; int32 — a bf16 cumsum cannot count past 256).
    fill = jnp.zeros((num_experts,), jnp.int32)
    for _ in range(num_selected):
        masked = jnp.where(avail > 0, probs, -jnp.inf)
        choice = jnp.argmax(masked, axis=-1)              # [T]
        gate = jnp.take_along_axis(probs, choice[:, None], axis=-1)[:, 0]
        onehot_i = jax.nn.one_hot(choice, num_experts, dtype=jnp.int32)
        pos = jnp.cumsum(onehot_i, axis=0) - 1 + fill[None, :]  # [T, E]
        slot = jnp.sum(pos * onehot_i, axis=-1)           # [T]
        fill = fill + jnp.sum(
            onehot_i * (slot < capacity)[:, None], axis=0)
        avail = avail * (1 - onehot_i).astype(probs.dtype)
        total_mask = total_mask + onehot_i.astype(probs.dtype)
        choices.append(choice)
        slots.append(slot)
        gates.append(gate)

    if normalize_gates and num_selected > 1:
        # GShard convention: the selected gates are renormalised to sum to
        # 1 per token (over ALL k choices, dropped or not).
        denom = jnp.maximum(sum(gates), 1e-9)
        gates = [g / denom for g in gates]
    combine_w = [
        jnp.where(s < capacity, g, 0.0).astype(dtype)
        for s, g in zip(slots, gates)
    ]

    aux = switch_aux_loss(probs, total_mask / num_selected)
    return _Routing(choices, slots, combine_w), aux


# ---------------------------------------------------------------------------
# Gather-only permutation (round 3): dispatch/combine and BOTH their
# transposes run as row gathers. XLA's autodiff of a gather emits a
# scatter-add, and TPU row scatters cost ~2.3x a gather (chip microbench
# in artifacts/moe_dispatch_r3.json) — but a capacity slot is owned by at
# most ONE assignment, so every transpose is itself a gather through the
# inverse slot->assignment map. The custom_vjps below encode that.


def _routing_indices(routing: _Routing, num_experts: int, capacity: int,
                     tokens: int):
    """Stacked per-round destination indices plus the inverse map.

    ``dests/keeps [k, T]``: each assignment's flat buffer slot (clamped
    when dropped) and liveness. ``inv_token/inv_round/inv_valid [E*C]``:
    which (round, token) assignment owns each buffer slot. Building the
    inverse IS a scatter, but of int32 scalars (k*T * 4 bytes), not of
    D-wide rows — the 768x-smaller payload is the whole trick. Dropped
    assignments get the out-of-range flat index ec and fall out via
    ``mode="drop"`` (clamping would corrupt a neighbouring expert's
    slot 0); kept slots are unique by construction (the cumsum carry
    counts kept assignments only), so ``.set`` cannot collide."""
    ec = num_experts * capacity
    dests, keeps = [], []
    inv = jnp.full((ec,), -1, jnp.int32)
    for r, (e_idx, slot) in enumerate(zip(routing.expert_idx,
                                          routing.slot)):
        keep = slot < capacity
        flat = jnp.where(keep, e_idx * capacity + slot, ec)
        ids = (r * tokens
               + jax.lax.iota(jnp.int32, tokens))
        inv = inv.at[flat].set(ids, mode="drop")
        dests.append(jnp.where(keep, flat, 0))
        keeps.append(keep)
    inv_valid = inv >= 0
    safe_inv = jnp.where(inv_valid, inv, 0)
    return (jnp.stack(dests), jnp.stack(keeps),
            safe_inv % tokens, safe_inv // tokens, inv_valid)


@jax.custom_vjp
def _pack_rows(x, inv_token, inv_valid, dests, keeps):
    """[T, D] token rows -> [E*C, D] buffer rows (zeros in unowned
    slots): a single gather through the inverse map."""
    return jnp.where(inv_valid[:, None], x[inv_token], 0)


def _pack_rows_fwd(x, inv_token, inv_valid, dests, keeps):
    return _pack_rows(x, inv_token, inv_valid, dests, keeps), (dests, keeps)


def _pack_rows_bwd(res, g):
    dests, keeps = res
    # dx[t] = sum over the <=k slots that read token t — per-round
    # gathers, NOT the scatter-add autodiff would emit.
    dx = None
    for r in range(dests.shape[0]):
        term = jnp.where(keeps[r][:, None], g[dests[r]], 0)
        dx = term if dx is None else dx + term
    return dx, None, None, None, None


_pack_rows.defvjp(_pack_rows_fwd, _pack_rows_bwd)


@jax.custom_vjp
def _combine_rows(out_flat, w, dests, keeps, inv_token, inv_round,
                  inv_valid):
    """Gate-weighted combine: y[t] = sum_r w[r,t] * out_flat[dests[r,t]]
    (dropped assignments carry weight 0 already)."""
    y = None
    for r in range(dests.shape[0]):
        term = out_flat[dests[r]] * w[r][:, None]
        y = term if y is None else y + term
    return y


def _combine_fwd(out_flat, w, dests, keeps, inv_token, inv_round,
                 inv_valid):
    y = _combine_rows(out_flat, w, dests, keeps, inv_token, inv_round,
                      inv_valid)
    return y, (out_flat, w, dests, keeps, inv_token, inv_round, inv_valid)


def _combine_bwd(res, dy):
    out_flat, w, dests, keeps, inv_token, inv_round, inv_valid = res
    # d_out[ec] = w of the assignment owning the slot * dy of its token —
    # one gather through the inverse map (the scatter-free transpose).
    w_at_slot = w[inv_round, inv_token]                  # [E*C]
    dout = jnp.where(inv_valid[:, None],
                     dy[inv_token] * w_at_slot[:, None], 0)
    # dw[r, t] = <dy[t], out_flat[dests[r, t]]> for kept assignments —
    # recomputes the forward gather instead of carrying [k, T, D]
    # residuals (memory-flat; gathers are the cheap primitive here).
    dw = jnp.stack([
        jnp.where(keeps[r],
                  jnp.sum(dy * out_flat[dests[r]].astype(dy.dtype), -1),
                  0).astype(w.dtype)
        for r in range(dests.shape[0])
    ])
    return dout.astype(out_flat.dtype), dw, None, None, None, None, None


_combine_rows.defvjp(_combine_fwd, _combine_bwd)


def _pack_to_experts(x: jax.Array, idx, num_experts: int,
                     capacity: int) -> jax.Array:
    dests, keeps, inv_token, inv_round, inv_valid = idx
    buf = _pack_rows(x, inv_token, inv_valid, dests, keeps)
    return buf.reshape(num_experts, capacity, x.shape[1])


def _gather_from_experts(expert_out: jax.Array, routing: _Routing,
                         idx) -> jax.Array:
    num_experts, capacity, d = expert_out.shape
    dests, keeps, inv_token, inv_round, inv_valid = idx
    w = jnp.stack(routing.combine_w)                     # [k, T]
    return _combine_rows(expert_out.reshape(num_experts * capacity, d),
                         w, dests, keeps, inv_token, inv_round, inv_valid)


def _capacity(tokens: int, num_experts: int, capacity_factor: float,
              num_selected: int) -> int:
    # GShard top-k convention: top-k routing emits k*T assignments, so
    # capacity provisions k*T/E * factor slots per expert — otherwise even
    # perfectly uniform top-2 routing would capacity-drop ~37% of
    # assignments at the default capacity_factor of 1.25.
    return max(int(-(-tokens * num_selected * capacity_factor
                     // num_experts)),
               num_selected)


def moe_apply(expert_fn: Callable[[Any, jax.Array], jax.Array],
              expert_params: Any,
              x: jax.Array,
              gate_logits: jax.Array,
              axis_name: str = "expert",
              capacity_factor: float = 1.25,
              num_selected: int = 1,
              normalize_gates: bool = True) -> Tuple[jax.Array, jax.Array]:
    """Run an MoE layer. MUST be called inside ``shard_map`` with
    ``expert_params`` sharded over ``axis_name`` (leading expert axis, one
    expert per device) and ``x``/``gate_logits`` carrying this device's
    tokens (``[T, D]`` / ``[T, E]``).

    Returns ``(y, aux_loss)``: ``y[T, D]`` is the gate-weighted expert
    output per token (zero for capacity-dropped tokens — add the residual
    outside), ``aux_loss`` the local Switch balancing loss (pmean it with
    the data loss).
    """
    num_experts = axis_size(axis_name)
    tokens, d_model = x.shape
    capacity = _capacity(tokens, num_experts, capacity_factor, num_selected)

    probs = jax.nn.softmax(gate_logits, axis=-1)  # [T, E]
    routing, aux = _route(
        probs, capacity, num_selected, normalize_gates, x.dtype)
    idx = _routing_indices(routing, num_experts, capacity, tokens)

    # Pack assignment rows into [E, C, D]; all-to-all so each device
    # receives its expert's buffer from every peer: [E_src, C, D].
    expert_in = _pack_to_experts(x, idx, num_experts, capacity)
    expert_in = jax.lax.all_to_all(expert_in, axis_name,
                                   split_axis=0, concat_axis=0)
    local_params = jax.tree.map(lambda a: jnp.squeeze(a, axis=0),
                                expert_params)
    expert_out = expert_fn(
        local_params, expert_in.reshape(num_experts * capacity, d_model))
    expert_out = expert_out.reshape(num_experts, capacity, -1)
    expert_out = jax.lax.all_to_all(expert_out, axis_name,
                                    split_axis=0, concat_axis=0)
    y = _gather_from_experts(expert_out, routing, idx)
    return y, aux


def moe_apply_dense(expert_fn: Callable[[Any, jax.Array], jax.Array],
                    stacked_params: Any,
                    x: jax.Array,
                    gate_logits: jax.Array,
                    capacity_factor: float = 1.25,
                    num_selected: int = 1,
                    normalize_gates: bool = True
                    ) -> Tuple[jax.Array, jax.Array]:
    """Single-device twin of :func:`moe_apply`: identical routing (same
    masks, same capacity drops), but every expert is resident — the expert
    dimension runs under ``vmap`` instead of ``all_to_all``. Use it outside
    ``shard_map`` (tests, single-chip runs, reference numerics)."""
    leaves = jax.tree.leaves(stacked_params)
    num_experts = leaves[0].shape[0]
    tokens, _ = x.shape
    capacity = _capacity(tokens, num_experts, capacity_factor, num_selected)

    probs = jax.nn.softmax(gate_logits, axis=-1)
    routing, aux = _route(
        probs, capacity, num_selected, normalize_gates, x.dtype)
    idx = _routing_indices(routing, num_experts, capacity, tokens)

    expert_in = _pack_to_experts(x, idx, num_experts,
                                 capacity)                  # [E, C, D]
    expert_out = jax.vmap(expert_fn)(stacked_params, expert_in)
    y = _gather_from_experts(expert_out, routing, idx)
    return y, aux
