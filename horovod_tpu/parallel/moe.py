"""Expert parallelism: mixture-of-experts dispatch over a mesh axis.

The reference has no MoE (2019 CNN-era, SURVEY.md §2.3); this is a TPU
extension on the same substrate: experts live along an ``"expert"`` mesh
axis, and token dispatch/return ride ``jax.lax.all_to_all`` over ICI — the
canonical TPU MoE layout (GShard/Switch): tokens are dispatched into
``[experts, capacity, d_model]`` buffers with einsums against a one-hot
dispatch mask, exchanged all-to-all so each device holds its expert's
tokens from every peer, transformed, and exchanged back.

Routing is top-k with capacity dropping (Switch for ``k=1``, GShard for
``k=2``): per expert at most ``capacity = ceil(k*T/E * capacity_factor)``
assignments survive (scaled by ``k`` because top-k routing emits ``k*T``
assignments in total); overflow tokens pass through with zero expert
output (the standard residual-passthrough convention). The Switch load-balancing
auxiliary loss is returned alongside the output.
"""

from __future__ import annotations

from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp

from .mesh import axis_size


def switch_aux_loss(probs: jax.Array, expert_mask: jax.Array) -> jax.Array:
    """Load-balancing loss (Switch Transformer eq. 4): E * sum_e
    fraction_of_tokens(e) * mean_router_prob(e). Minimised at uniform
    routing, where it equals 1. Accumulated in float32: a bf16 mean over
    many tokens would round the fractions."""
    num_experts = probs.shape[-1]
    fraction = expert_mask.astype(jnp.float32).mean(axis=0)
    mean_prob = probs.astype(jnp.float32).mean(axis=0)
    return num_experts * jnp.sum(fraction * mean_prob)


def _dispatch_masks(probs: jax.Array, capacity: int, num_selected: int,
                    normalize_gates: bool,
                    dtype) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Top-k routing with capacity dropping, shared by the distributed and
    dense paths. Returns ``(dispatch, combine, aux)`` with masks of shape
    ``[T, E, C]``."""
    tokens, num_experts = probs.shape
    # Top-k routing: k rounds of argmax with already-chosen experts masked
    # out, accumulating one dispatch/combine mask pair.
    dispatch = jnp.zeros((tokens, num_experts, capacity), dtype)
    combine = jnp.zeros((tokens, num_experts, capacity), dtype)
    avail = jnp.ones_like(probs)          # experts still choosable per token
    # Tokens already assigned per expert (fills capacity slots in order).
    fill = jnp.zeros((num_experts,), jnp.int32)
    total_mask = jnp.zeros_like(probs)
    gate_sum = jnp.zeros((tokens,), dtype)
    for _ in range(num_selected):
        masked = jnp.where(avail > 0, probs, -jnp.inf)
        choice = jnp.argmax(masked, axis=-1)              # [T]
        # Routing decisions come from f32 probs; the combine weights drop to
        # the activation dtype so y doesn't silently promote bf16 streams.
        gate = jnp.take_along_axis(
            probs, choice[:, None], axis=-1)[:, 0].astype(dtype)
        # Slot index math stays in int32 regardless of x.dtype: a bf16
        # cumsum cannot represent token counts past 256 and would silently
        # collide slots. Only the finished 0/1 masks are cast to x.dtype.
        onehot_i = jax.nn.one_hot(choice, num_experts,
                                  dtype=jnp.int32)        # [T, E]
        # Slot index of each token within its chosen expert, continuing
        # after slots used by earlier rounds.
        pos = jnp.cumsum(onehot_i, axis=0) - 1 + fill[None, :]  # [T, E]
        pos_tok = jnp.sum(pos * onehot_i, axis=-1)        # [T]
        keep = pos_tok < capacity
        slot = jax.nn.one_hot(jnp.where(keep, pos_tok, capacity),
                              capacity, dtype=dtype)        # [T, C]
        onehot = onehot_i.astype(dtype)
        d = onehot[:, :, None] * slot[:, None, :] \
            * keep[:, None, None].astype(dtype)
        dispatch = dispatch + d
        combine = combine + d * gate[:, None, None]
        fill = fill + jnp.sum(onehot_i * keep[:, None], axis=0)
        avail = avail * (1.0 - onehot)
        total_mask = total_mask + onehot
        gate_sum = gate_sum + gate

    if normalize_gates and num_selected > 1:
        # GShard convention: the selected gates are renormalised to sum to 1
        # per token (dropped or not).
        combine = combine / jnp.maximum(gate_sum, 1e-9)[:, None, None]

    aux = switch_aux_loss(probs, total_mask / num_selected)
    return dispatch, combine, aux


def _capacity(tokens: int, num_experts: int, capacity_factor: float,
              num_selected: int) -> int:
    # GShard top-k convention: top-k routing emits k*T assignments, so
    # capacity provisions k*T/E * factor slots per expert — otherwise even
    # perfectly uniform top-2 routing would capacity-drop ~37% of
    # assignments at the default capacity_factor of 1.25.
    return max(int(-(-tokens * num_selected * capacity_factor
                     // num_experts)),
               num_selected)


def moe_apply(expert_fn: Callable[[Any, jax.Array], jax.Array],
              expert_params: Any,
              x: jax.Array,
              gate_logits: jax.Array,
              axis_name: str = "expert",
              capacity_factor: float = 1.25,
              num_selected: int = 1,
              normalize_gates: bool = True) -> Tuple[jax.Array, jax.Array]:
    """Run an MoE layer. MUST be called inside ``shard_map`` with
    ``expert_params`` sharded over ``axis_name`` (leading expert axis, one
    expert per device) and ``x``/``gate_logits`` carrying this device's
    tokens (``[T, D]`` / ``[T, E]``).

    Returns ``(y, aux_loss)``: ``y[T, D]`` is the gate-weighted expert
    output per token (zero for capacity-dropped tokens — add the residual
    outside), ``aux_loss`` the local Switch balancing loss (pmean it with
    the data loss).
    """
    num_experts = axis_size(axis_name)
    tokens, d_model = x.shape
    capacity = _capacity(tokens, num_experts, capacity_factor, num_selected)

    probs = jax.nn.softmax(gate_logits, axis=-1)  # [T, E]
    dispatch, combine, aux = _dispatch_masks(
        probs, capacity, num_selected, normalize_gates, x.dtype)

    # [T, E, C] x [T, D] -> [E, C, D]; all-to-all so each device receives
    # its expert's buffer from every peer: [E_src, C, D].
    expert_in = jnp.einsum("tec,td->ecd", dispatch, x)
    expert_in = jax.lax.all_to_all(expert_in, axis_name,
                                   split_axis=0, concat_axis=0)
    local_params = jax.tree.map(lambda a: jnp.squeeze(a, axis=0),
                                expert_params)
    expert_out = expert_fn(
        local_params, expert_in.reshape(num_experts * capacity, d_model))
    expert_out = expert_out.reshape(num_experts, capacity, -1)
    expert_out = jax.lax.all_to_all(expert_out, axis_name,
                                    split_axis=0, concat_axis=0)
    y = jnp.einsum("ecd,tec->td", expert_out, combine)
    return y, aux


def moe_apply_dense(expert_fn: Callable[[Any, jax.Array], jax.Array],
                    stacked_params: Any,
                    x: jax.Array,
                    gate_logits: jax.Array,
                    capacity_factor: float = 1.25,
                    num_selected: int = 1,
                    normalize_gates: bool = True
                    ) -> Tuple[jax.Array, jax.Array]:
    """Single-device twin of :func:`moe_apply`: identical routing (same
    masks, same capacity drops), but every expert is resident — the expert
    dimension runs under ``vmap`` instead of ``all_to_all``. Use it outside
    ``shard_map`` (tests, single-chip runs, reference numerics)."""
    leaves = jax.tree.leaves(stacked_params)
    num_experts = leaves[0].shape[0]
    tokens, _ = x.shape
    capacity = _capacity(tokens, num_experts, capacity_factor, num_selected)

    probs = jax.nn.softmax(gate_logits, axis=-1)
    dispatch, combine, aux = _dispatch_masks(
        probs, capacity, num_selected, normalize_gates, x.dtype)

    expert_in = jnp.einsum("tec,td->ecd", dispatch, x)     # [E, C, D]
    expert_out = jax.vmap(expert_fn)(stacked_params, expert_in)
    y = jnp.einsum("ecd,tec->td", expert_out, combine)
    return y, aux
