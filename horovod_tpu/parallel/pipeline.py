"""Pipeline parallelism over a mesh axis (TPU extension).

The reference implements data parallelism only (SURVEY.md §2.3); pipeline
parallelism is out of its scope but first-class here because the mesh
substrate carries it naturally: stages live along a ``"pipe"`` mesh axis,
activations hop stage→stage over ICI with ``jax.lax.ppermute``, and the
whole schedule compiles into one XLA program — no per-microbatch host
round-trips, no NCCL-style send/recv threads.

Schedules:

- **GPipe** (Huang et al. 2019, the default) — all microbatches flow
  forward through the stage ring inside one ``lax.scan``; XLA overlaps each
  tick's compute with the ppermute transfer. The bubble fraction is
  ``(S-1)/(M+S-1)`` for ``S`` stages and ``M`` microbatches, so pick
  ``M >= 4*S`` in practice. Autodiff runs through the scan/ppermute, giving
  the mirrored backward schedule for free; wrap the stage body in
  ``jax.checkpoint`` (the ``remat`` flag below) to keep per-tick live
  memory at one microbatch per stage — but the scan still stashes one
  carry per tick, so activation memory grows O(M).
- **1F1B** (PipeDream-Flush, Narayanan et al. 2021) — forward and backward
  interleave inside ONE ``lax.scan``: once warm, each round runs one
  forward (new microbatch) and one backward (completed microbatch), with
  activations ppermuting down the ring and cotangents ppermuting back up.
  A microbatch's stashed input lives only ``2(S-1-s)+1`` rounds at stage
  ``s``, so activation memory is O(S) — independent of M — at the same
  bubble as GPipe. Because the backward is fused into the schedule, the
  cotangent of each microbatch must exist the moment the last stage
  finishes it: the 1F1B path therefore owns the loss (``loss_fn``) and
  returns ``(loss, grads)`` directly instead of activations.

Usage sketch (see ``tests/test_pipeline.py``)::

    mesh = hvd.parallel.make_mesh({"data": 2, "pipe": 4})
    # stage_params: pytree whose leaves have leading axis = #stages
    y = jax.jit(jax.shard_map(
        lambda p, x: pipeline_apply(stage_fn, p, x, axis_name="pipe"),
        mesh=mesh,
        in_specs=(P("pipe"), P(None, "data")),
        out_specs=P(None, "data")))(stage_params, microbatches)

Constraints (the classic homogeneous-pipeline contract): every stage maps
activations of one shape to the same shape, and the number of scan ticks is
``M + S - 1``.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp

from .mesh import axis_size


def stack_stage_params(params_list):
    """Stack per-stage parameter pytrees along a new leading axis (the axis
    sharded over the ``pipe`` mesh axis)."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *params_list)


def pipeline_apply(stage_fn: Callable[[Any, jax.Array], jax.Array],
                   stage_params: Any,
                   microbatches: jax.Array,
                   axis_name: str = "pipe",
                   remat: bool = True,
                   schedule: str = "gpipe",
                   loss_fn: Callable[..., jax.Array] | None = None,
                   targets: jax.Array | None = None):
    """Run a pipelined forward (GPipe) or fused forward+backward (1F1B).
    MUST be called inside ``shard_map`` with ``stage_params`` sharded over
    ``axis_name`` (leading stage axis) and ``microbatches`` of shape
    ``[M, mb, ...]`` replicated along it.

    ``schedule="gpipe"`` (default) returns ``[M, mb, ...]`` outputs that
    are VALID ON THE LAST STAGE ONLY (other stages hold garbage); reduce
    with :func:`pipeline_loss` or mask by
    ``lax.axis_index(axis_name) == S-1`` before use, and take gradients
    with ordinary autodiff through the call.

    ``schedule="1f1b"`` requires ``loss_fn(y[, target]) -> scalar`` (the
    per-microbatch loss; ``targets [M, ...]`` optional) and returns
    ``(loss, grads)``: the mean per-microbatch loss (replicated over the
    axis) and the local stage's parameter gradients (same ``[1, ...]``
    leading-axis layout as ``stage_params`` — use ``P(axis_name)`` as its
    out_spec). See :func:`pipeline_1f1b` for why the backward is fused.
    """
    if schedule == "1f1b":
        return pipeline_1f1b(stage_fn, stage_params, microbatches,
                             loss_fn, targets, axis_name=axis_name)
    if schedule != "gpipe":
        raise ValueError(
            f"pipeline_apply: unknown schedule {schedule!r}; "
            "expected 'gpipe' or '1f1b'")
    idx = jax.lax.axis_index(axis_name)
    n_stages = axis_size(axis_name)
    num_mb = microbatches.shape[0]

    # shard_map hands each device its [1, ...] slice of the stacked params.
    local_params = jax.tree.map(lambda a: jnp.squeeze(a, axis=0),
                                stage_params)
    body = jax.checkpoint(stage_fn) if remat else stage_fn

    def tick(carry, t):
        recv = carry
        # Stage 0 injects microbatch t (clamped: bubble ticks recompute the
        # last microbatch; their outputs are dropped, so no cotangent flows
        # through them).
        inject = jax.lax.dynamic_index_in_dim(
            microbatches, jnp.minimum(t, num_mb - 1), keepdims=False)
        x = jnp.where(idx == 0, inject, recv)
        y = body(local_params, x)
        # Hand activations to the next stage; the last stage's edge wraps to
        # stage 0 but is ignored there (stage 0 always injects).
        send = jax.lax.ppermute(
            y, axis_name,
            [(i, (i + 1) % n_stages) for i in range(n_stages)])
        return send, y

    init = jnp.zeros_like(microbatches[0])
    _, ys = jax.lax.scan(tick, init, jnp.arange(num_mb + n_stages - 1))
    # On the last stage, microbatch m completes at tick m + (S-1).
    return jax.lax.dynamic_slice_in_dim(ys, n_stages - 1, num_mb)


def pipeline_1f1b(stage_fn: Callable[[Any, jax.Array], jax.Array],
                  stage_params: Any,
                  microbatches: jax.Array,
                  loss_fn: Callable[..., jax.Array],
                  targets: jax.Array | None = None,
                  axis_name: str = "pipe"):
    """1F1B (PipeDream-Flush) fused training schedule. MUST be called
    inside ``shard_map`` (same sharding contract as :func:`pipeline_apply`).

    One ``lax.scan`` over ``M + 2(S-1)`` rounds runs the whole fwd+bwd:
    stage ``s`` forwards microbatch ``m`` at round ``m + s`` and backwards
    it at round ``m + 2(S-1) - s`` (the last stage back-to-back, upstream
    stages as the cotangent ppermutes up the ring). Each stage stashes only
    the microbatch INPUTS still awaiting their backward (ring buffer of
    ``2S-1`` slots) and recomputes the stage VJP from the stash — so
    activation memory is O(S), independent of M, where GPipe's scan stashes
    O(M) carries. Gradients accumulate per stage across microbatches; no
    autodiff runs through the scan itself (the VJPs are taken per stage,
    per round).

    ``loss_fn(y)`` or ``loss_fn(y, target)`` must return the scalar loss of
    one microbatch; the returned ``loss``/``grads`` correspond to the MEAN
    over microbatches. Gradients flow to ``stage_params`` only (not to
    ``microbatches``/``targets``).
    """
    if loss_fn is None:
        raise ValueError("pipeline_1f1b: loss_fn is required (the 1F1B "
                         "schedule computes the backward in-line, so it "
                         "must own the per-microbatch loss)")
    idx = jax.lax.axis_index(axis_name)
    n_stages = axis_size(axis_name)
    num_mb = microbatches.shape[0]
    last = n_stages - 1
    span = 2 * (n_stages - 1)

    local_params = jax.tree.map(lambda a: jnp.squeeze(a, axis=0),
                                stage_params)

    def mb_loss(y, t):
        return loss_fn(y) if targets is None else loss_fn(y, t)

    fwd = [(i, (i + 1) % n_stages) for i in range(n_stages)]
    bwd = [(i, (i - 1) % n_stages) for i in range(n_stages)]
    stash_size = 2 * n_stages - 1  # > max input lifetime 2(S-1)+1 rounds

    def round_fn(carry, r):
        fwd_recv, bwd_recv, stash, grad_acc, loss_acc = carry

        # ---- forward slot: microbatch m_f = r - idx ----
        m_f = r - idx
        do_f = (m_f >= 0) & (m_f < num_mb)
        m_f_c = jnp.clip(m_f, 0, num_mb - 1)
        inject = jax.lax.dynamic_index_in_dim(microbatches, m_f_c,
                                              keepdims=False)
        x_in = jnp.where(idx == 0, inject, fwd_recv)
        y = stage_fn(local_params, x_in)
        tgt = (jnp.zeros(()) if targets is None
               else jax.lax.dynamic_index_in_dim(targets, m_f_c,
                                                 keepdims=False))
        # Last stage: per-microbatch loss + its cotangent, available the
        # round the microbatch completes — this is what lets the backward
        # start immediately instead of after a full forward sweep.
        l_m, dy = jax.value_and_grad(mb_loss)(y, tgt)
        loss_acc = loss_acc + jnp.where(do_f & (idx == last), l_m, 0.0)
        stash = jnp.where(
            do_f,
            jax.lax.dynamic_update_index_in_dim(
                stash, x_in, m_f_c % stash_size, axis=0),
            stash)

        # ---- backward slot: microbatch m_b = r - (2(S-1) - idx) ----
        m_b = r - (span - idx)
        do_b = (m_b >= 0) & (m_b < num_mb)
        m_b_c = jnp.clip(m_b, 0, num_mb - 1)
        # At the last stage m_b == m_f: the cotangent is this round's dy.
        # Upstream stages receive theirs from the next stage's previous
        # round via the reverse ppermute. Mean-loss scaling folds in here.
        g_in = jnp.where(idx == last, dy / num_mb, bwd_recv)
        x_saved = jax.lax.dynamic_index_in_dim(stash, m_b_c % stash_size,
                                               keepdims=False)
        _, stage_vjp = jax.vjp(stage_fn, local_params, x_saved)
        dp, dx = stage_vjp(g_in)
        grad_acc = jax.tree.map(
            lambda acc, g: acc + jnp.where(do_b, g, 0.0), grad_acc, dp)

        # Ring hops: activations down (wrap edge into stage 0 is ignored —
        # it always injects), cotangents up (wrap edge into the last stage
        # is ignored — it always uses its own dy).
        fwd_send = jax.lax.ppermute(y, axis_name, fwd)
        bwd_send = jax.lax.ppermute(dx, axis_name, bwd)
        return (fwd_send, bwd_send, stash, grad_acc, loss_acc), None

    zero_act = jnp.zeros_like(microbatches[0])
    init = (
        zero_act,
        zero_act,
        jnp.zeros((stash_size,) + microbatches.shape[1:],
                  microbatches.dtype),
        jax.tree.map(jnp.zeros_like, local_params),
        jnp.zeros(()),
    )
    (_, _, _, grad_acc, loss_acc), _ = jax.lax.scan(
        round_fn, init, jnp.arange(num_mb + span))

    loss = jax.lax.psum(loss_acc, axis_name) / num_mb
    grads = jax.tree.map(lambda g: g[None], grad_acc)
    return loss, grads


def collect_from_last_stage(y: jax.Array,
                            axis_name: str = "pipe") -> jax.Array:
    """Broadcast the last stage's (valid) outputs to every stage, replacing
    the garbage elsewhere — handy when the pipeline output itself (not just
    a loss) must leave the ``shard_map`` replicated over the pipe axis."""
    idx = jax.lax.axis_index(axis_name)
    n_stages = axis_size(axis_name)
    return jax.lax.psum(jnp.where(idx == n_stages - 1, y, 0), axis_name)


def pipeline_loss(per_mb_loss: jax.Array, axis_name: str = "pipe") -> jax.Array:
    """Reduce per-microbatch losses computed from :func:`pipeline_apply`
    outputs: keep the last stage's value, zero the garbage elsewhere, and
    share it with every stage (so the loss — and its gradients — are
    consistent across the pipe axis)."""
    idx = jax.lax.axis_index(axis_name)
    n_stages = axis_size(axis_name)
    masked = jnp.where(idx == n_stages - 1, per_mb_loss.mean(), 0.0)
    return jax.lax.psum(masked, axis_name)
