"""Pipeline parallelism over a mesh axis (TPU extension).

The reference implements data parallelism only (SURVEY.md §2.3); pipeline
parallelism is out of its scope but first-class here because the mesh
substrate carries it naturally: stages live along a ``"pipe"`` mesh axis,
activations hop stage→stage over ICI with ``jax.lax.ppermute``, and the
whole schedule compiles into one XLA program — no per-microbatch host
round-trips, no NCCL-style send/recv threads.

Schedule: GPipe (Huang et al. 2019) — all microbatches flow forward through
the stage ring inside one ``lax.scan``; XLA overlaps each tick's compute
with the ppermute transfer. The bubble fraction is ``(S-1)/(M+S-1)`` for
``S`` stages and ``M`` microbatches, so pick ``M >= 4*S`` in practice.
Autodiff runs through the scan/ppermute, giving the mirrored backward
schedule for free; wrap the stage body in ``jax.checkpoint`` (the
``remat`` flag below) to keep live memory at one microbatch per stage.

Usage sketch (see ``tests/test_pipeline.py``)::

    mesh = hvd.parallel.make_mesh({"data": 2, "pipe": 4})
    # stage_params: pytree whose leaves have leading axis = #stages
    y = jax.jit(jax.shard_map(
        lambda p, x: pipeline_apply(stage_fn, p, x, axis_name="pipe"),
        mesh=mesh,
        in_specs=(P("pipe"), P(None, "data")),
        out_specs=P(None, "data")))(stage_params, microbatches)

Constraints (the classic homogeneous-pipeline contract): every stage maps
activations of one shape to the same shape, and the number of scan ticks is
``M + S - 1``.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp

from .mesh import axis_size


def stack_stage_params(params_list):
    """Stack per-stage parameter pytrees along a new leading axis (the axis
    sharded over the ``pipe`` mesh axis)."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *params_list)


def pipeline_apply(stage_fn: Callable[[Any, jax.Array], jax.Array],
                   stage_params: Any,
                   microbatches: jax.Array,
                   axis_name: str = "pipe",
                   remat: bool = True) -> jax.Array:
    """Run a GPipe forward pass. MUST be called inside ``shard_map`` with
    ``stage_params`` sharded over ``axis_name`` (leading stage axis) and
    ``microbatches`` of shape ``[M, mb, ...]`` replicated along it.

    Returns ``[M, mb, ...]`` outputs that are VALID ON THE LAST STAGE ONLY
    (other stages hold garbage); reduce with :func:`pipeline_loss` or mask
    by ``lax.axis_index(axis_name) == S-1`` before use.
    """
    idx = jax.lax.axis_index(axis_name)
    n_stages = axis_size(axis_name)
    num_mb = microbatches.shape[0]

    # shard_map hands each device its [1, ...] slice of the stacked params.
    local_params = jax.tree.map(lambda a: jnp.squeeze(a, axis=0),
                                stage_params)
    body = jax.checkpoint(stage_fn) if remat else stage_fn

    def tick(carry, t):
        recv = carry
        # Stage 0 injects microbatch t (clamped: bubble ticks recompute the
        # last microbatch; their outputs are dropped, so no cotangent flows
        # through them).
        inject = jax.lax.dynamic_index_in_dim(
            microbatches, jnp.minimum(t, num_mb - 1), keepdims=False)
        x = jnp.where(idx == 0, inject, recv)
        y = body(local_params, x)
        # Hand activations to the next stage; the last stage's edge wraps to
        # stage 0 but is ignored there (stage 0 always injects).
        send = jax.lax.ppermute(
            y, axis_name,
            [(i, (i + 1) % n_stages) for i in range(n_stages)])
        return send, y

    init = jnp.zeros_like(microbatches[0])
    _, ys = jax.lax.scan(tick, init, jnp.arange(num_mb + n_stages - 1))
    # On the last stage, microbatch m completes at tick m + (S-1).
    return jax.lax.dynamic_slice_in_dim(ys, n_stages - 1, num_mb)


def collect_from_last_stage(y: jax.Array,
                            axis_name: str = "pipe") -> jax.Array:
    """Broadcast the last stage's (valid) outputs to every stage, replacing
    the garbage elsewhere — handy when the pipeline output itself (not just
    a loss) must leave the ``shard_map`` replicated over the pipe axis."""
    idx = jax.lax.axis_index(axis_name)
    n_stages = axis_size(axis_name)
    return jax.lax.psum(jnp.where(idx == n_stages - 1, y, 0), axis_name)


def pipeline_loss(per_mb_loss: jax.Array, axis_name: str = "pipe") -> jax.Array:
    """Reduce per-microbatch losses computed from :func:`pipeline_apply`
    outputs: keep the last stage's value, zero the garbage elsewhere, and
    share it with every stage (so the loss — and its gradients — are
    consistent across the pipe axis)."""
    idx = jax.lax.axis_index(axis_name)
    n_stages = axis_size(axis_name)
    masked = jnp.where(idx == n_stages - 1, per_mb_loss.mean(), 0.0)
    return jax.lax.psum(masked, axis_name)
