"""Two-level (hierarchical) collectives for multi-slice topologies.

Reference: ``NCCLHierarchicalAllreduce`` (``horovod/common/ops/
nccl_operations.cc:167-363``: NCCL reduce-scatter within the node → MPI
allreduce across nodes → NCCL allgather within the node) and
``MPIHierarchicalAllgather`` (``mpi_operations.cc:179-329``). The TPU
analogue: the fast inner fabric is ICI within a pod slice, the slow outer
fabric is DCN across slices. With a 2-D mesh ``(outer, inner)`` the same
bandwidth structure is:

    psum_scatter over inner (ICI)  →  psum over outer (DCN, 1/inner of the
    bytes)  →  all_gather over inner (ICI)

which sends the minimum possible volume over the slow axis — exactly the
reference's trick, expressed as three XLA collectives that the compiler
schedules/overlaps.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax


def hierarchical_allreduce(x, inner_axis: str, outer_axis: str,
                           average: bool = False):
    """Allreduce over ``inner_axis`` x ``outer_axis`` with the
    cross-``outer`` traffic reduced to 1/|inner| of the payload (reference
    nccl_operations.cc:219-327). Works on any shape: internally flattened
    and padded to the inner axis size, as the reference pads fused buffers
    to ``local_size * FUSION_BUFFER_ATOMIC_UNIT``
    (nccl_operations.cc:210-216)."""
    inner = lax.psum(1, inner_axis)
    shape = x.shape
    flat = x.reshape(-1)
    n = flat.shape[0]
    pad = (-n) % inner
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    shard = lax.psum_scatter(flat, inner_axis, tiled=True)   # ICI
    shard = lax.psum(shard, outer_axis)                      # DCN, 1/inner
    full = lax.all_gather(shard, inner_axis, tiled=True)     # ICI
    out = full[:n].reshape(shape)
    if average:
        out = out / (inner * lax.psum(1, outer_axis))
    return out


def hierarchical_allgather(x, inner_axis: str, outer_axis: str):
    """Two-level allgather: gather within the fast axis first, then across
    the slow axis (reference MPIHierarchicalAllgather: node-shared-memory
    gather + cross-node Allgatherv, mpi_operations.cc:179-329).

    Result rank order follows (outer, inner) mesh order."""
    inner_gathered = lax.all_gather(x, inner_axis, tiled=True)
    return lax.all_gather(inner_gathered, outer_axis, tiled=True)
