"""Peer-to-peer checkpoint-shard exchange (docs/sharded-checkpoint.md).

The transfer half of fast elastic restore: after a reshape, survivors
already hold the whole committed pytree in memory, so the only bytes
that must move are the shards a member is MISSING (a joiner's everything,
a diverged rank's mismatches). This module moves them over the existing
authenticated wires using the SHARD_FETCH/SHARD_DATA frame kinds
(``common/wire.py``), routed through the coordinator star exactly like
trace collection — requester → coordinator → owner → coordinator →
requester — so restore needs no connectivity the job doesn't already
have, and no rank ever re-broadcasts the whole model.

Addressing is by CONTENT DIGEST (``utils/checkpoint.shard_digest``): a
fetch names the shard id, the digest the authority (rank 0's commit)
declared, and the flat-leaf indices that make it up; an owner serves the
shard only if its own committed copy hashes to that exact digest. That
makes the plane self-validating — a racing commit, a stale reply from a
torn restore, or a foreign epoch's traffic can never splice wrong bytes
into a restore; at worst a fetch comes back ``found=False`` and the
requester walks its fallback chain (next surviving holder, then the
manifest-validated on-disk shard, then a loud error naming everything it
tried).

Frames are serviced transparently inside whatever recv loop drains them
(the controller thread's lockstep reads), so the plane stays invisible
to the negotiation protocol — the spec in ``analysis/protocol.py``
declares the kinds legal self-loops in the steady states and protocheck
verifies every chaos run against it.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..analysis.lockorder import make_lock
from ..common import hvd_logging as logging
from ..utils.checkpoint import (
    SHARDED_PREFIX,
    _sharded_steps,
    load_shard,
    manifest_path,
    pack_shard,
    read_manifest,
    shard_digest,
    shard_path,
    unpack_shard,
)

# Per-holder attempt bound: long enough for a big shard to cross the
# star twice under load, short enough that a dead owner's chain falls
# through to disk well inside the liveness deadline.
FETCH_ATTEMPT_TIMEOUT = 15.0


class ShardFetchError(RuntimeError):
    """No source produced the shard: every surviving holder declined or
    timed out and no manifest-validated on-disk copy matched."""


class _Fetch:
    __slots__ = ("shard", "digest", "nonce", "event", "found", "data")

    def __init__(self, shard: int, digest: str, nonce: int = 0):
        self.shard = shard
        self.digest = digest
        # Per-attempt id: a late reply from a TIMED-OUT earlier attempt
        # (slow relay) must not fulfill a newer attempt's future — it
        # would mark a live holder not-found and poison the fallback
        # chain one-behind all the way to a spurious ShardFetchError.
        self.nonce = nonce
        self.event = threading.Event()
        self.found = False
        self.data: Optional[bytes] = None


class ShardExchange:
    """One process's half of the shard plane: requester futures, the
    provider serving this rank's committed copy, and — on rank 0 — the
    star relay. Installed onto a live Controller's wires; reform() keeps
    Wire objects, so an installation survives membership epochs."""

    def __init__(self):
        # Covers the pending-futures table only; never held across a
        # wire send (lock-graph discipline: shards.pending is a leaf).
        self._lock = make_lock("shards.pending")
        self._pending: Dict[Tuple[int, str], _Fetch] = {}
        self._provider = None
        self._provider_owner = None
        self._ctl = None
        self._nonce = 0

    # ------------------------------------------------------------- install

    def install(self, controller) -> None:
        """Bind to a controller and hook the shard callback onto its
        wires (both star sides). Idempotent; re-binding to a NEW
        controller drops stale futures."""
        if controller is self._ctl:
            return
        with self._lock:
            self._pending = {}
        self._ctl = controller
        service = getattr(controller, "_service", None)
        client = getattr(controller, "_client", None)
        if service is not None:
            service.set_shard_callback(self._on_frame)
        if client is not None:
            client.wire.set_shard_callback(self._on_frame)

    def set_provider(self, fn, owner=None) -> None:
        """``fn(shard_id, digest, leaf_ids) -> Optional[bytes]`` serving
        this rank's committed copy (None = no matching copy here).
        ``owner`` tags who installed it, so that owner's teardown can
        release the closure (and the snapshot it pins) without clobbering
        a newer installation."""
        self._provider = fn
        self._provider_owner = owner

    def clear_provider(self, owner) -> None:
        """Drop the provider iff ``owner`` still owns it."""
        if self._provider_owner is owner:
            self._provider = None
            self._provider_owner = None

    # ------------------------------------------------------- frame handling

    def _serve(self, info: dict) -> dict:
        """Build the reply for a fetch this rank owns."""
        blob = None
        provider = self._provider
        if provider is not None:
            try:
                blob = provider(int(info["shard"]), info["digest"],
                                list(info.get("leaves", ())))
            except Exception as exc:
                logging.warning("shards: provider failed for shard %s: %s",
                                info.get("shard"), exc)
                blob = None
        return {"shard": int(info["shard"]), "digest": info["digest"],
                "req": int(info["req"]), "nonce": info.get("nonce"),
                "found": blob is not None, "data": blob}

    def _fulfill(self, info: dict) -> None:
        key = (int(info["shard"]), info["digest"])
        with self._lock:
            fetch = self._pending.get(key)
            if fetch is None or fetch.nonce != info.get("nonce"):
                fetch = None  # superseded/stale attempt's reply: drop
            else:
                del self._pending[key]
        if fetch is None:
            return
        fetch.found = bool(info.get("found"))
        fetch.data = info.get("data")
        fetch.event.set()

    def _coordinator_wire(self, rank: int):
        service = getattr(self._ctl, "_service", None)
        if service is None:
            return None
        with service._wires_lock:
            return service.wires.get(rank)

    def _on_frame(self, event: str, info: dict) -> None:
        """Per-wire callback (runs on whatever thread drained the frame).
        Worker side: serve fetches, consume replies. Coordinator side:
        serve/consume when addressed to rank 0, relay otherwise; a relay
        target that died answers the requester ``found=False`` at once
        so its fallback chain advances instead of waiting out a timeout."""
        ctl = self._ctl
        if ctl is None:
            return
        is_coord = getattr(ctl, "_service", None) is not None
        if event == "fetch":
            owner = int(info.get("owner", -1))
            if not is_coord:
                self._reply(self._serve(info))
                return
            if owner == 0:
                self._reply(self._serve(info))
                return
            wire = self._coordinator_wire(owner)
            if wire is None:
                self._reply({"shard": int(info["shard"]),
                             "digest": info["digest"],
                             "req": int(info["req"]),
                             "nonce": info.get("nonce"),
                             "found": False, "data": None})
                return
            try:
                wire.send_shard_fetch(info)
            except Exception as exc:
                logging.debug("shards: relay to owner %d failed (%s)",
                              owner, exc)
                self._reply({"shard": int(info["shard"]),
                             "digest": info["digest"],
                             "req": int(info["req"]),
                             "nonce": info.get("nonce"),
                             "found": False, "data": None})
        else:  # "data"
            req = int(info.get("req", -1))
            if is_coord and req != 0:
                wire = self._coordinator_wire(req)
                if wire is None:
                    return  # requester died: nothing to relay to
                try:
                    wire.send_shard_data(info)
                except Exception as exc:
                    logging.debug("shards: relay to requester %d failed "
                                  "(%s)", req, exc)
                return
            self._fulfill(info)

    def _reply(self, info: dict) -> None:
        """Send a SHARD_DATA answer toward the requester: workers hand it
        to the star; rank 0 sends straight to the requester's wire (or
        fulfills its own future for a local serve)."""
        ctl = self._ctl
        if getattr(ctl, "_service", None) is not None:
            if int(info["req"]) == 0:
                self._fulfill(info)
                return
            wire = self._coordinator_wire(int(info["req"]))
            if wire is None:
                return
            try:
                wire.send_shard_data(info)
            except Exception as exc:
                logging.debug("shards: reply to requester %d failed (%s)",
                              info["req"], exc)
            return
        client = getattr(ctl, "_client", None)
        if client is None:
            return
        try:
            client.wire.send_shard_data(info)
        except Exception as exc:
            logging.debug("shards: reply send failed (%s)", exc)

    # ------------------------------------------------------------ requester

    def fetch_async(self, shard: int, digest: str,
                    leaf_ids: Sequence[int], owner: int) -> _Fetch:
        """Issue one fetch toward ``owner`` (a surviving holder's current
        rank); returns the future the SHARD_DATA reply fulfills."""
        fetch = _Fetch(shard, digest)
        with self._lock:  # call-free region (lock-graph discipline)
            self._nonce += 1
            fetch.nonce = self._nonce
            self._pending[(shard, digest)] = fetch
        ctl = self._ctl
        rank = ctl.topo.rank
        info = {"shard": int(shard), "digest": digest,
                "leaves": [int(i) for i in leaf_ids],
                "req": int(rank), "owner": int(owner),
                "nonce": fetch.nonce}
        try:
            if rank == 0:
                wire = self._coordinator_wire(owner)
                if wire is None:
                    raise ConnectionError(f"no wire to owner {owner}")
                wire.send_shard_fetch(info)
            else:
                ctl._client.wire.send_shard_fetch(info)
        except Exception as exc:
            logging.debug("shards: fetch send to owner %d failed (%s)",
                          owner, exc)
            fetch.found = False
            fetch.event.set()
        return fetch

    def wait(self, fetch: _Fetch,
             timeout: float = FETCH_ATTEMPT_TIMEOUT) -> bool:
        """Block the (user) restore thread on one fetch, watching for the
        job tearing underneath it: a reshape fence raises the retryable
        RanksChangedError so ``hvd.elastic.run`` restarts the restore at
        the new epoch — the kill-mid-fetch chaos contract."""
        deadline = time.monotonic() + timeout
        while not fetch.event.wait(0.02):
            ctl = self._ctl
            fence = getattr(ctl, "_reshape_fence", None)
            if fence is not None:
                raise fence
            if ctl is None or ctl._closed.is_set():
                raise RuntimeError(
                    "shard fetch aborted: the controller shut down")
            if time.monotonic() > deadline:
                with self._lock:
                    self._pending.pop((fetch.shard, fetch.digest), None)
                return False
        return fetch.found


def fetch_shard(exchange: ShardExchange, shard: int, digest: str,
                leaf_ids: Sequence[int], holders: Sequence[int],
                disk_dir: Optional[str] = None,
                prefix: str = SHARDED_PREFIX,
                attempt_timeout: float = FETCH_ATTEMPT_TIMEOUT
                ) -> Tuple[List[np.ndarray], str]:
    """Fetch one shard through its fallback chain: each surviving holder
    in order (peer memory), then the newest on-disk step whose manifest
    records this exact digest (the dead-owner path), then a loud error
    naming every source tried. Returns ``(arrays, source)`` with source
    ``"peer"`` or ``"disk"``."""
    tried: List[str] = []
    for owner in holders:
        fetch = exchange.fetch_async(shard, digest, leaf_ids, owner)
        if exchange.wait(fetch, timeout=attempt_timeout) and fetch.data:
            try:
                return unpack_shard(fetch.data, expect_digest=digest), \
                    "peer"
            except ValueError as exc:
                tried.append(f"rank {owner} (bad payload: {exc})")
                continue
        tried.append(f"rank {owner} (no matching copy or timeout)")
    arrays = _disk_shard(disk_dir, shard, digest, prefix)
    if arrays is not None:
        return arrays, "disk"
    tried.append(f"disk under {disk_dir!r} (no manifest records digest "
                 f"{digest})")
    raise ShardFetchError(
        f"shard {shard} (digest {digest}) unrecoverable; tried: "
        + "; ".join(tried))


def _disk_shard(directory: Optional[str], shard: int, digest: str,
                prefix: str) -> Optional[List[np.ndarray]]:
    """Newest on-disk copy of a shard matching ``digest``, manifest-
    validated — the fallback when every in-memory holder is gone."""
    if not directory:
        return None
    for step in _sharded_steps(directory, prefix):
        try:
            manifest = read_manifest(manifest_path(directory, step, prefix))
            digests = manifest.get("digests", [])
            if shard >= len(digests) or digests[shard] != digest:
                continue
            world = int(manifest["world_size"])
            return load_shard(shard_path(directory, step, shard, world,
                                         prefix), expect_digest=digest)
        except (OSError, ValueError, KeyError):
            continue  # torn/incomplete step: keep scanning older ones
    return None


def make_memory_provider(get_flat):
    """Provider over an in-memory committed snapshot: ``get_flat()``
    returns the current flat leaf list (or None). Serves a shard iff the
    requested leaves hash to the requested digest — self-validating
    against racing commits."""

    def provide(shard: int, digest: str,
                leaf_ids: Sequence[int]) -> Optional[bytes]:
        flat = get_flat()
        if flat is None:
            return None
        try:
            arrays = [np.ascontiguousarray(np.asarray(flat[i]))
                      for i in leaf_ids]
        except Exception:
            # Out-of-range leaf, non-array leaf, or an unreadable jax
            # buffer (deleted by a donated jit): no copy to serve.
            return None
        if shard_digest(arrays) != digest:
            return None
        return pack_shard(arrays)

    return provide


_exchange: Optional[ShardExchange] = None


def exchange() -> ShardExchange:
    """Process-wide exchange (one controller per real process; the sim
    harness builds its own instances per logical rank)."""
    global _exchange
    if _exchange is None:
        _exchange = ShardExchange()
    return _exchange
