"""Elastic training API (docs/elastic.md) — the user-facing half of
elastic membership, in the shape upstream Horovod's elastic mode later
standardized (``hvd.elastic.run`` + a state object):

    state = hvd.elastic.State(step=0, params=params, opt_state=opt_state)

    @hvd.elastic.run
    def train(state):
        while state.step < total_steps:
            grads = ...                       # uses hvd collectives
            state.params = update(state.params, grads)
            state.step += 1
            if state.step % 100 == 0:
                state.commit()
        return state.params

The runtime half lives in the controller (``HOROVOD_ELASTIC=1``): when a
rank dies or a joiner is admitted, the coordinator re-forms the world at
a bumped membership epoch and every in-flight collective fails with
:class:`RanksChangedError`. The ``run`` wrapper catches it, acknowledges
the reshape, rolls every tracked value back to the last ``commit()``
synced from rank 0 (``jax.broadcast_parameters`` for array pytrees,
``broadcast_object`` for everything else), and calls the function again —
so survivors and joiners alike resume from one consistent point, losing
at most the work since the last commit.
"""

from __future__ import annotations

import copy
import functools
from typing import Any, Dict

import numpy as np

from ..common import basics
from ..common import hvd_logging as logging
from ..common.wire import RanksChangedError  # noqa: F401  (public API)

__all__ = ["RanksChangedError", "State", "run", "epoch"]


def epoch() -> int:
    """Current membership epoch: 1 at rendezvous (and always 1 for
    single-process or non-elastic jobs), bumped by every reshape."""
    ctl = basics.state().controller
    if ctl is None:
        return 1
    return int(getattr(ctl, "membership_epoch", 1))


def _is_array_tree(value: Any) -> bool:
    """True when every leaf is an ndarray-like — the broadcast_parameters
    fast path, which keeps dtypes/shapes without a pickle round trip."""
    import jax

    leaves = jax.tree_util.tree_flatten(value)[0]
    return bool(leaves) and all(
        isinstance(leaf, np.ndarray) or hasattr(leaf, "__array_namespace__")
        or type(leaf).__module__.startswith(("jax", "jaxlib"))
        for leaf in leaves)


class State:
    """Tracked training state: every keyword becomes an attribute.
    ``commit()`` snapshots the current values; ``restore()`` rolls back to
    the last commit with rank 0's copy winning on every rank — the
    reference's broadcast-from-root consistency contract, applied at
    every membership epoch boundary."""

    def __init__(self, **objects: Any):
        if not objects:
            raise ValueError(
                "hvd.elastic.State needs at least one tracked value, e.g. "
                "State(step=0, params=params)")
        self._names = tuple(sorted(objects))
        for name, value in objects.items():
            setattr(self, name, value)
        self._committed: Dict[str, Any] = {}
        self.commit()

    def commit(self) -> None:
        """Snapshot the current values as the restore point. Purely local
        (no collective): call it at a point every rank reaches in the
        same iteration, or ranks will restore to different steps."""
        self._committed = {name: copy.deepcopy(getattr(self, name))
                           for name in self._names}

    def restore(self) -> None:
        """Roll every tracked value back to the last commit, re-synced
        from rank 0 (reference ``broadcast_parameters`` contract) so all
        members of the new epoch — joiners included — resume identical."""
        st = basics.state()
        for name in self._names:
            value = self._committed[name]
            if st.topology.size > 1:
                if _is_array_tree(value):
                    from ..jax import broadcast_parameters

                    value = broadcast_parameters(value, root_rank=0)
                else:
                    from ..ops.collective_ops import broadcast_object

                    value = broadcast_object(
                        value, root_rank=0, name=f"elastic.state.{name}")
            setattr(self, name, copy.deepcopy(value))
        self.commit()


def _acknowledge_reshape() -> None:
    """Clear the controller's reshape fence: collectives enqueued from
    here on ride the new epoch (until then they fail with the same
    RanksChangedError their drained siblings got)."""
    ctl = basics.state().controller
    if ctl is not None and hasattr(ctl, "clear_reshape_fence"):
        ctl.clear_reshape_fence()


def run(func):
    """Decorate the training loop for elastic execution (reference
    ``hvd.elastic.run`` shape): sync state from rank 0, run ``func(state,
    *args, **kwargs)``, and on :class:`RanksChangedError` — a reshape
    interrupted the loop — restore and run it again. Any other exception
    propagates unchanged."""

    @functools.wraps(func)
    def wrapper(state: State, *args, **kwargs):
        while True:
            try:
                _acknowledge_reshape()
                state.restore()
                return func(state, *args, **kwargs)
            except RanksChangedError as exc:
                logging.warning(
                    "elastic: %s; restoring state from rank 0 and "
                    "resuming the training loop", exc)
                continue

    return wrapper
