"""Elastic training API (docs/elastic.md) — the user-facing half of
elastic membership, in the shape upstream Horovod's elastic mode later
standardized (``hvd.elastic.run`` + a state object):

    state = hvd.elastic.State(step=0, params=params, opt_state=opt_state)

    @hvd.elastic.run
    def train(state):
        while state.step < total_steps:
            grads = ...                       # uses hvd collectives
            state.params = update(state.params, grads)
            state.step += 1
            if state.step % 100 == 0:
                state.commit()
        return state.params

The runtime half lives in the controller (``HOROVOD_ELASTIC=1``): when a
rank dies or a joiner is admitted, the coordinator re-forms the world at
a bumped membership epoch and every in-flight collective fails with
:class:`RanksChangedError`. The ``run`` wrapper catches it, acknowledges
the reshape, rolls every tracked value back to the last ``commit()``,
and calls the function again.

Restore keeps the reference's **rank-0 consistency contract** but not
its mechanism (docs/sharded-checkpoint.md): rank 0's commit is the
authority, published as tiny metadata (per-shard content digests over a
deterministic flat-leaf layout). A survivor whose committed shards hash
to the authority's digests keeps its LOCAL copy — zero bytes moved, so
reshape-to-first-step time is flat in model size — and only mismatching
or missing shards (a joiner's everything) are fetched from surviving
owners over the existing authenticated wires, with a manifest-validated
on-disk fallback for shards no live member holds. The legacy rank-0
whole-pytree re-broadcast remains as the non-elastic path and behind
``HOROVOD_ELASTIC_RESTORE=broadcast``.

``commit()`` additionally hands this rank's 1/world_size shard of the
snapshot to the async ``hvd-ckpt-writer`` thread when ``HOROVOD_CKPT_DIR``
is set (or :meth:`State.enable_sharded_checkpoint` was called) — the
step loop never blocks on storage.
"""

from __future__ import annotations

import copy
import functools
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .. import metrics
from ..common import basics
from ..common import config as config_mod
from ..common import hvd_logging as logging
from ..common.wire import RanksChangedError  # noqa: F401  (public API)
from ..utils import checkpoint as ckpt
from . import shards as shards_mod

__all__ = ["RanksChangedError", "State", "run", "epoch"]

_em = None


def _elastic_metrics():
    """Lazy registration (tests/test_metrics_lint.py): the restore-plane
    series beside the controller's reshape ones."""
    global _em
    if _em is None:
        from types import SimpleNamespace

        restore_bytes = metrics.counter(
            "hvd_elastic_restore_bytes_total",
            "Committed-state bytes materialized per restore, by source "
            "(local = digest-matched in-memory copy, peer = fetched "
            "shard, disk = manifest-validated fallback).", ("source",))
        fetches = metrics.counter(
            "hvd_elastic_shard_fetches_total",
            "Checkpoint-shard fetches resolved during restores, by "
            "source.", ("source",))
        _em = SimpleNamespace(
            restore_seconds=metrics.histogram(
                "hvd_elastic_restore_seconds",
                "Wall time of one State.restore(): authority metadata "
                "sync + shard verification + any fetches — the "
                "reshape-to-consistent-state half of recovery, beside "
                "hvd_elastic_reshape_seconds."),
            restore_bytes=restore_bytes,
            fetches=fetches,
        )
    return _em


def epoch() -> int:
    """Current membership epoch: 1 at rendezvous (and always 1 for
    single-process or non-elastic jobs), bumped by every reshape."""
    ctl = basics.state().controller
    if ctl is None:
        return 1
    return int(getattr(ctl, "membership_epoch", 1))


def _is_array_tree(value: Any) -> bool:
    """True when every leaf is an ndarray-like — the broadcast_parameters
    fast path, which keeps dtypes/shapes without a pickle round trip."""
    import jax

    leaves = jax.tree_util.tree_flatten(value)[0]
    return bool(leaves) and all(
        isinstance(leaf, np.ndarray) or hasattr(leaf, "__array_namespace__")
        or type(leaf).__module__.startswith(("jax", "jaxlib"))
        for leaf in leaves)


def _leaf_is_array(leaf: Any) -> bool:
    """Shard-plane leaf classification: real arrays shard; Python
    scalars/objects (a step counter, a config string) ride the tiny
    authority metadata instead, so their TYPES survive a restore (a
    joiner's ``step`` stays an int, not a 0-d array)."""
    return (isinstance(leaf, (np.ndarray, np.generic))
            or type(leaf).__module__.startswith(("jax", "jaxlib")))


def _is_jax_leaf(leaf: Any) -> bool:
    return type(leaf).__module__.startswith(("jax", "jaxlib"))


def _materialize_live(leaf: Any) -> Any:
    """The live value a restore hands back for one committed leaf.
    numpy: a buffer copy — np arrays mutate in place, so the live value
    must own its memory or user writes would corrupt the restore point.
    jax: the committed array ITSELF — jax arrays are immutable, so the
    alias is safe and a restore of a jax pytree moves and copies ZERO
    model bytes. (A donated jit argument deleting the shared buffer
    breaks the user's own live value just the same; on non-root ranks
    the digest plane treats the unreadable committed leaf as a mismatch
    and re-fetches from peers — heals instead of corrupting — while
    rank 0, the authority, fails loudly in _authority_meta.) Arbitrary
    objects fall back to deepcopy."""
    if isinstance(leaf, np.ndarray):
        return leaf.copy()
    if _is_jax_leaf(leaf):
        return leaf
    return copy.deepcopy(leaf)


class State:
    """Tracked training state: every keyword becomes an attribute.
    ``commit()`` snapshots the current values; ``restore()`` rolls back
    to the last commit with rank 0's copy authoritative on every rank —
    the reference's broadcast-from-root consistency contract, applied at
    every membership epoch boundary (by digest verification + p2p shard
    fetch under elastic membership; by re-broadcast otherwise)."""

    def __init__(self, **objects: Any):
        if not objects:
            raise ValueError(
                "hvd.elastic.State needs at least one tracked value, e.g. "
                "State(step=0, params=params)")
        self._names = tuple(sorted(objects))
        for name, value in objects.items():
            setattr(self, name, value)
        self._committed: Dict[str, Any] = {}
        self._commit_id = 0
        self._commit_world = 1
        self._flat_cache: Optional[tuple] = None
        self._writer: Optional[ckpt.AsyncShardWriter] = None
        self._save_step = 0
        # Async digest precompute (the hvd-ckpt-digest thread): restore's
        # shard verification needs the digest table of the LAST commit,
        # and hashing the whole model inline would put an O(model) pass
        # back on the recovery path this subsystem exists to flatten.
        # Commit kicks the worker; restore uses the table when it is
        # ready for the current commit + layout, else recomputes inline
        # (pure fallback — same digests either way).
        self._digest_table: Optional[tuple] = None
        self._digest_wake = threading.Event()
        self._digest_stop = threading.Event()
        self._digest_thread: Optional[threading.Thread] = None
        ckpt_dir = config_mod.elastic_ckpt_dir()
        if ckpt_dir:
            self.enable_sharded_checkpoint(ckpt_dir)
        self.commit()
        self._install_exchange()

    # ------------------------------------------------------------- storage

    def enable_sharded_checkpoint(self, directory: str,
                                  keep: Optional[int] = None) -> None:
        """Turn on the continuous async disk tier: every ``commit()``
        hands this rank's shard to the ``hvd-ckpt-writer`` thread
        (rank 0 adds the manifest). Never blocks the step loop."""
        if self._writer is not None:
            return
        self._writer = ckpt.AsyncShardWriter(
            directory, keep=keep if keep is not None
            else config_mod.elastic_ckpt_keep())
        self._save_step = self._writer.next_step()

    def flush_checkpoints(self, timeout: float = 30.0) -> bool:
        """Wait for the writer to drain (teardown/tests only)."""
        return self._writer.flush(timeout) if self._writer else True

    @property
    def checkpoint_dir(self) -> Optional[str]:
        return self._writer.directory if self._writer else None

    # -------------------------------------------------------------- commit

    def commit(self) -> None:
        """Snapshot the current values as the restore point. Purely local
        (no collective): call it at a point every rank reaches in the
        same iteration, or ranks will restore to different steps. With
        the disk tier on, also enqueues this rank's shard for the async
        writer — the snapshot below is the only step-loop cost."""
        self._commit_world = max(1, self._topology_size())
        # Ordering contract with _flat_commit's lock-free readers (the
        # digest thread, the shard provider on a recv thread): the NEW
        # committed dict must be visible before the NEW commit id, so a
        # reader that observes the bumped id always flattens the bumped
        # snapshot. A reader that captures the old id with the new dict
        # merely caches under a key no one will hit again.
        self._committed = {name: copy.deepcopy(getattr(self, name))
                          for name in self._names}
        self._commit_id += 1
        self._flat_cache = None
        if self._writer is not None:
            try:
                self._submit_shards()
            except Exception as exc:  # storage must never fail the step
                logging.warning(
                    "elastic: sharded checkpoint submit skipped: %s", exc)
        if config_mod.elastic_enabled() \
                and config_mod.elastic_restore_mode() == "p2p":
            # The table only ever feeds _restore_p2p; non-elastic /
            # broadcast-mode jobs must not pay a background full-model
            # hash per commit for a reader that cannot run. (Restore
            # recomputes inline when no table is ready — the kick is an
            # optimization, never a correctness dependency.)
            self._kick_digests()

    @staticmethod
    def _topology_size() -> int:
        """Current world size, 1 when hvd.init() has not run yet —
        commit() stays purely local and construction-before-init keeps
        working (the pre-r15 contract)."""
        try:
            return basics.state().topology.size
        except Exception:
            return 1

    # -- async digest precompute --------------------------------------------

    def _kick_digests(self) -> None:
        if self._digest_thread is None:
            self._digest_thread = threading.Thread(
                target=self._digest_loop, name="hvd-ckpt-digest",
                daemon=True)
            self._digest_thread.start()
        self._digest_wake.set()

    def close(self) -> None:
        """Release the background workers (digest thread + disk writer).
        Optional — both are daemons — but a process constructing many
        States (benches, tests) should not accumulate pinned snapshots."""
        self._digest_stop.set()
        self._digest_wake.set()
        thread = self._digest_thread
        if thread is not None:
            thread.join(timeout=10.0)
        if self._writer is not None:
            self._writer.close()
        # Release the shard provider (it closes over this State's whole
        # committed snapshot) — unless a newer State took it over.
        shards_mod.exchange().clear_provider(self)

    def _digest_loop(self) -> None:
        while not self._digest_stop.is_set():
            if not self._digest_wake.wait(timeout=0.5):
                continue
            self._digest_wake.clear()
            if self._digest_stop.is_set():
                return
            try:
                cid = self._commit_id
                flat, _td, array_ids, _obj = self._flat_commit()
                layout = self._layout(flat, array_ids, self._commit_world)
                digests = self._hash_layout(flat, layout)
                if self._commit_id == cid:
                    # Verified unchanged: a commit racing this pass just
                    # re-kicked the worker; its table lands next round.
                    self._digest_table = (
                        cid, tuple(tuple(ids) for ids in layout), digests)
            except Exception as exc:
                logging.debug("elastic: digest precompute failed: %s", exc)

    @staticmethod
    def _hash_layout(flat: List[Any], layout: List[List[int]]
                     ) -> List[Optional[str]]:
        """Per-shard digests of this process's committed leaves under an
        arbitrary (possibly the authority's) layout; None where a shard
        references leaves this rank cannot hash (index out of range, or
        an object leaf where the authority has an array)."""
        out: List[Optional[str]] = []
        for ids in layout:
            if any(i >= len(flat) or not _leaf_is_array(flat[i])
                   for i in ids):
                out.append(None)
                continue
            try:
                out.append(ckpt.shard_digest(
                    [np.ascontiguousarray(np.asarray(flat[i]))
                     for i in ids]))
            except Exception:
                # Unreadable leaf (e.g. a jax buffer deleted by a
                # donated jit): treated as a mismatch — the shard
                # re-fetches from a peer instead of crashing.
                out.append(None)
        return out

    def _digests_for(self, layout: List[List[int]]
                     ) -> List[Optional[str]]:
        """The digest table for ``layout`` against the current commit:
        the precomputed one when it matches, else an inline pass."""
        table = self._digest_table
        key = tuple(tuple(ids) for ids in layout)
        if (table is not None and table[0] == self._commit_id
                and table[1] == key):
            return table[2]
        flat = self._flat_commit()[0]
        return self._hash_layout(flat, layout)

    def _flat_commit(self) -> tuple:
        """``(flat, treedef, array_ids, objects)`` of the committed dict
        — flat leaves in jax tree order, the indices that shard (real
        arrays), and the object leaves that ride metadata instead.
        Cached per commit."""
        cached = self._flat_cache  # snapshot: the provider thread reads
        # this concurrently with commit() replacing it; a stale snapshot
        # only yields a digest mismatch, which the fetch plane treats as
        # "no matching copy here".
        if cached is not None and cached[0] == self._commit_id:
            return cached[1]
        import jax

        # Capture the id FIRST, then ONE reference to the committed dict
        # (commit() replaces the whole dict, never mutates it, and
        # publishes it before bumping the id): the flatten below can
        # never mix leaves of two commits, and a racing capture caches
        # under a dead id instead of poisoning the current one.
        cid = self._commit_id
        committed = self._committed
        tree = {name: committed[name] for name in self._names}
        flat, treedef = jax.tree_util.tree_flatten(tree)
        array_ids = [i for i, leaf in enumerate(flat)
                     if _leaf_is_array(leaf)]
        objects = {i: leaf for i, leaf in enumerate(flat)
                   if not _leaf_is_array(leaf)}
        out = (flat, treedef, array_ids, objects)
        self._flat_cache = (cid, out)
        return out

    def _layout(self, flat: List[Any], array_ids: List[int],
                world: int) -> List[List[int]]:
        """Flat-id shard map for this commit: the deterministic
        lightest-shard walk over array-leaf byte sizes. Sizes come from
        the leaves' own ``nbytes`` — never np.asarray, which would be a
        blocking device-to-host copy per jax leaf on the step loop."""
        nbytes = [int(flat[i].nbytes) for i in array_ids]
        positions = ckpt.shard_layout(nbytes, world)
        return [[array_ids[p] for p in shard] for shard in positions]

    def _submit_shards(self) -> None:
        st = basics.state()
        rank = st.topology.rank
        world = self._commit_world
        if rank >= world:
            return
        flat, _treedef, array_ids, objects = self._flat_commit()
        layout = self._layout(flat, array_ids, world)
        # RAW leaf references, no conversion: np.asarray on a jax leaf
        # is a blocking device-to-host copy, and the whole point of the
        # async tier is that the step loop never pays one. pack_shard /
        # shard_digest convert on the writer thread; the committed
        # snapshot is immutable, so the references stay valid.
        mine = [flat[i] for i in layout[rank]]
        step = self._save_step
        self._save_step += 1
        manifest = None
        if rank == 0:
            epoch_now = epoch()

            def build_manifest(flat=flat, layout=layout, objects=objects,
                               step=step, world=world,
                               epoch_now=epoch_now):
                # Materialize + digest the WHOLE commit on the writer
                # thread — neither the transfer nor the hash ever runs
                # on the step loop.
                digests = [ckpt.shard_digest(
                    [np.ascontiguousarray(np.asarray(flat[i]))
                     for i in ids]) for ids in layout]
                return {"step": step, "epoch": epoch_now,
                        "world_size": world, "layout": layout,
                        "digests": digests,
                        "objects_hex": ckpt.pack_objects(objects)}

            manifest = build_manifest
        self._writer.submit(step, rank, world, mine, manifest=manifest)

    # ------------------------------------------------------------- restore

    def restore(self) -> None:
        """Roll every tracked value back to the last commit, consistent
        with rank 0 on every member of the new epoch — joiners included.
        Under elastic membership this is the p2p path (digest-matched
        survivors move zero bytes); otherwise rank 0 re-broadcasts."""
        t0 = time.monotonic()
        st = basics.state()
        mon = metrics.on()
        if st.topology.size <= 1:
            for name in self._names:
                setattr(self, name, copy.deepcopy(self._committed[name]))
        elif (config_mod.elastic_enabled()
                and config_mod.elastic_restore_mode() == "p2p"
                and self._p2p_capable(st)):
            self._restore_p2p(st)
        else:
            self._restore_broadcast(st)
        if mon:
            _elastic_metrics().restore_seconds.observe(
                time.monotonic() - t0)

    def _p2p_capable(self, st) -> bool:
        """The shard plane rides the python engine's TCP star; any other
        controller shape (native engine, no controller) keeps the
        broadcast path."""
        ctl = st.controller
        return (ctl is not None and hasattr(ctl, "clear_reshape_fence")
                and (getattr(ctl, "_service", None) is not None
                     or getattr(ctl, "_client", None) is not None))

    def _install_exchange(self) -> None:
        try:
            st = basics.state()
        except Exception:
            return  # before hvd.init(): restore installs it later
        if not self._p2p_capable(st):
            return
        ex = shards_mod.exchange()
        ex.install(st.controller)
        ex.set_provider(shards_mod.make_memory_provider(
            lambda: self._flat_commit()[0]), owner=self)

    def _restore_broadcast(self, st) -> None:
        """Legacy rank-0 whole-pytree re-sync — one materialization per
        tracked value (the committed snapshot is broadcast as-is and the
        live attribute is the single fresh copy)."""
        restored: Dict[str, Any] = {}
        for name in self._names:
            value = self._committed[name]
            if _is_array_tree(value):
                from ..jax import broadcast_parameters

                value = broadcast_parameters(value, root_rank=0)
            else:
                from ..ops.collective_ops import broadcast_object

                value = broadcast_object(
                    value, root_rank=0, name=f"elastic.state.{name}")
            restored[name] = value
            setattr(self, name, copy.deepcopy(value))
        # Whole-dict swap (lock-free reader contract), then invalidate
        # the flat/digest caches exactly like the p2p rebuild does. No
        # digest kick: the table only feeds _restore_p2p, which this
        # mode — by definition — never runs (commit() has the same
        # guard); a later mode flip recomputes inline once.
        self._committed = restored
        self._commit_id += 1
        self._flat_cache = None
        self._digest_table = None
        if metrics.on() and st.topology.rank != 0:
            # Root received nothing — only non-root ranks count the
            # re-broadcast bytes as transferred. Leaf .nbytes, never
            # np.asarray: the count must not itself transfer the model.
            flat = self._flat_commit()[0]
            nbytes = sum(int(leaf.nbytes) for leaf in flat
                         if _leaf_is_array(leaf))
            _elastic_metrics().restore_bytes.labels("peer").inc(nbytes)

    # -- the p2p path -------------------------------------------------------

    def _authority_meta(self) -> dict:
        """Rank 0's view of its commit, as tiny metadata: layout +
        per-shard digests + the object leaves. O(model) HASHING, O(1)
        bytes on the wire."""
        flat, _treedef, array_ids, objects = self._flat_commit()
        layout = self._layout(flat, array_ids, self._commit_world)
        digests: List[Optional[str]] = self._digests_for(layout)
        if any(d is None for d in digests):
            # Rank 0 IS the root of truth: an unreadable committed leaf
            # here (e.g. a jax buffer deleted by a donated jit) leaves
            # nothing for peers to heal FROM — fail loudly instead of
            # publishing digests no holder and no manifest can match.
            raise RuntimeError(
                "elastic: rank 0's committed state is unreadable (a "
                "tracked jax buffer was deleted, e.g. by a donated jit "
                "argument); p2p restore has no authority to serve — "
                "resume from the disk tier (restore_latest_sharded) or "
                "re-commit readable values")
        return {
            "commit_id": self._commit_id,
            "world": self._commit_world,
            "nleaves": len(flat),
            "layout": layout,
            "digests": digests,
            # Writer-step alignment: every member adopts rank 0's next
            # save step at restore, so a joiner's counter (seeded from
            # its own disk scan) can't desync the shard/manifest step
            # namespace and leave every post-join step incomplete.
            "save_step": self._save_step,
            "objects_hex": ckpt.pack_objects(objects),
        }

    def _match_bitmap(self, meta: dict) -> List[bool]:
        """Which authority shards this rank's committed copy already
        holds byte-exactly (digest over the authority's layout; the
        table is usually precomputed by the hvd-ckpt-digest thread, so
        this is O(shards) on the recovery path, not O(model))."""
        mine = self._digests_for(meta["layout"])
        return [m is not None and m == digest
                for m, digest in zip(mine, meta["digests"])]

    def _restore_p2p(self, st) -> None:
        from ..ops.collective_ops import allgather_object, broadcast_object

        self._install_exchange()
        rank = st.topology.rank
        size = st.topology.size
        # 1. Authority metadata from rank 0 (tiny), then every member's
        # per-shard match bitmap (tinier). Both ride the ordinary
        # negotiated collectives, so a reshape tears them with the same
        # retryable RanksChangedError as any in-flight work.
        meta = broadcast_object(
            self._authority_meta() if rank == 0 else None,
            root_rank=0, name="elastic.restore.meta")
        bitmap = self._match_bitmap(meta) if rank != 0 \
            else [True] * len(meta["layout"])
        bitmaps = allgather_object(bitmap, name="elastic.restore.holders")
        holders: List[List[int]] = []
        for k in range(len(meta["layout"])):
            holders.append([r for r in range(size)
                            if k < len(bitmaps[r]) and bitmaps[r][k]])
        # 2. Fetch what's missing. Owners rotate over the holder set per
        # shard, so a joiner's pulls spread across survivors instead of
        # re-serializing on rank 0 (rank 0 is always a holder — it IS
        # the authority — so every chain is non-empty).
        flat, treedef, _array_ids, _objects = self._flat_commit()
        if len(flat) != meta["nleaves"]:
            raise ValueError(
                f"elastic: this rank tracks {len(flat)} leaves but rank "
                f"0's commit has {meta['nleaves']} — State structure must "
                "match across members")
        mon = metrics.on()
        fetched: Dict[int, List[np.ndarray]] = {}
        missing = [k for k in range(len(meta["layout"])) if not bitmap[k]]
        chains: Dict[int, List[int]] = {}
        first: Dict[int, Any] = {}
        ex = shards_mod.exchange()
        for k in missing:
            chain = [holders[k][(k + j) % len(holders[k])]
                     for j in range(len(holders[k]))]
            chain = [r for i, r in enumerate(chain)
                     if r != rank and r not in chain[:i]]
            chains[k] = chain
            if chain:
                # First-choice fetches go out together; stragglers and
                # fallbacks resolve per shard below.
                first[k] = ex.fetch_async(k, meta["digests"][k],
                                          meta["layout"][k], chain[0])
        local_bytes = 0
        for k in missing:
            arrays = None
            source = "peer"
            f = first.get(k)
            if f is not None and ex.wait(f) and f.data:
                try:
                    arrays = ckpt.unpack_shard(
                        f.data, expect_digest=meta["digests"][k])
                except ValueError:
                    arrays = None
            if arrays is None:
                arrays, source = shards_mod.fetch_shard(
                    ex, k, meta["digests"][k], meta["layout"][k],
                    chains[k][1:], disk_dir=self.checkpoint_dir
                    or config_mod.elastic_ckpt_dir())
            fetched[k] = arrays
            if mon:
                m = _elastic_metrics()
                m.fetches.labels(source).inc()
                m.restore_bytes.labels(source).inc(
                    sum(int(a.nbytes) for a in arrays))
        # 3. Rebuild: matched shards keep the local committed copy (the
        # live attribute is the single fresh materialization), fetched
        # shards replace it, object leaves adopt rank 0's verbatim.
        committed_flat = list(flat)
        live_flat: List[Any] = [None] * len(flat)
        for k, ids in enumerate(meta["layout"]):
            if bitmap[k]:
                for i in ids:
                    if mon:
                        # Leaf .nbytes, never np.asarray: the zero-copy
                        # survivor path must not transfer the model just
                        # to count the bytes it did NOT move.
                        local_bytes += int(flat[i].nbytes)
                    live_flat[i] = _materialize_live(flat[i])
            else:
                for i, arr in zip(ids, fetched[k]):
                    if _is_jax_leaf(flat[i]):
                        import jax.numpy as jnp

                        arr = jnp.asarray(arr)
                    committed_flat[i] = arr
                    live_flat[i] = _materialize_live(arr)
        for i, obj in ckpt.unpack_objects(meta).items():
            committed_flat[int(i)] = obj
            live_flat[int(i)] = copy.deepcopy(obj)
        for i in range(len(flat)):
            if live_flat[i] is None:  # a leaf in no shard and no blob
                live_flat[i] = copy.deepcopy(committed_flat[i])
        import jax

        committed_tree = jax.tree_util.tree_unflatten(
            treedef, committed_flat)
        live_tree = jax.tree_util.tree_unflatten(treedef, live_flat)
        # Whole-dict swap, never in-place mutation: _flat_commit's
        # lock-free readers rely on any _committed they captured staying
        # internally consistent.
        self._committed = {name: committed_tree[name]
                           for name in self._names}
        for name in self._names:
            setattr(self, name, live_tree[name])
        if int(meta.get("save_step", -1)) >= 0 and self._writer is not None:
            self._save_step = int(meta["save_step"])
        if missing or ckpt.pack_objects(_objects) != meta.get(
                "objects_hex"):
            # Committed content changed (fetched shards / adopted
            # objects): this IS a new commit for caching purposes — a
            # stale digest table surviving here would mis-compare every
            # fetched shard on the NEXT restore and re-fetch bytes this
            # rank now holds byte-exactly. The bump also invalidates any
            # digest-loop pass racing this restore. The all-match case
            # (every survivor, every reshape) keeps its still-valid
            # table: the zero-hash recovery path stays zero-hash.
            self._commit_id += 1
            self._flat_cache = None
            self._digest_table = None
            self._kick_digests()
        if mon:
            _elastic_metrics().restore_bytes.labels("local").inc(
                local_bytes)
            metrics.record_sampled_event(
                "elastic_restore", missing=len(missing),
                shards=len(meta["layout"]), local_bytes=local_bytes)


def _acknowledge_reshape() -> None:
    """Clear the controller's reshape fence: collectives enqueued from
    here on ride the new epoch (until then they fail with the same
    RanksChangedError their drained siblings got)."""
    ctl = basics.state().controller
    if ctl is not None and hasattr(ctl, "clear_reshape_fence"):
        ctl.clear_reshape_fence()


def run(func):
    """Decorate the training loop for elastic execution (reference
    ``hvd.elastic.run`` shape): sync state from rank 0, run ``func(state,
    *args, **kwargs)``, and on :class:`RanksChangedError` — a reshape
    interrupted the loop — restore and run it again. Any other exception
    propagates unchanged."""

    @functools.wraps(func)
    def wrapper(state: State, *args, **kwargs):
        while True:
            try:
                _acknowledge_reshape()
                state.restore()
                return func(state, *args, **kwargs)
            except RanksChangedError as exc:
                logging.warning(
                    "elastic: %s; restoring state and resuming the "
                    "training loop", exc)
                continue

    return wrapper
