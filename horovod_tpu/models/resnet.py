"""ResNet v1.5 family in flax — the framework's flagship vision benchmark
models.

The reference benchmarks ResNet-50/101 through tf_cnn_benchmarks and ships
``examples/keras_imagenet_resnet50.py`` / ``examples/pytorch_imagenet_resnet50.py``
(SURVEY.md §6, ``docs/benchmarks.md:10-34``). This is a from-scratch
TPU-first implementation, not a port: NHWC layout (XLA's native conv layout
on TPU), bfloat16 activations with float32 parameters/batch-stats, and large
fused convolutions that tile cleanly onto the MXU.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any


class BottleneckBlock(nn.Module):
    """1x1 -> 3x3 -> 1x1 bottleneck with projection shortcut (v1.5: stride
    on the 3x3)."""

    filters: int
    strides: Tuple[int, int]
    conv: ModuleDef
    norm: ModuleDef
    act: Callable

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3), self.strides)(y)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters * 4, (1, 1), self.strides,
                                 name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class ResNet(nn.Module):
    """ResNet v1.5. ``dtype`` is the activation/compute dtype; parameters and
    batch statistics stay float32 (bf16 activations keep the MXU fed at
    double rate while fp32 master weights preserve convergence)."""

    stage_sizes: Sequence[int]
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype,
                       param_dtype=jnp.float32)
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5, dtype=self.dtype,
                       param_dtype=jnp.float32)
        act = nn.relu

        x = jnp.asarray(x, self.dtype)
        x = conv(self.num_filters, (7, 7), (2, 2), padding=[(3, 3), (3, 3)],
                 name="conv_init")(x)
        x = norm(name="bn_init")(x)
        x = act(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = BottleneckBlock(
                    filters=self.num_filters * 2 ** i,
                    strides=strides, conv=conv, norm=norm, act=act)(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32,
                     param_dtype=jnp.float32, name="head")(x)
        return x


ResNet18Sizes = [2, 2, 2, 2]  # (uses bottleneck here; kept for tiny tests)
ResNet50 = partial(ResNet, stage_sizes=[3, 4, 6, 3])
ResNet101 = partial(ResNet, stage_sizes=[3, 4, 23, 3])
ResNet152 = partial(ResNet, stage_sizes=[3, 8, 36, 3])
# Tiny variant for hermetic CPU tests / multichip dry runs.
ResNetTiny = partial(ResNet, stage_sizes=[1, 1], num_filters=8, num_classes=10)
