"""MNIST MLP — the smallest end-to-end model, mirroring the role of the
reference's mnist examples (``examples/pytorch_mnist.py`` et al.) as the
smoke-test architecture."""

from __future__ import annotations

from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp


class MnistMLP(nn.Module):
    features: Sequence[int] = (128, 64)
    num_classes: int = 10
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        x = x.reshape((x.shape[0], -1)).astype(self.dtype)
        for f in self.features:
            x = nn.relu(nn.Dense(f, dtype=self.dtype)(x))
        return nn.Dense(self.num_classes, dtype=jnp.float32)(x)
