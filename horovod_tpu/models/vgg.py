"""VGG family in flax — the third model of the reference's headline benchmark
table (68% scaling efficiency for VGG-16 at 512 GPUs, reference
``README.md:58``, ``docs/benchmarks.md:6``).

TPU-first: NHWC layout, bfloat16 activations / float32 parameters, and the
classifier MLP expressed as plain Dense layers so the big 25088x4096 matmul
lands on the MXU in bf16. VGG is deliberately the communication-heavy member
of the benchmark set (138M parameters, mostly in the classifier) — it is the
model that stresses gradient all-reduce bandwidth rather than compute, which
is why the reference reports its scaling separately.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp


class VGG(nn.Module):
    """VGG-A/B/D/E ("11/13/16/19-layer") convnet.

    ``stage_sizes`` gives the number of 3x3 convs per stage; each stage ends
    with a 2x2 max-pool. ``num_filters`` doubles per stage, capped at 512.
    """

    stage_sizes: Sequence[int]
    num_classes: int = 1000
    num_filters: int = 64
    classifier_width: int = 4096
    dropout_rate: float = 0.5
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(nn.Conv, kernel_size=(3, 3), padding="SAME",
                       dtype=self.dtype, param_dtype=jnp.float32)
        x = jnp.asarray(x, self.dtype)
        for i, n_convs in enumerate(self.stage_sizes):
            filters = min(self.num_filters * 2 ** i, 512)
            for j in range(n_convs):
                x = conv(filters, name=f"conv{i}_{j}")(x)
                x = nn.relu(x)
            x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        for k in range(2):
            x = nn.Dense(self.classifier_width, dtype=self.dtype,
                         param_dtype=jnp.float32, name=f"fc{k}")(x)
            x = nn.relu(x)
            x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        x = nn.Dense(self.num_classes, dtype=jnp.float32,
                     param_dtype=jnp.float32, name="head")(x)
        return x


VGG11 = partial(VGG, stage_sizes=[1, 1, 2, 2, 2])
VGG13 = partial(VGG, stage_sizes=[2, 2, 2, 2, 2])
VGG16 = partial(VGG, stage_sizes=[2, 2, 3, 3, 3])
VGG19 = partial(VGG, stage_sizes=[2, 2, 4, 4, 4])
# Tiny variant for hermetic CPU tests.
VGGTiny = partial(VGG, stage_sizes=[1, 1], num_filters=8,
                  classifier_width=32, num_classes=10)
