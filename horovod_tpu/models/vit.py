"""Vision Transformer in flax — the vision counterpart of the BERT/Llama
transformer stack.

The reference's vision benchmarks are CNNs (ResNet/Inception/VGG,
``docs/benchmarks.md``); ViT extends the model zoo with the architecture
modern vision training actually scales — and it is a pure win on TPU: the
patch embedding is one strided conv (a single MXU matmul per patch grid) and
everything after is the same MXU-friendly einsum attention the language
models use, so the flash-attention kernel seam (``attention_fn``), remat,
and the DP/TP/FSDP shardings all apply unchanged.

TPU-first choices mirror ``bert.py``: bfloat16 activations / fp32 params,
static shapes, pre-LN blocks (ViT convention), ``jax.checkpoint`` per block
under ``remat``.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional

import flax.linen as nn
import jax.numpy as jnp

from .bert import SelfAttention
from .llama import token_nll


@dataclasses.dataclass(frozen=True)
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    num_classes: int = 1000
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    dropout_rate: float = 0.0
    dtype: Any = jnp.bfloat16
    # Classification-head compute dtype; None = model dtype (see
    # LlamaConfig.head_dtype).
    head_dtype: Any = None
    # jax.checkpoint each block in the backward pass (see LlamaConfig.remat).
    remat: bool = False

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2


VIT_B16 = ViTConfig()
VIT_S16 = ViTConfig(hidden_size=384, num_heads=6, intermediate_size=1536)
VIT_TINY = ViTConfig(image_size=32, patch_size=8, num_classes=10,
                     hidden_size=64, num_layers=2, num_heads=2,
                     intermediate_size=128)


class ViTBlock(nn.Module):
    """Pre-LN transformer block (the ViT/GPT convention; BERT's blocks are
    post-LN, so this is its own module while the attention core is shared)."""

    config: Any  # ViTConfig; SelfAttention reads the shared field subset
    attention_fn: Optional[Callable] = None

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        cfg = self.config
        h = nn.LayerNorm(dtype=cfg.dtype, param_dtype=jnp.float32)(x)
        h = SelfAttention(cfg, attention_fn=self.attention_fn)(
            h, mask=None, deterministic=deterministic)
        h = nn.Dropout(cfg.dropout_rate)(h, deterministic=deterministic)
        x = x + h
        h = nn.LayerNorm(dtype=cfg.dtype, param_dtype=jnp.float32)(x)
        h = nn.Dense(cfg.intermediate_size, dtype=cfg.dtype,
                     param_dtype=jnp.float32)(h)
        h = nn.gelu(h)
        h = nn.Dense(cfg.hidden_size, dtype=cfg.dtype,
                     param_dtype=jnp.float32)(h)
        h = nn.Dropout(cfg.dropout_rate)(h, deterministic=deterministic)
        return x + h


class VisionTransformer(nn.Module):
    """Patch embed + CLS token + pre-LN encoder + classification head."""

    config: ViTConfig
    attention_fn: Optional[Callable] = None

    @nn.compact
    def __call__(self, images, deterministic: bool = True):
        """``images``: (B, H, W, C) floats (NHWC, the TPU conv layout)."""
        cfg = self.config
        b = images.shape[0]
        # Patch embedding as ONE strided conv: XLA lowers it to a single
        # (B*patches, p*p*C) x (p*p*C, hidden) MXU matmul.
        x = nn.Conv(cfg.hidden_size,
                    kernel_size=(cfg.patch_size, cfg.patch_size),
                    strides=(cfg.patch_size, cfg.patch_size),
                    dtype=cfg.dtype, param_dtype=jnp.float32,
                    name="patch_embed")(images.astype(cfg.dtype))
        x = x.reshape(b, -1, cfg.hidden_size)  # (B, patches, hidden)

        cls = self.param("cls_token", nn.initializers.zeros,
                         (1, 1, cfg.hidden_size), jnp.float32)
        x = jnp.concatenate(
            [jnp.broadcast_to(cls, (b, 1, cfg.hidden_size)).astype(cfg.dtype),
             x], axis=1)
        pos = self.param("position_embeddings",
                         nn.initializers.normal(stddev=0.02),
                         (1, cfg.num_patches + 1, cfg.hidden_size),
                         jnp.float32)
        x = x + pos.astype(cfg.dtype)
        x = nn.Dropout(cfg.dropout_rate)(x, deterministic=deterministic)

        block_cls = (nn.remat(ViTBlock, static_argnums=(2,))
                     if cfg.remat else ViTBlock)
        for i in range(cfg.num_layers):
            x = block_cls(cfg, attention_fn=self.attention_fn,
                          name=f"layer_{i}")(x, deterministic)

        x = nn.LayerNorm(dtype=cfg.dtype, param_dtype=jnp.float32,
                         name="final_norm")(x)
        logits = nn.Dense(cfg.num_classes,
                          dtype=cfg.head_dtype or cfg.dtype,
                          param_dtype=jnp.float32, name="head")(x[:, 0])
        return logits


def classification_loss(logits, labels):
    """Mean cross entropy over the batch, lse-formulated (no (B, C) f32
    log-softmax materialization — ``llama.token_nll``)."""
    return token_nll(logits, labels).mean()
