"""Decoder-only transformer LM (Llama-style: RMSNorm, rotary embeddings,
SwiGLU, grouped-query attention).

No reference-repo equivalent (2019-era); required by the rebuild's target
workloads (BASELINE.json config "Llama-3-8B — stress fused allreduce at LLM
gradient sizes"). TPU-first: bf16 activations / fp32 params, einsum
attention with the same ``attention_fn`` seam as BERT (flash / ring
attention plug in), static shapes. GQA K/V stay at ``num_kv_heads`` through
attention fns that declare ``supports_gqa`` (the flash kernel routes query
heads to their K/V group in the grid — no repeat); others get repeated K/V.
"""

from __future__ import annotations

import contextlib
import dataclasses
import functools
from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 128256
    dim: int = 4096
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 8
    ffn_hidden: int = 14336
    rope_theta: float = 500000.0
    max_seq_len: int = 8192
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    # lm_head compute dtype; None = model dtype (bf16 — measured on v5e:
    # 215.4 vs 222.0 ms/step for f32, first-step loss identical to 4
    # decimals). Set jnp.float32 if downstream consumers of RAW logits
    # (perplexity eval, logit distillation) need full precision — the
    # in-tree losses upcast inside the lse reduction either way.
    head_dtype: Any = None
    # Rematerialize each block's activations in the backward pass
    # (jax.checkpoint): live activations drop from O(layers) to O(1)
    # layers' worth at ~1/3 extra FLOPs — the knob that lets sequence
    # length scale past what HBM holds at remat=False.
    remat: bool = False


LLAMA_8B = LlamaConfig()
LLAMA_1B = LlamaConfig(dim=2048, num_layers=16, num_heads=32, num_kv_heads=8,
                       ffn_hidden=8192)
# ~320M params: fits one 16 GB chip WITH f32 Adam state — the single-chip
# benchmark config. LLAMA_1B also trains single-chip by swapping the
# memory: adafactor (factored second moments) + chunked_causal_lm_loss
# runs 12.0k tok/s on a v5e (Adam moments alone would need ~8.8 GiB);
# Adam-state sharding across chips is the ZeRO-1 wrapper's job.
LLAMA_300M = LlamaConfig(vocab_size=32000, dim=1024, num_layers=16,
                         num_heads=16, num_kv_heads=8, ffn_hidden=4096)
LLAMA_TINY = LlamaConfig(vocab_size=512, dim=64, num_layers=2, num_heads=4,
                         num_kv_heads=2, ffn_hidden=128, max_seq_len=256)


class RMSNorm(nn.Module):
    eps: float = 1e-5
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        scale = self.param("scale", nn.initializers.ones, (x.shape[-1],),
                           jnp.float32)
        # All-f32 chain, deliberately: a bf16-application variant (f32
        # stats, bf16 multiply) measured SLOWER on v5e (56.0k vs 59.3k
        # tok/s Llama-300M — it splits the fused norm chain) and loosened
        # sp-parity tolerances. XLA fuses this form fully.
        x32 = x.astype(jnp.float32)
        norm = x32 * jnp.reciprocal(
            jnp.sqrt(jnp.mean(x32 ** 2, axis=-1, keepdims=True) + self.eps))
        return (norm * scale).astype(self.dtype)


def rotary_embedding(x, theta: float, positions=None):
    """Apply RoPE to (B, S, H, D). ``positions`` are the GLOBAL token
    positions of the rows — defaults to 0..S-1. Shape (S,) rotates every
    batch row alike (training, whole-batch decode); shape (B, S) gives
    each sequence its own positions (the serving tier's continuous
    batches mix sequences at heterogeneous decode positions). Under
    sequence parallelism each shard must pass its own global offsets
    (e.g. ``axis_index * S_local + arange(S_local)``) or every shard
    would rotate as if it held the sequence start."""
    b, s, h, d = x.shape
    half = d // 2
    freqs = 1.0 / (theta ** (np.arange(0, half, dtype=np.float32) / half))
    if positions is None:
        positions = jnp.arange(s, dtype=jnp.float32)
    # Angles/cos/sin in f32 (positional phase must not quantize: at
    # position 64k a bf16 angle would be off by whole radians), then the
    # APPLICATION runs in the activation dtype — the rotation factors are
    # in [-1, 1] where bf16 is at its densest, and the f32 elementwise
    # over (B, S, H, D) this replaces was ~8% of the Llama-300M step
    # (XProf round 3).
    angles = positions.astype(jnp.float32)[..., :, None] * freqs
    if angles.ndim == 2:                               # (S, half)
        cos = jnp.cos(angles)[None, :, None, :].astype(x.dtype)
        sin = jnp.sin(angles)[None, :, None, :].astype(x.dtype)
    else:                                              # (B, S, half)
        cos = jnp.cos(angles)[:, :, None, :].astype(x.dtype)
        sin = jnp.sin(angles)[:, :, None, :].astype(x.dtype)
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate(
        [x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)


class LlamaAttention(nn.Module):
    config: LlamaConfig
    attention_fn: Optional[Callable] = None

    @nn.compact
    def __call__(self, x, positions=None, cache=None, cache_index=None):
        """``cache``/``cache_index``: autoregressive-decoding mode (see
        :func:`init_kv_cache`). The new K/V rows are written into the
        static-shape cache at ``cache_index`` and attention runs against
        the whole window under an explicit positional mask; returns
        ``(out, new_cache)``. Training mode (``cache=None``) is unchanged.
        """
        cfg = self.config
        head_dim = cfg.dim // cfg.num_heads
        dense = lambda heads, name: nn.DenseGeneral(  # noqa: E731
            features=(heads, head_dim), axis=-1, use_bias=False,
            dtype=cfg.dtype, param_dtype=jnp.float32, name=name)
        q = rotary_embedding(dense(cfg.num_heads, "wq")(x), cfg.rope_theta,
                             positions)
        k = rotary_embedding(dense(cfg.num_kv_heads, "wk")(x),
                             cfg.rope_theta, positions)
        v = dense(cfg.num_kv_heads, "wv")(x)
        out_proj = nn.DenseGeneral(features=cfg.dim, axis=(-2, -1),
                                   use_bias=False, dtype=cfg.dtype,
                                   param_dtype=jnp.float32, name="wo")

        if cache is not None:
            ctx, new_cache = _cached_attention(q, k, v, cache, cache_index)
            return out_proj(ctx), new_cache

        # flash_attention / reference_attention / ring_attention handle
        # grouped K/V heads natively (the flash grid routes each query
        # head to its group's K/V row — no repeated K/V copy in HBM; the
        # ring rotates Hkv-head blocks, Hkv/H the ICI bytes). Repeat only
        # for attention_fns that don't declare GQA support via a
        # ``supports_gqa`` attribute.
        gqa_native = (self.attention_fn is None
                      or getattr(self.attention_fn, "supports_gqa", False))
        if not gqa_native:
            from ..ops.attention import repeat_kv

            k, v = repeat_kv(q, k, v)
        if self.attention_fn is not None:
            ctx = self.attention_fn(q, k, v, None)
        else:
            from ..ops.attention import reference_attention

            ctx = reference_attention(q, k, v, causal=True)
        return out_proj(ctx)


# Trace-time switch for the Pallas decode-attention fast path. Default on:
# the kernel consumes the cache in the default major-to-minor layout, which
# frees XLA to keep the loop-carried cache d-minor and make the per-step
# one-row cache write a true in-place update (the XLA formulation forces a
# seq-minor layout whose one-row update rewrites the whole buffer —
# artifacts/decode_ceiling_r5.json). generate() classifies the variables'
# sharding (see classify_decode_sharding): heads-sharded-on-TP meshes ride
# the kernel through shard_map (``_DECODE_TP``); exotic shardings fall back
# to the einsum path, which GSPMD shards naturally.
_DECODE_KERNEL = True
# When set, single-token cached attention runs the kernel per-shard inside
# ``jax.shard_map``: (mesh, head_axis, batch_axis).
_DECODE_TP = None


@contextlib.contextmanager
def decode_kernel_disabled():
    """Within this context, single-token cached attention uses the plain
    XLA einsum path instead of the Pallas kernel (trace-time static)."""
    global _DECODE_KERNEL
    prev = _DECODE_KERNEL
    _DECODE_KERNEL = False
    try:
        yield
    finally:
        _DECODE_KERNEL = prev


@contextlib.contextmanager
def _decode_tp_override(value):
    global _DECODE_TP
    prev = _DECODE_TP
    _DECODE_TP = value
    try:
        yield
    finally:
        _DECODE_TP = prev


def decode_kernel_sharded(mesh, head_axis: str, batch_axis=None):
    """Within this context, single-token cached attention runs the Pallas
    kernel per-shard inside ``jax.shard_map`` over ``head_axis`` (the TP
    axis sharding attention heads), with the one-row cache write kept
    in-place per shard (trace-time static; see
    ``ops.decode_attention.sharded_decode_step``)."""
    return _decode_tp_override((mesh, head_axis, batch_axis))


def decode_path_context(path: str, mesh=None, head_axis=None,
                        batch_axis=None):
    """THE path -> trace-time-context switch, shared by ``_decode`` and
    the serving engine's compiled programs — one place decides what each
    classifier verdict means. ``"kernel"`` explicitly CLEARS any ambient
    TP context: the traced program must match its jit cache key, not
    whatever context the caller happens to hold."""
    if path == "kernel_tp":
        return decode_kernel_sharded(mesh, head_axis, batch_axis)
    if path == "kernel":
        return _decode_tp_override(None)
    return decode_kernel_disabled()


def _cached_attention(q, k, v, cache, cache_index):
    """Decode-mode attention: write the s new K/V rows at ``cache_index``,
    attend every query (global position ``cache_index + i``) over the full
    static window under ``key_pos <= q_pos``. Masked logits hit
    exp(-inf) = 0 exactly, so the softmax equals the one over only the
    valid prefix. Grouped-query: queries attend their K/V group directly
    (no repeated K/V in the cache).

    Four code paths, one semantics: single-token steps ride the Pallas
    decode kernel (see ``_DECODE_KERNEL`` above — it keeps the carried
    cache in a layout where the row write is in-place), per-shard inside
    ``shard_map`` when the TP mesh shards heads (``_DECODE_TP``); prefill
    at static index 0 attends over the FRESH rows so no matmul ever
    consumes the cache buffers (a dot on them would re-pin the seq-minor
    layout the kernel path exists to avoid); the general chunked-append
    form (traced or nonzero index with s > 1) keeps the reference
    masked-window einsum. Each path is labeled with a
    ``jax.named_scope("hvd.decode.<path>")`` so the chosen path is
    attributable from HLO metadata and profiler traces
    (``utils.comm_accounting.decode_path_markers``)."""
    b, s, h, d = q.shape
    hkv = k.shape[2]
    group = h // hkv
    # The cache is stored ROW-FLAT, (B, L, Hkv*D): the decode kernel then
    # consumes it with no reshape anywhere near the buffers (an XLA-side
    # split of the flat axis would re-open the layout question; an
    # in-kernel split of tiled minor dims is not Mosaic-legal).
    kc = k.astype(cache["k"].dtype)
    vc = v.astype(cache["v"].dtype)
    scale = 1.0 / np.sqrt(d)
    if "tables" in cache:
        # PAGED decode (hvd.serving): the cache entry is the shared
        # block pool plus this batch's block tables, and ``cache_index``
        # is the per-sequence position VECTOR (B,) — one batch mixes
        # sequences at heterogeneous decode positions (continuous
        # batching). Prefill never lands here: it runs on a contiguous
        # scratch cache and the engine scatters whole blocks into the
        # pool (serving.engine._paged_prefill).
        if s != 1:
            raise ValueError(
                f"paged cache is single-token decode only (s={s})")
        from ..ops.decode_attention import (
            paged_cache_write,
            paged_decode_attention,
            paged_gather_attention,
            sharded_paged_decode_step,
        )

        tables = cache["tables"]
        if _DECODE_KERNEL and _DECODE_TP is not None:
            mesh, head_axis, batch_axis = _DECODE_TP
            with jax.named_scope("hvd.decode.paged_tp"):
                ctx, k_pool, v_pool = sharded_paged_decode_step(
                    q, kc, vc, cache["k"], cache["v"], tables,
                    cache_index, hkv, mesh=mesh, head_axis=head_axis,
                    batch_axis=batch_axis, sm_scale=scale)
        else:
            k_pool, v_pool = paged_cache_write(
                cache["k"], cache["v"], kc, vc, tables, cache_index)
            if _DECODE_KERNEL:
                with jax.named_scope("hvd.decode.paged"):
                    ctx = paged_decode_attention(
                        q, k_pool, v_pool, tables, cache_index, hkv,
                        sm_scale=scale)
            else:
                # The gather-einsum fallback shares the einsum marker:
                # it IS the einsum path, reading through the tables.
                with jax.named_scope("hvd.decode.einsum"):
                    ctx = paged_gather_attention(
                        q, k_pool, v_pool, tables, cache_index, hkv,
                        sm_scale=scale)
        return ctx, {"k": k_pool, "v": v_pool, "tables": tables}
    if s == 1 and _DECODE_KERNEL and _DECODE_TP is not None:
        # TP-sharded serving: cache-row write AND kernel run per-shard
        # inside shard_map — the outer dynamic_update_slice below never
        # touches the sharded cache buffers.
        from ..ops.decode_attention import sharded_decode_step

        mesh, head_axis, batch_axis = _DECODE_TP
        with jax.named_scope("hvd.decode.kernel_tp"):
            ctx, k_cache, v_cache = sharded_decode_step(
                q, kc, vc, cache["k"], cache["v"], cache_index, hkv,
                mesh=mesh, head_axis=head_axis, batch_axis=batch_axis,
                sm_scale=scale)
        return ctx, {"k": k_cache, "v": v_cache}
    k_cache = jax.lax.dynamic_update_slice(
        cache["k"], kc.reshape(b, s, hkv * d), (0, cache_index, 0))
    v_cache = jax.lax.dynamic_update_slice(
        cache["v"], vc.reshape(b, s, hkv * d), (0, cache_index, 0))
    window = k_cache.shape[1]
    if s == 1 and _DECODE_KERNEL:
        from ..ops.decode_attention import decode_attention

        with jax.named_scope("hvd.decode.kernel"):
            ctx = decode_attention(q, k_cache, v_cache, cache_index, hkv,
                                   sm_scale=scale)
        return ctx, {"k": k_cache, "v": v_cache}
    if s > 1 and isinstance(cache_index, int) and cache_index == 0:
        # Prefill at index 0: the valid window IS the fresh rows — no
        # matmul consumes the cache buffers (their layout must stay
        # friendly to the decode loop's row writes). Attend over the
        # CACHE-DTYPE rows (kc/vc), so prefill sees exactly the values
        # every later decode step reads back — one semantics across
        # paths even when the cache dtype quantizes.
        with jax.named_scope("hvd.decode.prefill"):
            qg = q.reshape(b, s, hkv, group, d)
            logits = jnp.einsum("bshgd,blhd->bshgl", qg, kc).astype(
                jnp.float32) * scale
            causal = (jnp.arange(s)[None, :] <= jnp.arange(s)[:, None])
            logits = jnp.where(causal[None, :, None, None, :], logits,
                               jnp.finfo(jnp.float32).min)
            probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
            ctx = jnp.einsum("bshgl,blhd->bshgd", probs, vc)
        return ctx.reshape(b, s, h, d), {"k": k_cache, "v": v_cache}
    # General path (einsum over the 4D view; also the s == 1 path under
    # exotic multi-device sharding — see _DECODE_KERNEL above).
    with jax.named_scope("hvd.decode.einsum"):
        qg = q.reshape(b, s, hkv, group, d)
        k4 = k_cache.reshape(b, window, hkv, d)
        v4 = v_cache.reshape(b, window, hkv, d)
        logits = jnp.einsum("bshgd,blhd->bshgl", qg, k4).astype(
            jnp.float32) * scale
        q_pos = cache_index + jnp.arange(s)
        key_pos = jnp.arange(window)
        mask = key_pos[None, :] <= q_pos[:, None]          # (s, window)
        logits = jnp.where(mask[None, :, None, None, :], logits,
                           jnp.finfo(jnp.float32).min)
        probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
        ctx = jnp.einsum("bshgl,blhd->bshgd", probs, v4).reshape(b, s, h, d)
    return ctx, {"k": k_cache, "v": v_cache}


def attention_sublayer(cfg, attention_fn, x, positions, cache, cache_index):
    """Pre-norm attention + residual, shared by ``LlamaBlock`` and
    ``MoeBlock`` so ONE place owns the cache protocol (plain function:
    flax submodules created here live in the calling module's compact
    scope, keeping the param names ``attention_norm``/``attention``).
    Returns ``(x, new_cache_or_None)``."""
    attn_in = RMSNorm(cfg.norm_eps, cfg.dtype, name="attention_norm")(x)
    attn = LlamaAttention(cfg, attention_fn=attention_fn, name="attention")
    if cache is None:
        return x + attn(attn_in, positions), None
    a, new_cache = attn(attn_in, positions, cache, cache_index)
    return x + a, new_cache


class LlamaBlock(nn.Module):
    config: LlamaConfig
    attention_fn: Optional[Callable] = None

    @nn.compact
    def __call__(self, x, positions=None, cache=None, cache_index=None):
        cfg = self.config
        x, new_cache = attention_sublayer(cfg, self.attention_fn, x,
                                          positions, cache, cache_index)
        h = RMSNorm(cfg.norm_eps, cfg.dtype, name="ffn_norm")(x)
        dense = lambda f, name: nn.Dense(  # noqa: E731
            f, use_bias=False, dtype=cfg.dtype, param_dtype=jnp.float32,
            name=name)
        gated = nn.silu(dense(cfg.ffn_hidden, "w_gate")(h)) * \
            dense(cfg.ffn_hidden, "w_up")(h)
        out = x + dense(cfg.dim, "w_down")(gated)
        return out if cache is None else (out, new_cache)


class LlamaLM(nn.Module):
    """Causal LM: embeddings + blocks + tied-free output head."""

    config: LlamaConfig
    attention_fn: Optional[Callable] = None

    @nn.compact
    def __call__(self, input_ids, positions=None, return_hidden=False,
                 cache=None, cache_index=None):
        """``positions``: global token positions of the local rows, shape
        (S,). Required under sequence parallelism (each shard passes its
        global offsets so RoPE rotates correctly); defaults to 0..S-1.
        ``return_hidden``: skip the lm_head and return the final-norm
        hidden states (B, S, dim) — pair with
        :func:`chunked_causal_lm_loss`.
        ``cache``/``cache_index``: autoregressive decoding — the rows are
        the tokens at global positions ``cache_index..cache_index+S-1``
        (RoPE positions default accordingly), the per-layer K/V land in
        the cache, and the call returns ``(logits, new_cache)``. Use
        :func:`init_kv_cache` + :func:`generate`."""
        cfg = self.config
        if cache is not None and positions is None:
            steps = jnp.arange(input_ids.shape[1])
            if getattr(cache_index, "ndim", 0):
                # Per-sequence positions (paged/serving decode): the
                # index is a (B,) vector, each row rotates at its own
                # global position.
                positions = cache_index[:, None] + steps
            else:
                positions = cache_index + steps
        x = nn.Embed(cfg.vocab_size, cfg.dim, param_dtype=jnp.float32,
                     name="tok_embeddings")(input_ids).astype(cfg.dtype)
        new_cache = {}
        block_cls = nn.remat(LlamaBlock) if cfg.remat else LlamaBlock
        for i in range(cfg.num_layers):
            if cache is None:
                x = block_cls(cfg, attention_fn=self.attention_fn,
                              name=f"layer_{i}")(x, positions)
            else:
                # Decoding never needs remat (no backward pass).
                x, new_cache[f"layer_{i}"] = LlamaBlock(
                    cfg, attention_fn=self.attention_fn,
                    name=f"layer_{i}")(x, positions, cache[f"layer_{i}"],
                                       cache_index)
        x = RMSNorm(cfg.norm_eps, cfg.dtype, name="final_norm")(x)
        if return_hidden:
            # For chunked_causal_lm_loss: the caller applies the lm_head
            # chunk-by-chunk so the (B, S, V) logits never materialize.
            return x
        # Head matmul in head_dtype (default: model compute dtype; MXU
        # accumulates f32 internally) — see LlamaConfig.head_dtype.
        logits = nn.Dense(cfg.vocab_size, use_bias=False,
                          dtype=cfg.head_dtype or cfg.dtype,
                          param_dtype=jnp.float32, name="lm_head")(x)
        return logits if cache is None else (logits, new_cache)


def init_kv_cache(cfg, batch_size: int, max_len: int, dtype=None):
    """Static-shape per-layer K/V cache for autoregressive decoding:
    ``{layer_i: {"k"/"v": (B, max_len, num_kv_heads * head_dim)}}`` —
    each position's GQA heads stored ROW-FLAT so the Pallas decode kernel
    consumes the buffers with no reshape (see ``_cached_attention``; the
    einsum paths view the flat axis as (Hkv, D)). GQA pays off directly
    here: the cache holds ``num_kv_heads`` head rows, an H/Hkv memory
    saving over repeating K/V (the reason GQA exists). ``cfg`` is any
    config with dim/num_heads/num_kv_heads/num_layers (``LlamaConfig`` or
    ``MoeConfig``)."""
    dtype = dtype or cfg.dtype
    head_dim = cfg.dim // cfg.num_heads
    # Windows past the decode kernel's single-tile VMEM budget get
    # L-tiled; round them to a 128 multiple so a decent tile DIVISOR
    # exists (<= +6% extra masked rows; small windows stay exact — no
    # read amplification where a single tile serves anyway).
    if max_len > 1024:
        max_len = (max_len + 127) // 128 * 128
    shape = (batch_size, max_len, cfg.num_kv_heads * head_dim)
    return {
        f"layer_{i}": {"k": jnp.zeros(shape, dtype),
                       "v": jnp.zeros(shape, dtype)}
        for i in range(cfg.num_layers)
    }


@dataclasses.dataclass(frozen=True)
class DecodePath:
    """Verdict of :func:`classify_decode_sharding`: which single-token
    decode path :func:`generate` traces, and why. ``generate`` records
    its last verdict in ``LAST_DECODE_PATH`` so harnesses and bench rows
    can prove which path ran (the HLO-metadata twin is
    ``utils.comm_accounting.decode_path_markers``)."""

    path: str                       # "kernel" | "kernel_tp" | "einsum"
    reason: str
    mesh: Any = None
    head_axis: Optional[str] = None
    batch_axis: Optional[str] = None


#: Last :class:`DecodePath` chosen by :func:`generate` (None before any
#: call). Read-only attribution for harnesses; not used for dispatch.
LAST_DECODE_PATH: Optional[DecodePath] = None


def _multi_device(leaf) -> bool:
    sh = getattr(leaf, "sharding", None)
    if sh is None:
        return False
    try:
        return (len(sh.device_set) > 1
                and not sh.is_fully_replicated)
    except (AttributeError, TypeError):
        return True  # unknown sharding type: take the safe path


def classify_decode_sharding(variables, prompt_ids,
                             num_kv_heads: int) -> DecodePath:
    """Pick the single-token decode path from the variables' shardings.

    Three-way dispatch (the blanket ``sharded -> einsum`` fallback this
    replaces threw away a measured ~47%-of-step win exactly on the
    multi-chip serving path):

    * nothing is sharded over a multi-device mesh → ``"kernel"`` (the
      single-device Pallas fast path, as before);
    * the Megatron TP pattern — attention projections sharded on the
      heads dim only, all on ONE mesh axis whose size divides
      ``num_kv_heads``, batch replicated or sharded on one other axis —
      → ``"kernel_tp"``: attention is per-head independent, so the
      kernel runs per-shard inside ``shard_map``
      (``ops.decode_attention.sharded_decode_step``) with in-place
      per-shard cache-row writes;
    * anything exotic (sequence-sharded prompt, uneven head splits,
      mixed meshes, non-Named shardings) → ``"einsum"``, which GSPMD
      shards naturally.
    """
    from ..parallel.mesh import common_mesh, sharding_axes

    leaves = jax.tree_util.tree_leaves((variables, prompt_ids))
    if not any(_multi_device(leaf) for leaf in leaves):
        return DecodePath("kernel", "replicated: single-device kernel")
    mesh = common_mesh((variables, prompt_ids))
    if mesh is None:
        return DecodePath(
            "einsum", "unknown sharding types or mixed meshes")

    # Megatron TP pattern: wq/wk/wv kernels (dim, heads, head_dim) may
    # shard ONLY dim 1, wo (heads, head_dim, dim) only dim 0 — all on
    # one axis.
    head_axes = set()
    clean = True

    def visit(path, leaf):
        nonlocal clean
        names = {getattr(p, "key", str(p)) for p in path}
        if "kernel" not in names:
            return
        proj = names & {"wq", "wk", "wv", "wo"}
        if not proj:
            return
        axes = sharding_axes(leaf)
        if axes is None:
            clean = _multi_device(leaf) is False and clean
            return
        head_dim = 0 if "wo" in proj else 1
        for i, dim_axes in enumerate(axes):
            if i == head_dim:
                if len(dim_axes) > 1:
                    clean = False
                head_axes.update(dim_axes)
            elif dim_axes:
                clean = False

    jax.tree_util.tree_map_with_path(visit, variables)
    if not clean:
        return DecodePath(
            "einsum", "attention params sharded off the heads dim")
    if len(head_axes) != 1:
        return DecodePath(
            "einsum",
            "attention heads not sharded on exactly one mesh axis "
            f"(axes={sorted(head_axes)})")
    (head_axis,) = head_axes
    tp = mesh.shape[head_axis]
    if num_kv_heads % tp:
        return DecodePath(
            "einsum", f"uneven head split: Hkv ({num_kv_heads}) % "
            f"tp ({tp}) != 0")

    batch_axis = None
    if _multi_device(prompt_ids):
        p_axes = sharding_axes(prompt_ids)
        if p_axes is None or any(p_axes[1:]) or len(p_axes[0]) > 1:
            return DecodePath(
                "einsum", "prompt sharded off the batch dim "
                "(sequence-sharded cache is exotic)")
        if p_axes[0]:
            (batch_axis,) = p_axes[0]
            if (batch_axis == head_axis
                    or prompt_ids.shape[0] % mesh.shape[batch_axis]):
                return DecodePath(
                    "einsum", f"batch axis {batch_axis!r} unusable "
                    "(clashes with head axis or uneven split)")
    return DecodePath(
        "kernel_tp",
        f"heads sharded on {head_axis!r} (tp={tp}): shard_mapped kernel",
        mesh, head_axis, batch_axis)


def generate(model, variables, prompt_ids, max_new_tokens: int,
             max_len: Optional[int] = None, temperature: float = 0.0,
             rng=None, unroll: int = 1):
    """Autoregressive decoding with the KV cache: prefill the prompt in one
    call, then ``lax.scan`` single-token steps — the whole loop is two
    compiled programs regardless of length (no per-token dispatch).
    ``model`` is any causal LM with the cache call contract (``LlamaLM``,
    ``MoeLM``).

    ``temperature`` 0.0 = greedy argmax (default); > 0 samples from
    ``softmax(logits / temperature)`` using ``rng``. Returns
    ``(B, prompt + max_new_tokens)`` ids (prompt included).

    ``unroll``: tokens decoded per ``lax.scan`` iteration (the loop body
    is replicated; the cache takes one in-place row write per token
    either way). >1 amortizes the fixed per-iteration while-loop cost
    that dominates small-batch decode (``artifacts/decode_ceiling_r6``);
    identical tokens at any value.

    This is the inference counterpart of the training path the framework
    benchmarks; for serving without this framework see ``docs/inference.md``
    (checkpoints are plain pytrees; sharding-path dispatch is described
    in ``docs/decode-serving.md``)."""
    cfg = model.config
    b, s = prompt_ids.shape
    if max_len is None:
        # MoeConfig has no max_seq_len (RoPE-only positions); cap on it
        # only where the config declares one.
        max_len = min(getattr(cfg, "max_seq_len", s + max_new_tokens),
                      s + max_new_tokens)
    if s + max_new_tokens > max_len:
        raise ValueError(
            f"prompt ({s}) + max_new_tokens ({max_new_tokens}) exceeds the "
            f"cache window max_len={max_len}")
    if temperature > 0.0 and rng is None:
        raise ValueError("temperature sampling needs an rng key")
    if rng is None:
        rng = jax.random.PRNGKey(0)  # unused on the greedy path
    if max_new_tokens <= 0:
        return prompt_ids
    # greedy is the only STATIC part of the sampling decision: temperature
    # rides in as a traced operand so a temperature sweep shares one
    # compiled program instead of recompiling the prefill+scan per value.
    #
    # Sharding classifier (see classify_decode_sharding): heads-on-TP
    # meshes keep the Pallas fast path through shard_map; only exotic
    # shardings trace the einsum form, which GSPMD shards naturally.
    global LAST_DECODE_PATH
    info = classify_decode_sharding(variables, prompt_ids,
                                    cfg.num_kv_heads)
    if not _DECODE_KERNEL:
        info = DecodePath("einsum", "decode_kernel_disabled()")
    LAST_DECODE_PATH = info
    new_tokens = _decode(model, variables, prompt_ids, rng,
                         jnp.float32(temperature), int(max_new_tokens),
                         int(max_len), temperature <= 0.0, info.path,
                         info.mesh, info.head_axis, info.batch_axis,
                         int(unroll))
    return jnp.concatenate([prompt_ids, new_tokens], axis=1)


@functools.partial(
    jax.jit,
    static_argnames=("model", "max_new_tokens", "max_len", "greedy",
                     "path", "mesh", "head_axis", "batch_axis", "unroll"))
def _decode(model, variables, prompt_ids, rng, temperature, max_new_tokens,
            max_len, greedy, path="kernel", mesh=None, head_axis=None,
            batch_axis=None, unroll=1):
    """Compiled decode body. Module-level with the model as a STATIC arg
    (flax modules hash by structure): repeated ``generate`` calls with the
    same model/shapes hit the jit cache — a per-call ``@jax.jit`` closure
    would recompile the prefill+scan program on every invocation.
    ``path`` (+ mesh/axes for the shard_mapped kernel; Mesh hashes by
    devices and axis names) is part of the jit cache key — a bare global
    flag would be ignored on a cache hit."""
    with decode_path_context(path, mesh, head_axis, batch_axis):
        return _decode_body(model, variables, prompt_ids, rng, temperature,
                            max_new_tokens, max_len, greedy, unroll)


def _decode_body(model, variables, prompt_ids, rng, temperature,
                 max_new_tokens, max_len, greedy, unroll=1):
    cfg = model.config
    b, s = prompt_ids.shape

    def pick(logits, step_rng):
        logits = logits.astype(jnp.float32)
        if greedy:
            return jnp.argmax(logits, axis=-1)
        return jax.random.categorical(step_rng, logits / temperature)

    cache = init_kv_cache(cfg, b, max_len)
    logits, cache = model.apply(variables, prompt_ids, cache=cache,
                                cache_index=0)
    rng, step_rng = jax.random.split(rng)
    first = pick(logits[:, -1], step_rng)

    def body(carry, i):
        tok, cache, rng = carry
        logits, cache = model.apply(variables, tok[:, None], cache=cache,
                                    cache_index=s + i)
        rng, step_rng = jax.random.split(rng)
        nxt = pick(logits[:, -1], step_rng)
        return (nxt, cache, rng), nxt

    # lax.scan handles the zero-length xs of max_new_tokens == 1. unroll
    # replicates the body per while iteration (decode_floor_probe: the
    # fixed per-iteration platform cost is what bounds small-batch
    # decode) — token stream identical at any unroll.
    (_, _, _), rest = jax.lax.scan(
        body, (first, cache, rng), jnp.arange(max_new_tokens - 1),
        unroll=min(unroll, max(max_new_tokens - 1, 1)))
    return jnp.concatenate([first[:, None], rest.T], axis=1)


def llama_tp_param_specs(params, axis: str = "model"):
    """Megatron-style tensor-parallel ``PartitionSpec`` tree for
    ``LlamaLM`` params, for the GSPMD path: ``device_put`` params with
    ``NamedSharding(mesh, spec)`` and ``jax.jit`` the step — XLA derives
    the activation collectives from the shardings (no shard_map needed).

    Layout (the classic column→row pairing, so each block needs ONE
    psum after attention and one after the FFN):
      wq/wk/wv  (dim, heads, head_dim)  — heads sharded (column-parallel)
      wo        (heads, head_dim, dim)  — heads sharded (row-parallel)
      w_gate/up (dim, ffn_hidden)       — hidden sharded (column)
      w_down    (ffn_hidden, dim)       — hidden sharded (row)
      lm_head   (dim, vocab)            — vocab sharded (column; the loss's
                                          lse reduces over vocab via psum)
      tok_embeddings (vocab, dim)       — vocab sharded
      norms / scales                    — replicated

    Requires num_heads, num_kv_heads, ffn_hidden and vocab_size divisible
    by the axis size. Compose with a ``data`` axis for dp x tp."""
    from jax.sharding import PartitionSpec as P

    rules = {
        "wq": P(None, axis, None),
        "wk": P(None, axis, None),
        "wv": P(None, axis, None),
        "wo": P(axis, None, None),
        "w_gate": P(None, axis),
        "w_up": P(None, axis),
        "w_down": P(axis, None),
        "lm_head": P(None, axis),
        "tok_embeddings": P(axis, None),
    }

    def spec(path, x):
        names = {getattr(k, "key", str(k)) for k in path}
        for name, s in rules.items():
            if name in names:
                return s
        return P()

    return jax.tree_util.tree_map_with_path(spec, params)


def token_nll(logits, targets):
    """Per-token negative log-likelihood via the lse formulation:
    ``lse(logits) - logits[target]``. Unlike ``log_softmax`` +
    ``take_along_axis`` this never materializes a (..., V) f32 array —
    the f32 upcast fuses into the logsumexp reduction and the target
    logit is a gather — which cuts ~1 GiB of peak HBM at
    (B=8, S=1024, V=32000) and is what lets larger batches fit."""
    # Gather BEFORE the upcast: astype-then-gather would force the f32
    # copy this formulation exists to avoid (the upcast inside logsumexp
    # fuses into the reduction; a gather consumer would not).
    lse = jax.scipy.special.logsumexp(logits.astype(jnp.float32), axis=-1)
    target_logit = jnp.take_along_axis(
        logits, targets[..., None], axis=-1)[..., 0].astype(jnp.float32)
    return lse - target_logit


def causal_lm_loss(logits, input_ids):
    """Next-token cross entropy (shifted)."""
    return token_nll(logits[:, :-1], input_ids[:, 1:]).mean()


def chunked_causal_lm_loss(hidden, head_kernel, input_ids,
                           num_chunks: int = 8):
    """:func:`causal_lm_loss` with the lm_head fused in, applied one
    sequence chunk at a time under ``jax.checkpoint``: the full (B, S, V)
    logits — and, in the backward pass, their same-sized cotangent — never
    exist; peak extra HBM is O(B * S/num_chunks * V). At Llama-300M
    S=16384 that's the ~2 GiB that makes single-chip training fit where
    the fused-head path OOMs.

    ``hidden``: final-norm hidden states from
    ``model.apply(..., return_hidden=True)``, shape (B, S, dim);
    ``head_kernel``: ``params["lm_head"]["kernel"]`` (dim, V).
    The LOSS matches ``causal_lm_loss`` on the full logits exactly (each
    logit row is the same dot product; the mean is reassembled exactly).
    Head/hidden GRADIENTS agree up to bf16 rounding at chunk boundaries:
    each chunk's dW partial quantizes to bf16 before the cross-chunk sum,
    where the fused head quantizes once (measured ~0.7% grad-norm delta —
    bf16-training noise level)."""
    b, s, d = hidden.shape
    if s % num_chunks:
        raise ValueError(
            f"chunked_causal_lm_loss: seq len {s} must be divisible by "
            f"num_chunks {num_chunks}")
    c = s // num_chunks
    # Shifted targets over the FULL sequence; the final position has no
    # next token — it wraps to a garbage value and is masked out below.
    targets = jnp.concatenate([input_ids[:, 1:], input_ids[:, :1]], axis=1)
    h = hidden.reshape(b, num_chunks, c, d).transpose(1, 0, 2, 3)
    t = targets.reshape(b, num_chunks, c).transpose(1, 0, 2)
    w = head_kernel.astype(hidden.dtype)

    @jax.checkpoint
    def chunk_nll(h_c, t_c):
        # Same matmul dtype as the in-model lm_head (MXU f32 accumulate).
        return token_nll(h_c @ w, t_c)

    nll = jax.lax.map(lambda args: chunk_nll(*args), (h, t))
    nll = nll.transpose(1, 0, 2).reshape(b, s)
    return nll[:, :-1].mean()


def sp_causal_lm_loss(logits, input_ids, axis_name: str):
    """Sequence-parallel twin of :func:`causal_lm_loss`: ``logits`` /
    ``input_ids`` are the LOCAL (contiguous-layout) sequence shards inside
    ``shard_map``. The next-token shift crosses shard boundaries, so each
    shard fetches its right neighbor's first token over one ``ppermute``
    (riding ICI) and the global final position is masked out; the result
    is the same global mean on every shard — numerically identical to the
    single-device loss on the gathered sequence."""
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    nxt = jax.lax.ppermute(
        input_ids[:, :1], axis_name,
        [(i, (i - 1) % n) for i in range(n)])
    targets = jnp.concatenate([input_ids[:, 1:], nxt], axis=1)
    nll = token_nll(logits, targets)
    valid = jnp.ones(input_ids.shape, bool).at[:, -1].set(idx != n - 1)
    total = jax.lax.psum(jnp.where(valid, nll, 0.0).sum(), axis_name)
    count = jax.lax.psum(valid.sum(), axis_name)
    return total / count
