"""Mixture-of-experts transformer LM — the expert-parallel model family.

No reference counterpart (the reference is 2019 CNN-era data parallelism,
SURVEY.md §2.3); this extends the Llama-style LM (``models/llama.py``) with
a Switch/GShard MoE feed-forward on every other layer, wired to the
expert-parallel substrate (``parallel/moe.py``):

- ``expert_axis=None`` (default): every expert is resident and dispatch
  runs densely under ``vmap`` (``moe_apply_dense``) — single-chip runs,
  tests, eval.
- ``expert_axis="expert"`` inside ``shard_map``: expert parameters are
  sharded one-per-device along that mesh axis and token dispatch rides
  ``all_to_all`` over ICI (``moe_apply``). The routing (and therefore the
  numerics) is identical in both modes.

The MLM/causal losses and non-MoE machinery are shared with the Llama
family. Aux (load-balancing) losses from every MoE layer are summed into
the ``"aux_loss"`` collection — fold ``sum(aux) * aux_weight`` into the
objective.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional

import flax.linen as nn
import jax.numpy as jnp

from ..parallel.moe import moe_apply, moe_apply_dense
from .llama import (  # noqa: F401
    LlamaAttention,
    LlamaBlock,
    LlamaConfig,
    RMSNorm,
    causal_lm_loss,
)


@dataclasses.dataclass(frozen=True)
class MoeConfig:
    vocab_size: int = 32000
    dim: int = 2048
    num_layers: int = 16
    num_heads: int = 32
    num_kv_heads: int = 8
    ffn_hidden: int = 5632
    num_experts: int = 8
    expert_hidden: int = 5632
    num_selected: int = 2
    capacity_factor: float = 1.25
    moe_every: int = 2           # every moe_every-th layer gets an MoE FFN
    norm_eps: float = 1e-5
    rope_theta: float = 1e4
    dtype: Any = jnp.bfloat16
    # lm_head compute dtype; None = model dtype (see
    # LlamaConfig.head_dtype).
    head_dtype: Any = None
    # jax.checkpoint each block in the backward pass (see
    # LlamaConfig.remat).
    remat: bool = False

    def llama(self) -> LlamaConfig:
        return LlamaConfig(
            vocab_size=self.vocab_size, dim=self.dim,
            num_layers=self.num_layers, num_heads=self.num_heads,
            num_kv_heads=self.num_kv_heads, ffn_hidden=self.ffn_hidden,
            norm_eps=self.norm_eps, rope_theta=self.rope_theta,
            dtype=self.dtype, head_dtype=self.head_dtype)


MOE_TINY = MoeConfig(vocab_size=512, dim=64, num_layers=2, num_heads=4,
                     num_kv_heads=2, ffn_hidden=128, num_experts=4,
                     expert_hidden=128, moe_every=2)
# 249.7M params (151M routed across 8 experts + 98.7M dense, counted from
# the init tree): a single-chip MoE benchmark config (top-2 of 8 experts,
# every other layer routed).
MOE_SMALL = MoeConfig(vocab_size=32000, dim=768, num_layers=12,
                      num_heads=12, num_kv_heads=6, ffn_hidden=2048,
                      num_experts=8, expert_hidden=2048, moe_every=2)


class MoeFFN(nn.Module):
    """Top-k routed feed-forward: gate -> dispatch -> per-expert gated MLP
    -> combine. Expert weights carry a leading expert axis: the GLOBAL
    expert count in dense mode, the LOCAL count (one per device) under
    ``shard_map`` — flax validates declared param shapes at apply time, so
    the sharded mode must declare the slice it will actually receive."""

    config: MoeConfig
    expert_axis: Optional[str] = None
    local_experts: Optional[int] = None
    # Decode mode: capacity covers the all-tokens-to-one-expert worst case
    # (cf = E/k) so no assignment is ever dropped — see MoeBlock.
    no_drop: bool = False

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        b, s, d = x.shape
        tokens = x.reshape(b * s, d)

        gate_w = self.param("gate", nn.initializers.normal(0.02),
                            (d, cfg.num_experts), jnp.float32)
        # Router in float32 (Switch: routing is precision-sensitive).
        logits = tokens.astype(jnp.float32) @ gate_w

        n_param = (self.local_experts
                   if self.expert_axis is not None and self.local_experts
                   else cfg.num_experts)
        experts = {
            "wi": self.param(
                "wi", nn.initializers.lecun_normal(),
                (n_param, d, cfg.expert_hidden), jnp.float32),
            "wo": self.param(
                "wo", nn.initializers.lecun_normal(),
                (n_param, cfg.expert_hidden, d), jnp.float32),
        }

        def expert_fn(p, t):
            h = nn.silu(t @ p["wi"].astype(cfg.dtype))
            return h @ p["wo"].astype(cfg.dtype)

        capacity_factor = (cfg.num_experts / cfg.num_selected
                           if self.no_drop else cfg.capacity_factor)
        kwargs = dict(capacity_factor=capacity_factor,
                      num_selected=cfg.num_selected)
        if self.expert_axis is None:
            y, aux = moe_apply_dense(expert_fn, experts,
                                     tokens.astype(cfg.dtype),
                                     logits, **kwargs)
        else:
            y, aux = moe_apply(expert_fn, experts,
                               tokens.astype(cfg.dtype), logits,
                               axis_name=self.expert_axis, **kwargs)
        self.sow("aux_loss", "moe", aux)
        return y.reshape(b, s, d)


class MoeBlock(nn.Module):
    """Transformer block with a routed FFN (dense layers reuse
    ``LlamaBlock`` directly — see ``MoeLM``)."""

    config: MoeConfig
    expert_axis: Optional[str] = None
    local_experts: Optional[int] = None
    attention_fn: Optional[Callable] = None

    @nn.compact
    def __call__(self, x, positions=None, cache=None, cache_index=None):
        cfg = self.config
        from .llama import attention_sublayer

        x, new_cache = attention_sublayer(cfg.llama(), self.attention_fn, x,
                                          positions, cache, cache_index)
        h = RMSNorm(cfg.norm_eps, cfg.dtype, name="ffn_norm")(x)
        # Decode runs the experts at NO-DROP capacity (cf = E/k covers the
        # all-tokens-to-one-expert worst case): training-time capacity
        # drops are a throughput/regularization tradeoff computed from the
        # per-CALL token pool, and a single-token decode step's tiny pool
        # would bind capacity differently from the training forward —
        # dropping tokens at inference is never the right trade.
        out = x + MoeFFN(cfg, expert_axis=self.expert_axis,
                         local_experts=self.local_experts,
                         no_drop=cache is not None,
                         name="moe_ffn")(h)
        return out if cache is None else (out, new_cache)


class MoeLM(nn.Module):
    """Causal MoE LM. Apply with ``{"params": params}`` (not the full init
    variables — a stale ``aux_loss`` collection would double-count) and
    ``mutable=["aux_loss"]`` to collect the per-layer balancing losses:

        logits, col = model.apply({"params": p}, ids, mutable=["aux_loss"])
        aux = sum(jax.tree.leaves(col["aux_loss"]))

    For expert parallelism set ``expert_axis`` to the mesh axis and
    ``local_experts=1`` (the one-expert-per-device contract), shard the
    ``wi``/``wo`` leaves over that axis, and apply inside ``shard_map``.
    """

    config: MoeConfig
    expert_axis: Optional[str] = None
    local_experts: Optional[int] = None
    attention_fn: Optional[Callable] = None

    @nn.compact
    def __call__(self, input_ids, positions=None, return_hidden=False,
                 cache=None, cache_index=None):
        """``positions``: global token positions of the local rows (see
        ``LlamaLM.__call__``) — required under sequence parallelism.
        ``return_hidden``: skip the lm_head and return the final-norm
        hidden states — pair with ``models.chunked_causal_lm_loss``
        (same contract as ``LlamaLM``).
        ``cache``/``cache_index``: autoregressive decoding, same contract
        as ``LlamaLM`` (``models.llama.generate`` works on this model too;
        aux-loss sow is a no-op outside a mutable collection). Decode runs
        the experts at NO-DROP capacity (see ``MoeBlock``): capacity is
        otherwise computed from the per-call token pool, so a single-token
        step would drop different assignments than a full forward. Decode
        therefore matches a full forward exactly WHEN the full forward's
        own capacity doesn't bind; under training-config capacity drops
        the two can legitimately diverge (the drop is a training
        artifact)."""
        cfg = self.config
        if cache is not None and positions is None:
            positions = cache_index + jnp.arange(input_ids.shape[1])
        x = nn.Embed(cfg.vocab_size, cfg.dim, param_dtype=jnp.float32,
                     name="tok_embeddings")(input_ids).astype(cfg.dtype)
        new_cache = {}
        moe_cls = nn.remat(MoeBlock) if cfg.remat else MoeBlock
        dense_cls = nn.remat(LlamaBlock) if cfg.remat else LlamaBlock
        for i in range(cfg.num_layers):
            # Every moe_every-th layer is routed (moe_every=1: all layers);
            # the rest are plain LlamaBlocks (shared implementation).
            routed = i % cfg.moe_every == cfg.moe_every - 1
            if cache is not None:
                # Decoding never needs remat (no backward pass).
                cls = MoeBlock if routed else LlamaBlock
                kwargs = (dict(expert_axis=self.expert_axis,
                               local_experts=self.local_experts)
                          if routed else {})
                x, new_cache[f"layer_{i}"] = cls(
                    cfg if routed else cfg.llama(),
                    attention_fn=self.attention_fn, name=f"layer_{i}",
                    **kwargs)(x, positions, cache[f"layer_{i}"], cache_index)
            elif routed:
                x = moe_cls(cfg, expert_axis=self.expert_axis,
                            local_experts=self.local_experts,
                            attention_fn=self.attention_fn,
                            name=f"layer_{i}")(x, positions)
            else:
                x = dense_cls(cfg.llama(), attention_fn=self.attention_fn,
                              name=f"layer_{i}")(x, positions)
        x = RMSNorm(cfg.norm_eps, cfg.dtype, name="final_norm")(x)
        if return_hidden:
            return x
        # Head matmul in head_dtype (default: model compute dtype),
        # matching LlamaLM — see LlamaConfig.head_dtype.
        logits = nn.Dense(cfg.vocab_size, use_bias=False,
                          dtype=cfg.head_dtype or cfg.dtype,
                          param_dtype=jnp.float32, name="lm_head")(x)
        return logits if cache is None else (logits, new_cache)
