"""Inception V3 in flax — the reference's first headline benchmark model
(90% scaling efficiency at 512 GPUs, reference ``README.md:58``,
``docs/benchmarks.md:5-6``).

From-scratch TPU-first implementation of Szegedy et al. 2015
(arXiv:1512.00567): NHWC, bf16 activations / fp32 parameters+batch-stats,
every conv bias-free and followed by BatchNorm+ReLU. The mixed blocks
(A/B/C/D/E) concatenate parallel towers on the channel axis — XLA fuses the
concat with the consumers, and the many small convs batch onto the MXU.
Aux-logits head included (used only when ``train`` and ``aux_logits``).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Sequence, Tuple, Union

import flax.linen as nn
import jax.numpy as jnp

Ints = Union[int, Tuple[int, int]]


def _pair(v: Ints) -> Tuple[int, int]:
    return (v, v) if isinstance(v, int) else v


class ConvBN(nn.Module):
    """Conv -> BatchNorm -> ReLU, the Inception building unit."""

    features: int
    kernel: Ints = 1
    strides: Ints = 1
    padding: Union[str, Sequence[Tuple[int, int]]] = "SAME"
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = nn.Conv(self.features, _pair(self.kernel), _pair(self.strides),
                    padding=self.padding, use_bias=False, dtype=self.dtype,
                    param_dtype=jnp.float32)(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                         epsilon=1e-3, dtype=self.dtype,
                         param_dtype=jnp.float32)(x)
        return nn.relu(x)


def _avg_pool_same(x):
    return nn.avg_pool(x, (3, 3), strides=(1, 1), padding="SAME")


class InceptionA(nn.Module):
    """35x35 block: 1x1 / 5x5 / double-3x3 / pool towers."""

    pool_features: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        cbn = partial(ConvBN, dtype=self.dtype)
        t1 = cbn(64)(x, train)
        t5 = cbn(64, 5)(cbn(48)(x, train), train)
        t3 = cbn(96, 3)(cbn(96, 3)(cbn(64)(x, train), train), train)
        tp = cbn(self.pool_features)(_avg_pool_same(x), train)
        return jnp.concatenate([t1, t5, t3, tp], axis=-1)


class InceptionB(nn.Module):
    """35x35 -> 17x17 grid reduction."""

    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        cbn = partial(ConvBN, dtype=self.dtype)
        t3 = cbn(384, 3, 2, "VALID")(x, train)
        td = cbn(96, 3, 2, "VALID")(
            cbn(96, 3)(cbn(64)(x, train), train), train)
        tp = nn.max_pool(x, (3, 3), strides=(2, 2))
        return jnp.concatenate([t3, td, tp], axis=-1)


class InceptionC(nn.Module):
    """17x17 block with factorized 7x7 (1x7 + 7x1) towers."""

    channels_7x7: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        cbn = partial(ConvBN, dtype=self.dtype)
        c7 = self.channels_7x7
        t1 = cbn(192)(x, train)
        t7 = cbn(192, (1, 7))(
            cbn(c7, (7, 1))(cbn(c7)(x, train), train), train)
        td = cbn(c7)(x, train)
        for k, f in [((7, 1), c7), ((1, 7), c7), ((7, 1), c7), ((1, 7), 192)]:
            td = cbn(f, k)(td, train)
        tp = cbn(192)(_avg_pool_same(x), train)
        return jnp.concatenate([t1, t7, td, tp], axis=-1)


class InceptionD(nn.Module):
    """17x17 -> 8x8 grid reduction."""

    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        cbn = partial(ConvBN, dtype=self.dtype)
        t3 = cbn(320, 3, 2, "VALID")(cbn(192)(x, train), train)
        t7 = cbn(192, 3, 2, "VALID")(
            cbn(192, (7, 1))(
                cbn(192, (1, 7))(cbn(192)(x, train), train), train), train)
        tp = nn.max_pool(x, (3, 3), strides=(2, 2))
        return jnp.concatenate([t3, t7, tp], axis=-1)


class InceptionE(nn.Module):
    """8x8 block with expanded-filterbank (split 1x3 / 3x1) towers."""

    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        cbn = partial(ConvBN, dtype=self.dtype)
        t1 = cbn(320)(x, train)
        a = cbn(384)(x, train)
        t3 = jnp.concatenate(
            [cbn(384, (1, 3))(a, train), cbn(384, (3, 1))(a, train)], axis=-1)
        b = cbn(384, 3)(cbn(448)(x, train), train)
        td = jnp.concatenate(
            [cbn(384, (1, 3))(b, train), cbn(384, (3, 1))(b, train)], axis=-1)
        tp = cbn(192)(_avg_pool_same(x), train)
        return jnp.concatenate([t1, t3, td, tp], axis=-1)


class InceptionV3(nn.Module):
    """Inception V3 classifier. Input 299x299x3 (any HxW >= 75 works).

    ``aux_logits``: when True and ``train``, returns ``(logits, aux_logits)``
    as in the paper; otherwise just ``logits``.
    """

    num_classes: int = 1000
    aux_logits: bool = False
    dropout_rate: float = 0.5
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        cbn = partial(ConvBN, dtype=self.dtype)
        x = jnp.asarray(x, self.dtype)
        # Stem: 299 -> 35x35x192.
        x = cbn(32, 3, 2, "VALID")(x, train)
        x = cbn(32, 3, 1, "VALID")(x, train)
        x = cbn(64, 3)(x, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = cbn(80, 1, 1, "VALID")(x, train)
        x = cbn(192, 3, 1, "VALID")(x, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        # 3x A @35, B, 4x C @17, D, 2x E @8.
        for pool_features in (32, 64, 64):
            x = InceptionA(pool_features, dtype=self.dtype)(x, train)
        x = InceptionB(dtype=self.dtype)(x, train)
        for c7 in (128, 160, 160, 192):
            x = InceptionC(c7, dtype=self.dtype)(x, train)
        aux = None
        if self.aux_logits and train:
            # 5x5/3 pool as in the paper; clamped so sub-299 inputs (tests)
            # keep a non-empty grid.
            win = (min(5, x.shape[1]), min(5, x.shape[2]))
            a = nn.avg_pool(x, win, strides=(3, 3))
            a = cbn(128)(a, train)
            a = cbn(768, a.shape[1:3], padding="VALID")(a, train)
            a = jnp.mean(a, axis=(1, 2))
            aux = nn.Dense(self.num_classes, dtype=jnp.float32,
                           param_dtype=jnp.float32, name="aux_head")(a)
        x = InceptionD(dtype=self.dtype)(x, train)
        for _ in range(2):
            x = InceptionE(dtype=self.dtype)(x, train)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        x = nn.Dense(self.num_classes, dtype=jnp.float32,
                     param_dtype=jnp.float32, name="head")(x)
        if aux is not None:
            return x, aux
        return x
