"""Model zoo: the architectures the reference benchmarks with (ResNet family,
MNIST MLP) plus the rebuild's BERT target (BASELINE.md)."""

from .bert import (  # noqa: F401
    BERT_BASE,
    BERT_LARGE,
    BERT_TINY,
    BertConfig,
    BertEncoder,
    mlm_loss,
)
from .llama import (  # noqa: F401
    LLAMA_1B,
    LLAMA_300M,
    LLAMA_8B,
    LLAMA_TINY,
    DecodePath,
    LlamaConfig,
    LlamaLM,
    causal_lm_loss,
    chunked_causal_lm_loss,
    classify_decode_sharding,
    generate,
    init_kv_cache,
    llama_tp_param_specs,
    sp_causal_lm_loss,
    token_nll,
)
from .inception import InceptionV3  # noqa: F401
from .moe_lm import (  # noqa: F401
    MOE_SMALL,
    MOE_TINY,
    MoeConfig,
    MoeLM,
)
from .mlp import MnistMLP  # noqa: F401
from .resnet import (  # noqa: F401
    ResNet,
    ResNet50,
    ResNet101,
    ResNet152,
    ResNetTiny,
)
from .vgg import (  # noqa: F401
    VGG,
    VGG11,
    VGG13,
    VGG16,
    VGG19,
    VGGTiny,
)
from .vit import (  # noqa: F401
    VIT_B16,
    VIT_S16,
    VIT_TINY,
    VisionTransformer,
    ViTConfig,
    classification_loss,
)
