"""BERT encoder in flax — the framework's flagship language benchmark model.

The rebuild targets "≥90% scaling efficiency for ResNet-50 and BERT-base"
(BASELINE.md); the reference itself has no BERT code (2019, CNN-centric), so
this is specified by the target, not ported. TPU-first choices: bfloat16
activations / fp32 params, einsum-formulated attention (MXU-friendly, and the
seam where the Pallas flash-attention kernel and ring-attention sequence
parallelism plug in — see ``horovod_tpu.ops.attention`` /
``horovod_tpu.parallel.sequence``), static shapes throughout.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional

import flax.linen as nn
import jax.numpy as jnp
import numpy as np

from .llama import token_nll


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    dropout_rate: float = 0.1
    dtype: Any = jnp.bfloat16
    # mlm_head compute dtype; None = model dtype (see
    # LlamaConfig.head_dtype — set jnp.float32 for full-precision raw
    # logits).
    head_dtype: Any = None
    # jax.checkpoint each transformer block in the backward pass (see
    # LlamaConfig.remat).
    remat: bool = False


BERT_BASE = BertConfig()
BERT_LARGE = BertConfig(hidden_size=1024, num_layers=24, num_heads=16,
                        intermediate_size=4096)
BERT_TINY = BertConfig(vocab_size=1024, hidden_size=64, num_layers=2,
                       num_heads=2, intermediate_size=128,
                       max_position_embeddings=128)


class SelfAttention(nn.Module):
    """Multi-head attention via einsum. ``attention_fn`` lets callers swap
    the core softmax(QK^T)V for a Pallas flash kernel or a ring-attention
    sequence-parallel variant without touching the module."""

    config: BertConfig
    attention_fn: Optional[Callable] = None

    @nn.compact
    def __call__(self, x, mask, deterministic: bool = True):
        cfg = self.config
        head_dim = cfg.hidden_size // cfg.num_heads
        dense = lambda name: nn.DenseGeneral(  # noqa: E731
            features=(cfg.num_heads, head_dim), axis=-1, dtype=cfg.dtype,
            param_dtype=jnp.float32, name=name)
        q = dense("query")(x)
        k = dense("key")(x)
        v = dense("value")(x)

        if self.attention_fn is not None:
            ctx = self.attention_fn(q, k, v, mask)
        else:
            scale = 1.0 / np.sqrt(head_dim)
            logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
            if mask is not None:
                big_neg = jnp.finfo(jnp.float32).min
                logits = jnp.where(mask[:, None, None, :], logits, big_neg)
            probs = nn.softmax(logits.astype(jnp.float32)).astype(cfg.dtype)
            probs = nn.Dropout(cfg.dropout_rate)(
                probs, deterministic=deterministic)
            ctx = jnp.einsum("bhqk,bkhd->bqhd", probs, v)

        out = nn.DenseGeneral(features=cfg.hidden_size, axis=(-2, -1),
                              dtype=cfg.dtype, param_dtype=jnp.float32,
                              name="out")(ctx)
        return out


class TransformerBlock(nn.Module):
    config: BertConfig
    attention_fn: Optional[Callable] = None

    @nn.compact
    def __call__(self, x, mask, deterministic: bool = True):
        cfg = self.config
        attn = SelfAttention(cfg, attention_fn=self.attention_fn)(
            x, mask, deterministic)
        attn = nn.Dropout(cfg.dropout_rate)(attn, deterministic=deterministic)
        x = nn.LayerNorm(dtype=cfg.dtype, param_dtype=jnp.float32)(x + attn)
        h = nn.Dense(cfg.intermediate_size, dtype=cfg.dtype,
                     param_dtype=jnp.float32)(x)
        h = nn.gelu(h)
        h = nn.Dense(cfg.hidden_size, dtype=cfg.dtype,
                     param_dtype=jnp.float32)(h)
        h = nn.Dropout(cfg.dropout_rate)(h, deterministic=deterministic)
        return nn.LayerNorm(dtype=cfg.dtype, param_dtype=jnp.float32)(x + h)


class BertEncoder(nn.Module):
    """Embeddings + transformer stack + MLM head (tied-free simple head)."""

    config: BertConfig
    attention_fn: Optional[Callable] = None

    @nn.compact
    def __call__(self, input_ids, token_type_ids=None, attention_mask=None,
                 deterministic: bool = True, positions=None):
        """``positions``: global token positions of the local rows, shape
        (S,) — required under sequence parallelism (each shard passes its
        global offsets so the learned position embedding indexes
        correctly); defaults to 0..S-1. The GLOBAL sequence length must
        stay within ``cfg.max_position_embeddings``: a learned position
        table cannot extrapolate, and out-of-range indices would be
        silently clamped by ``nn.Embed`` — unlike RoPE models
        (``LlamaLM``), BERT's SP context is capped by its table size."""
        cfg = self.config
        b, s = input_ids.shape
        if attention_mask is None:
            attention_mask = jnp.ones((b, s), dtype=bool)
        else:
            attention_mask = attention_mask.astype(bool)
        if token_type_ids is None:
            token_type_ids = jnp.zeros((b, s), dtype=jnp.int32)
        if positions is None:
            positions = jnp.arange(s)

        tok = nn.Embed(cfg.vocab_size, cfg.hidden_size,
                       param_dtype=jnp.float32, name="token_embeddings")(
                           input_ids)
        pos = nn.Embed(cfg.max_position_embeddings, cfg.hidden_size,
                       param_dtype=jnp.float32, name="position_embeddings")(
                           positions[None, :])
        typ = nn.Embed(cfg.type_vocab_size, cfg.hidden_size,
                       param_dtype=jnp.float32, name="type_embeddings")(
                           token_type_ids)
        x = (tok + pos + typ).astype(cfg.dtype)
        x = nn.LayerNorm(dtype=cfg.dtype, param_dtype=jnp.float32,
                         name="embed_norm")(x)
        x = nn.Dropout(cfg.dropout_rate)(x, deterministic=deterministic)

        block_cls = (nn.remat(TransformerBlock, static_argnums=(3,))
                     if cfg.remat else TransformerBlock)
        for i in range(cfg.num_layers):
            x = block_cls(cfg, attention_fn=self.attention_fn,
                          name=f"layer_{i}")(
                              x, attention_mask, deterministic)

        # Head matmul in head_dtype (default: model compute dtype; MXU
        # accumulates f32 internally); mlm_loss upcasts to f32 before the
        # softmax.
        logits = nn.Dense(cfg.vocab_size,
                          dtype=cfg.head_dtype or cfg.dtype,
                          param_dtype=jnp.float32, name="mlm_head")(x)
        return logits


def mlm_loss(logits, labels, label_mask):
    """Masked-LM cross entropy over positions where label_mask is 1.

    Uses the lse formulation (``lse(logits) - logits[label]``) so no
    (B, S, V) f32 array is materialized — see
    ``horovod_tpu.models.llama.token_nll``."""
    nll = token_nll(logits, labels)
    label_mask = label_mask.astype(jnp.float32)
    return (nll * label_mask).sum() / jnp.maximum(label_mask.sum(), 1.0)
