"""The cluster doctor's rule catalog (docs/doctor.md).

Each rule is a pure function ``Evidence -> Iterator[Diagnosis]`` — no
clocks, no env, no I/O — so every rule is unit-testable from synthetic
evidence and behaves identically live (the ``/doctor`` endpoint, the
coordinator's periodic sweep) and offline (``tools.doctor`` over an
artifact directory). A rule that cannot see its minimum evidence yields
nothing: absence of data is not health, and the report records which
sources were present.

Thresholds are module constants, deliberately conservative: a doctor
that cries wolf gets ignored, and every ``Diagnosis`` carries the raw
evidence series so the operator can re-judge the verdict.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Iterator, List, Optional, Tuple

from ..metrics import quantile
from .evidence import Evidence

SEVERITIES = ("critical", "warning", "info")

# -- persistent straggler ----------------------------------------------------
# A rank must be late this often / this much before it is named.
STRAGGLER_MIN_COLLECTIVES = 10     # report: attributed collectives needed
STRAGGLER_CYCLE_SHARE = 0.3        # report: fraction a rank arrived last
STRAGGLER_MIN_LATENESS = 0.010     # seconds, p99 floor (live + report)
STRAGGLER_CRITICAL_LATENESS = 0.100
STRAGGLER_MIN_SAMPLES = 20         # live: tick-lateness observations needed
STRAGGLER_SKEW_FACTOR = 3.0        # live: p99 vs other ranks' median p99
# -- clock sync --------------------------------------------------------------
CLOCK_MAX_UNCERTAINTY = 0.005      # seconds
# -- recv-wait skew ----------------------------------------------------------
RECV_WAIT_MIN_P99 = 0.020          # seconds
RECV_WAIT_SKEW_FACTOR = 3.0
# -- heartbeat flapping ------------------------------------------------------
FLAPPING_MIN_TRIPS = 2
FLAPPING_CRITICAL_TRIPS = 10
# -- cache collapse ----------------------------------------------------------
CACHE_MIN_TRAFFIC = 200            # hits + misses before judging
CACHE_COLLAPSE_RATE = 0.2
# -- restart churn -----------------------------------------------------------
RESTART_CHURN_MIN = 2
RESTART_CHURN_CRITICAL = 5

MEMBERSHIP_CHURN_MIN = 3           # elastic transitions before warning
MEMBERSHIP_CHURN_CRITICAL = 10
# -- autotune search ---------------------------------------------------------
AUTOTUNE_STALLED_MIN_CYCLES = 500  # controller cycles before "stalled"
AUTOTUNE_WANDER_MIN_STEPS = 10     # steps before "wandering" is judged
AUTOTUNE_WANDER_RATIO = 0.5        # last score vs best score
# -- serving tier ------------------------------------------------------------
SERVING_QUEUE_SATURATION_SHARE = 0.9   # waiting depth vs admission bound
SERVING_CRITICAL_REJECTS = 10          # shed requests before "critical"
SERVING_MIN_PREEMPTIONS = 3            # pool-dry recomputes before warning
SERVING_CRITICAL_PREEMPTIONS = 20
# -- serving fleet -----------------------------------------------------------
ROUTER_FLAPPING_MIN = 2                # replica departures before warning
ROUTER_FLAPPING_CRITICAL = 5
PREFIX_CACHE_MIN_TRAFFIC = 200         # whole pages judged before verdict
PREFIX_CACHE_COLLAPSE_RATE = 0.2
# -- capacity headroom -------------------------------------------------------
CAPACITY_HEADROOM_FACTOR = 2.0     # measured p99 vs the modeled curve
CAPACITY_MIN_CYCLES = 20           # cycle observations before judging
CAPACITY_MIN_RESHAPES = 3          # reshape observations before judging
# Below this modeled cost the controller's cycle pacer, not the control
# plane, sets the floor — small worlds would otherwise trip on pacing.
CAPACITY_MODELED_FLOOR = 0.005     # seconds
# How many of the newest completed telemetry windows the windowed rules
# (capacity_headroom, recv_wait_skew) judge when windows exist: two, so
# one window boundary never hides a fault that straddles it, and a
# transient heals within two rolls.
RECENT_WINDOWS = 2
# -- calibration drift -------------------------------------------------------
# A plane needs this many windows carrying data inside the live horizon
# before its slope is trusted; per-plane observation floors reuse the
# headroom rule's minimums.
DRIFT_MIN_WINDOWS = 2


@dataclasses.dataclass
class Diagnosis:
    """One structured verdict: what is wrong, where, how bad, what to do.
    ``evidence`` holds the raw numbers the verdict was derived from so an
    operator can re-judge it without re-running the rules."""

    rule: str
    severity: str          # "critical" | "warning" | "info"
    summary: str
    hint: str
    rank: Optional[int] = None
    evidence: dict = dataclasses.field(default_factory=dict)

    def to_dict(self) -> dict:
        return {"rule": self.rule, "severity": self.severity,
                "rank": self.rank, "summary": self.summary,
                "hint": self.hint, "evidence": self.evidence}

    def one_line(self) -> str:
        where = f"rank {self.rank} " if self.rank is not None else ""
        return f"[{self.severity}] {where}{self.rule}: {self.summary}"


def _series_totals(snapshots: Dict[int, dict], name: str
                   ) -> Dict[int, float]:
    """rank -> summed counter value for ``name`` across its label sets."""
    out: Dict[int, float] = {}
    for rank in sorted(snapshots):
        entry = snapshots[rank].get(name)
        if entry and entry.get("type") != "histogram":
            out[rank] = sum(v for _, v in entry.get("values", []))
    return out


def _counter_by_first_label(snap: dict, name: str) -> Dict[str, float]:
    entry = snap.get(name)
    if not entry:
        return {}
    return {labels[0]: value for labels, value in entry.get("values", [])
            if labels}


def _gauge(snapshots: Dict[int, dict], name: str) -> Optional[float]:
    """First rank's unlabeled gauge value, or None when absent anywhere."""
    for rank in sorted(snapshots):
        entry = snapshots[rank].get(name)
        if entry and entry.get("values"):
            return float(entry["values"][0][1])
    return None


def _per_label_quantiles(entry: Optional[dict], q: float
                         ) -> Dict[str, Tuple[float, int]]:
    """label-value -> (quantile, sample count) for a single-label
    histogram entry (e.g. hvd_controller_tick_lateness_seconds{rank})."""
    if not entry or entry.get("type") != "histogram":
        return {}
    out: Dict[str, Tuple[float, int]] = {}
    for labels, value in entry.get("values", []):
        if not labels:
            continue
        single = {"type": "histogram", "buckets": entry.get("buckets", []),
                  "values": [[[], value]]}
        est = quantile(single, q)
        if est is not None:
            out[labels[0]] = (est, int(value.get("count", 0)))
    return out


def _hist_quantile_and_count(snap: dict, name: str, q: float
                             ) -> Tuple[Optional[float], int]:
    entry = snap.get(name)
    est = quantile(entry, q)
    count = 0
    if entry and entry.get("type") == "histogram":
        count = sum(int(v.get("count", 0))
                    for _, v in entry.get("values", []))
    return est, count


def _ms(seconds: float) -> str:
    return f"{seconds * 1e3:.0f}ms"


def _sum_snaps(older: dict, newer: dict) -> dict:
    """Merge two delta snapshots of ONE rank: counters and histogram
    buckets add, gauges keep the newer level. Pure dict math — inputs
    are never mutated (they alias the window ring's records)."""
    out = dict(older)
    for name, entry in newer.items():
        prev = out.get(name)
        if (prev is None or entry.get("type") == "gauge"
                or prev.get("type") != entry.get("type")):
            out[name] = entry
            continue
        by_labels = {tuple(k): v for k, v in prev.get("values", [])}
        values = []
        seen = set()
        for labelvalues, value in entry.get("values", []):
            key = tuple(labelvalues)
            seen.add(key)
            prev_value = by_labels.get(key)
            if prev_value is None:
                values.append([list(labelvalues), value])
            elif entry.get("type") == "histogram":
                values.append([list(labelvalues), {
                    "counts": [a + b for a, b in
                               zip(value["counts"], prev_value["counts"])],
                    "sum": value["sum"] + prev_value["sum"],
                    "count": value["count"] + prev_value["count"]}])
            else:
                values.append([list(labelvalues), value + prev_value])
        for labelvalues, value in prev.get("values", []):
            if tuple(labelvalues) not in seen:
                values.append([list(labelvalues), value])
        out[name] = {**entry, "values": values}
    return out


def _recent_snapshots(ev: Evidence) -> Dict[int, dict]:
    """The windowed rules' input: per-rank telemetry merged over the
    last RECENT_WINDOWS completed windows when any exist, else the
    lifetime snapshots (jobs without a window roller keep the exact
    pre-window behavior). Judging the recent window is the point of the
    windowed plane: a slow warm-up heals once healthy windows roll past
    it, and fresh degradation is not diluted into hours of healthy
    history."""
    if not ev.windows:
        return ev.snapshots
    merged: Dict[int, dict] = {}
    for window in ev.windows[-RECENT_WINDOWS:]:
        for rank, snap in sorted(window.get("snapshots", {}).items()):
            rank = int(rank)
            cur = merged.get(rank)
            merged[rank] = snap if cur is None else _sum_snaps(cur, snap)
    return merged


# ---------------------------------------------------------------------------
# Rules


def check_persistent_straggler(ev: Evidence) -> Iterator[Diagnosis]:
    """One rank keeps arriving last at negotiation. Offline evidence is
    the r9 straggler report; live evidence is the coordinator's per-rank
    tick-lateness histogram — both express "late at negotiation"."""
    report = ev.straggler_report
    if report and report.get("collectives", 0) >= STRAGGLER_MIN_COLLECTIVES:
        total = report["collectives"]
        for rank_str in sorted(report.get("per_rank", {})):
            stats = report["per_rank"][rank_str]
            cycles = stats.get("straggler_cycles", 0)
            p99 = stats.get("lateness_p99_seconds") or 0.0
            if (cycles >= max(5, STRAGGLER_CYCLE_SHARE * total)
                    and p99 >= STRAGGLER_MIN_LATENESS):
                sev = ("critical" if p99 >= STRAGGLER_CRITICAL_LATENESS
                       else "warning")
                yield Diagnosis(
                    rule="persistent_straggler", severity=sev,
                    rank=int(rank_str),
                    summary=(f"arrived last at negotiation in {cycles} of "
                             f"{total} collectives (lateness p99 "
                             f"{_ms(p99)})"),
                    hint=(f"rank {rank_str} is persistently ≥"
                          f"{_ms(p99)} late at negotiation across {total} "
                          "collectives; suspect its NIC, a co-tenant "
                          "process, or its input pipeline"),
                    evidence={"straggler_cycles": cycles,
                              "collectives": total,
                              "lateness_p99_seconds": p99,
                              "source": "straggler_report"})
    # Live: the coordinator's tick-lateness histogram (rank label).
    for rank in sorted(ev.snapshots):
        per = _per_label_quantiles(
            ev.snapshots[rank].get("hvd_controller_tick_lateness_seconds"),
            0.99)
        if len(per) < 2:
            # One observed worker means no cluster to compare against:
            # the documented contract is "≥3x the cluster median", and
            # without peers the floor would degenerate to an absolute
            # threshold that names a merely compute-bound lone worker.
            continue
        for label in sorted(per):
            p99, count = per[label]
            others = [p for lbl, (p, _) in per.items() if lbl != label]
            floor = max(sorted(others)[len(others) // 2] if others else 0.0,
                        1e-3)
            if (count >= STRAGGLER_MIN_SAMPLES
                    and p99 >= STRAGGLER_MIN_LATENESS
                    and p99 >= STRAGGLER_SKEW_FACTOR * floor):
                sev = ("critical" if p99 >= STRAGGLER_CRITICAL_LATENESS
                       else "warning")
                yield Diagnosis(
                    rule="persistent_straggler", severity=sev,
                    rank=int(label),
                    summary=(f"coordinator waited ≥{_ms(p99)} (p99) for "
                             f"this rank's tick over {count} cycles"),
                    hint=(f"rank {label} is persistently ≥{_ms(p99)} late "
                          f"at negotiation across {count} collectives; "
                          "suspect its NIC, a co-tenant process, or its "
                          "input pipeline"),
                    evidence={"tick_lateness_p99_seconds": p99,
                              "cycles": count,
                              "cluster_median_p99_seconds": floor,
                              "source": "tick_lateness"})


def check_clock_sync(ev: Evidence) -> Iterator[Diagnosis]:
    """Clock-offset table quality: an unsynced or high-uncertainty rank
    silently degrades every downstream straggler attribution."""
    clock: Dict[int, dict] = {}
    if ev.clock:
        clock = {int(r): e for r, e in sorted(ev.clock.items())}
    elif ev.straggler_report and ev.straggler_report.get("clock"):
        clock = {int(r): e for r, e in
                 sorted(ev.straggler_report["clock"].items())}
    if len(clock) < 2:
        return
    workers = [r for r in sorted(clock) if r != 0]
    # "No ping plane ran at all" vs "the ping plane is broken": a python
    # engine job ALWAYS writes a clock_offsets.json (table entries carry
    # offset_seconds/samples), even when every pong was lost — only the
    # native engine leaves no table, and the clock evidence then comes
    # from the merged-trace metadata (applied_offset_seconds-shaped).
    from_table = any("offset_seconds" in clock[r] or "samples" in clock[r]
                     for r in workers)
    if (workers and not from_table
            and not any(clock[r].get("synced", False) for r in workers)):
        # No ping-pong plane ran AT ALL — a native-engine traced job:
        # spans come from the C++ engine's ring, and clock offsets ride
        # python-side heartbeats only (docs/tracing.md "Native engine").
        # That is a property of the job, not a broken heartbeat path, so
        # say so once at info instead of warning per rank.
        yield Diagnosis(
            rule="clock_sync_degraded", severity="info", rank=None,
            summary="no clock-offset table: every worker rank rebases "
                    "with offset 0 (native-engine jobs run no "
                    "python-side ping plane)",
            hint="same-host ranks share one monotonic clock, so the "
                 "merged timebase and straggler attribution stand; "
                 "across hosts treat sub-millisecond slacks as clock "
                 "noise, or run the python engine once to record a "
                 "clock_offsets.json",
            evidence={"clock": {str(r): clock[r] for r in workers}})
        return
    for rank in sorted(clock):
        entry = clock[rank]
        if rank == 0:
            continue  # rank 0 IS the reference clock
        if not entry.get("synced", False):
            yield Diagnosis(
                rule="clock_sync_degraded", severity="warning", rank=rank,
                summary="never completed a clock ping-pong; merged traces "
                        "rebase it with offset 0",
                hint=(f"rank {rank}'s heartbeat path never returned a "
                      "pong — straggler attribution involving it is "
                      "unreliable; check that heartbeats flow "
                      "(HOROVOD_HEARTBEAT_INTERVAL_SECONDS > 0) and that "
                      "nothing drops frames between it and rank 0"),
                evidence={"clock": entry})
            continue
        unc = entry.get("uncertainty_seconds")
        if unc is not None and unc >= CLOCK_MAX_UNCERTAINTY:
            yield Diagnosis(
                rule="clock_sync_degraded", severity="warning", rank=rank,
                summary=(f"clock offset uncertainty grew to {_ms(unc)} "
                         "(min-RTT window polluted)"),
                hint=(f"attribution finer than {_ms(unc)} against rank "
                      f"{rank} is noise; the RTT floor rose — look for "
                      "congestion or queueing between it and rank 0"),
                evidence={"clock": entry})


def check_recv_wait_skew(ev: Evidence) -> Iterator[Diagnosis]:
    """One worker's control-plane recvs wait far longer than the cluster
    median: its link (or the peer feeding it) is slow. Needs the rank-0
    cluster view with ≥2 WORKER snapshots — the coordinator's own
    recv-wait histogram is excluded on both sides of the comparison,
    because in the star topology rank 0's recvs block waiting for the
    slowest worker's tick: a sick worker inflates rank 0's profile, and
    judging it would blame exactly the wrong rank (the tick-lateness
    straggler rule owns that case). When telemetry windows exist the
    comparison runs over the recent windows' deltas, so one slow warm-up
    recv never brands a now-healthy link."""
    snapshots = _recent_snapshots(ev)
    per_rank: Dict[int, Tuple[float, int]] = {}
    for rank in sorted(snapshots):
        if rank == 0:
            continue
        p99, count = _hist_quantile_and_count(
            snapshots[rank], "hvd_wire_recv_wait_seconds", 0.99)
        if p99 is not None and count >= 20:
            per_rank[rank] = (p99, count)
    if len(per_rank) < 2:
        return
    for rank in sorted(per_rank):
        p99, count = per_rank[rank]
        # Median of the OTHER ranks' p99s (as in the live straggler
        # rule): a whole-cluster median would include the outlier's own
        # value and, at the documented 2-snapshot minimum, BE it —
        # making the rule unable to ever fire on a 2-rank job.
        others = sorted(p for r, (p, _) in per_rank.items() if r != rank)
        median = others[len(others) // 2]
        if (p99 >= RECV_WAIT_MIN_P99
                and p99 >= RECV_WAIT_SKEW_FACTOR * max(median, 1e-3)):
            yield Diagnosis(
                rule="recv_wait_skew", severity="warning", rank=rank,
                summary=(f"recv-wait p99 {_ms(p99)} vs cluster median "
                         f"{_ms(median)} over {count} recvs"),
                hint=(f"rank {rank} waits {p99 / max(median, 1e-9):.1f}x "
                      "the cluster median for control frames; its NIC, "
                      "its host, or the path to the coordinator is slow"),
                evidence={"recv_wait_p99_seconds": p99,
                          "cluster_median_p99_seconds": median,
                          "recvs": count})


def check_heartbeat_flapping(ev: Evidence) -> Iterator[Diagnosis]:
    """Repeated liveness-deadline trips on a rank that is still alive:
    heartbeats arrive in bursts with gaps — a flapping link or a starved
    process, and the precursor of a spurious abort."""
    trips_by_rank: Dict[int, float] = _series_totals(
        ev.snapshots, "hvd_wire_deadline_trips_total")
    for events in ev.postmortems:
        for event in events:
            if event.get("kind") == "deadline_trip" and "rank" in event:
                rank = int(event["rank"])
                trips_by_rank[rank] = trips_by_rank.get(rank, 0) + 1
    for rank in sorted(trips_by_rank):
        trips = int(trips_by_rank[rank])
        if trips >= FLAPPING_MIN_TRIPS:
            sev = ("critical" if trips >= FLAPPING_CRITICAL_TRIPS
                   else "warning")
            yield Diagnosis(
                rule="heartbeat_flapping", severity=sev, rank=rank,
                summary=(f"tripped its liveness deadline {trips} times "
                         "without the job dying"),
                hint=(f"rank {rank} sees heartbeat gaps longer than "
                      "HOROVOD_COMM_TIMEOUT_SECONDS in bursts; look for "
                      "GC/GIL pauses, CPU starvation by a co-tenant, or a "
                      "flapping NIC — each trip is one missed frame away "
                      "from a job abort"),
                evidence={"deadline_trips": trips})


def check_cache_hit_collapse(ev: Evidence) -> Iterator[Diagnosis]:
    """Response-cache hit rate collapsed under real traffic. Expected
    briefly after membership-relevant events (restart, abort, autotune
    flipping the cache categorical); persistent collapse means the
    negotiation fast path is off for the steady state."""
    for rank in sorted(ev.snapshots):
        snap = ev.snapshots[rank]
        entry_h = snap.get("hvd_controller_cache_hits_total")
        entry_m = snap.get("hvd_controller_cache_misses_total")
        if entry_h is None and entry_m is None:
            continue
        hits = sum(v for _, v in (entry_h or {}).get("values", []))
        misses = sum(v for _, v in (entry_m or {}).get("values", []))
        total = hits + misses
        if total < CACHE_MIN_TRAFFIC:
            continue
        rate = hits / total
        if rate < CACHE_COLLAPSE_RATE:
            membership = {}
            if ev.restart_epoch:
                membership["restart_epoch"] = ev.restart_epoch
            aborts = _series_totals(
                {rank: snap}, "hvd_controller_aborts_total").get(rank)
            if aborts:
                membership["aborts"] = aborts
            yield Diagnosis(
                rule="cache_hit_collapse", severity="warning", rank=rank,
                summary=(f"response-cache hit rate {rate:.0%} over "
                         f"{int(total)} requests"),
                hint=("a re-warm after a restart/abort recovers on its "
                      "own; a persistent collapse means tensor names do "
                      "not repeat (dynamic graph or unnamed collectives) "
                      "or HOROVOD_CACHE_CAPACITY is too small for the "
                      "working set"
                      + (" — this job shows membership churn: "
                         f"{membership}" if membership else "")),
                evidence={"hit_rate": round(rate, 4), "hits": hits,
                          "misses": misses, **membership})
    # Serving prefix cache: same rule slug, its own hint branches — a
    # warm-prefix rate this low under real page traffic means the fleet
    # is re-prefilling prompts it should be admitting near-free.
    for rank in sorted(ev.snapshots):
        snap = ev.snapshots[rank]
        entry_h = snap.get("hvd_serving_prefix_hits_total")
        entry_m = snap.get("hvd_serving_prefix_misses_total")
        if entry_h is None and entry_m is None:
            continue
        hits = sum(v for _, v in (entry_h or {}).get("values", []))
        misses = sum(v for _, v in (entry_m or {}).get("values", []))
        total = hits + misses
        if total < PREFIX_CACHE_MIN_TRAFFIC:
            continue
        rate = hits / total
        if rate >= PREFIX_CACHE_COLLAPSE_RATE:
            continue
        restarts = int(ev.restart_epoch) or int(max(_series_totals(
            ev.snapshots, "hvd_launcher_restarts_total").values(),
            default=0))
        if restarts:
            # Post-restart re-warm: the index died with the old
            # process's pools — distinct from a cold cache that never
            # warmed, which points at the traffic, not the lifecycle.
            hint = (f"post-restart re-warm (restart epoch {restarts}): "
                    "the prefix index lives in the engine's pools and "
                    "died with the previous process; the hit rate "
                    "recovers as shared prompts repopulate it — no "
                    "action here unless the restarts themselves recur "
                    "(see restart_churn)")
        else:
            hint = ("prefix-cache cold start, or traffic that shares no "
                    "page-aligned prefixes: if the rate stays this low "
                    "under steady load, check that system prompts are "
                    "byte-identical across requests (one drifted token "
                    "unshares every page after it) and that prompts "
                    "span at least one whole HOROVOD_SERVING_BLOCK_SIZE "
                    "page; raise HOROVOD_SERVING_PREFIX_CAPACITY if "
                    "evictions churn the index")
        yield Diagnosis(
            rule="cache_hit_collapse", severity="warning", rank=rank,
            summary=(f"serving prefix-cache hit rate {rate:.0%} over "
                     f"{int(total)} whole pages"),
            hint=hint,
            evidence={"prefix_hit_rate": round(rate, 4), "hits": hits,
                      "misses": misses, "restart_epoch": restarts,
                      "source": "serving_prefix"})


def check_restart_churn(ev: Evidence) -> Iterator[Diagnosis]:
    """The supervisor keeps relaunching the job: each restart replays
    init + cache warmup, and a crash loop converges on zero useful
    work."""
    restarts = ev.restart_epoch
    launcher = max(_series_totals(
        ev.snapshots, "hvd_launcher_restarts_total").values(), default=0)
    restarts = max(int(restarts), int(launcher))
    if restarts >= RESTART_CHURN_MIN:
        sev = ("critical" if restarts >= RESTART_CHURN_CRITICAL
               else "warning")
        yield Diagnosis(
            rule="restart_churn", severity=sev,
            summary=f"job is on restart epoch {restarts}",
            hint=("the job is crash-looping under --max-restarts; read "
                  "the flight-recorder postmortems (the dump tail names "
                  "the dead rank and in-flight ops) and fix the "
                  "recurring failure instead of raising the restart "
                  "budget"),
            evidence={"restart_epoch": restarts})


def check_membership_churn(ev: Evidence) -> Iterator[Diagnosis]:
    """An elastic job that keeps re-forming is paying the reshape tax —
    every transition discards in-flight collectives and re-broadcasts
    state from rank 0 — and usually has ONE sick host behind it. A
    couple of transitions is elastic working as designed; a stream of
    them is a flapping rank."""
    transitions = max(_series_totals(
        ev.snapshots, "hvd_membership_transitions_total").values(),
        default=0)
    if transitions < MEMBERSHIP_CHURN_MIN:
        return
    # Name the flapper: the old global rank most often lost to reshapes.
    # Counters are cumulative, so take each label's max across snapshots
    # (the coordinator owns the series; workers may echo stale copies).
    departures: Dict[str, float] = {}
    for rank in sorted(ev.snapshots):
        for label, value in _counter_by_first_label(
                ev.snapshots[rank],
                "hvd_membership_rank_departures_total").items():
            departures[label] = max(departures.get(label, 0.0), value)
    flapper: Optional[int] = None
    if departures:
        flapper = int(max(sorted(departures),
                          key=lambda label: departures[label]))
    sev = ("critical" if transitions >= MEMBERSHIP_CHURN_CRITICAL
           else "warning")
    epoch = _gauge(ev.snapshots, "hvd_membership_epoch")
    hint = ("each reshape discards in-flight work and re-syncs parameters "
            "from rank 0, so a flapping member costs far more than its "
            "own capacity")
    if flapper is not None:
        hint = (f"rank {flapper} keeps leaving the job "
                f"({int(departures[str(flapper)])} departure(s)); suspect "
                "its host (preemption, OOM kills, flaky NIC) before "
                "raising --elastic-respawns — " + hint)
    yield Diagnosis(
        rule="membership_churn", severity=sev, rank=flapper,
        summary=(f"{int(transitions)} elastic membership transitions "
                 f"(grow+shrink) this job"
                 + (f", now at epoch {int(epoch)}"
                    if epoch is not None else "")),
        hint=hint,
        evidence={"transitions": int(transitions),
                  "departures_by_rank": {k: int(v) for k, v in
                                         sorted(departures.items())},
                  "membership_epoch": epoch})


def check_autotune_search(ev: Evidence) -> Iterator[Diagnosis]:
    """The GP search itself can be the patient: a tuner that never
    scores is stalled; one whose current configuration scores far below
    its own best late in the search is wandering on noise."""
    active = _gauge(ev.snapshots, "hvd_autotune_active")
    if active is None or active < 1.0:
        return
    steps = _gauge(ev.snapshots, "hvd_autotune_steps_completed") or 0.0
    if steps == 0:
        # Zero steps is NORMAL early on (the tuner needs warmup + a full
        # sample window of payload cycles before its first score); only
        # a search still scoreless after a meaningful number of cycles
        # is stalled — without this guard every autotuned job reports
        # unhealthy from its very first /doctor scrape.
        cycles = 0
        for rank in sorted(ev.snapshots):
            _, count = _hist_quantile_and_count(
                ev.snapshots[rank], "hvd_controller_cycle_seconds", 0.5)
            cycles = max(cycles, count)
        if cycles >= AUTOTUNE_STALLED_MIN_CYCLES:
            yield Diagnosis(
                rule="autotune_stalled", severity="info",
                summary=(f"autotune has scored no configuration after "
                         f"{cycles} controller cycles"),
                hint=("the tuner only scores cycles that execute payload "
                      "bytes; if eager traffic is flowing and this "
                      "persists, the controller is seeing empty cycles "
                      "only"),
                evidence={"steps_completed": 0,
                          "cycles_observed": cycles})
        return
    last = None
    best = _gauge(ev.snapshots, "hvd_autotune_best_objective")
    for rank in sorted(ev.snapshots):
        by_label = _counter_by_first_label(
            ev.snapshots[rank], "hvd_autotune_objective")
        if by_label:
            last = by_label.get("score")
            break
    if (last is not None and best is not None and best > 0
            and steps >= AUTOTUNE_WANDER_MIN_STEPS
            and last < AUTOTUNE_WANDER_RATIO * best):
        yield Diagnosis(
            rule="autotune_wandering", severity="warning",
            summary=(f"search moved to a configuration scoring "
                     f"{last / best:.0%} of its own best after "
                     f"{int(steps)} steps"),
            hint=("the objective surface is noisy (timeshared host or "
                  "stragglers distorting cycle timing); consider "
                  "HOROVOD_AUTOTUNE_STRAGGLER_WEIGHT to discount "
                  "straggler noise, or pin the knobs you already trust "
                  "via their HOROVOD_* env vars"),
            evidence={"last_score": last, "best_score": best,
                      "steps_completed": int(steps)})


def check_serving_pressure(ev: Evidence) -> Iterator[Diagnosis]:
    """The serving tier is saturated: admission control is shedding
    load (queue at its bound / rejects counted), or the paged KV pool
    keeps running dry (preemption-by-recompute replaying whole
    prefixes). Both are capacity verdicts with direct knobs —
    docs/serving.md. Evidence: the ``hvd_serving_*`` series in any
    rank's snapshot; ``rank`` is attached only when more than one rank
    serves (a lone serving process needs no rank attribution)."""
    many = len(ev.snapshots) > 1
    for rank in sorted(ev.snapshots):
        snap = ev.snapshots[rank]
        subject = rank if many else None
        limit = _gauge({rank: snap}, "hvd_serving_queue_limit")
        depth = _gauge({rank: snap}, "hvd_serving_queue_depth") or 0.0
        rejects = _counter_by_first_label(
            snap, "hvd_serving_requests_total").get("rejected", 0.0)
        if limit and (rejects > 0
                      or depth >= SERVING_QUEUE_SATURATION_SHARE * limit):
            sev = ("critical" if rejects >= SERVING_CRITICAL_REJECTS
                   else "warning")
            yield Diagnosis(
                rule="serving_queue_saturation", severity=sev, rank=subject,
                summary=(f"serving queue at {int(depth)}/{int(limit)} "
                         f"with {int(rejects)} rejected request(s)"),
                hint=("admission control is shedding load — arrivals "
                      "outpace the decode loop; add serving capacity "
                      "(another replica, or a larger "
                      "HOROVOD_SERVING_MAX_BATCH if the chip has "
                      "headroom) or slow the client, and check "
                      "hvd_serving_ttft_seconds for how far the backlog "
                      "already pushed first-token latency"),
                evidence={"queue_depth": int(depth),
                          "queue_limit": int(limit),
                          "rejected": int(rejects)})
        total_preempts = sum(
            v for _, v in (snap.get("hvd_serving_preemptions_total")
                           or {}).get("values", []))
        if total_preempts >= SERVING_MIN_PREEMPTIONS:
            sev = ("critical"
                   if total_preempts >= SERVING_CRITICAL_PREEMPTIONS
                   else "warning")
            blocks = _gauge({rank: snap}, "hvd_serving_blocks_total")
            yield Diagnosis(
                rule="serving_block_exhaustion", severity=sev, rank=subject,
                summary=(f"paged KV pool ran dry {int(total_preempts)} "
                         "time(s) (preemption-by-recompute)"),
                hint=("each preemption drops a sequence's KV blocks and "
                      "re-prefills its whole prefix later — correct but "
                      "pure overhead; raise HOROVOD_SERVING_NUM_BLOCKS "
                      "(more HBM for the pool) or lower "
                      "HOROVOD_SERVING_MAX_BATCH so fewer sequences "
                      "share it"),
                evidence={"preemptions": int(total_preempts),
                          "blocks_total": (int(blocks)
                                           if blocks is not None else None)})


def check_router_replica_flapping(ev: Evidence) -> Iterator[Diagnosis]:
    """Serving replicas keep leaving the fleet: every departure is a
    reshape (requests re-route, in-flight work replays, and the dead
    replica's whole prefix cache is lost), so a flapping replica taxes
    the survivors far beyond its own capacity — the serving twin of
    ``membership_churn``. Counters are cumulative; take each replica
    label's max across snapshots."""
    departures: Dict[str, float] = {}
    for rank in sorted(ev.snapshots):
        for label, value in _counter_by_first_label(
                ev.snapshots[rank],
                "hvd_router_replica_departures_total").items():
            departures[label] = max(departures.get(label, 0.0), value)
    total = int(sum(departures.values()))
    if total < ROUTER_FLAPPING_MIN:
        return
    flapper = max(sorted(departures), key=lambda label: departures[label])
    sev = ("critical" if total >= ROUTER_FLAPPING_CRITICAL else "warning")
    replicas = _gauge(ev.snapshots, "hvd_router_replicas")
    epoch = _gauge(ev.snapshots, "hvd_router_epoch")
    yield Diagnosis(
        rule="router_replica_flapping", severity=sev,
        summary=(f"{total} serving replica departure(s) this fleet"
                 + (f", {int(replicas)} replica(s) still live"
                    if replicas is not None else "")),
        hint=(f"replica {flapper} left the fleet "
              f"{int(departures[flapper])} time(s); every departure "
              "re-routes its queue, replays its in-flight requests on "
              "the survivors, and cold-starts its prefix cache on "
              "rejoin — suspect that replica's host (OOM kills, "
              "preemption, device resets) before adding capacity"),
        evidence={"departures_total": total,
                  "departures_by_replica": {k: int(v) for k, v in
                                            sorted(departures.items())},
                  "live_replicas": (int(replicas)
                                    if replicas is not None else None),
                  "router_epoch": (int(epoch)
                                   if epoch is not None else None)})


def check_capacity_headroom(ev: Evidence) -> Iterator[Diagnosis]:
    """The job's live control-plane latencies have left the calibrated
    capacity envelope: negotiation or reshape p99 for the CURRENT world
    size runs ≥2x what the committed scaling curves predict
    (docs/capacity.md). That gap means the planner's forward
    extrapolations understate this job — re-plan before trusting a
    scale-up. Needs a calibration artifact
    (HOROVOD_CAPACITY_CALIBRATION live, or a capacity/simcluster
    artifact beside the traces offline) and the ``hvd_membership_size``
    abscissa. When telemetry windows exist, the p99 is judged over the
    recent windows' deltas — a slow warm-up heals within two rolls, and
    degradation after hours of health is not diluted into lifetime
    aggregates."""
    data = ev.capacity_calibration
    if not data or not data.get("control_plane"):
        return
    snapshots = _recent_snapshots(ev)
    world = _gauge(snapshots, "hvd_membership_size")
    if world is None or world < 1:
        return
    from ..utils.scaling_model import control_plane_from_artifact
    try:
        cal = control_plane_from_artifact(data)
    except (KeyError, TypeError, ValueError):
        return
    world = int(world)
    planes = (
        ("negotiation", "hvd_controller_cycle_seconds",
         CAPACITY_MIN_CYCLES, cal.negotiation_seconds(world)),
        ("reshape", "hvd_elastic_reshape_seconds",
         CAPACITY_MIN_RESHAPES, cal.reshape_seconds(world)),
    )
    for plane, series, min_samples, modeled in planes:
        # The coordinator owns both series; take the worst qualifying
        # rank in case a worker echoes a stale (smaller) copy.
        worst: Optional[Tuple[float, int]] = None
        for rank in sorted(snapshots):
            p99, count = _hist_quantile_and_count(
                snapshots[rank], series, 0.99)
            if p99 is not None and count >= min_samples:
                if worst is None or p99 > worst[0]:
                    worst = (p99, count)
        if worst is None:
            continue
        p99, count = worst
        floor = max(modeled, CAPACITY_MODELED_FLOOR)
        if p99 >= CAPACITY_HEADROOM_FACTOR * floor:
            yield Diagnosis(
                rule="capacity_headroom", severity="warning",
                summary=(f"{plane} p99 {_ms(p99)} at world size {world} "
                         f"vs modeled {_ms(modeled)} "
                         f"({p99 / max(modeled, 1e-9):.1f}x the "
                         "calibrated curve)"),
                hint=(f"the {plane} plane runs "
                      f"{p99 / max(modeled, 1e-9):.1f}x its calibrated "
                      "cost for this world size, so capacity-planner "
                      "extrapolations understate this job; find what "
                      "changed since calibration (slower hosts, a "
                      "straggler, congested control path — see the other "
                      "findings), or re-run examples/capacity_probe.py "
                      "on this substrate and point "
                      "HOROVOD_CAPACITY_CALIBRATION at the fresh "
                      "artifact"),
                evidence={"plane": plane,
                          "measured_p99_seconds": p99,
                          "modeled_seconds": modeled,
                          "world_size": world,
                          "factor": round(p99 / max(modeled, 1e-9), 2),
                          "samples": count,
                          "windows_judged": (
                              min(len(ev.windows), RECENT_WINDOWS)
                              if ev.windows else 0),
                          "calibration_source": data.get(
                              "substrate", "artifact")})


def check_calibration_drift(ev: Evidence) -> Iterator[Diagnosis]:
    """The LIVE re-fit of a control-plane curve has drifted ≥2x past
    the committed calibration's per-rank slope (docs/capacity.md "Live
    recalibration"): the committed capacity curves now understate this
    job's control plane structurally — not one slow percentile
    (capacity_headroom's case) but the fitted cost-per-rank itself.
    Residual-aware: the committed artifact's own ``fit_residual``
    widens the threshold, so ±20% box-pace swing between calibration
    and today never fires it. Needs both a committed calibration
    artifact and a live summary (the rank-0 window roller feeding
    ``utils/live_calibration.py`` live, or a persisted
    ``capacity_live.json`` beside the traces offline)."""
    live = ev.live_calibration
    data = ev.capacity_calibration
    if not live or not data or not data.get("control_plane"):
        return
    from ..utils.live_calibration import drift_report

    min_observations = {"negotiation": CAPACITY_MIN_CYCLES,
                        "reshape": CAPACITY_MIN_RESHAPES}
    for plane, row in sorted(drift_report(live, data).items()):
        if (row["observations"] < min_observations.get(
                plane, CAPACITY_MIN_RESHAPES)
                or row["windows"] < DRIFT_MIN_WINDOWS):
            continue
        if row["ratio"] < row["threshold"]:
            continue
        live_slope = row["live_per_rank_s"]
        committed_slope = row["committed_per_rank_s"]
        yield Diagnosis(
            rule="calibration_drift", severity="warning",
            summary=(f"{plane} per-rank cost re-fit live at "
                     f"{live_slope * 1e6:.0f}us/rank vs committed "
                     f"{committed_slope * 1e6:.0f}us/rank "
                     f"({row['ratio']:.1f}x, threshold "
                     f"{row['threshold']:.1f}x)"),
            hint=(f"the {plane} plane's live slope drifted "
                  f"{row['ratio']:.1f}x past the committed calibration "
                  "(residual-aware threshold "
                  f"{row['threshold']:.1f}x) — the capacity planner's "
                  "forward extrapolations are stale for this job; "
                  "re-plan from the live curves (python -m "
                  "horovod_tpu.tools.capacity --live "
                  "$HOROVOD_CAPACITY_LIVE_DIR), and if the drift "
                  "persists re-run examples/capacity_probe.py and "
                  "re-point HOROVOD_CAPACITY_CALIBRATION; with "
                  "HOROVOD_AUTOTUNE_PRIORS=capacity the tuner re-seeds "
                  "from the live curves automatically"),
            evidence={"plane": plane,
                      "live_per_rank_seconds": live_slope,
                      "committed_per_rank_seconds": committed_slope,
                      "ratio": row["ratio"],
                      "threshold": row["threshold"],
                      "fit_residual": row["fit_residual"],
                      "observations": row["observations"],
                      "windows": row["windows"],
                      "world_size": live.get("world_size"),
                      "calibration_source": data.get(
                          "substrate", "artifact")})


ALL_RULES = (
    check_persistent_straggler,
    check_clock_sync,
    check_recv_wait_skew,
    check_heartbeat_flapping,
    check_cache_hit_collapse,
    check_restart_churn,
    check_membership_churn,
    check_autotune_search,
    check_serving_pressure,
    check_router_replica_flapping,
    check_capacity_headroom,
    check_calibration_drift,
)

# Every rule slug the catalog can emit — the hvd_doctor_findings gauge
# zeroes the full set each sweep so a healed finding visibly drops to 0.
RULE_SLUGS = (
    "persistent_straggler",
    "clock_sync_degraded",
    "recv_wait_skew",
    "heartbeat_flapping",
    "cache_hit_collapse",
    "restart_churn",
    "membership_churn",
    "autotune_stalled",
    "autotune_wandering",
    "serving_queue_saturation",
    "serving_block_exhaustion",
    "router_replica_flapping",
    "capacity_headroom",
    "calibration_drift",
)


def diagnose(ev: Evidence) -> List[Diagnosis]:
    """Run every rule, dedupe (rule, rank) keeping the worse severity,
    and return findings ordered most-severe first."""
    best: Dict[Tuple[str, Optional[int]], Diagnosis] = {}
    order = {s: i for i, s in enumerate(SEVERITIES)}
    for rule in ALL_RULES:
        for finding in rule(ev):
            key = (finding.rule, finding.rank)
            kept = best.get(key)
            if kept is None or order[finding.severity] < order[kept.severity]:
                best[key] = finding
    return sorted(
        best.values(),
        key=lambda d: (order[d.severity], d.rule,
                       -1 if d.rank is None else d.rank))
