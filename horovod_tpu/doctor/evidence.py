"""Evidence collection for the cluster doctor.

The doctor's rules (``doctor/rules.py``) are pure functions over ONE
bundle of observations; this module builds that bundle from either of
the two worlds the observability stack lives in:

* **Live** (:meth:`Evidence.live`) — the r8 metrics plane: this rank's
  registry snapshot plus every worker snapshot piggybacked on controller
  ticks (the rank-0 cluster view), and the current restart epoch. Used
  by the ``/doctor`` endpoint and the coordinator's periodic sweep.
* **Artifacts** (:meth:`Evidence.from_artifacts`) — the r9 trace plane
  left behind on disk: ``straggler_report.json`` (attributed in memory
  from the per-rank traces when missing), ``clock_offsets.json``, and
  any flight-recorder JSONL postmortems. Used by
  ``python -m horovod_tpu.tools.doctor`` long after the job is gone.

Collection is read-only and best-effort: a missing or malformed
artifact yields an absent field (rules skip what they cannot see), never
an exception — the doctor must keep diagnosing a half-dead job.
"""

from __future__ import annotations

import dataclasses
import glob
import json
import os
from typing import Dict, List, Optional


@dataclasses.dataclass
class Evidence:
    """Everything one doctor pass may consult. All fields optional —
    each rule states its own minimum and silently stands down below it."""

    # rank -> metrics registry snapshot (hvd.metrics.snapshot() shape).
    snapshots: Dict[int, dict] = dataclasses.field(default_factory=dict)
    # straggler_report.json contents (trace/straggler.py attribute()).
    straggler_report: Optional[dict] = None
    # rank -> clock_offsets.json entry (trace/clock.py table()).
    clock: Optional[Dict[int, dict]] = None
    # Flight-recorder postmortems: one event list (parsed JSONL) per file.
    postmortems: List[List[dict]] = dataclasses.field(default_factory=list)
    # HOROVOD_RESTART_EPOCH (live) / launcher_restart count (artifacts).
    restart_epoch: int = 0
    # Control-plane calibration artifact (scaling_model shape: a
    # "control_plane" dict of measured sizes) for the capacity_headroom
    # rule. Live jobs opt in via HOROVOD_CAPACITY_CALIBRATION; offline
    # runs pick up a committed capacity/simcluster artifact beside the
    # traces when one exists.
    capacity_calibration: Optional[dict] = None
    # Completed telemetry windows (metrics.windows() shape, oldest
    # first): the windowed rules judge the RECENT windows instead of
    # lifetime-cumulative snapshots whenever any exist.
    windows: List[dict] = dataclasses.field(default_factory=list)
    # Live-calibration summary (utils/live_calibration.py
    # LiveCalibration.summary() shape) for the calibration_drift rule.
    # Live jobs carry the in-process re-fit; offline runs rebuild an
    # equivalent summary from a persisted capacity_live.json.
    live_calibration: Optional[dict] = None
    # "live" or "artifacts:<dir>" — recorded in the report for operators.
    source: str = "live"

    @classmethod
    def live(cls) -> "Evidence":
        """This process's registry + the piggybacked worker snapshots.
        On rank 0 that is the whole job; on a worker it is one rank."""
        from .. import metrics
        from ..common.config import (
            capacity_calibration_path,
            env_rank,
            restart_epoch,
        )

        from ..utils import live_calibration

        local = env_rank() or 0
        snapshots = {local: metrics.snapshot()}
        for rank, snap in sorted(metrics.remote_snapshots().items()):
            snapshots.setdefault(int(rank), snap)
        calibration = None
        cal_path = capacity_calibration_path()
        if cal_path:
            calibration = _load_json(cal_path)
        return cls(snapshots=snapshots, restart_epoch=restart_epoch(),
                   capacity_calibration=calibration,
                   windows=metrics.windows(),
                   live_calibration=live_calibration.live_summary(),
                   source="live")

    @classmethod
    def from_artifacts(cls, path: str) -> "Evidence":
        """Everything diagnosable in an artifact directory (a traced
        job's ``HOROVOD_TRACE_DIR``, possibly also holding flight-recorder
        dumps). Read-only: a missing straggler report is attributed in
        memory from the per-rank traces, never written back."""
        from ..trace import (
            MERGED_TRACE_FILE,
            OFFSETS_FILE,
            REPORT_FILE,
            load_offsets,
            merge_events,
            rank_trace_files,
        )
        from ..trace.straggler import attribute

        report = _load_json(os.path.join(path, REPORT_FILE))
        clock = load_offsets(os.path.join(path, OFFSETS_FILE)) or None
        if report is None:
            events = _load_json(os.path.join(path, MERGED_TRACE_FILE))
            if events is None:
                files = rank_trace_files(path)
                if files:
                    per_rank = {}
                    for rank, file_path in sorted(files.items()):
                        loaded = _load_json(file_path)
                        if isinstance(loaded, list):
                            per_rank[rank] = loaded
                    if per_rank:
                        try:
                            events = merge_events(per_rank, clock or {})
                        except ValueError:
                            events = None
            if isinstance(events, list):
                # feed=False: an offline diagnosis must not mutate (or
                # require) a live metrics registry.
                report = attribute(events, feed=False)
        if report is not None and clock is None and report.get("clock"):
            clock = {int(r): entry
                     for r, entry in sorted(report["clock"].items())}
        postmortems = _load_postmortems(path)
        restarts = sum(
            1 for events in postmortems for ev in events
            if ev.get("kind") == "launcher_restart")
        calibration = None
        for name in ("capacity_r17.json", "simcluster_r13.json"):
            loaded = _load_json(os.path.join(path, name))
            if loaded and loaded.get("control_plane"):
                calibration = loaded
                break
        # A dead job's persisted live re-fit (capacity_live.json) lets
        # the drift rule run offline against the committed calibration
        # found beside it.
        live_summary = None
        live_artifact = _load_json(os.path.join(path, "capacity_live.json"))
        if live_artifact is not None:
            from ..utils.live_calibration import summary_from_artifact

            live_summary = summary_from_artifact(live_artifact)
        return cls(straggler_report=report, clock=clock,
                   postmortems=postmortems, restart_epoch=restarts,
                   capacity_calibration=calibration,
                   live_calibration=live_summary,
                   source=f"artifacts:{path}")

    def ranks_observed(self) -> List[int]:
        ranks = set(self.snapshots)
        if self.straggler_report:
            ranks.update(int(r) for r in
                         self.straggler_report.get("ranks", []))
        if self.clock:
            ranks.update(int(r) for r in self.clock)
        for events in self.postmortems:
            for ev in events:
                if "rank" in ev:
                    ranks.add(int(ev["rank"]))
        return sorted(ranks)


def _load_json(path: str):
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _load_postmortems(path: str) -> List[List[dict]]:
    """Parse every flight-recorder dump under ``path``: any ``*.jsonl*``
    file whose first line is a ``flight_recorder_dump`` header (the
    recorder's ``{rank}``/``.rankN`` expansion makes names vary)."""
    out: List[List[dict]] = []
    for file_path in sorted(glob.glob(os.path.join(path, "*.jsonl*"))):
        if ".tmp." in os.path.basename(file_path):
            # A dump killed between temp-write and os.replace leaves its
            # private temp file behind; counting it would double every
            # event the completed dump also carries.
            continue
        events: List[dict] = []
        try:
            with open(file_path) as f:
                for line in f:
                    line = line.strip()
                    if line:
                        events.append(json.loads(line))
        except (OSError, ValueError):
            continue
        if events and events[0].get("kind") == "flight_recorder_dump":
            out.append(events)
    return out
