"""Cluster doctor: rule-based diagnosis over the observability stack.

Rounds 7–9 built the evidence — per-rank metrics with a rank-0 cluster
view, flight-recorder postmortems, clock-synced traces with straggler
attribution — and round 10 linted the code that produces it. This layer
is the first CONSUMER that reads all of it end to end: a fixed catalog
of rules (``doctor/rules.py``) turns raw series and reports into
structured :class:`~horovod_tpu.doctor.rules.Diagnosis` records —
severity, subject rank, the evidence numbers, and a human remediation
hint ("rank 1 is persistently ≥50ms late at negotiation across 200
collectives; suspect its NIC or a co-tenant").

Three surfaces, one engine (docs/doctor.md):

* **Live HTTP** — rank 0's metrics endpoint also serves ``GET /doctor``
  (JSON report over the cluster view), so the same scrape target that
  answers "what are the numbers" answers "what is wrong".
* **Periodic log line** — the coordinator runs a sweep every
  ``HOROVOD_DOCTOR_CYCLES`` cycles, logs one summary line, and mirrors
  per-rule finding counts into the ``hvd_doctor_*`` gauges.
* **Offline CLI** — ``python -m horovod_tpu.tools.doctor <artifact-dir>``
  diagnoses a dead job from what it left on disk (straggler report,
  clock offsets, flight-recorder JSONL), attributing the trace in
  memory when the report file is missing.

Everything here is read-only over the evidence and inert unless called;
nothing registers metrics at import time.
"""

from __future__ import annotations

from typing import List, Optional

from .evidence import Evidence  # noqa: F401
from .rules import (  # noqa: F401
    ALL_RULES,
    RULE_SLUGS,
    Diagnosis,
    diagnose,
)

__all__ = [
    "Evidence", "Diagnosis", "ALL_RULES", "RULE_SLUGS", "diagnose",
    "report", "render_text", "summary", "periodic_line", "http_body",
]

_m = None


def _doctor_metrics():
    """Lazy registration (tests/test_metrics_lint.py: never at import
    time)."""
    global _m
    if _m is None:
        from types import SimpleNamespace

        from .. import metrics

        _m = SimpleNamespace(
            runs=metrics.counter(
                "hvd_doctor_runs_total",
                "Completed cluster-doctor sweeps on this rank."),
            findings=metrics.gauge(
                "hvd_doctor_findings",
                "Findings per rule in the most recent doctor sweep "
                "(0 once a finding heals).", ("rule",)))
    return _m


def report(evidence: Optional[Evidence] = None) -> dict:
    """Run the full rule catalog and return the JSON-clean report served
    by ``GET /doctor`` and printed by the offline CLI. With no evidence
    given, diagnoses the live process (rank-0 cluster view when the
    worker snapshots have been piggybacked). A live sweep also mirrors
    per-rule counts into the ``hvd_doctor_*`` series."""
    ev = evidence if evidence is not None else Evidence.live()
    findings = diagnose(ev)
    counts = {severity: 0 for severity in ("critical", "warning", "info")}
    for finding in findings:
        counts[finding.severity] += 1
    if ev.source == "live":
        from .. import metrics

        if metrics.on():
            m = _doctor_metrics()
            m.runs.inc()
            per_rule = {slug: 0 for slug in RULE_SLUGS}
            for finding in findings:
                per_rule[finding.rule] = per_rule.get(finding.rule, 0) + 1
            for slug in sorted(per_rule):
                m.findings.labels(slug).set(per_rule[slug])
    return {
        "source": ev.source,
        "ranks_observed": ev.ranks_observed(),
        "healthy": not findings,
        "counts": counts,
        "findings": [finding.to_dict() for finding in findings],
    }


def summary(rep: Optional[dict] = None) -> dict:
    """Compact verdict for ``bench.py`` rows (the ``"health"`` field):
    how many rules hit and the worst finding's hint. All-empty on a
    healthy run — honest emptiness beats invented detail."""
    rep = rep if rep is not None else report()
    findings = rep.get("findings", [])
    worst = findings[0] if findings else None
    return {
        "findings": len(findings),
        "rules_hit": sorted({f["rule"] for f in findings}),
        "worst_rank": worst.get("rank") if worst else None,
        "worst_hint": worst.get("hint") if worst else None,
    }


def render_text(rep: dict) -> str:
    """Human rendering of a report (CLI default output)."""
    lines = [f"cluster doctor — source: {rep.get('source', '?')}, "
             f"ranks observed: {rep.get('ranks_observed', [])}"]
    findings = rep.get("findings", [])
    if not findings:
        lines.append("healthy: no rule produced a finding")
    for finding in findings:
        where = (f" rank {finding['rank']}"
                 if finding.get("rank") is not None else "")
        lines.append(
            f"[{finding['severity']}] {finding['rule']}{where}: "
            f"{finding['summary']}")
        lines.append(f"    hint: {finding['hint']}")
        if finding.get("evidence"):
            lines.append(f"    evidence: {finding['evidence']}")
    return "\n".join(lines) + "\n"


def periodic_line(evidence: Optional[Evidence] = None,
                  rep: Optional[dict] = None) -> str:
    """One log line for the coordinator's periodic sweep. Pass ``rep``
    to render a report already produced by :func:`report` — calling
    :func:`report` twice would double-count the sweep gauges."""
    if rep is None:
        rep = report(evidence)
    if rep["healthy"]:
        return (f"healthy ({len(rep['ranks_observed'])} rank(s) "
                "observed)")
    parts = []
    for finding in rep["findings"][:3]:
        where = (f"rank {finding['rank']} "
                 if finding.get("rank") is not None else "")
        parts.append(f"{where}{finding['rule']} [{finding['severity']}]")
    more = len(rep["findings"]) - 3
    if more > 0:
        parts.append(f"+{more} more")
    return (f"{len(rep['findings'])} finding(s): " + "; ".join(parts)
            + f" — full report at /doctor; worst hint: "
              f"{rep['findings'][0]['hint']}")


def http_body() -> "tuple[str, str]":
    """(content type, body) for the exporter's ``GET /doctor`` route."""
    import json

    return ("application/json; charset=utf-8",
            json.dumps(report(), indent=1, sort_keys=True) + "\n")
