"""Process/device topology discovery — the TPU-native replacement for MPI
communicator setup.

The reference derives rank/size from ``MPI_Comm_rank/size``, local rank from an
``MPI_Comm_split_type(SHARED)`` node communicator, and cross rank from an
``MPI_Comm_split(local_rank)`` (reference ``horovod/common/operations.cc:890-959``).
On TPU there is no mpirun: topology comes from the TPU runtime / JAX process
model, or from environment variables set by our launcher (``horovodrun``).

Precedence:
  1. ``HOROVOD_RANK``/``HOROVOD_SIZE`` (+``_LOCAL_RANK``/``_LOCAL_SIZE``) —
     set by our launcher; also accepts OpenMPI's ``OMPI_COMM_WORLD_*`` names
     for drop-in compatibility (the reference's tests read those,
     ``test/common.py:25-58``).
  2. JAX multi-host runtime: ``jax.process_index()`` / ``jax.process_count()``
     (one process per TPU host, the idiomatic pod-slice model).
  3. Single-process default: rank 0 of 1.

Note on semantics: a Horovod "rank" is one *process*. The reference runs one
process per GPU so rank==device; on TPU one process drives several chips and
intra-process data parallelism is expressed over the device mesh (see
``horovod_tpu.parallel``). ``num_devices``/``local_devices`` expose chip-level
topology alongside the process-level rank/size.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Tuple

from .config import env_str


def _first_env_int(names: Sequence[str]) -> Optional[int]:
    for name in names:
        val = env_str(name)
        if val is not None and val.strip():
            try:
                return int(val)
            except ValueError:
                pass
    return None


@dataclasses.dataclass(frozen=True)
class Topology:
    """Immutable view of the job topology, fixed at ``hvd.init()``.

    Mirrors the rank/size/local/cross ints kept in the reference's
    ``HorovodGlobalState`` (``horovod/common/global_state.h:60-75``).
    """

    rank: int
    size: int
    local_rank: int
    local_size: int
    cross_rank: int
    cross_size: int
    # Chip-level topology (TPU-only extension; 0 devices possible under
    # pure-CPU tests before JAX is imported).
    num_devices: int = 0
    local_num_devices: int = 0
    is_homogeneous: bool = True

    def validate(self) -> None:
        if not (0 <= self.rank < self.size):
            raise ValueError(f"rank {self.rank} out of range for size {self.size}")
        if not (0 <= self.local_rank < self.local_size):
            raise ValueError(
                f"local_rank {self.local_rank} out of range for local_size {self.local_size}"
            )


def _device_counts() -> Tuple[int, int]:
    """Total and per-process accelerator device counts from JAX, if importable."""
    try:
        import jax

        return jax.device_count(), jax.local_device_count()
    except Exception:  # pragma: no cover - jax always present in this image
        return 0, 0


def detect(ranks: Optional[Sequence[int]] = None,
           probe_devices: bool = True) -> Topology:
    """Discover topology. ``ranks`` narrows the job to a subset, mirroring
    ``hvd.init(ranks)`` in the reference (``horovod/common/basics.py:29-55``).

    ``probe_devices=False`` skips the JAX device-count probe entirely:
    after backend acquisition failed its bounded retries (a wedged attempt
    may still hold xla_bridge's backend lock), re-entering
    ``jax.device_count()`` here would hang unboundedly — the caller
    already knows there are no usable accelerators.
    """
    rank = _first_env_int(["HOROVOD_RANK", "OMPI_COMM_WORLD_RANK", "PMI_RANK"])
    size = _first_env_int(["HOROVOD_SIZE", "OMPI_COMM_WORLD_SIZE", "PMI_SIZE"])
    if (rank is None) != (size is None):
        # Half-set launcher env is a misconfiguration, not a fallback case:
        # silently training as rank 0 of 1 on every host corrupts results.
        raise RuntimeError(
            "partially-set launcher environment: exactly one of rank/size is "
            f"present (rank={rank}, size={size}); set both HOROVOD_RANK and "
            "HOROVOD_SIZE (or neither, to use the JAX process model)")

    num_devices, local_num_devices = (
        _device_counts() if probe_devices else (0, 0))

    if rank is None:
        # No launcher env: fall back to the JAX process model.
        try:
            import jax

            rank = jax.process_index()
            size = jax.process_count()
        except Exception:  # pragma: no cover
            rank, size = 0, 1

    local_rank = _first_env_int(
        ["HOROVOD_LOCAL_RANK", "OMPI_COMM_WORLD_LOCAL_RANK"]
    )
    local_size = _first_env_int(
        ["HOROVOD_LOCAL_SIZE", "OMPI_COMM_WORLD_LOCAL_SIZE"]
    )
    if local_rank is None or local_size is None:
        # Single process per host (the TPU idiom) unless the launcher says
        # otherwise.
        local_rank, local_size = 0, 1

    cross_rank = _first_env_int(["HOROVOD_CROSS_RANK"])
    cross_size = _first_env_int(["HOROVOD_CROSS_SIZE"])
    if cross_rank is None or cross_size is None:
        # Homogeneous assumption: nodes all have local_size ranks. The
        # reference verifies homogeneity with an allgather of local sizes
        # (operations.cc:936-952); our launcher exports explicit CROSS_* vars
        # for heterogeneous layouts instead.
        cross_rank = rank // max(local_size, 1)
        cross_size = (size + local_size - 1) // max(local_size, 1)

    if ranks:
        ranks = list(ranks)
        if sorted(set(ranks)) != sorted(ranks):
            raise ValueError("init(ranks=...) must not contain duplicates")
        if rank in ranks:
            new_rank = ranks.index(rank)
            topo = Topology(
                rank=new_rank,
                size=len(ranks),
                local_rank=0,
                local_size=1,
                cross_rank=new_rank,
                cross_size=len(ranks),
                num_devices=num_devices,
                local_num_devices=local_num_devices,
            )
            topo.validate()
            return topo
        raise RuntimeError(
            f"process rank {rank} not in init(ranks={ranks}); reference "
            "semantics: non-member processes must not call horovod APIs "
            "(horovod/common/basics.py:44-55)"
        )

    topo = Topology(
        rank=rank,
        size=size,
        local_rank=local_rank,
        local_size=local_size,
        cross_rank=cross_rank,
        cross_size=cross_size,
        num_devices=num_devices,
        local_num_devices=local_num_devices,
    )
    topo.validate()
    return topo
