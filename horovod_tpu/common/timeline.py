"""Horovod Timeline: Chrome-tracing JSON profiler for the eager tier.

Reference: ``horovod/common/timeline.{h,cc}`` — rank 0 writes one
chrome://tracing file covering all ranks (the coordinator knows every tensor's
lifecycle), with a dedicated writer thread draining a lock-free queue so the
hot path never blocks (``timeline.h:46-74``, ``WriterLoop`` ``timeline.cc:120``),
and a per-tensor state machine UNKNOWN→NEGOTIATING→TOP_LEVEL→ACTIVITY
(``timeline.h:76``).

Same design here: ``record()`` enqueues; a daemon thread serializes. Each
tensor gets a chrome "process" (pid) carrying its name, as in the reference's
metadata events. Enabled via ``HOROVOD_TIMELINE=<file>``; cycle markers via
``HOROVOD_TIMELINE_MARK_CYCLES`` (``operations.cc:986-996``).
"""

from __future__ import annotations

import json
import queue
import threading
import time
from typing import Dict, Optional

from . import hvd_logging as logging
from .. import metrics
from ..analysis.lockorder import make_lock

# Activity vocabulary (reference common/common.h:30-51, with the CUDA/MPI
# entries replaced by their TPU analogues).
NEGOTIATE_ALLREDUCE = "NEGOTIATE_ALLREDUCE"
NEGOTIATE_ALLGATHER = "NEGOTIATE_ALLGATHER"
NEGOTIATE_BROADCAST = "NEGOTIATE_BROADCAST"
ALLREDUCE = "ALLREDUCE"
ALLGATHER = "ALLGATHER"
BROADCAST = "BROADCAST"
QUEUE = "QUEUE"
WAIT_FOR_DATA = "WAIT_FOR_DATA"
WAIT_FOR_OTHER_TENSOR_DATA = "WAIT_FOR_OTHER_TENSOR_DATA"
INIT_FUSION_BUFFER = "INIT_FUSION_BUFFER"
MEMCPY_IN_FUSION_BUFFER = "MEMCPY_IN_FUSION_BUFFER"
MEMCPY_OUT_FUSION_BUFFER = "MEMCPY_OUT_FUSION_BUFFER"
XLA_COLLECTIVE = "XLA_COLLECTIVE"
TCP_COLLECTIVE = "TCP_COLLECTIVE"
CYCLE_START = "CYCLE_START"

_m = None


def _tl_metrics():
    global _m
    if _m is None:
        from types import SimpleNamespace

        _m = SimpleNamespace(
            emitted=metrics.counter(
                "hvd_timeline_events_total",
                "Timeline events enqueued to the writer thread."),
            dropped=metrics.counter(
                "hvd_timeline_events_dropped_total",
                "Timeline events dropped on writer-queue overflow."))
    return _m


class Timeline:
    """Async chrome-trace writer. All public methods are thread-safe and
    non-blocking (enqueue only)."""

    _SHUTDOWN = object()

    def __init__(self, filename: str, mark_cycles: bool = False):
        self._filename = filename
        self.mark_cycles = mark_cycles
        self._queue: "queue.Queue" = queue.Queue(maxsize=1 << 20)
        self._pids: Dict[str, int] = {}
        self._lock = make_lock("timeline.pids")
        self._start = time.monotonic()
        self._file = open(filename, "w")
        self._file.write("[\n")
        self._closed = False
        # Absolute anchor for the otherwise process-private timebase
        # (docs/tracing.md): wall clock at the monotonic origin + rank, so
        # even a standalone per-rank trace can be laid against another
        # rank's (or the merged cluster trace) instead of floating.
        from .config import env_rank

        self._file.write(json.dumps({
            "name": "clock_sync", "ph": "M", "pid": 0,
            # hvdlint: disable=HVD004 (the wall anchor IS the point)
            "args": {"wall_anchor": time.time(),
                     "monotonic_origin": self._start,
                     "rank": env_rank()},
        }) + ",\n")
        self._dropped = 0  # overflow count; surfaced at close()
        # Own lock, NOT self._lock: _tensor_pid emits while holding
        # self._lock, so an overflow inside that call must not re-acquire
        # it (non-reentrant -> self-deadlock).
        self._drop_lock = make_lock("timeline.drops")
        self._writer = threading.Thread(
            target=self._writer_loop, name="hvd-timeline-writer", daemon=True
        )
        self._writer.start()

    # -- internal ----------------------------------------------------------

    def _now_us(self) -> int:
        return int((time.monotonic() - self._start) * 1e6)

    def _emit(self, event: dict) -> None:
        if self._closed:
            return
        try:
            self._queue.put_nowait(event)
        except queue.Full:
            # Drop rather than block the hot path (the reference's lock-free
            # queue has the same overflow policy by construction) — but
            # never silently: count the loss, warn once at close, and stamp
            # the total into the trace metadata.
            with self._drop_lock:
                self._dropped += 1
            if metrics.on():
                _tl_metrics().dropped.inc()
        else:
            if metrics.on():
                _tl_metrics().emitted.inc()

    def _writer_loop(self) -> None:
        while True:
            ev = self._queue.get()
            if ev is Timeline._SHUTDOWN:
                return
            self._file.write(json.dumps(ev) + ",\n")

    def _tensor_pid(self, tensor_name: str) -> int:
        with self._lock:
            pid = self._pids.get(tensor_name)
            if pid is None:
                pid = len(self._pids) + 1
                self._pids[tensor_name] = pid
                # Chrome metadata event naming the "process" after the tensor
                # (reference timeline.cc WriteEvent 'M' records).
                self._emit({
                    "name": "process_name", "ph": "M", "pid": pid,
                    "args": {"name": tensor_name},
                })
                self._emit({
                    "name": "process_sort_index", "ph": "M", "pid": pid,
                    "args": {"sort_index": pid},
                })
            return pid

    # -- lifecycle events (reference timeline.h:84-116) ---------------------

    def negotiate_start(self, tensor_name: str, request_type: str) -> None:
        pid = self._tensor_pid(tensor_name)
        self._emit({"name": f"NEGOTIATE_{request_type.upper()}", "ph": "B",
                    "pid": pid, "ts": self._now_us()})

    def negotiate_rank_ready(self, tensor_name: str, rank: int) -> None:
        """Instant event when a rank's request arrives at the coordinator
        (reference records per-rank negotiation phases)."""
        pid = self._tensor_pid(tensor_name)
        self._emit({"name": str(rank), "ph": "i", "pid": pid,
                    "ts": self._now_us(), "s": "p"})

    def negotiate_end(self, tensor_name: str, request_type: str) -> None:
        pid = self._tensor_pid(tensor_name)
        self._emit({"name": f"NEGOTIATE_{request_type.upper()}", "ph": "E",
                    "pid": pid, "ts": self._now_us()})

    def start(self, tensor_name: str, op_name: str) -> None:
        """Top-level operation span (ALLREDUCE/ALLGATHER/BROADCAST)."""
        pid = self._tensor_pid(tensor_name)
        self._emit({"name": op_name, "ph": "B", "pid": pid,
                    "ts": self._now_us()})

    def activity_start(self, tensor_name: str, activity: str) -> None:
        pid = self._tensor_pid(tensor_name)
        self._emit({"name": activity, "ph": "B", "pid": pid, "tid": 1,
                    "ts": self._now_us()})

    def activity_end(self, tensor_name: str) -> None:
        pid = self._tensor_pid(tensor_name)
        self._emit({"ph": "E", "pid": pid, "tid": 1, "ts": self._now_us()})

    def end(self, tensor_name: str) -> None:
        pid = self._tensor_pid(tensor_name)
        self._emit({"ph": "E", "pid": pid, "ts": self._now_us()})

    def mark_cycle_start(self) -> None:
        """Instant event per controller cycle, opt-in
        (``HOROVOD_TIMELINE_MARK_CYCLES``, reference operations.cc:996)."""
        if self.mark_cycles:
            self._emit({"name": CYCLE_START, "ph": "i", "pid": 0,
                        "ts": self._now_us(), "s": "g"})

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._queue.put(Timeline._SHUTDOWN)
        self._writer.join(timeout=5.0)
        if self._dropped:
            # One-time, not per-drop: a saturated queue would otherwise
            # flood the log from the hot path it exists to protect.
            logging.warning(
                "timeline: dropped %d event(s) on writer-queue overflow — "
                "the trace at %s is incomplete (dropped_events in the "
                "trace_end metadata records the count)",
                self._dropped, self._filename)
        # Chrome tracing accepts a trailing comma-less final entry; emit a
        # terminator metadata record then close the array.
        self._file.write(json.dumps({"name": "trace_end", "ph": "M", "pid": 0,
                                     "args": {"dropped_events": self._dropped}}))
        self._file.write("\n]\n")
        self._file.close()
