"""SPMD-tier observability: the jit-tier counterpart of the eager
timeline.

The reference's flagship observability subsystem is the timeline
(``horovod/common/timeline.cc:120`` — per-tensor activity spans written
by rank 0, viewed in chrome://tracing). Our eager tier reproduces it
(``common/timeline.py``/``core/src/timeline.h``); on the tier that
actually runs on TPU (jit/GSPMD), collectives are XLA ops inside one
compiled program, so the equivalent record is the XLA profiler trace —
this module wires it up:

* Every traced collective in ``horovod_tpu.ops.collective_ops`` runs
  under ``jax.named_scope("hvd.<op>[.<name>]")``, so its spans show up
  in profiler traces — and its ops carry the scope in lowered HLO
  metadata — under the same user-visible names the eager timeline
  records (``hvd.allreduce.DistributedOptimizer.3``, ...).
* ``trace(log_dir)`` / ``start_trace``/``stop_trace`` wrap
  ``jax.profiler`` with the reference's HOROVOD_TIMELINE-style
  env-var activation (``HOROVOD_PROFILE_DIR``).
* ``annotate(name)`` / ``step(n)`` label host-side regions and training
  steps in the same trace.

View traces with TensorBoard's profile plugin or Perfetto
(``docs/timeline.md``).
"""

from __future__ import annotations

import contextlib
import os
from typing import Iterator, Optional

from .config import env_str

import jax

__all__ = ["trace", "start_trace", "stop_trace", "annotate", "step",
           "named_scope", "PROFILE_DIR_ENV"]

PROFILE_DIR_ENV = "HOROVOD_PROFILE_DIR"

# Re-export: model code can label its own regions with the same mechanism
# the collectives use; the labels land in HLO metadata (survive
# compilation), unlike TraceAnnotation which is host-side only.
named_scope = jax.named_scope


@contextlib.contextmanager
def trace(log_dir: Optional[str] = None) -> Iterator[None]:
    """Capture a profiler trace of the enclosed block::

        with hvd.profiler.trace("/tmp/prof"):
            for _ in range(3):
                params, opt_state, loss = step(params, opt_state, batch)
            jax.block_until_ready(loss)

    ``log_dir`` defaults to ``$HOROVOD_PROFILE_DIR`` (the reference
    activates its timeline with the HOROVOD_TIMELINE env var the same
    way); with neither set, the block runs unprofiled — safe to leave in
    production code. Remember to block on the last output: dispatch is
    async and an un-synced trace records only enqueues."""
    log_dir = log_dir or env_str(PROFILE_DIR_ENV)
    if not log_dir:
        yield
        return
    os.makedirs(log_dir, exist_ok=True)
    with jax.profiler.trace(log_dir):
        yield


def start_trace(log_dir: Optional[str] = None) -> None:
    """Non-context form of :func:`trace` (pair with :func:`stop_trace`)."""
    log_dir = log_dir or env_str(PROFILE_DIR_ENV)
    if not log_dir:
        raise ValueError(
            f"start_trace: pass log_dir or set ${PROFILE_DIR_ENV}")
    os.makedirs(log_dir, exist_ok=True)
    jax.profiler.start_trace(log_dir)


def stop_trace() -> None:
    jax.profiler.stop_trace()


def annotate(name: str):
    """Host-side trace span (``jax.profiler.TraceAnnotation``): labels the
    time between dispatching ops, e.g. data loading. For device-side
    labels that survive compilation use :func:`named_scope`."""
    return jax.profiler.TraceAnnotation(name)


def step(step_num: int):
    """Label one training step in the trace
    (``jax.profiler.StepTraceAnnotation``) — TensorBoard's profile
    plugin groups device activity by these."""
    return jax.profiler.StepTraceAnnotation("train", step_num=step_num)
