"""Autotuning: Bayesian optimization of fusion threshold + cycle time.

Reference: ``horovod/common/parameter_manager.{h,cc}`` (tunable-parameter
stack scored by observed bytes/sec) driven by
``common/optim/bayesian_optimization.cc`` + ``common/optim/gaussian_process.cc``
(GP surrogate + expected-improvement acquisition, Eigen + L-BFGS). Same
architecture here in numpy: a GP with RBF kernel models score(params); each
tuning step scores the current configuration over a sample window, then
moves to the acquisition argmax (random-candidate search instead of L-BFGS —
two smooth dimensions need no quasi-Newton machinery).

Tuned knobs (the reference's full set, ``parameter_manager.h:35-85``):
  * fusion threshold, log2-bytes in [20, 28]  (1 MiB .. 256 MiB)
  * cycle time, ms in [1, 25]
  * hierarchical allreduce / hierarchical allgather / cache enabled —
    categorical, coordinate-descent (CategoricalParameter analogue)
Each knob honors a ``fixed=`` override when the user's env supplies an
explicit value (reference ``operations.cc:1005-1049``).

Enabled by ``HOROVOD_AUTOTUNE``; per-step CSV via ``HOROVOD_AUTOTUNE_LOG``
(reference ``operations.cc:1074-1078``). The coordinator tunes and the new
values ride the cycle reply to all ranks (reference ``SyncParams``,
``parameter_manager.cc:223``).

Straggler-aware scoring (no reference counterpart — closes ROADMAP item
5 over the r8/r9 observability planes): when ``straggler_weight`` > 0,
each cycle may carry the coordinator's observed negotiation slack (how
late the slowest rank's tick arrived beyond the pacing bound) and its
total excess recv-wait; a configuration's score becomes

    score = median(bytes/sec) / (1 + w*slack_frac + w*wait_frac)

with both penalty terms medians of the per-cycle fractions
``slack/seconds`` — a scale-free "fraction of the cycle spent waiting
on stragglers". Two configurations with identical throughput therefore
rank strictly by their slack, and the per-step log records every
component so the blend is auditable after the fact.
"""

from __future__ import annotations

import time
from typing import List, Optional, Tuple

import numpy as np


class GaussianProcess:
    """GP regression, RBF kernel + noise (reference
    ``optim/gaussian_process.{h,cc}``)."""

    def __init__(self, length_scale: float = 1.0, signal_var: float = 1.0,
                 noise_var: float = 1e-4):
        self.length_scale = length_scale
        self.signal_var = signal_var
        self.noise_var = noise_var
        self._x: Optional[np.ndarray] = None
        self._alpha: Optional[np.ndarray] = None
        self._chol: Optional[np.ndarray] = None

    def _kernel(self, a: np.ndarray, b: np.ndarray) -> np.ndarray:
        d2 = ((a[:, None, :] - b[None, :, :]) ** 2).sum(-1)
        return self.signal_var * np.exp(-0.5 * d2 / self.length_scale ** 2)

    def fit(self, x: np.ndarray, y: np.ndarray) -> None:
        self._x = np.asarray(x, dtype=np.float64)
        y = np.asarray(y, dtype=np.float64)
        k = self._kernel(self._x, self._x)
        k[np.diag_indices_from(k)] += self.noise_var
        self._chol = np.linalg.cholesky(k)
        self._alpha = np.linalg.solve(
            self._chol.T, np.linalg.solve(self._chol, y))

    def predict(self, x: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        x = np.asarray(x, dtype=np.float64)
        ks = self._kernel(x, self._x)
        mu = ks @ self._alpha
        v = np.linalg.solve(self._chol, ks.T)
        var = np.maximum(
            self.signal_var - (v ** 2).sum(0), 1e-12)
        return mu, np.sqrt(var)


def _norm_pdf(z):
    return np.exp(-0.5 * z ** 2) / np.sqrt(2 * np.pi)


def _norm_cdf(z):
    from math import erf

    return 0.5 * (1.0 + np.vectorize(erf)(z / np.sqrt(2.0)))


class BayesianOptimizer:
    """Expected-improvement Bayesian optimization over a box (reference
    ``optim/bayesian_optimization.{h,cc}``: EI acquisition, xi=0.01)."""

    def __init__(self, bounds: List[Tuple[float, float]], xi: float = 0.01,
                 seed: int = 0):
        self.bounds = np.asarray(bounds, dtype=np.float64)
        self.xi = xi
        self._rng = np.random.RandomState(seed)
        self._x: List[np.ndarray] = []
        self._y: List[float] = []
        self.gp = GaussianProcess(length_scale=0.25)

    def _normalize(self, x: np.ndarray) -> np.ndarray:
        lo, hi = self.bounds[:, 0], self.bounds[:, 1]
        return (x - lo) / (hi - lo)

    def add_sample(self, x, y: float) -> None:
        self._x.append(self._normalize(np.asarray(x, dtype=np.float64)))
        self._y.append(float(y))

    def suggest(self, n_candidates: int = 512) -> np.ndarray:
        lo, hi = self.bounds[:, 0], self.bounds[:, 1]
        if len(self._x) < 2:
            return lo + self._rng.rand(len(self.bounds)) * (hi - lo)
        x = np.stack(self._x)
        y = np.asarray(self._y)
        # Normalize scores for GP conditioning.
        y_mean, y_std = y.mean(), max(y.std(), 1e-9)
        self.gp.fit(x, (y - y_mean) / y_std)
        cand = self._rng.rand(n_candidates, len(self.bounds))
        mu, sigma = self.gp.predict(cand)
        best = ((y - y_mean) / y_std).max()
        imp = mu - best - self.xi
        z = imp / sigma
        ei = imp * _norm_cdf(z) + sigma * _norm_pdf(z)
        pick = cand[int(np.argmax(ei))]
        return lo + pick * (hi - lo)


# The reference's full categorical knob set (parameter_manager.h:66-85):
# hierarchical allreduce, hierarchical allgather, response-cache enable.
CATEGORICAL_KNOBS = ("hierarchical_allreduce", "hierarchical_allgather",
                     "cache_enabled")
# Continuous knobs, for ``fixed=`` spelling. ``ring_chunk`` (round 10) is
# the native ring's transfer-chunk size — per-rank pipelining granularity
# for the reduce-while-receive sink and the compress-ahead cursor
# (docs/wire-compression.md); it only joins the search when the caller
# provides an initial value (a job without the native ring has no chunk
# to tune).
CONTINUOUS_KNOBS = ("fusion_threshold", "cycle_time", "ring_chunk",
                    "bucket_bytes")
# log2-bytes box for the ring chunk: 64 KiB .. 2 MiB, bracketing the
# per-link-class defaults (config.RING_CHUNK_BYTES_BY_LINK).
RING_CHUNK_LOG2_BOUNDS = (16.0, 21.0)
# log2-bytes box for the backward-order gradient bucket (round 12,
# docs/overlap.md): 2 MiB .. 64 MiB, bracketing the 8 MiB default —
# small buckets launch reductions earlier (more overlap), big buckets
# amortize negotiation; the sweet spot is workload-dependent, which is
# why it joins the search.
BUCKET_BYTES_LOG2_BOUNDS = (21.0, 26.0)


class ParameterManager:
    """Scores the live configuration by observed throughput and proposes the
    next one (reference ``parameter_manager.cc:155-222`` Update/Tune).

    Joint parameter set at reference parity (``parameter_manager.h:35-85``):
    the continuous (fusion threshold, cycle time) pair under Bayesian
    optimization, plus the categorical knobs {hierarchical allreduce,
    hierarchical allgather, cache enabled} explored by coordinate descent —
    each unfixed knob is visited in turn, both values held for a few BO
    steps, the better locked in, over ``CATEGORY_SWEEPS`` passes.

    ``fixed`` mirrors the reference's per-knob ``fixed=`` override
    (``SetTensorFusionThresholdBytes(v, true)`` etc., set when the user's
    env provides an explicit value, ``operations.cc:1005-1049``): a fixed
    knob keeps its initial value and is excluded from the search.
    """

    WARMUP_SAMPLES = 3      # discarded after every parameter change
    SAMPLES_PER_STEP = 10   # scored cycles per configuration
    CATEGORY_STEPS = 3      # BO steps per categorical value visit
    CATEGORY_SWEEPS = 2     # full passes over the categorical knobs
    # Tuning FINISHES: after this many scored BO configurations (and the
    # categorical sweeps are done) the manager pins the best-seen
    # configuration and stops — the reference's BAYES_OPT_MAX_SAMPLES=20 +
    # SetAutoTuning(false) + BestValue() contract
    # (parameter_manager.cc:30,210,473-475). Without termination the
    # search pays exploration cost for the whole job; with noisy scores
    # (timeshared CPUs) it can wander indefinitely.
    BO_MAX_STEPS = 20

    def __init__(self, fusion_threshold: int, cycle_time_ms: float,
                 log_path: Optional[str] = None, seed: int = 0,
                 categoricals: Optional[dict] = None,
                 fixed=frozenset(),
                 tune_hierarchical: bool = False,
                 hierarchical: bool = False,
                 straggler_weight: float = 0.0,
                 ring_chunk_bytes: Optional[int] = None,
                 bucket_bytes: Optional[int] = None,
                 overlap_weight: float = 0.0):
        # Legacy spelling (round-3 callers/tests): hierarchical allreduce
        # only, tuned iff tune_hierarchical.
        if categoricals is None:
            categoricals = {"hierarchical_allreduce": hierarchical}
            if not tune_hierarchical:
                fixed = set(fixed) | {"hierarchical_allreduce"}
        self.fixed = frozenset(fixed)
        # Ring transfer chunk joins the BO box as a third dimension only
        # when the caller supplies an initial value AND the knob isn't
        # pinned — jobs without the native ring keep the original 2-D
        # search (and its exact behavior) bit for bit.
        self._tune_chunk = (ring_chunk_bytes is not None
                            and "ring_chunk" not in self.fixed)
        # Gradient-bucket size (round 12) joins on the same terms: only
        # when the caller supplies an initial value and the env didn't
        # pin it — jobs without the bucket scheduler keep their exact
        # search box.
        self._tune_bucket = (bucket_bytes is not None
                             and "bucket_bytes" not in self.fixed)
        bounds = [(20.0, 28.0), (1.0, 25.0)]  # (log2 fusion bytes, cycle ms)
        if self._tune_chunk:
            bounds.append(RING_CHUNK_LOG2_BOUNDS)  # log2 chunk bytes
        if self._tune_bucket:
            bounds.append(BUCKET_BYTES_LOG2_BOUNDS)  # log2 bucket bytes
        self._bo = BayesianOptimizer(bounds, seed=seed)
        # Exact pinned values for fixed knobs: a log2/2** round trip would
        # drift a non-power-of-two user threshold.
        self._initial_threshold = int(fusion_threshold)
        self._initial_cycle_ms = float(cycle_time_ms)
        self.fusion_threshold = int(fusion_threshold)
        self.cycle_time_ms = float(cycle_time_ms)
        self.ring_chunk_bytes = (int(ring_chunk_bytes)
                                 if ring_chunk_bytes is not None else None)
        self.best_ring_chunk_bytes = self.ring_chunk_bytes
        self.bucket_bytes = (int(bucket_bytes)
                             if bucket_bytes is not None else None)
        self.best_bucket_bytes = self.bucket_bytes
        self.categoricals = {k: bool(v) for k, v in categoricals.items()}
        self._warmup_left = self.WARMUP_SAMPLES
        self._scores: List[float] = []
        # Per-cycle straggler cost as a fraction of the cycle: slack
        # (worst rank's lateness) and excess recv-wait, both reset with
        # the score window on every parameter change.
        self.straggler_weight = max(0.0, float(straggler_weight))
        self._slack_fracs: List[float] = []
        self._wait_fracs: List[float] = []
        # Overlap-aware scoring (round 16, docs/overlap.md): when the
        # bucket scheduler publishes a measured backward/comm overlap
        # efficiency, the blend rewards it — the tuner then optimizes
        # step time, not just wire bandwidth. 0 (the default, and every
        # pre-r16 caller) keeps the objective bit-identical.
        self.overlap_weight = max(0.0, float(overlap_weight))
        self._overlaps: List[float] = []
        self._bo_steps = 0
        self._completed = False
        self._log_path = log_path
        self._log_header_due = log_path is not None
        self._best_score = -np.inf
        # Components of the most recently scored configuration (and of
        # the best-seen one) — the hvd_autotune_* gauges and the doctor's
        # wandering-search rule read these.
        self.last_objective: Optional[dict] = None
        self.best_objective: Optional[dict] = None
        self.best_fusion_threshold = self.fusion_threshold
        self.best_cycle_time_ms = self.cycle_time_ms
        self.best_categoricals = dict(self.categoricals)
        # Coordinate-descent plan over the unfixed categoricals: per knob,
        # hold the initial value CATEGORY_STEPS BO steps, then the flipped
        # value, lock the better, move on; CATEGORY_SWEEPS full passes.
        self._cat_order = [k for k in self.categoricals
                           if k not in self.fixed]
        self._cat_pos = 0            # knob index within the sweep
        self._cat_sweep = 0
        self._cat_phase = 0          # 0 = initial value, 1 = flipped
        self._cat_steps = 0
        self._cat_phase_scores = [-np.inf, -np.inf]
        self._cats_converged = not self._cat_order

    @property
    def tunable(self) -> bool:
        """False when every knob is pinned or settled — record()
        short-circuits, so a fully-pinned (or fully-converged) job never
        pays the per-step GP Cholesky for values it would discard."""
        if self._completed:
            return False
        cats_active = bool(self._cat_order) and not self._cats_converged
        continuous_active = self._tune_chunk or self._tune_bucket or not (
            {"fusion_threshold", "cycle_time"} <= self.fixed)
        return cats_active or continuous_active

    @property
    def hierarchical(self) -> bool:  # legacy accessor
        return self.categoricals.get("hierarchical_allreduce", False)

    def _advance_categoricals(self, score: float) -> None:
        if self._cats_converged:
            return
        knob = self._cat_order[self._cat_pos]
        self._cat_phase_scores[self._cat_phase] = max(
            self._cat_phase_scores[self._cat_phase], score)
        self._cat_steps += 1
        if self._cat_steps < self.CATEGORY_STEPS:
            return
        self._cat_steps = 0
        if self._cat_phase == 0:
            self._cat_phase = 1
            self.categoricals[knob] = not self.categoricals[knob]
            return
        # Both values visited: lock the better and move to the next knob.
        keep_flipped = self._cat_phase_scores[1] > self._cat_phase_scores[0]
        if not keep_flipped:
            self.categoricals[knob] = not self.categoricals[knob]
        self._cat_phase = 0
        self._cat_phase_scores = [-np.inf, -np.inf]
        self._cat_pos += 1
        if self._cat_pos >= len(self._cat_order):
            self._cat_pos = 0
            self._cat_sweep += 1
            if self._cat_sweep >= self.CATEGORY_SWEEPS:
                self._cats_converged = True

    @staticmethod
    def blend(throughput: float, slack_frac: float, wait_frac: float,
              weight: float, overlap: Optional[float] = None,
              overlap_weight: float = 0.0) -> float:
        """The straggler-aware objective: throughput discounted by the
        fraction of each cycle spent waiting on stragglers. Strictly
        decreasing in both penalty fractions at fixed throughput, so two
        configurations with identical bytes/sec rank by their slack.
        When an ``overlap`` sample exists (the bucket scheduler's measured
        overlap efficiency in [0, 1]), the score is additionally
        multiplied by ``1 + overlap_weight * overlap`` — strictly
        increasing in overlap, and a no-op (bit-identical) when no sample
        arrived."""
        score = throughput / (1.0 + weight * max(0.0, slack_frac)
                              + weight * max(0.0, wait_frac))
        if overlap is not None:
            score *= 1.0 + overlap_weight * max(0.0, min(1.0, overlap))
        return score

    def record(self, nbytes: int, seconds: float,
               slack_seconds: float = 0.0,
               recv_wait_seconds: float = 0.0,
               overlap: Optional[float] = None
               ) -> Optional[Tuple[int, float, dict]]:
        """Feed one cycle's totals; returns new (fusion_threshold, cycle_ms,
        categoricals) when the manager moves to a new configuration, else
        None. ``slack_seconds``/``recv_wait_seconds`` are the coordinator's
        per-cycle straggler observations (worst rank's tick lateness /
        total excess tick wait); both default to 0, which reduces the
        objective to the reference's pure bytes/sec. ``overlap`` is the
        bucket scheduler's most recent measured overlap efficiency, when
        one exists — sampled per window alongside the throughput."""
        if nbytes <= 0 or seconds <= 0 or not self.tunable:
            return None
        if self._warmup_left > 0:
            self._warmup_left -= 1
            return None
        self._scores.append(nbytes / seconds)
        if self.straggler_weight > 0:
            self._slack_fracs.append(max(0.0, slack_seconds) / seconds)
            self._wait_fracs.append(max(0.0, recv_wait_seconds) / seconds)
        if self.overlap_weight > 0 and overlap is not None:
            self._overlaps.append(max(0.0, min(1.0, float(overlap))))
        if len(self._scores) < self.SAMPLES_PER_STEP:
            return None

        # MEDIAN of the per-cycle rates (reference sorts scores_ and takes
        # scores_[SAMPLES/2], parameter_manager.cc:176-180): a mean lets
        # one contended cycle on a timeshared host poison the whole
        # configuration's score. The straggler penalties get the same
        # median treatment — one contended cycle must not smear an
        # otherwise clean configuration.
        throughput = float(np.median(self._scores))  # bytes/sec
        w = self.straggler_weight
        slack_frac = (float(np.median(self._slack_fracs))
                      if self._slack_fracs else 0.0)
        wait_frac = (float(np.median(self._wait_fracs))
                     if self._wait_fracs else 0.0)
        overlap_med = (float(np.median(self._overlaps))
                       if self._overlaps else None)
        score = self.blend(throughput, slack_frac, wait_frac, w,
                           overlap=overlap_med,
                           overlap_weight=self.overlap_weight)
        self.last_objective = {
            "throughput_bytes_per_sec": throughput,
            "slack_penalty": w * slack_frac,
            "recv_wait_penalty": w * wait_frac,
            "overlap_bonus": (self.overlap_weight * overlap_med
                              if overlap_med is not None else 0.0),
            "score": score,
        }
        params = [np.log2(self.fusion_threshold), self.cycle_time_ms]
        if self._tune_chunk:
            params.append(np.log2(self.ring_chunk_bytes))
        if self._tune_bucket:
            params.append(np.log2(self.bucket_bytes))
        self._bo.add_sample(tuple(params), score)
        if score > self._best_score:
            self._best_score = score
            self.best_fusion_threshold = self.fusion_threshold
            self.best_cycle_time_ms = self.cycle_time_ms
            self.best_ring_chunk_bytes = self.ring_chunk_bytes
            self.best_bucket_bytes = self.bucket_bytes
            self.best_categoricals = dict(self.categoricals)
            self.best_objective = dict(self.last_objective)
        if self._log_path:
            cat_items = sorted(self.categoricals.items())
            chunk_col = f",{self.ring_chunk_bytes}" if self._tune_chunk \
                else ""
            bucket_col = f",{self.bucket_bytes}" if self._tune_bucket \
                else ""
            # The overlap column joins only when the term is live; it
            # sits BEFORE the throughput/penalty/score tail so the
            # score-is-last-column contract (r3) survives.
            ob = self.last_objective["overlap_bonus"]
            overlap_col = f",{ob:.6f}" if self.overlap_weight > 0 else ""
            with open(self._log_path, "a") as f:
                if self._log_header_due:
                    # Self-describing: the column set varies with the
                    # categorical knobs (and the ring-chunk knob), so
                    # name them — but only at the top of a fresh file
                    # (restarts append data rows).
                    if f.tell() == 0:
                        chunk_hdr = (",ring_chunk_bytes"
                                     if self._tune_chunk else "")
                        chunk_hdr += (",bucket_bytes"
                                      if self._tune_bucket else "")
                        overlap_hdr = (",overlap_bonus"
                                       if self.overlap_weight > 0 else "")
                        f.write("time,fusion_threshold,cycle_time_ms"
                                + chunk_hdr + ","
                                + ",".join(k for k, _ in cat_items)
                                + overlap_hdr
                                + ",throughput_bytes_per_sec,"
                                "slack_penalty,recv_wait_penalty,"
                                "score_bytes_per_sec\n")
                    self._log_header_due = False
                cats = ",".join(str(int(v)) for _, v in cat_items)
                # Log-row wall stamp, read next to other logs — not
                # duration math. hvdlint: disable=HVD004
                f.write(f"{time.time():.3f},{self.fusion_threshold},"
                        f"{self.cycle_time_ms:.3f}{chunk_col}{bucket_col},"
                        f"{cats}{overlap_col},"
                        f"{throughput:.1f},{w * slack_frac:.6f},"
                        f"{w * wait_frac:.6f},{score:.1f}\n")

        self._advance_categoricals(score)

        self._bo_steps += 1
        if self._cats_converged and self._bo_steps >= self.BO_MAX_STEPS:
            # Tuning complete: pin the best-seen configuration and stop
            # (reference SetAutoTuning(false) + BestValue(),
            # parameter_manager.cc:210,113-129). The returned tuple is
            # the final config the caller pushes down.
            self._completed = True
            self.fusion_threshold = self.best_fusion_threshold
            self.cycle_time_ms = self.best_cycle_time_ms
            self.ring_chunk_bytes = self.best_ring_chunk_bytes
            self.bucket_bytes = self.best_bucket_bytes
            self.categoricals = dict(self.best_categoricals)
            if self._log_path:
                with open(self._log_path, "a") as f:
                    f.write(f"# tuning complete: pinned "
                            f"{self.fusion_threshold},"
                            f"{self.cycle_time_ms:.3f} "
                            f"(best score {self._best_score:.1f})\n")
            return (self.fusion_threshold, self.cycle_time_ms,
                    dict(self.categoricals))

        nxt = self._bo.suggest()
        # fixed= continuous knobs keep their EXACT initial value (reference
        # TunableParameter::SetValue(value, fixed=true) semantics).
        self.fusion_threshold = (
            self._initial_threshold if "fusion_threshold" in self.fixed
            else int(2 ** nxt[0]))
        self.cycle_time_ms = (
            self._initial_cycle_ms if "cycle_time" in self.fixed
            else float(nxt[1]))
        idx = 2
        if self._tune_chunk:
            self.ring_chunk_bytes = int(2 ** nxt[idx])
            idx += 1
        if self._tune_bucket:
            self.bucket_bytes = int(2 ** nxt[idx])
        self._scores = []
        self._slack_fracs = []
        self._wait_fracs = []
        self._overlaps = []
        self._warmup_left = self.WARMUP_SAMPLES
        return (self.fusion_threshold, self.cycle_time_ms,
                dict(self.categoricals))

    @property
    def steps_scored(self) -> int:
        """Scored BO configurations so far (the gauge publisher keys its
        "something changed" check on this)."""
        return self._bo_steps

    def state(self) -> dict:
        """JSON-clean tuner state for the ``hvd_autotune_*`` gauges and
        the doctor's wandering/stalled-search rules."""
        return {
            "active": bool(self.tunable),
            "steps_completed": self._bo_steps,
            "steps_remaining": max(0, self.BO_MAX_STEPS - self._bo_steps),
            "fusion_threshold": int(self.fusion_threshold),
            "cycle_time_ms": float(self.cycle_time_ms),
            "best_fusion_threshold": int(self.best_fusion_threshold),
            "best_cycle_time_ms": float(self.best_cycle_time_ms),
            "ring_chunk_bytes": (int(self.ring_chunk_bytes)
                                 if self.ring_chunk_bytes is not None
                                 else None),
            "best_ring_chunk_bytes": (int(self.best_ring_chunk_bytes)
                                      if self.best_ring_chunk_bytes
                                      is not None else None),
            "bucket_bytes": (int(self.bucket_bytes)
                             if self.bucket_bytes is not None else None),
            "best_bucket_bytes": (int(self.best_bucket_bytes)
                                  if self.best_bucket_bytes is not None
                                  else None),
            "straggler_weight": self.straggler_weight,
            "overlap_weight": self.overlap_weight,
            "last_objective": self.last_objective,
            "best_objective": self.best_objective,
        }
