"""Autotuning: Bayesian optimization of fusion threshold + cycle time.

Reference: ``horovod/common/parameter_manager.{h,cc}`` (tunable-parameter
stack scored by observed bytes/sec) driven by
``common/optim/bayesian_optimization.cc`` + ``common/optim/gaussian_process.cc``
(GP surrogate + expected-improvement acquisition, Eigen + L-BFGS). Same
architecture here in numpy: a GP with RBF kernel models score(params); each
tuning step scores the current configuration over a sample window, then
moves to the acquisition argmax (random-candidate search instead of L-BFGS —
two smooth dimensions need no quasi-Newton machinery).

Tuned knobs (the eager tier's two continuous parameters, as in the
reference's joint-Bayesian group, ``parameter_manager.h:35-43``):
  * fusion threshold, log2-bytes in [20, 28]  (1 MiB .. 256 MiB)
  * cycle time, ms in [1, 25]

Enabled by ``HOROVOD_AUTOTUNE``; per-step CSV via ``HOROVOD_AUTOTUNE_LOG``
(reference ``operations.cc:1074-1078``). The coordinator tunes and the new
values ride the cycle reply to all ranks (reference ``SyncParams``,
``parameter_manager.cc:223``).
"""

from __future__ import annotations

import time
from typing import List, Optional, Tuple

import numpy as np


class GaussianProcess:
    """GP regression, RBF kernel + noise (reference
    ``optim/gaussian_process.{h,cc}``)."""

    def __init__(self, length_scale: float = 1.0, signal_var: float = 1.0,
                 noise_var: float = 1e-4):
        self.length_scale = length_scale
        self.signal_var = signal_var
        self.noise_var = noise_var
        self._x: Optional[np.ndarray] = None
        self._alpha: Optional[np.ndarray] = None
        self._chol: Optional[np.ndarray] = None

    def _kernel(self, a: np.ndarray, b: np.ndarray) -> np.ndarray:
        d2 = ((a[:, None, :] - b[None, :, :]) ** 2).sum(-1)
        return self.signal_var * np.exp(-0.5 * d2 / self.length_scale ** 2)

    def fit(self, x: np.ndarray, y: np.ndarray) -> None:
        self._x = np.asarray(x, dtype=np.float64)
        y = np.asarray(y, dtype=np.float64)
        k = self._kernel(self._x, self._x)
        k[np.diag_indices_from(k)] += self.noise_var
        self._chol = np.linalg.cholesky(k)
        self._alpha = np.linalg.solve(
            self._chol.T, np.linalg.solve(self._chol, y))

    def predict(self, x: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        x = np.asarray(x, dtype=np.float64)
        ks = self._kernel(x, self._x)
        mu = ks @ self._alpha
        v = np.linalg.solve(self._chol, ks.T)
        var = np.maximum(
            self.signal_var - (v ** 2).sum(0), 1e-12)
        return mu, np.sqrt(var)


def _norm_pdf(z):
    return np.exp(-0.5 * z ** 2) / np.sqrt(2 * np.pi)


def _norm_cdf(z):
    from math import erf

    return 0.5 * (1.0 + np.vectorize(erf)(z / np.sqrt(2.0)))


class BayesianOptimizer:
    """Expected-improvement Bayesian optimization over a box (reference
    ``optim/bayesian_optimization.{h,cc}``: EI acquisition, xi=0.01)."""

    def __init__(self, bounds: List[Tuple[float, float]], xi: float = 0.01,
                 seed: int = 0):
        self.bounds = np.asarray(bounds, dtype=np.float64)
        self.xi = xi
        self._rng = np.random.RandomState(seed)
        self._x: List[np.ndarray] = []
        self._y: List[float] = []
        self.gp = GaussianProcess(length_scale=0.25)

    def _normalize(self, x: np.ndarray) -> np.ndarray:
        lo, hi = self.bounds[:, 0], self.bounds[:, 1]
        return (x - lo) / (hi - lo)

    def add_sample(self, x, y: float) -> None:
        self._x.append(self._normalize(np.asarray(x, dtype=np.float64)))
        self._y.append(float(y))

    def suggest(self, n_candidates: int = 512) -> np.ndarray:
        lo, hi = self.bounds[:, 0], self.bounds[:, 1]
        if len(self._x) < 2:
            return lo + self._rng.rand(len(self.bounds)) * (hi - lo)
        x = np.stack(self._x)
        y = np.asarray(self._y)
        # Normalize scores for GP conditioning.
        y_mean, y_std = y.mean(), max(y.std(), 1e-9)
        self.gp.fit(x, (y - y_mean) / y_std)
        cand = self._rng.rand(n_candidates, len(self.bounds))
        mu, sigma = self.gp.predict(cand)
        best = ((y - y_mean) / y_std).max()
        imp = mu - best - self.xi
        z = imp / sigma
        ei = imp * _norm_cdf(z) + sigma * _norm_pdf(z)
        pick = cand[int(np.argmax(ei))]
        return lo + pick * (hi - lo)


class ParameterManager:
    """Scores the live configuration by observed throughput and proposes the
    next one (reference ``parameter_manager.cc:155-222`` Update/Tune).

    Besides the joint-Bayesian continuous pair, optionally tunes
    hierarchical allreduce on/off — the reference's categorical dimension
    (``parameter_manager.h:35-43`` CategoricalParameterChain): each category
    is explored for a few BO steps over two sweeps, then the better one is
    locked in while the continuous search continues."""

    WARMUP_SAMPLES = 3      # discarded after every parameter change
    SAMPLES_PER_STEP = 10   # scored cycles per configuration
    CATEGORY_STEPS = 3      # BO steps per category visit
    CATEGORY_SWEEPS = 2     # full passes over both categories

    def __init__(self, fusion_threshold: int, cycle_time_ms: float,
                 log_path: Optional[str] = None, seed: int = 0,
                 tune_hierarchical: bool = False,
                 hierarchical: bool = False):
        # (log2 fusion bytes, cycle ms)
        self._bo = BayesianOptimizer([(20.0, 28.0), (1.0, 25.0)], seed=seed)
        self.fusion_threshold = int(fusion_threshold)
        self.cycle_time_ms = float(cycle_time_ms)
        self.hierarchical = bool(hierarchical)
        self._warmup_left = self.WARMUP_SAMPLES
        self._bytes = 0
        self._seconds = 0.0
        self._samples = 0
        self._log_path = log_path
        self._best_score = -np.inf
        self.best_fusion_threshold = self.fusion_threshold
        self.best_cycle_time_ms = self.cycle_time_ms
        self._cat_fixed = not tune_hierarchical
        self._cat_scores = {False: -np.inf, True: -np.inf}
        self._cat_steps = 0
        self._cat_visits = 0

    def record(self, nbytes: int,
               seconds: float) -> Optional[Tuple[int, float, bool]]:
        """Feed one cycle's totals; returns new (fusion_threshold, cycle_ms,
        hierarchical) when the manager moves to a new configuration, else
        None."""
        if nbytes <= 0 or seconds <= 0:
            return None
        if self._warmup_left > 0:
            self._warmup_left -= 1
            return None
        self._bytes += nbytes
        self._seconds += seconds
        self._samples += 1
        if self._samples < self.SAMPLES_PER_STEP:
            return None

        score = self._bytes / self._seconds  # bytes/sec, higher is better
        params = (np.log2(self.fusion_threshold), self.cycle_time_ms)
        self._bo.add_sample(params, score)
        if score > self._best_score:
            self._best_score = score
            self.best_fusion_threshold = self.fusion_threshold
            self.best_cycle_time_ms = self.cycle_time_ms
        self._cat_scores[self.hierarchical] = max(
            self._cat_scores[self.hierarchical], score)
        if self._log_path:
            with open(self._log_path, "a") as f:
                f.write(f"{time.time():.3f},{self.fusion_threshold},"
                        f"{self.cycle_time_ms:.3f},"
                        f"{int(self.hierarchical)},{score:.1f}\n")

        if not self._cat_fixed:
            self._cat_steps += 1
            if self._cat_steps >= self.CATEGORY_STEPS:
                self._cat_steps = 0
                self._cat_visits += 1
                if self._cat_visits >= 2 * self.CATEGORY_SWEEPS:
                    self._cat_fixed = True
                    self.hierarchical = bool(
                        self._cat_scores[True] > self._cat_scores[False])
                else:
                    self.hierarchical = not self.hierarchical

        nxt = self._bo.suggest()
        self.fusion_threshold = int(2 ** nxt[0])
        self.cycle_time_ms = float(nxt[1])
        self._bytes = 0
        self._seconds = 0.0
        self._samples = 0
        self._warmup_left = self.WARMUP_SAMPLES
        return self.fusion_threshold, self.cycle_time_ms, self.hierarchical
