"""Async-operation handles.

Reference: ``horovod/torch/handle_manager.{h,cc}`` — an int-keyed map from
handle to completion Status, filled in by the background thread's callback and
joined by ``synchronize()``. Here a Handle owns a ``threading.Event`` plus the
result; the manager keeps results alive until waited (the reference pins
tensors in ``_handle_map`` for the same reason, ``torch/mpi_ops.py:54``).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional


class Handle:
    __slots__ = ("_id", "_event", "_result", "_error", "_manager",
                 "tensor_sizes")

    def __init__(self, handle_id: int, manager: "HandleManager"):
        self._id = handle_id
        self._event = threading.Event()
        self._result: Any = None
        self._error: Optional[BaseException] = None
        self._manager = manager
        # For allgather handles: every rank's first-dim size from the
        # negotiated Response (reference Response.tensor_sizes, carried to
        # the adapter via TensorShape in torch/adapter_v2.cc:91-102) — so
        # autograd backward can locate this rank's slice WITHOUT a second
        # sizes-allgather. None for other ops / size-1 fast paths.
        self.tensor_sizes: Optional[list] = None

    @property
    def id(self) -> int:
        return self._id

    def set_result(self, value: Any) -> None:
        self._result = value
        self._event.set()

    def set_error(self, exc: BaseException) -> None:
        self._error = exc
        self._event.set()

    def done(self) -> bool:
        return self._event.is_set()

    def wait(self, timeout: Optional[float] = None) -> Any:
        if not self._event.wait(timeout):
            raise TimeoutError(f"handle {self._id} not complete after {timeout}s")
        self._manager.clear(self._id)
        if self._error is not None:
            raise self._error
        return self._result


class HandleManager:
    """Allocates handles and retains them until cleared
    (reference ``torch/handle_manager.h:31-42``)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._next = 0
        self._live: Dict[int, Handle] = {}

    def allocate(self) -> Handle:
        with self._lock:
            hid = self._next
            self._next += 1
            h = Handle(hid, self)
            self._live[hid] = h
            return h

    def completed(self, value: Any) -> Handle:
        """A handle that is already resolved (size-1 fast path)."""
        h = self.allocate()
        h.set_result(value)
        return h

    def clear(self, handle_id: int) -> None:
        with self._lock:
            self._live.pop(handle_id, None)

    def outstanding(self) -> int:
        with self._lock:
            return sum(1 for h in self._live.values() if not h.done())
