"""Bounded retries with exponential backoff + jitter for wedgeable init.

Round 6 lost an entire session to an un-retried, un-bounded TPU backend
init (``artifacts/tpu_outage_r6.md``): every attempt hung inside native
init until an external watchdog killed it. The init path must never be an
infinite hang — it either succeeds, fails after a bounded number of
attempts, or (opt-in) degrades to a CPU dryrun backend that logs loudly.

Knobs (read by :func:`init_retry_env`):

* ``HOROVOD_TPU_INIT_RETRIES`` — max attempts (default 3).
* ``HOROVOD_TPU_INIT_BACKOFF`` — base backoff seconds (default 1.0); the
  delay doubles per attempt, capped at 30s, with ±25% seeded jitter so a
  whole pod slice doesn't re-dial the coordinator in lockstep.
* ``HOROVOD_TPU_INIT_TIMEOUT`` — per-attempt deadline seconds for
  :func:`run_with_deadline` (default 300s — bounded by default, because
  the r6 outage hung rather than raised; 0 disables).
"""

from __future__ import annotations

import random
import threading
import time
from typing import Any, Callable, Optional, Sequence, Tuple

from . import hvd_logging as logging
from .config import _env_float, _env_int
from .. import metrics

BACKOFF_MAX_SECONDS = 30.0

_m = None


def _retry_metrics():
    global _m
    if _m is None:
        from types import SimpleNamespace

        _m = SimpleNamespace(
            failures=metrics.counter(
                "hvd_retry_attempt_failures_total",
                "Failed attempts inside retry_call (init hardening)."),
            backoff=metrics.counter(
                "hvd_retry_backoff_seconds_total",
                "Total seconds slept backing off between retry attempts."),
            giveups=metrics.counter(
                "hvd_retry_giveups_total",
                "retry_call budgets exhausted (RetryError raised)."))
    return _m


class RetryError(RuntimeError):
    """All attempts failed; ``last`` is the final attempt's exception."""

    def __init__(self, describe: str, attempts: int, last: BaseException):
        self.attempts = attempts
        self.last = last
        super().__init__(
            f"{describe} failed after {attempts} attempt(s): {last}")


class DeadlineExceeded(RuntimeError):
    """The bounded call did not finish within its per-attempt deadline."""


def init_retry_env() -> Tuple[int, float]:
    """(max attempts, base backoff seconds) for the init path."""
    attempts = max(1, _env_int("HOROVOD_TPU_INIT_RETRIES", 3))
    backoff = max(0.0, _env_float("HOROVOD_TPU_INIT_BACKOFF", 1.0))
    return attempts, backoff


def retry_call(fn: Callable[[], Any], *, attempts: int = 3,
               backoff: float = 1.0,
               backoff_max: float = BACKOFF_MAX_SECONDS,
               jitter: float = 0.25, seed: Optional[int] = None,
               describe: str = "operation",
               retry_on: Sequence[type] = (Exception,),
               sleep: Callable[[float], None] = time.sleep) -> Any:
    """Call ``fn`` up to ``attempts`` times with exponential backoff.

    Jitter is drawn from ``random.Random(seed)`` — pass the rank as the
    seed and the delays are deterministic per process yet decorrelated
    across the job. Raises :class:`RetryError` (chained to the last
    failure) when every attempt failed."""
    rng = random.Random(seed)
    retry_on = tuple(retry_on)
    last: Optional[BaseException] = None
    for attempt in range(1, attempts + 1):
        try:
            return fn()
        except retry_on as exc:
            last = exc
            if metrics.on():
                _retry_metrics().failures.inc()
                metrics.record_event("retry", what=describe, attempt=attempt,
                                     attempts=attempts,
                                     error=str(exc)[:200])
            if attempt == attempts:
                break
            delay = min(backoff_max, backoff * (2.0 ** (attempt - 1)))
            if jitter:
                delay *= 1.0 + jitter * rng.uniform(-1.0, 1.0)
            logging.warning(
                "%s failed (attempt %d/%d): %s; retrying in %.1fs",
                describe, attempt, attempts, exc, max(0.0, delay))
            if delay > 0:
                if metrics.on():
                    _retry_metrics().backoff.inc(delay)
                sleep(delay)
    if metrics.on():
        _retry_metrics().giveups.inc()
        metrics.record_event("retry_giveup", what=describe,
                             attempts=attempts, error=str(last)[:200])
    raise RetryError(describe, attempts, last) from last


def run_with_deadline(fn: Callable[[], Any], seconds: float,
                      describe: str = "operation") -> Any:
    """Run ``fn`` on a worker thread and give up after ``seconds``.

    A wedged native call can't be cancelled from Python — on timeout the
    daemon thread is abandoned (and says so in the log) while the caller
    gets a clean :class:`DeadlineExceeded` to retry or fail on, instead of
    hanging the whole rank."""
    if seconds <= 0:
        return fn()
    result: list = []
    error: list = []

    def _body():
        try:
            result.append(fn())
        except BaseException as exc:  # re-raised on the caller thread
            error.append(exc)

    t = threading.Thread(target=_body, name="hvd-deadline-call", daemon=True)
    t.start()
    t.join(seconds)
    if t.is_alive():
        logging.error(
            "%s did not finish within %.1fs; abandoning the wedged attempt "
            "on a daemon thread", describe, seconds)
        raise DeadlineExceeded(
            f"{describe} did not finish within {seconds}s")
    if error:
        raise error[0]
    return result[0] if result else None
