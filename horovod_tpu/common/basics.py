"""Process-global framework state and lifecycle (init/shutdown).

The reference keeps a singleton ``HorovodGlobalState`` owning the background
coordinator thread (``horovod/common/operations.cc:90``, ``global_state.h:44``)
and exposes a C ABI ``horovod_init/rank/size/...`` consumed through ctypes
(``horovod/common/basics.py``). The TPU-native rebuild keeps the same lifecycle
surface, but the heavy machinery differs by tier:

* **SPMD tier** (single controller process per host, jit over the device
  mesh): no negotiation is needed — XLA's SPMD model already guarantees every
  device executes the same collectives in the same order, which is exactly the
  invariant the reference's negotiation protocol establishes dynamically
  (SURVEY.md §5 "Distributed communication backend"). Collectives lower
  straight to XLA ops over ICI.
* **Eager multi-process tier** (Horovod parity for host tensors / torch): a
  background controller with tensor fusion, response cache, timeline and stall
  detection, speaking a TCP control plane instead of MPI.
"""

from __future__ import annotations

import atexit
import os
import threading
from typing import Optional, Sequence

from . import config as config_mod
from . import hvd_logging as logging
from . import retry
from .config import Config
from .topology import Topology, detect
from .. import metrics

_m = None


def _init_metrics():
    global _m
    if _m is None:
        from types import SimpleNamespace

        _m = SimpleNamespace(
            cpu_fallback=metrics.counter(
                "hvd_init_cpu_fallback_total",
                "HOROVOD_TPU_INIT_FALLBACK_CPU degradations to the CPU "
                "dryrun backend."))
    return _m


class HorovodTpuState:
    """Python analogue of the reference ``HorovodGlobalState``
    (``horovod/common/global_state.h:44-154``): one per process, created by
    ``init()``, torn down by ``shutdown()``/interpreter exit."""

    def __init__(self, config: Config, topology: Topology):
        self.config = config
        self.topology = topology
        self.initialized = True
        self.shut_down = False
        self.mutex = threading.RLock()
        # Lazily-created subsystems (eager tier only).
        self.controller = None  # control plane + eager collectives
        self.timeline = None
        self.parameter_manager = None
        self.metrics_exporter = None  # per-rank Prometheus endpoint

    def close(self) -> None:
        with self.mutex:
            if self.shut_down:
                return
            self.shut_down = True
            self.initialized = False
            if self.controller is not None:
                if getattr(self.controller, "_failure", None) is not None:
                    # Unclean shutdown: the job died but nothing dumped yet
                    # (or the dump is stale) — rewrite the postmortem with
                    # the full ring as of teardown.
                    metrics.dump_flight_recorder("unclean_shutdown")
                self.controller.shutdown()
                self.controller = None
            if self.timeline is not None:
                self.timeline.close()
                self.timeline = None
            if self.metrics_exporter is not None:
                self.metrics_exporter.close()
                self.metrics_exporter = None


_state: Optional[HorovodTpuState] = None
_state_lock = threading.Lock()


def _preflight_coordinator(coord: str, attempts: int = 3,
                           timeout: float = 2.0) -> None:
    """Cheap TCP health probe of the distributed coordinator before the
    expensive ``jax.distributed.initialize``: a dead/unroutable coordinator
    is reported in seconds with a precise message instead of surfacing as a
    wedged init. Non-fatal — the retried initialize is the authority (the
    coordinator may legitimately come up a moment later)."""
    import socket

    from .wire import parse_addr

    try:
        host, port_no = parse_addr(coord)
    except ValueError:
        return  # let initialize() produce its own error for a bad address

    def _dial():
        socket.create_connection((host, port_no), timeout=timeout).close()

    try:
        retry.retry_call(_dial, attempts=attempts, backoff=0.2, jitter=0.0,
                         describe=f"preflight probe of coordinator {coord}",
                         retry_on=(OSError,))
    except retry.RetryError as exc:
        logging.warning(
            "preflight: distributed coordinator %s not reachable yet (%s); "
            "proceeding — jax.distributed.initialize will retry/timeout",
            coord, exc.last)


def _maybe_init_jax_distributed() -> None:
    """Join the JAX distributed runtime when the launcher requested SPMD
    multi-host mode (``horovodrun --spmd``).

    This is the TPU-native analogue of the reference's multi-node data plane
    (NCCL ring over the cluster, ``horovod/common/ops/nccl_operations.cc``):
    after ``jax.distributed.initialize`` every process sees the *global*
    device set, ``hvd.parallel.mesh()`` spans all hosts, and collectives
    inside ``jit`` ride ICI within a slice and DCN across slices — no
    per-tensor controller needed (the SPMD program itself is the negotiation,
    SURVEY.md §5).

    Hardened (round-6 outage, artifacts/tpu_outage_r6.md): preflight-probed
    and retried with exponential backoff under ``HOROVOD_TPU_INIT_RETRIES``/
    ``_BACKOFF`` instead of wedging on the first dead coordinator."""
    coord = config_mod.spmd_coordinator()
    if not coord:
        return
    rank = config_mod.env_rank()
    size = config_mod.env_size()
    if rank is None or size is None:
        raise RuntimeError(
            "HOROVOD_SPMD_COORDINATOR is set but HOROVOD_RANK/HOROVOD_SIZE "
            "are not; launch through horovodrun --spmd (or export all three)")
    import jax

    try:
        already = jax.distributed.is_initialized()
    except AttributeError:  # older jax without the public probe
        from jax._src import distributed as _dist

        already = _dist.global_state.client is not None
    if already:
        return
    kwargs = {}
    raw_timeout = (config_mod.env_str("HOROVOD_START_TIMEOUT") or "").strip()
    if raw_timeout:
        # One parser for every HOROVOD_START_TIMEOUT consumer
        # (config.start_timeout_seconds): garbage falls back to the same
        # 120s default the rendezvous windows use, instead of being
        # silently dropped here and honored there. An EXPLICIT <=0 keeps
        # the historical meaning: drop the kwarg and let
        # jax.distributed.initialize apply its own (300s) default.
        try:
            explicit_off = float(raw_timeout) <= 0
        except (ValueError, OverflowError):
            explicit_off = False
        if not explicit_off:
            kwargs["initialization_timeout"] = int(
                config_mod.start_timeout_seconds())
    if rank != 0:
        # Rank 0 HOSTS the coordinator service inside initialize();
        # probing it from rank 0 before the call would always fail.
        _preflight_coordinator(coord)

    def _reset_distributed_state():
        """Best-effort teardown of a HALF-initialized jax.distributed: a
        failed connect leaves global_state.client assigned (State.initialize
        sets it before connecting), so without a reset every retry would
        trip the 'should only be called once' guard and mask the real
        error."""
        try:
            jax.distributed.shutdown()
            return
        except Exception:
            pass
        try:
            from jax._src import distributed as _dist

            _dist.global_state.client = None
            _dist.global_state.service = None
        except Exception:
            pass

    def _attempt():
        from .. import fault

        fault.hook("init_distributed")
        try:
            jax.distributed.initialize(
                coordinator_address=coord,
                num_processes=size,
                process_id=rank,
                **kwargs)
        except Exception:
            _reset_distributed_state()
            raise

    attempts, backoff = retry.init_retry_env()
    retry.retry_call(_attempt, attempts=attempts, backoff=backoff,
                     seed=rank, describe="jax.distributed.initialize")


def _acquire_backend() -> bool:
    """Force JAX backend (TPU runtime) acquisition under the init retry
    policy, so a wedged/flaky backend init fails fast and retries instead
    of hanging the rank forever (the round-6 failure mode).

    Returns whether the backend is usable. False means NOTHING may touch
    jax device APIs again this process — an abandoned wedged attempt may
    still hold xla_bridge's backend lock, so any re-entry (including
    topology's device probe) would hang unboundedly.

    With ``HOROVOD_TPU_INIT_FALLBACK_CPU=1`` an exhausted retry budget
    degrades — loudly — to a CPU dryrun backend so the job can still run
    parity/debug work while the pool is down."""
    try:
        import jax
    except Exception:  # pragma: no cover - jax always present in this image
        return False  # same tolerance as topology._device_counts

    from .. import fault as fault_mod

    # Bounded BY DEFAULT: the r6 outage was an init that hung rather than
    # raised — with no deadline the retry/fallback machinery would never
    # even engage. 300s is ~10x a healthy cold TPU init; 0 disables.
    per_attempt = config_mod._env_float("HOROVOD_TPU_INIT_TIMEOUT", 300.0)

    def _attempt():
        fault_mod.hook("init")
        # device_count materializes the platform backend (the call that
        # wedged in artifacts/tpu_outage_r6.md).
        return retry.run_with_deadline(
            jax.local_device_count, per_attempt, "jax backend init")

    attempts, backoff = retry.init_retry_env()
    try:
        retry.retry_call(_attempt, attempts=attempts, backoff=backoff,
                         seed=config_mod.env_rank() or 0,
                         describe="jax backend acquisition")
        return True
    except retry.RetryError as exc:
        from .config import _env_bool

        if _env_bool("HOROVOD_TPU_INIT_FALLBACK_CPU"):
            if metrics.on():
                _init_metrics().cpu_fallback.inc()
            metrics.record_event("init_fallback_cpu", attempts=attempts,
                                 error=str(exc.last)[:200])
            logging.error(
                "jax backend acquisition failed after %d attempts; "
                "HOROVOD_TPU_INIT_FALLBACK_CPU=1 — DEGRADING TO THE CPU "
                "DRYRUN BACKEND. This process will NOT use accelerators; "
                "results are for parity/debugging only.", attempts)
            os.environ["JAX_PLATFORMS"] = "cpu"
            jax.config.update("jax_platforms", "cpu")
            # The fallback itself must stay deadline-bounded: an abandoned
            # wedged attempt may still hold xla_bridge's backend lock, and
            # an unbounded call here would wedge the very path built to
            # never wedge.
            try:
                retry.run_with_deadline(
                    jax.local_device_count, per_attempt or 120.0,
                    "CPU fallback backend init")
                return True
            except retry.DeadlineExceeded:
                logging.error(
                    "CPU fallback is unreachable too: the wedged init "
                    "attempt still holds the JAX backend lock. Continuing "
                    "on the host-only eager tier; jax device APIs are "
                    "UNUSABLE in this process.")
                return False
        if isinstance(exc.last, fault_mod.FaultInjected):
            raise  # injected wedges are test assertions: never swallow
        # Bounded, loud, and non-fatal — the pre-hardening contract
        # (topology._device_counts) tolerated a dead backend by reporting
        # 0 devices; the eager host tier still works without accelerators.
        logging.error(
            "jax backend acquisition failed after %d bounded attempts "
            "(%s); continuing WITHOUT accelerator devices — set "
            "HOROVOD_TPU_INIT_FALLBACK_CPU=1 to degrade to a CPU dryrun "
            "backend, or fix the TPU pool and relaunch", attempts, exc.last)
        return False


def init(ranks: Optional[Sequence[int]] = None) -> None:
    """Initialize horovod_tpu. Idempotent, like the reference's
    ``InitializeHorovodOnce`` (``horovod/common/operations.cc:1566-1583``).

    ``ranks`` restricts the job to a subset of processes, mirroring
    ``hvd.init(ranks)`` (``horovod/common/basics.py:29-55``). mpi4py
    communicators are not supported — there is no MPI on TPU; pass ``ranks``
    or use the launcher's env instead.
    """
    global _state
    with _state_lock:
        if _state is not None and _state.initialized:
            return
        config = Config.from_env()
        logging.configure(config.log_level, config.log_hide_timestamp)
        # Launcher-spawned ranks arm the parent-death watchdog (reference
        # spark/task/mpirun_exec_fn.py:25-35): an orphaned rank must kill
        # itself, not hold ring ports until a peer timeout. Runtime import:
        # run/ imports common/ at module load.
        from ..run.watchdog import maybe_install_from_env

        maybe_install_from_env()
        _maybe_init_jax_distributed()
        backend_ok = _acquire_backend()
        # After a failed acquisition the device probe must not re-enter
        # jax (a wedged attempt may still hold the backend lock).
        topology = detect(ranks, probe_devices=backend_ok)
        logging.set_rank(topology.rank)
        _state = HorovodTpuState(config, topology)
        if metrics.on():
            metrics.record_event(
                "init", size=topology.size,
                restart_epoch=config_mod._env_int(
                    "HOROVOD_RESTART_EPOCH", 0))
            # Scrape endpoint at HOROVOD_METRICS_PORT + rank (None when the
            # port knob is unset — snapshot() keeps working without it).
            _state.metrics_exporter = metrics.maybe_start_exporter(
                topology.rank)
        # Engine selection for the multi-process eager tier: the native C++
        # engine (negotiation + fusion + cache + timeline in engine.cc over
        # the TCP ring) is the default whenever the launcher exported ring
        # addresses; HOROVOD_ENGINE=python (or the star data plane) keeps the
        # Python controller. The choice must be identical on every rank —
        # both derive from launcher-exported env, so it is. Tracing
        # (HOROVOD_TRACE_DIR) no longer steers this choice: since round 14
        # the native engine stamps the same span vocabulary into its C
        # ring (docs/tracing.md), so traced jobs keep the fast path; only
        # elastic membership still requires the python controller below.
        from .config import ring_data_plane_enabled

        engine = config_mod.engine()
        if engine is None:
            engine = "native" if ring_data_plane_enabled() else "python"
        if config_mod.elastic_enabled() and engine == "native":
            # Elastic membership lives in the Python controller (the native
            # engine's ring is fixed-membership); the pin must be identical
            # on every rank — it derives from launcher-exported env, so it
            # is. horovodrun --elastic already exports the python engine.
            logging.warning(
                "HOROVOD_ELASTIC=1 requires the python controller engine; "
                "overriding the native engine selection (docs/elastic.md)")
            engine = "python"
        use_native = topology.size > 1 and engine == "native"
        if config.timeline_filename and topology.rank == 0 and not use_native:
            # Native engine writes the timeline itself (C++ writer thread).
            from .timeline import Timeline

            _state.timeline = Timeline(config.timeline_filename,
                                       mark_cycles=config.timeline_mark_cycles)
        if use_native:
            from ..controller.native import NativeController

            _state.controller = NativeController(config, topology)
        elif topology.size > 1 and config_mod.controller_addr():
            # Python controller over the TCP star.
            from ..controller.controller import Controller

            _state.controller = Controller(config, topology,
                                           timeline=_state.timeline)
        logging.debug(
            "horovod_tpu initialized: rank=%d size=%d local_rank=%d "
            "local_size=%d devices=%d/%d",
            topology.rank, topology.size, topology.local_rank,
            topology.local_size, topology.local_num_devices,
            topology.num_devices,
        )


def replace_topology(topology: Topology) -> None:
    """Elastic-reshape hook (``controller/controller.py``): swap the global
    state's topology after a membership change so ``hvd.rank()``/
    ``hvd.size()`` and the log prefix track the re-formed world. Runs on
    the controller thread (or the init thread for a joiner's admission);
    deliberately lock-free — the topology reference swap is atomic and
    ``_state_lock`` may be held by the very ``init()`` that is admitting
    a joiner."""
    if _state is not None:
        _state.topology = topology
    logging.set_rank(topology.rank)


def shutdown() -> None:
    """Tear down background services (reference ``horovod_shutdown``,
    ``operations.cc:1605-1614``)."""
    global _state
    with _state_lock:
        if _state is not None:
            _state.close()
            _state = None


atexit.register(shutdown)


def _ensure_initialized() -> HorovodTpuState:
    # The reference raises "Horovod has not been initialized; use hvd.init()"
    # from every API entry point (horovod/common/operations.cc:1587-1593).
    if _state is None or not _state.initialized:
        raise ValueError(
            "Horovod has not been initialized; use hvd.init().")
    return _state


def state() -> HorovodTpuState:
    return _ensure_initialized()


def controller():
    """The running eager-tier background controller, or a curated error.

    Shared guard for every framework adapter (torch/tf/mxnet/ops): the eager
    data plane needs the TCP controller that ``horovodrun`` bootstraps."""
    st = state()
    if st.controller is None:
        raise RuntimeError(
            "eager collectives at size > 1 require the background controller; "
            "launch through horovodrun (which exports HOROVOD_CONTROLLER_ADDR) "
            "or use the SPMD tier (collectives inside jit/shard_map over a "
            "multi-host mesh)")
    return st.controller


def is_initialized() -> bool:
    return _state is not None and _state.initialized


def rank() -> int:
    return _ensure_initialized().topology.rank


def size() -> int:
    return _ensure_initialized().topology.size


def local_rank() -> int:
    return _ensure_initialized().topology.local_rank


def local_size() -> int:
    return _ensure_initialized().topology.local_size


def cross_rank() -> int:
    return _ensure_initialized().topology.cross_rank


def cross_size() -> int:
    return _ensure_initialized().topology.cross_size


def num_devices() -> int:
    """Total accelerator chips in the job (TPU extension; the reference has no
    equivalent because rank==GPU there)."""
    return _ensure_initialized().topology.num_devices


def local_num_devices() -> int:
    return _ensure_initialized().topology.local_num_devices


def mpi_threads_supported() -> bool:
    """Parity shim for ``hvd.mpi_threads_supported()``
    (``horovod/common/basics.py:96-104``). There is no MPI in the TPU runtime;
    the controller's TCP plane is always thread-safe, so report True."""
    _ensure_initialized()
    return True
