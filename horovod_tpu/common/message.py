"""Control-plane message protocol: Request / Response.

Reference: ``horovod/common/message.{h,cc}`` + ``common/wire/message.fbs`` —
each rank's background thread emits a ``Request`` per pending tensor (rank,
type, dtype, name, shape, root); the coordinator replies with a fused
``ResponseList``. The reference serializes with FlatBuffers; we use plain
dataclasses over the authenticated wire (``horovod_tpu.common.wire``) — the
payloads are tiny and latency is dominated by the network round trip, and the
native (C++) data plane exchanges raw buffers, not these messages.

``construct_response`` reproduces the reference's full cross-rank validation
matrix (``ConstructResponse``, ``horovod/common/operations.cc:198-371``):
mismatched dtype / op / shape / root across ranks must produce an ERROR
response whose message is delivered to every participating rank's callback.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Dict, List, Optional, Sequence, Tuple


class RequestType(enum.IntEnum):
    # reference message.h:47
    ALLREDUCE = 0
    ALLGATHER = 1
    BROADCAST = 2


class ResponseType(enum.IntEnum):
    # reference message.h:132
    ALLREDUCE = 0
    ALLGATHER = 1
    BROADCAST = 2
    ERROR = 3


@dataclasses.dataclass
class Request:
    """One rank's declaration that a tensor is ready (reference
    ``message.h:40-120``)."""

    request_rank: int
    request_type: RequestType
    tensor_name: str
    tensor_dtype: str  # numpy dtype string, e.g. "float32"
    tensor_shape: Tuple[int, ...]
    root_rank: int = -1  # broadcast only
    # Launch priority (0 = none; docs/overlap.md): the coordinator
    # stable-sorts each cycle's fused responses by the tagged priority so
    # the optimizer-critical bucket launches first on every rank. Must
    # agree across ranks for a given tensor (like dtype); NOT part of the
    # validation matrix — a mismatch reorders, it doesn't error.
    priority: int = 0


@dataclasses.dataclass
class RequestList:
    """Everything one rank has pending this cycle (reference
    ``message.h:186-215``). ``shutdown`` cooperatively propagates teardown
    (reference operations.cc:1442-1445)."""

    requests: List[Request] = dataclasses.field(default_factory=list)
    shutdown: bool = False


@dataclasses.dataclass
class Response:
    """Coordinator's instruction to execute (possibly fused) collectives
    (reference ``message.h:125-184``)."""

    response_type: ResponseType
    tensor_names: List[str] = dataclasses.field(default_factory=list)
    error_message: str = ""
    # Allgather only: every rank's dim-0 size, rank order (reference
    # message.h:170-180 tensor_sizes).
    tensor_sizes: List[int] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class ResponseList:
    responses: List[Response] = dataclasses.field(default_factory=list)
    shutdown: bool = False


_TYPE_NAMES = {
    RequestType.ALLREDUCE: "allreduce",
    RequestType.ALLGATHER: "allgather",
    RequestType.BROADCAST: "broadcast",
}


def construct_response(requests: Sequence[Request], size: int) -> Response:
    """Build one tensor's Response after all ``size`` ranks have submitted
    requests, running the cross-rank consistency checks.

    Mirrors reference ``ConstructResponse`` (``operations.cc:198-371``)
    including the error strings' spirit: first mismatch wins, and the error
    names the offending ranks' values.
    """
    assert len(requests) == size, "construct_response requires all ranks"
    first = requests[0]
    name = first.tensor_name

    # Ordered by the reference's own check order: op type, then dtype, then
    # op-specific shape/root rules.
    for req in requests[1:]:
        if req.request_type != first.request_type:
            return Response(
                ResponseType.ERROR, [name],
                error_message=(
                    f"Mismatched collective operations: rank "
                    f"{first.request_rank} requested "
                    f"{_TYPE_NAMES[first.request_type]} of tensor {name}, but "
                    f"rank {req.request_rank} requested "
                    f"{_TYPE_NAMES[req.request_type]}."))
    for req in requests[1:]:
        if req.tensor_dtype != first.tensor_dtype:
            return Response(
                ResponseType.ERROR, [name],
                error_message=(
                    f"Mismatched data types: rank {first.request_rank} has "
                    f"tensor {name} with dtype {first.tensor_dtype}, but rank "
                    f"{req.request_rank} has dtype {req.tensor_dtype}."))

    if first.request_type == RequestType.ALLREDUCE:
        for req in requests[1:]:
            if req.tensor_shape != first.tensor_shape:
                return Response(
                    ResponseType.ERROR, [name],
                    error_message=(
                        f"Mismatched allreduce tensor shapes: rank "
                        f"{first.request_rank} has shape {first.tensor_shape} "
                        f"for tensor {name}, but rank {req.request_rank} has "
                        f"shape {req.tensor_shape}."))
        return Response(ResponseType.ALLREDUCE, [name])

    if first.request_type == RequestType.BROADCAST:
        for req in requests[1:]:
            if req.root_rank != first.root_rank:
                return Response(
                    ResponseType.ERROR, [name],
                    error_message=(
                        f"Mismatched broadcast root ranks: rank "
                        f"{first.request_rank} specified root "
                        f"{first.root_rank} for tensor {name}, but rank "
                        f"{req.request_rank} specified {req.root_rank}."))
        if not (0 <= first.root_rank < size):
            return Response(
                ResponseType.ERROR, [name],
                error_message=(
                    f"Invalid broadcast root rank {first.root_rank} for "
                    f"tensor {name}: world size is {size}."))
        # Non-root shapes must match the root's (the reference checks all
        # ranks agree, operations.cc:311-330).
        root_req = next(r for r in requests if r.request_rank == first.root_rank)
        for req in requests:
            if req.tensor_shape != root_req.tensor_shape:
                return Response(
                    ResponseType.ERROR, [name],
                    error_message=(
                        f"Mismatched broadcast tensor shapes: root rank "
                        f"{root_req.request_rank} has shape "
                        f"{root_req.tensor_shape} for tensor {name}, but rank "
                        f"{req.request_rank} has shape {req.tensor_shape}."))
        return Response(ResponseType.BROADCAST, [name])

    assert first.request_type == RequestType.ALLGATHER
    for req in requests[1:]:
        if len(req.tensor_shape) != len(first.tensor_shape):
            return Response(
                ResponseType.ERROR, [name],
                error_message=(
                    f"Mismatched allgather tensor ranks: rank "
                    f"{first.request_rank} has rank-{len(first.tensor_shape)} "
                    f"tensor {name}, but rank {req.request_rank} has rank "
                    f"{len(req.tensor_shape)}."))
        if len(first.tensor_shape) == 0:
            return Response(
                ResponseType.ERROR, [name],
                error_message=(
                    f"Allgather of scalar tensor {name} is not possible: "
                    "tensors must have at least one dimension."))
        if req.tensor_shape[1:] != first.tensor_shape[1:]:
            return Response(
                ResponseType.ERROR, [name],
                error_message=(
                    f"Mismatched allgather tensor shapes: all dimensions "
                    f"except the first must match; rank {first.request_rank} "
                    f"has shape {first.tensor_shape} for tensor {name}, but "
                    f"rank {req.request_rank} has shape {req.tensor_shape}."))
    if len(first.tensor_shape) == 0:
        return Response(
            ResponseType.ERROR, [name],
            error_message=(
                f"Allgather of scalar tensor {name} is not possible: "
                "tensors must have at least one dimension."))
    by_rank: Dict[int, Request] = {r.request_rank: r for r in requests}
    sizes = [by_rank[r].tensor_shape[0] for r in range(size)]
    return Response(ResponseType.ALLGATHER, [name], tensor_sizes=sizes)
