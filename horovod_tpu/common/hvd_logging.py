"""Leveled, rank-aware logging.

Reference: ``horovod/common/logging.{h,cc}`` — stream-style ``LOG(LEVEL, rank)``
macros with the level drawn from ``HOROVOD_LOG_LEVEL`` and optional timestamp
suppression via ``HOROVOD_LOG_HIDE_TIME``. We reuse Python's stdlib logging with
the same level vocabulary (trace..fatal) and a rank prefix once the controller
knows its rank.
"""

from __future__ import annotations

import logging
import sys

TRACE = 5  # below DEBUG, matches reference LogLevel::TRACE (logging.h:8)
logging.addLevelName(TRACE, "TRACE")

_LEVELS = {
    "trace": TRACE,
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warning": logging.WARNING,
    "error": logging.ERROR,
    "fatal": logging.CRITICAL,
}

_logger = logging.getLogger("horovod_tpu")
_configured = False
_rank_prefix = ""


def configure(level_name: str | None = None, hide_time: bool | None = None) -> None:
    global _configured
    if level_name is None:
        from .config import log_level_name

        level_name = log_level_name()
    if hide_time is None:
        from .config import _env_bool

        hide_time = _env_bool("HOROVOD_LOG_HIDE_TIME")
    level = _LEVELS.get(level_name, logging.WARNING)
    handler = logging.StreamHandler(sys.stderr)
    fmt = "[%(levelname)s] %(message)s" if hide_time else "%(asctime)s [%(levelname)s] %(message)s"
    handler.setFormatter(logging.Formatter(fmt))
    _logger.handlers[:] = [handler]
    _logger.setLevel(level)
    _logger.propagate = False
    _configured = True


def set_rank(rank: int) -> None:
    global _rank_prefix
    _rank_prefix = "[%d]: " % rank


def _log(level: int, msg: str, *args) -> None:
    if not _configured:
        configure()
    _logger.log(level, _rank_prefix + msg, *args)


def trace(msg, *args):
    _log(TRACE, msg, *args)


def debug(msg, *args):
    _log(logging.DEBUG, msg, *args)


def info(msg, *args):
    _log(logging.INFO, msg, *args)


def warning(msg, *args):
    _log(logging.WARNING, msg, *args)


def error(msg, *args):
    _log(logging.ERROR, msg, *args)


def fatal(msg, *args):
    _log(logging.CRITICAL, msg, *args)
