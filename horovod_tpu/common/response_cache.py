"""Response cache: skip re-negotiating tensors seen before.

Reference: ``horovod/common/response_cache.{h,cc}`` — an LRU of Responses
keyed by tensor name + parameters (dtype/shape/op/root), bit-indexed so that
per-cycle coordination is a single bitvector AND-allreduce across ranks
(``response_cache.cc:303``) instead of the full Gatherv/Bcast negotiation.
A hit whose parameters changed invalidates the entry (propagated with an
OR pass).

Here bitvectors are arbitrary-precision Python ints; the star control plane
ANDs/ORs them at the coordinator (``horovod_tpu.controller``). Capacity
defaults to 1024 (reference ``global_state.h:135``); 0 disables caching.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Dict, Optional, Tuple

from .message import Request, RequestType, Response


def _params_of(req: Request) -> Tuple:
    return (req.request_type, req.tensor_dtype, req.tensor_shape, req.root_rank)


class ResponseCache:
    def __init__(self, capacity: int = 1024):
        self.capacity = capacity
        # name -> (bit position, params, response). Bit positions are stable
        # for an entry's lifetime (reference bit-indexed cache,
        # response_cache.h:43-92).
        self._entries: "OrderedDict[str, Tuple[int, Tuple, Response]]" = OrderedDict()
        self._free_bits: list[int] = list(range(capacity))
        self._by_bit: Dict[int, str] = {}

    def __len__(self) -> int:
        return len(self._entries)

    def lookup(self, req: Request) -> Optional[int]:
        """Bit position on a parameter-exact hit; None on miss.

        Deliberately does NOT touch LRU order: cache state must evolve
        identically on every rank so bit positions stay coherent (the
        reference keeps coherence the same way — cache mutations happen only
        at points that occur in identical order on all ranks). Lookups happen
        in local-queue order, which may differ per rank; use ``touch`` at
        deterministic execution points instead."""
        entry = self._entries.get(req.tensor_name)
        if entry is None:
            return None
        bit, params, _ = entry
        if params != _params_of(req):
            return None
        return bit

    def touch(self, bit: int) -> None:
        """LRU-touch an entry. Only call at points ordered identically across
        ranks (bypass execution walks sorted agreed bits)."""
        name = self._by_bit.get(bit)
        if name is not None:
            self._entries.move_to_end(name)

    def stale_bit(self, req: Request) -> Optional[int]:
        """Bit of a same-name entry whose params no longer match (to be
        invalidated across ranks)."""
        entry = self._entries.get(req.tensor_name)
        if entry is None:
            return None
        bit, params, _ = entry
        return bit if params != _params_of(req) else None

    def get(self, bit: int) -> Tuple[str, Response]:
        name = self._by_bit[bit]
        _, _, response = self._entries[name]
        return name, response

    def request_of(self, bit: int) -> Optional[Request]:
        name = self._by_bit.get(bit)
        if name is None:
            return None
        _, params, _ = self._entries[name]
        rtype, dtype, shape, root = params
        return Request(request_rank=-1, request_type=rtype, tensor_name=name,
                       tensor_dtype=dtype, tensor_shape=shape, root_rank=root)

    def put(self, req: Request, response: Response) -> None:
        if self.capacity <= 0:
            return
        if req.tensor_name in self._entries:
            bit, _, _ = self._entries[req.tensor_name]
            self._entries[req.tensor_name] = (bit, _params_of(req), response)
            self._entries.move_to_end(req.tensor_name)
            return
        if not self._free_bits:
            # Evict LRU (reference evicts lowest-priority entry,
            # response_cache.cc put path).
            old_name, (old_bit, _, _) = next(iter(self._entries.items()))
            del self._entries[old_name]
            del self._by_bit[old_bit]
            self._free_bits.append(old_bit)
        bit = self._free_bits.pop(0)
        self._entries[req.tensor_name] = (bit, _params_of(req), response)
        self._by_bit[bit] = req.tensor_name

    def evict_bit(self, bit: int) -> None:
        name = self._by_bit.pop(bit, None)
        if name is not None:
            del self._entries[name]
            self._free_bits.append(bit)

    def evict_name(self, name: str) -> None:
        entry = self._entries.pop(name, None)
        if entry is not None:
            bit, _, _ = entry
            del self._by_bit[bit]
            self._free_bits.append(bit)

    def bits_to_mask(self, bits) -> int:
        mask = 0
        for b in bits:
            mask |= 1 << b
        return mask

    @staticmethod
    def mask_to_bits(mask: int) -> list[int]:
        bits = []
        i = 0
        while mask:
            if mask & 1:
                bits.append(i)
            mask >>= 1
            i += 1
        return bits
