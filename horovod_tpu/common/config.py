"""Runtime configuration for horovod_tpu.

The reference configures everything through environment variables read once at
startup inside ``BackgroundThreadLoop`` (reference ``horovod/common/operations.cc:987-1080``).
We keep the exact same variable names so operator muscle memory (and existing
launch scripts) carry over, and add ``HOROVOD_TPU_*`` variables for knobs that
only exist on TPU.

Unlike the reference, configuration is an explicit dataclass snapshot rather
than globals scattered through a god object: JAX programs are functional, and a
frozen config travels well through jit boundaries.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional

# Defaults mirror reference horovod/common/operations.cc:1005 (64 MiB fusion
# threshold), :1013 (5 ms cycle time) and horovod/common/global_state.h:135
# (1024-entry response cache).
DEFAULT_FUSION_THRESHOLD_BYTES = 64 * 1024 * 1024
DEFAULT_CYCLE_TIME_MS = 5.0
DEFAULT_CACHE_CAPACITY = 1024
DEFAULT_STALL_CHECK_SECONDS = 60.0
DEFAULT_START_TIMEOUT_SECONDS = 120.0
# Liveness bound for the post-rendezvous control plane: a blocked recv that
# sees NO frame (not even a heartbeat) for this long declares the peer dead
# instead of hanging forever (the reference's timeout-less sockets could).
DEFAULT_COMM_TIMEOUT_SECONDS = 120.0


def start_timeout_seconds(
        default: float = DEFAULT_START_TIMEOUT_SECONDS) -> float:
    """THE parser for ``HOROVOD_START_TIMEOUT`` (reference horovodrun
    --start-timeout). Garbage and non-positive values fall back to
    ``default`` — every consumer (rendezvous accept/connect windows in
    ``controller/service.py``, ``jax.distributed.initialize`` in
    ``common/basics.py``) must agree, or the two planes time out at
    different moments and the slower one wins by hanging."""
    try:
        val = float(os.environ.get("HOROVOD_START_TIMEOUT", ""))
    except (ValueError, OverflowError):
        return default
    return val if val > 0 else default


def comm_timeout_seconds() -> float:
    """``HOROVOD_COMM_TIMEOUT_SECONDS``: per-recv liveness deadline on the
    eager control plane. 0 (or negative) disables the deadline entirely —
    the pre-fault-tolerance behavior."""
    val = _env_float("HOROVOD_COMM_TIMEOUT_SECONDS",
                     DEFAULT_COMM_TIMEOUT_SECONDS)
    return val if val > 0 else 0.0


def heartbeat_interval_seconds() -> float:
    """``HOROVOD_HEARTBEAT_INTERVAL_SECONDS``: idle-cycle heartbeat frame
    period (0 disables). Defaults to a quarter of the comm timeout capped
    at 10s, so a live-but-quiet peer always beats the deadline with slack
    for scheduler noise. With the deadline disabled entirely
    (HOROVOD_COMM_TIMEOUT_SECONDS=0) heartbeats default OFF too — nothing
    would consume them; the env var still forces them on if wanted."""
    timeout = comm_timeout_seconds()
    default = min(10.0, timeout / 4.0) if timeout else 0.0
    val = _env_float("HOROVOD_HEARTBEAT_INTERVAL_SECONDS", default)
    return val if val > 0 else 0.0


def ring_data_plane_enabled() -> bool:
    """True when the launcher exported per-rank ring addresses and the
    operator did not force the pure-Python star data plane. The single
    source of truth for both engine selection (basics.init) and the Python
    controller's ring construction — the predicate must be identical on
    every rank, and both sites must agree."""
    return bool(os.environ.get("HOROVOD_RING_ADDRS")) and \
        os.environ.get("HOROVOD_CPU_OPS", "ring") != "star"


def env_rank() -> Optional[int]:
    """``HOROVOD_RANK`` as Optional[int]; unset/empty/garbage -> None.
    THE parser for every consumer (metrics rank labels, flight-recorder
    paths, fault-plan rank filters) — they must agree on what a
    malformed launch environment means, and none of them may crash on
    it."""
    val = os.environ.get("HOROVOD_RANK")
    if val is None or not val.strip():
        return None
    try:
        return int(val)
    except ValueError:
        return None


def env_str(name: str, default: Optional[str] = None) -> Optional[str]:
    """Raw environment string, unset -> ``default``. THE generic reader:
    every value read of the environment outside this module goes through
    an accessor here (enforced by hvdlint HVD003), so there is exactly
    one place that decides what unset/empty/garbage means per knob."""
    val = os.environ.get(name)
    return default if val is None else val


def env_size() -> Optional[int]:
    """``HOROVOD_SIZE`` as Optional[int]; unset/empty/garbage -> None
    (the :func:`env_rank` convention — the two must agree on what a
    malformed launch environment means)."""
    val = os.environ.get("HOROVOD_SIZE")
    if val is None or not val.strip():
        return None
    try:
        return int(val)
    except ValueError:
        return None


def engine() -> Optional[str]:
    """``HOROVOD_ENGINE`` (native/python), None when the launcher left
    the choice to :func:`ring_data_plane_enabled`. Every rank derives the
    same answer from the same launcher-exported env."""
    return os.environ.get("HOROVOD_ENGINE") or None


def controller_addr() -> Optional[str]:
    """``HOROVOD_CONTROLLER_ADDR``: the coordinator's TCP star endpoint,
    exported by horovodrun; None outside a launched eager job."""
    return os.environ.get("HOROVOD_CONTROLLER_ADDR") or None


def spmd_coordinator() -> Optional[str]:
    """``HOROVOD_SPMD_COORDINATOR``: jax.distributed coordinator address
    (horovodrun --spmd); None outside SPMD multi-host mode."""
    return os.environ.get("HOROVOD_SPMD_COORDINATOR") or None


def secret_key_hex() -> Optional[str]:
    """``HOROVOD_SECRET_KEY`` (hex), the per-job HMAC key minted by the
    launcher. None means the hermetic single-job default applies
    (``common/wire.job_secret``) — both wire implementations and the
    launcher must agree on that fallback."""
    return os.environ.get("HOROVOD_SECRET_KEY") or None


def ring_addrs() -> Optional[str]:
    """``HOROVOD_RING_ADDRS``: per-rank addresses for the native ring
    data plane (launcher-exported, identical on every rank)."""
    return os.environ.get("HOROVOD_RING_ADDRS") or None


def local_ring_addrs() -> Optional[str]:
    return os.environ.get("HOROVOD_LOCAL_RING_ADDRS") or None


def cross_ring_addrs() -> Optional[str]:
    return os.environ.get("HOROVOD_CROSS_RING_ADDRS") or None


# Wire dtypes the native ring can put f32 allreduce payloads on the wire
# as (docs/wire-compression.md); must match core.bindings.WIRE_DTYPE_CODES.
RING_WIRE_DTYPES = ("none", "bf16", "fp16", "int8")

# Default wire dtype per link class — RING_CHUNK_BYTES_BY_LINK's sibling
# table (docs/wire-compression.md). Fast intra-node/ICI links lose more
# to the cast kernels than the saved bytes buy back, so they default to
# the untouched f32 stream; DCN/TCP-class links are exactly where
# int8+error-feedback pays (the reference's cross-node hop,
# nccl_operations.cc:167-363).
RING_WIRE_DTYPE_BY_LINK = {
    "local": "none",
    "ici": "none",
    "tcp": "int8",
    "dcn": "int8",
}

# Default transfer-chunk bytes per link class (docs/wire-compression.md):
# loopback wants big chunks (syscall overhead dominates, no real wire to
# overlap with), plain TCP keeps the round-3 256 KiB sweet spot, DCN-class
# NICs amortize better at 512 KiB, ICI-class links are long-BDP pipes.
RING_CHUNK_BYTES_BY_LINK = {
    "local": 1 << 20,
    "tcp": 256 << 10,
    "dcn": 512 << 10,
    "ici": 2 << 20,
}


def ring_wire_dtype() -> str:
    """``HOROVOD_RING_WIRE_DTYPE``: on-the-wire representation of f32
    payloads in the native ring's allreduce data phases — ``bf16``/``fp16``
    halve every hop's bytes (accumulation stays f32), ``int8`` quarters
    them with per-block scales + error feedback (convergence contract in
    docs/wire-compression.md). Unset/garbage -> ``none``, which keeps the
    byte stream identical to the pre-round-10 ring. Must be identical on
    every rank (launcher-exported, like the other ring knobs)."""
    val = (os.environ.get("HOROVOD_RING_WIRE_DTYPE") or "").strip().lower()
    return val if val in RING_WIRE_DTYPES else "none"


def _link_class_env(name: str) -> Optional[str]:
    """A *_LINK_CLASS env value when valid, else None (garbage falls back
    to the caller's inference path, never crashes)."""
    val = (os.environ.get(name) or "").strip().lower()
    return val if val in RING_CHUNK_BYTES_BY_LINK else None


def local_ring_link_class() -> str:
    """``HOROVOD_LOCAL_RING_LINK_CLASS``: link class of the hierarchical
    plane's intra-node ring. Unset/garbage -> inferred from the
    launcher-exported local ring addresses (same-host ranks are loopback,
    hence ``local``); operators on ICI fabrics export it explicitly."""
    val = _link_class_env("HOROVOD_LOCAL_RING_LINK_CLASS")
    if val is not None:
        return val
    from ..run.nic_discovery import infer_link_class

    return infer_link_class(local_ring_addrs())


def cross_ring_link_class() -> str:
    """``HOROVOD_CROSS_RING_LINK_CLASS``: link class of the hierarchical
    plane's inter-node ring (the local roots' ring). Unset/garbage ->
    inferred from the cross ring addresses — anything spanning hosts is
    ``tcp``; known DCN fabrics export the class explicitly (the chunk
    table AND the wire-dtype table key off it)."""
    val = _link_class_env("HOROVOD_CROSS_RING_LINK_CLASS")
    if val is not None:
        return val
    from ..run.nic_discovery import infer_link_class

    return infer_link_class(cross_ring_addrs())


def _wire_dtype_for(env_name: str, link_class: str) -> str:
    """Shared resolver for the per-link wire dtypes: an explicit valid
    env value wins; unset/garbage falls back to the link-class default
    (``RING_WIRE_DTYPE_BY_LINK``), never to a crash."""
    val = (os.environ.get(env_name) or "").strip().lower()
    if val in RING_WIRE_DTYPES:
        return val
    return RING_WIRE_DTYPE_BY_LINK[link_class]


def ring_wire_dtype_local() -> str:
    """``HOROVOD_RING_WIRE_DTYPE_LOCAL``: on-the-wire representation of
    f32 allreduce payloads on the hierarchical plane's LOCAL (intra-node)
    ring. Default by link class: local/ici -> ``none`` (the fast hop —
    cast kernels cost more than the bytes they save), tcp/dcn -> ``int8``.
    Launcher-exported, identical on every rank (like every ring knob)."""
    return _wire_dtype_for("HOROVOD_RING_WIRE_DTYPE_LOCAL",
                           local_ring_link_class())


def ring_wire_dtype_cross() -> str:
    """``HOROVOD_RING_WIRE_DTYPE_CROSS``: wire dtype for the hierarchical
    plane's CROSS ring (local roots, the slow inter-node hop — exactly
    where int8+error-feedback pays most; docs/wire-compression.md).
    Default by link class: tcp/dcn -> ``int8``, local/ici -> ``none``."""
    return _wire_dtype_for("HOROVOD_RING_WIRE_DTYPE_CROSS",
                           cross_ring_link_class())


def ring_chunk_bytes() -> int:
    """``HOROVOD_RING_CHUNK_BYTES``: transfer-chunk size for the ring's
    reduce-while-receive sink and compress-ahead cursor (per-rank
    pipelining granularity only — the int8 wire format is anchored on
    fixed quant blocks, so ranks need not agree). 0 (default, and for
    garbage) means auto: the per-link-class table keyed by
    :func:`ring_link_class`, and the knob joins the GP autotuner's search
    when ``HOROVOD_AUTOTUNE`` is on. Explicit values pin the knob
    (excluded from the search, like every other fixed= override)."""
    return max(0, _env_int("HOROVOD_RING_CHUNK_BYTES", 0))


def ring_link_class() -> str:
    """``HOROVOD_RING_LINK_CLASS``: the flat ring's link class
    (local/tcp/dcn/ici), keying the default chunk table. Unset -> inferred
    from the launcher-exported ring addresses (``run.nic_discovery
    .infer_link_class``): loopback-only -> ``local``, anything spanning
    hosts -> ``tcp``; operators on known DCN/ICI fabrics export the class
    explicitly (or the launcher does, where NIC discovery identified
    one)."""
    val = (os.environ.get("HOROVOD_RING_LINK_CLASS") or "").strip().lower()
    if val in RING_CHUNK_BYTES_BY_LINK:
        return val
    from ..run.nic_discovery import infer_link_class

    return infer_link_class(ring_addrs())


def resolved_ring_chunk_bytes() -> int:
    """The chunk size the ring should start at: the explicit env value, or
    the link-class default. One resolver so the controller, the autotuner
    seeding, and the metrics gauge agree."""
    explicit = ring_chunk_bytes()
    if explicit:
        return explicit
    return RING_CHUNK_BYTES_BY_LINK[ring_link_class()]


# Default gradient-bucket size for the backward-order bucket scheduler
# (docs/overlap.md): big enough that per-bucket negotiation overhead
# amortizes, small enough that the first reduction launches while most of
# the backward pass is still running (the reference's fusion-buffer cycle
# achieves the same balance with its 64 MiB buffer + 5 ms cycle).
DEFAULT_BUCKET_BYTES = 8 * 1024 * 1024


def bucket_bytes() -> int:
    """``HOROVOD_BUCKET_BYTES``: size bound for the backward-order
    gradient buckets (controller/bucket_scheduler.py). 0 (default, and
    for garbage) means auto — the 8 MiB default, and the knob joins the
    GP autotuner's search when ``HOROVOD_AUTOTUNE`` is on. An explicit
    positive value pins the knob (``fixed=`` semantics, like
    HOROVOD_RING_CHUNK_BYTES)."""
    return max(0, _env_int("HOROVOD_BUCKET_BYTES", 0))


def resolved_bucket_bytes() -> int:
    """The bucket size the scheduler should start at: the explicit env
    value, or the default. One resolver so the scheduler, the autotuner
    seeding, and docs agree."""
    explicit = bucket_bytes()
    return explicit if explicit else DEFAULT_BUCKET_BYTES


def cpu_ops() -> str:
    """``HOROVOD_CPU_OPS``: "star" forces the pure-Python star data
    plane; anything else (default "ring") allows the native rings. Part
    of the per-rank-identical path-selection predicate
    (:func:`ring_data_plane_enabled`)."""
    return os.environ.get("HOROVOD_CPU_OPS", "ring")


def flash_xla_bwd() -> bool:
    """``HOROVOD_FLASH_XLA_BWD``: trace-time escape hatch selecting the
    rematerialized XLA backward for flash attention (O(S^2) memory).
    Raw truthiness on purpose — the historical contract is "set to
    anything non-empty", and both consumers (ops/attention.py,
    parallel/sequence.py) must keep flipping together."""
    return bool(os.environ.get("HOROVOD_FLASH_XLA_BWD"))


def flight_recorder_path() -> Optional[str]:
    """``HOROVOD_FLIGHT_RECORDER``: crash-postmortem JSONL path (with
    ``{rank}``/``.rankN`` expansion applied by the recorder). None/blank
    disables — and, via ``metrics.on()``, setting it implicitly enables
    telemetry."""
    val = (os.environ.get("HOROVOD_FLIGHT_RECORDER") or "").strip()
    return val or None


def restart_epoch() -> int:
    """``HOROVOD_RESTART_EPOCH``: supervision attempt number, bumped by
    ``horovodrun --max-restarts`` per relaunch. 0 on the first launch,
    outside the launcher, and for garbage values (a malformed relaunch
    env must look like a fresh start, not crash resume logic)."""
    try:
        return max(0, int(os.environ.get("HOROVOD_RESTART_EPOCH", "0")))
    except ValueError:
        return 0


def tensorflow_custom_op_enabled() -> bool:
    """``HOROVOD_TENSORFLOW_CUSTOM_OP``: opt-out knob for the native TF
    custom-op data path. Historical semantics kept exactly: only the
    explicit negatives disable; unset and even empty mean enabled (NOT
    the ``_env_bool`` convention — existing launch scripts rely on
    it)."""
    return os.environ.get("HOROVOD_TENSORFLOW_CUSTOM_OP", "1") \
        .strip().lower() not in ("0", "false", "no", "off")


def log_level_name() -> str:
    """``HOROVOD_LOG_LEVEL`` lowercased, defaulting to "warning" — the
    one parser for both the early logging bootstrap
    (``hvd_logging.configure``) and ``Config.from_env``."""
    return os.environ.get("HOROVOD_LOG_LEVEL", "warning").lower()


def autotune_straggler_weight() -> float:
    """``HOROVOD_AUTOTUNE_STRAGGLER_WEIGHT``: how strongly the autotuner's
    objective discounts throughput for observed negotiation slack and
    coordinator recv-wait (docs/autotune.md). 0 restores the pure
    bytes/sec objective; negative/garbage values clamp to the default.
    Default 1.0 — with a healthy cluster both penalty terms are ~0, so
    the blend only bites when stragglers actually cost wall time."""
    val = _env_float("HOROVOD_AUTOTUNE_STRAGGLER_WEIGHT", 1.0)
    return val if val >= 0 else 1.0


def pipeline_enabled() -> bool:
    """``HOROVOD_PIPELINE``: the native engine's double-buffered data
    plane (docs/overlap.md) — a dedicated wire thread runs group N on
    the ring while the engine thread packs N+1 and copies out N-1.
    Default on; ``HOROVOD_PIPELINE=0`` is the escape hatch back to the
    serial fill->wire->copy-out stream (byte-identical results either
    way — the knob trades step time only). The engine also falls back
    to serial on the hierarchical allreduce plane, whose cross-hop
    scratch is shared."""
    return _env_bool("HOROVOD_PIPELINE", True)


def autotune_overlap_weight() -> float:
    """``HOROVOD_AUTOTUNE_OVERLAP_WEIGHT``: how strongly the autotuner's
    objective rewards measured backward/comm overlap (docs/autotune.md,
    docs/overlap.md). The blend multiplies the throughput score by
    ``1 + w * overlap_efficiency`` whenever the bucket scheduler has
    published a fresh overlap sample; 0 removes the term. Negative or
    garbage values clamp to the default 1.0."""
    val = _env_float("HOROVOD_AUTOTUNE_OVERLAP_WEIGHT", 1.0)
    return val if val >= 0 else 1.0


def doctor_cycles() -> int:
    """``HOROVOD_DOCTOR_CYCLES``: coordinator cycles between periodic
    cluster-doctor sweeps (the rank-0 log line + hvd_doctor_* gauges;
    docs/doctor.md). 0/negative disables the periodic sweep (the /doctor
    endpoint and offline CLI still work). Default 1000 — ~5s at the
    default 5 ms cycle time."""
    val = _env_int("HOROVOD_DOCTOR_CYCLES", 1000)
    return max(0, val)


def elastic_enabled() -> bool:
    """``HOROVOD_ELASTIC``: opt-in elastic membership (docs/elastic.md).
    When set, a dead rank triggers a coordinator-led reshape (survivors
    re-form at a bumped membership epoch) instead of a job-wide abort,
    and late worker hellos are admitted at the next epoch boundary.
    Unset, behavior is identical to the static fault-tolerance contract
    (docs/fault-tolerance.md)."""
    return _env_bool("HOROVOD_ELASTIC")


def elastic_join() -> bool:
    """``HOROVOD_ELASTIC_JOIN``: this worker is a late joiner — it sends
    a JOIN hello to a live coordinator and waits for its (rank, size,
    epoch) assignment instead of taking part in the initial rendezvous.
    Exported by ``horovodrun --elastic`` when it respawns a dead worker
    slot."""
    return _env_bool("HOROVOD_ELASTIC_JOIN")


def elastic_min_ranks() -> int:
    """``HOROVOD_ELASTIC_MIN_RANKS``: smallest world size an elastic
    reshape may re-form (coordinator included). Below it the job aborts
    exactly like the non-elastic path. Default 1 — the coordinator keeps
    going alone if it must."""
    return max(1, _env_int("HOROVOD_ELASTIC_MIN_RANKS", 1))


def elastic_max_ranks() -> int:
    """``HOROVOD_ELASTIC_MAX_RANKS``: largest world size joiners may grow
    the job to; joiners beyond it stay parked until a slot frees. 0 (the
    default) means unbounded."""
    return max(0, _env_int("HOROVOD_ELASTIC_MAX_RANKS", 0))


def elastic_ckpt_dir() -> Optional[str]:
    """``HOROVOD_CKPT_DIR``: directory for the continuous async sharded
    checkpoints (docs/sharded-checkpoint.md). When set, every
    ``hvd.elastic.State.commit()`` also hands this rank's shard of the
    committed pytree to the background ``hvd-ckpt-writer`` thread; the
    step loop never blocks on storage. Unset (the default), commits stay
    purely in-memory and the disk tier is off."""
    val = env_str("HOROVOD_CKPT_DIR")
    return val.strip() if val and val.strip() else None


def elastic_ckpt_keep() -> int:
    """``HOROVOD_CKPT_KEEP``: how many complete sharded-checkpoint steps
    the async writer retains on disk (older steps are pruned after a new
    one lands whole). Minimum/default 2 — the double buffer that makes a
    kill at ANY rename point leave a complete previous step visible."""
    return max(2, _env_int("HOROVOD_CKPT_KEEP", 2))


def elastic_restore_mode() -> str:
    """``HOROVOD_ELASTIC_RESTORE``: how ``hvd.elastic.State.restore()``
    re-establishes consistent state after a reshape (docs/elastic.md).
    ``p2p`` (the default under elastic membership) keeps digest-matching
    survivors' local commits and scatters only the missing shards over
    surviving owners; ``broadcast`` forces the legacy rank-0 whole-pytree
    re-broadcast (the bench baseline). Garbage falls back to p2p."""
    val = (env_str("HOROVOD_ELASTIC_RESTORE") or "").strip().lower()
    return "broadcast" if val == "broadcast" else "p2p"


def autotune_priors() -> str:
    """``HOROVOD_AUTOTUNE_PRIORS``: where the GP autotuner's initial
    bucket/chunk configuration comes from (docs/autotune.md, round 17).
    ``capacity`` seeds the first probed configuration from the capacity
    planner's calibrated recommendation for this world size
    (``utils.scaling_model.recommend_autotune_seeds`` over the artifact
    named by :func:`capacity_calibration_path`); anything else — the
    default ``off`` — keeps the resolver defaults. Explicit env pins
    (HOROVOD_BUCKET_BYTES / HOROVOD_RING_CHUNK_BYTES) always win over
    the prior."""
    val = (env_str("HOROVOD_AUTOTUNE_PRIORS") or "").strip().lower()
    return "capacity" if val == "capacity" else "off"


def capacity_calibration_path() -> Optional[str]:
    """``HOROVOD_CAPACITY_CALIBRATION``: path to a control-plane
    calibration artifact (the ``control_plane`` + ``model_vs_measured``
    JSON shape the sim measurement rig writes). Arms the
    ``capacity_headroom`` doctor rule and the ``capacity`` autotune
    priors; unset (default) both stand down — a fleet without a
    calibrated model has nothing honest to compare against."""
    val = env_str("HOROVOD_CAPACITY_CALIBRATION")
    return val.strip() if val and val.strip() else None


def metrics_window_seconds() -> float:
    """``HOROVOD_METRICS_WINDOW_SECONDS``: how long one rolling
    telemetry window lasts (docs/metrics.md). The rank-0 window roller
    delta-snapshots the cluster view at this cadence into a bounded
    ring (last 32 windows), feeding the windowed doctor rules and the
    live calibration re-fit (docs/capacity.md). Garbage/non-positive
    falls back to the default 30s."""
    val = _env_float("HOROVOD_METRICS_WINDOW_SECONDS", 30.0)
    return val if val > 0 else 30.0


def capacity_refit_windows() -> int:
    """``HOROVOD_CAPACITY_REFIT_WINDOWS``: telemetry windows between
    live-calibration re-fits (docs/capacity.md) — every N completed
    windows rank 0 re-fits the control-plane curves from the windowed
    histograms and, when ``HOROVOD_CAPACITY_LIVE_DIR`` is set, rewrites
    ``capacity_live.json``. Minimum/garbage clamps to 1; default 8."""
    val = _env_int("HOROVOD_CAPACITY_REFIT_WINDOWS", 8)
    return max(1, val)


def capacity_live_dir() -> Optional[str]:
    """``HOROVOD_CAPACITY_LIVE_DIR``: directory where rank 0 persists
    ``capacity_live.json`` — the live re-fit of the control-plane
    calibration in the exact ``capacity_r17.json`` schema, stamped
    ``"source": "live"`` (docs/capacity.md). Written on every
    ``HOROVOD_CAPACITY_REFIT_WINDOWS``-th window and at shutdown.
    Unset (default): the live re-fit stays in memory only."""
    val = env_str("HOROVOD_CAPACITY_LIVE_DIR")
    return val.strip() if val and val.strip() else None


def serving_max_batch() -> int:
    """``HOROVOD_SERVING_MAX_BATCH``: decode-batch slots in the serving
    engine — the most sequences one continuous-batching decode step
    carries (docs/serving.md). Garbage/non-positive falls back to the
    default 8 (the b8 decode floor the batcher exists to amortize)."""
    val = _env_int("HOROVOD_SERVING_MAX_BATCH", 8)
    return val if val > 0 else 8


def serving_block_size() -> int:
    """``HOROVOD_SERVING_BLOCK_SIZE``: KV-cache page size in token
    positions. Default 16 — on real models the flat head width is a
    128-lane multiple, so a 16-row block is one bf16 Mosaic tile."""
    val = _env_int("HOROVOD_SERVING_BLOCK_SIZE", 16)
    return val if val > 0 else 16


def serving_num_blocks() -> int:
    """``HOROVOD_SERVING_NUM_BLOCKS``: physical KV pool capacity in
    blocks (the null block is extra). 0 (default) = fully provisioned —
    every decode slot can hold a max-length sequence, so preemption is
    impossible; operators lower it to oversubscribe HBM and let
    preemption-by-recompute absorb the tail."""
    return max(0, _env_int("HOROVOD_SERVING_NUM_BLOCKS", 0))


def serving_queue_depth() -> int:
    """``HOROVOD_SERVING_QUEUE_DEPTH``: admission bound — submissions
    beyond this many WAITING requests are rejected loudly
    (``hvd.serving.RejectedError``) instead of queueing without bound."""
    val = _env_int("HOROVOD_SERVING_QUEUE_DEPTH", 128)
    return val if val > 0 else 128


def serving_max_seq_len() -> int:
    """``HOROVOD_SERVING_MAX_SEQ_LEN``: per-sequence position budget
    (prompt + generated) in the serving engine. 0 (default) = the
    model's own ``max_seq_len``."""
    return max(0, _env_int("HOROVOD_SERVING_MAX_SEQ_LEN", 0))


def serving_prefix_cache() -> bool:
    """``HOROVOD_SERVING_PREFIX_CACHE``: copy-on-write prefix sharing on
    the paged KV pool (docs/serving.md). Default ON — per-request tokens
    are bit-identical with it on or off (the pinned parity contract), so
    the knob exists for A/B measurement and paranoia, not correctness."""
    return _env_bool("HOROVOD_SERVING_PREFIX_CACHE", True)


def serving_prefix_capacity() -> int:
    """``HOROVOD_SERVING_PREFIX_CAPACITY``: most blocks the prefix index
    may hold references to (its LRU bound). 0 (default) = no dedicated
    bound — cold entries are released only under pool pressure, which is
    the right default because cached pages are free until somebody
    needs the blocks."""
    return max(0, _env_int("HOROVOD_SERVING_PREFIX_CAPACITY", 0))


def router_replicas() -> int:
    """``HOROVOD_ROUTER_REPLICAS``: engine replicas ``hvd.serving.fleet``
    spins up when the caller does not pass an explicit count. Default 2
    — the smallest fleet where replica death is a reshape instead of an
    outage."""
    val = _env_int("HOROVOD_ROUTER_REPLICAS", 2)
    return val if val > 0 else 2


def router_affinity() -> bool:
    """``HOROVOD_ROUTER_AFFINITY``: prefix-affinity placement — requests
    whose first whole page matches a prefix recently routed somewhere
    follow it there (that replica's prefix cache is warm for them).
    Default ON; off = pure least-loaded."""
    return _env_bool("HOROVOD_ROUTER_AFFINITY", True)


def router_retries() -> int:
    """``HOROVOD_ROUTER_RETRIES``: times the router replays one request
    on another replica after its serving replica died mid-flight (the
    recompute path: greedy decoding is deterministic, so the replay's
    tokens are identical and already-streamed ones are skipped). Beyond
    it the failure surfaces to the caller."""
    return max(0, _env_int("HOROVOD_ROUTER_RETRIES", 2))


def fault_plan_raw() -> Optional[str]:
    """``HOROVOD_FAULT_PLAN``: inline JSON or ``@file`` reference for the
    deterministic fault-injection plan; None/blank disables."""
    val = os.environ.get("HOROVOD_FAULT_PLAN")
    if not val or not val.strip():
        return None
    return val


def _env_bool(name: str, default: bool = False) -> bool:
    val = os.environ.get(name)
    if val is None:
        return default
    return val.strip().lower() not in ("", "0", "false", "no", "off")


def _env_float(name: str, default: float) -> float:
    val = os.environ.get(name)
    if val is None or not val.strip():
        return default
    try:
        return float(val)
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    val = os.environ.get(name)
    if val is None or not val.strip():
        return default
    try:
        return int(val)
    except ValueError:
        return default


@dataclasses.dataclass(frozen=True)
class Config:
    """Snapshot of all runtime knobs, read from the environment at init().

    Env-variable names intentionally match the reference (SURVEY.md §5
    "Config / flag system") so scripts written for the reference keep working.
    """

    # Tensor Fusion (reference operations.cc:1005): fused buffers up to this
    # many bytes are reduced in one collective.
    fusion_threshold_bytes: int = DEFAULT_FUSION_THRESHOLD_BYTES
    # Background controller tick, ms (reference operations.cc:1013).
    cycle_time_ms: float = DEFAULT_CYCLE_TIME_MS
    # Response-cache entries (reference global_state.h:135); 0 disables.
    cache_capacity: int = DEFAULT_CACHE_CAPACITY
    # Two-level (ICI-within-slice / DCN-across-slices) collectives, the TPU
    # analogue of reference NCCLHierarchicalAllreduce (nccl_operations.cc:167).
    hierarchical_allreduce: bool = False
    hierarchical_allgather: bool = False
    # Chrome-trace timeline output path (reference operations.cc:986-996).
    timeline_filename: Optional[str] = None
    timeline_mark_cycles: bool = False
    # Cluster-wide distributed tracing (docs/tracing.md): every rank
    # writes clock-anchored phase spans under this directory; rank 0
    # merges them (+ straggler report) at clean shutdown. TPU-era
    # extension — the reference timeline is per-rank only.
    trace_dir: Optional[str] = None
    # Stall detection (reference operations.cc:688-769).
    stall_check_disable: bool = False
    stall_check_seconds: float = DEFAULT_STALL_CHECK_SECONDS
    stall_shutdown_seconds: float = 0.0  # 0 = never force shutdown
    # Liveness: per-recv control-plane deadline (0 = no deadline) and idle
    # heartbeat period (0 = no heartbeats). See docs/fault-tolerance.md.
    comm_timeout_seconds: float = DEFAULT_COMM_TIMEOUT_SECONDS
    heartbeat_interval_seconds: float = 10.0
    # Autotuner (reference parameter_manager.cc).
    autotune: bool = False
    autotune_log: Optional[str] = None
    # Logging level name: trace/debug/info/warning/error/fatal.
    log_level: str = "warning"
    log_hide_timestamp: bool = False
    # TPU-only: dtype used on the wire for fused allreduce ("float32",
    # "bfloat16"). bfloat16 halves ICI bytes; reference's closest analogue is
    # fp16 Compression (torch/compression.py:45-74).
    tpu_reduction_dtype: Optional[str] = None

    @staticmethod
    def from_env() -> "Config":
        timeline = os.environ.get("HOROVOD_TIMELINE") or None
        autotune_log = os.environ.get("HOROVOD_AUTOTUNE_LOG") or None
        return Config(
            fusion_threshold_bytes=_env_int(
                "HOROVOD_FUSION_THRESHOLD", DEFAULT_FUSION_THRESHOLD_BYTES
            ),
            cycle_time_ms=_env_float("HOROVOD_CYCLE_TIME", DEFAULT_CYCLE_TIME_MS),
            cache_capacity=_env_int("HOROVOD_CACHE_CAPACITY", DEFAULT_CACHE_CAPACITY),
            hierarchical_allreduce=_env_bool("HOROVOD_HIERARCHICAL_ALLREDUCE"),
            hierarchical_allgather=_env_bool("HOROVOD_HIERARCHICAL_ALLGATHER"),
            timeline_filename=timeline,
            timeline_mark_cycles=_env_bool("HOROVOD_TIMELINE_MARK_CYCLES"),
            trace_dir=(os.environ.get("HOROVOD_TRACE_DIR") or "").strip()
            or None,
            stall_check_disable=_env_bool("HOROVOD_STALL_CHECK_DISABLE"),
            stall_check_seconds=_env_float(
                "HOROVOD_STALL_CHECK_TIME_SECONDS", DEFAULT_STALL_CHECK_SECONDS
            ),
            stall_shutdown_seconds=_env_float(
                "HOROVOD_STALL_SHUTDOWN_TIME_SECONDS", 0.0
            ),
            comm_timeout_seconds=comm_timeout_seconds(),
            heartbeat_interval_seconds=heartbeat_interval_seconds(),
            autotune=_env_bool("HOROVOD_AUTOTUNE"),
            autotune_log=autotune_log,
            log_level=os.environ.get("HOROVOD_LOG_LEVEL", "warning").lower(),
            log_hide_timestamp=_env_bool("HOROVOD_LOG_HIDE_TIME"),
            tpu_reduction_dtype=os.environ.get("HOROVOD_TPU_REDUCTION_DTYPE") or None,
        )
