"""Build + load the TF custom-op binding to the native engine.

Reference: the per-framework shared library the reference builds in
``setup.py`` and loads via ``tf.load_op_library`` semantics
(``horovod/tensorflow/mpi_ops.py:33-58`` ``_load_library``), plus the
gradient registrations for the three ops
(``horovod/tensorflow/mpi_ops.py:82-171``).

Like the native core (``core/bindings.py``), the library self-builds on
first use with the toolchain at hand — here against the installed
TensorFlow's headers (``tf.sysconfig``) — and everything degrades to the
``tf.py_function`` path when a piece is missing (no g++, no TF headers, or
the engine is the pure-Python controller)."""

from __future__ import annotations

import fcntl
import hashlib
import os
import subprocess
import threading
from typing import Optional

import tensorflow as tf

from ..common import hvd_logging as logging
from ..core import bindings as core_bindings

_SRC = os.path.join(os.path.dirname(os.path.abspath(__file__)), "src",
                    "tf_ops.cc")
_BUILD_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "build")

_lock = threading.Lock()
_module = None
_load_failed: Optional[str] = None
_autoname_fallback: dict = {}


def _lib_path() -> str:
    # Key the artifact on the TF version: a TF upgrade changes the ABI and
    # must produce a fresh .so (the reference rebuilds per framework install
    # the same way, setup.py probing the live TF).
    tag = hashlib.sha256(
        ("tf:" + tf.__version__).encode()).hexdigest()[:12]
    return os.path.join(_BUILD_DIR, f"libhvdtf-{tag}.so")


def build() -> str:
    """Compile the op library (idempotent, mtime-cached, flock-serialized:
    N ranks starting at once must not each spend minutes compiling against
    the TF headers on one core)."""
    os.makedirs(_BUILD_DIR, exist_ok=True)
    lib_path = _lib_path()

    def fresh() -> bool:
        return (os.path.exists(lib_path)
                and os.path.getmtime(lib_path) >= os.path.getmtime(_SRC))

    if fresh():
        return lib_path
    lock_path = lib_path + ".lock"
    with open(lock_path, "w") as lock_file:
        fcntl.flock(lock_file, fcntl.LOCK_EX)
        try:
            if fresh():  # another rank built it while we waited
                return lib_path
            flags = tf.sysconfig.get_compile_flags()
            link_flags = tf.sysconfig.get_link_flags()
            tmp_path = f"{lib_path}.tmp.{os.getpid()}"
            cmd = ["g++", "-O2", "-shared", "-fPIC", "-pthread", *flags,
                   _SRC, "-o", tmp_path, *link_flags, "-ldl"]
            logging.debug("building TF op library: %s", " ".join(cmd))
            try:
                result = subprocess.run(cmd, capture_output=True, text=True)
                if result.returncode != 0:
                    raise RuntimeError(
                        f"TF op library build failed:\n{result.stderr[-4000:]}")
                os.replace(tmp_path, lib_path)
            finally:
                if os.path.exists(tmp_path):
                    os.remove(tmp_path)
        finally:
            fcntl.flock(lock_file, fcntl.LOCK_UN)
    return lib_path


def load():
    """Build + ``tf.load_op_library``; returns the op module or ``None``
    (with the reason logged once) so callers can fall back to py_function."""
    global _module, _load_failed
    with _lock:
        if _module is not None:
            return _module
        if _load_failed is not None:
            return None
        try:
            # The op library attaches to the SAME core .so the ctypes tier
            # drives: build (or reuse) it and export its path for the
            # kernels' dlopen.
            core_path = core_bindings.build()
            os.environ["HOROVOD_TPU_CORE_LIB"] = core_path
            path = build()
            _module = tf.load_op_library(path)
        except (RuntimeError, FileNotFoundError, tf.errors.OpError) as exc:
            _load_failed = str(exc)
            logging.warning(
                "TF custom-op library unavailable (%s); collectives use the "
                "tf.py_function path", exc)
            return None
        return _module


def available() -> bool:
    return load() is not None


def _names(kind: str, name: Optional[str]) -> str:
    """Cross-rank-consistent tensor name. Explicit names pass through; for
    anonymous tensors the native controller's autoname counter is the
    namespace shared with the ctypes tier, so a custom-op collective can
    never collide with a pending controller-enqueued one.

    Inside ``tf.function`` this runs at trace time, fixing the name into the
    graph — the reference's graph-node-name behavior
    (``tensorflow/mpi_ops.py:66-80``): names repeat across step executions
    (legal: uniqueness is only required among concurrently-pending ops) and
    advance on retrace identically on every rank."""
    if name is not None:
        return name
    from ..common import basics

    try:
        return basics.controller()._autoname(kind, None)
    except (ValueError, RuntimeError):
        # No controller (size-1 smoke use, or a SavedModel reloaded before
        # hvd.init): a local counter keeps names unique within the process.
        with _lock:
            n = _autoname_fallback.get(kind, 0)
            _autoname_fallback[kind] = n + 1
        return f"{kind}.tfop.{n}"


def allreduce_sum(tensor: tf.Tensor, name: Optional[str] = None) -> tf.Tensor:
    ops = load()
    return ops.horovod_tpu_allreduce(
        tensor, tensor_name=_names("allreduce", name))


def allgather(tensor: tf.Tensor, name: Optional[str] = None) -> tf.Tensor:
    ops = load()
    return ops.horovod_tpu_allgather(
        tensor, tensor_name=_names("allgather", name))


def broadcast(tensor: tf.Tensor, root_rank: int,
              name: Optional[str] = None) -> tf.Tensor:
    ops = load()
    return ops.horovod_tpu_broadcast(
        tensor, tensor_name=_names("broadcast", name), root_rank=root_rank)


# ---------------------------------------------------------------------------
# Gradients (reference horovod/tensorflow/mpi_ops.py:82-171). Registered at
# import; they only fire when the op library loaded and a tape/graph
# differentiates through these ops.

@tf.RegisterGradient("HorovodTpuAllreduce")
def _allreduce_grad(op, grad):
    # d(sum_r x_r)/dx = 1 on every rank; the upstream grads differ per rank,
    # so the backward is itself a sum-allreduce (mpi_ops.py:82-93).
    return allreduce_sum(grad)


@tf.RegisterGradient("HorovodTpuAllgather")
def _allgather_grad(op, grad):
    # Sum grads across ranks, then slice out this rank's rows using the
    # gathered per-rank first dims (mpi_ops.py:115-138).
    from ..common import basics

    grad = allreduce_sum(grad)
    d0 = tf.shape(op.inputs[0], out_type=tf.int32)[:1]
    dims = tf.reshape(allgather(d0), [basics.size()])
    splits = tf.split(grad, num_or_size_splits=dims, axis=0)
    return splits[basics.rank()]


@tf.RegisterGradient("HorovodTpuBroadcast")
def _broadcast_grad(op, grad):
    # All grads flow to the root's input; other ranks' inputs don't affect
    # the output (mpi_ops.py:158-171).
    from ..common import basics

    root_rank = op.get_attr("root_rank")
    reduced = allreduce_sum(grad)
    if basics.rank() != root_rank:
        return reduced * 0
    return reduced


# Reference-name module surface: horovod/tensorflow/mpi_ops.py re-exports
# the lifecycle basics at module level (mpi_ops.py:42-58); keep drop-in
# imports working here too.
from ..common.basics import (  # noqa: E402,F401
    init,
    local_rank,
    local_size,
    mpi_threads_supported,
    rank,
    shutdown,
    size,
)
