"""TF-tensor gradient compression (reference
``horovod/tensorflow/compression.py``, 74 lines — same interface, plus the
TPU-native bf16)."""

from __future__ import annotations

import tensorflow as tf


class Compressor:
    @staticmethod
    def compress(tensor):
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        raise NotImplementedError


class NoneCompressor(Compressor):
    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class _CastCompressor(Compressor):
    wire_dtype = None

    @classmethod
    def compress(cls, tensor):
        ctx = tensor.dtype
        if tensor.dtype.is_floating and tensor.dtype != cls.wire_dtype:
            return tf.cast(tensor, cls.wire_dtype), ctx
        return tensor, ctx

    @classmethod
    def decompress(cls, tensor, ctx):
        if ctx is not None and tensor.dtype != ctx:
            return tf.cast(tensor, ctx)
        return tensor


class FP16Compressor(_CastCompressor):
    wire_dtype = tf.float16


class BF16Compressor(_CastCompressor):
    wire_dtype = tf.bfloat16


class Compression:
    none = NoneCompressor
    fp16 = FP16Compressor
    bf16 = BF16Compressor
