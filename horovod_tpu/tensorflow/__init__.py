"""TensorFlow user API: ``import horovod_tpu.tensorflow as hvd``.

Reference: ``horovod/tensorflow/__init__.py`` (321 lines) + the custom-op
layer ``tensorflow/mpi_ops.cc``. The reference targets TF1 graph mode
(AsyncOpKernels + SessionRunHook); this rebuild targets TF2 eager /
``tf.function`` — the op surface is the same (allreduce with the
IndexedSlices→allgather sparse path, broadcast_variables,
DistributedOptimizer, DistributedGradientTape). Collectives take the
custom-op data path when the native engine is live (real AsyncOpKernel
graph nodes, ``src/tf_ops.cc`` — reference ``tensorflow/mpi_ops.cc``
parity), falling back to ``tf.py_function`` through the shared controller
otherwise (see ``docs/migration.md`` for the boundary). For migrating TF1 session code, the v1 surface is
kept as a ``tf.compat.v1`` shim: ``broadcast_global_variables`` returns the
grouped assign op and ``BroadcastGlobalVariablesHook`` is a
``SessionRunHook`` (reference ``tensorflow/__init__.py:90-143``); TF2 eager
users should prefer ``broadcast_variables`` /
``keras.callbacks.BroadcastGlobalVariablesCallback``.
"""

from __future__ import annotations

import threading
from typing import Optional

import numpy as np
import tensorflow as tf

from ..common import basics
from ..common.basics import (  # noqa: F401
    cross_rank,
    cross_size,
    init,
    is_initialized,
    local_rank,
    local_size,
    mpi_threads_supported,
    rank,
    shutdown,
    size,
)
from ..ops.collective_ops import (  # noqa: F401  (framework-agnostic)
    allgather_object,
    barrier,
    broadcast_object,
)
from .compression import Compression  # noqa: F401

_custom_op_vote_lock = threading.Lock()


def _controller():
    return basics.controller()


def _custom_ops():
    """The native custom-op module when the fast path is live, else None.

    Fast path = real TF graph nodes (AsyncOpKernels in
    ``src/tf_ops.cc`` enqueueing into the C++ engine): no GIL on the data
    path, SavedModel-serializable, reference ``tensorflow/mpi_ops.cc``
    parity. Requires the native engine (the ops attach to the in-process
    engine the ctypes tier initialized) and an opt-out escape hatch
    ``HOROVOD_TENSORFLOW_CUSTOM_OP=0``.

    The choice is AGREED ACROSS RANKS (min over local availability via one
    controller allreduce, memoized on the controller): the custom-op path
    fixes anonymous names into graphs at trace time while the py_function
    fallback draws a fresh autoname per execution, so a mixed-path job
    (one host missing TF headers, or a per-rank opt-out) would diverge the
    name sequence and stall negotiation."""
    ctrl = _controller()
    cached = getattr(ctrl, "_tf_custom_op_agreed", None)
    if cached is not None:
        from . import tf_ops

        return tf_ops if cached else None
    # Serialize the probe+vote: two threads both missing the cache would
    # each issue the agreement collective, but peers answer it exactly once
    # (the second vote would hit the duplicate-name rejection or hang).
    with _custom_op_vote_lock:
        cached = getattr(ctrl, "_tf_custom_op_agreed", None)
        if cached is not None:
            from . import tf_ops

            return tf_ops if cached else None
        return _custom_ops_vote(ctrl)


def _custom_ops_vote(ctrl):
    from ..common.config import tensorflow_custom_op_enabled

    local_ok = True
    if not tensorflow_custom_op_enabled():
        local_ok = False
    else:
        from ..controller.native import NativeController

        if not isinstance(ctrl, NativeController):
            local_ok = False
        else:
            from . import tf_ops

            local_ok = tf_ops.available()
    agreed = bool(local_ok)
    if size() > 1:
        votes = np.asarray(ctrl.allreduce(
            np.array([1 if local_ok else 0], dtype=np.int32), average=False,
            name="hvd.tf.custom_op.agree"))
        agreed = int(votes[0]) == size()
        if local_ok and not agreed:
            from ..common import hvd_logging as logging

            logging.warning(
                "TF custom-op path disabled job-wide: another rank lacks it "
                "(build failure or HOROVOD_TENSORFLOW_CUSTOM_OP=0)")
    ctrl._tf_custom_op_agreed = agreed
    if not agreed:
        return None
    from . import tf_ops

    return tf_ops


def _np_collective(fn, tensor: tf.Tensor, out_dtype=None) -> tf.Tensor:
    """Run a controller collective on a TF tensor, staying graph-compatible:
    under tf.function the call is embedded as a py_function node (the TF2
    counterpart of the reference's AsyncOpKernel enqueue,
    tensorflow/mpi_ops.cc:276-303). Fallback path — the custom-op library
    (``_custom_ops``) is preferred when available."""
    out_dtype = out_dtype or tensor.dtype

    def runner(t):
        return tf.convert_to_tensor(fn(t.numpy()), dtype=out_dtype)

    if tf.executing_eagerly():
        return runner(tensor)
    return tf.py_function(runner, [tensor], out_dtype)


def allreduce(tensor, average: bool = True, name: Optional[str] = None,
              compression=Compression.none):
    """Mean/sum across ranks; ``tf.IndexedSlices`` take the sparse
    allgather path (reference ``tensorflow/__init__.py:36-87``)."""
    if isinstance(tensor, tf.IndexedSlices):
        # Gather values+indices everywhere; averaging divides values by size
        # (reference tensorflow/__init__.py:62-78).
        values = allgather(tensor.values,
                           name=None if name is None else f"{name}.values")
        indices = allgather(tensor.indices,
                            name=None if name is None else f"{name}.indices")
        if average:
            values = tf.cast(values, tensor.values.dtype) / size()
        return tf.IndexedSlices(values, indices,
                                dense_shape=tensor.dense_shape)
    tensor = tf.convert_to_tensor(tensor)
    if size() == 1:
        return tf.identity(tensor)
    compressed, ctx = compression.compress(tensor)
    ops = _custom_ops()
    if ops is not None:
        out = ops.allreduce_sum(compressed, name=name)
        if average and out.dtype != tf.bool:
            # Graph-level divide (reference tensorflow/__init__.py:36-87);
            # int dtypes round-trip through the division like the
            # controller's truncate-cast post-divide.
            out = tf.cast(out / size(), out.dtype)
    else:
        ctrl = _controller()
        out = _np_collective(
            lambda a: ctrl.allreduce(a, average=average, name=name),
            compressed)
    return compression.decompress(out, ctx)


def grouped_allreduce(tensors, average: bool = True,
                      name: Optional[str] = None,
                      compression=Compression.none) -> list:
    """Allreduce a list of tensors as one fusion group (later-Horovod API
    surface; executed by the same enqueue-together + Tensor Fusion path).
    In eager mode all members are enqueued before any is joined, so the
    engine sees the whole group in one cycle; inside ``tf.function`` each
    member is its own graph node — custom-op kernels when the fast path is
    live, py_function otherwise — and the executor schedules them
    concurrently, which lands them in the same engine cycle in practice."""
    if not isinstance(tensors, (list, tuple)):
        raise TypeError("grouped_allreduce expects a list/tuple of tensors")
    tensors = list(tensors)
    # Consistent across tiers and BEFORE anything is enqueued: the sparse
    # path is per-tensor allreduce() business.
    if any(isinstance(t, tf.IndexedSlices) for t in tensors):
        raise ValueError(
            "grouped_allreduce does not take IndexedSlices; use "
            "allreduce() for the sparse allgather path")
    if tf.executing_eagerly() and size() > 1:
        ctrl = _controller()
        handles = []
        for i, t in enumerate(tensors):
            tt = tf.convert_to_tensor(t)
            # Compress at the TF level (the controller's compression hooks
            # are numpy-domain; the single-tensor allreduce does the same).
            compressed, cctx = compression.compress(tt)
            h = ctrl.allreduce_async(
                compressed.numpy(), average=average,
                name=None if name is None else f"{name}.{i}")
            handles.append((compressed.dtype, cctx, h))
        return [
            compression.decompress(
                tf.convert_to_tensor(np.asarray(h.wait()), dtype=cdt), cctx)
            for cdt, cctx, h in handles
        ]
    return [
        allreduce(t, average=average,
                  name=None if name is None else f"{name}.{i}",
                  compression=compression)
        for i, t in enumerate(tensors)
    ]


def allgather(tensor, name: Optional[str] = None):
    tensor = tf.convert_to_tensor(tensor)
    if size() == 1:
        return tf.identity(tensor)
    ops = _custom_ops()
    if ops is not None:
        return ops.allgather(tensor, name=name)
    ctrl = _controller()
    return _np_collective(lambda a: ctrl.allgather(a, name=name), tensor)


def broadcast(tensor, root_rank: int, name: Optional[str] = None):
    tensor = tf.convert_to_tensor(tensor)
    if size() == 1:
        if root_rank != 0:
            raise ValueError(f"root_rank {root_rank} out of range for size 1")
        return tf.identity(tensor)
    if not 0 <= root_rank < size():
        # Fail fast on every rank; an out-of-range root passes validation
        # (all ranks agree on it) and would hang the data phase.
        raise ValueError(
            f"root_rank {root_rank} out of range for size {size()}")
    ops = _custom_ops()
    if ops is not None:
        return ops.broadcast(tensor, root_rank=root_rank, name=name)
    ctrl = _controller()
    return _np_collective(
        lambda a: ctrl.broadcast(a, root_rank=root_rank, name=name), tensor)


def broadcast_variables(variables, root_rank: int = 0) -> None:
    """Assign every variable root's value (reference
    ``broadcast_global_variables``/``broadcast_variables``,
    ``tensorflow/__init__.py:90-109``), async-enqueued then joined so the
    fusion engine can pack them."""
    variables = list(variables)
    if size() == 1:
        if root_rank != 0:
            raise ValueError(f"root_rank {root_rank} out of range for size 1")
        return
    ctrl = _controller()
    handles = [
        ctrl.broadcast_async(v.numpy(), root_rank=root_rank,
                             name=f"broadcast.var.{i}")
        for i, v in enumerate(variables)
    ]
    for v, h in zip(variables, handles):
        v.assign(tf.convert_to_tensor(np.asarray(h.wait()), dtype=v.dtype))


def _broadcast_group_op(variables, root_rank: int):
    """Grouped assign op: every variable takes root's value. Graph-mode
    analogue of :func:`broadcast_variables` (the reference builds the same
    ``tf.group`` of assigns, ``tensorflow/__init__.py:100-109``)."""
    return tf.group(*[
        v.assign(broadcast(v, root_rank=root_rank,
                           name=f"broadcast.gvar.{i}"))
        for i, v in enumerate(variables)
    ])


def broadcast_global_variables(root_rank: int = 0):
    """TF1-compat (reference ``tensorflow/__init__.py:90-98``): broadcast
    the ``tf.compat.v1`` global-variables collection from ``root_rank``,
    returning the grouped assign op to run in your session. Only meaningful
    under the v1 graph stack — TF2 eager has no global collection; call
    ``broadcast_variables(model.variables, root_rank)`` there."""
    gvars = tf.compat.v1.global_variables()
    if tf.executing_eagerly() or not gvars:
        raise NotImplementedError(
            "no tf.compat.v1 global-variables collection is active; in "
            "TF2 eager call hvd.broadcast_variables(model.variables, "
            "root_rank) instead (session users: build the model inside a "
            "tf.compat.v1 graph so variables register in the collection, "
            "or use hvd.BroadcastGlobalVariablesHook)")
    return _broadcast_group_op(gvars, root_rank)


class BroadcastGlobalVariablesHook(tf.compat.v1.train.SessionRunHook):
    """``SessionRunHook`` broadcasting all global variables from
    ``root_rank`` when the session is created — the TF1 checkpoint/resume
    consistency contract (reference ``tensorflow/__init__.py:112-143``).

    ``device`` is accepted for signature parity and ignored: collective
    placement is the controller's concern here, not a graph device string.
    """

    def __init__(self, root_rank: int = 0, device: str = ""):
        super().__init__()
        self.root_rank = root_rank
        self.bcast_op = None
        self.device = device

    def begin(self):
        # Rebuild if a new graph is active (reference :130-134).
        if (self.bcast_op is None
                or self.bcast_op.graph is not tf.compat.v1.get_default_graph()):
            self.bcast_op = broadcast_global_variables(self.root_rank)

    def after_create_session(self, session, coord):
        session.run(self.bcast_op)


class DistributedGradientTape(tf.GradientTape):
    """``tf.GradientTape`` whose ``gradient()`` averages grads across ranks
    (reference ``tensorflow/__init__.py:247-321``)."""

    def __init__(self, *args, compression=Compression.none,
                 device_dense="", device_sparse="", **kwargs):
        super().__init__(*args, **kwargs)
        self._compression = compression

    def gradient(self, target, sources, output_gradients=None, **kwargs):
        grads = super().gradient(target, sources, output_gradients, **kwargs)
        if size() == 1:
            return grads
        return [
            allreduce(g, average=True, name=f"DistributedGradientTape.{i}",
                      compression=self._compression)
            if g is not None else None
            for i, g in enumerate(grads)
        ]


def _distributed_optimizer_class(base, compression=Compression.none):
    """Subclass ``base`` (a keras optimizer class) so ``apply_gradients``
    first averages the gradients across ranks. Class-level seam shared by
    :func:`DistributedOptimizer` (wraps an instance) and
    ``keras.load_model`` (wraps classes for deserialization, reference
    ``_keras/__init__.py:93-109``)."""

    class _Distributed(base):
        def apply_gradients(self, grads_and_vars, *args, **kwargs):
            grads_and_vars = list(grads_and_vars)
            if size() > 1:
                grads_and_vars = [
                    (allreduce(g, average=True,
                               name=f"DistributedOptimizer.grad.{i}",
                               compression=compression), v)
                    if g is not None else (g, v)
                    for i, (g, v) in enumerate(grads_and_vars)
                ]
            return super().apply_gradients(grads_and_vars, *args, **kwargs)

    _Distributed.__name__ = f"Distributed{base.__name__}"
    _Distributed._hvd_distributed = True  # keras.load_model double-wrap guard
    return _Distributed


def DistributedOptimizer(optimizer, name: Optional[str] = None,
                         compression=Compression.none,
                         device_dense: str = "", device_sparse: str = "",
                         backward_passes_per_step: int = 1):
    """Wrap a keras optimizer so ``apply_gradients`` first averages the
    gradients across ranks (reference ``tensorflow/__init__.py:146-244``;
    the reference overrides ``compute_gradients`` on TF1 optimizers — the
    Keras-3 equivalent seam is ``apply_gradients``)."""
    if backward_passes_per_step != 1:
        raise ValueError(
            "backward_passes_per_step > 1 is not supported on the TF tier; "
            "use hvd.torch or hvd.jax for local gradient accumulation")

    cls = _distributed_optimizer_class(optimizer.__class__, compression)
    return cls.from_config(optimizer.get_config())
