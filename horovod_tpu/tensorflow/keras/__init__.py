"""``import horovod_tpu.tensorflow.keras as hvd`` — parity alias for the
reference's ``horovod/tensorflow/keras`` package (same shared impl as
``horovod_tpu.keras``)."""

from ...keras import (  # noqa: F401
    Compression,
    DistributedOptimizer,
    allgather,
    allgather_object,
    allreduce,
    barrier,
    broadcast,
    broadcast_global_variables,
    broadcast_object,
    broadcast_variables,
    callbacks,
    cross_rank,
    cross_size,
    init,
    is_initialized,
    load_model,
    local_rank,
    local_size,
    mpi_threads_supported,
    rank,
    shutdown,
    size,
)
