// TensorFlow custom-op binding for the native eager engine.
//
// Reference: horovod/tensorflow/mpi_ops.cc (466 lines) — three AsyncOpKernels
// (HorovodAllreduce/Allgather/Broadcast, registered at mpi_ops.cc:306-463)
// that enqueue into the background coordinator and fire TF's `done` callback
// from the completion path. This rebuild keeps that architecture but targets
// the TPU-native engine (core/src/engine.cc): the kernel enqueues through the
// same C ABI the ctypes tier uses (`hvd_eng_enqueue`/`hvd_eng_wait`), and a
// small waiter pool plays the role of the reference's detached finalizer
// thread (common/ops/cuda_operations.cc:148-178), joining engine handles and
// resuming the TF executor off the hot path.
//
// Unlike the tf.py_function fallback (tensorflow/__init__.py), these ops are
// real graph nodes: no GIL on the data path, SavedModel-serializable, and
// usable from any TF executor thread.
//
// The engine is initialized by Python (`hvd.init()` → NativeController);
// this library attaches to the already-loaded core .so by dlopen'ing the
// path exported in HOROVOD_TPU_CORE_LIB (dlopen of an already-mapped
// library returns the same handle, so both tiers drive one engine).

#include <dlfcn.h>

#include <algorithm>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <functional>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include "tensorflow/core/framework/op.h"
#include "tensorflow/core/framework/op_kernel.h"
#include "tensorflow/core/framework/shape_inference.h"
#include "tensorflow/core/platform/errors.h"

namespace hvd_tpu {

using ::tensorflow::AsyncOpKernel;
using ::tensorflow::DataType;
using ::tensorflow::OpKernelConstruction;
using ::tensorflow::OpKernelContext;
using ::tensorflow::Tensor;
using ::tensorflow::TensorShape;
using ::tensorflow::errors::FailedPrecondition;
using ::tensorflow::errors::InvalidArgument;
using ::tensorflow::errors::Unknown;

// ---------------------------------------------------------------------------
// Core-engine C ABI, resolved at runtime (see module docstring).

struct CoreApi {
  // Trailing void* is the round-10 int8 error-feedback residual slot;
  // the TF tier never compensates (no per-tensor residual store here),
  // so it always passes nullptr — but the POINTER TYPE must match the
  // core's 9-arg ABI or the callee reads a garbage residual off the
  // stack (hvdabi pins every fn-pointer type here against engine.cc).
  long long (*enqueue)(int, const char*, void*, const long long*, int, int,
                       int, void*, int) = nullptr;
  int (*wait)(long long) = nullptr;
  int (*result_ndim)(long long) = nullptr;
  void (*result_shape)(long long, long long*) = nullptr;
  int (*result_dtype)(long long) = nullptr;
  int (*result_copy)(long long, void*) = nullptr;
  int (*result_in_place)(long long) = nullptr;
  const char* (*handle_error)(long long) = nullptr;
  void (*release)(long long) = nullptr;
  const char* (*last_error)() = nullptr;
  std::string init_error;
  bool ok = false;
};

CoreApi* Api() {
  static CoreApi* api = [] {
    auto* a = new CoreApi();
    const char* path = getenv("HOROVOD_TPU_CORE_LIB");
    if (path == nullptr || *path == '\0') {
      a->init_error =
          "HOROVOD_TPU_CORE_LIB is not set; load this library through "
          "horovod_tpu.tensorflow (which exports the core .so path before "
          "tf.load_op_library)";
      return a;
    }
    void* h = dlopen(path, RTLD_NOW | RTLD_LOCAL);
    if (h == nullptr) {
      a->init_error = std::string("dlopen of core library failed: ") +
                      dlerror();
      return a;
    }
    auto sym = [&](const char* name) -> void* {
      void* s = dlsym(h, name);
      if (s == nullptr && a->init_error.empty())
        a->init_error = std::string("missing core symbol ") + name;
      return s;
    };
    a->enqueue = reinterpret_cast<decltype(a->enqueue)>(sym("hvd_eng_enqueue"));
    a->wait = reinterpret_cast<decltype(a->wait)>(sym("hvd_eng_wait"));
    a->result_ndim =
        reinterpret_cast<decltype(a->result_ndim)>(sym("hvd_eng_result_ndim"));
    a->result_shape = reinterpret_cast<decltype(a->result_shape)>(
        sym("hvd_eng_result_shape"));
    a->result_dtype = reinterpret_cast<decltype(a->result_dtype)>(
        sym("hvd_eng_result_dtype"));
    a->result_copy =
        reinterpret_cast<decltype(a->result_copy)>(sym("hvd_eng_result_copy"));
    a->result_in_place = reinterpret_cast<decltype(a->result_in_place)>(
        sym("hvd_eng_result_in_place"));
    a->handle_error = reinterpret_cast<decltype(a->handle_error)>(
        sym("hvd_eng_handle_error"));
    a->release =
        reinterpret_cast<decltype(a->release)>(sym("hvd_eng_release"));
    a->last_error =
        reinterpret_cast<decltype(a->last_error)>(sym("hvd_eng_last_error"));
    a->ok = a->init_error.empty();
    return a;
  }();
  return api;
}

// Engine dtype codes (must match DType in core/src/ring.cc and
// core/bindings.py _DTYPE_CODES).
int DtypeCode(DataType d) {
  switch (d) {
    case ::tensorflow::DT_FLOAT: return 0;
    case ::tensorflow::DT_DOUBLE: return 1;
    case ::tensorflow::DT_INT32: return 2;
    case ::tensorflow::DT_INT64: return 3;
    case ::tensorflow::DT_UINT8: return 4;
    case ::tensorflow::DT_HALF: return 5;
    case ::tensorflow::DT_BFLOAT16: return 6;
    case ::tensorflow::DT_INT8: return 7;
    case ::tensorflow::DT_INT16: return 8;
    case ::tensorflow::DT_UINT16: return 9;
    case ::tensorflow::DT_BOOL: return 10;
    default: return -1;
  }
}

// ---------------------------------------------------------------------------
// Waiter pool: the completion side of the reference's AsyncOpKernel design.
// ComputeAsync enqueues into the engine and returns immediately; these
// threads block in hvd_eng_wait (engine cv, no polling), then run the
// finalizer (copy result / set status) and fire TF's `done`. FIFO matches
// the engine's cycle-ordered completion closely enough; a head-of-line wait
// never deadlocks because engine progress doesn't depend on waiters.

class Waiter {
 public:
  static Waiter& Get() {
    static Waiter* w = new Waiter();  // leaked: process-lifetime threads
    return *w;
  }

  // `finalize(rc)` runs on a waiter thread after the engine resolves the
  // handle; it must release the handle itself (so it can read the result
  // slot first) and must end by calling the op's done callback.
  void Submit(long long handle, std::function<void(int)> finalize) {
    {
      std::lock_guard<std::mutex> l(mu_);
      queue_.push_back({handle, std::move(finalize)});
    }
    cv_.notify_one();
  }

 private:
  struct Item {
    long long handle;
    std::function<void(int)> finalize;
  };

  Waiter() {
    for (int i = 0; i < 2; i++) {
      std::thread([this] { Loop(); }).detach();
    }
  }

  void Loop() {
    for (;;) {
      Item item;
      {
        std::unique_lock<std::mutex> l(mu_);
        cv_.wait(l, [this] { return !queue_.empty(); });
        item = std::move(queue_.front());
        queue_.pop_front();
      }
      int rc = Api()->wait(item.handle);
      item.finalize(rc);
    }
  }

  std::mutex mu_;
  std::condition_variable cv_;
  std::deque<Item> queue_;
};

// Shared ComputeAsync plumbing. `op` is the engine op code (0 allreduce,
// 1 allgather, 2 broadcast).
constexpr int kAllreduce = 0;
constexpr int kAllgather = 1;
constexpr int kBroadcast = 2;

long long EnqueueOrFail(OpKernelContext* ctx,
                        AsyncOpKernel::DoneCallback& done, int op,
                        const std::string& name, void* data,
                        const Tensor& shaped_like, int root_rank) {
  CoreApi* api = Api();
  if (!api->ok) {
    ctx->SetStatus(FailedPrecondition(api->init_error));
    done();
    return -1;
  }
  int code = DtypeCode(shaped_like.dtype());
  if (code < 0) {
    ctx->SetStatus(InvalidArgument(
        "dtype ", ::tensorflow::DataTypeString(shaped_like.dtype()),
        " is not supported by the native engine"));
    done();
    return -1;
  }
  int ndim = shaped_like.dims();
  std::vector<long long> dims(std::max(ndim, 1), 0);
  for (int i = 0; i < ndim; i++) dims[i] = shaped_like.dim_size(i);
  long long h = api->enqueue(op, name.c_str(), data, dims.data(), ndim, code,
                             root_rank, nullptr, /*priority=*/0);
  if (h == -2) {
    ctx->SetStatus(InvalidArgument(
        "Duplicate tensor name '", name,
        "': a collective with this name is already pending; names must be "
        "unique until the operation completes."));
    done();
    return -1;
  }
  if (h == -3) {
    // Engine enqueue's closed/shutdown code (engine.cc enqueue); the
    // ctypes tier maps this to ShutdownError the same way (native.py).
    ctx->SetStatus(FailedPrecondition("Horovod has been shut down"));
    done();
    return -1;
  }
  if (h < 0) {
    ctx->SetStatus(FailedPrecondition(
        "engine enqueue failed (", api->last_error(),
        "); has hvd.init() run with the native engine?"));
    done();
    return -1;
  }
  return h;
}

// Finalizer for the in-place ops (allreduce/broadcast): the engine wrote the
// result directly into the output tensor's buffer, so success needs no copy.
void FinishInPlace(OpKernelContext* ctx, AsyncOpKernel::DoneCallback done,
                   long long handle, int rc) {
  CoreApi* api = Api();
  if (rc != 0) {
    ctx->SetStatus(Unknown(api->handle_error(handle)));
  }
  api->release(handle);
  done();
}

class AllreduceKernel : public AsyncOpKernel {
 public:
  explicit AllreduceKernel(OpKernelConstruction* ctx) : AsyncOpKernel(ctx) {
    OP_REQUIRES_OK(ctx, ctx->GetAttr("tensor_name", &tensor_name_));
  }

  void ComputeAsync(OpKernelContext* ctx, DoneCallback done) override {
    const Tensor& input = ctx->input(0);
    Tensor* output = nullptr;
    // Reuse the input buffer when TF lets us (refcount 1): the engine
    // reduces in place, so forwarding makes the whole op zero-copy
    // (reference fused-buffer memcpy avoidance, mpi_operations.cc:40-49).
    OP_REQUIRES_OK_ASYNC(
        ctx,
        ctx->forward_input_or_allocate_output({0}, 0, input.shape(), &output),
        done);
    if (output->data() != input.data() && input.TotalBytes() > 0) {
      std::memcpy(output->data(), input.data(), input.TotalBytes());
    }
    const std::string name =
        tensor_name_.empty() ? std::string(this->name()) : tensor_name_;
    long long h =
        EnqueueOrFail(ctx, done, kAllreduce, name, output->data(), *output,
                      /*root_rank=*/-1);
    if (h < 0) return;  // status set + done called
    Waiter::Get().Submit(h, [ctx, done, h](int rc) {
      FinishInPlace(ctx, done, h, rc);
    });
  }

 private:
  std::string tensor_name_;
};

class AllgatherKernel : public AsyncOpKernel {
 public:
  explicit AllgatherKernel(OpKernelConstruction* ctx) : AsyncOpKernel(ctx) {
    OP_REQUIRES_OK(ctx, ctx->GetAttr("tensor_name", &tensor_name_));
  }

  void ComputeAsync(OpKernelContext* ctx, DoneCallback done) override {
    // The engine reads the input buffer asynchronously; capturing the
    // Tensor (refcounted) in the finalizer keeps it alive until the handle
    // resolves — the _handle_map contract (torch/mpi_ops.py:54).
    Tensor input = ctx->input(0);
    const std::string name =
        tensor_name_.empty() ? std::string(this->name()) : tensor_name_;
    long long h = EnqueueOrFail(ctx, done, kAllgather, name, input.data(),
                                input, /*root_rank=*/-1);
    if (h < 0) return;
    Waiter::Get().Submit(h, [ctx, done, h, input](int rc) {
      CoreApi* api = Api();
      if (rc != 0) {
        ctx->SetStatus(Unknown(api->handle_error(h)));
        api->release(h);
        done();
        return;
      }
      // Output first-dim is only known after negotiation (the response
      // carries every rank's first dim, message.h Response): allocate the
      // TF output now, from the completion thread — exactly how the
      // reference allocates through TFOpContext from the coordinator
      // (tensorflow/mpi_ops.cc:225-258).
      int ndim = api->result_ndim(h);
      std::vector<long long> dims(std::max(ndim, 1), 0);
      api->result_shape(h, dims.data());
      TensorShape shape;
      for (int i = 0; i < ndim; i++) shape.AddDim(dims[i]);
      Tensor* output = nullptr;
      ::tensorflow::Status s = ctx->allocate_output(0, shape, &output);
      if (s.ok() && output->TotalBytes() > 0) {
        api->result_copy(h, output->data());
      }
      if (!s.ok()) ctx->SetStatus(s);
      api->release(h);
      done();
    });
  }

 private:
  std::string tensor_name_;
};

class BroadcastKernel : public AsyncOpKernel {
 public:
  explicit BroadcastKernel(OpKernelConstruction* ctx) : AsyncOpKernel(ctx) {
    OP_REQUIRES_OK(ctx, ctx->GetAttr("tensor_name", &tensor_name_));
    OP_REQUIRES_OK(ctx, ctx->GetAttr("root_rank", &root_rank_));
  }

  void ComputeAsync(OpKernelContext* ctx, DoneCallback done) override {
    const Tensor& input = ctx->input(0);
    Tensor* output = nullptr;
    OP_REQUIRES_OK_ASYNC(
        ctx,
        ctx->forward_input_or_allocate_output({0}, 0, input.shape(), &output),
        done);
    if (output->data() != input.data() && input.TotalBytes() > 0) {
      std::memcpy(output->data(), input.data(), input.TotalBytes());
    }
    const std::string name =
        tensor_name_.empty() ? std::string(this->name()) : tensor_name_;
    long long h = EnqueueOrFail(ctx, done, kBroadcast, name, output->data(),
                                *output, root_rank_);
    if (h < 0) return;
    Waiter::Get().Submit(h, [ctx, done, h](int rc) {
      FinishInPlace(ctx, done, h, rc);
    });
  }

 private:
  std::string tensor_name_;
  int root_rank_;
};

// ---------------------------------------------------------------------------
// Op registry. Same surface as the reference (tensorflow/mpi_ops.cc:313-463)
// — allreduce is SUM (averaging is a graph-level divide, reference
// tensorflow/__init__.py:36-87) — widened to every engine dtype (the
// reference's MPI type table stops at the MPI basics; the ring kernels
// cover int8/uint16/bool/bfloat16 too, ring.cc DType).

#define HVD_NUMERIC_TYPES \
  "{int8, int16, int32, int64, uint8, uint16, float16, bfloat16, float32, " \
  "float64, bool}"

REGISTER_OP("HorovodTpuAllreduce")
    .Attr("T: " HVD_NUMERIC_TYPES)
    .Attr("tensor_name: string = ''")
    .Input("tensor: T")
    .Output("sum: T")
    .SetShapeFn([](::tensorflow::shape_inference::InferenceContext* c) {
      c->set_output(0, c->input(0));
      return ::tensorflow::OkStatus();
    })
    .Doc("Sum `tensor` across all horovod_tpu ranks (bool: logical OR).");

REGISTER_OP("HorovodTpuAllgather")
    .Attr("T: " HVD_NUMERIC_TYPES)
    .Attr("tensor_name: string = ''")
    .Input("tensor: T")
    .Output("output: T")
    .SetShapeFn([](::tensorflow::shape_inference::InferenceContext* c) {
      ::tensorflow::shape_inference::ShapeHandle output;
      TF_RETURN_IF_ERROR(
          c->ReplaceDim(c->input(0), 0, c->UnknownDim(), &output));
      c->set_output(0, output);
      return ::tensorflow::OkStatus();
    })
    .Doc("Concatenate `tensor` from all ranks along dimension 0; ranks may "
         "differ in the first dimension only.");

REGISTER_OP("HorovodTpuBroadcast")
    .Attr("T: " HVD_NUMERIC_TYPES)
    .Attr("tensor_name: string = ''")
    .Attr("root_rank: int")
    .Input("tensor: T")
    .Output("output: T")
    .SetShapeFn([](::tensorflow::shape_inference::InferenceContext* c) {
      c->set_output(0, c->input(0));
      return ::tensorflow::OkStatus();
    })
    .Doc("Broadcast `tensor` from `root_rank` to all ranks.");

// One registration per op covers every allowed T: the kernels branch on the
// runtime dtype (DtypeCode), so no TypeConstraint fan-out is needed.
REGISTER_KERNEL_BUILDER(
    Name("HorovodTpuAllreduce").Device(::tensorflow::DEVICE_CPU),
    AllreduceKernel);
REGISTER_KERNEL_BUILDER(
    Name("HorovodTpuAllgather").Device(::tensorflow::DEVICE_CPU),
    AllgatherKernel);
REGISTER_KERNEL_BUILDER(
    Name("HorovodTpuBroadcast").Device(::tensorflow::DEVICE_CPU),
    BroadcastKernel);

}  // namespace hvd_tpu
