"""``horovodrun`` for TPU clusters.

Reference: ``horovod/run/run.py`` (489 lines) — parses ``-np``/``-H``, does an
ssh preflight, discovers routable NICs via driver/task TCP services, then
execs ``mpirun`` which fans out ranks via orted. On TPU none of the MPI
machinery exists; the launcher's jobs reduce to:

  1. mint a per-job HMAC secret and pick the coordinator address,
  2. for remote hosts: cached ssh preflight (reference ``run/run.py:46-102``),
  3. start one process per rank with the topology exported in env
     (``HOROVOD_RANK/SIZE/LOCAL_RANK/LOCAL_SIZE/CONTROLLER_ADDR/SECRET_KEY``),
  4. stream rank-prefixed output, propagate failures, kill stragglers.

Local ranks are direct children; remote hosts (``-H host:slots``) fan out
over ssh with the env inlined (the reference's ``-x VAR`` passthrough,
``run/run.py:462-480``). On a TPU pod slice you typically run one process
per host and let the SPMD tier drive all local chips; ``--bind-chips``
instead partitions the host's chips among local ranks via
``TPU_VISIBLE_DEVICES`` (one-chip-per-process, the reference's
one-GPU-per-rank model).
"""

from __future__ import annotations

import argparse
import os
import shlex
import signal
import socket
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..common import config as config_mod
from ..common.wire import make_secret
from .. import metrics

_m = None


def _launcher_metrics():
    global _m
    if _m is None:
        from types import SimpleNamespace

        _m = SimpleNamespace(restarts=metrics.counter(
            "hvd_launcher_restarts_total",
            "Supervised relaunches performed by horovodrun "
            "--max-restarts."))
    return _m


def parse_hosts(hosts: Optional[str], np_: int) -> List[Tuple[str, int]]:
    """Parse ``-H host1:2,host2:2`` (reference ``run/run.py:285-342``)."""
    if not hosts:
        return [("localhost", np_)]
    out = []
    for part in hosts.split(","):
        part = part.strip()
        if not part:
            continue
        host, _, slots = part.partition(":")
        out.append((host, int(slots) if slots else 1))
    total = sum(s for _, s in out)
    if total < np_:
        raise ValueError(
            f"-np {np_} exceeds total slots {total} in -H {hosts!r}")
    return out


def _free_port() -> int:
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _derived_port(base: int, offset: int) -> int:
    """Map a base+offset onto a valid port regardless of where the ephemeral
    base landed (remote-host heuristic; override env vars if it clashes)."""
    return 20000 + (base + offset) % 40000


def _is_local(host: str) -> bool:
    return host in ("localhost", "127.0.0.1", socket.gethostname())


def build_rank_env(base: Dict[str, str], rank: int, size: int,
                   local_rank: int, local_size: int, cross_rank: int,
                   cross_size: int, controller_addr: str, secret: str,
                   bind_chips: bool, spmd: bool = False,
                   restart_epoch: int = 0, elastic: bool = False,
                   min_ranks: int = 1, max_ranks: int = 0,
                   elastic_join: bool = False) -> Dict[str, str]:
    env = dict(base)
    env.update({
        "HOROVOD_RANK": str(rank),
        "HOROVOD_SIZE": str(size),
        "HOROVOD_LOCAL_RANK": str(local_rank),
        "HOROVOD_LOCAL_SIZE": str(local_size),
        "HOROVOD_CROSS_RANK": str(cross_rank),
        "HOROVOD_CROSS_SIZE": str(cross_size),
        "HOROVOD_SECRET_KEY": secret,
        # Supervision attempt number (--max-restarts): training scripts
        # key restart-vs-fresh on this (utils.checkpoint.restart_epoch()).
        "HOROVOD_RESTART_EPOCH": str(restart_epoch),
    })
    if elastic:
        # Elastic membership (docs/elastic.md): pin the python controller
        # engine (the ring data planes are fixed-membership) and scrub any
        # inherited ring endpoints so no rank tries to build one.
        env.update({
            "HOROVOD_ELASTIC": "1",
            "HOROVOD_ELASTIC_MIN_RANKS": str(min_ranks),
            "HOROVOD_ELASTIC_MAX_RANKS": str(max_ranks),
            "HOROVOD_ENGINE": "python",
        })
        for var in ("HOROVOD_RING_ADDRS", "HOROVOD_LOCAL_RING_ADDRS",
                    "HOROVOD_CROSS_RING_ADDRS"):
            env.pop(var, None)
        if elastic_join:
            env["HOROVOD_ELASTIC_JOIN"] = "1"
        else:
            # A fresh (rendezvous) rank must not inherit a stale join flag
            # from the launcher's own environment.
            env.pop("HOROVOD_ELASTIC_JOIN", None)
    else:
        env.pop("HOROVOD_ELASTIC", None)
        env.pop("HOROVOD_ELASTIC_JOIN", None)
    # Ranks we spawn watch their parent and die when orphaned (local: this
    # launcher; remote: the ssh session's shell). HOROVOD_PARENT_WATCHDOG=0
    # in the launcher's env opts out and is inherited via `base`.
    env.setdefault("HOROVOD_PARENT_WATCHDOG", "1")
    if spmd:
        # SPMD multi-host mode: ranks join the JAX distributed runtime and
        # every process sees the global device set; no eager controller.
        # Scrub any eager-tier endpoints inherited from the launcher's own
        # environment or the worker would also try to join a stale TCP ring.
        env.pop("HOROVOD_CONTROLLER_ADDR", None)
        env.pop("HOROVOD_RING_ADDRS", None)
        env.pop("HOROVOD_ENGINE", None)
        env["HOROVOD_SPMD_COORDINATOR"] = controller_addr
    else:
        env["HOROVOD_CONTROLLER_ADDR"] = controller_addr
    if bind_chips:
        env["TPU_VISIBLE_DEVICES"] = str(local_rank)
        env["TPU_PROCESS_BOUNDS"] = f"1,1,1"
    return env


_SSH_CACHE = os.path.expanduser("~/.horovod_tpu/ssh_preflight.json")
_SSH_CACHE_TTL_S = 300.0


def _boot_id() -> str:
    """Scope for on-disk monotonic stamps: CLOCK_MONOTONIC is only
    comparable within one boot, so the cache records which boot wrote it
    and entries from any other boot are discarded wholesale."""
    try:
        with open("/proc/sys/kernel/random/boot_id") as f:
            return f.read().strip()
    except OSError:
        return "unknown-boot"


def ssh_preflight(hosts: List[str], ssh_port: int = 22,
                  use_cache: bool = True, timeout: float = 10.0) -> None:
    """Verify passwordless ssh to every remote host before fanning out
    (reference ``run/run.py:46-102``: threaded check with an on-disk cache
    so repeated launches skip it). Raises RuntimeError listing unreachable
    hosts; successes are cached for five minutes."""
    import json

    cache: Dict[str, float] = {}
    # Monotonic, not wall clock: an NTP step mid-TTL would expire (or
    # revive) entries spuriously. CLOCK_MONOTONIC is boot-relative and
    # only comparable within one boot, so the file carries the writing
    # boot's id and a mismatch discards it entirely (a pre-reboot stamp
    # can otherwise look in-TTL once uptime catches up). The 0 <= age
    # guard additionally drops stamps from the future within a boot.
    now = time.monotonic()
    boot = _boot_id()
    if use_cache:
        try:
            with open(_SSH_CACHE) as f:
                data = json.load(f)
            entries = (data.get("entries", {})
                       if data.get("boot_id") == boot else {})
            # Pre-boot_id cache files (a bare dict) hold wall-clock or
            # foreign-boot stamps: treat as empty, it's a 5-minute cache.
            cache = {h: t for h, t in entries.items()
                     if 0 <= now - t < _SSH_CACHE_TTL_S}
        except (OSError, ValueError, AttributeError):
            cache = {}

    # Cache key includes the port: success on 22 says nothing about 2222.
    def key(h):
        return f"{h}:{ssh_port}"

    to_check = [h for h in hosts if not _is_local(h) and key(h) not in cache]
    failures: Dict[str, str] = {}
    lock = threading.Lock()

    def check(host):
        try:
            res = subprocess.run(
                ["ssh", "-o", "StrictHostKeyChecking=no", "-o",
                 "BatchMode=yes", "-o", f"ConnectTimeout={int(timeout)}",
                 "-p", str(ssh_port), host, "true"],
                capture_output=True, text=True, timeout=timeout + 5)
            ok, msg = res.returncode == 0, (res.stderr or res.stdout).strip()
        except Exception as exc:  # missing ssh binary, subprocess timeout
            ok, msg = False, str(exc)
        with lock:
            if ok:
                cache[key(host)] = now
            else:
                failures[host] = msg

    # daemon=False on purpose: the preflight's join IS the launch gate.
    threads = [threading.Thread(target=check, args=(h,),
                                name=f"hvd-ssh-preflight-{h}", daemon=False)
               for h in to_check]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    if use_cache and cache:
        try:
            os.makedirs(os.path.dirname(_SSH_CACHE), exist_ok=True)
            with open(_SSH_CACHE, "w") as f:
                json.dump({"boot_id": boot, "entries": cache}, f)
        except OSError:
            pass
    if failures:
        detail = "; ".join(f"{h}: {msg or 'ssh failed'}"
                           for h, msg in sorted(failures.items()))
        raise RuntimeError(
            f"ssh preflight failed for {sorted(failures)} — passwordless "
            f"ssh is required for remote hosts ({detail})")


def discover_routable_addrs(hosts: List[str], ssh_port: int, secret: str,
                            timeout: float = 60.0) -> Optional[Dict[str, str]]:
    """Ring-probe every host's interfaces and return {host: routable_ip}
    (reference NIC discovery, ``run/run.py:105-256``): a probe task runs on
    each host (ssh for remote, a thread locally), dials every advertised
    interface of the next host, and the driver keeps, per host, an address
    its predecessor proved reachable. Returns None if discovery can't
    complete — callers fall back to the ``-H`` names."""
    from . import task_fn as task_fn_module
    from .nic_discovery import NICDriverService, list_interfaces, \
        run_probe_task

    if len(hosts) < 2:
        return None
    driver = NICDriverService(len(hosts), timeout=timeout)
    # Remote tasks dial every candidate concurrently; loopback is useless to
    # them (and could even connect to the WRONG host's bound port).
    candidates = [ip for _, ip in list_interfaces()
                  if not ip.startswith("127.")] \
        or [ip for _, ip in list_interfaces()]
    driver_addrs = ",".join(f"{ip}:{driver.port}" for ip in candidates)
    procs: List[Tuple[str, subprocess.Popen, List[str]]] = []
    threads: List[threading.Thread] = []
    thread_errors: List[str] = []
    try:
        for i, host in enumerate(hosts):
            if _is_local(host):
                def _local_probe(idx=i):
                    try:
                        run_probe_task(idx, f"127.0.0.1:{driver.port}")
                    except Exception as exc:  # checked by the poll loop
                        thread_errors.append(f"local probe {idx}: {exc}")

                t = threading.Thread(target=_local_probe,
                                     name=f"hvd-nic-probe-{i}", daemon=True)
                t.start()
                threads.append(t)
            else:
                # The standalone probe script rides ssh stdin (python -):
                # the remote host needs no horovod_tpu checkout and pays no
                # package import to enumerate its NICs.
                remote = (f"env HOROVOD_SECRET_KEY={shlex.quote(secret)} "
                          f"python3 - {i} {driver_addrs}")
                # close the script handle once Popen has dup'd it into the
                # child — otherwise one fd leaks per remote host per run.
                with open(task_fn_module.__file__) as script:
                    p = subprocess.Popen(
                        ["ssh", "-o", "StrictHostKeyChecking=no",
                         "-p", str(ssh_port), host, remote],
                        stdin=script,
                        stdout=subprocess.DEVNULL, stderr=subprocess.PIPE,
                        text=True)
                # Drain stderr continuously: a chatty remote interpreter
                # must not wedge on a full pipe mid-protocol.
                buf: List[str] = []
                threading.Thread(target=lambda p=p, b=buf: b.extend(
                    iter(p.stderr.readline, "")),
                    name=f"hvd-nic-stderr-{host}", daemon=True).start()
                procs.append((host, p, buf))
        # Poll instead of blocking: a probe that dies instantly (no remote
        # python3, auth failure) should fail the discovery now, with its
        # stderr, not after the full timeout.
        deadline = time.monotonic() + timeout
        while not driver.done():
            if thread_errors:
                sys.stderr.write(
                    f"horovodrun: NIC {thread_errors[0]}; falling back to "
                    "-H host names\n")
                return None
            for host, p, buf in procs:
                if p.poll() not in (None, 0):
                    err = "".join(buf).strip()
                    sys.stderr.write(
                        f"horovodrun: NIC probe on {host} exited with code "
                        f"{p.returncode}"
                        + (f": {err}" if err else "")
                        + "; falling back to -H host names\n")
                    return None
            if time.monotonic() > deadline:
                sys.stderr.write(
                    "horovodrun: NIC discovery timed out; falling back to "
                    "-H host names (override with --controller-addr / "
                    "HOROVOD_RING_ADDRS if unroutable)\n")
                return None
            time.sleep(0.1)
        routable = driver.routable_addrs()
        return {host: routable[i] for i, host in enumerate(hosts)
                if i in routable}
    finally:
        driver.close()
        for _, p, _ in procs:
            if p.poll() is None:
                p.terminate()


def _stream(prefix: str, pipe, out) -> None:
    for line in iter(pipe.readline, ""):
        out.write(f"{prefix}{line}")
        out.flush()
    pipe.close()


def run(args: argparse.Namespace) -> int:
    """Supervised launch: run the job, and on a non-zero exit tear it down,
    back off, and relaunch up to ``--max-restarts`` times with
    ``HOROVOD_RESTART_EPOCH`` bumped (elastic-lite: training scripts resume
    from their latest ``utils/checkpoint.py`` checkpoint — later Horovod
    solved this as Elastic Horovod; on TPU the supervisor restarts whole
    processes instead of rebuilding rings in place)."""
    max_restarts = getattr(args, "max_restarts", 0)
    backoff = max(0.0, getattr(args, "restart_backoff", 1.0))
    epoch = 0
    interrupted = threading.Event()

    def _exit(code: int) -> int:
        # The supervisor's registry/ring live in THIS process — no rank
        # ever exports them. A supervised run that restarted dumps its own
        # flight recorder so the restart history survives the terminal.
        # The launcher has no HOROVOD_RANK, so the dump lands on the bare
        # path (or a "{rank}" placeholder expands to "launcher") — never
        # clobbering a rank's postmortem.
        if epoch > 0:
            metrics.record_event("launcher_exit", exit_code=code,
                                 restarts=epoch)
            metrics.dump_flight_recorder("launcher_exit")
        return code

    while True:
        code = _run_attempt(args, restart_epoch=epoch,
                            interrupted=interrupted)
        if interrupted.is_set():
            # Operator-initiated teardown (SIGINT/SIGTERM) is not a fault;
            # never auto-restart over the operator's intent.
            return _exit(_finish_trace(args, code))
        if code == 0 or epoch >= max_restarts:
            if code != 0 and max_restarts > 0:
                sys.stderr.write(
                    f"horovodrun: giving up after {epoch} restart(s); "
                    f"final exit code {code}\n")
            return _exit(_finish_trace(args, code))
        epoch += 1
        delay = min(30.0, backoff * (2.0 ** (epoch - 1)))
        sys.stderr.write(
            f"horovodrun: job failed with exit code {code}; restarting "
            f"(attempt {epoch}/{max_restarts}) in {delay:.1f}s with "
            f"HOROVOD_RESTART_EPOCH={epoch}\n")
        # Event.wait, not time.sleep: a SIGINT during the backoff (the
        # still-installed handler sets `interrupted`) must cancel the
        # relaunch, not schedule one more multi-hour attempt.
        if interrupted.wait(delay):
            epoch -= 1  # cancelled during backoff: this restart never ran
            return _exit(_finish_trace(args, code))
        # Counted only once the backoff survives: a restart that was
        # cancelled mid-backoff must not appear in the restart history.
        if metrics.on():
            _launcher_metrics().restarts.inc()
            metrics.record_event("launcher_restart", epoch=epoch,
                                 exit_code=code)


def _finish_trace(args: argparse.Namespace, code: int) -> int:
    """Post-run trace hook for ``--trace``: rank 0 already merged on a
    clean shutdown; after a crash (or a kill) the per-rank files are
    still on disk, so merge whatever exists and point the operator at
    the artifacts either way. Never changes the exit code."""
    trace_dir = getattr(args, "trace", None)
    if not trace_dir:
        return code
    try:
        from .. import trace as trace_mod

        merged = os.path.join(trace_dir, trace_mod.MERGED_TRACE_FILE)
        report = os.path.join(trace_dir, trace_mod.REPORT_FILE)
        if not os.path.exists(merged):
            if not trace_mod.rank_trace_files(trace_dir):
                sys.stderr.write(
                    f"horovodrun: no per-rank traces under {trace_dir} to "
                    "merge\n")
                return code
            trace_mod.merge_trace_dir(trace_dir)
        if not os.path.exists(report):
            trace_mod.write_report(trace_dir, feed=False)
        sys.stderr.write(
            f"horovodrun: merged trace at {merged}; straggler report at "
            f"{report}\n")
    except Exception as exc:  # tracing must never fail the launch result
        sys.stderr.write(f"horovodrun: trace merge failed: {exc} "
                         "(retry with python -m horovod_tpu.tools."
                         f"straggler {trace_dir})\n")
    return code


def _run_attempt(args: argparse.Namespace, restart_epoch: int = 0,
                 interrupted: Optional[threading.Event] = None) -> int:
    hosts = parse_hosts(args.hosts, args.np)
    if getattr(args, "trace", None):
        # Cluster tracing (docs/tracing.md): every rank writes spans under
        # the shared dir; rank 0 merges at shutdown. BOTH eager engines
        # emit the same fixed phase vocabulary now — the native C++
        # engine stamps spans into its C ring and the controller drains
        # them (round 14) — so --trace no longer pins
        # HOROVOD_ENGINE=python; traced jobs keep the fast path.
        os.makedirs(args.trace, exist_ok=True)
        os.environ["HOROVOD_TRACE_DIR"] = args.trace
        if args.spmd:
            # Say so NOW, not via an empty directory at exit: spans come
            # from the eager controllers, not the SPMD tier.
            sys.stderr.write(
                "horovodrun: WARNING --trace has no span source under "
                "--spmd — collective spans come from the eager controller "
                "engines; expect no trace.rank*.json files "
                "(docs/tracing.md)\n")
    size = args.np
    secret = config_mod.secret_key_hex() or make_secret()
    coord_host = hosts[0][0]
    any_remote_host = any(not _is_local(h) for h, _ in hosts)
    host_ip: Dict[str, str] = {}
    if any_remote_host:
        ssh_preflight([h for h, _ in hosts], ssh_port=args.ssh_port,
                      use_cache=not args.disable_cache)
        # Skip the ring-probe only when every consumer of its result is
        # already overridden: the coordinator address explicitly, and the
        # ring addresses either absent entirely (SPMD mode) or explicitly —
        # including the hierarchical rings when those are requested.
        from ..common.config import _env_bool
        hier_requested = (_env_bool("HOROVOD_HIERARCHICAL_ALLREDUCE")
                          or _env_bool("HOROVOD_HIERARCHICAL_ALLGATHER"))
        hier_overridden = ("HOROVOD_LOCAL_RING_ADDRS" in os.environ
                           and "HOROVOD_CROSS_RING_ADDRS" in os.environ)
        all_overridden = bool(args.controller_addr) and (
            args.spmd or ("HOROVOD_RING_ADDRS" in os.environ
                          and (not hier_requested or hier_overridden)))
        if not args.disable_nic_discovery and not all_overridden:
            # Probe tasks and the driver authenticate with the job secret.
            os.environ["HOROVOD_SECRET_KEY"] = secret
            host_ip = discover_routable_addrs(
                [h for h, _ in hosts], args.ssh_port, secret) or {}
    def _public_host(host: str) -> str:
        """Address other hosts should dial for `host`: the ring-probed
        routable IP when discovery ran, else the -H name; local entries in
        mixed jobs need a reachable name, not loopback."""
        if _is_local(host):
            return (host_ip.get(host) or socket.gethostname()
                    if any_remote_host else "127.0.0.1")
        return host_ip.get(host, host)

    coord_host = _public_host(coord_host)
    coord_addr = args.controller_addr or f"{coord_host}:{_free_port()}"

    assignments = []  # (rank, host, local_rank, local_size, cross_rank)
    rank = 0
    for cross_rank, (host, slots) in enumerate(hosts):
        local = min(slots, size - rank)
        for lr in range(local):
            assignments.append((rank, host, lr, local, cross_rank))
            rank += 1
        if rank >= size:
            break

    # Telemetry endpoints: each rank serves /metrics at base + rank
    # (common/basics.py). Print the resolved URLs so operators never
    # compute the port offset by hand; rank 0's endpoint additionally
    # aggregates every worker's piggybacked snapshot (rank-labeled).
    metrics_base = config_mod.env_str("HOROVOD_METRICS_PORT")
    if metrics_base:
        try:
            base_port = int(metrics_base)
        except ValueError:
            base_port = 0
        if base_port > 0:
            for r, host, _, _, _ in assignments:
                sys.stderr.write(
                    f"horovodrun: rank {r} metrics at "
                    f"http://{_public_host(host)}:{base_port + r}/metrics\n")
            if args.verbose:
                sys.stderr.write(
                    "horovodrun: cluster view (every rank's series, "
                    "rank-labeled) at http://"
                    f"{_public_host(assignments[0][1])}:{base_port}"
                    "/metrics\n")
        else:
            sys.stderr.write(
                "horovodrun: ignoring unparseable HOROVOD_METRICS_PORT="
                f"{metrics_base!r}; metrics endpoints disabled\n")

    # Per-rank addresses for the native C++ ring data plane (eager tier only;
    # SPMD workers have no ring). Local-only jobs bind loopback with
    # verified-free ports; with remote hosts in play the local entries must
    # be reachable, so use the hostname and a common base port on remote
    # machines (override via HOROVOD_RING_ADDRS if the heuristic clashes).
    elastic = getattr(args, "elastic", False)
    ring_addrs_env = None
    if not args.spmd and not elastic:
        ring_base = _free_port()
        ring_addrs = []
        for r, host, _, _, _ in assignments:
            if _is_local(host):
                ring_addrs.append(f"{_public_host(host)}:{_free_port()}")
            else:
                ring_addrs.append(
                    f"{_public_host(host)}:{_derived_port(ring_base, r)}")
        ring_addrs_env = config_mod.ring_addrs() or ",".join(ring_addrs)

    # Per-group ring addresses for the two-level (hierarchical) data plane
    # (HOROVOD_HIERARCHICAL_ALLREDUCE/ALLGATHER): one ring inside each host
    # entry plus a ring of the entries' first ranks, so the flags can simply
    # be flipped on the training command. Exported only for homogeneous
    # layouts (every populated group the same size, >1): with mixed group
    # sizes the per-rank gate and count math would diverge across ranks and
    # the lockstep data phases would deadlock — those layouts stay on the
    # flat ring (the reference's homogeneity check serves the same purpose,
    # operations.cc:936-952).
    local_ring_by_rank: Dict[int, str] = {}
    cross_ring_env = None
    groups: Dict[int, list] = {}
    for a in assignments:
        groups.setdefault(a[4], []).append(a)
    group_sizes = {len(m) for m in groups.values()}
    if not args.spmd and not elastic and len(groups) > 1 and \
            group_sizes.issubset({
            max(group_sizes)}) and max(group_sizes) > 1:
        # Remote ports share ring_base with the flat ring, in disjoint
        # offset bands — flat [0, size), local [size, 2*size), cross
        # [2*size, 3*size) — so two rings can never be told to bind the
        # same port on one host.

        def _group_addr(host, offset):
            if _is_local(host):
                return f"{_public_host(host)}:{_free_port()}"
            return f"{_public_host(host)}:{_derived_port(ring_base, offset)}"

        cross_addrs = []
        for cr in sorted(groups):
            members = groups[cr]
            addrs = [_group_addr(host, size + r)
                     for r, host, _, _, _ in members]
            for r, _, _, _, _ in members:
                local_ring_by_rank[r] = ",".join(addrs)
            root_r, root_host = members[0][0], members[0][1]
            cross_addrs.append(_group_addr(root_host, 2 * size + root_r))
        cross_ring_env = ",".join(cross_addrs)
        if ("HOROVOD_LOCAL_RING_ADDRS" in os.environ) != \
                ("HOROVOD_CROSS_RING_ADDRS" in os.environ):
            sys.stderr.write(
                "horovodrun: only one of HOROVOD_LOCAL_RING_ADDRS/"
                "HOROVOD_CROSS_RING_ADDRS is set; ignoring it in favor of "
                "the launcher-computed hierarchical rings (set both to "
                "override)\n")

    procs: List[subprocess.Popen] = []
    threads = []
    failed = threading.Event()

    def spawn(rank, host, local_rank, local_size, cross_rank, join=False):
        # cross_size counts POPULATED groups: with -np smaller than the total
        # slots, trailing -H entries receive no ranks and must not count.
        env = build_rank_env(
            dict(os.environ), rank, size, local_rank, local_size,
            cross_rank, len(groups), coord_addr, secret, args.bind_chips,
            spmd=args.spmd, restart_epoch=restart_epoch, elastic=elastic,
            min_ranks=getattr(args, "min_ranks", 1),
            max_ranks=getattr(args, "max_ranks", 0), elastic_join=join)
        env["HOROVOD_START_TIMEOUT"] = str(args.start_timeout)
        if not args.spmd and not elastic:
            env["HOROVOD_RING_ADDRS"] = ring_addrs_env
            # A complete user-set hierarchical pair wins (build_rank_env
            # already inherited it); anything less gets the computed pair —
            # the two consumers (controller and native engine) require both,
            # so a half-set pair would silently fall back to the flat ring.
            if rank in local_ring_by_rank and cross_ring_env and \
                    not ("HOROVOD_LOCAL_RING_ADDRS" in os.environ
                         and "HOROVOD_CROSS_RING_ADDRS" in os.environ):
                env["HOROVOD_LOCAL_RING_ADDRS"] = local_ring_by_rank[rank]
                env["HOROVOD_CROSS_RING_ADDRS"] = cross_ring_env
        if _is_local(host):
            cmd = args.command
        else:
            # ssh fan-out with env inlined (reference run/run.py:462-485 via
            # mpirun -x; no orted here — ranks connect straight back to the
            # coordinator's TCP service).
            exports = " ".join(
                f"{k}={shlex.quote(v)}" for k, v in env.items()
                if k.startswith(("HOROVOD_", "TPU_", "JAX_", "PYTHONPATH")))
            remote = f"cd {shlex.quote(os.getcwd())} && env {exports} " + \
                " ".join(shlex.quote(c) for c in args.command)
            cmd = ["ssh", "-o", "StrictHostKeyChecking=no",
                   "-p", str(args.ssh_port), host, remote]
            env = dict(os.environ)
        proc = subprocess.Popen(
            cmd, env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, bufsize=1)
        procs.append(proc)
        t = threading.Thread(
            target=_stream, args=(f"[{rank}]: " if size > 1 else "",
                                  proc.stdout, sys.stdout),
            name=f"hvd-rank-stream-{rank}", daemon=True)
        t.start()
        threads.append(t)
        return proc

    for a in assignments:
        spawn(*a)

    def _terminate_all(signum=None, frame=None):
        if signum is not None and interrupted is not None:
            interrupted.set()  # operator signal: suppress supervised restart
        for p in procs:
            if p.poll() is None:
                p.terminate()

    signal.signal(signal.SIGINT, _terminate_all)
    signal.signal(signal.SIGTERM, _terminate_all)

    exit_code = 0
    assignment_by_rank = {a[0]: a for a in assignments}
    # Elastic (docs/elastic.md): a dead WORKER is respawned individually as
    # a joiner (the coordinator admits it at the next epoch boundary) up to
    # --elastic-respawns times per slot, instead of the whole job being
    # torn down; the job ends when the coordinator's process does.
    respawns_left = {a[0]: getattr(args, "elastic_respawns", 0)
                     for a in assignments if a[0] != 0}
    try:
        pending = [(a[0], procs[i]) for i, a in enumerate(assignments)]
        done = False
        while pending and not done:
            for rank_id, p in list(pending):
                rc = p.poll()
                if rc is None:
                    continue
                pending.remove((rank_id, p))
                if not elastic:
                    if rc != 0 and exit_code == 0:
                        exit_code = rc
                        sys.stderr.write(
                            f"horovodrun: rank {rank_id} exited with code "
                            f"{rc}; terminating remaining ranks\n")
                        failed.set()
                        _terminate_all()
                    continue
                if rank_id == 0:
                    # The coordinator IS the job in elastic mode: its exit
                    # (clean or not) ends the run; lingering workers and
                    # half-admitted joiners are torn down with it.
                    exit_code = rc
                    done = True
                    _terminate_all()
                    break
                if rc == 0 or interrupted is not None and interrupted.is_set():
                    continue  # graceful leave / operator teardown: no respawn
                if respawns_left.get(rank_id, 0) > 0:
                    respawns_left[rank_id] -= 1
                    sys.stderr.write(
                        f"horovodrun: rank {rank_id} exited with code {rc}; "
                        "respawning its slot as an elastic joiner "
                        f"({respawns_left[rank_id]} respawn(s) left)\n")
                    pending.append((
                        rank_id, spawn(*assignment_by_rank[rank_id],
                                       join=True)))
                else:
                    sys.stderr.write(
                        f"horovodrun: rank {rank_id} exited with code {rc}; "
                        "elastic respawn budget exhausted — continuing with "
                        "the survivors\n")
            if pending and not done:
                time.sleep(0.05)
    finally:
        _terminate_all()
        for t in threads:
            t.join(timeout=2.0)
    return exit_code


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="horovodrun",
        description="Launch a horovod_tpu job (TPU-native horovodrun: no "
                    "mpirun, no ssh preflight for local jobs).")
    from .. import __version__

    parser.add_argument("-np", "--num-proc", dest="np", type=int,
                        required=True,
                        help="total number of processes (ranks)")
    # argparse's version action exits during parse, before required-arg
    # validation, so plain `horovodrun -v` works (reference horovodrun -v).
    parser.add_argument("-v", "--version", action="version",
                        version=f"horovod_tpu v{__version__}")
    parser.add_argument("-H", "--hosts", "--host", default=None,
                        help="host1:slots,host2:slots (default: all local)")
    parser.add_argument("--controller-addr", default=None,
                        help="coordinator bind address host:port "
                             "(default: auto on rank-0 host)")
    parser.add_argument("--bind-chips", action="store_true",
                        help="partition local TPU chips among local ranks via "
                             "TPU_VISIBLE_DEVICES (one-chip-per-rank model)")
    parser.add_argument("--spmd", action="store_true",
                        help="SPMD multi-host mode: ranks join the JAX "
                             "distributed runtime (one process per host, "
                             "global mesh over all chips); collectives run "
                             "inside jit over ICI/DCN instead of the eager "
                             "controller")
    parser.add_argument("-p", "--ssh-port", type=int, default=22,
                        help="ssh port for remote hosts (reference "
                             "horovodrun -p)")
    parser.add_argument("--start-timeout", type=int, default=600,
                        help="seconds to wait for all ranks to start and "
                             "rendezvous before aborting (reference "
                             "horovodrun --start-timeout)")
    parser.add_argument("--elastic", action="store_true",
                        help="elastic membership (docs/elastic.md): a dead "
                             "rank re-forms the job with the survivors at a "
                             "bumped membership epoch instead of aborting "
                             "it, dead worker slots are respawned "
                             "individually as joiners, and late workers "
                             "are admitted at epoch boundaries; pins the "
                             "python controller engine")
    parser.add_argument("--min-ranks", type=int, default=1,
                        help="elastic: abort (like a static job) if a "
                             "reshape would drop below this world size "
                             "(default 1)")
    parser.add_argument("--max-ranks", type=int, default=0,
                        help="elastic: park joiners beyond this world size "
                             "until a slot frees (default 0 = unbounded)")
    parser.add_argument("--elastic-respawns", type=int, default=3,
                        help="elastic: times each dead worker slot is "
                             "respawned as a joiner before the job simply "
                             "continues with the survivors (default 3)")
    parser.add_argument("--max-restarts", type=int, default=0,
                        help="on a non-zero rank exit, tear the job down "
                             "and relaunch up to N times with exponential "
                             "backoff and HOROVOD_RESTART_EPOCH bumped; "
                             "training scripts resume from their latest "
                             "checkpoint (elastic-lite; default 0 = no "
                             "restarts)")
    parser.add_argument("--restart-backoff", type=float, default=1.0,
                        help="base seconds for the exponential restart "
                             "backoff (doubles per restart, capped at 30s)")
    parser.add_argument("--trace", metavar="DIR", default=None,
                        help="cluster-wide distributed tracing: every rank "
                             "writes clock-anchored phase spans under DIR "
                             "(HOROVOD_TRACE_DIR) — under either eager "
                             "engine, native included; rank 0 merges them "
                             "into DIR/merged_trace.json with a straggler "
                             "report at shutdown (docs/tracing.md)")
    parser.add_argument("--disable-cache", action="store_true",
                        help="skip the ssh-preflight result cache "
                             "(reference horovodrun --disable-cache)")
    parser.add_argument("--disable-nic-discovery", action="store_true",
                        help="skip the interface ring-probe on multi-host "
                             "launches and dial the -H names directly")
    parser.add_argument("--verbose", action="store_true")
    parser.add_argument("command", nargs=argparse.REMAINDER,
                        help="training command")
    args = parser.parse_args(argv)
    if not args.command:
        parser.error("no command given")
    if args.spmd and args.bind_chips:
        parser.error("--spmd and --bind-chips conflict: SPMD mode needs "
                     "every process to see all its host's chips")
    if args.spmd and args.elastic:
        parser.error("--spmd and --elastic conflict: the JAX distributed "
                     "runtime is a static world; elastic membership lives "
                     "in the eager controller tier")
    if args.elastic and args.min_ranks > args.np:
        parser.error(f"--min-ranks {args.min_ranks} exceeds -np {args.np}")
    if args.command[0] == "--":
        args.command = args.command[1:]
    return run(args)


if __name__ == "__main__":
    sys.exit(main())
