"""Per-host NIC-probe task (reference ``run/task_fn.py``).

DELIBERATELY STANDALONE: stdlib-only, imports nothing from horovod_tpu —
the launcher pipes this file over ssh stdin (``python - <index> <addrs>``),
so the remote host needs no horovod_tpu checkout and pays no package/jax
import just to enumerate NICs. ``nic_discovery`` imports the shared pieces
from here (single implementation); the wire framing below must stay
byte-compatible with ``common/wire.py``:

    [1-byte kind][4-byte big-endian length][32-byte HMAC-SHA256][payload]

keyed by ``HOROVOD_SECRET_KEY`` (hex) from the environment, HMAC over
kind+payload. The probe protocol only uses kind 0 (DATA) and skips
kind 1 (HEARTBEAT) frames like the package Wire does.
"""

from __future__ import annotations

import hashlib
import hmac
import json
import os
import pickle
import socket
import struct
import sys
import threading
from typing import List, Optional, Sequence, Tuple

PROBE_TIMEOUT = 3.0
_HDR = struct.Struct(">BI")  # wire._HDR: frame kind, payload length
_DIGEST_LEN = 32
_MAX_FRAME = 1 << 31  # wire.MAX_FRAME: bound BEFORE reading the payload
_FRAME_DATA = 0
_FRAME_HEARTBEAT = 1


def _secret() -> bytes:
    # Standalone by contract (ssh-piped, no package on the remote host):
    # the one env read that CANNOT route through common/config.py.
    key = os.environ.get("HOROVOD_SECRET_KEY")  # hvdlint: disable=HVD003
    if key:
        return bytes.fromhex(key)
    return b"horovod-tpu-default-insecure-key"  # wire.job_secret default


def _send_obj(sock: socket.socket, obj) -> None:
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    digest = hmac.new(_secret(), bytes((_FRAME_DATA,)) + payload,
                      hashlib.sha256).digest()
    sock.sendall(_HDR.pack(_FRAME_DATA, len(payload)) + digest + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    while n:
        chunk = sock.recv(min(n, 1 << 20))
        if not chunk:
            raise ConnectionError("peer closed connection")
        chunks.append(chunk)
        n -= len(chunk)
    return b"".join(chunks)


def _recv_obj(sock: socket.socket):
    while True:
        header = _recv_exact(sock, _HDR.size + _DIGEST_LEN)
        kind, length = _HDR.unpack(header[:_HDR.size])
        if length > _MAX_FRAME:
            raise RuntimeError(f"oversized probe frame ({length} bytes)")
        payload = _recv_exact(sock, length)
        if not hmac.compare_digest(header[_HDR.size:],
                                   hmac.new(_secret(),
                                            bytes((kind,)) + payload,
                                            hashlib.sha256).digest()):
            raise RuntimeError("HMAC digest mismatch on probe frame")
        if kind == _FRAME_HEARTBEAT:
            continue
        if kind != _FRAME_DATA:
            raise RuntimeError(f"unexpected probe frame kind {kind}")
        return pickle.loads(payload)


def list_interfaces() -> List[Tuple[str, str]]:
    """(interface, IPv4 address) pairs of this host, loopback last (a
    loopback route only helps same-host links)."""
    pairs: List[Tuple[str, str]] = []
    try:
        import fcntl

        SIOCGIFADDR = 0x8915
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            for _, name in socket.if_nameindex():
                try:
                    packed = fcntl.ioctl(
                        s.fileno(), SIOCGIFADDR,
                        struct.pack("256s", name.encode()[:255]))
                    pairs.append((name, socket.inet_ntoa(packed[20:24])))
                except OSError:
                    continue  # interface without an IPv4 address
    except (ImportError, OSError):
        pass
    if not pairs:
        try:
            pairs = [("host", socket.gethostbyname(socket.gethostname()))]
        except OSError:
            pairs = [("lo", "127.0.0.1")]
    pairs.sort(key=lambda p: p[1].startswith("127."))
    return pairs


def _dial_driver(driver_addr: str) -> socket.socket:
    """Dial every candidate concurrently, first answer wins: a firewalled
    candidate black-holes for PROBE_TIMEOUT instead of serialising 30 s
    stalls."""
    candidates = driver_addr.split(",")
    winner: List[socket.socket] = []
    errors: List[Exception] = []
    lock = threading.Lock()
    done = threading.Event()

    def _try(cand):
        host, port = cand.rsplit(":", 1)
        try:
            s = socket.create_connection((host, int(port)),
                                         timeout=PROBE_TIMEOUT)
        except OSError as exc:
            with lock:
                errors.append(exc)
                if len(errors) == len(candidates):
                    done.set()
            return
        with lock:
            if winner:
                s.close()
                return
            winner.append(s)
            done.set()

    for cand in candidates:
        threading.Thread(target=_try, args=(cand,),
                         name=f"hvd-probe-dial-{cand}", daemon=True).start()
    done.wait(PROBE_TIMEOUT + 2.0)
    with lock:
        if not winner:
            raise ConnectionError(
                f"could not reach NIC driver at any of {driver_addr}: "
                f"{errors[-1] if errors else 'timeout'}")
        return winner[0]


def run_probe_task(index: int, driver_addr: str,
                   addrs: Optional[Sequence[Tuple[str, str]]] = None) -> dict:
    """One host's probe: advertise local interfaces, try every interface
    address of the next host in the ring, report the reachable ones.
    Returns the driver's final answer."""
    addrs = list(addrs) if addrs is not None else list_interfaces()

    # Probe listener the *previous* host will dial.
    probe_srv = socket.create_server(("0.0.0.0", 0))
    probe_port = probe_srv.getsockname()[1]
    accepting = True

    def _absorb():
        while accepting:
            try:
                conn, _ = probe_srv.accept()
                conn.close()
            except OSError:
                return

    threading.Thread(target=_absorb, name="hvd-probe-absorb",
                     daemon=True).start()

    sock = _dial_driver(driver_addr)
    # Protocol waits are driver-paced (replies arrive only after every host
    # checks in) — the dial timeout must not apply to them.
    sock.settimeout(None)
    with sock:
        _send_obj(sock, {"op": "register", "index": index,
                         "addrs": addrs, "probe_port": probe_port})
        ans = _recv_obj(sock)
        if "error" in ans:
            raise RuntimeError(f"NIC discovery failed: {ans['error']}")

        # Probe every advertised address concurrently: a veth/docker-heavy
        # peer can advertise dozens, and 3 s each sequentially would starve
        # the other tasks' protocol waits.
        reachable: List[Tuple[str, str]] = []
        lock = threading.Lock()

        def _try(name, ip):
            try:
                with socket.create_connection(
                        (ip, ans["next_probe_port"]),
                        timeout=PROBE_TIMEOUT):
                    with lock:
                        reachable.append((name, ip))
            except OSError:
                pass

        # daemon=False on purpose: the join below IS the probe barrier.
        probes = [threading.Thread(target=_try, args=tuple(a),
                                   name=f"hvd-probe-{a[1]}", daemon=False)
                  for a in ans["next_addrs"]]
        for t in probes:
            t.start()
        for t in probes:
            t.join()
        # Restore the advertised order (real NICs before loopback) so
        # "first reachable" stays meaningful.
        order = {tuple(a): k for k, a in enumerate(ans["next_addrs"])}
        reachable.sort(key=lambda a: order[tuple(a)])

        _send_obj(sock, {"op": "report", "index": index,
                         "reachable": reachable})
        final = _recv_obj(sock)
    accepting = False
    probe_srv.close()
    if "error" in final:
        raise RuntimeError(f"NIC discovery failed: {final['error']}")
    return final


def main() -> int:
    index, driver_addr = int(sys.argv[1]), sys.argv[2]
    final = run_probe_task(index, driver_addr)
    # Machine-readable result on stdout (tests parse it; the launcher's
    # driver already holds the same answer).
    print(json.dumps({"routable": final["routable"],
                      "common_interfaces": final["common_interfaces"]}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
