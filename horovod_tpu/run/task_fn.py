"""Per-host NIC-probe task, launched over ssh by the launcher.

Reference: ``run/task_fn.py`` (the per-host task server the driver starts to
ring-probe interfaces). Usage (launcher-internal):

    python -m horovod_tpu.run.task_fn <index> <driver_addr[,driver_addr...]>

The job secret rides ``HOROVOD_SECRET_KEY`` in the environment, so probe
traffic is authenticated with the same key as the control plane.
"""

import sys

from .nic_discovery import run_probe_task


def main() -> int:
    index, driver_addr = int(sys.argv[1]), sys.argv[2]
    run_probe_task(index, driver_addr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
