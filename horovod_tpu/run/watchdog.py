"""Parent-death watchdog for launcher-spawned ranks.

Reference: ``spark/task/mpirun_exec_fn.py:25-35`` — an orphaned rank
(its parent launcher/executor died) kills itself instead of living on,
holding ring ports and the TPU until some peer timeout fires. Here the
same contract covers every spawn path: ``horovodrun`` local children,
ssh-fanned remote ranks (their watched parent is the ssh session's
shell — the session tears down when the launcher side goes away), and
``horovod_tpu.spark`` tasks.

Two layers, both armed by :func:`install`:

* ``prctl(PR_SET_PDEATHSIG, SIGTERM)`` (Linux): the kernel delivers
  SIGTERM the instant the parent dies — no polling latency.
* A daemon thread polling ``os.getppid()``: catches the cases prctl
  can't (non-Linux, or the exec'd interpreter re-parented between fork
  and install) by noticing the re-parent to init/subreaper. It sends
  SIGTERM to let ``hvd.shutdown``/atexit run, then escalates to
  ``os._exit`` after a grace period in case the engine is wedged on the
  very sockets the dead launcher held open.

Ranks opt in via ``HOROVOD_PARENT_WATCHDOG=1``, which the launcher and
the Spark task function export; standalone processes calling
``hvd.init()`` from a user's shell are never watched (their parent
dying — the shell exiting — must not kill training).
"""

from __future__ import annotations

import os
import signal
import sys
import threading
import time

_POLL_INTERVAL_S = 1.0
_GRACE_S = 5.0

_lock = threading.Lock()
# Keyed on the installing pid, not a bare bool: after a fork the child
# inherits the module state but NOT the watchdog thread (threads don't
# survive fork), so a bool would leave forked ranks unwatched while
# install() refuses to re-arm.
_installed_pid: "int | None" = None


def _set_pdeathsig(signum: int) -> bool:
    """Best-effort ``prctl(PR_SET_PDEATHSIG, signum)`` (Linux only)."""
    try:
        import ctypes

        libc = ctypes.CDLL(None, use_errno=True)
        PR_SET_PDEATHSIG = 1
        return libc.prctl(PR_SET_PDEATHSIG, signum, 0, 0, 0) == 0
    except Exception:
        return False


def install(poll_interval: float = _POLL_INTERVAL_S,
            grace: float = _GRACE_S) -> bool:
    """Arm the watchdog against the CURRENT parent. Idempotent per
    process (a forked child re-arms against ITS parent); returns whether
    a watchdog is armed. No-op (False) when already orphaned at install
    time — with the original parent unknowable, killing would be a
    guess."""
    global _installed_pid
    with _lock:
        if _installed_pid == os.getpid():
            return True
        parent = os.getppid()
        if parent <= 1:
            return False
        _set_pdeathsig(signal.SIGTERM)

        def _watch():
            from ..common.config import env_rank

            while True:
                time.sleep(poll_interval)
                if os.getppid() != parent:
                    rank = env_rank()
                    try:
                        # Best-effort: stderr may BE a pipe to the dead
                        # parent — a BrokenPipeError here must not stop
                        # the reaping below.
                        sys.stderr.write(
                            f"horovod_tpu: parent {parent} died; "
                            "terminating orphaned rank "
                            f"{'?' if rank is None else rank}\n")
                        sys.stderr.flush()
                    except Exception:
                        pass
                    os.kill(os.getpid(), signal.SIGTERM)
                    time.sleep(grace)
                    os._exit(signal.SIGTERM + 128)

        threading.Thread(target=_watch, name="hvd-parent-watchdog",
                         daemon=True).start()
        _installed_pid = os.getpid()
        return True


def maybe_install_from_env() -> bool:
    """Arm iff the launcher asked for it (``HOROVOD_PARENT_WATCHDOG``).
    Called from ``hvd.init()``; safe to call any number of times."""
    from ..common.config import _env_bool

    if not _env_bool("HOROVOD_PARENT_WATCHDOG"):
        return False
    return install()
