"""Launcher package (``python -m horovod_tpu.run`` / ``bin/horovodrun``)."""

from .launch import main, parse_hosts, run  # noqa: F401
