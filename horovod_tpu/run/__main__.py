import sys

from .launch import main

sys.exit(main())
