"""Routable-interface (NIC) discovery for multi-host launches.

Reference shape (``run/run.py:105-256``): the launcher starts a TCP driver
service, launches a small task server on every host (over ssh), and each
task *ring-probes* the next host — it tries to connect to every advertised
interface address of task ``(i+1) % N`` and reports which ones worked. The
driver then knows, per host, an address its ring predecessor can actually
route to, and the set of interface names that worked on every link
(the reference intersects exactly this set to build
``-mca btl_tcp_if_include``).

Here the result feeds the launcher directly: the coordinator address and the
per-rank ring addresses use the discovered routable IPs instead of whatever
``-H`` happened to say, so multi-homed hosts (management NIC + DCN NIC) work
without ``--controller-addr`` / ``HOROVOD_RING_ADDRS`` overrides.

The probe task itself lives in ``task_fn.py`` — standalone and
stdlib-only so the launcher can pipe it over ssh stdin (no horovod_tpu
install or jax import on the remote side); this module re-exports it and
hosts the driver, whose transport is the job's authenticated ``Wire``
framing (byte-compatible with the standalone probe's).
"""

from __future__ import annotations

import socket
import threading
import time
from typing import Dict, List, Tuple

from ..common.wire import Wire
from .task_fn import (  # noqa: F401  (re-exported shared implementation)
    PROBE_TIMEOUT,
    list_interfaces,
    run_probe_task,
)


def infer_link_class(ring_addrs) -> str:
    """Classify the flat ring's link from its launcher-exported addresses
    (``HOROVOD_RING_ADDRS``: comma-separated host:port per rank): every
    host loopback -> ``local`` (same-box job, no real NIC on the path);
    anything else -> ``tcp``. DCN/ICI fabrics cannot be told apart from
    plain ethernet by address alone — operators (or a launcher that
    learned it from the probe report) export HOROVOD_RING_LINK_CLASS
    explicitly. Keys the per-link-class chunk table in
    ``common.config.RING_CHUNK_BYTES_BY_LINK``."""
    if not ring_addrs:
        return "local"
    hosts = set()
    for addr in str(ring_addrs).split(","):
        host = addr.rsplit(":", 1)[0].strip().lower()
        if host:
            hosts.add(host)
    local_names = {"127.0.0.1", "localhost", "::1", "0.0.0.0"}
    if hosts <= local_names:
        return "local"
    # One distinct non-loopback host still means every hop is same-box.
    if len(hosts - local_names) == 1:
        try:
            own = set()
            for _, ip in list_interfaces():
                own.add(ip.lower())
            if hosts - local_names <= own:
                return "local"
        except OSError:
            pass
    return "tcp"


class NICDriverService:
    """Rendezvous for the probe tasks. One instance per launch; threads
    serve each task connection."""

    def __init__(self, num_hosts: int, timeout: float = 60.0):
        self._num = num_hosts
        self._timeout = timeout
        self._lock = threading.Condition()
        self._registered: Dict[int, dict] = {}
        self._reports: Dict[int, List[Tuple[str, str]]] = {}
        self._srv = socket.create_server(("0.0.0.0", 0))
        self.port = self._srv.getsockname()[1]
        self._accept_thread = threading.Thread(target=self._accept_loop,
                                               name="hvd-nic-accept",
                                               daemon=True)
        self._accept_thread.start()

    def _accept_loop(self) -> None:
        while True:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             name="hvd-nic-serve", daemon=True).start()

    def _serve(self, conn: socket.socket) -> None:
        wire = Wire(conn)
        try:
            while True:
                msg = wire.recv_obj()
                op = msg.get("op")
                if op == "register":
                    with self._lock:
                        self._registered[msg["index"]] = msg
                        self._lock.notify_all()
                        ok = self._wait(
                            lambda: len(self._registered) == self._num)
                    if not ok:
                        wire.send_obj({"error": "registration timeout"})
                        return
                    nxt = self._registered[(msg["index"] + 1) % self._num]
                    wire.send_obj({"next_addrs": nxt["addrs"],
                                   "next_probe_port": nxt["probe_port"]})
                elif op == "report":
                    with self._lock:
                        self._reports[msg["index"]] = msg["reachable"]
                        self._lock.notify_all()
                        ok = self._wait(
                            lambda: len(self._reports) == self._num)
                    if not ok:
                        wire.send_obj({"error": "report timeout"})
                        return
                    wire.send_obj({"routable": self.routable_addrs(),
                                   "common_interfaces":
                                       sorted(self.common_interfaces())})
                    return
        except (ConnectionError, OSError):
            return
        finally:
            conn.close()

    def _wait(self, pred) -> bool:
        deadline = time.monotonic() + self._timeout
        while not pred():
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return False
            self._lock.wait(remaining)
        return True

    def routable_addrs(self) -> Dict[int, str]:
        """Per host index: an IP of that host proven reachable from its ring
        predecessor (first reported wins; interface enumeration order puts
        real NICs before loopback)."""
        out = {}
        for i in range(self._num):
            pred = (i - 1) % self._num
            reached = self._reports.get(pred, [])
            if reached:
                out[i] = reached[0][1]
        return out

    def common_interfaces(self) -> set:
        """Interface names that worked on every probed link (the
        reference's intersection that feeds ``btl_tcp_if_include``)."""
        sets = [set(name for name, _ in r) for r in self._reports.values()]
        return set.intersection(*sets) if sets else set()

    def done(self) -> bool:
        with self._lock:
            return len(self._reports) == self._num

    def wait_done(self) -> bool:
        with self._lock:
            return self._wait(lambda: len(self._reports) == self._num)

    def close(self) -> None:
        try:
            self._srv.close()
        except OSError:
            pass
