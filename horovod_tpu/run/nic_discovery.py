"""Routable-interface (NIC) discovery for multi-host launches.

Reference shape (``run/run.py:105-256``): the launcher starts a TCP driver
service, launches a small task server on every host (over ssh), and each
task *ring-probes* the next host — it tries to connect to every advertised
interface address of task ``(i+1) % N`` and reports which ones worked. The
driver then knows, per host, an address its ring predecessor can actually
route to, and the set of interface names that worked on every link
(the reference intersects exactly this set to build
``-mca btl_tcp_if_include``).

Here the result feeds the launcher directly: the coordinator address and the
per-rank ring addresses use the discovered routable IPs instead of whatever
``-H`` happened to say, so multi-homed hosts (management NIC + DCN NIC) work
without ``--controller-addr`` / ``HOROVOD_RING_ADDRS`` overrides.

Pure stdlib: interfaces are enumerated with ``SIOCGIFADDR`` ioctls (Linux),
falling back to a hostname lookup; transport is the job's authenticated
``Wire`` framing.
"""

from __future__ import annotations

import socket
import struct
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

from ..common.wire import Wire

PROBE_TIMEOUT = 3.0


def list_interfaces() -> List[Tuple[str, str]]:
    """Enumerate (interface, IPv4 address) pairs of this host, loopback
    last (a loopback route only helps same-host links)."""
    pairs: List[Tuple[str, str]] = []
    try:
        import fcntl

        SIOCGIFADDR = 0x8915
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            for _, name in socket.if_nameindex():
                try:
                    packed = fcntl.ioctl(
                        s.fileno(), SIOCGIFADDR,
                        struct.pack("256s", name.encode()[:255]))
                    pairs.append((name, socket.inet_ntoa(packed[20:24])))
                except OSError:
                    continue  # interface without an IPv4 address
    except (ImportError, OSError):
        pass
    if not pairs:
        try:
            pairs = [("host", socket.gethostbyname(socket.gethostname()))]
        except OSError:
            pairs = [("lo", "127.0.0.1")]
    pairs.sort(key=lambda p: p[1].startswith("127."))
    return pairs


class NICDriverService:
    """Rendezvous for the probe tasks. One instance per launch; threads
    serve each task connection."""

    def __init__(self, num_hosts: int, timeout: float = 60.0):
        self._num = num_hosts
        self._timeout = timeout
        self._lock = threading.Condition()
        self._registered: Dict[int, dict] = {}
        self._reports: Dict[int, List[Tuple[str, str]]] = {}
        self._srv = socket.create_server(("0.0.0.0", 0))
        self.port = self._srv.getsockname()[1]
        self._accept_thread = threading.Thread(target=self._accept_loop,
                                               daemon=True)
        self._accept_thread.start()

    def _accept_loop(self) -> None:
        while True:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn: socket.socket) -> None:
        wire = Wire(conn)
        try:
            while True:
                msg = wire.recv_obj()
                op = msg.get("op")
                if op == "register":
                    with self._lock:
                        self._registered[msg["index"]] = msg
                        self._lock.notify_all()
                        ok = self._wait(
                            lambda: len(self._registered) == self._num)
                    if not ok:
                        wire.send_obj({"error": "registration timeout"})
                        return
                    nxt = self._registered[(msg["index"] + 1) % self._num]
                    wire.send_obj({"next_addrs": nxt["addrs"],
                                   "next_probe_port": nxt["probe_port"]})
                elif op == "report":
                    with self._lock:
                        self._reports[msg["index"]] = msg["reachable"]
                        self._lock.notify_all()
                        ok = self._wait(
                            lambda: len(self._reports) == self._num)
                    if not ok:
                        wire.send_obj({"error": "report timeout"})
                        return
                    wire.send_obj({"routable": self.routable_addrs(),
                                   "common_interfaces":
                                       sorted(self.common_interfaces())})
                    return
        except (ConnectionError, OSError):
            return
        finally:
            conn.close()

    def _wait(self, pred) -> bool:
        deadline = time.monotonic() + self._timeout
        while not pred():
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return False
            self._lock.wait(remaining)
        return True

    def routable_addrs(self) -> Dict[int, str]:
        """Per host index: an IP of that host proven reachable from its ring
        predecessor (first reported wins; interface enumeration order puts
        real NICs before loopback)."""
        out = {}
        for i in range(self._num):
            pred = (i - 1) % self._num
            reached = self._reports.get(pred, [])
            if reached:
                out[i] = reached[0][1]
        return out

    def common_interfaces(self) -> set:
        """Interface names that worked on every probed link (the
        reference's intersection that feeds ``btl_tcp_if_include``)."""
        sets = [set(name for name, _ in r) for r in self._reports.values()]
        return set.intersection(*sets) if sets else set()

    def done(self) -> bool:
        with self._lock:
            return len(self._reports) == self._num

    def wait_done(self) -> bool:
        with self._lock:
            return self._wait(lambda: len(self._reports) == self._num)

    def close(self) -> None:
        try:
            self._srv.close()
        except OSError:
            pass


def run_probe_task(index: int, driver_addr: str,
                   addrs: Optional[Sequence[Tuple[str, str]]] = None) -> dict:
    """One host's probe task: advertise local interfaces, then try every
    interface address of the next host in the ring and report the ones that
    accepted a TCP connection. Returns the driver's final answer."""
    addrs = list(addrs) if addrs is not None else list_interfaces()

    # Probe listener the *previous* host will dial.
    probe_srv = socket.create_server(("0.0.0.0", 0))
    probe_port = probe_srv.getsockname()[1]
    accepting = True

    def _absorb():
        while accepting:
            try:
                conn, _ = probe_srv.accept()
                conn.close()
            except OSError:
                return

    threading.Thread(target=_absorb, daemon=True).start()

    # The driver advertises every candidate address it has (comma-separated)
    # — the task dials them in order until one answers (the reference's task
    # services do the same against the driver's address list).
    sock = None
    last_err: Optional[Exception] = None
    for cand in driver_addr.split(","):
        host, port = cand.rsplit(":", 1)
        try:
            sock = socket.create_connection((host, int(port)),
                                            timeout=PROBE_TIMEOUT * 10)
            break
        except OSError as exc:
            last_err = exc
    if sock is None:
        raise ConnectionError(
            f"could not reach NIC driver at any of {driver_addr}: {last_err}")
    # The register/report replies arrive only after EVERY host has checked
    # in, which can take far longer than the dial timeout — the protocol's
    # patience is the driver's, not the socket's.
    sock.settimeout(None)
    with sock:
        wire = Wire(sock)
        wire.send_obj({"op": "register", "index": index,
                       "addrs": addrs, "probe_port": probe_port})
        ans = wire.recv_obj()
        if "error" in ans:
            raise RuntimeError(f"NIC discovery failed: {ans['error']}")

        # Probe every advertised address concurrently: a veth/docker-heavy
        # peer can advertise dozens, and 3 s each sequentially would starve
        # the other tasks' protocol waits.
        reachable = []
        lock = threading.Lock()

        def _try(name, ip):
            try:
                with socket.create_connection(
                        (ip, ans["next_probe_port"]),
                        timeout=PROBE_TIMEOUT):
                    with lock:
                        reachable.append((name, ip))
            except OSError:
                pass

        probes = [threading.Thread(target=_try, args=a)
                  for a in ans["next_addrs"]]
        for t in probes:
            t.start()
        for t in probes:
            t.join()
        # Restore the advertised order (real NICs before loopback) so
        # "first reachable" stays meaningful.
        order = {(n, i): k for k, (n, i) in enumerate(ans["next_addrs"])}
        reachable.sort(key=lambda a: order[a])

        wire.send_obj({"op": "report", "index": index,
                       "reachable": reachable})
        final = wire.recv_obj()
    accepting = False
    probe_srv.close()
    if "error" in final:
        raise RuntimeError(f"NIC discovery failed: {final['error']}")
    return final
