"""Machine-readable wire/epoch protocol: one declarative spec, two checkers.

Round 7 gave the control plane authenticated DATA/HEARTBEAT/ABORT frames;
round 12's elastic membership stacked JOIN/RESHAPE, membership epochs,
ack drains, and a reshape fence on top. Until now the only definition of
a *legal* frame sequence was the union of ``common/wire.py``'s recv
loops and ``controller/service.py``'s handshakes — implicit, unreviewable,
and exactly the kind of contract the eventual C++ port would silently
drift from. This module makes the protocol a checkable artifact:

* :data:`SPEC` — ONE declarative structure (plain dicts/strings, no
  code) describing, per wire-peer role (``coordinator`` side of a worker
  connection, ``worker`` client side, parked ``joiner``), which frame
  kinds are legal in which state, in which direction, with which epoch
  guard, and what state each one leads to. This is the porting contract
  ROADMAP item 2 needs (docs/static-analysis.md has the rendered state
  tables).
* **Static conformance** — :func:`check_handlers` parses the real
  ``wire.py``/``service.py``/``controller.py`` and proves every frame-kind
  dispatch branch maps to a spec entry and every spec entry has a handler
  branch (handler↔spec bijection over all five kinds, all three roles).
  Surfaced as hvdlint rule HVD008 and ``python -m
  horovod_tpu.tools.protocheck`` (exit 1 on drift).
* **Runtime conformance** — :class:`ProtocolMonitor`, an opt-in
  (``HOROVOD_PROTOCHECK=1``) per-wire monitor fed by ``Wire`` send/recv.
  Every frame is checked against the spec transition for the wire's role
  and current state; an off-spec transition is recorded (and the whole
  table dumped to ``protocheck.json`` at exit, flight-recorder-style
  ``{rank}``/``.rankN`` path expansion) or raised immediately under
  ``HOROVOD_PROTOCHECK=raise``. The r7/r12 chaos suites run under the
  monitor, so every kill/drop/delay/join/leave scenario doubles as a
  conformance run.

Epoch discipline: membership epochs are compared ONLY through
:func:`epoch_advances` / :func:`epoch_is_stale` — the sanctioned
monotonic helpers (hvdlint HVD009 flags raw ``<``/``>`` on epochs in
protocol-surface code). The helpers are trivial on purpose: the point is
one auditable definition of "newer epoch" shared by the runtime, the
monitor guards, and the reshape drain.

Stdlib-only by contract: ``common/wire.py`` imports this at module load
(same constraint as :mod:`~horovod_tpu.analysis.lockorder`).
"""

from __future__ import annotations

import ast
import atexit
import json
import os
import sys
import threading
from typing import Dict, Iterator, List, Optional, Tuple

# ---------------------------------------------------------------------------
# Epoch helpers — THE sanctioned monotonic comparisons (hvdlint HVD009).


def epoch_advances(new: int, current: int) -> bool:
    """True when ``new`` is a legal successor epoch: membership epochs
    only ever move forward, so any reshape/assignment carrying
    ``new <= current`` is protocol drift, not a rewind."""
    return new > current  # hvdlint: disable=HVD009 (the sanctioned helper)


def epoch_is_stale(seen: int, current: int) -> bool:
    """True when ``seen`` belongs to a superseded epoch (an ack from a
    reshape attempt that failed mid-handshake and was retried at a
    fresh epoch). Stale acks are drained, never errors."""
    return seen < current  # hvdlint: disable=HVD009 (the sanctioned helper)


# ---------------------------------------------------------------------------
# The spec. Data, not code: dict literals all the way down, consumed by
# the static checker (check_handlers), the runtime monitor
# (ProtocolMonitor), the docs renderer (render_state_tables), and
# hvdlint HVD008.
#
# Keys: SPEC[role]["states"][state][(direction, kind)] -> transition dict:
#   {"next": <state>}                  legal; move to <state>
#   {"next": <state>, "guard": <name>} legal iff the named guard holds
#   {"violation": <why>}               a branch the handlers must HAVE,
#                                      whose firing is itself the finding
#                                      (e.g. JOIN in the data stream)
# Guards (interpreted by the monitor, documented for the port):
#   epoch_advances   the frame's epoch must be > the wire's committed one
#   ack_commits      JOIN ack epoch == the pending reshape epoch (commit)
#                    or stale (< pending: superseded attempt, stay put);
#                    an ack from the future is a violation
#   ack_matches      worker's outbound ack must equal the assignment epoch

KINDS = ("data", "heartbeat", "abort", "join", "reshape", "shard_fetch",
         "shard_data")

# Heartbeats are liveness riding a background thread; they are legal in
# every state, both directions, and never change state. Spelling that
# out per state would bury the interesting transitions, so the monitor
# and checker treat heartbeat as implicitly self-looping everywhere;
# the constant records the decision as data.
HEARTBEAT_ALWAYS_LEGAL = True

SPEC = {
    "coordinator": {
        # Rank 0's side of ONE worker/joiner connection (the service holds
        # an independent machine per wire).
        "initial": "handshake",
        "states": {
            "handshake": {
                ("recv", "data"): {"next": "steady",
                                   "note": "rendezvous hello"},
                ("recv", "join"): {"next": "parked",
                                   "note": "elastic join hello"},
                ("recv", "abort"): {"violation":
                                    "abort frame during a hello"},
                ("recv", "reshape"): {"violation":
                                      "reshape frame during a hello"},
                ("recv", "shard_fetch"): {"violation":
                                          "shard frame during a hello"},
                ("recv", "shard_data"): {"violation":
                                         "shard frame during a hello"},
            },
            "steady": {
                ("recv", "data"): {"next": "steady",
                                   "note": "tick / tensor payload"},
                ("recv", "abort"): {"violation":
                                    "workers never originate aborts"},
                ("recv", "reshape"): {"violation":
                                      "workers never originate reshapes"},
                ("recv", "join"): {"violation":
                                   "join frame in the data stream"},
                ("recv", "shard_fetch"): {"next": "steady",
                                          "note": "shard request to relay "
                                                  "(or serve, owner 0)"},
                ("recv", "shard_data"): {"next": "steady",
                                         "note": "shard reply to relay "
                                                 "(or consume, req 0)"},
                ("send", "data"): {"next": "steady",
                                   "note": "cycle reply / tensor payload"},
                ("send", "abort"): {"next": "dead",
                                    "note": "coordinated abort broadcast"},
                ("send", "reshape"): {"next": "draining",
                                      "guard": "epoch_advances",
                                      "note": "membership assignment"},
                ("send", "join"): {"violation":
                                   "the coordinator never sends join "
                                   "frames"},
                ("send", "shard_fetch"): {"next": "steady",
                                          "note": "relayed shard request "
                                                  "(rank 0 requester or "
                                                  "star hop)"},
                ("send", "shard_data"): {"next": "steady",
                                         "note": "relayed or locally "
                                                 "served shard reply"},
            },
            "parked": {
                # A validated joiner waiting for an epoch boundary. Only
                # heartbeats flow until the admission RESHAPE.
                ("send", "reshape"): {"next": "draining",
                                      "guard": "epoch_advances",
                                      "note": "admission assignment"},
                ("send", "abort"): {"next": "dead",
                                    "note": "job failed while parked"},
                ("recv", "data"): {"violation":
                                   "parked joiner sent data"},
                ("recv", "join"): {"violation":
                                   "parked joiner re-sent its hello"},
                ("recv", "abort"): {"violation":
                                    "workers never originate aborts"},
                ("recv", "reshape"): {"violation":
                                      "workers never originate reshapes"},
                ("recv", "shard_fetch"): {"violation":
                                          "parked joiner has no shard "
                                          "plane until admission"},
                ("recv", "shard_data"): {"violation":
                                         "parked joiner has no shard "
                                         "plane until admission"},
                ("send", "shard_fetch"): {"violation":
                                          "no shard relay to a parked "
                                          "joiner"},
                ("send", "shard_data"): {"violation":
                                         "no shard relay to a parked "
                                         "joiner"},
            },
            "draining": {
                # After send(reshape): drain the member's wire to its ack.
                ("recv", "data"): {"next": "draining",
                                   "note": "dead-epoch traffic, discarded"},
                ("recv", "join"): {"next": "steady", "guard": "ack_commits",
                                   "note": "reshape ack (stale acks stay "
                                           "draining)"},
                ("recv", "abort"): {"next": "dead",
                                    "note": "defensive: recv_reshape_ack "
                                            "surfaces a remote abort"},
                ("recv", "reshape"): {"violation":
                                      "workers never originate reshapes"},
                ("recv", "shard_fetch"): {"next": "draining",
                                          "note": "dead-epoch shard "
                                                  "traffic, discarded"},
                ("recv", "shard_data"): {"next": "draining",
                                         "note": "dead-epoch shard "
                                                 "traffic, discarded"},
                ("send", "reshape"): {"next": "draining",
                                      "guard": "epoch_advances",
                                      "note": "retry at a fresh epoch after "
                                              "a member failed mid-"
                                              "handshake"},
                ("send", "abort"): {"next": "dead",
                                    "note": "job failed mid-reshape"},
                ("send", "shard_fetch"): {"next": "draining",
                                          "note": "defensive: a relay "
                                                  "racing the reshape; "
                                                  "the member's torn "
                                                  "restore ignores it"},
                ("send", "shard_data"): {"next": "draining",
                                         "note": "defensive: a relayed "
                                                 "reply racing the "
                                                 "reshape"},
            },
            "dead": {
                # Terminal: the job is failing; only stray heartbeats may
                # still cross before the close.
            },
        },
    },
    "worker": {
        # A non-zero rank's client side: one persistent wire.
        "initial": "init",
        "states": {
            "init": {
                ("send", "data"): {"next": "steady",
                                   "note": "rendezvous hello"},
            },
            "steady": {
                ("send", "data"): {"next": "steady",
                                   "note": "tick / tensor payload"},
                ("recv", "data"): {"next": "steady",
                                   "note": "cycle reply / tensor payload"},
                ("recv", "abort"): {"next": "dead",
                                    "note": "coordinated abort"},
                ("recv", "reshape"): {"next": "reshaping",
                                      "guard": "epoch_advances",
                                      "note": "membership changed"},
                ("recv", "join"): {"violation":
                                   "join frame in the data stream"},
                ("recv", "shard_fetch"): {"next": "steady",
                                          "note": "relayed shard request "
                                                  "(this rank owns a "
                                                  "matching copy)"},
                ("recv", "shard_data"): {"next": "steady",
                                         "note": "shard reply for this "
                                                 "rank's restore"},
                ("send", "abort"): {"violation":
                                    "workers never originate aborts"},
                ("send", "reshape"): {"violation":
                                      "workers never originate reshapes"},
                ("send", "join"): {"violation":
                                   "reshape ack without a reshape"},
                ("send", "shard_fetch"): {"next": "steady",
                                          "note": "shard request toward "
                                                  "the coordinator star"},
                ("send", "shard_data"): {"next": "steady",
                                         "note": "served shard reply "
                                                 "(this rank is the "
                                                 "owner)"},
            },
            "reshaping": {
                # Between the RESHAPE tearing out of a recv and this
                # side's acknowledgement: the epoch drain runs locally,
                # nothing but the ack may go out.
                ("send", "join"): {"next": "steady", "guard": "ack_matches",
                                   "note": "reshape acknowledgement"},
                ("send", "data"): {"violation":
                                   "data before the reshape was acked"},
                # The restore thread may race the RESHAPE by a frame: a
                # fetch (or a served reply) already leaving when the
                # assignment lands is LATE traffic the coordinator's
                # drain discards — legal, unlike data, which would
                # desync the negotiated stream.
                ("send", "shard_fetch"): {"next": "reshaping",
                                          "note": "late fetch from a "
                                                  "restore the reshape "
                                                  "is tearing; the "
                                                  "drain discards it"},
                ("send", "shard_data"): {"next": "reshaping",
                                         "note": "late served reply; "
                                                 "the drain discards "
                                                 "it"},
                ("recv", "abort"): {"next": "dead",
                                    "note": "job failed mid-reshape"},
                ("recv", "reshape"): {"next": "reshaping",
                                      "guard": "epoch_advances",
                                      "note": "superseded by a fresher "
                                              "reshape"},
            },
            "dead": {},
        },
    },
    "joiner": {
        # A late worker dialing a live elastic job; becomes an ordinary
        # worker the moment its admission commits.
        "initial": "init",
        "states": {
            "init": {
                ("send", "join"): {"next": "parked",
                                   "note": "join hello"},
            },
            "parked": {
                ("recv", "reshape"): {"next": "reshaping",
                                      "guard": "epoch_advances",
                                      "note": "admission assignment"},
                ("recv", "abort"): {"next": "dead",
                                    "note": "job failed while parked"},
                ("recv", "data"): {"violation":
                                   "coordinator is not elastic (data "
                                   "instead of an assignment)"},
                ("recv", "join"): {"violation":
                                   "join frame echoed back"},
                ("recv", "shard_fetch"): {"violation":
                                          "parked joiner has no shard "
                                          "plane until admission"},
                ("recv", "shard_data"): {"violation":
                                         "parked joiner has no shard "
                                         "plane until admission"},
                ("send", "data"): {"violation":
                                   "parked joiner sent data"},
                ("send", "shard_fetch"): {"violation":
                                          "parked joiner sent shard "
                                          "traffic"},
                ("send", "shard_data"): {"violation":
                                         "parked joiner sent shard "
                                         "traffic"},
            },
            # Admitted: from here on the wire behaves exactly like a
            # worker's (same transitions, stated once via the post-build
            # aliases below so the two roles cannot drift apart).
            "reshaping": {},
            "steady": {},
            "dead": {},
        },
    },
}

# An admitted joiner IS a worker: alias the steady/reshaping row sets
# after admission. The aliases are part of the declarative structure
# (shared references, established once here, data either way).
SPEC["joiner"]["states"]["steady"] = SPEC["worker"]["states"]["steady"]
SPEC["joiner"]["states"]["reshaping"] = SPEC["worker"]["states"]["reshaping"]

ROLES = tuple(sorted(SPEC))

# Which membership epoch a fresh wire is at, per role. Workers/coordinator
# wires exist from rendezvous (epoch 1); a joiner has no epoch until its
# admission assignment commits one.
INITIAL_EPOCH = {"coordinator": 1, "worker": 1, "joiner": 0}

# Documented invariants the monitor cannot see at the wire layer (they
# live above it), recorded here so the spec is the one contract document:
INVARIANTS = (
    {"name": "ack_before_commit",
     "where": "controller/service.py::CoordinatorService.reform",
     "statement": "a membership epoch is committed (wires dict swapped) "
                  "only after EVERY member acked exactly that epoch; a "
                  "member failing mid-handshake restarts the whole "
                  "handshake at a fresh epoch"},
    {"name": "fence_before_enqueue",
     "where": "controller/controller.py::Controller._enqueue + "
              "_drain_epoch",
     "statement": "between a reshape's epoch drain and the user-level "
                  "acknowledgement (hvd.elastic.run clearing the fence), "
                  "every new enqueue fails with the same retryable "
                  "RanksChangedError its drained siblings got — a lone "
                  "post-drain enqueue would negotiate a tensor no peer "
                  "knows and hang the new epoch"},
    {"name": "epoch_monotonicity",
     "where": "analysis/protocol.py::epoch_advances / epoch_is_stale",
     "statement": "membership epochs only move forward; stale acks are "
                  "drained, assignments must advance the epoch"},
)


# ---------------------------------------------------------------------------
# Spec self-checks (consumed by tools/protocheck and tests).


def check_spec() -> List[str]:
    """Internal consistency of :data:`SPEC`: every role covers every
    frame kind in both directions (transition or declared violation —
    the bijection's spec half), next-states exist, guards are known,
    and every non-terminal state is reachable. Returns problem strings
    (empty == consistent)."""
    problems: List[str] = []
    known_guards = {"epoch_advances", "ack_commits", "ack_matches"}
    for role in ROLES:
        states = SPEC[role]["states"]
        initial = SPEC[role]["initial"]
        if initial not in states:
            problems.append(f"{role}: initial state {initial!r} undefined")
        reachable = {initial}
        frontier = [initial]
        while frontier:
            state = frontier.pop()
            for key in sorted(states.get(state, {})):
                entry = states[state][key]
                nxt = entry.get("next")
                if nxt is not None and nxt not in reachable:
                    reachable.add(nxt)
                    frontier.append(nxt)
        covered: Dict[Tuple[str, str], bool] = {}
        for state in sorted(states):
            if state not in reachable and states[state]:
                problems.append(f"{role}: state {state!r} unreachable")
            for (direction, kind), entry in sorted(states[state].items()):
                if kind not in KINDS:
                    problems.append(
                        f"{role}.{state}: unknown kind {kind!r}")
                if direction not in ("send", "recv"):
                    problems.append(
                        f"{role}.{state}: unknown direction {direction!r}")
                if "next" in entry and entry["next"] not in states:
                    problems.append(
                        f"{role}.{state}: next state {entry['next']!r} "
                        "undefined")
                if "next" not in entry and "violation" not in entry:
                    problems.append(
                        f"{role}.{state}.{direction}.{kind}: entry is "
                        "neither a transition nor a declared violation")
                guard = entry.get("guard")
                if guard is not None and guard not in known_guards:
                    problems.append(
                        f"{role}.{state}: unknown guard {guard!r}")
                covered[(direction, kind)] = True
        for direction in ("send", "recv"):
            for kind in KINDS:
                if kind == "heartbeat":
                    continue  # implicitly legal everywhere (see above)
                if not covered.get((direction, kind)):
                    problems.append(
                        f"{role}: kind {kind!r} ({direction}) appears in "
                        "no state — the spec does not cover it")
    return problems


# ---------------------------------------------------------------------------
# Runtime monitor.

ENV_KNOB = "HOROVOD_PROTOCHECK"
ENV_OUTPUT = "HOROVOD_PROTOCHECK_OUTPUT"
DEFAULT_OUTPUT = "protocheck.json"

_mode: Optional[str] = None


def _invalidate_in_child() -> None:
    global _mode
    _mode = None


os.register_at_fork(after_in_child=_invalidate_in_child)


def _protocheck_mode() -> str:
    """"" (off), "record", or "raise". Cached like lockcheck_enabled;
    read directly (not via common/config.py) because wire.py loads this
    module before the package and it must stay import-cycle-free."""
    global _mode
    if _mode is None:
        # hvdlint: disable=HVD003 (pre-package module, see docstring)
        val = (os.environ.get(ENV_KNOB) or "").strip().lower()
        if val in ("", "0", "false", "no", "off"):
            _mode = ""
        elif val == "raise":
            _mode = "raise"
        else:
            _mode = "record"
    return _mode


def protocheck_enabled() -> bool:
    return bool(_protocheck_mode())


def refresh_mode() -> None:
    """Drop the cached HOROVOD_PROTOCHECK mode so the next check re-reads
    the environment. Real ranks only ever set the knob before launch (the
    cache is correct for them); the in-process sim harness
    (horovod_tpu/sim) toggles it around a cluster's lifetime and must
    re-resolve on both edges."""
    global _mode
    _mode = None


class ProtocolViolationError(RuntimeError):
    """An off-spec wire transition under ``HOROVOD_PROTOCHECK=raise``."""


class _Recorder:
    """Process-global violation/transition tally shared by every wire's
    monitor; dumped to ``protocheck.json`` at exit (and on demand)."""

    def __init__(self):
        self._mu = threading.Lock()
        self.transitions = 0
        self.violations: List[dict] = []

    def note_ok(self) -> None:
        with self._mu:
            self.transitions += 1

    def note_violation(self, entry: dict) -> None:
        with self._mu:
            self.transitions += 1
            if len(self.violations) < 1000:  # bounded artifact
                self.violations.append(entry)
        sys.stderr.write(
            "protocheck: OFF-SPEC wire transition: "
            f"{entry['role']}.{entry['state']} {entry['direction']} "
            f"{entry['kind']}: {entry['detail']}\n")

    def report(self) -> dict:
        with self._mu:
            return {
                "enabled": protocheck_enabled(),
                "transitions": self.transitions,
                "violations": list(self.violations),
                "ok": not self.violations,
            }

    def clear(self) -> None:
        with self._mu:
            self.transitions = 0
            self.violations.clear()


_recorder = _Recorder()


def recorder() -> _Recorder:
    return _recorder


def output_path() -> str:
    """Artifact path with the flight recorder's ``{rank}``/``.rankN``
    expansion so ranks never clobber each other."""
    # hvdlint: disable=HVD003 (pre-package module, import-cycle-free)
    path = (os.environ.get(ENV_OUTPUT) or "").strip() or DEFAULT_OUTPUT
    rank = (os.environ.get("HOROVOD_RANK") or "").strip() or None  # hvdlint: disable=HVD003
    if "{rank}" in path:
        return path.replace("{rank}", rank if rank is not None else "0")
    if rank is not None:
        return f"{path}.rank{rank}"
    return path


def write_report(path: Optional[str] = None) -> Optional[str]:
    """Dump the conformance tally. Returns the path, or None when the
    monitor is off or the dump fails (never raises — the monitor must
    not fail the job it observes)."""
    if not protocheck_enabled():
        return None
    out = path or output_path()
    try:
        tmp = f"{out}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(_recorder.report(), f, indent=1, sort_keys=True)
            f.write("\n")
        os.replace(tmp, out)
    except OSError as exc:
        sys.stderr.write(f"protocheck: cannot write report: {exc}\n")
        return None
    return out


def _atexit_dump() -> None:
    if protocheck_enabled():
        write_report()


atexit.register(_atexit_dump)


class ProtocolMonitor:
    """One wire's conformance state machine: role, current state, and
    committed/pending membership epochs, advanced by every frame the
    wire sends or receives. Thread-safe (the heartbeat thread sends on
    the same wire the controller thread receives on)."""

    __slots__ = ("role", "state", "epoch", "pending_epoch", "_mu", "_rec")

    def __init__(self, role: str, recorder_: Optional[_Recorder] = None):
        if role not in SPEC:
            raise ValueError(f"unknown protocol role {role!r}")
        self.role = role
        self.state = SPEC[role]["initial"]
        self.epoch = INITIAL_EPOCH[role]
        # The epoch of the reshape currently in flight on this wire
        # (coordinator: sent, awaiting ack; worker/joiner: received,
        # awaiting our ack).
        self.pending_epoch: Optional[int] = None
        self._mu = threading.Lock()
        self._rec = recorder_ if recorder_ is not None else _recorder

    # -- guard evaluation ---------------------------------------------------

    def _guard_holds(self, guard: str, info: Optional[dict]
                     ) -> Tuple[bool, str]:
        if guard == "epoch_advances":
            new = (info or {}).get("epoch")
            if not isinstance(new, int):
                return False, f"reshape without an integer epoch: {info!r}"
            if not epoch_advances(new, self.epoch):
                return False, (f"epoch must advance: got {new}, committed "
                               f"epoch is {self.epoch}")
            return True, ""
        if guard == "ack_commits":
            ack = (info or {}).get("ack")
            if not isinstance(ack, int):
                # A join payload with no ack in the drain is a join hello
                # where an ack belongs.
                return False, f"expected a reshape ack, got {info!r}"
            pending = self.pending_epoch
            if pending is not None and ack == pending:
                return True, ""
            if pending is not None and epoch_is_stale(ack, pending):
                return True, ""  # superseded attempt's ack: drained
            return False, (f"ack for epoch {ack} but the pending reshape "
                           f"is epoch {pending}")
        if guard == "ack_matches":
            ack = (info or {}).get("ack")
            if not isinstance(ack, int):
                return False, f"expected a reshape ack, got {info!r}"
            if ack != self.pending_epoch:
                return False, (f"acked epoch {ack} but the assignment was "
                               f"epoch {self.pending_epoch}")
            return True, ""
        return False, f"unknown guard {guard!r}"

    def _commit(self, key: Tuple[str, str], entry: dict,
                info: Optional[dict]) -> None:
        """Apply the transition's epoch effects (mutates under _mu)."""
        direction, kind = key
        if kind == "reshape":
            self.pending_epoch = (info or {}).get("epoch")
        elif kind == "join" and entry.get("guard") in ("ack_commits",
                                                       "ack_matches"):
            ack = (info or {}).get("ack")
            if isinstance(ack, int) and ack == self.pending_epoch:
                self.epoch = ack
                self.pending_epoch = None
        self.state = entry["next"]

    # -- the one entry point ------------------------------------------------

    def observe(self, direction: str, kind_name: str,
                info: Optional[dict] = None) -> None:
        """Check one frame against the spec and advance the machine.
        Records (or raises, under ``HOROVOD_PROTOCHECK=raise``) on any
        off-spec transition; never blocks the wire otherwise."""
        if kind_name == "heartbeat":
            self._rec.note_ok()  # legal everywhere, state unchanged
            return
        with self._mu:
            states = SPEC[self.role]["states"]
            entry = states.get(self.state, {}).get((direction, kind_name))
            if entry is None:
                detail = (f"kind {kind_name!r} ({direction}) has no spec "
                          f"entry in state {self.state!r}")
            elif "violation" in entry:
                detail = entry["violation"]
            else:
                guard = entry.get("guard")
                if guard is not None:
                    ok, why = self._guard_holds(guard, info)
                    if not ok:
                        detail = f"guard {guard} failed: {why}"
                    else:
                        detail = None
                else:
                    detail = None
                if detail is None:
                    # ack_commits with a STALE ack stays in place (the
                    # drain keeps reading); everything else transitions.
                    if (entry.get("guard") == "ack_commits"
                            and isinstance((info or {}).get("ack"), int)
                            and (info or {})["ack"] != self.pending_epoch):
                        pass  # stale ack: drained, no state change
                    else:
                        self._commit((direction, kind_name), entry, info)
                    self._rec.note_ok()
                    return
            violation = {
                "role": self.role,
                "state": self.state,
                "direction": direction,
                "kind": kind_name,
                "epoch": self.epoch,
                "pending_epoch": self.pending_epoch,
                "detail": detail,
            }
        self._rec.note_violation(violation)
        if _protocheck_mode() == "raise":
            raise ProtocolViolationError(
                f"protocol violation: {self.role}.{violation['state']} "
                f"{direction} {kind_name}: {detail}")


def make_monitor(role: str) -> Optional[ProtocolMonitor]:
    """Factory the wire layer calls when a role is assigned: a live
    monitor under ``HOROVOD_PROTOCHECK``, None (zero cost) otherwise."""
    if not protocheck_enabled():
        return None
    return ProtocolMonitor(role)


# ---------------------------------------------------------------------------
# Static conformance: handler dispatch <-> spec bijection.
#
# HANDLERS maps each real dispatch site (file suffix + function qualname)
# to the (role, state, direction) combinations it serves. The checker
# parses the file, extracts the set of FRAME_* kinds the function
# branches on, and compares it against the union of kinds the spec
# declares (transition or violation) for those combinations:
#   * a spec kind the handler never branches on  -> "missing transition"
#   * a handler branch for a kind the spec bans  -> "unreachable transition"
# Any FRAME_* dispatch outside a declared handler is "handler drift".

HANDLERS = {
    # recv_bytes serves the lockstep data stream on both star sides and
    # the joiner's await_assignment (first real frame).
    "common/wire.py::Wire.recv_bytes": (
        ("worker", "steady", "recv"),
        ("coordinator", "steady", "recv"),
        ("joiner", "parked", "recv"),
    ),
    # recv_hello serves rendezvous + the elastic join listener.
    "common/wire.py::Wire.recv_hello": (
        ("coordinator", "handshake", "recv"),
    ),
    # recv_reshape_ack drains a member's wire to its ack.
    "common/wire.py::Wire.recv_reshape_ack": (
        ("coordinator", "draining", "recv"),
    ),
}

# FRAME_* mentions that are definitions/plumbing, not dispatch: listed so
# the drift scan can prove the handler table above is complete.
_NON_DISPATCH_ALLOWED = {
    "common/wire.py": {
        # Frame constructors (senders) and the frame-layer plumbing.
        "Wire.send_bytes", "Wire.send_heartbeat", "Wire.send_abort",
        "Wire.send_join", "Wire.send_reshape", "Wire.try_send_heartbeat",
        "Wire.send_clock_ping", "Wire._handle_clock_payload",
        "Wire.send_shard_fetch", "Wire.send_shard_data",
        "Wire._handle_shard_frame",
        "Wire._send_frame", "Wire._try_send_frame", "Wire._recv_frame",
        "<module>",  # FRAME_* constant definitions, _KNOWN_KINDS, names
    },
    "controller/service.py": {
        # The join listener compares the recv_hello RESULT kind — the
        # dispatch itself lives in recv_hello; this is admission
        # validation on top of it.
        "CoordinatorService.start_join_listener",
        "<module>",  # import
    },
    "controller/controller.py": {
        "<module>",
    },
}

_KIND_CONST_TO_NAME = {
    "FRAME_DATA": "data", "FRAME_HEARTBEAT": "heartbeat",
    "FRAME_ABORT": "abort", "FRAME_JOIN": "join",
    "FRAME_RESHAPE": "reshape", "FRAME_SHARD_FETCH": "shard_fetch",
    "FRAME_SHARD_DATA": "shard_data",
}


def _function_index(tree: ast.AST) -> Dict[str, ast.AST]:
    """{"Class.method" / "func" / "<module>": node} for one module."""
    index: Dict[str, ast.AST] = {"<module>": tree}

    def walk(node: ast.AST, prefix: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                name = f"{prefix}{child.name}"
                index[name] = child
                walk(child, f"{name}.")
            elif isinstance(child, ast.ClassDef):
                walk(child, f"{prefix}{child.name}.")
            else:
                walk(child, prefix)

    walk(tree, "")
    return index


def _kinds_referenced(node: ast.AST) -> "set[str]":
    """FRAME_* constant names referenced under ``node``, as kind names."""
    out = set()
    for sub in ast.walk(node):
        name = None
        if isinstance(sub, ast.Name):
            name = sub.id
        elif isinstance(sub, ast.Attribute):
            name = sub.attr
        if name in _KIND_CONST_TO_NAME:
            out.add(_KIND_CONST_TO_NAME[name])
    return out


def _owning_function(index: Dict[str, ast.AST], lineno: int) -> str:
    """Innermost indexed function containing ``lineno`` (else <module>)."""
    best = "<module>"
    best_span = None
    for qualname, node in index.items():
        if qualname == "<module>":
            continue
        end = getattr(node, "end_lineno", node.lineno)
        if node.lineno <= lineno <= end:
            span = end - node.lineno
            if best_span is None or span < best_span:
                best, best_span = qualname, span
    return best


def spec_kinds_for(combos) -> "set[str]":
    """Union of kinds with ANY spec entry (transition or declared
    violation) across ``(role, state, direction)`` combos — plus
    heartbeat, which is implicitly legal everywhere."""
    kinds = {"heartbeat"}
    for role, state, direction in combos:
        for (d, kind) in SPEC[role]["states"][state]:
            if d == direction:
                kinds.add(kind)
    return kinds


PROTOCOL_SURFACE = tuple(sorted({k.split("::")[0] for k in HANDLERS}
                                | set(_NON_DISPATCH_ALLOWED)))


def check_module(relsuffix: str, tree: ast.AST) -> List[dict]:
    """Handler↔spec bijection for ONE protocol-surface module (used by
    hvdlint HVD008 per file and by :func:`check_handlers` for the whole
    surface). Returns finding dicts with path/line/message."""
    findings: List[dict] = []
    index = _function_index(tree)
    declared = {k.split("::")[1]: combos
                for k, combos in HANDLERS.items()
                if k.split("::")[0] == relsuffix}
    # 1. Declared handlers: branch set == spec set.
    for qualname, combos in sorted(declared.items()):
        node = index.get(qualname)
        if node is None:
            findings.append({
                "path": relsuffix, "line": 0,
                "message": f"declared handler {qualname} no longer "
                           "exists (update protocol.HANDLERS)"})
            continue
        handled = _kinds_referenced(node)
        expected = spec_kinds_for(combos)
        for kind in sorted(expected - handled):
            findings.append({
                "path": relsuffix, "line": node.lineno,
                "message": f"handler {qualname} has no branch for "
                           f"frame kind {kind!r}, which the spec "
                           f"declares for {sorted(combos)} (missing "
                           "transition)"})
        for kind in sorted(handled - expected):
            findings.append({
                "path": relsuffix, "line": node.lineno,
                "message": f"handler {qualname} branches on frame "
                           f"kind {kind!r}, which the spec declares "
                           f"in none of {sorted(combos)} (unreachable "
                           "transition — extend the spec or delete "
                           "the branch)"})
    # 2. Drift: FRAME_* dispatch outside declared handlers/senders.
    allowed = set(declared) | _NON_DISPATCH_ALLOWED.get(relsuffix, set())
    seen_owners = set()
    for sub in ast.walk(tree):
        name = None
        if isinstance(sub, ast.Name):
            name = sub.id
        elif isinstance(sub, ast.Attribute):
            name = sub.attr
        if name not in _KIND_CONST_TO_NAME:
            continue
        owner = _owning_function(index, sub.lineno)
        # Attribute the mention to the outermost declared/allowed
        # scope: nested helpers inside an allowed function inherit.
        top = owner
        while top and top not in allowed and "." in top:
            top = top.rsplit(".", 1)[0]
        if owner in allowed or top in allowed:
            continue
        if owner not in seen_owners:
            seen_owners.add(owner)
            findings.append({
                "path": relsuffix, "line": sub.lineno,
                "message": f"frame-kind dispatch in {owner} is not "
                           "declared in protocol.HANDLERS (handler "
                           "drift): map it to spec states or list it "
                           "as a non-dispatch site"})
    return findings


def check_handlers(pkg_dir: str) -> List[dict]:
    """The static half of conformance: parse the whole protocol surface
    and prove handler↔spec bijection. Returns finding dicts (empty ==
    the code and the spec agree); each carries path/line/message so
    hvdlint (HVD008) and tools/protocheck can render them."""
    findings: List[dict] = []
    for relsuffix in PROTOCOL_SURFACE:
        path = os.path.join(pkg_dir, *relsuffix.split("/"))
        try:
            with open(path, encoding="utf-8") as f:
                tree = ast.parse(f.read(), filename=path)
        except (OSError, SyntaxError) as exc:
            findings.append({"path": relsuffix, "line": 0,
                             "message": f"cannot parse: {exc}"})
            continue
        findings.extend(check_module(relsuffix, tree))
    return findings


# ---------------------------------------------------------------------------
# Docs renderer (tools/protocheck --dump-spec; pasted into
# docs/static-analysis.md).


def render_state_tables() -> str:
    lines: List[str] = []
    for role in ROLES:
        lines.append(f"### role `{role}` (initial: "
                     f"`{SPEC[role]['initial']}`, epoch "
                     f"{INITIAL_EPOCH[role]})")
        lines.append("")
        lines.append("| state | event | outcome |")
        lines.append("| --- | --- | --- |")
        states = SPEC[role]["states"]
        for state in sorted(states):
            for (direction, kind), entry in sorted(states[state].items()):
                event = f"{direction} {kind}"
                if "violation" in entry:
                    outcome = f"VIOLATION — {entry['violation']}"
                else:
                    outcome = f"→ `{entry['next']}`"
                    if entry.get("guard"):
                        outcome += f" (guard: {entry['guard']})"
                    if entry.get("note"):
                        outcome += f" — {entry['note']}"
                lines.append(f"| `{state}` | {event} | {outcome} |")
            if not states[state]:
                lines.append(f"| `{state}` | — | terminal; only "
                             "heartbeats may still cross |")
        lines.append("")
    lines.append("(heartbeats are legal in every state, both directions, "
                 "and never change state — liveness rides below the "
                 "protocol.)")
    return "\n".join(lines) + "\n"


def iter_spec_entries() -> Iterator[Tuple[str, str, str, str, dict]]:
    """(role, state, direction, kind, entry) over the whole spec."""
    for role in ROLES:
        states = SPEC[role]["states"]
        for state in sorted(states):
            for (direction, kind), entry in sorted(states[state].items()):
                yield role, state, direction, kind, entry
