"""hvdlint distributed-correctness rules (HVD001..HVD011).

Each rule encodes one invariant the runtime depends on but cannot check
until a job is already hung:

* HVD001 — a collective call lexically inside a rank-conditional branch.
  Every rank must issue the same collectives in the same order (the
  coordinator's negotiation assumes it; SURVEY §L1); a collective only
  some ranks reach deadlocks the rest. Intentional subgroup collectives
  (e.g. the hierarchical cross-ring on local roots) carry a suppression.
* HVD002 — unordered dict/set iteration in controller/negotiation paths.
  Wire payload construction and response walks must be identical on
  every rank; dict insertion order is process-local history. Wrap the
  walk in ``sorted(...)``.
* HVD003 — ``os.environ`` value reads outside ``common/config.py``.
  Config has exactly one choke point so every rank parses a knob the
  same way; a stray read invents a second, subtly different parser.
  Mutations (``os.environ[...] = v``, ``.pop``, ``.update``) and
  membership tests stay allowed — exporting env to children is the
  launcher's job.
* HVD004 — ``time.time()`` where a duration/deadline is being measured.
  Wall clocks step (NTP) and a stepped deadline fires early or never;
  ``time.monotonic()`` is the duration clock. Wall-clock *anchors*
  (trace clock-sync, event timestamps) are legitimate and carry
  suppressions.
* HVD005 — ``threading.Thread`` without explicit ``name=`` and
  ``daemon=``. An anonymous non-daemon thread is invisible in stack
  dumps and blocks interpreter exit; every spawn site must decide both.
* HVD006 — import-time side effects: metric registration, env value
  reads, or thread spawns at module top level. Importing must be free
  (the zero-overhead-off telemetry contract and fork semantics depend
  on it).
* HVD007 — metric catalog discipline: every literal metric name
  registered via ``counter()``/``gauge()``/``histogram()`` must be
  ``hvd_``-prefixed snake_case and have exactly one owning call site
  (the AST successor of the regex checks in tests/test_metrics_lint.py).
* HVD008 — wire-protocol handler completeness: the frame-kind dispatch
  in ``wire.py`` must stay a bijection with the declarative protocol
  spec (``analysis/protocol.py``) — a missing branch is a frame the
  code cannot handle, an extra branch is a transition the spec does not
  know (drift either way; the C++ port inherits the spec).
* HVD009 — membership epochs compared with raw ``<``/``>`` instead of
  the sanctioned monotonic helpers (``epoch_advances``/
  ``epoch_is_stale``): one auditable definition of "newer epoch" for
  the runtime, the reshape drain, and the conformance monitor.
* HVD010 — cross-language ABI drift: a ctypes declaration in
  ``core/bindings.py`` that disagrees with the ``extern "C"``
  definition in the C++ core (arg count, ctype compatibility, restype)
  — the hvdabi extractor (``analysis/cpp.py``) checks this with a
  parse, not a rebuild. Never baselinable.
* HVD011 — native counter/series mirror drift: the metrics package
  consuming a counter key the C layout does not define, or registering
  a ``hvd_native_*``/``hvd_ring_*`` series with no owning counter slot
  in ``analysis/cpp.NATIVE_SERIES_MAP``. Never baselinable.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Optional, Tuple, Type

from .dataflow import (  # noqa: F401  (COLLECTIVE_NAMES/RANK_NAMES/
    COLLECTIVE_NAMES,    # _mentions_rank are part of this module's
    RANK_NAMES,          # historical public surface)
    call_name as _call_name,
    iter_divergent_collectives,
    mentions_rank as _mentions_rank,
)
from .framework import Finding, Rule, SourceFile

CONFIG_MODULE_SUFFIX = "common/config.py"

METRIC_FACTORIES = frozenset({"counter", "gauge", "histogram"})
_METRIC_NAME_RE = re.compile(r"^hvd_[a-z][a-z0-9_]*$")


def _is_os_environ(node: ast.AST) -> bool:
    return (isinstance(node, ast.Attribute) and node.attr == "environ"
            and isinstance(node.value, ast.Name) and node.value.id == "os")


class DivergentCollectiveRule(Rule):
    code = "HVD001"
    name = "divergent-collective"
    description = ("collective call inside a rank-conditional branch — "
                   "directly or reached through module-local helper calls "
                   "(call-graph + rank-taint dataflow): ranks taking the "
                   "other branch never enqueue it and the job deadlocks "
                   "at negotiation")

    def __init__(self, interprocedural: bool = True):
        # interprocedural=False reproduces the round-10 lexical rule
        # exactly; tests pin its blind spots against the upgraded pass.
        self.interprocedural = interprocedural

    def check(self, src: SourceFile) -> Iterator[Finding]:
        def suppressed(line: int) -> bool:
            return src.is_suppressed(self.code, line)

        for node, message in iter_divergent_collectives(
                src.tree, is_suppressed=suppressed,
                interprocedural=self.interprocedural):
            yield self.finding(src, node, message)


class UnorderedIterationRule(Rule):
    code = "HVD002"
    name = "unordered-controller-iteration"
    description = ("unordered dict/set iteration in controller/negotiation "
                   "paths: wire payloads and response walks must be "
                   "identical on every rank — wrap in sorted(...)")

    PATH_MARKERS = ("controller/",)
    METHODS = frozenset({"items", "keys", "values"})

    def __init__(self, all_paths: bool = False):
        # all_paths=True drops the controller/ scoping — the aux gate
        # over tests/ and examples/ uses it (mp scenario bodies run on
        # every rank; a dict-order-dependent expectation is a flake).
        self.all_paths = all_paths

    def check(self, src: SourceFile) -> Iterator[Finding]:
        if not self.all_paths and \
                not any(m in src.relpath for m in self.PATH_MARKERS):
            return
        sorted_args = set()
        for node in ast.walk(src.tree):
            if (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)
                    and node.func.id == "sorted" and node.args):
                sorted_args.add(id(node.args[0]))
        for node in ast.walk(src.tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in self.METHODS
                    and not node.args and not node.keywords
                    and id(node) not in sorted_args):
                yield self.finding(
                    src, node,
                    f"unordered '.{node.func.attr}()' walk in a controller "
                    "path; dict order is process-local history — wrap in "
                    "sorted(...) so every rank walks the same order")


class StrayEnvReadRule(Rule):
    code = "HVD003"
    name = "stray-env-read"
    description = ("os.environ value read outside common/config.py: all "
                   "knob parsing goes through the config accessors so "
                   "every rank agrees on malformed values")

    def check(self, src: SourceFile) -> Iterator[Finding]:
        if src.relpath.endswith(CONFIG_MODULE_SUFFIX):
            return
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Call):
                func = node.func
                if (isinstance(func, ast.Attribute) and func.attr == "get"
                        and _is_os_environ(func.value)):
                    yield self._found(src, node)
                elif (isinstance(func, ast.Attribute)
                      and func.attr == "getenv"
                      and isinstance(func.value, ast.Name)
                      and func.value.id == "os"):
                    yield self._found(src, node)
            elif (isinstance(node, ast.Subscript)
                  and _is_os_environ(node.value)
                  and isinstance(getattr(node, "ctx", None), ast.Load)):
                # ctx distinguishes reads from writes/deletes on its own:
                # `os.environ["K"] = v` is a Store, `del ...` a Del. Only
                # Load-context subscripts are value reads.
                yield self._found(src, node)

    def _found(self, src: SourceFile, node: ast.AST) -> Finding:
        var = None
        key = None
        if isinstance(node, ast.Call) and node.args:
            key = node.args[0]
        elif isinstance(node, ast.Subscript):
            key = node.slice
        if isinstance(key, ast.Constant) and isinstance(key.value, str):
            var = key.value
        what = f" of {var!r}" if var else ""
        return self.finding(
            src, node,
            f"direct os.environ read{what} bypasses common/config.py; "
            "add/use a config accessor so every consumer parses the knob "
            "identically")


class WallClockDeadlineRule(Rule):
    code = "HVD004"
    name = "wall-clock-duration"
    description = ("time.time() used where durations/deadlines are "
                   "measured; wall clocks step under NTP — use "
                   "time.monotonic() (wall-clock anchors carry a "
                   "suppression)")

    def check(self, src: SourceFile) -> Iterator[Finding]:
        bare_time_imported = any(
            isinstance(node, ast.ImportFrom) and node.module == "time"
            and any(a.name == "time" for a in node.names)
            for node in ast.walk(src.tree))
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            hit = (isinstance(func, ast.Attribute) and func.attr == "time"
                   and isinstance(func.value, ast.Name)
                   and func.value.id == "time")
            hit = hit or (bare_time_imported and isinstance(func, ast.Name)
                          and func.id == "time")
            if hit:
                yield self.finding(
                    src, node,
                    "time.time() in runtime code: use time.monotonic() for "
                    "durations/deadlines; a genuine wall-clock anchor "
                    "(trace clock-sync, event timestamps) should carry "
                    "'# hvdlint: disable=HVD004'")


class AnonymousThreadRule(Rule):
    code = "HVD005"
    name = "anonymous-thread"
    description = ("threading.Thread without explicit name= and daemon=: "
                   "anonymous threads are invisible in stack dumps and an "
                   "implicit daemon=False blocks interpreter exit")

    def check(self, src: SourceFile) -> Iterator[Finding]:
        thread_imported = any(
            isinstance(node, ast.ImportFrom) and node.module == "threading"
            and any(a.name == "Thread" for a in node.names)
            for node in ast.walk(src.tree))
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            hit = (isinstance(func, ast.Attribute) and func.attr == "Thread"
                   and isinstance(func.value, ast.Name)
                   and func.value.id == "threading")
            hit = hit or (thread_imported and isinstance(func, ast.Name)
                          and func.id == "Thread")
            if not hit:
                continue
            kwargs = {kw.arg for kw in node.keywords}
            missing = [k for k in ("name", "daemon") if k not in kwargs]
            if missing:
                missing_txt = " and ".join(m + "=" for m in missing)
                yield self.finding(
                    src, node,
                    f"threading.Thread without explicit {missing_txt}; "
                    "name every thread (hvd-*) and state daemon-ness "
                    "explicitly")


class ImportTimeSideEffectRule(Rule):
    code = "HVD006"
    name = "import-time-side-effect"
    description = ("module-top-level side effect (metric registration, env "
                   "value read, thread spawn): importing must be free — "
                   "the zero-overhead telemetry and fork contracts depend "
                   "on it")

    def check(self, src: SourceFile) -> Iterator[Finding]:
        for stmt in self._top_level_statements(src.tree):
            for node in ast.walk(stmt):
                if not isinstance(node, ast.Call):
                    continue
                cname = _call_name(node)
                func = node.func
                if cname in METRIC_FACTORIES and self._is_registration(node):
                    yield self.finding(
                        src, node,
                        f"metric '{cname}(...)' registered at import time; "
                        "registration must be lazy (first use), see the "
                        "_m/SimpleNamespace convention")
                elif (isinstance(func, ast.Attribute) and func.attr == "get"
                      and _is_os_environ(func.value)) or (
                          isinstance(func, ast.Attribute)
                          and func.attr == "getenv"
                          and isinstance(func.value, ast.Name)
                          and func.value.id == "os"):
                    yield self.finding(
                        src, node,
                        "env value read at import time: module constants "
                        "must not freeze the environment before the "
                        "launcher/runtime finished exporting it — read "
                        "lazily through common/config.py")
                elif (isinstance(func, ast.Attribute)
                      and func.attr == "Thread"
                      and isinstance(func.value, ast.Name)
                      and func.value.id == "threading"):
                    yield self.finding(
                        src, node,
                        "thread spawned at import time: threads don't "
                        "survive fork and import order becomes a runtime "
                        "dependency — spawn from init paths")

    @staticmethod
    def _top_level_statements(tree: ast.Module):
        """Module-level statements, descending into top-level if/try
        bodies (the common guard patterns) but never into defs/classes."""
        pending = list(tree.body)
        while pending:
            stmt = pending.pop(0)
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            if isinstance(stmt, (ast.If, ast.Try)):
                for field in ("body", "orelse", "finalbody"):
                    pending.extend(getattr(stmt, field, []) or [])
                for h in getattr(stmt, "handlers", []) or []:
                    pending.extend(h.body)
                continue
            yield stmt

    @staticmethod
    def _is_registration(node: ast.Call) -> bool:
        """A registration passes a literal metric name first — matching
        HVD007's notion of a catalog entry."""
        return bool(node.args) and isinstance(node.args[0], ast.Constant) \
            and isinstance(node.args[0].value, str)


class MetricCatalogRule(Rule):
    code = "HVD007"
    name = "metric-catalog"
    description = ("registered metric names must be unique (one owning "
                   "call site), snake_case, and hvd_-prefixed — the "
                   "telemetry namespace stays coherent as PRs add series")

    def __init__(self):
        # Cross-file state for the duration of one run_lint() pass (a
        # fresh instance per run): first-seen call site per metric name.
        self._seen: Dict[str, str] = {}

    def check(self, src: SourceFile) -> Iterator[Finding]:
        for name, node in self.registrations(src.tree):
            if not _METRIC_NAME_RE.match(name):
                yield self.finding(
                    src, node,
                    f"metric name {name!r} violates the catalog convention "
                    "(want hvd_ + lowercase snake_case)")
            first = self._seen.get(name)
            if first is None:
                self._seen[name] = f"{src.relpath}:{node.lineno}"
            else:
                yield self.finding(
                    src, node,
                    f"metric {name!r} registered at more than one call "
                    f"site (first owner: {first}); each name must have "
                    "exactly one owner")

    @staticmethod
    def registrations(tree: ast.AST) -> Iterator[Tuple[str, ast.Call]]:
        """Every ``counter/gauge/histogram("literal", ...)`` call —
        the shared definition of "a catalog entry" (test_metrics_lint
        builds its name inventory on this)."""
        for node in ast.walk(tree):
            if (isinstance(node, ast.Call)
                    and _call_name(node) in METRIC_FACTORIES
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                yield node.args[0].value, node


class ProtocolHandlerRule(Rule):
    code = "HVD008"
    name = "protocol-handler-completeness"
    description = ("frame-kind dispatch must stay a bijection with the "
                   "declarative wire-protocol spec "
                   "(analysis/protocol.py): a missing branch is a frame "
                   "the code cannot handle, an extra one is a transition "
                   "the spec does not know — drift either way, and the "
                   "C++ port inherits the spec")

    def check(self, src: SourceFile) -> Iterator[Finding]:
        from . import protocol

        relsuffix = next(
            (s for s in protocol.PROTOCOL_SURFACE
             if src.relpath.endswith(s)), None)
        if relsuffix is None:
            return
        for entry in protocol.check_module(relsuffix, src.tree):
            yield Finding(rule=self.code, path=src.relpath,
                          line=entry["line"], col=0,
                          message=entry["message"])


class RawEpochComparisonRule(Rule):
    code = "HVD009"
    name = "raw-epoch-comparison"
    description = ("membership epoch compared with raw </>: use the "
                   "sanctioned monotonic helpers (epoch_advances / "
                   "epoch_is_stale in analysis/protocol.py) so the "
                   "runtime, the reshape drain, and the conformance "
                   "monitor share ONE definition of \"newer epoch\"")

    # The membership-epoch protocol surface. keras/run training/restart
    # "epoch"s are a different concept and stay out of scope.
    PATH_MARKERS = ("common/wire.py", "controller/", "elastic/")
    _ORDERING_OPS = (ast.Lt, ast.Gt, ast.LtE, ast.GtE)

    @staticmethod
    def _names_epoch(node: ast.AST) -> bool:
        for sub in ast.walk(node):
            name = None
            if isinstance(sub, ast.Name):
                name = sub.id
            elif isinstance(sub, ast.Attribute):
                name = sub.attr
            if name is not None and "epoch" in name.lower():
                return True
        return False

    def check(self, src: SourceFile) -> Iterator[Finding]:
        if not any(m in src.relpath for m in self.PATH_MARKERS):
            return
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Compare):
                continue
            if not any(isinstance(op, self._ORDERING_OPS)
                       for op in node.ops):
                continue  # ==/!= are fine: equality is not an ordering
            if self._names_epoch(node):
                yield self.finding(
                    src, node,
                    "membership epoch compared with a raw ordering "
                    "operator; use epoch_advances()/epoch_is_stale() "
                    "(analysis/protocol.py) — the sanctioned monotonic "
                    "helpers the conformance monitor shares")


class AbiDriftRule(Rule):
    code = "HVD010"
    name = "abi-drift"
    description = ("ctypes declaration in core/bindings.py disagrees "
                   "with the extern \"C\" definition in the C++ core "
                   "(arg count, ctype compatibility, restype) — checked "
                   "statically by hvdabi (analysis/cpp.py), no rebuild")

    def check(self, src: SourceFile) -> Iterator[Finding]:
        if not src.relpath.endswith("core/bindings.py"):
            return
        # The C++ sources are located from the installed package tree
        # (cpp module's own location), NOT from src.abspath: fixture
        # tests hand lint_source() fake paths.
        from . import cpp

        for f in cpp.bindings_source_findings(src.source):
            yield Finding(rule=self.code, path=src.relpath,
                          line=f["line"] or 1, col=0,
                          message=f["message"])


class CounterDriftRule(Rule):
    code = "HVD011"
    name = "counter-series-drift"
    description = ("native counter/series mirror drift: the metrics "
                   "package consumes a counter key the C layout does "
                   "not define, or registers a hvd_native_*/hvd_ring_* "
                   "series with no owning counter slot in "
                   "analysis/cpp.NATIVE_SERIES_MAP")

    def check(self, src: SourceFile) -> Iterator[Finding]:
        if not src.relpath.endswith("metrics/__init__.py"):
            return
        from . import cpp

        for f in cpp.metrics_source_findings(src.source):
            yield Finding(rule=self.code, path=src.relpath,
                          line=f["line"] or 1, col=0,
                          message=f["message"])


ALL_RULES: List[Type[Rule]] = [
    DivergentCollectiveRule,
    UnorderedIterationRule,
    StrayEnvReadRule,
    WallClockDeadlineRule,
    AnonymousThreadRule,
    ImportTimeSideEffectRule,
    MetricCatalogRule,
    ProtocolHandlerRule,
    RawEpochComparisonRule,
    AbiDriftRule,
    CounterDriftRule,
]


def aux_rules() -> List[Rule]:
    """The scoped rule-set for the ``tests/`` + ``examples/`` scan
    (docs/static-analysis.md): mp scenario bodies run on every rank, so
    a dict-order-dependent expectation (HVD002, unscoped here) is a
    flake and an anonymous thread (HVD005) hides hangs; example scripts
    are copied into user jobs, so import-time side effects (HVD006)
    propagate. Pre-existing findings live in .hvdlint-aux-baseline.json
    — a ratchet like the package baseline, minus the size cap."""
    return [UnorderedIterationRule(all_paths=True), AnonymousThreadRule(),
            ImportTimeSideEffectRule()]


def get_rule(code: str) -> Type[Rule]:
    for cls in ALL_RULES:
        if cls.code == code.upper():
            return cls
    raise KeyError(f"unknown hvdlint rule {code!r}")
