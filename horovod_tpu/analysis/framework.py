"""hvdlint core: findings, rule plugin API, suppressions, baseline, reporters.

Deliberately small and dependency-free (stdlib ``ast`` only). The shape
follows the classic linter architecture — parse each file once, hand the
tree to every registered rule, post-filter through inline suppressions
and the checked-in baseline — but the rules themselves are
project-specific distributed-correctness checks (``rules.py``), which is
the whole point: generic linters cannot know that a collective inside a
rank-conditional branch deadlocks the job.

Suppression syntax (same line or the line directly above the finding)::

    blobs = self._collect()  # hvdlint: disable=HVD002 <reason>
    # hvdlint: disable=HVD001,HVD004
    # hvdlint: disable=all

Baseline workflow: ``python -m horovod_tpu.tools.lint --write-baseline``
records today's findings keyed by ``(rule, path, message)`` — NOT line
numbers, so unrelated edits don't invalidate entries — and subsequent
runs report only NEW findings. The gate test (``tests/test_lint.py``)
fails on any non-baselined finding, keeping the package clean as it
grows.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Tuple

# The pragma may sit anywhere inside a comment ("... rationale.
# hvdlint: disable=HVD004"); the code list ends at the first character
# that can't be part of a code, so trailing prose is ignored.
_SUPPRESS_RE = re.compile(
    r"#.*?hvdlint:\s*disable=([A-Za-z0-9_]+(?:\s*,\s*[A-Za-z0-9_]+)*)")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One lint finding. ``path`` is repo/package-relative for stable
    baselines and readable reports."""

    rule: str          # "HVD001"
    path: str          # "horovod_tpu/controller/controller.py"
    line: int          # 1-based
    col: int           # 0-based (ast convention)
    message: str

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"


class SourceFile:
    """Parsed view of one source file, shared by every rule."""

    def __init__(self, abspath: str, relpath: str, source: str):
        self.abspath = abspath
        self.relpath = relpath
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=relpath)
        self._suppressed: Optional[Dict[int, set]] = None

    @classmethod
    def read(cls, abspath: str, relpath: Optional[str] = None) -> "SourceFile":
        with open(abspath, encoding="utf-8") as f:
            source = f.read()
        return cls(abspath, relpath or abspath, source)

    # -- suppressions -------------------------------------------------------

    def _suppressions(self) -> Dict[int, set]:
        """{1-based line: {"HVD001", ...} or {"ALL"}} from inline pragmas."""
        if self._suppressed is None:
            table: Dict[int, set] = {}
            for i, text in enumerate(self.lines, start=1):
                m = _SUPPRESS_RE.search(text)
                if m:
                    codes = {c.strip().upper()
                             for c in m.group(1).split(",") if c.strip()}
                    table[i] = codes
            self._suppressed = table
        return self._suppressed

    def is_suppressed(self, rule: str, line: int) -> bool:
        """A finding at ``line`` is suppressed by a pragma on that line or
        on the line directly above it (for wrapped/long statements)."""
        table = self._suppressions()
        for candidate in (line, line - 1):
            codes = table.get(candidate)
            if codes and ("ALL" in codes or rule.upper() in codes):
                return True
        return False


class Rule:
    """Plugin base. Subclasses set ``code``/``name``/``description`` and
    implement :meth:`check` yielding findings. ``finding()`` is the one
    constructor so messages stay uniform."""

    code: str = "HVD000"
    name: str = "base"
    description: str = ""

    def check(self, src: SourceFile) -> Iterator[Finding]:
        raise NotImplementedError

    def finding(self, src: SourceFile, node: ast.AST, message: str) -> Finding:
        return Finding(rule=self.code, path=src.relpath,
                       line=getattr(node, "lineno", 1),
                       col=getattr(node, "col_offset", 0), message=message)


@dataclasses.dataclass
class LintResult:
    findings: List[Finding]          # new (reported) findings
    baselined: List[Finding]         # matched a baseline entry
    suppressed_count: int
    files_scanned: int
    parse_errors: List[Tuple[str, str]]  # (path, error)


# ---------------------------------------------------------------------------
# Walking + running


def iter_python_files(paths: Sequence[str],
                      root: Optional[str] = None,
                      exclude_dirs: Sequence[str] = ("__pycache__",),
                      ) -> Iterator[Tuple[str, str]]:
    """Yield ``(abspath, relpath)`` for every ``.py`` under ``paths``
    (files accepted directly), skipping directories named in
    ``exclude_dirs`` (``__pycache__`` by default; the aux test/example
    scan also drops ``lint_fixtures`` — fixtures fire by design).
    ``relpath`` is relative to ``root`` (default: each path's parent
    directory), with ``/`` separators so baselines are platform-stable."""
    skip = set(exclude_dirs) | {"__pycache__"}
    for path in paths:
        path = os.path.abspath(path)
        base = os.path.abspath(root) if root else os.path.dirname(path)
        if os.path.isfile(path):
            yield path, os.path.relpath(path, base).replace(os.sep, "/")
            continue
        for dirpath, dirnames, filenames in os.walk(path):
            dirnames[:] = sorted(d for d in dirnames if d not in skip)
            for fname in sorted(filenames):
                if fname.endswith(".py"):
                    ap = os.path.join(dirpath, fname)
                    yield ap, os.path.relpath(ap, base).replace(os.sep, "/")


def run_lint(paths: Sequence[str], rules: Optional[Sequence[Rule]] = None,
             baseline: Optional[Iterable[dict]] = None,
             root: Optional[str] = None,
             select: Optional[Sequence[str]] = None,
             exclude_dirs: Sequence[str] = ("__pycache__",)) -> LintResult:
    """Run ``rules`` over every python file under ``paths``.

    ``baseline`` is an iterable of entry dicts (see :func:`load_baseline`);
    matching findings are moved to ``result.baselined``. ``select``
    restricts to specific rule codes. Unparseable files are reported in
    ``parse_errors`` instead of crashing the whole run (the gate test
    fails on those too — a syntax error in the package is a finding)."""
    if rules is None:
        from .rules import ALL_RULES

        rules = [cls() for cls in ALL_RULES]
    if select:
        wanted = {c.upper() for c in select}
        rules = [r for r in rules if r.code in wanted]
    # MULTISET of baseline keys: each entry absorbs exactly one finding.
    # A plain set would make one grandfathered HVD004 entry silently
    # exempt every future wall-clock violation in that file (messages
    # are file-invariant for several rules).
    baseline_budget: Dict[Tuple[str, str, str], int] = {}
    for e in (baseline or []):
        if str(e.get("rule", "")) in NEVER_BASELINE:
            # ABI/counter drift must never be grandfathered: a baseline
            # entry for these rules (hand-edited in) is simply ignored,
            # so the finding still fails the gate.
            continue
        k = baseline_key(e)
        baseline_budget[k] = baseline_budget.get(k, 0) + 1

    findings: List[Finding] = []
    baselined: List[Finding] = []
    suppressed = 0
    scanned = 0
    errors: List[Tuple[str, str]] = []
    for abspath, relpath in iter_python_files(paths, root=root,
                                              exclude_dirs=exclude_dirs):
        try:
            src = SourceFile.read(abspath, relpath)
        except (SyntaxError, UnicodeDecodeError, OSError) as exc:
            errors.append((relpath, str(exc)))
            continue
        scanned += 1
        for rule in rules:
            for f in rule.check(src):
                if src.is_suppressed(f.rule, f.line):
                    suppressed += 1
                elif baseline_budget.get(baseline_key(f.as_dict()), 0) > 0:
                    baseline_budget[baseline_key(f.as_dict())] -= 1
                    baselined.append(f)
                else:
                    findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    baselined.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return LintResult(findings=findings, baselined=baselined,
                      suppressed_count=suppressed, files_scanned=scanned,
                      parse_errors=errors)


def lint_source(source: str, relpath: str,
                rules: Optional[Sequence[Rule]] = None) -> List[Finding]:
    """Lint one in-memory source blob (fixture tests, editor plugins).
    ``relpath`` matters: path-scoped rules (HVD002) key on it. Inline
    suppressions apply; no baseline."""
    if rules is None:
        from .rules import ALL_RULES

        rules = [cls() for cls in ALL_RULES]
    src = SourceFile(relpath, relpath, source)
    out: List[Finding] = []
    for rule in rules:
        out.extend(f for f in rule.check(src)
                   if not src.is_suppressed(f.rule, f.line))
    out.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return out


# ---------------------------------------------------------------------------
# Baseline io


def baseline_key(entry: dict) -> Tuple[str, str, str]:
    """Stable identity for a finding: rule + path + message. Line numbers
    are deliberately excluded — they drift with every unrelated edit."""
    return (str(entry.get("rule", "")), str(entry.get("path", "")),
            str(entry.get("message", "")))


def load_baseline(path: str) -> List[dict]:
    """Entries from a baseline file; a missing file is an empty baseline
    (the common case for new checkouts), malformed JSON raises."""
    try:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
    except FileNotFoundError:
        return []
    entries = data.get("findings", []) if isinstance(data, dict) else data
    if not isinstance(entries, list):
        raise ValueError(f"baseline {path}: expected a list of findings")
    return entries


#: Rules whose findings may NEVER be baselined: cross-language ABI and
#: counter/series drift (HVD010/HVD011) describe a contract that is
#: already broken on disk — grandfathering one ships the round-10
#: stack-garbage bug class. ``write_baseline`` refuses them and
#: ``run_lint`` ignores hand-edited baseline entries carrying them.
NEVER_BASELINE = frozenset({"HVD010", "HVD011"})


def write_baseline(path: str, findings: Sequence[Finding]) -> str:
    """Write the grandfather file. Line numbers are recorded for human
    orientation only; matching ignores them (see :func:`baseline_key`).

    Raises ``ValueError`` for findings from :data:`NEVER_BASELINE`
    rules — ABI drift must be fixed, not grandfathered."""
    refused = sorted({f.rule for f in findings if f.rule in NEVER_BASELINE})
    if refused:
        raise ValueError(
            "refusing to baseline %s finding(s): ABI/counter drift must "
            "be fixed, never grandfathered (docs/static-analysis.md)"
            % ", ".join(refused))
    entries = [f.as_dict() for f in
               sorted(findings, key=lambda f: (f.path, f.line, f.rule))]
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"comment": "hvdlint baseline: grandfathered findings. "
                              "Matching ignores line numbers; shrink this "
                              "file, never grow it (docs/static-analysis.md).",
                   "findings": entries}, f, indent=1, sort_keys=True)
        f.write("\n")
    return path


# ---------------------------------------------------------------------------
# Reporters


def render_text(result: LintResult, verbose: bool = False) -> str:
    lines = [f.render() for f in result.findings]
    for path, err in result.parse_errors:
        lines.append(f"{path}:0:0: PARSE-ERROR {err}")
    if verbose and result.baselined:
        lines.append("")
        lines.extend("baselined: " + f.render() for f in result.baselined)
    lines.append(
        f"hvdlint: {len(result.findings)} finding(s), "
        f"{len(result.baselined)} baselined, "
        f"{result.suppressed_count} suppressed, "
        f"{result.files_scanned} file(s) scanned")
    return "\n".join(lines) + "\n"


def render_json(result: LintResult) -> str:
    return json.dumps({
        "findings": [f.as_dict() for f in result.findings],
        "baselined": [f.as_dict() for f in result.baselined],
        "suppressed_count": result.suppressed_count,
        "files_scanned": result.files_scanned,
        "parse_errors": [{"path": p, "error": e}
                         for p, e in result.parse_errors],
    }, indent=1, sort_keys=True) + "\n"
