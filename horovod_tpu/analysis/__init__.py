"""Project-specific static analysis (``hvdlint``) + runtime lock checking.

Horovod's core invariant — every rank issues the same collectives in the
same order — is enforced at runtime by the coordinator; the round-9
tracing additionally assumes every rank walks the identical
bypass+responses order, and the fault-tolerance plane assumes ~25 locks
across wire/controller/metrics/heartbeats never invert. Nothing checked
any of this before a 256-chip job hung. This package does, in two
complementary ways:

* :mod:`~horovod_tpu.analysis.framework` + :mod:`~horovod_tpu.analysis.rules`
  — an AST-based lint over the package source with distributed-correctness
  rules (HVD001..HVD011), ``# hvdlint: disable=RULE`` suppressions, a
  checked-in baseline for grandfathered findings (HVD010/HVD011 — the
  cross-language ABI rules — are ``NEVER_BASELINE``), and JSON/text
  reporters.
  CLI: ``python -m horovod_tpu.tools.lint``; gate: ``tests/test_lint.py``.
* :mod:`~horovod_tpu.analysis.dataflow` — the call-graph + rank-taint
  machinery behind the interprocedural rules (HVD001 catches a
  collective reached through helper calls under a rank conditional).
* :mod:`~horovod_tpu.analysis.protocol` — the machine-readable wire/epoch
  protocol: ONE declarative state-machine spec per wire-peer role,
  checked statically against the real handler dispatch (HVD008,
  ``python -m horovod_tpu.tools.protocheck``) and dynamically by the
  opt-in ``HOROVOD_PROTOCHECK=1`` runtime monitor in ``Wire``.
* :mod:`~horovod_tpu.analysis.lockorder` — a runtime lock-order detector
  (``HOROVOD_LOCKCHECK=1``): tracked locks record the global acquisition-
  order graph and report cycles (potential deadlocks) with both stacks;
  plus the STATIC potential-order graph (:func:`lockorder.static_graph`)
  and the static×runtime join (:func:`lockorder.join_reports`) that
  reports statically-possible cycles never observed at runtime.
* :mod:`~horovod_tpu.analysis.autofix` — mechanical ``--fix`` repairs
  for HVD002/HVD005 (idempotent by construction).
* :mod:`~horovod_tpu.analysis.cpp` — the hvdabi cross-language plane: a
  declarative (no-compiler) extractor over the C++ core's
  ``extern "C"`` signatures, counter-slot layout, frame-kind anchors,
  and mutex regions, with checkers for the ABI bijection
  (``bindings.py`` ↔ C ↔ tf_ops ``CoreApi``), counter/metrics parity,
  native frame-kind coverage (``protocheck --native``), and the C++
  half of the whole-process static lock graph.
  CLI: ``python -m horovod_tpu.tools.abicheck``.

Everything here is stdlib-only and import-light: ``common/wire.py`` (and
every other hot module) imports :func:`~horovod_tpu.analysis.lockorder.make_lock`
at module load, so this package must never pull in numpy/jax.

See ``docs/static-analysis.md`` for the rule catalog and workflows.
"""

from .framework import (  # noqa: F401
    NEVER_BASELINE,
    Finding,
    LintResult,
    Rule,
    SourceFile,
    baseline_key,
    iter_python_files,
    lint_source,
    load_baseline,
    render_json,
    render_text,
    run_lint,
    write_baseline,
)
from .lockorder import (  # noqa: F401
    LockGraph,
    TrackedLock,
    find_cycles,
    join_reports,
    lockcheck_enabled,
    make_lock,
    static_graph,
)
from .protocol import (  # noqa: F401
    ProtocolMonitor,
    ProtocolViolationError,
    epoch_advances,
    epoch_is_stale,
    protocheck_enabled,
)
from .rules import ALL_RULES, aux_rules, get_rule  # noqa: F401

__all__ = [
    "NEVER_BASELINE",
    "Finding", "LintResult", "Rule", "SourceFile", "baseline_key",
    "iter_python_files", "lint_source", "load_baseline", "render_json",
    "render_text", "run_lint", "write_baseline", "ALL_RULES", "aux_rules",
    "get_rule", "LockGraph", "TrackedLock", "find_cycles", "join_reports",
    "lockcheck_enabled", "make_lock", "static_graph", "ProtocolMonitor",
    "ProtocolViolationError", "epoch_advances", "epoch_is_stale",
    "protocheck_enabled",
]
