"""Call-graph + dataflow machinery behind the interprocedural lint rules.

The round-10 rules were purely lexical: HVD001 could only see a
collective call *textually* inside a rank-conditional branch, so

    if rank == 0:
        warm_up()          # warm_up() -> _sync() -> hvd.barrier()

slipped straight through — exactly the divergent-collective deadlock the
rule exists to catch, one helper call away. This module adds the two
pieces that close that hole, shared by ``rules.py`` (HVD001) and the
static lock-graph pass (``lockorder.static_graph``):

* **Module call graph** (:class:`ModuleFunctions`): every function and
  method in one module, indexed by qualified and bare name; call sites
  are resolved by the called object's trailing identifier
  (``self._helper(...)`` → every ``_helper`` in the module). Resolution
  is deliberately an over-approximation — for "could this reach a
  collective / acquire a lock" questions a superset answer is the safe
  one, false negatives are the expensive ones.
* **Rank-taint reaching definitions** (:func:`tainted_rank_names`): a
  fixpoint over simple assignments that tracks which locals are derived
  from rank-valued expressions (``is_root = rank == 0`` taints
  ``is_root``), so a conditional on a *renamed* rank value is still
  rank-conditional.
* **Collective reachability** (:func:`collective_reach`): which module
  functions can (transitively) issue a collective, with the discovery
  chain preserved for actionable messages. Collective calls carrying an
  inline ``hvdlint: disable=HVD001`` suppression do not taint the
  closure — a justified subgroup collective stays justified through a
  wrapper.

Scope: one module at a time (the lint framework hands rules one file);
cross-module chains are out of scope here and documented as such in
docs/static-analysis.md. :class:`PackageIndex` (used by the lock pass,
which runs as its own whole-package pass) lifts the same machinery to a
set of files.

Stdlib-only like the rest of ``horovod_tpu.analysis``.
"""

from __future__ import annotations

import ast
from typing import Callable, Dict, Iterator, List, Optional, Set, Tuple

# Names that enqueue a collective on the eager tier (package API surface
# plus the in-place/async variants and ring-backend methods). THE
# canonical set — rules.py re-exports it.
COLLECTIVE_NAMES = frozenset({
    "allreduce", "allreduce_", "allreduce_async",
    "allgather", "allgather_", "allgather_async",
    "broadcast", "broadcast_", "broadcast_async",
    "alltoall", "reducescatter", "barrier",
    "grouped_allreduce", "grouped_allreduce_",
    "broadcast_parameters", "broadcast_optimizer_state",
    "broadcast_object", "allgather_object", "broadcast_variables",
})

# Identifiers whose appearance in an ``if`` test marks it rank-conditional.
RANK_NAMES = frozenset({"rank", "local_rank", "cross_rank", "process_index"})


def call_name(node: ast.Call) -> Optional[str]:
    """Trailing identifier of the called object: ``hvd.allreduce`` ->
    ``allreduce``, ``barrier`` -> ``barrier``."""
    func = node.func
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


def mentions_rank(test: ast.AST,
                  tainted: "frozenset[str] | Set[str]" = frozenset()) -> bool:
    """True when the expression references a rank name or a local the
    taint analysis derived from one."""
    for node in ast.walk(test):
        if isinstance(node, ast.Name) and (node.id in RANK_NAMES
                                           or node.id in tainted):
            return True
        if isinstance(node, ast.Attribute) and node.attr in RANK_NAMES:
            return True
    return False


def iter_own_nodes(fn: ast.AST) -> Iterator[ast.AST]:
    """Walk a function's OWN body: descend everywhere except into nested
    function/class definitions (those execute on their own schedule, not
    as part of this function's control flow) and lambdas (callbacks)."""
    pending = list(ast.iter_child_nodes(fn))
    while pending:
        node = pending.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            continue
        yield node
        pending.extend(ast.iter_child_nodes(node))


def tainted_rank_names(fn: ast.AST) -> Set[str]:
    """Reaching definitions over rank-derived values, flow-insensitively:
    the fixpoint of "assigned from an expression mentioning rank or an
    already-tainted name". Single module-local pass; no kill-set (a
    later clean reassignment does not un-taint — over-approximation,
    consistent with the rest of the analysis)."""
    tainted: Set[str] = set()
    changed = True
    while changed:
        changed = False
        for node in iter_own_nodes(fn):
            target = None
            value = None
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                target, value = node.targets[0].id, node.value
            elif isinstance(node, ast.AnnAssign) \
                    and isinstance(node.target, ast.Name) \
                    and node.value is not None:
                target, value = node.target.id, node.value
            elif isinstance(node, ast.NamedExpr) \
                    and isinstance(node.target, ast.Name):
                target, value = node.target.id, node.value
            if target is None or target in tainted:
                continue
            if mentions_rank(value, tainted):
                tainted.add(target)
                changed = True
    return tainted


class ModuleFunctions:
    """Index of every function/method in one module tree."""

    def __init__(self, tree: ast.AST):
        self.tree = tree
        self.index: Dict[str, ast.AST] = {}
        self.by_bare: Dict[str, List[str]] = {}

        def walk(node: ast.AST, prefix: str) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    qualname = f"{prefix}{child.name}"
                    self.index[qualname] = child
                    self.by_bare.setdefault(child.name, []).append(qualname)
                    walk(child, f"{qualname}.")
                elif isinstance(child, ast.ClassDef):
                    walk(child, f"{prefix}{child.name}.")
                else:
                    walk(child, prefix)

        walk(tree, "")

    def resolve(self, bare: str) -> List[str]:
        """Every module function a call to ``bare`` might reach
        (over-approximate by design)."""
        return self.by_bare.get(bare, [])


def collective_reach(funcs: ModuleFunctions,
                     is_suppressed: Optional[Callable[[int], bool]] = None,
                     ) -> Dict[str, Tuple[str, Tuple[str, ...]]]:
    """``{qualname: (collective_name, call_chain)}`` for every module
    function that can transitively issue a collective. ``call_chain`` is
    the discovery path of qualnames from the function down to (but not
    including) the collective call itself. ``is_suppressed(line)``
    filters collective call sites already justified inline — a wrapped
    subgroup collective must not re-flag every caller."""
    suppressed = is_suppressed or (lambda line: False)
    direct: Dict[str, str] = {}
    calls: Dict[str, Set[str]] = {}
    for qualname, node in funcs.index.items():
        called: Set[str] = set()
        for sub in iter_own_nodes(node):
            if not isinstance(sub, ast.Call):
                continue
            cname = call_name(sub)
            if cname is None:
                continue
            if cname in COLLECTIVE_NAMES:
                if not suppressed(sub.lineno) and qualname not in direct:
                    direct[qualname] = cname
            else:
                called.add(cname)
        calls[qualname] = called

    reach: Dict[str, Tuple[str, Tuple[str, ...]]] = {
        qn: (cname, (qn,)) for qn, cname in direct.items()}
    changed = True
    while changed:
        changed = False
        for qualname in sorted(funcs.index):
            if qualname in reach:
                continue
            for bare in sorted(calls[qualname]):
                hit = None
                for callee in sorted(funcs.resolve(bare)):
                    if callee != qualname and callee in reach:
                        hit = callee
                        break
                if hit is not None:
                    cname, chain = reach[hit]
                    reach[qualname] = (cname, (qualname,) + chain)
                    changed = True
                    break
    return reach


def iter_divergent_collectives(
        tree: ast.AST,
        is_suppressed: Optional[Callable[[int], bool]] = None,
        interprocedural: bool = True,
) -> Iterator[Tuple[ast.AST, str]]:
    """The HVD001 engine: yields ``(node, message)`` for every collective
    issued — directly or through module-local helper calls — inside a
    rank-conditional branch. ``interprocedural=False`` reproduces the
    round-10 lexical rule exactly (kept so its blind spots stay pinned
    by tests)."""
    funcs = ModuleFunctions(tree)
    reach = (collective_reach(funcs, is_suppressed)
             if interprocedural else {})
    out: List[Tuple[ast.AST, str]] = []

    def visit(node: ast.AST, inside: bool, tainted: Set[str]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # A new scope: taint is per-function; the body does NOT
            # inherit the caller's conditional context lexically (the
            # interprocedural pass charges call SITES instead).
            fn_tainted = tainted_rank_names(node) if interprocedural \
                else set()
            for child in ast.iter_child_nodes(node):
                visit(child, False, fn_tainted)
            return
        if isinstance(node, ast.If) and mentions_rank(node.test, tainted):
            # The test expression itself runs on every rank.
            visit(node.test, inside, tainted)
            for child in node.body + node.orelse:
                visit(child, True, tainted)
            return
        if isinstance(node, ast.Call) and inside:
            cname = call_name(node)
            if cname in COLLECTIVE_NAMES:
                out.append((node, (
                    f"collective '{cname}' inside a rank-conditional "
                    "branch (divergent-collective deadlock): hoist it "
                    "out, or suppress if the subgroup genuinely "
                    "matches the conditional")))
            elif interprocedural and cname is not None:
                hit = None
                for callee in sorted(funcs.resolve(cname)):
                    if callee in reach:
                        hit = callee
                        break
                if hit is not None:
                    collective, chain = reach[hit]
                    path = " -> ".join(chain) + f" -> {collective}"
                    out.append((node, (
                        f"call to '{cname}' inside a rank-conditional "
                        f"branch reaches collective '{collective}' "
                        f"(via {path}): ranks taking the other branch "
                        "never enqueue it and the job deadlocks — hoist "
                        "the call out, or suppress if every rank "
                        "ultimately issues the same collectives")))
        for child in ast.iter_child_nodes(node):
            visit(child, inside, tainted)

    visit(tree, False, set())
    yield from out


class PackageIndex:
    """Cross-file function index for whole-package passes (the static
    lock graph): the same over-approximate bare-name resolution as
    :class:`ModuleFunctions`, lifted over many modules."""

    def __init__(self):
        # (relpath, qualname) -> node; bare name -> [(relpath, qualname)]
        self.functions: Dict[Tuple[str, str], ast.AST] = {}
        self.by_bare: Dict[str, List[Tuple[str, str]]] = {}
        self.modules: Dict[str, ast.AST] = {}

    def add_module(self, relpath: str, tree: ast.AST) -> None:
        self.modules[relpath] = tree
        funcs = ModuleFunctions(tree)
        for qualname, node in funcs.index.items():
            key = (relpath, qualname)
            self.functions[key] = node
            self.by_bare.setdefault(
                qualname.rsplit(".", 1)[-1], []).append(key)

    def resolve(self, bare: str) -> List[Tuple[str, str]]:
        return self.by_bare.get(bare, [])
